"""Long-context training: zig-zag ring attention + GQA flash + remat.

The whole long-sequence stack in one runnable loop:

- the sequence shards over the mesh's "seq" axis and every attention hop
  runs the fused Pallas kernel with a dynamic causal shift
  (sofa_tpu/workloads/ring_flash.py) — no [T, T] score matrix anywhere;
- zig-zag layout balances causal work across shards
  (``TransformerConfig.zigzag``);
- KV heads stay compact over the ring's ppermute hops (native GQA:
  group-factor fewer ICI bytes);
- each layer rematerializes in the backward (``remat=True``), so live
  activations are one layer deep regardless of depth.

Profiled, the trace shows the ring's collective-permute traffic, the
``pallas@...`` kernel rows with their cost estimates, and per-step fw/bw
attribution:

    sofa stat "python examples/long_context.py" --logdir llog/ --enable_aisi

Runs anywhere: on TPU the fused kernel (and zig-zag, when sequence-
parallel) switches on automatically; on CPU virtual devices
(XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu)
the same script demos the ring + remat structure with the kernel's
unfused twin — the Pallas rows and zig-zag layout appear on TPU runs.
"""

import dataclasses

import jax

from sofa_tpu.workloads.common import fence, make_mesh, step_annotation
from sofa_tpu.workloads.transformer import TransformerConfig, build


def main(steps: int = 8, seq: int = 512):
    n = len(jax.devices())
    sp = max(d for d in (1, 2, 4, 8) if n % d == 0 and d <= n)
    dp = n // sp
    mesh = make_mesh(("data", "seq", "model"), (dp, sp, 1))
    on_tpu = jax.default_backend() == "tpu"
    cfg = dataclasses.replace(
        TransformerConfig.tiny(seq=seq),
        # flash=None is the auto rule: the fused kernel on TPU whenever
        # the per-shard length supports it, unfused fallback elsewhere —
        # forcing True would make unsupported shard lengths a hard error
        flash=None,
        zigzag=sp > 1 and on_tpu,
        remat=True,
    )
    # batch shards over the data axis, so it must scale with it
    params, opt_state, step, tokens = build(cfg, mesh, batch=2 * dp, seq=seq)
    params, opt_state, loss = step(params, opt_state, tokens)   # compile
    fence(loss)
    for i in range(steps):
        with step_annotation(i):
            params, opt_state, loss = step(params, opt_state, tokens)
    fence(loss)
    print(f"mesh={dict(mesh.shape)} seq={seq} remat=on "
          f"zigzag={'on' if cfg.zigzag else 'off'} "
          f"final loss {float(loss):.4f} after {steps} steps")


if __name__ == "__main__":
    main()
