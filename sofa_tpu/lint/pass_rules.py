"""SL010–SL013 — the pass contract rules (analysis + fleet domains).

``sofa_tpu/analysis/registry.py`` made every analysis pass declare its
contract (frames/columns/features read, features/artifacts produced,
ordering edges) as plain literals on the ``@analysis_pass`` decorator,
and ``sofa_tpu/analysis/fleet.py`` reuses the same machinery for
``@fleet_pass`` cross-run passes over the archive index.  These rules
are what make those declarations *verified* rather than documentation:
each decorated pass body is checked against its own declaration, and the
cross-pass dependency graph is validated from the declarations alone —
statically, before any trace is ever analyzed.

SL010  a pass body may only touch frames, columns, and feature keys it
       declared (undeclared read/write = finding).  Analysis passes are
       checked against the trace schema; fleet passes against the
       pinned index family schemas (their ``reads_columns`` entries are
       ``family.column`` qualified and must exist in archive/index.py's
       column constants)
SL011  a declaration may not claim outputs the body never produces
SL012  the declared graph must schedule: no dependency cycles, no read
       of a feature no registered pass (or the driver's ambient set —
       analysis domain only; the fleet driver injects nothing)
       provides, no ``after`` edge to an unknown pass, and no ``after``
       edge crossing the analysis/fleet domain boundary — the two
       registries never co-schedule
SL013  pass bodies must not call another pass directly — composition
       happens in the scheduler, where fault isolation and the
       meta.passes ledger live

Feature names are fnmatch-style patterns; dynamic feature names
(f-strings) canonicalize with ``*`` replacing each interpolated segment,
and ``by_regex`` arguments canonicalize by collapsing regex metacharacter
runs to ``*``.  The overlap test below is the SAME algebra the runtime
scheduler uses (registry.patterns_overlap — keep them in sync): what
lints clean is exactly what schedules.
"""

from __future__ import annotations

import ast
import re
from fnmatch import fnmatchcase
from typing import Dict, Iterable, List, Optional, Tuple

from sofa_tpu.lint.core import FileContext, Finding, PassDecl, Rule, SEV_ERROR

_FEATURE_WRITES = ("add", "add_info")
_FEATURE_READS = ("get", "by_regex")


def _overlap(a: str, b: str) -> bool:
    """Mirror of registry.patterns_overlap (no import: lint never loads
    the pandas-heavy analysis stack)."""
    return fnmatchcase(a, b) or fnmatchcase(b, a)


def _covered(pattern: str, declared) -> bool:
    return any(_overlap(pattern, d) for d in declared)


def _canon_str(node: ast.expr) -> str:
    """Canonical feature pattern of a name expression: literals verbatim,
    f-strings with ``*`` per interpolation, anything fully dynamic ``*``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts) or "*"
    return "*"


_REGEX_META = re.compile(r"(\\[dwsDWS][*+?]?|\[[^]]*\][*+?]?|\.[*+?]?"
                         r"|[*+?]|\{\d+(,\d*)?\}|\(|\)|\||\^|\$)+")


def _canon_regex(pattern: str) -> str:
    """Collapse regex metacharacter runs to ``*`` and unescape literals:
    ``tpu\\d+_op_time`` -> ``tpu*_op_time``."""
    out = _REGEX_META.sub("*", pattern)
    return out.replace("\\", "")


class _PassIndex:
    """Per-file cache joining FileContext to the project's PassDecls."""

    def __init__(self, ctx: FileContext):
        self.decls: Dict[str, PassDecl] = {
            d.func: d for d in ctx.project.passes
            if d.relpath == ctx.relpath}
        #: function name -> pass name, across the whole linted tree.
        self.all_funcs: Dict[str, str] = {
            d.func: d.name for d in ctx.project.passes}
        #: ids of nodes inside any decorator expression (the declaration's
        #: own literals must not be mistaken for body accesses).
        self.deco_nodes = set()
        self.funcdefs: Dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name in self.decls:
                    self.funcdefs[node.name] = node
                for deco in node.decorator_list:
                    for sub in ast.walk(deco):
                        self.deco_nodes.add(id(sub))


def _index(ctx: FileContext) -> _PassIndex:
    idx = getattr(ctx, "_pass_index", None)
    if idx is None:
        idx = _PassIndex(ctx)
        ctx._pass_index = idx
    return idx


def _enclosing_pass(ctx: FileContext,
                    node: ast.AST) -> "Tuple[PassDecl, ast.AST] | None":
    idx = _index(ctx)
    if not idx.decls or id(node) in idx.deco_nodes:
        return None
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            decl = idx.decls.get(anc.name)
            if decl is not None and idx.funcdefs.get(anc.name) is anc:
                return decl, anc
    return None


def _param_names(funcdef, decl: PassDecl) -> Tuple[str, str]:
    """(frames, features) parameter names of a pass body.  Analysis
    passes are ``fn(frames, cfg, features)``; fleet passes are
    ``fn(state, tables, ctx, features)`` — their "frames" mapping is the
    declared-slice table dict in slot 1."""
    args = [a.arg for a in funcdef.args.args]
    if decl.domain == "fleet":
        frames = args[1] if len(args) > 1 else "tables"
        features = args[3] if len(args) > 3 else "features"
    else:
        frames = args[0] if args else "frames"
        features = args[2] if len(args) > 2 else "features"
    return frames, features


class UndeclaredPassAccess(Rule):
    """SL010 — a registered pass touches only what it declared.  Frame
    lookups (``frames.get("x")`` / ``frames["x"]``) must name declared
    ``reads_frames``; any string literal naming a trace column (analysis)
    or a declared index family's column (fleet, ``family.column``
    qualified) must be in ``reads_columns``; ``features.add/add_info``
    names must match ``provides_features``; ``features.get/by_regex``
    must match ``reads_features`` (or the pass's own provides — reading
    back your own output is composition-free).  :meth:`finish` also
    checks fleet declarations themselves against the pinned family
    schemas: a ``reads_columns`` entry naming an unknown family or a
    column outside its schema is a phantom read."""

    rule_id = "SL010"
    severity = SEV_ERROR
    node_types = (ast.Call, ast.Subscript, ast.Constant)

    def visit(self, ctx: FileContext, node: ast.AST) -> Iterable[Finding]:
        hit = _enclosing_pass(ctx, node)
        if hit is None:
            return
        decl, funcdef = hit
        frames_p, features_p = _param_names(funcdef, decl)
        if isinstance(node, ast.Constant):
            if not isinstance(node.value, str):
                return
            if decl.domain == "fleet":
                qualified = [f"{fam}.{node.value}"
                             for fam in decl.reads_frames
                             if f"{fam}.{node.value}"
                             in ctx.project.index_columns]
                if qualified and not any(q in decl.reads_columns
                                         for q in qualified):
                    yield self.finding(
                        ctx, node,
                        f"fleet pass {decl.name!r} touches index column "
                        f"{node.value!r} of a declared family without a "
                        f"matching reads_columns entry ({qualified[0]!r})")
            elif node.value in ctx.project.columns and \
                    node.value not in decl.reads_columns:
                yield self.finding(
                    ctx, node,
                    f"pass {decl.name!r} touches trace column "
                    f"{node.value!r} it does not declare in reads_columns")
            return
        if isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Name) \
                    and node.value.id == frames_p \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str) \
                    and node.slice.value not in decl.reads_frames:
                yield self.finding(
                    ctx, node,
                    f"pass {decl.name!r} reads frame "
                    f"{node.slice.value!r} it does not declare in "
                    "reads_frames")
            return
        fn = node.func
        if not isinstance(fn, ast.Attribute) \
                or not isinstance(fn.value, ast.Name):
            return
        recv, attr = fn.value.id, fn.attr
        arg0 = node.args[0] if node.args else None
        if recv == frames_p and attr == "get" and arg0 is not None:
            if isinstance(arg0, ast.Constant) \
                    and isinstance(arg0.value, str) \
                    and arg0.value not in decl.reads_frames:
                yield self.finding(
                    ctx, node,
                    f"pass {decl.name!r} reads frame {arg0.value!r} it "
                    "does not declare in reads_frames")
            return
        if recv != features_p or arg0 is None:
            return
        if attr in _FEATURE_WRITES:
            pat = _canon_str(arg0)
            if not _covered(pat, decl.provides_features):
                yield self.finding(
                    ctx, node,
                    f"pass {decl.name!r} writes feature {pat!r} its "
                    "declaration does not provide — declare it in "
                    "provides_features")
        elif attr in _FEATURE_READS:
            pat = _canon_str(arg0)
            if attr == "by_regex" and isinstance(arg0, ast.Constant) \
                    and isinstance(arg0.value, str):
                pat = _canon_regex(arg0.value)
            allowed = (tuple(decl.reads_features)
                       + tuple(decl.provides_features)
                       + tuple(ctx.project.ambient_features))
            if not _covered(pat, allowed):
                yield self.finding(
                    ctx, node,
                    f"pass {decl.name!r} reads feature {pat!r} it does "
                    "not declare in reads_features — undeclared reads "
                    "hide scheduling dependencies")

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        index_cols = ctx.project.index_columns
        if not index_cols:
            return
        families = {q.split(".", 1)[0] for q in index_cols}
        idx = _index(ctx)
        for _func, decl in sorted(idx.decls.items()):
            if decl.domain != "fleet":
                continue
            for fam in decl.reads_frames:
                if fam not in families:
                    yield Finding(
                        ctx.relpath, decl.line, self.rule_id,
                        f"fleet pass {decl.name!r} declares reads_frames "
                        f"{fam!r} which is not an archive index family "
                        f"({sorted(families)})", self.severity)
            for col in decl.reads_columns:
                fam, _, bare = col.partition(".")
                if not bare or fam not in decl.reads_frames:
                    yield Finding(
                        ctx.relpath, decl.line, self.rule_id,
                        f"fleet pass {decl.name!r} declares reads_columns "
                        f"{col!r} — entries must be 'family.column' with "
                        "the family in reads_frames", self.severity)
                elif col not in index_cols:
                    yield Finding(
                        ctx.relpath, decl.line, self.rule_id,
                        f"fleet pass {decl.name!r} declares reads_columns "
                        f"{col!r} outside the pinned {fam!r} family "
                        "schema — a phantom read the index can never "
                        "serve", self.severity)


class PhantomPassOutput(Rule):
    """SL011 — a declaration may not claim outputs the body never writes:
    every ``provides_features`` pattern needs a matching
    ``features.add/add_info`` and every ``provides_artifacts`` file a
    naming literal.  A body that *forwards* the features object into a
    helper call delegates its writes (the aisi/hsg wrappers); delegated
    contracts are trusted, not flagged."""

    rule_id = "SL011"
    severity = SEV_ERROR
    node_types = ()

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        idx = _index(ctx)
        for func, decl in sorted(idx.decls.items()):
            funcdef = idx.funcdefs.get(func)
            if funcdef is None:
                continue
            frames_p, features_p = _param_names(funcdef, decl)
            writes: List[str] = []
            strings: List[str] = []
            forwarded = False
            for node in ast.walk(funcdef):
                if id(node) in idx.deco_nodes:
                    continue
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str):
                    strings.append(node.value)
                if not isinstance(node, ast.Call):
                    continue
                for arg in list(node.args) + [k.value for k in
                                              node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id == features_p:
                        forwarded = True
                fn = node.func
                if isinstance(fn, ast.Attribute) \
                        and isinstance(fn.value, ast.Name) \
                        and fn.value.id == features_p \
                        and fn.attr in _FEATURE_WRITES and node.args:
                    writes.append(_canon_str(node.args[0]))
            if forwarded:
                continue
            for pat in decl.provides_features:
                if not any(_overlap(w, pat) for w in writes):
                    yield Finding(
                        ctx.relpath, decl.line, self.rule_id,
                        f"pass {decl.name!r} declares provides_features "
                        f"{pat!r} but its body never writes a matching "
                        "feature — drop the claim or produce it",
                        self.severity)
            for artifact in decl.provides_artifacts:
                if artifact not in strings:
                    yield Finding(
                        ctx.relpath, decl.line, self.rule_id,
                        f"pass {decl.name!r} declares artifact "
                        f"{artifact!r} but its body never names it — "
                        "drop the claim or write the file",
                        self.severity)


class UnschedulablePassGraph(Rule):
    """SL012 — the declared dependency graph must schedule, verified from
    the declarations alone: every ``reads_features`` pattern needs a
    provider (some registered pass of the same domain, or — analysis
    domain only — the driver's AMBIENT_FEATURES), every ``after`` edge a
    registered target *in the same domain* (the analysis and fleet
    registries never co-schedule, so a cross-domain edge is a contract
    error, not an ordering hint), and each domain's graph must be
    acyclic.  Findings anchor at the declaring decorator."""

    rule_id = "SL012"
    severity = SEV_ERROR
    node_types = ()

    def _graph(self, decls: Tuple[PassDecl, ...]) -> Dict[str, set]:
        by_name = {d.name: d for d in decls}
        deps: Dict[str, set] = {d.name: set() for d in decls}
        for d in decls:
            for dep in d.after:
                if dep in by_name and dep != d.name \
                        and by_name[dep].domain == d.domain:
                    deps[d.name].add(dep)
            for pat in d.reads_features:
                for other in decls:
                    if other.name != d.name \
                            and other.domain == d.domain and \
                            _covered(pat, other.provides_features):
                        deps[d.name].add(other.name)
        return deps

    def _cyclic_names(self, deps: Dict[str, set]) -> set:
        # Kahn peel: whatever cannot be scheduled is on (or behind) a cycle.
        remaining = dict(deps)
        changed = True
        done: set = set()
        while changed:
            changed = False
            for name, d in list(remaining.items()):
                if d <= done:
                    done.add(name)
                    del remaining[name]
                    changed = True
        return set(remaining)

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        mine = [d for d in ctx.project.passes if d.relpath == ctx.relpath]
        if not mine:
            return
        all_decls = tuple(ctx.project.passes)
        domain_of = {d.name: d.domain for d in all_decls}
        deps = self._graph(all_decls)
        cyclic = self._cyclic_names(deps)
        for d in mine:
            for dep in d.after:
                if dep not in domain_of:
                    yield Finding(
                        ctx.relpath, d.line, self.rule_id,
                        f"pass {d.name!r} declares after={dep!r} but no "
                        "registered pass has that name",
                        self.severity)
                elif domain_of[dep] != d.domain:
                    yield Finding(
                        ctx.relpath, d.line, self.rule_id,
                        f"{d.domain} pass {d.name!r} declares "
                        f"after={dep!r}, a {domain_of[dep]} pass — the "
                        "two registries never co-schedule, so a "
                        "cross-domain edge can never order anything",
                        self.severity)
            for pat in d.reads_features:
                if d.domain == "analysis" \
                        and _covered(pat, ctx.project.ambient_features):
                    continue
                if not any(_covered(pat, o.provides_features)
                           for o in all_decls if o.domain == d.domain):
                    yield Finding(
                        ctx.relpath, d.line, self.rule_id,
                        f"pass {d.name!r} reads feature {pat!r} that no "
                        "registered pass provides (and the analyze driver "
                        "does not supply ambiently) — it will never be "
                        "satisfied",
                        self.severity)
            if d.name in cyclic:
                yield Finding(
                    ctx.relpath, d.line, self.rule_id,
                    f"pass {d.name!r} is part of a declared dependency "
                    f"cycle ({sorted(cyclic)}) — the scheduler cannot "
                    "order it",
                    self.severity)


class DirectPassCall(Rule):
    """SL013 — pass bodies must not call another registered pass
    directly: composition happens through the scheduler, which is where
    fault isolation, the telemetry span, and the meta.passes entry live.
    A direct call runs the callee twice, outside its contract."""

    rule_id = "SL013"
    severity = SEV_ERROR
    node_types = (ast.Call,)

    def visit(self, ctx: FileContext, node: ast.Call) -> Iterable[Finding]:
        hit = _enclosing_pass(ctx, node)
        if hit is None:
            return
        decl, _funcdef = hit
        resolved = ctx.resolve_call(node)
        if not resolved:
            return
        leaf = resolved.rsplit(".", 1)[-1]
        target = _index(ctx).all_funcs.get(leaf)
        if target is not None and leaf != decl.func:
            yield self.finding(
                ctx, node,
                f"pass {decl.name!r} calls pass {target!r} "
                f"({leaf}) directly — compose via declared dependencies "
                "(reads_features/after); the scheduler owns execution, "
                "fault isolation, and the meta.passes ledger")


PASS_RULES = (
    UndeclaredPassAccess,
    PhantomPassOutput,
    UnschedulablePassGraph,
    DirectPassCall,
)
