"""HBM attribution (memprof) subsystem: pprof decode, site aggregation,
peak-trigger gating, the analysis pass, and the in-process capture path.

The snapshot format is the public pprof Profile proto as emitted by
jax.profiler.device_memory_profile() (verified live: sample types
(allocations,count)/(space,bytes), string labels kind/device, leaf-first
frames).  No reference analogue — nvsmi stops at one used-MB total
(reference sofa_record.py:300-310).
"""

import gzip
import json
import os

import pytest

from sofa_tpu.analysis.features import Features
from sofa_tpu.config import SofaConfig
from sofa_tpu.ingest.memprof import (
    aggregate_sites,
    load_memprof,
    parse_memprof,
)


@pytest.fixture
def cfg(logdir):
    return SofaConfig(logdir=logdir)


def build_profile():
    """A two-device, three-site profile shaped like the live JAX output."""
    from sofa_tpu.ingest import memprof_pb2

    p = memprof_pb2.Profile()
    strings = [""]

    def intern(s):
        if s not in strings:
            strings.append(s)
        return strings.index(s)

    for t, u in (("allocations", "count"), ("space", "bytes")):
        vt = p.sample_type.add()
        vt.type, vt.unit = intern(t), intern(u)

    def add_function(fid, name):
        fn = p.function.add()
        fn.id, fn.name = fid, intern(name)
        loc = p.location.add()
        loc.id = fid
        ln = loc.line.add()
        ln.function_id = fid
        return fid

    # Leaf-first runtime plumbing, then the user frame the site should pick.
    add_function(1, "__call__")
    add_function(2, "_pjit_call_impl_python")
    add_function(3, "train_step")
    add_function(4, "load_batch")
    add_function(5, "backend_compile_and_load")

    def add_sample(stack, count, nbytes, kind, device):
        s = p.sample.add()
        s.location_id.extend(stack)
        s.value.extend([count, nbytes])
        for key, val in (("kind", kind), ("device", device)):
            lb = s.label.add()
            lb.key, lb.str = intern(key), intern(val)

    add_sample([1, 2, 3], 2, 6 * 2**20, "buffer", "TPU_0")
    add_sample([1, 2, 3], 1, 2 * 2**20, "buffer", "TPU_1")
    add_sample([1, 2, 4], 4, 1 * 2**20, "buffer", "TPU_0")
    add_sample([5], 1, 0, "executable", "")
    p.string_table.extend(strings)
    return p


def write_profile(path, gz=True):
    blob = build_profile().SerializeToString()
    with open(path, "wb") as f:
        f.write(gzip.compress(blob) if gz else blob)


def test_parse_memprof_sites_and_labels(tmp_path):
    path = str(tmp_path / "memprof.pb.gz")
    write_profile(path)
    df = parse_memprof(path)
    assert len(df) == 4
    # Runtime plumbing frames (__call__/_pjit...) never become the site.
    train = df[df["site"] == "train_step"]
    assert len(train) == 2 and set(train["device"]) == {"TPU_0", "TPU_1"}
    assert int(train["bytes"].sum()) == 8 * 2**20
    # Full stack is preserved leaf-first for flame-style drill-down.
    assert train["stack"].iloc[0] == "__call__;_pjit_call_impl_python;train_step"
    assert df[df["kind"] == "executable"]["bytes"].iloc[0] == 0
    # Raw (non-gzip) blobs parse too — synthetic fixtures and foreign tools.
    raw = str(tmp_path / "raw.pb")
    write_profile(raw, gz=False)
    assert len(parse_memprof(raw)) == 4


def test_aggregate_sites_share_and_order(tmp_path):
    path = str(tmp_path / "memprof.pb.gz")
    write_profile(path)
    sites = aggregate_sites(parse_memprof(path))
    assert list(sites["site"][:2]) == ["train_step", "load_batch"]
    top = sites.iloc[0]
    assert top["bytes"] == 8 * 2**20 and top["count"] == 3
    assert top["share"] == pytest.approx(8 / 9)
    assert aggregate_sites(None).empty


def test_load_memprof_meta_sidecar(cfg):
    assert load_memprof(cfg.logdir) == (None, {})
    path = cfg.path("memprof.pb.gz")
    write_profile(path)
    with open(path + ".meta.json", "w") as f:
        json.dump({"trigger": "peak", "total_bytes": 9 * 2**20}, f)
    df, meta = load_memprof(cfg.logdir)
    assert len(df) == 4 and meta["trigger"] == "peak"


def test_memprof_profile_pass(cfg):
    from sofa_tpu.analysis.tpu import memprof_profile

    write_profile(cfg.path("memprof.pb.gz"))
    with open(cfg.path("memprof.pb.gz.meta.json"), "w") as f:
        json.dump({"trigger": "peak", "total_bytes": 9 * 2**20}, f)
    feats = Features()
    memprof_profile({}, cfg, feats)
    assert feats.get("memprof_held_gb") == pytest.approx(9 * 2**20 / 1e9)
    assert feats.get("memprof_buffers") == 7
    assert feats.get("memprof_sites") == 2
    assert feats.get("memprof_devices") == 2
    assert os.path.isfile(cfg.path("tpu_memprof.csv"))
    rendered = feats.render()
    assert "memprof_top_site" in rendered and "train_step" in rendered

    # Absent snapshot: the pass is a silent no-op (per-pass degradation).
    empty = SofaConfig(logdir=cfg.logdir + "none/")
    memprof_profile({}, empty, Features())


def test_parse_memprof_fuzz_random_bytes(tmp_path):
    """Arbitrary bytes either parse to a frame or raise promptly — the
    parser must never hang or return malformed columns (same contract as
    the pcap and native-scan fuzz tests)."""
    import random

    rng = random.Random(0)
    path = str(tmp_path / "fuzz.bin")
    base = build_profile().SerializeToString()
    for trial in range(60):
        if trial % 3 == 0:
            blob = bytes(rng.randbytes(rng.randrange(0, 400)))
        elif trial % 3 == 1:  # truncated real proto, sometimes gzipped
            cut = base[:rng.randrange(0, len(base))]
            blob = gzip.compress(cut) if trial % 2 else cut
        else:  # real proto with flipped bytes
            b = bytearray(base)
            for _ in range(rng.randrange(1, 6)):
                b[rng.randrange(len(b))] = rng.randrange(256)
            blob = bytes(b)
        with open(path, "wb") as f:
            f.write(blob)
        try:
            df = parse_memprof(path)
        except Exception:
            continue  # rejecting malformed input is correct
        assert list(df.columns) == ["device", "kind", "count", "bytes",
                                    "site", "stack"]


class _StubJax:
    """Stands in for the jax module inside snapshot_memprof."""

    calls = 0

    class profiler:  # noqa: N801 - mimics module attribute access
        @staticmethod
        def device_memory_profile():
            _StubJax.calls += 1
            return gzip.compress(build_profile().SerializeToString())


def test_peak_trigger_growth_gate(tmp_path, monkeypatch):
    from sofa_tpu.collectors import tpumon

    # The stub provides the runtime's native profiler; the gate logic under
    # test is identical either way.
    monkeypatch.setenv("SOFA_MEMPROF_NATIVE", "1")
    ns = tpumon._ns
    path = str(tmp_path / "memprof.pb.gz")
    _StubJax.calls = 0
    ns["_MEMPROF"].update(snap=0, last=0.0)

    ns["_maybe_memprof"](_StubJax, path, 100 * 2**20)
    assert _StubJax.calls == 1 and os.path.isfile(path)
    meta = json.load(open(path + ".meta.json"))
    assert meta["trigger"] == "peak"
    assert meta["total_bytes"] == 100 * 2**20

    # <2% growth over the last SNAPSHOT: gate holds, no re-snapshot.
    ns["_MEMPROF"]["last"] = 0.0
    ns["_maybe_memprof"](_StubJax, path, 101 * 2**20)
    assert _StubJax.calls == 1

    # Real growth but inside the 2s rate limit: deferred, baseline NOT
    # raised — a ratcheting baseline would let gradual growth outrun the
    # gate forever and freeze the snapshot at startup state.
    import time as _time
    ns["_MEMPROF"]["last"] = _time.time()
    ns["_maybe_memprof"](_StubJax, path, 200 * 2**20)
    assert _StubJax.calls == 1
    assert ns["_MEMPROF"]["snap"] == 100 * 2**20

    # Rate limit expired: the deferred growth fires.
    ns["_MEMPROF"]["last"] = 0.0
    ns["_maybe_memprof"](_StubJax, path, 200 * 2**20)
    assert _StubJax.calls == 2
    assert ns["_MEMPROF"]["snap"] == 200 * 2**20

    # Compounding sub-2% ticks re-trigger once the SUM passes 2%.
    ns["_MEMPROF"]["last"] = 0.0
    for total in (202, 204, 206):  # each +1% of snap, cumulative +3%
        ns["_maybe_memprof"](_StubJax, path, total * 2**20)
    assert _StubJax.calls == 3

    # Disabled (no path) and zero totals are no-ops.
    ns["_maybe_memprof"](_StubJax, None, 400 * 2**20)
    ns["_maybe_memprof"](_StubJax, path, 0)
    assert _StubJax.calls == 3


def test_snapshot_memprof_atomic_and_resilient(tmp_path, monkeypatch):
    from sofa_tpu.collectors.tpumon import snapshot_memprof

    monkeypatch.setenv("SOFA_MEMPROF_NATIVE", "1")
    path = str(tmp_path / "memprof.pb.gz")
    assert snapshot_memprof(_StubJax, path, "final", 0)
    assert parse_memprof(path).shape[0] == 4
    import glob
    assert not glob.glob(path + ".tmp*")  # writer-unique tmps all cleaned

    class _Broken:
        class profiler:  # noqa: N801
            @staticmethod
            def device_memory_profile():
                raise RuntimeError("chip mid-teardown")

    # Failure is reported, not raised — the profiled app must survive.
    assert not snapshot_memprof(_Broken, str(tmp_path / "x.pb.gz"), "final", 0)


class _FakeDevice:
    def __init__(self, did):
        self.platform, self.id = "tpu", did


class _FakeShard:
    def __init__(self, did, nbytes):
        self.device = _FakeDevice(did)
        self.data = type("D", (), {"nbytes": nbytes})()


class _FakeFrame:
    def __init__(self, fn, file, line):
        self.function_name, self.file_name, self.line_num = fn, file, line


class _FakeArray:
    def __init__(self, frames, shards):
        self.traceback = (type("TB", (), {"frames": [
            _FakeFrame(*f) for f in frames]})() if frames else None)
        self.addressable_shards = shards
        self.nbytes = sum(s.data.nbytes for s in shards)


def test_snapshot_live_arrays_roundtrip(tmp_path):
    """Default (plugin-safe) path: the hand-encoded pprof from
    jax.live_arrays() decodes through the same parse_memprof as the
    runtime's native profile, with stacks, devices, and byte totals
    intact."""
    from sofa_tpu.collectors.tpumon import snapshot_memprof

    stack = [("__call__", "jax/x.py", 1),
             ("_pjit_call_impl_python", "jax/pjit.py", 2),
             ("train_step", "train.py", 40)]

    class _LiveJax:
        @staticmethod
        def live_arrays():
            return [
                # same stack twice -> one aggregated sample per device
                _FakeArray(stack, [_FakeShard(0, 100), _FakeShard(1, 50)]),
                _FakeArray(stack, [_FakeShard(0, 7)]),
                _FakeArray([("load_batch", "input.py", 9)],
                           [_FakeShard(0, 1000)]),
                _FakeArray([], [_FakeShard(0, 3)]),      # no traceback
            ]

    path = str(tmp_path / "memprof.pb.gz")
    assert snapshot_memprof(_LiveJax, path, "peak", 1160)
    df = parse_memprof(path)
    assert set(df["kind"]) == {"buffer"}
    assert int(df["bytes"].sum()) == 1160
    t0 = df[(df["site"] == "train_step") & (df["device"] == "tpu:0")]
    assert len(t0) == 1
    assert int(t0["bytes"].iloc[0]) == 107 and int(t0["count"].iloc[0]) == 2
    assert int(df.loc[df["device"] == "tpu:1", "bytes"].sum()) == 50
    row = df[df["site"] == "load_batch"].iloc[0]
    assert row["stack"] == "load_batch"
    assert (df["site"] == "(stackless buffer)").any()
    # the deep stack survives leaf-first
    assert df[df["site"] == "train_step"]["stack"].iloc[0].startswith(
        "__call__;_pjit_call_impl_python;train_step")


def test_snapshot_live_arrays_real_backend(tmp_path):
    """End-to-end on the real (CPU-mesh) jax: live_arrays tracebacks and
    shard devices flow through the encoder into a parseable profile that
    covers a held buffer's bytes."""
    import jax
    import jax.numpy as jnp

    from sofa_tpu.collectors.tpumon import snapshot_memprof

    held = jnp.ones((512, 512), jnp.float32)           # 1 MB
    held = jax.jit(lambda x: x + 1)(held)
    held.block_until_ready()
    path = str(tmp_path / "memprof.pb.gz")
    assert snapshot_memprof(jax, path, "peak", held.nbytes)
    df = parse_memprof(path)
    buf = df[df["kind"] == "buffer"]
    assert int(buf["bytes"].sum()) >= held.nbytes
    # backend-agnostic: conftest pins cpu, but SOFA_TPU_TEST_REAL=1 runs
    # this same test against the real chip's platform label
    platform = jax.default_backend()
    assert buf["device"].str.startswith(f"{platform}:").any()
    assert (buf["site"] != "").all()
    del held


def make_profile(sites):
    """One buffer sample per {site_name: bytes} behind a runtime frame."""
    from sofa_tpu.ingest import memprof_pb2

    p = memprof_pb2.Profile()
    strings = [""]

    def intern(s):
        if s not in strings:
            strings.append(s)
        return strings.index(s)

    for t, u in (("allocations", "count"), ("space", "bytes")):
        vt = p.sample_type.add()
        vt.type, vt.unit = intern(t), intern(u)
    fn = p.function.add()
    fn.id, fn.name = 1, intern("__call__")
    loc = p.location.add()
    loc.id = 1
    loc.line.add().function_id = 1
    for i, (site, nbytes) in enumerate(sites.items(), start=2):
        fn = p.function.add()
        fn.id, fn.name = i, intern(site)
        loc = p.location.add()
        loc.id = i
        loc.line.add().function_id = i
        s = p.sample.add()
        s.location_id.extend([1, i])
        s.value.extend([1, nbytes])
        for key, val in (("kind", "buffer"), ("device", "TPU_0")):
            lb = s.label.add()
            lb.key, lb.str = intern(key), intern(val)
    p.string_table.extend(strings)
    return p


def test_sofa_mem_diff_site_deltas(tmp_path):
    from sofa_tpu.ml.diff import sofa_mem_diff

    mb = 2**20
    for name, sites in (
        ("base", {"train_step": 100 * mb, "load_batch": 10 * mb}),
        ("match", {"train_step": 250 * mb, "kv_cache": 50 * mb}),
    ):
        d = tmp_path / name
        d.mkdir()
        with open(d / "memprof.pb.gz", "wb") as f:
            f.write(gzip.compress(make_profile(sites).SerializeToString()))
    cfg = SofaConfig(logdir=str(tmp_path / "out") + "/")
    cfg.base_logdir = str(tmp_path / "base")
    cfg.match_logdir = str(tmp_path / "match")
    table = sofa_mem_diff(cfg)
    assert table is not None
    assert os.path.isfile(cfg.path("mem_diff.csv"))
    # Sorted by |delta|: train_step (+150MB) first, then kv_cache (+50MB,
    # new in match -> ratio inf), then load_batch (-10MB, gone).
    assert list(table["site"][:3]) == ["train_step", "kv_cache", "load_batch"]
    t = table.set_index("site")
    assert t.loc["train_step", "delta"] == 150 * mb
    assert t.loc["train_step", "ratio"] == pytest.approx(2.5)
    assert t.loc["kv_cache", "ratio"] == float("inf")
    assert t.loc["load_batch", "delta"] == -10 * mb

    # One side missing its snapshot: warn-and-skip, never raise.
    cfg.match_logdir = str(tmp_path / "nowhere")
    assert sofa_mem_diff(cfg) is None


def test_export_folded_memory_flamegraph(cfg):
    """--folded exports HBM bytes per allocation stack, root-first, width
    = bytes (the pprof flame-view convention), executables excluded."""
    from sofa_tpu.export_folded import export_folded

    write_profile(cfg.path("memprof.pb.gz"))
    paths = export_folded(cfg, frames={})
    assert cfg.path("memprof.folded") in paths
    lines = open(cfg.path("memprof.folded")).read().splitlines()
    by_stack = dict(line.rsplit(" ", 1) for line in lines)
    # build_profile: train_step holds 6MB+2MB on one stack, load_batch 1MB.
    assert by_stack["train_step;_pjit_call_impl_python;__call__"] == str(8 * 2**20)
    assert by_stack["load_batch;_pjit_call_impl_python;__call__"] == str(1 * 2**20)
    assert not any("backend_compile" in s for s in by_stack)  # kind=executable, 0 bytes

    # A truncated snapshot degrades with a warning, never a traceback —
    # static/perfetto artifacts may already have succeeded in this export.
    with open(cfg.path("memprof.pb.gz"), "wb") as f:
        f.write(b"\x1f\x8b\x08\x00junk")
    assert export_folded(cfg, frames={}) == []


def test_export_folded_memprof_cluster_hosts(tmp_path):
    """--cluster_hosts folds every host's snapshot, hostname as root frame."""
    from sofa_tpu.export_folded import export_folded

    top = str(tmp_path / "clog") + "/"
    for host in ("h1", "h2"):
        d = top.rstrip("/") + f"-{host}/"
        os.makedirs(d)
        with open(d + "memprof.pb.gz", "wb") as f:
            f.write(gzip.compress(build_profile().SerializeToString()))
    cfg = SofaConfig(logdir=top)
    cfg.cluster_hosts = ["h1", "h2"]
    paths = export_folded(cfg, frames={})
    assert cfg.path("memprof.folded") in paths
    lines = open(cfg.path("memprof.folded")).read().splitlines()
    by_stack = dict(line.rsplit(" ", 1) for line in lines)
    for host in ("h1", "h2"):
        assert by_stack[f"{host};train_step;_pjit_call_impl_python;__call__"] \
            == str(8 * 2**20)


def test_api_profile_captures_memprof(logdir):
    """End-to-end on the CPU backend: sofa_tpu.api.profile leaves a
    parseable allocation-site snapshot beside the trace."""
    import jax
    import jax.numpy as jnp

    import sofa_tpu.api as api

    cfg = SofaConfig(logdir=logdir)
    cfg.enable_tpu_mon = False  # exercise the final-snapshot fallback path
    with api.profile(logdir, cfg=cfg):
        x = jnp.ones((64, 64))
        jax.jit(lambda a: a @ a)(x).block_until_ready()
    df, meta = load_memprof(logdir)
    assert df is not None and not df.empty
    assert meta.get("trigger") == "final"
    assert (df["kind"] == "buffer").any()


def test_diff_cli_stages_board(tmp_path):
    """`sofa diff` leaves a browsable logdir: the board (incl. the Diff
    page reading tpu_diff/mem_diff/swarm_diff) is staged beside the CSVs."""
    import subprocess
    import sys as _sys

    mb = 2**20
    for name, sites in (("base", {"train_step": 100 * mb}),
                        ("match", {"train_step": 150 * mb})):
        d = tmp_path / name
        d.mkdir()
        with open(d / "memprof.pb.gz", "wb") as f:
            f.write(gzip.compress(make_profile(sites).SerializeToString()))
    out = str(tmp_path / "out") + "/"
    r = subprocess.run(
        [_sys.executable, "-m", "sofa_tpu", "diff",
         "--base_logdir", str(tmp_path / "base"),
         "--match_logdir", str(tmp_path / "match"),
         "--logdir", out],
        capture_output=True, text=True, timeout=180,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr[-400:]
    assert os.path.isfile(out + "mem_diff.csv")
    assert os.path.isfile(out + "diff-report.html")
    assert os.path.isfile(out + "sofa_board.js")
