"""`sofa agent` — the per-host fleet daemon: watch, spool, forward.

The fleet control plane's host half (ROADMAP "Fleet control plane";
docs/FLEET.md).  A long-lived loop that

1. **watches** a directory for finished recordings (a logdir counts as
   finished when its ``run_manifest.json`` exists, no pipeline verb
   holds the mid-write sentinel, its journal has no begun-but-uncommitted
   stage, and it has been quiet for ``--settle_s``);
2. **spools** each finished run into a durable local content-addressed
   archive (archive/spool.py — the bytes are safe before any network is
   involved, and the ingest is journaled in the logdir so `sofa resume`
   replays a kill);
3. **forwards** spooled runs to the fleet service (`sofa serve`) over
   the idempotent resumable upload protocol (archive/client.py): bounded
   timeouts, capped exponential backoff with jitter, typed refusals.

Failure stance: the service being unreachable, overloaded (503), or
over quota (429) NEVER loses a run and never wedges the loop — the run
stays spooled and the drain pass retries on the next tick, with the
service attempts themselves backed off (jittered) so a thousand agents
whose service just rebooted do not re-arrive as one wave.  A SIGKILLed
agent restarts into the same spool and journal; thanks to the
have-list protocol the resumed push re-sends zero committed objects.

Each delivered (or spooled-only) run gets ``meta.agent`` — and, once the
service acks the commit, ``meta.serve`` — in its own run manifest, so
`sofa status` and tools/manifest_check.py can audit the transport leg
exactly like any pipeline stage (docs/OBSERVABILITY.md).

Exit codes (``--once``): 0 everything discovered is spooled and — when a
service is configured — delivered; 1 degraded (spooled but undelivered);
2 usage error.  Daemon mode runs until SIGINT.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from sofa_tpu import faults, telemetry
from sofa_tpu.concurrency import jittered_backoff
from sofa_tpu.printing import (
    print_error,
    print_progress,
    print_warning,
)


def discover_logdirs(watch: str) -> List[str]:
    """Candidate logdirs under ``watch``: the directory itself and its
    immediate children that carry a run manifest."""
    from sofa_tpu.telemetry import MANIFEST_NAME

    out: List[str] = []
    candidates = [watch]
    try:
        candidates += sorted(
            os.path.join(watch, n) for n in os.listdir(watch)
            if os.path.isdir(os.path.join(watch, n)))
    except OSError:
        return []
    for d in candidates:
        if os.path.isfile(os.path.join(d, MANIFEST_NAME)):
            out.append(d if d.endswith("/") else d + "/")
    return out


def logdir_ready(logdir: str, settle_s: float = 0.5) -> bool:
    """A run is shippable when nothing is still writing it: no live
    mid-write sentinel, no begun-but-uncommitted journal stage, and the
    manifest quiet for ``settle_s`` (a recording host finishing analyze
    re-writes it within seconds)."""
    from sofa_tpu.durability import journal_state, read_journal
    from sofa_tpu.trace import derived_writing

    if derived_writing(logdir):
        return False
    for stage, st in journal_state(read_journal(logdir)).items():
        if stage != "push" and not st.get("committed"):
            return False
    from sofa_tpu.archive.spool import _manifest_mtime

    mtime = _manifest_mtime(logdir)
    if mtime is None:
        return False
    return (time.time() - mtime / 1e9) >= max(settle_s, 0.0)  # sofa-lint: disable=SL003 — compared against a file mtime, which IS wall clock; monotonic has no common epoch with it


class _AgentPass:
    """One scan+drain pass; holds the tick's tallies for meta.agent and
    the exit code."""

    def __init__(self):
        self.discovered = 0
        self.spooled = 0
        self.pushed = 0
        self.failed = 0


def _push_meta(spool, client, logdir: str, run_id: str) -> dict:
    """Deliver one spooled run; returns the meta.agent ``push`` section
    (status pushed|spooled|rejected) and patches meta.serve on success."""
    from sofa_tpu import metrics as fleet_metrics
    from sofa_tpu.archive.client import ServiceRejected, ServiceUnavailable

    t0 = time.perf_counter()
    base_attempts = client.attempts
    # one trace id per push ATTEMPT: every request of this delivery
    # (have/put/commit) carries it as X-Sofa-Trace, and the service's
    # spans — handler, WAL append, drain, index commit — join under it
    # in the exported fleet trace (docs/FLEET.md "Observing the tier")
    client.trace_id = fleet_metrics.new_trace_id()
    try:
        result = spool.push(run_id, client)
    except ServiceRejected as e:
        print_warning(f"agent: service rejected {run_id[:12]}: {e} — "
                      "the run stays spooled"
                      + (" (over quota: raise --quota_mb server-side or "
                         "gc the tenant)" if e.quota else ""))
        return {"status": "rejected", "error": str(e)[:300],
                "quota": bool(e.quota),
                "trace": client.trace_id,
                "attempts": client.attempts - base_attempts,
                "wall_s": round(time.perf_counter() - t0, 3)}
    except ServiceUnavailable as e:
        print_warning(f"agent: service unreachable for {run_id[:12]}: "
                      f"{e} — spooled, will retry")
        return {"status": "spooled", "error": str(e)[:300],
                "trace": client.trace_id,
                "attempts": client.attempts - base_attempts,
                "wall_s": round(time.perf_counter() - t0, 3)}
    spool.mark_pushed(logdir, run_id, result.get("server") or {})
    return {"status": "pushed",
            "objects_sent": result.get("objects_sent", 0),
            "bytes_sent": result.get("bytes_sent", 0),
            "trace": client.trace_id,
            "attempts": client.attempts - base_attempts,
            "wall_s": round(time.perf_counter() - t0, 3),
            "server": result.get("server") or {}}


def _process_logdir(cfg, spool, client, logdir: str,
                    tick: _AgentPass) -> None:
    """Spool (if changed) and forward (if a service is configured) one
    finished logdir, recording meta.agent/meta.serve in its manifest."""
    import copy

    tick.discovered += 1
    ent = spool.entry(logdir)
    needs_spool = spool.needs_ingest(logdir)
    needs_push = client is not None and (needs_spool
                                         or not ent.get("pushed"))
    if not (needs_spool or needs_push):
        return
    lcfg = copy.deepcopy(cfg)
    lcfg.logdir = logdir
    lcfg.__post_init__()
    tel = telemetry.begin("agent")
    try:
        if needs_spool:
            summary = spool.spool(lcfg)
            if summary is None:
                tick.failed += 1
                return
            tick.spooled += 1
        run_id = spool.entry(logdir).get("run")
        meta_agent = {
            "spool": spool.root,
            "run": run_id,
            "service": client.base if client is not None else None,
            "tenant": client.tenant if client is not None else None,
        }
        push = None
        if client is not None and run_id:
            push = _push_meta(spool, client, logdir, run_id)
            meta_agent["push"] = push
            if push["status"] == "pushed":
                tick.pushed += 1
                ack = push.get("server") or {}
                tel.set_meta(serve={
                    "url": client.base,
                    "tenant": str(ack.get("tenant", client.tenant)),
                    "run": str(ack.get("run", run_id)),
                    "new": bool(ack.get("new")),
                    "quota_used_mb": ack.get("quota_used_mb"),
                    "committed_unix": round(time.time(), 3),
                })
                if isinstance(ack.get("tier"), dict):
                    # the scaled tier stamps which worker committed the
                    # run and how deep its ingest queue sat — the
                    # manifest's record of the placement decision
                    # (validated by tools/manifest_check.py)
                    tel.set_meta(tier={**ack["tier"],
                                       "url": client.base})
                if isinstance(ack.get("metrics"), dict):
                    # the tier's observability fold rides the ack home:
                    # the manifest records the push's trace id, wall,
                    # and the worker's scrape/SLO state at commit time
                    # (validated by tools/manifest_check.py)
                    tel.set_meta(metrics={
                        **ack["metrics"],
                        "trace": push.get("trace") or "",
                        "push_wall_s": push.get("wall_s"),
                    })
                    if ack["metrics"].get("slo_ok") is not None:
                        tel.set_meta(slo={
                            "ok": bool(ack["metrics"].get("slo_ok")),
                            "breaching": list(
                                ack["metrics"].get("slo_breaching")
                                or []),
                        })
            else:
                tick.failed += 1
        if client is not None:
            # the endpoint-health picture AFTER the push (meta.health,
            # docs/OBSERVABILITY.md): which endpoint served, how many
            # failovers this client has taken, which breakers stand
            # open — failover leaves a durable record in the manifest,
            # never just a log line
            from sofa_tpu.archive.client import HEALTH_SCHEMA, HEALTH_VERSION

            meta_agent["service"] = client.base  # post-failover truth
            tel.set_meta(health={
                "schema": HEALTH_SCHEMA, "version": HEALTH_VERSION,
                "endpoints": list(client.endpoints),
                "active": client.base,
                "failovers": int(client.failovers),
                "breakers_open": [u for u in client.endpoints
                                  if client.breaker_open(u)],
            })
        tel.set_meta(agent=meta_agent)
        tel.write(logdir, rc=0 if (push is None
                                   or push["status"] == "pushed") else 1,
                  cfg=lcfg)
        spool.refresh_fingerprint(logdir)
    finally:
        telemetry.end(tel)


def _drain_orphans(spool, client, tick: _AgentPass) -> None:
    """Push spooled runs whose source logdir is gone (deleted after
    spooling — the spool is the only surviving copy, which is the
    point): delivery must not depend on the source outliving the
    outage."""
    for run_id, logdir in spool.pending_runs().items():
        if os.path.isdir(logdir):
            continue  # the normal per-logdir path owns it
        push = _push_meta(spool, client, logdir, run_id)
        if push["status"] == "pushed":
            tick.pushed += 1
        else:
            tick.failed += 1


def sofa_agent(cfg, watch: "str | None" = None, once: bool = False) -> int:
    """``sofa agent <watch_dir> [--service URL] [--once]`` — see the
    module docstring for the loop and the exit contract."""
    from sofa_tpu.archive.client import client_from_cfg
    from sofa_tpu.archive.spool import Spool, resolve_spool

    watch = watch or cfg.logdir
    if not os.path.isdir(watch):
        print_error(f"agent: watch directory {watch} does not exist")
        return 2
    plan = faults.install_from(cfg)
    try:
        spool = Spool(resolve_spool(cfg))
        client = client_from_cfg(cfg)
        if client is None:
            print_progress(
                f"agent: no --service configured — spool-only mode "
                f"(runs land in {spool.root}; point --service at a "
                "`sofa serve` endpoint to forward)")
        poll_s = max(float(getattr(cfg, "agent_poll_s", 5.0) or 5.0), 0.05)
        settle_s = float(getattr(cfg, "agent_settle_s", 0.5) or 0.0)
        service_failures = 0
        next_service_try = 0.0  # monotonic; 0 = try immediately
        while True:
            tick = _AgentPass()
            # Service attempts are themselves backed off (jittered):
            # after an outage, a fleet of agents must trickle back, not
            # stampede.  --once always makes one full attempt.
            gate_service = (client is not None and not once
                            and time.monotonic() < next_service_try)
            use_client = None if gate_service else client
            for logdir in discover_logdirs(watch):
                if os.path.abspath(logdir).startswith(spool.root):
                    continue  # never ship the spool into itself
                if not logdir_ready(logdir, settle_s=settle_s):
                    continue
                _process_logdir(cfg, spool, use_client, logdir, tick)
            if use_client is not None:
                _drain_orphans(spool, use_client, tick)
            if use_client is not None:
                if tick.failed:
                    service_failures += 1
                    backoff = jittered_backoff(
                        service_failures,
                        getattr(cfg, "agent_backoff_s", 0.5),
                        getattr(cfg, "agent_backoff_cap_s", 30.0))
                    next_service_try = time.monotonic() + backoff
                elif tick.pushed or tick.discovered:
                    service_failures = 0
            if once:
                undelivered = len(spool.pending_runs()) \
                    if client is not None else 0
                print_progress(
                    f"agent: {tick.discovered} run(s) discovered, "
                    f"{tick.spooled} spooled, {tick.pushed} pushed"
                    + (f", {undelivered} awaiting the service"
                       if undelivered else ""))
                return 1 if undelivered else 0
            time.sleep(poll_s)
    except KeyboardInterrupt:
        print_progress("agent: stopped")
        return 0
    finally:
        if plan is not None:
            faults.clear()
