"""Test configuration.

Tests never require real TPU hardware: JAX is pinned to the CPU backend with
8 virtual devices so sharding/collective paths (device meshes, pjit,
shard_map) compile and execute anywhere.  Set SOFA_TPU_TEST_REAL=1 to run the
few opt-in tests that want the real chip.
"""

import os
import sys

# The image may force-register a TPU backend via sitecustomize regardless of
# JAX_PLATFORMS (and that backend's init can hang if the device tunnel is
# busy), so the env var alone is not enough: pin the platform at the jax
# config level below, before any backend initializes.  Tests that need the
# 8-device mesh build it via make_mesh(..., platform="cpu"); the
# virtual-device flag guarantees the CPU backend always has 8.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

if not os.environ.get("SOFA_TPU_TEST_REAL"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

import pytest  # noqa: E402


@pytest.fixture
def logdir(tmp_path):
    d = tmp_path / "sofalog"
    d.mkdir()
    return str(d) + "/"


def pytest_configure(config):
    config.addinivalue_line("markers", "real_tpu: needs the real TPU chip")


def pytest_collection_modifyitems(config, items):
    if os.environ.get("SOFA_TPU_TEST_REAL"):
        return
    skip = pytest.mark.skip(reason="set SOFA_TPU_TEST_REAL=1 to run on real TPU")
    for item in items:
        if "real_tpu" in item.keywords:
            item.add_marker(skip)
