"""Install-story smoke gate (VERDICT r2 missing #4).

The reference proves install+run across a distro matrix
(/root/reference/test/test.py:37-78); CI here cannot boot distros, but this
is the same contract scaled to one image: the COMMITTED tree (git archive,
so an uncommitted packaging break cannot hide) installs into a FRESH venv
with pip, the `sofa` console script exists, and record -> report completes
there.  Offline-safe: --system-site-packages resolves numpy/pandas from the
image and --no-deps/--no-build-isolation keep pip off the network.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(argv, **kw):
    return subprocess.run(argv, capture_output=True, text=True, **kw)


def _matrix_mod():
    import importlib

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return importlib.import_module("test_matrix")
    finally:
        sys.path.pop(0)


def test_matrix_discovers_running_interpreter_first():
    mod = _matrix_mod()
    found = mod.discover_interpreters()
    assert found, "no interpreters discovered"
    assert found[0][0] == sys.executable
    # labels are impl+version, deduplicated
    labels = [key for _, key in found]
    assert len(set(labels)) == len(labels)
    assert labels[0].startswith("cpython3.")


def test_matrix_log_rows_are_dated_and_appended(tmp_path, monkeypatch):
    mod = _matrix_mod()
    log = tmp_path / "INSTALL_MATRIX.log"
    monkeypatch.setattr(mod, "LOG", str(log))
    mod._append_log([("debian:stable-slim", "PASS", "ok", 12.3)])
    mod._append_log([("python:3.11-slim", "SKIP", "no docker", 0.1)])
    lines = log.read_text().splitlines()
    assert len(lines) == 2  # appended, not truncated
    assert "PASS" in lines[0] and "SKIP" in lines[1]
    assert lines[0].split()[0].endswith("Z")  # dated, UTC


def test_matrix_venv_case_degradation_ladder(tmp_path):
    """The ensurepip-less interpreter climbs the ladder (--without-pip
    venv + host-pip --python), so with a bogus wheel it reaches and FAILS
    at the install step — venv creation is no longer the blocker; an
    interpreter that cannot create ANY venv still yields the explicit
    SKIP row, never a silent pass or a crash."""
    mod = _matrix_mod()
    bare = "/usr/bin/python3.11"
    if os.access(bare, os.X_OK) \
            and bare != os.path.realpath(sys.executable):
        _label, status, detail, _dt = mod.venv_case(
            bare, "bare", wheel="unused.whl", workdir=str(tmp_path))
        assert status == "FAIL"
        assert "pip install" in detail
    broken = tmp_path / "notpython"
    broken.write_text("#!/bin/sh\nexit 1\n")
    broken.chmod(0o755)
    _label, status, detail, _dt = mod.venv_case(
        str(broken), "broken", wheel="unused.whl", workdir=str(tmp_path))
    assert status == "SKIP"
    assert "venv creation unavailable" in detail


def test_fresh_venv_install_and_record(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    # git archive emits a tar stream of the COMMITTED tree; pipe to tar -x
    p1 = subprocess.Popen(["git", "-C", REPO, "archive", "HEAD"],
                          stdout=subprocess.PIPE)
    p2 = subprocess.Popen(["tar", "-x", "-C", str(src)], stdin=p1.stdout)
    p1.stdout.close()
    assert p2.wait() == 0 and p1.wait() == 0

    venv = tmp_path / "venv"
    r = _run([sys.executable, "-m", "venv", str(venv)])
    if r.returncode != 0:
        pytest.skip(f"venv creation unavailable here: {r.stderr[-300:]}")
    # This image's python is itself a venv, so `--system-site-packages`
    # would expose the BARE system python (no setuptools/numpy).  Expose
    # the running env's site-packages via PYTHONPATH instead — same
    # offline-dependency role, and the venv's own site-packages (where
    # sofa_tpu lands) still wins for the package under test.
    import sysconfig

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=sysconfig.get_paths()["purelib"])
    if not (venv / "bin" / "pip").is_file():
        # an ensurepip-less interpreter creates a pip-less venv: the
        # install story cannot be exercised here at all — explicit skip,
        # never a misleading FAIL (the matrix tool's degradation ladder
        # covers climbing past this on hosts that have a host pip)
        pytest.skip("venv created without pip (ensurepip unavailable)")
    pip = str(venv / "bin" / "pip")
    r = _run([pip, "install", "--no-deps", "--no-build-isolation",
              "--quiet", str(src)], env=env)
    assert r.returncode == 0, r.stderr[-2000:]

    sofa = venv / "bin" / "sofa"
    assert sofa.is_file(), "console script not installed"
    # Run every subprocess from a NEUTRAL cwd with the fresh-install check
    # first: cwd (the repo checkout) and PYTHONPATH both precede the venv's
    # site-packages on sys.path, and either would shadow the install under
    # test — masking exactly the packaging breaks this gate exists to catch.
    cwd = str(tmp_path)
    r = _run([str(venv / "bin" / "python"), "-c",
              "import sofa_tpu; print(sofa_tpu.__file__)"], env=env, cwd=cwd)
    assert r.returncode == 0, r.stderr[-500:]
    assert str(venv) in r.stdout, (
        f"venv import resolves outside the venv: {r.stdout.strip()}")
    logdir = str(tmp_path / "ilog") + "/"
    r = _run([str(sofa), "record", "sleep 1", "--logdir", logdir,
              "--disable_xprof"], env=env, cwd=cwd)
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-1000:])
    assert os.path.isfile(os.path.join(logdir, "sofa_time.txt"))
    r = _run([str(sofa), "report", "--logdir", logdir], env=env, cwd=cwd)
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-1000:])
    assert "Complete!!" in r.stdout
