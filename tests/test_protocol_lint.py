"""Protocol-contract flow analysis: SL024–SL028 fixtures, seeded
mutations over copies of the shipped service.py/client.py, the
shipped-tree closure gate, and the `sofa protocol` inventory verb
(schema, exit codes, determinism).

Fixture trees opt into companions per rule, mirroring the artifact
graph's discipline: a STATUS_ERRORS-bearing pkg/archive/protocol.py
activates the graph; docs/OBSERVABILITY.md enables SL026; a
KINDS+NET_KINDS module enables SL027; tools/*.py at the repo root
enables the chaos-reference leg.  Absent companions keep those legs
inert, matching how a single-file `sofa lint` run behaves.
"""

import json
import os
import sys
import textwrap

from sofa_tpu.lint.core import ProjectContext, lint_paths
from sofa_tpu.lint.rules import default_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

PROTO_IDS = ("SL024", "SL025", "SL026", "SL027", "SL028")

#: Minimal vocabulary: statuses without error strings so fixtures that
#: never attach a body do not trip the dead-vocabulary leg.
SLIM_VOCAB = """
    STATUS_ERRORS = {200: (), 429: (), 503: (), 504: ()}
    RETRY_AFTER_STATUSES = (429, 503)
    NO_RETRY_AFTER_STATUSES = (504,)
    CLIENT_RETRY_STATUSES = (429, 503)
    CLIENT_FATAL_STATUSES = (401,)
    CLIENT_RESUME_STATUSES = ()
    CLIENT_RETRY_FLOOR = 500
    ROUTES = ("GET /v1/ping",)
"""

#: Full vocabulary for the clean kitchen-sink tree: typed errors, a
#: fatal override, a placeholder route.
FULL_VOCAB = """
    ERR_BUSY = "busy"
    ERR_QUOTA = "quota"
    STATUS_ERRORS = {
        200: (),
        429: (ERR_BUSY, ERR_QUOTA),
        503: (ERR_BUSY,),
        504: ("deadline",),
    }
    RETRY_AFTER_STATUSES = (429, 503)
    NO_RETRY_AFTER_STATUSES = (504,)
    CLIENT_RETRY_STATUSES = (429, 503)
    CLIENT_FATAL_STATUSES = (401,)
    CLIENT_RESUME_STATUSES = ()
    CLIENT_RETRY_FLOOR = 500
    FATAL_ERRORS = (ERR_QUOTA,)
    ROUTES = (
        "GET /v1/ping",
        "POST /v1/<tenant>/commit",
    )
"""


def run_protocol_rules(tmp_path, files):
    """Write {relname: src} under tmp_path, lint the .py files, return
    only the SL024–SL028 findings."""
    paths = []
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
        if rel.endswith(".py"):
            paths.append(str(p))
    project = ProjectContext.detect(paths, base=str(tmp_path))
    fs = lint_paths(paths, default_rules(), project=project,
                    base=str(tmp_path))
    return [f for f in fs if f.rule_id in PROTO_IDS]


# --- the clean kitchen sink -------------------------------------------------

def test_protocol_clean_kitchen_sink(tmp_path):
    """A tree exercising every leg — typed refusals, Retry-After on
    both sides of the line, matching client dispatch, a placeholder
    route — produces zero findings."""
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": FULL_VOCAB,
        "pkg/archive/service.py": """
            class H:
                def _json(self, code, doc, retry_after=None):
                    pass
                def _refuse(self, key, code, doc, retry_after="1"):
                    self._json(code, doc, retry_after=retry_after)
                def handle(self):
                    seg = "ping"
                    if seg == "commit":
                        pass
                    path = "/v1/ping"
                    self._json(200, {"ok": True})
                    self._refuse("429_busy", 429, {"error": "busy"})
                    self._refuse("429_quota", 429, {"error": "quota"})
                    self._refuse("503_busy", 503, {"error": "busy"})
                    self._refuse("504_deadline", 504,
                                 {"error": "deadline"}, retry_after=None)
        """,
        "pkg/archive/client.py": """
            class ServiceUnavailable(Exception):
                pass
            class ServiceRejected(Exception):
                pass
            def dispatch(e, doc):
                url = "/v1/<t>/commit"
                if e.code == 429 and doc.get("error") == "quota":
                    raise ServiceRejected(e)
                if e.code in (401,):
                    raise ServiceRejected(e)
                if e.code in (429, 503) or e.code >= 500:
                    raise ServiceUnavailable(e)
        """,
    })
    assert fs == [], [f.render() for f in fs]


# --- SL024 ------------------------------------------------------------------

def test_sl024_flags_undeclared_status(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/archive/service.py": """
            class H:
                def _json(self, code, doc, retry_after=None):
                    pass
                def handle(self):
                    path = "/v1/ping"
                    self._json(418, {})
        """,
    })
    assert [f.rule_id for f in fs] == ["SL024"]
    assert "418" in fs[0].message and fs[0].file.endswith("service.py")


def test_sl024_flags_unknown_client_route(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/archive/service.py": """
            def handle(self):
                seg = "ping"
                url = "/v1/ghost"
        """,
    })
    assert [f.rule_id for f in fs] == ["SL024"]
    assert "/v1/ghost" in fs[0].message and "404" in fs[0].message


def test_sl024_flags_dead_route_entry(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB.replace(
            'ROUTES = ("GET /v1/ping",)',
            'ROUTES = ("GET /v1/ping", "GET /v1/ghost")'),
        "pkg/archive/service.py": """
            def handle(self):
                url = "/v1/ping"
        """,
    })
    assert [f.rule_id for f in fs] == ["SL024"]
    assert "ghost" in fs[0].message and "dead route" in fs[0].message
    assert fs[0].file.endswith("protocol.py")


def test_sl024_flags_dead_status_and_dead_error(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": """
            STATUS_ERRORS = {
                200: (),
                418: (),
                429: ("busy", "dead_err"),
            }
            RETRY_AFTER_STATUSES = (429,)
            NO_RETRY_AFTER_STATUSES = ()
            CLIENT_RETRY_STATUSES = (429,)
            CLIENT_FATAL_STATUSES = ()
            CLIENT_RESUME_STATUSES = ()
            ROUTES = ("GET /v1/ping",)
        """,
        "pkg/archive/service.py": """
            class H:
                def _json(self, code, doc, retry_after=None):
                    pass
                def handle(self):
                    path = "/v1/ping"
                    self._json(200, {"ok": 1})
                    self._json(429, {"error": "busy"}, retry_after="1")
        """,
        "pkg/archive/client.py": """
            class ServiceUnavailable(Exception):
                pass
            def dispatch(e):
                if e.code in (429,):
                    raise ServiceUnavailable(e)
        """,
    })
    msgs = sorted(f.message for f in fs)
    assert [f.rule_id for f in fs] == ["SL024", "SL024"]
    assert any("418" in m and "dead status" in m for m in msgs)
    assert any("dead_err" in m and "dead vocabulary" in m for m in msgs)
    assert all(f.file.endswith("protocol.py") for f in fs)


# --- SL025 ------------------------------------------------------------------

def test_sl025_flags_missing_retry_after(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/archive/service.py": """
            class H:
                def _json(self, code, doc, retry_after=None):
                    pass
                def handle(self):
                    path = "/v1/ping"
                    self._json(429, {})
        """,
    })
    assert [f.rule_id for f in fs] == ["SL025"]
    assert "attaches no Retry-After" in fs[0].message


def test_sl025_flags_deadline_with_retry_after(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/archive/service.py": """
            class H:
                def _json(self, code, doc, retry_after=None):
                    pass
                def handle(self):
                    path = "/v1/ping"
                    self._json(504, {}, retry_after="1")
        """,
    })
    assert [f.rule_id for f in fs] == ["SL025"]
    assert "deadline refusal" in fs[0].message


def test_sl025_flags_untyped_and_undeclared_bodies(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB.replace(
            "429: ()", '429: ("busy",)'),
        "pkg/archive/service.py": """
            class H:
                def _json(self, code, doc, retry_after=None):
                    pass
                def _refuse(self, key, code, doc, retry_after="1"):
                    self._json(code, doc, retry_after=retry_after)
                def handle(self):
                    path = "/v1/ping"
                    self._refuse("k", 429, {})
                    self._refuse("k", 429, {"error": "mystery"})
                    self._refuse("k", 429, {"error": "busy"})
        """,
    })
    msgs = sorted(f.message for f in fs)
    assert [f.rule_id for f in fs] == ["SL025", "SL025"]
    assert any("no typed" in m for m in msgs)
    assert any("'mystery'" in m and "STATUS_ERRORS[429]" in m
               for m in msgs)


def test_sl025_flags_raw_send_bypass(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/archive/service.py": """
            class H:
                def handle(self):
                    path = "/v1/ping"
                    self.send_response(503)
        """,
    })
    assert [f.rule_id for f in fs] == ["SL025"]
    assert "bypasses the typed refusal helpers" in fs[0].message


# --- SL026 ------------------------------------------------------------------

def test_sl026_both_directions(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/svc.py": """
            import os
            ALIVE = os.environ.get("SOFA_ALIVE", "")
            GHOST = os.environ.get("SOFA_GHOST", "")
        """,
        "docs/OBSERVABILITY.md": """
            | knob | default |
            |---|---|
            | `SOFA_ALIVE` | - |
            | `SOFA_DEAD` | - |
        """,
    })
    assert [f.rule_id for f in fs] == ["SL026", "SL026"]
    ghost = next(f for f in fs if "SOFA_GHOST" in f.message)
    assert ghost.file.endswith("svc.py")
    assert "undocumented" in ghost.message
    dead = next(f for f in fs if "SOFA_DEAD" in f.message)
    assert dead.file.endswith("OBSERVABILITY.md")
    assert "dead registry row" in dead.message


def test_sl026_inert_without_docs_registry(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/svc.py": """
            import os
            GHOST = os.environ.get("SOFA_GHOST", "")
        """,
    })
    assert [f.rule_id for f in fs] == []


# --- SL027 ------------------------------------------------------------------

def test_sl027_phantom_and_unconsumed_kinds(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/faults.py": """
            KINDS = ("stall", "drop")
            NET_KINDS = ("refuse",)
            def consume(spec):
                if spec.kind == "stall":
                    return 1
                if spec.kind == "ghost":
                    return 2
        """,
    })
    msgs = sorted(f.message for f in fs)
    assert [f.rule_id for f in fs] == ["SL027"] * 3
    assert any("'ghost'" in m and "phantom" in m for m in msgs)
    assert any("'drop'" in m and "silent no-op" in m for m in msgs)
    assert any("'refuse'" in m and "silent no-op" in m for m in msgs)


def test_sl027_taint_scoping_in_importers(tmp_path):
    """A `.kind` compare on a name NOT assigned from a faults.*() call
    (an ingest task, say) is a different namespace and stays silent; a
    fault-tainted name consuming an undeclared kind is a phantom."""
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/faults.py": """
            KINDS = ("stall",)
            NET_KINDS = ("refuse",)
            def maybe_fault(op):
                return None
            def consume(spec):
                if spec.kind == "stall":
                    return 1
                if spec.kind == "refuse":
                    return 2
        """,
        "pkg/ingest.py": """
            from pkg import faults
            def go(pending):
                tasks = [t for t in pending if t.kind == "proc"]
                spec = faults.maybe_fault("op")
                if spec and spec.kind == "ghost2":
                    return spec
                return tasks
        """,
    })
    assert [f.rule_id for f in fs] == ["SL027"]
    assert "'ghost2'" in fs[0].message
    assert fs[0].file.endswith("ingest.py")


def test_sl027_chaos_reference_leg(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/faults.py": """
            KINDS = ("stall",)
            NET_KINDS = ("refuse",)
            def consume(spec):
                if spec.kind == "stall":
                    return 1
                if spec.kind == "refuse":
                    return 2
        """,
        "tools/chaos.py": """
            USED = ("stall",)
        """,
    })
    assert [f.rule_id for f in fs] == ["SL027"]
    assert "'refuse'" in fs[0].message
    assert "no chaos/test reference" in fs[0].message


# --- SL028 ------------------------------------------------------------------

def test_sl028_divergent_retry_set_and_floor(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/archive/client.py": """
            class ServiceUnavailable(Exception):
                pass
            def dispatch(e):
                if e.code in (408,) or \\
                        e.code > 500:
                    raise ServiceUnavailable(e)
        """,
    })
    msgs = sorted(f.message for f in fs)
    assert all(f.rule_id == "SL028" for f in fs)
    assert any("[408]" in m and "CLIENT_RETRY_STATUSES" in m
               for m in msgs)
    assert any("retry floor 501" in m for m in msgs)
    assert any("429" in m and "never retries" in m for m in msgs)


def test_sl028_fatal_override_outside_vocabulary(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": """
            STATUS_ERRORS = {200: (), 429: ("busy", "quota")}
            RETRY_AFTER_STATUSES = ()
            NO_RETRY_AFTER_STATUSES = ()
            CLIENT_RETRY_STATUSES = ()
            CLIENT_FATAL_STATUSES = ()
            CLIENT_RESUME_STATUSES = ()
            FATAL_ERRORS = ("quota",)
            ROUTES = ("GET /v1/ping",)
        """,
        "pkg/archive/client.py": """
            class ServiceRejected(Exception):
                pass
            def dispatch(e, doc):
                if e.code == 429 and doc.get("error") == "busy":
                    raise ServiceRejected(e)
        """,
    })
    sl28 = [f for f in fs if f.rule_id == "SL028"]
    msgs = sorted(f.message for f in sl28)
    assert len(sl28) == 2
    assert any("'busy'" in m and "FATAL_ERRORS does not declare" in m
               for m in msgs)
    assert any("'quota'" in m and "dead override" in m for m in msgs)


def test_sl028_fatal_vs_retryable_contradiction(tmp_path):
    fs = run_protocol_rules(tmp_path, {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/archive/client.py": """
            class ServiceUnavailable(Exception):
                pass
            class ServiceRejected(Exception):
                pass
            def dispatch(e):
                if e.code in (429, 503) or e.code >= 500:
                    raise ServiceUnavailable(e)
                if e.code in (429,):
                    raise ServiceRejected(e)
        """,
    })
    assert any(f.rule_id == "SL028"
               and "contradictory contract" in f.message for f in fs)


# --- seeded mutations over copies of the shipped tree -----------------------

SHIPPED = {
    "pkg/archive/protocol.py": "sofa_tpu/archive/protocol.py",
    "pkg/archive/service.py": "sofa_tpu/archive/service.py",
    "pkg/archive/tier.py": "sofa_tpu/archive/tier.py",
    "pkg/archive/client.py": "sofa_tpu/archive/client.py",
}


def lint_shipped_copy(tmp_path, mutations=None, extra_shipped=(),
                      extra_files=None):
    """Copy the shipped protocol core under tmp_path/pkg, apply
    {destrel: fn(src)} mutations, lint, return (protocol findings,
    {destrel: final source})."""
    sources, paths = {}, []
    items = dict(SHIPPED)
    items.update(dict(extra_shipped))
    for destrel, realrel in items.items():
        with open(os.path.join(REPO, realrel), encoding="utf-8") as f:
            src = f.read()
        if mutations and destrel in mutations:
            src = mutations[destrel](src)
        p = tmp_path / destrel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        sources[destrel] = src
        paths.append(str(p))
    for rel, body in (extra_files or {}).items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(body)
    project = ProjectContext.detect(paths, base=str(tmp_path))
    fs = lint_paths(paths, default_rules(), project=project,
                    base=str(tmp_path))
    return [f for f in fs if f.rule_id in PROTO_IDS], sources


def _line_of(src: str, needle: str) -> int:
    assert needle in src
    return src[:src.index(needle)].count("\n") + 1


def test_shipped_copy_is_protocol_clean(tmp_path):
    """The protocol core (vocab + service + tier + client) is closed on
    its own — the mutation tests below start from zero findings."""
    fs, _src = lint_shipped_copy(tmp_path)
    assert fs == [], [f.render() for f in fs]


def test_mutated_refusal_without_retry_after_fires_sl025(tmp_path):
    needle = 'self._refuse("503_draining", 503, {"error": ERR_DRAINING})'
    swap = needle[:-1] + ", retry_after=None)"
    fs, src = lint_shipped_copy(tmp_path, mutations={
        "pkg/archive/service.py":
            lambda s: s.replace(needle, swap, 1)})
    line = _line_of(src["pkg/archive/service.py"], swap)
    hits = [f for f in fs if f.rule_id == "SL025"]
    assert [(f.file.endswith("service.py"), f.line) for f in hits] == \
        [(True, line)], [f.render() for f in fs]
    assert "attaches no Retry-After" in hits[0].message


def test_mutated_route_typo_fires_sl024(tmp_path):
    # a one-segment typo would still shape-match "OPTIONS /v1/<any>";
    # typo a two-segment route so no declared shape fits
    needle = 'f"/v1/{self.tenant}/have"'
    swap = 'f"/v1/{self.tenant}/hav"'
    fs, src = lint_shipped_copy(tmp_path, mutations={
        "pkg/archive/client.py": lambda s: s.replace(needle, swap, 1)})
    line = _line_of(src["pkg/archive/client.py"], swap)
    hits = [f for f in fs if f.rule_id == "SL024"]
    assert [(f.file.endswith("client.py"), f.line) for f in hits] == \
        [(True, line)], [f.render() for f in fs]
    assert "/v1/<>/hav" in hits[0].message


def test_mutated_retry_tuple_fires_sl028(tmp_path):
    needle = "if e.code in CLIENT_RETRY_STATUSES or \\"
    swap = "if e.code in (408, 422, 425) or \\"
    fs, src = lint_shipped_copy(tmp_path, mutations={
        "pkg/archive/client.py": lambda s: s.replace(needle, swap, 1)})
    line = _line_of(src["pkg/archive/client.py"], swap)
    hits = [f for f in fs if f.rule_id == "SL028"
            and "diverge" in f.message]
    assert [(f.file.endswith("client.py"), f.line) for f in hits] == \
        [(True, line)], [f.render() for f in fs]
    assert "[408, 422, 425]" in hits[0].message


def test_mutated_ghost_knob_fires_sl026(tmp_path):
    from sofa_tpu.lint.protocol_rules import _KNOB_RE

    tokens = set()
    for realrel in SHIPPED.values():
        with open(os.path.join(REPO, realrel), encoding="utf-8") as f:
            tokens |= set(_KNOB_RE.findall(f.read()))
    docs = "| knob | default |\n|---|---|\n" + "\n".join(
        f"| `{t}` | - |" for t in sorted(tokens)) + "\n"
    probe = '\n_GHOST_PROBE = os.environ.get("SOFA_GHOST_KNOB", "")\n'
    fs, src = lint_shipped_copy(
        tmp_path,
        mutations={"pkg/archive/service.py": lambda s: s + probe},
        extra_files={"docs/OBSERVABILITY.md": docs})
    line = _line_of(src["pkg/archive/service.py"], "SOFA_GHOST_KNOB")
    hits = [f for f in fs if f.rule_id == "SL026"]
    assert [(f.file.endswith("service.py"), f.line) for f in hits] == \
        [(True, line)], [f.render() for f in fs]
    assert "SOFA_GHOST_KNOB" in hits[0].message


def test_mutated_phantom_kind_fires_sl027(tmp_path):
    probe = ('\ndef _phantom_probe(spec):\n'
             '    if spec.kind == "sl027_phantom":\n'
             '        return spec\n')
    fs, src = lint_shipped_copy(
        tmp_path,
        mutations={"pkg/faults.py": lambda s: s + probe},
        extra_shipped={"pkg/faults.py": "sofa_tpu/faults.py"}.items())
    line = _line_of(src["pkg/faults.py"], 'spec.kind == "sl027_phantom"')
    hits = [f for f in fs if f.rule_id == "SL027"
            and "phantom" in f.message]
    assert [(f.file.endswith("faults.py"), f.line) for f in hits] == \
        [(True, line)], [f.render() for f in hits]
    assert "'sl027_phantom'" in hits[0].message


# --- the shipped-tree closure gate -----------------------------------------

def test_shipped_tree_has_zero_protocol_findings():
    """Stronger than the baseline gate: SL024–SL028 must be fully
    burned down on the shipped tree — no grandfathering."""
    pkg = os.path.join(REPO, "sofa_tpu")
    fs = lint_paths([pkg], default_rules(), base=REPO)
    proto = [f for f in fs if f.rule_id in PROTO_IDS]
    assert proto == [], [f.render() for f in proto]


# --- the inventory verb -----------------------------------------------------

def test_build_inventory_full_closure():
    from sofa_tpu.protocol import build_inventory

    doc = build_inventory()
    assert doc["ok"] is True
    assert doc["counts"]["violations"] == 0
    paths = {r["path"] for r in doc["routes"]}
    assert "/v1/ping" in paths and len(doc["routes"]) >= 10
    statuses = {s["status"]: s for s in doc["statuses"]}
    assert statuses[429]["retry_after"] is True
    assert statuses[504]["no_retry_after"] is True
    assert statuses[401]["client"] == "fatal"
    assert statuses[409]["client"] == "resume"
    assert statuses[503]["client"] == "retry"
    knobs = {k["knob"] for k in doc["knobs"]}
    assert "SOFA_SERVE_TOKEN" in knobs
    undocumented = [k["knob"] for k in doc["knobs"]
                    if k["read_by"] and not k["documented"]]
    assert undocumented == []
    kinds = {r["kind"]: r for r in doc["fault_kinds"]}
    assert "http_500" in kinds
    for row in kinds.values():
        assert row["consumed_by"] and row["referenced"], row


def test_protocol_inventory_schema_validates():
    from sofa_tpu.protocol import build_inventory
    import manifest_check

    doc = build_inventory()
    assert manifest_check.validate_protocol_inventory(doc) == []
    assert manifest_check.validate_protocol_inventory(
        doc, require_healthy=True) == []
    broken = dict(doc, version=99)
    assert manifest_check.validate_protocol_inventory(broken)


def test_manifest_check_dispatches_protocol_doc(tmp_path):
    from sofa_tpu.protocol import build_inventory
    import manifest_check

    path = tmp_path / "proto.json"
    path.write_text(json.dumps(build_inventory()))
    assert manifest_check.check_path(str(path)) == 0


def test_cli_protocol_verb_json(capsys):
    from sofa_tpu.cli import main

    assert main(["protocol", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "sofa_tpu/protocol_inventory"
    assert doc["version"] == 1
    assert doc["ok"] is True


def test_cli_protocol_verb_human(capsys):
    from sofa_tpu.cli import main

    assert main(["protocol"]) == 0
    out = capsys.readouterr().out
    assert "GET /v1/ping" in out
    assert "full closure" in out


# --- lint CLI: --rule filter, exit codes, determinism -----------------------

def test_lint_cli_rule_filter_exit_codes(tmp_path, capsys):
    from sofa_tpu.lint.cli import run_lint

    rc = run_lint([os.path.join(REPO, "sofa_tpu"), "--base", REPO,
                   "--rule", ",".join(PROTO_IDS)])
    capsys.readouterr()
    assert rc == 0
    pkg = tmp_path / "pkg" / "archive"
    pkg.mkdir(parents=True)
    (pkg / "protocol.py").write_text(textwrap.dedent(SLIM_VOCAB))
    (pkg / "service.py").write_text(textwrap.dedent("""
        class H:
            def _json(self, code, doc, retry_after=None):
                pass
            def handle(self):
                path = "/v1/ping"
                self._json(418, {})
    """))
    rc = run_lint([str(tmp_path / "pkg"), "--no-baseline",
                   "--base", str(tmp_path), "--rule", "SL024"])
    capsys.readouterr()
    assert rc == 1
    rc = run_lint([str(tmp_path / "pkg"), "--no-baseline",
                   "--base", str(tmp_path), "--rule", "BOGUS"])
    capsys.readouterr()
    assert rc == 2


def test_explain_covers_protocol_rules(capsys):
    from sofa_tpu.lint.cli import run_lint

    for rid in PROTO_IDS:
        assert run_lint(["--explain", rid]) == 0
        out = capsys.readouterr().out
        assert rid in out


def test_protocol_findings_deterministic_across_jobs(tmp_path):
    files = {
        "pkg/archive/protocol.py": SLIM_VOCAB,
        "pkg/archive/service.py": """
            class H:
                def _json(self, code, doc, retry_after=None):
                    pass
                def handle(self):
                    path = "/v1/ping"
                    self._json(418, {})
                    self._json(429, {})
                    self._json(504, {}, retry_after="1")
        """,
        "pkg/archive/client.py": """
            class ServiceUnavailable(Exception):
                pass
            def dispatch(e):
                if e.code in (408,):
                    raise ServiceUnavailable(e)
        """,
    }
    paths = []
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
        paths.append(str(p))
    project = ProjectContext.detect(paths, base=str(tmp_path))
    runs = []
    for jobs in (1, 4):
        fs = lint_paths(paths, default_rules(), project=project,
                        base=str(tmp_path), jobs=jobs)
        runs.append([(f.file, f.line, f.rule_id, f.message)
                     for f in fs if f.rule_id in PROTO_IDS])
    assert runs[0] == runs[1]
    assert {r[2] for r in runs[0]} >= {"SL024", "SL025", "SL028"}
