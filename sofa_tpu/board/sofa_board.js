/* sofa_tpu board — self-contained chart + CSV utilities.
 *
 * The reference board depends on CDN-hosted d3/Highcharts/Plotly
 * (sofaboard/index.html); profiling hosts are often air-gapped, so this
 * board ships its own small canvas renderer instead: zoomable/pannable
 * scatter+line timeline with legend toggles and nearest-point tooltips.
 */

"use strict";

/* ---------- CSV ---------- */
function parseCSV(text) {
  const lines = text.split(/\r?\n/).filter((l) => l.length > 0);
  if (!lines.length) return { header: [], rows: [] };
  const header = splitCSVLine(lines[0]);
  const rows = lines.slice(1).map(splitCSVLine);
  return { header, rows };
}
function splitCSVLine(line) {
  const out = [];
  let cur = "", inQ = false;
  for (let i = 0; i < line.length; i++) {
    const c = line[i];
    if (inQ) {
      if (c === '"' && line[i + 1] === '"') { cur += '"'; i++; }
      else if (c === '"') inQ = false;
      else cur += c;
    } else if (c === '"') inQ = true;
    else if (c === ",") { out.push(cur); cur = ""; }
    else cur += c;
  }
  out.push(cur);
  return out;
}
function csvColumn(csv, name) {
  const i = csv.header.indexOf(name);
  return i < 0 ? [] : csv.rows.map((r) => r[i]);
}
async function fetchCSV(path) {
  const resp = await fetch(path);
  if (!resp.ok) throw new Error(path + ": " + resp.status);
  return parseCSV(await resp.text());
}

/* ---------- columnar series data ----------
 * report.js and the LOD tiles ship series data as columnar arrays
 * {x:[],y:[],d:[],names:[table],ni:[codes]} — names are interned into a
 * string table + int codes (smaller payload + one C-encoder dumps
 * server-side); the renderer works on point objects, so decode once at
 * load.  Legacy per-point arrays and plain name arrays pass through. */
function pointsFromColumnar(data) {
  if (!data) return [];
  if (Array.isArray(data)) return data;
  const xs = data.x || [], ys = data.y || [], ds = data.d || [];
  const table = data.names || null, codes = data.ni || null;
  const plain = data.name || [];
  const out = new Array(xs.length);
  for (let i = 0; i < xs.length; i++) {
    const nm = table ? (table[codes[i]] || "") : (plain[i] || "");
    out[i] = { x: xs[i], y: ys[i], name: nm, d: ds[i] || 0 };
  }
  return out;
}

/* ---------- LOD tiles ----------
 * Deep zoom fetches pre-gzipped columnar tiles from the pyramid the
 * pipeline wrote under _tiles/ (sofa_tpu/tiles.py).  The sofa viz server
 * negotiates Content-Encoding so the browser inflates transparently; a
 * dumb static host hands back raw gzip bytes, which are inflated here via
 * DecompressionStream (the magic-byte check tells the two apart). */
async function fetchGzJSON(path) {
  const resp = await fetch(path);
  if (!resp.ok) throw new Error(path + ": " + resp.status);
  const buf = new Uint8Array(await resp.arrayBuffer());
  if (buf.length > 1 && buf[0] === 0x1f && buf[1] === 0x8b) {
    const stream = new Blob([buf]).stream()
      .pipeThrough(new DecompressionStream("gzip"));
    return JSON.parse(await new Response(stream).text());
  }
  return JSON.parse(new TextDecoder().decode(buf));
}

/* Tiles are fixed-point integer columnar: x delta-encoded at sx
 * resolution, y/d scaled ints, names interned (sofa_tpu/tiles.py) —
 * integers encode and gzip far tighter than floats. */
function pointsFromTile(t) {
  if (!t.xd) return pointsFromColumnar(t);
  const out = new Array(t.xd.length);
  const table = t.names || [], codes = t.ni || [];
  let acc = 0;
  for (let i = 0; i < t.xd.length; i++) {
    acc += t.xd[i];
    out[i] = {
      x: acc * t.sx,
      y: (t.yv[i] || 0) * t.sy,
      name: table[codes[i]] || "",
      d: (t.dv[i] || 0) * t.sd,
    };
  }
  return out;
}

class TileLoader {
  constructor(manifest, base) {
    this.manifest = manifest || { series: {} };
    this.base = base || this.manifest.dir || "_tiles";
    this.cache = new Map(); // url -> Promise<tile|null>; 404 = empty window
  }
  entry(name) { return (this.manifest.series || {})[name]; }
  levelFor(ent, span) {
    // deepest level whose tile windows are ~the view span (1-4 tiles
    // visible); clamped to the pyramid's real depth
    const domain = Math.max(ent.x1 - ent.x0, 1e-12);
    const lvl = Math.ceil(Math.log2(Math.max(domain / Math.max(span, 1e-12), 1))) + 1;
    return Math.max(0, Math.min(ent.levels - 1, lvl));
  }
  tile(ent, name, level, n) {
    const url = this.base + "/" + (ent.path || name) + "/" + level + "/" + n + ".json.gz";
    if (!this.cache.has(url)) {
      this.cache.set(url, fetchGzJSON(url).catch(() => null));
    }
    return this.cache.get(url);
  }
  async range(name, x0, x1) {
    // every tile overlapping [x0, x1] at the view-appropriate level,
    // decoded and concatenated into renderer points (x-ordered: tiles are
    // ordered and points within a tile are x-sorted)
    const ent = this.entry(name);
    if (!ent) return null;
    const level = this.levelFor(ent, x1 - x0);
    const domain = Math.max(ent.x1 - ent.x0, 1e-12);
    const nt = Math.pow(2, level);
    const clamp = (v) => Math.max(0, Math.min(nt - 1, v));
    const lo = clamp(Math.floor(((x0 - ent.x0) / domain) * nt));
    const hi = clamp(Math.floor(((x1 - ent.x0) / domain) * nt));
    const jobs = [];
    for (let n = lo; n <= hi && jobs.length < 16; n++) {
      jobs.push(this.tile(ent, name, level, n));
    }
    const tiles = await Promise.all(jobs);
    const pts = [];
    let exact = true, count = 0;
    for (const t of tiles) {
      if (!t) continue; // sparse pyramid: missing tile = empty window
      exact = exact && !!t.exact;
      count += t.count || 0;
      for (const p of pointsFromTile(t)) pts.push(p);
    }
    return { level: level, points: pts, exact: exact, count: count };
  }
}

/* ---------- number formatting ---------- */
function fmt(v) {
  if (!isFinite(v)) return "-";
  const a = Math.abs(v);
  if (a >= 1e12) return (v / 1e12).toFixed(2) + "T";
  if (a >= 1e9) return (v / 1e9).toFixed(2) + "G";
  if (a >= 1e6) return (v / 1e6).toFixed(2) + "M";
  if (a >= 1e3) return (v / 1e3).toFixed(2) + "k";
  if (a >= 1 || a === 0) return v.toFixed(3).replace(/\.?0+$/, "");
  if (a >= 1e-3) return (v * 1e3).toFixed(3) + "m";
  if (a >= 1e-6) return (v * 1e6).toFixed(2) + "u";
  return (v * 1e9).toFixed(2) + "n";
}

/* ---------- Timeline chart ---------- */
class Timeline {
  constructor(canvas, opts) {
    this.canvas = canvas;
    this.ctx = canvas.getContext("2d");
    this.series = []; // {name,title,color,kind,data:[{x,y,name,d}],visible}
    this.opts = Object.assign({ logY: false, xLabel: "time (s)", yLabel: "" }, opts || {});
    this.margin = { l: 64, r: 16, t: 10, b: 34 };
    this.tooltip = null;
    this._bindEvents();
  }
  setSeries(series) {
    this.series = series.map((s) => {
      const pts = pointsFromColumnar(s.data);
      // overview = the report.js level-0 data; deep zoom swaps s.data for
      // tile points and resetView restores this
      return Object.assign({ visible: true }, s, { data: pts, overview: pts });
    });
    this.resetView();
  }
  setData(name, pts) {
    for (const s of this.series) {
      if (s.name === name) s.data = pts;
    }
  }
  resetView() {
    for (const s of this.series) {
      if (s.overview) s.data = s.overview;
    }
    let x0 = Infinity, x1 = -Infinity, y0 = Infinity, y1 = -Infinity;
    for (const s of this.series) {
      if (!s.visible) continue;
      for (const p of s.data) {
        if (p.x < x0) x0 = p.x;
        if (p.x > x1) x1 = p.x;
        const y = this._y(p.y);
        if (y < y0) y0 = y;
        if (y > y1) y1 = y;
      }
    }
    if (!isFinite(x0)) { x0 = 0; x1 = 1; y0 = 0; y1 = 1; }
    if (x0 === x1) { x1 = x0 + 1; }
    if (y0 === y1) { y1 = y0 + 1; }
    const padX = (x1 - x0) * 0.02, padY = (y1 - y0) * 0.05;
    this.view = { x0: x0 - padX, x1: x1 + padX, y0: y0 - padY, y1: y1 + padY };
    this.draw();
    this._emitViewChange();
  }
  _emitViewChange() {
    // debounced: a zoom gesture is a burst of wheel events — fetch tiles
    // once the view settles, not per tick
    if (!this.opts.onViewChange) return;
    clearTimeout(this._vcTimer);
    this._vcTimer = setTimeout(() => this.opts.onViewChange(this.view), 150);
  }
  _y(v) { return this.opts.logY ? Math.log10(Math.max(v, 1e-12)) : v; }
  _sx(x) {
    const w = this.canvas.width - this.margin.l - this.margin.r;
    return this.margin.l + ((x - this.view.x0) / (this.view.x1 - this.view.x0)) * w;
  }
  _sy(y) {
    const h = this.canvas.height - this.margin.t - this.margin.b;
    return this.margin.t + h - ((y - this.view.y0) / (this.view.y1 - this.view.y0)) * h;
  }
  draw() {
    const ctx = this.ctx, W = this.canvas.width, H = this.canvas.height;
    const css = getComputedStyle(document.body);
    ctx.fillStyle = css.getPropertyValue("--chart-bg") || "#ffffff";
    ctx.fillRect(0, 0, W, H);
    this._grid();
    for (const s of this.series) {
      if (!s.visible) continue;
      ctx.fillStyle = s.color;
      ctx.strokeStyle = s.color;
      if (s.kind === "line") {
        const groups = {};
        for (const p of s.data) {
          (groups[p.name] = groups[p.name] || []).push(p);
        }
        for (const key of Object.keys(groups)) {
          ctx.beginPath();
          let started = false;
          for (const p of groups[key]) {
            const sx = this._sx(p.x), sy = this._sy(this._y(p.y));
            if (!started) { ctx.moveTo(sx, sy); started = true; }
            else ctx.lineTo(sx, sy);
          }
          ctx.stroke();
        }
      } else if (s.kind === "bubble") {
        // comm scatter marks: per-point radius (payload) + color (dst)
        ctx.globalAlpha = 0.75;
        for (const p of s.data) {
          const sx = this._sx(p.x), sy = this._sy(this._y(p.y));
          if (sx < this.margin.l - 10 || sx > W - this.margin.r + 10) continue;
          ctx.fillStyle = p.c || s.color;
          ctx.beginPath();
          ctx.arc(sx, sy, p.r || 2, 0, 2 * Math.PI);
          ctx.fill();
        }
        ctx.globalAlpha = 1;
        ctx.fillStyle = s.color;
      } else {
        for (const p of s.data) {
          const sx = this._sx(p.x), sy = this._sy(this._y(p.y));
          if (sx < this.margin.l - 2 || sx > W - this.margin.r + 2) continue;
          ctx.fillRect(sx - 1.5, sy - 1.5, 3, 3);
        }
      }
    }
    if (this.tooltip) this._drawTooltip();
  }
  _grid() {
    const ctx = this.ctx, W = this.canvas.width, H = this.canvas.height;
    ctx.strokeStyle = "#8884";
    ctx.fillStyle = "#888";
    ctx.font = "11px sans-serif";
    ctx.lineWidth = 1;
    const xt = this._ticks(this.view.x0, this.view.x1, 8);
    for (const t of xt) {
      const sx = this._sx(t);
      ctx.beginPath(); ctx.moveTo(sx, this.margin.t); ctx.lineTo(sx, H - this.margin.b); ctx.stroke();
      ctx.fillText(fmt(t), sx - 12, H - this.margin.b + 14);
    }
    const yt = this.opts.yLabels
      ? this._intTicks(this.view.y0, this.view.y1, this.opts.yLabels.length)
      : this._ticks(this.view.y0, this.view.y1, 6);
    for (const t of yt) {
      const sy = this._sy(t);
      ctx.beginPath(); ctx.moveTo(this.margin.l, sy); ctx.lineTo(W - this.margin.r, sy); ctx.stroke();
      const label = this.opts.yLabels
        ? String(this.opts.yLabels[t] || "").slice(0, 15)
        : (this.opts.logY ? "1e" + fmt(t) : fmt(t));
      ctx.fillText(label, 4, sy + 4);
    }
    ctx.fillText(this.opts.xLabel, W / 2 - 20, H - 4);
  }
  _intTicks(a, b, n) {
    // categorical axis: integer positions only, at most ~12 labels shown
    const lo = Math.max(0, Math.ceil(a)), hi = Math.min(n - 1, Math.floor(b));
    const step = Math.max(1, Math.ceil((hi - lo + 1) / 12));
    const out = [];
    for (let v = lo; v <= hi; v += step) out.push(v);
    return out;
  }
  _ticks(a, b, n) {
    const span = b - a;
    if (span <= 0) return [a];
    const step = Math.pow(10, Math.floor(Math.log10(span / n)));
    const err = span / n / step;
    const mult = err >= 7.5 ? 10 : err >= 3 ? 5 : err >= 1.5 ? 2 : 1;
    const s = step * mult;
    const out = [];
    for (let v = Math.ceil(a / s) * s; v <= b; v += s) out.push(v);
    return out;
  }
  _bindEvents() {
    const cv = this.canvas;
    let dragging = null;
    cv.addEventListener("wheel", (e) => {
      e.preventDefault();
      const f = e.deltaY > 0 ? 1.2 : 1 / 1.2;
      const mx = this.view.x0 + ((e.offsetX - this.margin.l) /
        (cv.width - this.margin.l - this.margin.r)) * (this.view.x1 - this.view.x0);
      this.view.x0 = mx + (this.view.x0 - mx) * f;
      this.view.x1 = mx + (this.view.x1 - mx) * f;
      this.draw();
      this._emitViewChange();
    });
    cv.addEventListener("mousedown", (e) => { dragging = { x: e.offsetX, v: { ...this.view } }; });
    window.addEventListener("mouseup", () => { dragging = null; });
    cv.addEventListener("mousemove", (e) => {
      if (dragging) {
        const dx = (e.offsetX - dragging.x) / (cv.width - this.margin.l - this.margin.r) *
          (dragging.v.x1 - dragging.v.x0);
        this.view.x0 = dragging.v.x0 - dx;
        this.view.x1 = dragging.v.x1 - dx;
        this.draw();
        this._emitViewChange();
      } else {
        this._hover(e.offsetX, e.offsetY);
      }
    });
    cv.addEventListener("dblclick", () => this.resetView());
  }
  _hover(mx, my) {
    let best = null, bestD = 144;
    for (const s of this.series) {
      if (!s.visible) continue;
      for (const p of s.data) {
        const dx = this._sx(p.x) - mx, dy = this._sy(this._y(p.y)) - my;
        const d = dx * dx + dy * dy;
        if (d < bestD) { bestD = d; best = { p, s }; }
      }
    }
    this.tooltip = best ? { mx, my, best } : null;
    this.draw();
  }
  _drawTooltip() {
    const { mx, my, best } = this.tooltip;
    const ctx = this.ctx;
    const lines = [
      best.s.title,
      "t=" + fmt(best.p.x) + "s  y=" + fmt(best.p.y) +
        (best.p.d ? "  dur=" + fmt(best.p.d) + "s" : ""),
      best.p.name || "",
    ].filter((l) => l);
    ctx.font = "12px sans-serif";
    const w = Math.max(...lines.map((l) => ctx.measureText(l).width)) + 12;
    const h = lines.length * 16 + 8;
    let x = mx + 12, y = my - h - 4;
    if (x + w > this.canvas.width) x = mx - w - 12;
    if (y < 0) y = my + 12;
    ctx.fillStyle = "#222c";
    ctx.fillRect(x, y, w, h);
    ctx.fillStyle = best.s.color;
    ctx.fillRect(x, y, 4, h);
    ctx.fillStyle = "#fff";
    lines.forEach((l, i) => ctx.fillText(l, x + 8, y + 16 * (i + 1) - 2));
  }
}

/* ---------- legend ---------- */
function buildLegend(container, chart) {
  container.innerHTML = "";
  for (const s of chart.series) {
    const item = document.createElement("span");
    item.className = "legend-item" + (s.visible ? "" : " off");
    const sw = document.createElement("span");
    sw.className = "swatch";
    sw.style.background = s.color;
    item.appendChild(sw);
    item.appendChild(document.createTextNode(s.title + " (" + s.data.length + ")"));
    item.onclick = () => {
      s.visible = !s.visible;
      item.classList.toggle("off", !s.visible);
      chart.draw();
    };
    container.appendChild(item);
  }
}

/* ---------- tables ---------- */
function renderTable(el, header, rows, maxRows) {
  const t = document.createElement("table");
  const tr = document.createElement("tr");
  for (const h of header) {
    const th = document.createElement("th");
    th.textContent = h;
    tr.appendChild(th);
  }
  t.appendChild(tr);
  for (const row of rows.slice(0, maxRows || 200)) {
    const r = document.createElement("tr");
    for (const v of row) {
      const td = document.createElement("td");
      const n = Number(v);
      td.textContent = v !== "" && isFinite(n) && /[0-9]/.test(v) ? fmt(n) : v;
      r.appendChild(td);
    }
    t.appendChild(r);
  }
  el.innerHTML = "";
  el.appendChild(t);
}

/* ---------- parallel coordinates with per-axis brushing ----------
 * The reference's cpu/gpu reports are d3 parallel-coordinates with a drag
 * brush on every schema column (sofaboard/cpu-report.html:86-162); this is
 * the same exploration surface on the board's own canvas renderer (no CDN).
 * Drag vertically on an axis to brush; click an axis to clear it;
 * double-click anywhere to clear all brushes.  onSelect(rows) fires after
 * every brush change with the rows inside every active extent. */
class ParallelCoords {
  constructor(canvas, opts) {
    this.canvas = canvas;
    this.ctx = canvas.getContext("2d");
    this.opts = Object.assign({ color: "rgba(121,82,179,0.35)", maxRows: 3000 }, opts || {});
    this.dims = [];    // [{key,label,min,max,log}]
    this.rows = [];    // array of objects key->number
    this.brushes = {}; // key -> [loVal, hiVal] in data space
    this.margin = { l: 30, r: 30, t: 26, b: 10 };
    this._drag = null;
    this._bindEvents();
  }
  setData(dims, rows) {
    if (rows.length > this.opts.maxRows) {
      // uniform sample for draw responsiveness; brushing filters the sample
      const stride = Math.ceil(rows.length / this.opts.maxRows);
      rows = rows.filter((_, i) => i % stride === 0);
    }
    this.dims = dims.map((d) => {
      let min = Infinity, max = -Infinity;
      for (const r of rows) {
        const v = this._v(r, d);
        if (isFinite(v)) { if (v < min) min = v; if (v > max) max = v; }
      }
      if (!isFinite(min)) { min = 0; max = 1; }
      if (min === max) max = min + 1;
      return Object.assign({ min, max }, d);
    });
    this.rows = rows;
    this.brushes = {};
    this.draw();
  }
  _v(row, dim) {
    const raw = Number(row[dim.key]);
    return dim.log ? Math.log10(Math.max(raw, 1e-12)) : raw;
  }
  _ax(i) {
    const w = this.canvas.width - this.margin.l - this.margin.r;
    return this.margin.l + (this.dims.length < 2 ? w / 2 : (i * w) / (this.dims.length - 1));
  }
  _sy(dim, v) {
    const h = this.canvas.height - this.margin.t - this.margin.b;
    return this.margin.t + h - ((v - dim.min) / (dim.max - dim.min)) * h;
  }
  _yToVal(dim, py) {
    const h = this.canvas.height - this.margin.t - this.margin.b;
    return dim.min + ((this.margin.t + h - py) / h) * (dim.max - dim.min);
  }
  selected() {
    const active = this.dims.filter((d) => this.brushes[d.key]);
    if (!active.length) return this.rows;
    return this.rows.filter((r) => active.every((d) => {
      const v = this._v(r, d), [lo, hi] = this.brushes[d.key];
      return v >= lo && v <= hi;
    }));
  }
  draw() {
    const ctx = this.ctx, W = this.canvas.width, H = this.canvas.height;
    ctx.clearRect(0, 0, W, H);
    const sel = this.selected(); // one filter pass per frame, reused below
    const keep = new Set(sel);
    const anyBrush = this.dims.some((d) => this.brushes[d.key]);
    // dimmed lines first so selected lines stay on top
    for (const pass of anyBrush ? ["dim", "fg"] : ["fg"]) {
      ctx.strokeStyle = pass === "dim" ? "rgba(160,160,160,0.08)" : this.opts.color;
      ctx.beginPath();
      for (const r of this.rows) {
        if ((pass === "fg") !== keep.has(r)) continue;
        for (let i = 0; i < this.dims.length; i++) {
          const d = this.dims[i];
          const x = this._ax(i), y = this._sy(d, this._v(r, d));
          if (i === 0) ctx.moveTo(x, y); else ctx.lineTo(x, y);
        }
      }
      ctx.stroke();
    }
    ctx.font = "11px sans-serif";
    for (let i = 0; i < this.dims.length; i++) {
      const d = this.dims[i], x = this._ax(i);
      ctx.strokeStyle = "#999";
      ctx.beginPath();
      ctx.moveTo(x, this.margin.t);
      ctx.lineTo(x, H - this.margin.b);
      ctx.stroke();
      ctx.fillStyle = "#555";
      ctx.textAlign = "center";
      ctx.fillText(d.label || d.key, x, 12);
      ctx.fillStyle = "#999";
      ctx.fillText(fmt(d.log ? Math.pow(10, d.max) : d.max), x, this.margin.t - 3);
      ctx.fillText(fmt(d.log ? Math.pow(10, d.min) : d.min), x, H - 1);
      const b = this.brushes[d.key];
      if (b) {
        const y0 = this._sy(d, b[1]), y1 = this._sy(d, b[0]);
        ctx.fillStyle = "rgba(121,82,179,0.18)";
        ctx.fillRect(x - 7, y0, 14, y1 - y0);
        ctx.strokeStyle = "#7952b3";
        ctx.strokeRect(x - 7, y0, 14, y1 - y0);
      }
    }
    if (this.opts.onSelect) this.opts.onSelect(sel, this.rows);
  }
  _axisAt(px) {
    for (let i = 0; i < this.dims.length; i++) {
      if (Math.abs(px - this._ax(i)) <= 12) return i;
    }
    return -1;
  }
  _pos(ev) {
    const rect = this.canvas.getBoundingClientRect();
    return {
      x: ((ev.clientX - rect.left) * this.canvas.width) / rect.width,
      y: ((ev.clientY - rect.top) * this.canvas.height) / rect.height,
    };
  }
  _bindEvents() {
    this.canvas.addEventListener("mousedown", (ev) => {
      const p = this._pos(ev);
      const i = this._axisAt(p.x);
      if (i < 0) return;
      this._drag = { dim: this.dims[i], y0: p.y, moved: false };
    });
    this.canvas.addEventListener("mousemove", (ev) => {
      const p = this._pos(ev);
      if (!this._drag) {
        this.canvas.style.cursor = this._axisAt(p.x) >= 0 ? "row-resize" : "default";
        return;
      }
      this._drag.moved = true;
      const d = this._drag.dim;
      const a = this._yToVal(d, this._drag.y0), b = this._yToVal(d, p.y);
      this.brushes[d.key] = [Math.min(a, b), Math.max(a, b)];
      this.draw();
    });
    const finish = () => {
      if (this._drag && !this._drag.moved) { // plain click clears this axis
        delete this.brushes[this._drag.dim.key];
        this.draw();
      }
      this._drag = null;
    };
    this.canvas.addEventListener("mouseup", finish);
    this.canvas.addEventListener("mouseleave", finish);
    this.canvas.addEventListener("dblclick", () => {
      this.brushes = {};
      this.draw();
    });
  }
}

/* Parallel-coords bootstrap shared by the cpu/tpu report pages: fetch a
 * trace CSV, map its rows onto the requested dims, wire the count label. */
async function mountParallelCoords(canvasId, countId, file, dims, filter) {
  const csv = await fetchCSV(file);
  const idx = {};
  for (const d of dims) idx[d.key] = csv.header.indexOf(d.key);
  let rows = csv.rows;
  if (filter) {
    // filter receives a memoized name->index resolver, not the raw header:
    // header.indexOf per row would scan the header millions of times on a
    // pod-scale trace
    const memo = {};
    const col = (name) =>
      (name in memo ? memo[name] : (memo[name] = csv.header.indexOf(name)));
    rows = rows.filter((r) => filter(r, col));
  }
  const recs = rows.map((r) => {
    const o = {};
    for (const d of dims) o[d.key] = Number(r[idx[d.key]]);
    return o;
  });
  if (!recs.length) throw new Error(file + ": no rows");
  const countEl = document.getElementById(countId);
  const pc = new ParallelCoords(document.getElementById(canvasId), {
    onSelect: (sel, all) => {
      if (countEl) countEl.textContent = sel.length + " / " + all.length + " rows in brush";
    },
  });
  pc.setData(dims, recs);
  return pc;
}

/* ---------- stacked bar chart ---------- */
function drawStackedBars(canvas, labels, series, legendEl) {
  // series: [{title, color, values:[...]}] — one stack segment per series,
  // one bar per label (the run-report per-iteration breakdown).
  const ctx = canvas.getContext("2d");
  const W = canvas.width, H = canvas.height;
  ctx.clearRect(0, 0, W, H);
  const n = labels.length;
  if (!n || !series.length) return;
  let max = 1e-12;
  for (let i = 0; i < n; i++) {
    let t = 0;
    for (const sr of series) t += Number(sr.values[i]) || 0;
    if (t > max) max = t;
  }
  const left = 54, bottom = 20, top = 8;
  const bw = Math.min(48, (W - left - 10) / n);
  ctx.font = "11px sans-serif";
  labels.forEach((label, i) => {
    const x = left + i * bw;
    let y = H - bottom;
    for (const sr of series) {
      const v = Number(sr.values[i]) || 0;
      const hpx = (H - bottom - top) * (v / max);
      ctx.fillStyle = sr.color;
      ctx.fillRect(x + 1, y - hpx, Math.max(bw - 2, 1), hpx);
      y -= hpx;
    }
    ctx.fillStyle = "#888";
    if (n <= 40 || i % Math.ceil(n / 40) === 0) {
      ctx.fillText(String(label), x + 1, H - 6);
    }
  });
  ctx.fillStyle = "#888";
  ctx.fillText(fmt(max) + "s", 4, top + 10);
  ctx.fillText("0", 4, H - bottom);
  if (legendEl) {
    legendEl.innerHTML = "";
    for (const sr of series) {
      const item = document.createElement("span");
      item.className = "legend-item";
      const sw = document.createElement("span");
      sw.className = "swatch";
      sw.style.background = sr.color;
      item.appendChild(sw);
      item.appendChild(document.createTextNode(sr.title));
      legendEl.appendChild(item);
    }
  }
}

/* ---------- bar chart ---------- */
function drawBars(canvas, labels, values, color) {
  const ctx = canvas.getContext("2d");
  const W = canvas.width, H = canvas.height;
  ctx.clearRect(0, 0, W, H);
  const max = Math.max(...values, 1e-12);
  const left = 220, barH = Math.min(22, (H - 10) / Math.max(labels.length, 1));
  ctx.font = "11px sans-serif";
  labels.forEach((label, i) => {
    const y = 6 + i * barH;
    ctx.fillStyle = "#888";
    ctx.fillText(String(label).slice(0, 34), 4, y + barH * 0.7);
    ctx.fillStyle = color || "#7952b3";
    ctx.fillRect(left, y + 2, (W - left - 60) * (values[i] / max), barH - 5);
    ctx.fillStyle = "#888";
    ctx.fillText(fmt(values[i]), left + (W - left - 60) * (values[i] / max) + 4, y + barH * 0.7);
  });
}

/* ---------- live polling (`sofa live`, docs/LIVE.md) ---------- */
/* Polls run_manifest.json's meta.live stamp (rewritten atomically every
 * live epoch) and refetches report.js when the epoch advances, so the
 * timeline grows while the job runs.  Mid-epoch reads always see the
 * last committed generation (every live write is tmp+rename atomic);
 * polling stops on its own once the stream drains (active: false) or
 * the logdir carries no live section at all. */
function initLivePoll(onUpdate, intervalMs) {
  let epoch = null;
  let stopped = false;
  const tick = async () => {
    if (stopped) return;
    try {
      const resp = await fetch("run_manifest.json", { cache: "no-cache" });
      if (!resp.ok) return;
      const doc = await resp.json();
      const live = (doc.meta || {}).live;
      if (!live) { stopped = epoch !== null; return; }
      if (!live.active) {
        if (epoch !== null && live.epoch !== epoch) {
          await refetch(live);  // the drain's final converged artifacts
        }
        stopped = true;
        return;
      }
      if (live.epoch === epoch) return;
      await refetch(live);
    } catch (e) {
      /* a poll racing an epoch retries on the next tick */
    }
  };
  const refetch = async (live) => {
    const rep = await fetch("report.js", { cache: "no-cache" });
    if (!rep.ok) return;
    const text = await rep.text();
    const payload = JSON.parse(
      text.slice(text.indexOf("=") + 1).trim().replace(/;+$/, ""));
    epoch = live.epoch;
    onUpdate(payload, live);
  };
  const timer = setInterval(() => {
    if (stopped) { clearInterval(timer); return; }
    tick();
  }, intervalMs || 3000);
  tick();
  return timer;
}

function liveStatusText(live) {
  if (!live) return "";
  const srcs = live.sources || {};
  let streaming = 0, stalled = 0;
  for (const k in srcs) {
    if (srcs[k].status === "streaming") streaming++;
    if (srcs[k].status === "stalled") stalled++;
  }
  let txt = "LIVE epoch " + live.epoch + " · " + streaming + " streaming";
  if (stalled) txt += " · " + stalled + " STALLED";
  if (typeof live.watermark_s === "number")
    txt += " · watermark " + fmt(live.watermark_s) + "s";
  return txt;
}
