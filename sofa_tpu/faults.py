"""Fault injection: every degradation path, exercisable on demand.

SOFA's contract is that a profiling run *always* yields a usable trace even
when individual collectors misbehave (the reference's kill-all epilogue,
sofa_record.py:480-523).  Those paths are exactly the ones that never run in
a healthy dev loop — "Fake Runs, Real Fixes" (PAPERS.md) argues injected
failures are the only way to keep them honest.  This module parses a fault
spec and exposes the hook points the runtime threads through
``collectors/base.py`` and the preprocess ingest fan-out:

    SOFA_FAULTS='procmon:die@2s,tcpdump:wedge@stop,perf:fail@start'
    sofa record "python train.py" --inject_faults 'xprof:truncate@harvest'

Grammar (comma-joined entries)::

    entry  = <target> ":" <kind> [ "@" <when> ]
    target = collector name (procmon, tcpdump, perf, xprof, vmstat, ...)
             or ingest source name (mpstat, nettrace, xplane, ...;
             "pcap" aliases nettrace)
    kind   = die      kill the collector's backing process/thread mid-run
                      (@<delay> after start, e.g. @2s; default immediately)
             wedge    block forever at @<phase> (stop|harvest; default stop)
                      — exercises the bounded-epilogue deadlines
             fail     raise at @<phase> (start|stop|harvest; default start)
             truncate halve the collector's output files at harvest
             corrupt  ingest: the source's parse raises CorruptRawError,
                      driving the quarantine path
    when   = "start" | "stop" | "harvest" | <float>"s" (die delay)

Network fault kinds (target ``service``, consumed by the fleet transport
client in sofa_tpu/archive/client.py — the server is never faulted, so
what these prove is the CLIENT's retry/resume/backoff contract)::

    service:conn_refused[@start|@always]   connection refused
    service:conn_reset[@start|@always]     connection reset mid-request —
                                           the ack (if any) is lost in
                                           flight; retry must be a
                                           committed no-op
    service:stall[@start|@always]          request exceeds its deadline
    service:http_500[@start|@always]       server-side 5xx
    service:partial@<fraction>             upload body truncated at the
                                           fraction (0 < f < 1) — the
                                           server's hash check rejects it

Tier fault kinds (target ``service``, consumed by the SERVER side of the
scaled tier — archive/tier.py/service.py — never by the client)::

    service:worker_die@<n>     pool worker <n> (1-based ordinal) hard-
                               exits on its next write request; the
                               dispatcher/client retries onto a sibling
                               and the supervisor respawns it (fires only
                               at spawn generation 0 — a respawned worker
                               does not die again)
    service:replica_stale      the replica's puller pins itself at its
                               current commit while still learning the
                               upstream sha — /v1/query keeps answering,
                               with the honest X-Sofa-Replica-Stale /
                               X-Sofa-Replica-Behind headers
    service:slo_breach@<n>     scrape window <n> (1-based ordinal) of the
                               metrics plane reports a synthetic breach —
                               the typed slo_verdict, the catalog breach
                               event and the ``sofa status --fleet``
                               nonzero exit are exercisable without
                               hand-building real load (fires once)
    service:scrape_stall       the metrics scrape loop freezes: ticks
                               return without scraping, so last-scrape
                               age grows and the stale-scrape warning
                               path through manifest_warnings is
                               reachable (holds until the plan clears)
    service:disk_full@<n>      the tier's <n>-th WAL/store write (1-based,
                               counted across the process) raises ENOSPC —
                               the worker answers a typed 507/503 refusal
                               instead of acking a write it cannot make
                               durable (fires once; the retry lands)

Stream-source fault kinds (target = a tailable ingest source, consumed by
the `sofa live` tailer in sofa_tpu/live.py — docs/LIVE.md failure matrix)::

    <source>:tail_truncate[@<epoch>]   the tail read sees only half of the
                                       new bytes (a partial flush)
    <source>:tail_torn[@<epoch>]       the tail read ends mid-record — the
                                       torn-tail backoff must leave the
                                       partial record unconsumed
    <source>:rotate[@<epoch>]          the source reads as rotated (head
                                       signature mismatch): offsets reset
                                       and the file re-ingests from zero
    <source>:stall[@<epoch>|@always]   the source reports no growth this
                                       epoch, driving stalled detection

Stream faults fire at exactly the declared 1-based epoch ordinal
(default 1); ``@always`` never clears.

Firing policy: by default each network fault fires ONCE PER REQUEST KEY
(one object upload, one commit), so the first attempt fails and the
retry path is exercised deterministically; ``@start`` fires exactly once
for the whole plan (the session's first matching request); ``@always``
never clears (the spool-and-forward fallback path).  ``partial`` is
always once-per-key — the resend succeeds, proving resume-from-have-list.

Zero overhead when unset: every hook first reads the module-level plan and
returns on ``None`` — no parsing, no lookups, no env reads on the hot path.
The plan is installed by ``sofa record`` / ``sofa preprocess`` from
``cfg.inject_faults`` (or the SOFA_FAULTS env) and cleared in their
``finally``, so library users and tests never inherit a stale plan.

Supervisor/restart semantics live in sofa_tpu/supervisor.py; the quarantine
flow in sofa_tpu/preprocess.py.  See docs/ROBUSTNESS.md.
"""

from __future__ import annotations

import os
import re
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

KINDS = ("die", "wedge", "fail", "truncate", "corrupt",
         "conn_refused", "conn_reset", "stall", "http_500", "partial",
         "worker_die", "replica_stale", "slo_breach", "scrape_stall",
         "disk_full", "tail_truncate", "tail_torn", "rotate")
#: Kinds injected into the fleet transport client (archive/client.py)
#: rather than a collector lifecycle hook.
NET_KINDS = ("conn_refused", "conn_reset", "stall", "http_500", "partial",
             "worker_die", "replica_stale", "slo_breach", "scrape_stall",
             "disk_full")
#: The NET_KINDS subset consumed by the scaled tier's SERVER side
#: (archive/tier.py, archive/service.py, sofa_tpu/metrics.py) — the
#: transport client skips these entirely: a worker dying, a replica
#: lagging, the metrics plane misbehaving or the store's disk filling is
#: the tier's failure to absorb, not the client's to simulate.
TIER_KINDS = ("worker_die", "replica_stale", "slo_breach", "scrape_stall",
              "disk_full")
#: Kinds injected into the `sofa live` tailer (sofa_tpu/live.py) against a
#: streaming ingest source.  ``stall`` is shared vocabulary with NET_KINDS:
#: against the ``service`` target it is a transport stall, against a source
#: it freezes that source's tail for the epoch (docs/LIVE.md).
STREAM_KINDS = ("tail_truncate", "tail_torn", "rotate", "stall")
PHASES = ("start", "stop", "harvest")
#: Firing policies for NET_KINDS ("" = the default once-per-request-key).
NET_WHENS = ("start", "always")

# Spec targets users think of by raw-file name map onto the internal
# ingest-task name here.
ALIASES = {"pcap": "nettrace"}

# Which phase a kind fires in when the entry names none.
DEFAULT_PHASE = {"fail": "start", "wedge": "stop", "truncate": "harvest"}

# A wedge blocks "forever" relative to any sane deadline; the sleeping
# daemon thread is abandoned by the bounded epilogue and dies with the
# process.
_WEDGE_S = 3600.0

_DELAY_RE = re.compile(r"^(\d+(?:\.\d+)?)s?$")


class FaultInjected(RuntimeError):
    """Raised by a ``fail`` injection — a synthetic collector failure."""


@dataclass(frozen=True)
class FaultSpec:
    target: str
    kind: str
    phase: Optional[str] = None   # start|stop|harvest (fail/wedge/truncate)
    delay_s: Optional[float] = None  # die only
    fraction: Optional[float] = None  # partial only: body cut at this point
    when: Optional[str] = None    # NET_KINDS: start|always|None (per-key);
                                  # STREAM_KINDS: always|None (one epoch)
    epoch: Optional[int] = None   # STREAM_KINDS: 1-based live epoch ordinal

    def fires_at(self, phase: str) -> bool:
        return (self.phase or DEFAULT_PHASE.get(self.kind)) == phase


class FaultPlan:
    """Parsed fault spec, indexed by target for O(1) hook lookups."""

    def __init__(self, specs: List[FaultSpec]):
        from sofa_tpu.concurrency import Guard

        self.specs = list(specs)
        self._by_target: Dict[str, List[FaultSpec]] = {}
        for s in self.specs:
            self._by_target.setdefault(s.target, []).append(s)
        # Network faults are consumed (fire-once policies); the ledger is
        # written from whatever thread runs the transport client.
        self._fired_guard = Guard("faults.fired", protects=("_fired",))
        self._fired: Dict[tuple, bool] = {}

    def find(self, target: str, kind: str,
             phase: Optional[str] = None) -> Optional[FaultSpec]:
        for s in self._by_target.get(target, ()):
            if s.kind != kind:
                continue
            if phase is None or s.fires_at(phase):
                return s
        return None

    def corrupt_for(self, source: str) -> Optional[FaultSpec]:
        return self.find(source, "corrupt")

    def stream_fault(self, source: str, epoch: int) -> Optional[FaultSpec]:
        """The stream-source fault — if any — to apply to ``source`` in
        live epoch ``epoch`` (1-based).  Default firing is exactly the
        declared epoch ordinal (``@<n>``, default 1) so every torn-tail /
        rotation / stall path is deterministically reproducible;
        ``@always`` never clears (a permanently wedged source)."""
        for s in self._by_target.get(source, ()):
            if s.kind not in STREAM_KINDS:
                continue
            if s.when == "always" or (s.epoch or 1) == epoch:
                return s
        return None

    def service_fault(self, target: str, op: str,
                      key: str) -> Optional[FaultSpec]:
        """Consult-and-consume: the first network-kind spec for
        ``target`` that should fire for request ``op:key``.  ``@always``
        specs never clear; ``@start`` specs clear after the plan's first
        matching request; default specs clear per request key — so one
        plan deterministically fails each upload exactly once.
        ``partial`` only ever fires for object uploads (op ``put``): a
        truncated control request would be a plain 400, not the
        server-side hash rejection the kind exists to exercise."""
        for s in self._by_target.get(target, ()):
            if s.kind not in NET_KINDS or s.kind in TIER_KINDS:
                continue
            if s.kind == "partial" and op != "put":
                continue
            if s.when == "always":
                return s
            fkey = (s.kind, s.target,
                    "" if s.when == "start" else f"{op}:{key}")
            with self._fired_guard:
                if self._fired.get(fkey):
                    continue
                self._fired[fkey] = True
            return s
        return None

    def tier_worker_die(self, ordinal: int, generation: int) -> bool:
        """Consult-and-consume for ``worker_die@<n>``: True exactly once,
        for pool worker ``ordinal`` (1-based) at spawn generation 0 — a
        respawned worker (generation > 0) never re-fires even though the
        fork-inherited plan still lists the spec."""
        if generation != 0:
            return False
        for s in self._by_target.get("service", ()):
            if s.kind != "worker_die" or (s.epoch or 1) != ordinal:
                continue
            fkey = ("worker_die", ordinal)
            with self._fired_guard:
                if self._fired.get(fkey):
                    continue
                self._fired[fkey] = True
            return True
        return False

    def tier_replica_stale(self) -> bool:
        """Whether a ``replica_stale`` spec is active (never consumed —
        the replica stays pinned until the plan clears)."""
        return any(s.kind == "replica_stale"
                   for s in self._by_target.get("service", ()))

    def tier_slo_breach(self, window: int) -> bool:
        """Consult-and-consume for ``slo_breach@<n>``: True exactly once,
        at scrape window ``window`` (1-based) — the metrics plane folds a
        synthetic breached target into that window's verdict so the
        breach plumbing (typed verdict, catalog event, fleet-status exit)
        is exercised without real load."""
        for s in self._by_target.get("service", ()):
            if s.kind != "slo_breach" or (s.epoch or 1) != window:
                continue
            fkey = ("slo_breach", window)
            with self._fired_guard:
                if self._fired.get(fkey):
                    continue
                self._fired[fkey] = True
            return True
        return False

    def tier_scrape_stall(self) -> bool:
        """Whether a ``scrape_stall`` spec is active (never consumed —
        scrape ticks keep skipping until the plan clears)."""
        return any(s.kind == "scrape_stall"
                   for s in self._by_target.get("service", ()))

    def tier_disk_full(self) -> bool:
        """Consult-and-consume for ``disk_full@<n>``: True exactly once,
        at the plan's <n>-th consulted WAL/store write (1-based, counted
        process-wide across tenants).  The write site answers with a
        typed out-of-space refusal instead of acking bytes it never made
        durable; the consumed spec lets the client's retry land."""
        spec = None
        for s in self._by_target.get("service", ()):
            if s.kind == "disk_full":
                spec = s
                break
        if spec is None:
            return False
        with self._fired_guard:
            if self._fired.get(("disk_full",)):
                return False
            count = int(self._fired.get(("disk_full_writes",), 0)) + 1
            self._fired[("disk_full_writes",)] = count
            if count != (spec.epoch or 1):
                return False
            self._fired[("disk_full",)] = True
        return True


def parse(text: str) -> FaultPlan:
    """Parse a spec string; raises ValueError with the offending entry."""
    specs: List[FaultSpec] = []
    for entry in (e.strip() for e in text.split(",")):
        if not entry:
            continue
        target, sep, rest = entry.partition(":")
        if not sep or not target or not rest:
            raise ValueError(
                f"fault entry {entry!r}: expected <target>:<kind>[@<when>]")
        kind, _, when = rest.partition("@")
        if kind not in KINDS:
            raise ValueError(
                f"fault entry {entry!r}: kind {kind!r} not in {KINDS}")
        if kind in NET_KINDS and (target == "service"
                                  or kind not in STREAM_KINDS):
            # `stall` is in both vocabularies: the `service` target picks
            # the transport kind, any other target is a stream source.
            specs.append(_parse_net(entry, target, kind, when))
            continue
        if kind in STREAM_KINDS:
            specs.append(_parse_stream(entry, target, kind, when))
            continue
        phase: Optional[str] = None
        delay: Optional[float] = None
        if when:
            if when in PHASES:
                phase = when
            else:
                m = _DELAY_RE.match(when)
                if m is None:
                    raise ValueError(
                        f"fault entry {entry!r}: {when!r} is neither a "
                        f"phase {PHASES} nor a delay like '2s'")
                delay = float(m.group(1))
        if kind == "die" and phase is not None:
            raise ValueError(
                f"fault entry {entry!r}: die takes a delay (e.g. @2s), "
                "not a phase")
        if kind in ("fail", "wedge", "truncate") and delay is not None:
            raise ValueError(
                f"fault entry {entry!r}: {kind} takes a phase "
                f"{PHASES}, not a delay")
        if kind == "wedge" and phase == "start":
            raise ValueError(
                f"fault entry {entry!r}: wedge supports the bounded "
                "phases stop|harvest (start is unbounded by design — "
                "use fail@start)")
        specs.append(FaultSpec(target=ALIASES.get(target, target),
                               kind=kind, phase=phase, delay_s=delay))
    return FaultPlan(specs)


def _parse_stream(entry: str, target: str, kind: str,
                  when: str) -> FaultSpec:
    """One stream-source entry: ``<source>:<kind>[@<epoch>|@always]``.
    The ordinal names the 1-based live epoch the fault fires in (default
    1 — the first tail after the plan installs)."""
    target = ALIASES.get(target, target)
    if not when:
        return FaultSpec(target=target, kind=kind)
    if when == "always":
        return FaultSpec(target=target, kind=kind, when="always")
    try:
        epoch = int(when)
    except ValueError:
        epoch = 0
    if epoch < 1:
        raise ValueError(
            f"fault entry {entry!r}: stream kinds take a 1-based epoch "
            "ordinal (e.g. tail_torn@2) or 'always'")
    return FaultSpec(target=target, kind=kind, epoch=epoch)


def _parse_net(entry: str, target: str, kind: str,
               when: str) -> FaultSpec:
    """One network-kind entry (NET_KINDS grammar in the module doc)."""
    if kind == "worker_die":
        if not when:
            return FaultSpec(target=target, kind=kind, epoch=1)
        try:
            ordinal = int(when)
        except ValueError:
            ordinal = 0
        if ordinal < 1:
            raise ValueError(
                f"fault entry {entry!r}: worker_die takes a 1-based "
                "pool-worker ordinal (e.g. worker_die@2)")
        return FaultSpec(target=target, kind=kind, epoch=ordinal)
    if kind in ("replica_stale", "scrape_stall"):
        if when and when != "always":
            raise ValueError(
                f"fault entry {entry!r}: {kind} takes no firing "
                "policy (it holds until the plan clears)")
        return FaultSpec(target=target, kind=kind, when="always")
    if kind == "slo_breach":
        if not when:
            return FaultSpec(target=target, kind=kind, epoch=1)
        try:
            window = int(when)
        except ValueError:
            window = 0
        if window < 1:
            raise ValueError(
                f"fault entry {entry!r}: slo_breach takes a 1-based "
                "scrape-window ordinal (e.g. slo_breach@2)")
        return FaultSpec(target=target, kind=kind, epoch=window)
    if kind == "disk_full":
        if not when:
            return FaultSpec(target=target, kind=kind, epoch=1)
        try:
            nth = int(when)
        except ValueError:
            nth = 0
        if nth < 1:
            raise ValueError(
                f"fault entry {entry!r}: disk_full takes a 1-based "
                "write ordinal (e.g. disk_full@3)")
        return FaultSpec(target=target, kind=kind, epoch=nth)
    if kind == "partial":
        try:
            fraction = float(when)
        except ValueError:
            fraction = -1.0
        if not 0.0 < fraction < 1.0:
            raise ValueError(
                f"fault entry {entry!r}: partial needs a fraction in "
                "(0, 1), e.g. partial@0.5")
        return FaultSpec(target=target, kind=kind, fraction=fraction)
    if when and when not in NET_WHENS:
        raise ValueError(
            f"fault entry {entry!r}: {when!r} is not a network firing "
            f"policy {NET_WHENS} (default: once per request key)")
    return FaultSpec(target=target, kind=kind, when=when or None)


# --- active-plan registry ----------------------------------------------------
# One process-wide plan, installed per pipeline verb.  Not per-thread: the
# hooks fire from collector worker threads and pool workers that must see
# the verb's plan.

_PLAN: Optional[FaultPlan] = None
#: Armed die-timers (arm_die), cancelled by clear() — an injected "death"
#: scheduled near the end of a run must not fire into the NEXT run's
#: collectors after the verb that armed it already cleaned up (SL023's
#: stop-path invariant for timers).
_TIMERS: List[threading.Timer] = []


def active() -> Optional[FaultPlan]:
    return _PLAN


def install_from(cfg=None) -> Optional[FaultPlan]:
    """Install the plan from cfg.inject_faults, falling back to SOFA_FAULTS.

    A bad spec is a usage error (curated SofaUserError), not a traceback.
    Pair with :func:`clear` in a finally.
    """
    global _PLAN
    _PLAN = None  # a failed parse must never leave a previous plan live
    text = (getattr(cfg, "inject_faults", "")
            or os.environ.get("SOFA_FAULTS", "") or "").strip()
    if not text:
        return None
    from sofa_tpu.printing import SofaUserError, print_warning

    try:
        _PLAN = parse(text)
    except ValueError as e:
        raise SofaUserError(f"bad --inject_faults/SOFA_FAULTS spec: {e}") \
            from None
    # Loud on purpose — and print_warning rides the telemetry counters, so
    # a chaos run's manifest self-documents that faults were active.
    print_warning(f"fault injection ACTIVE: {text}")
    return _PLAN


def clear() -> None:
    global _PLAN
    _PLAN = None
    while _TIMERS:
        _TIMERS.pop().cancel()


# --- hook points -------------------------------------------------------------

def maybe_inject(name: str, phase: str) -> None:
    """Collector lifecycle hook (run_start/run_stop/run_harvest).

    ``fail`` raises FaultInjected; ``wedge`` blocks (only ever called for
    stop/harvest, which the bounded epilogue deadlines cover)."""
    plan = _PLAN
    if plan is None:
        return
    if plan.find(name, "fail", phase) is not None:
        raise FaultInjected(f"injected {name} failure at {phase} "
                            "(--inject_faults)")
    if phase != "start" and plan.find(name, "wedge", phase) is not None:
        time.sleep(_WEDGE_S)


def arm_die(col) -> None:
    """After a successful start: schedule the collector's backing worker to
    vanish the way a crash would (Collector.fault_kill)."""
    plan = _PLAN
    if plan is None:
        return
    spec = plan.find(col.name, "die")
    if spec is None:
        return
    t = threading.Timer(spec.delay_s or 0.0, col.fault_kill)
    t.daemon = True
    _TIMERS.append(t)  # clear() cancels stragglers at verb teardown
    t.start()


def maybe_service_fault(op: str, key: str = "",
                        target: str = "service") -> Optional[FaultSpec]:
    """Fleet-transport hook (archive/client.py): the network fault — if
    any — to apply to this request.  ``op:key`` identifies the request
    for the once-per-key policy (e.g. ``put:<sha>``); returns the spec
    (the CLIENT translates it into a refused connection, a timeout, a
    synthetic 500, or a truncated upload body) or None."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.service_fault(target, op, key)


def maybe_worker_die(ordinal: int, generation: int = 0) -> bool:
    """Scaled-tier hook (archive/service.py chaos_tick): True when pool
    worker ``ordinal`` (1-based) should hard-exit NOW — the
    ``worker_die@<n>`` cell.  Fires once, and only at spawn generation 0:
    the supervisor's respawn must come back healthy."""
    plan = _PLAN
    if plan is None:
        return False
    return plan.tier_worker_die(ordinal, generation)


def maybe_replica_stale() -> bool:
    """Scaled-tier hook (archive/tier.py puller): True while a
    ``replica_stale`` spec pins the replica at its current commit."""
    plan = _PLAN
    if plan is None:
        return False
    return plan.tier_replica_stale()


def maybe_slo_breach(window: int) -> bool:
    """Metrics-plane hook (sofa_tpu/metrics.py): True when scrape window
    ``window`` (1-based) should fold a synthetic breached target into its
    slo_verdict — the ``slo_breach@<n>`` cell.  Fires once."""
    plan = _PLAN
    if plan is None:
        return False
    return plan.tier_slo_breach(window)


def maybe_disk_full() -> bool:
    """Scaled-tier hook (archive/tier.py WAL appends, archive/service.py
    object uploads): True when THIS durable write should see ENOSPC — the
    ``disk_full@<n>`` cell.  The caller refuses the request with a typed
    out-of-space error instead of acking; fires once, so the client's
    backed-off retry proves recovery."""
    plan = _PLAN
    if plan is None:
        return False
    return plan.tier_disk_full()


def maybe_scrape_stall() -> bool:
    """Metrics-plane hook (sofa_tpu/metrics.py): True while a
    ``scrape_stall`` spec freezes the scrape loop — ticks return without
    scraping, so last-scrape age grows honestly."""
    plan = _PLAN
    if plan is None:
        return False
    return plan.tier_scrape_stall()


def maybe_stream_fault(source: str, epoch: int) -> Optional[FaultSpec]:
    """Live-tailer hook (sofa_tpu/live.py): the stream fault — if any —
    to apply to ``source`` in epoch ``epoch``.  The TAILER consumes the
    spec (truncating its read window, forcing the rotation path, or
    freezing the source) so every offset-resume and torn-tail branch is
    reachable on demand; returns the spec or None."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.stream_fault(source, epoch)


def maybe_truncate(col) -> None:
    """Harvest hook: halve every existing output file — a synthetic
    torn/partial harvest for the corrupt-input paths downstream."""
    plan = _PLAN
    if plan is None:
        return
    if plan.find(col.name, "truncate", "harvest") is None:
        return
    for path in col.outputs():
        try:
            if os.path.isfile(path):
                size = os.path.getsize(path)
                os.truncate(path, size // 2)
        except OSError:
            pass
