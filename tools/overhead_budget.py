#!/usr/bin/env python3
"""Per-collector overhead budget table (VERDICT r2 next #8).

SURVEY §6 lists the overhead *knobs* (sampler rates, tracer levels); the
reference substantiates its <5 % budget with measured paired runs
(/root/reference/validation/framework_eval.py) but never publishes the
marginal cost of each collector.  This measures exactly that: a tiny
transformer train loop is timed bare, then once per collector config, and
the marginal overhead of each lands in a markdown table
(docs/OVERHEAD_BUDGET.md).

Run on the real chip whenever the tunnel is healthy (validate_tpu's
``overhead_budget`` check calls this); on CPU it still runs end to end so
the mechanics stay tested, but the numbers only matter on TPU.
"""

from __future__ import annotations

import argparse
import os
import shutil
import tempfile
import threading
import time
from typing import Callable, List, Optional, Tuple


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    return xs[len(xs) // 2]


def _median_ci(xs: List[float],
               conf: float = 0.95) -> "Tuple[float, float] | None":
    """Nonparametric 95% CI for the median via binomial order statistics
    (normal approximation to the rank): distribution-free, so tunnel-RPC
    jitter with fat tails can't fake a tight bound the way a normal-theory
    SE would.  None below 6 samples — a sample range is NOT a 95% CI and
    publishing it as one would manufacture 'resolved ±0.00 %' rows from a
    single noisy pair."""
    import math

    n = len(xs)
    s = sorted(xs)
    if n < 6:
        return None
    z = 1.959964 if conf >= 0.95 else 1.644854
    delta = z * math.sqrt(n) / 2.0
    lo = max(0, int(math.floor(n / 2.0 - delta)))
    hi = min(n - 1, int(math.ceil(n / 2.0 + delta)) - 1)
    return s[lo], s[hi]


def _timed_once(step, state, tokens, n_steps: int) -> float:
    from sofa_tpu.workloads.common import fence

    t0 = time.perf_counter()
    params, opt = state
    for _ in range(n_steps):
        params, opt, loss = step(params, opt, tokens)
    fence(loss)   # NOT block_until_ready: see workloads/common.py:fence
    return time.perf_counter() - t0


def run_budget(steps: int = 50, reps: int = 20, batch: int = 4,
               seq: int = 128, out: Optional[str] = None,
               ci_target_pct: float = 2.0, max_reps: int = 32) -> str:
    """Measure marginal per-collector overhead; return the markdown table.

    ``reps`` interleaved bare/config pairs per collector (bare re-timed
    immediately before every config run so drift cancels within the
    pair); the published number is the pair-marginal median with a 95 %
    order-statistic CI.  If the CI half-width exceeds ``ci_target_pct``
    the loop keeps adding pairs up to ``max_reps`` — the budget's job is
    to *detect a 2 % regression*, and a row whose CI cannot do that says
    so explicitly instead of hiding behind "within noise".
    """
    import jax

    from sofa_tpu.config import SofaConfig
    from sofa_tpu.workloads.transformer import TransformerConfig, build

    max_reps = max(max_reps, reps)   # asking for N pairs always yields N

    cfg_t = TransformerConfig.tiny(seq=seq)
    params, opt, step, tokens = build(cfg_t, None, batch=batch, seq=seq)
    params, opt, loss = step(params, opt, tokens)  # compile once
    jax.block_until_ready(loss)
    state = (params, opt)

    scratch = tempfile.mkdtemp(prefix="sofa_budget_") + "/"

    def with_procmon(rate: int):
        from sofa_tpu.collectors.procmon import ProcMonCollector

        col = ProcMonCollector(SofaConfig(logdir=scratch,
                                          sys_mon_rate=rate))
        reason = col.probe()
        if reason is not None:
            raise RuntimeError(f"procmon unavailable: {reason}")
        col.start()
        return col.stop

    def with_tpumon(rate: int, memprof: bool = False):
        from sofa_tpu.collectors.tpumon import start_sampler

        ev = threading.Event()
        t = start_sampler(rate, scratch + "tpumon.txt", ev,
                          memprof_path=(scratch + "memprof.pb.gz"
                                        if memprof else None))

        def teardown():
            # Join so a final tick (up to 1/rate late, and a memprof
            # snapshot is stop-the-world) can't bleed into the NEXT
            # config's timed run.
            ev.set()
            t.join(timeout=3.0)
            if t.is_alive():
                # Surface it: the invariant is broken, the next row is
                # suspect (run_budget swallows teardown exceptions).
                print("WARNING: tpumon sampler did not stop within 3s — "
                      "the next config's timing may be contaminated")

        return teardown

    def with_xprof(python_tracer: bool = False):
        kwargs = {}
        try:
            po = jax.profiler.ProfileOptions()
            po.host_tracer_level = 2
            po.python_tracer_level = 1 if python_tracer else 0
            kwargs["profiler_options"] = po
        except Exception:  # noqa: BLE001 — older jax: defaults
            pass
        d = tempfile.mkdtemp(prefix="xprof_", dir=scratch)
        jax.profiler.start_trace(d, **kwargs)
        return jax.profiler.stop_trace

    def with_full_profile():
        import sofa_tpu.api as sofa

        cm = sofa.profile(scratch + "full/")
        cm.__enter__()
        return lambda: cm.__exit__(None, None, None)

    configs: List[Tuple[str, Callable[[], Callable[[], None]]]] = [
        ("procmon @ 10 Hz (default)", lambda: with_procmon(10)),
        ("procmon @ 100 Hz", lambda: with_procmon(100)),
        ("tpumon @ 1 Hz (default)", lambda: with_tpumon(1)),
        ("tpumon @ 20 Hz", lambda: with_tpumon(20)),
        ("tpumon @ 1 Hz + memprof snapshots",
         lambda: with_tpumon(1, memprof=True)),
        ("xprof trace (host_tracer=2)", lambda: with_xprof()),
        ("xprof + python tracer", lambda: with_xprof(python_tracer=True)),
        ("full sofa.profile() stack", with_full_profile),
    ]

    rows = []
    try:
        # Warm the whole path untimed first — on the tunneled chip the
        # first minute of a session runs visibly slower, and a
        # measure-bare-once-up-front design turned that drift into
        # *negative* overheads for every config measured later.
        for _ in range(2):
            _timed_once(step, state, tokens, steps)
        # Each rep measures bare IMMEDIATELY before the config run, and the
        # marginal is the median of the per-pair ratios: slow monotonic
        # drift (tunnel settling, thermal) cancels within a pair instead of
        # biasing every config against one stale baseline.
        bare_times: List[float] = []
        per_cfg: List[Tuple[str, Optional[float], List[float]]] = []
        fails: dict = {}
        for name, setup in configs:
            margins, cfg_times = [], []
            fail = None
            while len(margins) < max_reps:
                teardown = None
                try:
                    tb = _timed_once(step, state, tokens, steps)
                    teardown = setup()
                    tc = _timed_once(step, state, tokens, steps)
                except Exception as e:  # noqa: BLE001 — per-config degrade
                    fail = e
                    break
                finally:
                    if teardown is not None:
                        try:
                            teardown()
                        except Exception:  # noqa: BLE001
                            pass
                bare_times.append(tb)
                cfg_times.append(tc)
                margins.append((tc - tb) / tb * 100.0)
                if len(margins) >= max(reps, 6):
                    ci = _median_ci(margins)
                    if ci is not None and (ci[1] - ci[0]) / 2 <= ci_target_pct:
                        break   # the CI already resolves the target
            if fail is not None:
                fails[name] = fail
                per_cfg.append((name, None, []))
                continue
            per_cfg.append((name, _median(cfg_times), margins))
        if not bare_times:
            raise RuntimeError("no bare baseline measured — every config "
                               "failed before its paired bare run")
        # Noise floor from the bare runs themselves: on a tunneled chip the
        # RPC latency jitter between identical runs can exceed any real
        # sampler cost, and a signed % with no floor reads as a (nonsense)
        # speedup.  MAD-based so one straggler run doesn't inflate it.
        b_med = _median(bare_times)
        mad_pct = _median(
            [abs(t - b_med) for t in bare_times]) / b_med * 100.0
        # ±4 MAD ~ a 99% band for the paired-run jitter: a marginal only
        # counts as signal beyond it (a "-6 % speedup from full profiling"
        # at ±4.4 % 2-MAD read as real, which is absurd on its face)
        noise_pct = 4.0 * mad_pct
        rows.append(("bare (no collectors)", b_med,
                     f"baseline (bare-run noise floor ±{noise_pct:.1f} %)"))
        for name, t, margins in per_cfg:
            if t is None:
                rows.append((name, None, f"unavailable: {fails[name]}"))
                continue
            m = _median(margins)
            ci95 = _median_ci(margins)
            if ci95 is None:
                rows.append((name, t,
                             f"{m:+.2f} % — only {len(margins)} pair(s), "
                             "too few for a 95% CI (raise --reps)"))
                continue
            lo, hi = ci95
            half = (hi - lo) / 2.0
            # signed + CI on purpose: the row must say whether it COULD
            # detect a ci_target_pct regression, not hide behind "within
            # noise" (VERDICT r4 weak#2: every row said that, so the
            # per-collector budget was unmeasured)
            ci = f"{m:+.2f} % [95% CI {lo:+.2f}..{hi:+.2f}]"
            if half > ci_target_pct:
                verdict = (f"UNRESOLVED at ±{ci_target_pct:.0f} % "
                           f"(CI half-width {half:.2f} % after "
                           f"{len(margins)} pairs — lengthen --steps)")
            elif lo > 0:
                verdict = f"real cost, resolved to ±{half:.2f} %"
            else:
                verdict = f"≤{max(hi, 0):.2f} %, resolved to ±{half:.2f} %"
            rows.append((name, t, f"{ci} — {verdict}"))
    finally:
        shutil.rmtree(scratch, ignore_errors=True)

    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    lines = [
        "# Per-collector overhead budget",
        "",
        f"Measured {stamp} on backend **{jax.default_backend()}** "
        f"({len(jax.devices())} device(s)); tiny transformer train loop, "
        f"batch={batch} seq={seq}, {steps} steps x >= {reps} interleaved "
        f"bare/config pairs (adaptive up to {max_reps} until the 95 % "
        f"order-statistic CI of the pair-marginal median resolves "
        f"±{ci_target_pct:.0f} %).",
        "",
        "| Collector config | median loop time (s) | marginal overhead |",
        "|---|---|---|",
    ]
    for name, t, note in rows:
        ts = f"{t:.3f}" if t is not None else "—"
        lines.append(f"| {name} | {ts} | {note} |")
    lines.append("")
    lines.append("Knobs: `--sys_mon_rate`, `--tpu_mon_rate`, "
                 "`--xprof_host_tracer_level`, `--xprof_python_tracer`; "
                 "see SURVEY §6.")
    table = "\n".join(lines) + "\n"
    if out:
        os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
        with open(out, "w") as f:
            f.write(table)
    return table


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--reps", type=int, default=20,
                   help="minimum interleaved bare/config pairs per row")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--ci_target_pct", type=float, default=2.0,
                   help="stop adding pairs once the 95%% CI half-width "
                        "of the median marginal is under this")
    p.add_argument("--max_reps", type=int, default=32)
    p.add_argument("--out", default=None,
                   help="also write the table here (e.g. "
                        "docs/OVERHEAD_BUDGET.md)")
    args = p.parse_args(argv)

    import jax

    env_platforms = os.environ.get("JAX_PLATFORMS", "")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)

    print(run_budget(args.steps, args.reps, args.batch, args.seq, args.out,
                     ci_target_pct=args.ci_target_pct,
                     max_reps=args.max_reps))
    return 0


if __name__ == "__main__":
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.exit(main())
