#!/usr/bin/env python3
"""One-command reproduction of the off-chip performance numbers.

Generates the synthetic pod-scale capture (tools/pod_synth.py: 8 devices x
200k ops, static per-op cost metadata), times the headline paths, and
writes a dated markdown table to PERF_EVIDENCE.md — so those README
numbers are a `python tools/perf_evidence.py` away from re-measurement
rather than self-reported in commit messages.

On-chip numbers (profiling overhead on the real chip) come from bench.py /
tools/validate_tpu.py; native-scanner ingest throughput has its own
equivalence/perf coverage in tests/test_native_scan.py.
"""

from __future__ import annotations

import contextlib
import io
import os
import shutil
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _timed(label, fn, rows, reps: int = 3):
    best = None
    out = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    rows.append((label, best))
    print(f"  {label}: {best:.2f}s")
    return out


@contextlib.contextmanager
def _env(key: str, value: "str | None"):
    old = os.environ.get(key)
    if value is None:
        os.environ.pop(key, None)
    else:
        os.environ[key] = value
    try:
        yield
    finally:
        if old is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = old


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    workdir = tempfile.mkdtemp(prefix="sofa_evidence_") + "/"
    try:
        return _measure(workdir)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _measure(workdir: str) -> int:
    logdir = workdir + "podlog/"
    print(f"generating the synthetic pod capture in {logdir} ...")
    gen = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "pod_synth.py"),
         logdir],
        capture_output=True, text=True)
    if gen.returncode != 0:
        sys.stderr.write(gen.stdout + gen.stderr)
        return 1

    from sofa_tpu.analyze import load_frames, sofa_analyze
    from sofa_tpu.config import SofaConfig
    from sofa_tpu.export_perfetto import export_perfetto

    cfg = SofaConfig(logdir=logdir)
    rows = []

    def quiet(fn):
        def run():
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                fn()
            return buf.getvalue()
        return run

    frames = None

    def do_load():
        nonlocal frames
        frames = load_frames(cfg)

    _timed("load 1.6M-op frames (arrow CSV reader, parallel)",
           quiet(do_load), rows)
    _timed("analysis passes, in-memory frames (report path)",
           quiet(lambda: sofa_analyze(cfg, frames=dict(frames))), rows)
    # frames passed in: these rows measure the export alone, matching the
    # table's decomposition (the load row above already covers the read).
    with _env("SOFA_NATIVE_PERFETTO", "1"):
        out = _timed("Perfetto export, native writer",
                     quiet(lambda: export_perfetto(cfg, frames=frames)),
                     rows)
    if "(native writer" not in out:
        # A silent fallback would publish a mislabeled row.
        sys.stderr.write("ERROR: native writer did not run (no compiler?) "
                         "— refusing to write a mislabeled table\n")
        return 1
    with _env("SOFA_NATIVE_PERFETTO", "0"):
        _timed("Perfetto export, pure-Python fallback",
               quiet(lambda: export_perfetto(cfg, frames=frames)), rows)

    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    out_path = os.path.join(ROOT, "PERF_EVIDENCE.md")
    section = [
        "## Off-chip performance evidence\n\n",
        f"Measured {stamp} by `python tools/perf_evidence.py` "
        "(best of 3) on the synthetic 8-device x 200k-op capture "
        "(`tools/pod_synth.py`; 1.6M HLO events).  Regenerate "
        "anytime — this section is tool-owned, never hand-edited.\n\n",
        "| Path | best-of-3 wall time |\n|---|---|\n",
    ]
    section += [f"| {label} | {dt:.2f} s |\n" for label, dt in rows]
    section.append(
        "\nOther evidence paths: `python bench.py` (on-chip paired "
        "overhead + HLO coverage guard), `python tools/"
        "validate_tpu.py` (on-chip checklist), `python -m pytest "
        "tests/test_native_scan.py` (ingest scanner equivalence + "
        "fuzz), `python __graft_entry__.py 8` (multichip dryrun).\n")
    try:
        with open(out_path) as f:
            existing = f.read()
    except OSError:
        existing = ""
    with open(out_path, "w") as f:
        f.write(merge_evidence(existing, "".join(section)))
    print(f"wrote {out_path}")
    return 0


def merge_evidence(existing: str, off_chip_section: str) -> str:
    """Replace only the tool-owned off-chip section of PERF_EVIDENCE.md.

    Hand-written content before the '## Off-chip performance evidence'
    heading AND any '## ...' sections after it are preserved verbatim —
    the heading must sit at a line start, so prose merely *mentioning* it
    can't truncate the document.  (A whole-file rewrite here once deleted
    the committed on-chip section.)
    """
    import re

    # full-line anchor: a hand-written heading that merely STARTS with the
    # text (e.g. "## Off-chip performance evidence (archived)") is not the
    # tool-owned section
    m = re.search(r"(?m)^## Off-chip performance evidence[ \t]*$", existing)
    marker = "## Off-chip performance evidence"
    idx = m.start() if m else -1
    if idx < 0:
        head = (existing.rstrip() + "\n\n" if existing.strip()
                else "# Performance evidence\n\n")
        return head + off_chip_section
    head = existing[:idx]
    # later hand-written sections survive regeneration too
    nxt = existing.find("\n## ", idx + len(marker))
    tail = existing[nxt + 1:] if nxt >= 0 else ""
    if tail:
        return head + off_chip_section.rstrip() + "\n\n" + tail
    return head + off_chip_section


if __name__ == "__main__":
    sys.exit(main())
