"""The fleet tier's observability plane: metrics, SLOs, push tracing.

PR 2 taught every batch verb to observe itself (telemetry.py); PR 16
scaled ``sofa serve`` into a sharded worker tier — which promptly became
a blind spot: a profiler whose own service plane exposes no latency
histograms, no WAL/replica lag history, and no followable request path
contradicts the ROADMAP's "self-explaining" frontier.  "Enhancing
Performance Insight at Scale" (PAPERS.md) argues diagnostics
infrastructure must observe itself at fleet scale; KEET (PAPERS.md) shows
diagnosis is only as good as the grounded counters beneath it.  This
module is that substrate, three planes over one registry:

**Metrics** — :class:`MetricsRegistry` holds Guard-protected counters,
gauges and fixed-bucket histograms (p50/p99 by linear interpolation
inside the bucket — no sample retention, O(buckets) memory under any
load).  A per-worker :class:`Scraper` ticks every ``SCRAPE_INTERVAL_S``:
it computes tier gauges (WAL depth, drain lag, replica staleness),
freezes a flat snapshot, and appends changed values to a history that
persists into ``<root>/_metrics/worker<NNN>/`` as a chunked columnar
time-series store (frames.write_chunk_store — content-keyed chunks, no
wall-clock stamp in the index, so a scrape replayed over the same rows
is byte-identical regardless of ``--jobs``).  Idle windows append
nothing: the snapshot/history pair — and therefore the ``/v1/metrics``
ETag — only move when a value moves, which is what lets the board poll
cheaply with If-None-Match.

**Tracing** — ``sofa agent`` stamps each push with a trace id
(:func:`new_trace_id`) carried in the ``X-Sofa-Trace`` header; service
handlers, ``WalAppender``, the async drainer, index refresh and replica
pulls emit spans (:meth:`MetricsRegistry.span`) joined under that id.
The WAL record carries the id across the process boundary, so one push
is followable agent→ack→drain→index-commit→replica.  Spans land in a
bounded ring flushed to ``_metrics/fleet_trace/ring.<worker>.<pid>.json``
— the same Chrome-trace JSON as ``sofa_self_trace.json`` — and
:func:`export_fleet_trace` merges every ring into one Perfetto-openable
``fleet_trace.json`` beside user traces.

**SLOs** — ``sofa serve --slo 'push_p99_ms<50,wal_depth<1000'`` declares
targets (:func:`parse_slo`) evaluated per scrape window into a typed,
schema-versioned ``slo_verdict`` (the ``sofa live`` breach-vocabulary
discipline applied to the service): every target answers ``ok``,
``breach`` or ``no_data`` — never a silent skip.  Verdicts persist
atomically at ``_metrics/slo_verdict.json``; a breach TRANSITION appends
an ``slo_breach`` event to each tenant catalog (worker 0 only — one
ledger line per breach, not one per worker) so ``sofa regress`` and the
fleet board see it, and ``sofa status --fleet`` exits nonzero while a
breach is active.

Zero overhead when off: ``SOFA_TIER_METRICS=0`` turns every hook into a
fast no-op (bench.py's ``tier_metrics_overhead_pct`` measures the
difference and holds it under 5%).  Fault hooks ``slo_breach@<window>``
and ``scrape_stall`` (faults.py) make the breach and stale-scrape paths
exercisable on demand.  See docs/FLEET.md "Observing the tier".
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from sofa_tpu.concurrency import Guard

METRICS_SCHEMA = "sofa_tpu/fleet_metrics"
METRICS_VERSION = 1
SLO_SCHEMA = "sofa_tpu/slo_verdict"
SLO_VERSION = 1

#: Per-root observability state lives under ``<root>/_metrics/`` —
#: derived, digest-skipped (trace.py registries): the scrape loop
#: rewrites it outside any pipeline digest refresh.
METRICS_DIR_NAME = "_metrics"
FLEET_TRACE_DIR_NAME = "fleet_trace"
SLO_VERDICT_NAME = "slo_verdict.json"
FLEET_TRACE_NAME = "fleet_trace.json"

#: Scrape cadence (seconds).  Env-tunable for tests and chaos runs.
SCRAPE_INTERVAL_S = float(os.environ.get("SOFA_METRICS_SCRAPE_S", "2.0"))

#: A commit ack whose last scrape is older than this is a stale metrics
#: plane — manifest_warnings surfaces it on the pushed run's manifest.
STALE_SCRAPE_S = 30.0

#: Span ring capacity per process — oldest spans fall off; a push trace
#: is a handful of spans, so the ring holds hundreds of recent pushes.
RING_EVENTS = 4096

#: History rows kept in memory / persisted per worker (newest kept).
HISTORY_ROWS = 4096
#: Rows per history chunk — small on purpose: the tail-chunk rewrite per
#: scrape stays a few KiB (frames.write_chunk_store reuses the rest).
HISTORY_CHUNK_ROWS = 2048

#: Fixed histogram bucket upper bounds (ms).  Log-spaced so p50/p99 of a
#: sub-ms ack and a multi-second drain both land with ~2x resolution;
#: the last bucket is open-ended.
BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
              1000.0, 2000.0, 5000.0, 10000.0, float("inf"))

#: Chrome-trace lanes (tids) for fleet spans, mirroring telemetry.py's
#: _SELF_TRACE_LANES discipline: one lane per tier component so Perfetto
#: renders the push path as parallel tracks under one process.
FLEET_TRACE_LANES = {"service": 1, "wal": 2, "drain": 3, "refresh": 4,
                     "replica": 5, "agent": 6}
_OTHER_LANE = 7

#: Snapshot keys excluded from change-detection and the /v1/metrics ETag:
#: they move every scrape even when the tier is idle.
_VOLATILE_KEYS = ("scrape_wall_ms",)


def metrics_enabled() -> bool:
    """The kill switch: ``SOFA_TIER_METRICS=0`` turns every hook into a
    no-op (bench.py measures the on-vs-off overhead through this)."""
    return os.environ.get("SOFA_TIER_METRICS", "1") != "0"


def new_trace_id() -> str:
    """A fresh 16-hex push trace id for the X-Sofa-Trace header."""
    return os.urandom(8).hex()


# ---------------------------------------------------------------------------
# Fixed-bucket histograms.
# ---------------------------------------------------------------------------

class Histogram:
    """Fixed-bucket latency histogram: O(len(BUCKETS_MS)) memory under
    any load, percentiles by linear interpolation inside the bucket.
    NOT self-locking — the owning registry's guard wraps every access."""

    __slots__ = ("counts", "count", "total")

    def __init__(self):
        self.counts = [0] * len(BUCKETS_MS)
        self.count = 0
        self.total = 0.0

    def observe(self, value_ms: float) -> None:
        for i, hi in enumerate(BUCKETS_MS):
            if value_ms <= hi:
                self.counts[i] += 1
                break
        self.count += 1
        self.total += float(value_ms)

    def percentile(self, p: float) -> float:
        """The p-th percentile estimate (0 < p <= 100).  Rank lands in a
        bucket; interpolate linearly between its bounds (the open last
        bucket answers its lower bound — honest saturation, not a made-up
        ceiling)."""
        if self.count == 0:
            return 0.0
        rank = p / 100.0 * self.count
        seen = 0
        for i, n in enumerate(self.counts):
            if n == 0:
                continue
            if seen + n >= rank:
                lo = BUCKETS_MS[i - 1] if i else 0.0
                hi = BUCKETS_MS[i]
                if hi == float("inf"):
                    return lo
                frac = (rank - seen) / n
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += n
        return BUCKETS_MS[-2]


# ---------------------------------------------------------------------------
# The registry.
# ---------------------------------------------------------------------------

class MetricsRegistry:
    """One process's counters/gauges/histograms/span-ring for one fleet
    root.  Obtain via :func:`for_root` (keyed by abspath — tests on
    distinct tmp roots never share state; a respawned pool worker is a
    fresh process and re-registers naturally)."""

    def __init__(self, root: str, worker: int = 0):
        self.root = root
        self.worker = int(worker)
        self.guard = Guard("metrics.registry", reentrant=True, protects=(
            "_counters", "_gauges", "_hists", "_events", "_pending",
            "_history", "_last_flat", "_last_counters", "scrape_seq",
            "last_scrape_unix", "_slo_breaching", "slo_verdict"))
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}
        self._events: collections.deque = collections.deque(
            maxlen=RING_EVENTS)
        #: tenant -> trace ids drained but not yet index-committed; the
        #: refresh span claims them (mark/take below).
        self._pending: Dict[str, List[str]] = {}
        self._history: collections.deque = collections.deque(
            maxlen=HISTORY_ROWS)
        self._last_flat: Dict[str, float] = {}
        self._last_counters: Dict[str, int] = {}
        self.scrape_seq = 0
        self.last_scrape_unix = 0.0
        self._slo_breaching: Tuple[str, ...] = ()
        self.slo_verdict: Optional[dict] = None

    # -- write side (hot path: every hook gates on metrics_enabled) --------

    def inc(self, name: str, n: int = 1) -> None:
        if not metrics_enabled():
            return
        with self.guard:
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def set_gauge(self, name: str, value: float) -> None:
        if not metrics_enabled():
            return
        with self.guard:
            self._gauges[name] = float(value)

    def observe(self, name: str, value_ms: float) -> None:
        if not metrics_enabled():
            return
        with self.guard:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(value_ms)

    def span(self, name: str, cat: str, t0_unix: float, dur_s: float,
             trace: str = "", **args) -> None:
        """One Chrome-trace complete ("X") span in the fleet ring.
        ``cat`` picks the Perfetto lane (FLEET_TRACE_LANES); ``trace``
        is the push's X-Sofa-Trace id — the join key the tentpole's
        "one push, one id" contract hangs on."""
        if not metrics_enabled():
            return
        ev_args = {k: v for k, v in args.items() if v not in (None, "")}
        if trace:
            ev_args["trace"] = trace
        with self.guard:
            self._events.append({
                "name": name, "cat": cat,
                "ts": int(t0_unix * 1e6),  # absolute µs; flush re-bases
                "dur": max(int(dur_s * 1e6), 1),
                "tid": FLEET_TRACE_LANES.get(cat, _OTHER_LANE),
                "args": ev_args,
            })

    def mark_pending_refresh(self, tenant: str,
                             trace_ids: List[str]) -> None:
        """Drained-but-not-committed trace ids: the next index refresh
        for ``tenant`` emits its commit span under each of these."""
        ids = [t for t in trace_ids if t]
        if not ids or not metrics_enabled():
            return
        with self.guard:
            cur = self._pending.setdefault(tenant, [])
            cur.extend(ids)
            del cur[:-64]  # bounded: a refresh covers at most 64 ids

    def take_pending_refresh(self, tenant: str) -> List[str]:
        with self.guard:
            return self._pending.pop(tenant, [])

    def get_gauge(self, name: str, default: float = 0.0) -> float:
        with self.guard:
            return self._gauges.get(name, default)

    def record_window(self, t0: float, stable: Dict[str, float]) -> None:
        """Commit one scrape window: append CHANGED stable values to the
        history (idle windows append nothing — the /v1/metrics ETag only
        moves when a value moves), freeze counter baselines for the next
        window's rates, and stamp the scrape clock."""
        with self.guard:
            if stable != self._last_flat:
                for name in sorted(stable):
                    if stable[name] != self._last_flat.get(name):
                        self._history.append(
                            [round(t0, 3), name, float(stable[name])])
                self._last_flat = dict(stable)
            self._last_counters = dict(self._counters)
            self.scrape_seq += 1
            self.last_scrape_unix = t0

    def update_slo(self, verdict: dict) -> List[str]:
        """Install the window's verdict; returns the freshly-breaching
        target names (the TRANSITIONS — catalog events fire on these, not
        on every window a breach persists)."""
        with self.guard:
            prev = self._slo_breaching
            self._slo_breaching = tuple(verdict["breaching"])
            self.slo_verdict = verdict
        return [n for n in verdict["breaching"] if n not in prev]

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> Tuple[Dict[str, float], Dict[str, dict]]:
        """(flat values, histogram detail).  Flat keys are the SLO
        vocabulary: ``<counter>_total``/``<counter>_rps`` per counter,
        ``<hist>_p50_ms``/``<hist>_p99_ms``/``<hist>_count`` per
        histogram, gauges verbatim."""
        with self.guard:
            now = time.time()
            dt = max(now - self.last_scrape_unix, 1e-6) \
                if self.last_scrape_unix else 0.0
            flat: Dict[str, float] = dict(self._gauges)
            for name, n in sorted(self._counters.items()):
                flat[f"{name}_total"] = float(n)
                if dt:
                    delta = n - self._last_counters.get(name, 0)
                    flat[f"{name}_rps"] = round(delta / dt, 3)
            hists: Dict[str, dict] = {}
            for name, h in sorted(self._hists.items()):
                flat[f"{name}_p50_ms"] = round(h.percentile(50.0), 3)
                flat[f"{name}_p99_ms"] = round(h.percentile(99.0), 3)
                flat[f"{name}_count"] = float(h.count)
                hists[name] = {
                    "buckets_ms": [b for b in BUCKETS_MS
                                   if b != float("inf")],
                    "counts": list(h.counts),
                    "count": h.count,
                    "total_ms": round(h.total, 3),
                }
            return flat, hists

    def history_rows(self, offset: int = 0, limit: int = 0,
                     window_s: Optional[float] = None) -> Tuple[list, int]:
        """(rows, total): ``[t, name, value]`` rows oldest-first, after
        the window filter, paged by offset/limit (0 = no limit)."""
        with self.guard:
            rows = list(self._history)
        if window_s is not None:
            cut = time.time() - float(window_s)
            rows = [r for r in rows if r[0] >= cut]
        total = len(rows)
        rows = rows[offset:]
        if limit:
            rows = rows[:limit]
        return rows, total

    # -- trace ring flush --------------------------------------------------

    def flush_trace(self) -> Optional[str]:
        """Write this process's span ring to its per-pid file under
        ``_metrics/fleet_trace/`` — same Chrome-trace shape as
        ``sofa_self_trace.json`` (telemetry._write_self_trace), ts
        re-based to the ring's oldest span.  Returns the path, or None
        when the ring is empty."""
        with self.guard:
            events = list(self._events)
        if not events:
            return None
        from sofa_tpu.durability import atomic_write

        pid = os.getpid()
        # "_metrics" joined inline so the artifact-flow lint (SL014) sees
        # the registry fragment on the writer's path expression.
        tdir = os.path.join(self.root, "_metrics", FLEET_TRACE_DIR_NAME)
        os.makedirs(tdir, exist_ok=True)
        ts_zero = min(e["ts"] for e in events)
        out = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"sofa fleet worker{self.worker}"}}]
        for cat, lane in sorted(FLEET_TRACE_LANES.items(),
                                key=lambda kv: kv[1]):
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": lane, "args": {"name": cat}})
        for e in events:
            out.append({"name": e["name"], "ph": "X", "cat": e["cat"],
                        "ts": e["ts"] - ts_zero, "dur": e["dur"],
                        "pid": pid, "tid": e["tid"], "args": e["args"]})
        path = os.path.join(tdir, f"ring.{self.worker:03d}.{pid}.json")
        doc = {"traceEvents": out, "displayTimeUnit": "ms",
               "otherData": {"ts_zero_unix": ts_zero / 1e6,
                             "producer": "sofa_tpu/metrics.py",
                             "worker": self.worker, "pid": pid}}
        with atomic_write(path) as f:
            json.dump(doc, f, separators=(",", ":"))
        return path

    # -- history persistence ----------------------------------------------

    def persist_history(self) -> Optional[dict]:
        """Persist the history ring as a chunked columnar store at
        ``_metrics/worker<NNN>/`` (frames.write_chunk_store: content-
        keyed chunks, index a pure function of the rows — identical under
        any ``--jobs``).  None when pyarrow is absent (the in-memory ring
        still serves /v1/metrics) or the history is empty."""
        from sofa_tpu import frames

        if not frames.columnar_available():
            return None
        with self.guard:
            rows = list(self._history)
        if not rows:
            return None
        import pandas as pd

        df = pd.DataFrame(rows, columns=["t", "name", "value"])
        sdir = os.path.join(self.root, "_metrics",
                            f"worker{self.worker:03d}")
        return frames.write_chunk_store(
            df, sdir, f"metrics_worker{self.worker:03d}",
            columns=["t", "name", "value"],
            chunk_rows=HISTORY_CHUNK_ROWS, time_column="t")


# Process-wide registry cache, keyed by abspath(root): tier code reaches
# its root's registry from any module without threading a handle through
# every call signature (WalAppender and the drainer only know a tenant
# root — _root_of_tenant maps it back).
_REG_GUARD = Guard("metrics.roots", protects=("_REGISTRIES",))
_REGISTRIES: Dict[str, MetricsRegistry] = {}


def for_root(root: str, worker: Optional[int] = None) -> MetricsRegistry:
    key = os.path.abspath(root)
    with _REG_GUARD:
        reg = _REGISTRIES.get(key)
        if reg is None:
            reg = MetricsRegistry(key, worker=worker or 0)
            _REGISTRIES[key] = reg
        if worker is not None:
            reg.worker = int(worker)
        return reg


def for_tenant_root(tenant_root: str) -> MetricsRegistry:
    """The fleet root's registry for a ``<root>/tenants/<t>`` path; a
    bare store root (library/test callers) keys its own registry."""
    return for_root(_root_of_tenant(tenant_root))


def _root_of_tenant(tenant_root: str) -> str:
    t = os.path.abspath(tenant_root)
    parent = os.path.dirname(t)
    # literal, not service.TENANTS_DIR_NAME: service.py imports this
    # module, and the constant is schema-frozen ("tenants") either way
    if os.path.basename(parent) == "tenants":
        return os.path.dirname(parent)
    return t


# ---------------------------------------------------------------------------
# The fleet trace export.
# ---------------------------------------------------------------------------

def export_fleet_trace(root: str) -> Optional[dict]:
    """Merge every per-process ring under ``_metrics/fleet_trace/`` into
    one Perfetto-valid Chrome-trace doc, re-based to the oldest span
    across rings, and write it atomically as ``fleet_trace.json`` beside
    them.  Returns the doc (None when no ring has flushed) — the
    cross-process join the tentpole promises: the agent's push spans and
    the drainer's WAL-replay spans land in different rings from
    different pids, and come out as one timeline."""
    from sofa_tpu.durability import atomic_write

    tdir = os.path.join(root, "_metrics", FLEET_TRACE_DIR_NAME)
    try:
        names = sorted(n for n in os.listdir(tdir)
                       if n.startswith("ring.") and n.endswith(".json"))
    except OSError:
        return None
    rings = []
    for name in names:
        try:
            with open(os.path.join(tdir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue  # torn/stale ring: the merge serves what is whole
        if isinstance(doc, dict) and isinstance(doc.get("traceEvents"),
                                                list):
            rings.append(doc)
    if not rings:
        return None
    zeros = [float((r.get("otherData") or {}).get("ts_zero_unix") or 0.0)
             for r in rings]
    base = min(z for z in zeros) if zeros else 0.0
    events: List[dict] = []
    for r, zero in zip(rings, zeros):
        shift = int((zero - base) * 1e6)
        for e in r["traceEvents"]:
            if not isinstance(e, dict):
                continue
            if e.get("ph") == "M":
                events.append(e)
            else:
                events.append({**e, "ts": int(e.get("ts", 0)) + shift})
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"ts_zero_unix": base,
                         "producer": "sofa_tpu/metrics.py",
                         "rings": len(rings)}}
    with atomic_write(os.path.join(tdir, FLEET_TRACE_NAME)) as f:
        json.dump(doc, f, separators=(",", ":"))
    return doc


# ---------------------------------------------------------------------------
# SLOs.
# ---------------------------------------------------------------------------

#: Two-char ops first: "<=" must not parse as "<" + "=5".
SLO_OPS = ("<=", ">=", "<", ">")

_SLO_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyz0123456789_.")


@dataclass(frozen=True)
class SloTarget:
    name: str
    op: str
    value: float


def parse_slo(spec: str) -> Tuple[SloTarget, ...]:
    """Parse ``'push_p99_ms<50,wal_depth<1000,replica_behind<3'``.
    Metric names are the flat snapshot vocabulary (docs/FLEET.md lists
    it); a bad entry raises ValueError naming the offender — callers
    surface it as a usage error, never a traceback."""
    targets: List[SloTarget] = []
    for entry in (e.strip() for e in (spec or "").split(",")):
        if not entry:
            continue
        for op in SLO_OPS:
            name, sep, raw = entry.partition(op)
            if sep:
                break
        else:
            raise ValueError(
                f"SLO entry {entry!r}: expected <metric><op><value> "
                f"with op in {SLO_OPS}")
        name = name.strip()
        if not name or not set(name) <= _SLO_NAME_OK:
            raise ValueError(
                f"SLO entry {entry!r}: bad metric name {name!r} "
                "(lowercase, digits, '_', '.')")
        try:
            value = float(raw.strip())
        except ValueError:
            raise ValueError(
                f"SLO entry {entry!r}: bad threshold {raw.strip()!r}") \
                from None
        targets.append(SloTarget(name=name, op=op, value=value))
    return tuple(targets)


def _target_status(op: str, observed: float, value: float) -> str:
    ok = {"<": observed < value, "<=": observed <= value,
          ">": observed > value, ">=": observed >= value}[op]
    return "ok" if ok else "breach"


def evaluate_slo(targets: Tuple[SloTarget, ...],
                 values: Dict[str, float], window: int,
                 injected: bool = False) -> dict:
    """One scrape window's typed verdict.  Every declared target answers
    ``ok`` / ``breach`` / ``no_data`` — a metric the window never
    observed is said so, not silently skipped (the `sofa live` breach-
    vocabulary discipline).  ``injected`` folds the slo_breach fault's
    synthetic target in, so the breach plumbing is testable on an
    otherwise healthy tier."""
    rows: List[dict] = []
    for t in targets:
        observed = values.get(t.name)
        if observed is None:
            rows.append({"name": t.name, "op": t.op, "value": t.value,
                         "observed": None, "status": "no_data"})
            continue
        rows.append({"name": t.name, "op": t.op, "value": t.value,
                     "observed": round(float(observed), 3),
                     "status": _target_status(t.op, float(observed),
                                              t.value)})
    if injected:
        rows.append({"name": "injected_fault", "op": "<", "value": 0.0,
                     "observed": 1.0, "status": "breach"})
    breaching = [r["name"] for r in rows if r["status"] == "breach"]
    return {"schema": SLO_SCHEMA, "version": SLO_VERSION,
            "window": int(window),
            "generated_unix": round(time.time(), 3),
            "targets": rows, "breaching": breaching,
            "ok": not breaching}


# ---------------------------------------------------------------------------
# The scrape loop.
# ---------------------------------------------------------------------------

class Scraper:
    """One worker's scrape loop: tick -> gauges -> snapshot -> history ->
    chunk store + trace flush -> SLO verdict.  Run as a daemon thread by
    the serving process (`start`/`close`), or driven tick-by-tick in
    tests (`tick` is the whole contract; the thread is just cadence)."""

    def __init__(self, reg: MetricsRegistry,
                 slo_targets: Tuple[SloTarget, ...] = (),
                 interval_s: Optional[float] = None,
                 role: str = "primary"):
        self.reg = reg
        self.slo_targets = tuple(slo_targets)
        self.interval_s = (SCRAPE_INTERVAL_S if interval_s is None
                           else float(interval_s))
        self.role = role
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if not metrics_enabled() or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="sofa-metrics-scrape", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # sofa-lint: disable=SL002 — the scrape loop must never kill the serving process; a failed window is simply absent from the history
                pass

    # -- one window --------------------------------------------------------

    def tick(self) -> Optional[dict]:
        """One scrape window.  Returns the SLO verdict (None when no
        targets are declared and no fault injected, or when a
        ``scrape_stall`` fault froze the window — last-scrape age then
        grows honestly, which is the point of that fault)."""
        from sofa_tpu import faults

        reg = self.reg
        if not metrics_enabled():
            return None
        if faults.maybe_scrape_stall():
            reg.inc("scrape_stalled")
            return None
        t0 = time.time()
        self._collect_gauges()
        flat, _hists = reg.snapshot()
        window = reg.scrape_seq + 1
        stable = {k: v for k, v in flat.items()
                  if k not in _VOLATILE_KEYS}
        reg.record_window(t0, stable)
        reg.persist_history()
        reg.flush_trace()
        verdict = self._evaluate(flat, window)
        wall_ms = (time.time() - t0) * 1e3
        reg.set_gauge("scrape_wall_ms", round(wall_ms, 3))
        return verdict

    def _collect_gauges(self) -> None:
        """Tier gauges computed from disk each window: WAL depth across
        tenants and the drain lag behind the oldest pending work.
        Replica staleness is pushed by the puller itself
        (tier.ReplicaPuller sets ``replica_behind`` after each pull)."""
        from sofa_tpu.archive import tier

        reg = self.reg
        tdir = os.path.join(reg.root, "tenants")
        depth = 0
        tenants = 0
        try:
            names = sorted(os.listdir(tdir))
        except OSError:
            names = []
        for name in names:
            troot = os.path.join(tdir, name)
            if not os.path.isdir(troot):
                continue
            tenants += 1
            try:
                depth += tier.wal_depth(troot)
            except OSError:
                continue
        reg.set_gauge("wal_depth", depth)
        reg.set_gauge("tenants", tenants)
        last_drain = reg.get_gauge("last_drain_unix", 0.0)
        lag = 0.0
        if depth and last_drain:
            lag = max(time.time() - last_drain, 0.0)
        reg.set_gauge("drain_lag_s", round(lag, 3))

    def _evaluate(self, flat: Dict[str, float], window: int) \
            -> Optional[dict]:
        from sofa_tpu import faults

        injected = faults.maybe_slo_breach(window)
        if not self.slo_targets and not injected:
            return None
        verdict = evaluate_slo(self.slo_targets, flat, window,
                               injected=injected)
        reg = self.reg
        write_slo_verdict(reg.root, verdict)
        fresh = reg.update_slo(verdict)
        # Worker 0 alone writes catalog events: every pool worker scrapes
        # the same tier-level gauges, and a breach is one fact, not one
        # per worker.
        if fresh and reg.worker == 0:
            self._append_breach_events(verdict, fresh)
        return verdict

    def _append_breach_events(self, verdict: dict,
                              fresh: List[str]) -> None:
        from sofa_tpu.archive import catalog

        by_name = {r["name"]: r for r in verdict["targets"]}
        tdir = os.path.join(self.reg.root, "tenants")
        try:
            tenants = sorted(n for n in os.listdir(tdir)
                             if os.path.isdir(os.path.join(tdir, n)))
        except OSError:
            tenants = []
        for tenant in tenants:
            for name in fresh:
                row = by_name.get(name) or {}
                try:
                    catalog.append_event(
                        os.path.join(tdir, tenant), "slo_breach",
                        metric=name, op=row.get("op"),
                        threshold=row.get("value"),
                        observed=row.get("observed"),
                        window=verdict["window"],
                        worker=self.reg.worker)
                except OSError:
                    continue  # an unwritable tenant must not stall the scrape


def write_slo_verdict(root: str, verdict: dict) -> str:
    """Atomically persist the window's verdict at
    ``_metrics/slo_verdict.json`` (trace.py DERIVED/DIGEST-SKIP — the
    scrape loop rewrites it outside any digest refresh)."""
    from sofa_tpu.durability import atomic_write

    mdir = os.path.join(root, "_metrics")
    os.makedirs(mdir, exist_ok=True)
    path = os.path.join(mdir, SLO_VERDICT_NAME)
    with atomic_write(path) as f:
        json.dump(verdict, f, indent=1, sort_keys=True)
    return path


def load_slo_verdict(root: str) -> Optional[dict]:
    path = os.path.join(root, METRICS_DIR_NAME, SLO_VERDICT_NAME)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("schema") != SLO_SCHEMA:
        return None
    return doc


# ---------------------------------------------------------------------------
# The /v1/metrics document.
# ---------------------------------------------------------------------------

def metrics_doc(reg: MetricsRegistry, offset: int = 0, limit: int = 0,
                window_s: Optional[float] = None,
                role: str = "primary") -> Tuple[dict, str]:
    """(document, ETag) for ``GET /v1/metrics``.  The ETag hashes the
    doc minus its wall-clock stamps, so an idle tier — no counter moved,
    no history row appended, same verdict — answers 304 to If-None-Match
    polls no matter how many scrape windows passed."""
    flat, hists = reg.snapshot()
    rows, total = reg.history_rows(offset=offset, limit=limit,
                                   window_s=window_s)
    with reg.guard:
        verdict = reg.slo_verdict
        seq = reg.scrape_seq
        last = reg.last_scrape_unix
    doc = {
        "schema": METRICS_SCHEMA, "version": METRICS_VERSION,
        "role": role, "worker": reg.worker,
        "generated_unix": round(time.time(), 3),
        "last_scrape_unix": round(last, 3),
        "scrape_seq": seq,
        "interval_s": SCRAPE_INTERVAL_S,
        "snapshot": {k: v for k, v in sorted(flat.items())},
        "histograms": hists,
        "history": {"total": total, "offset": int(offset),
                    "limit": int(limit),
                    # ring rows are [t, name, value] triples; the wire
                    # shape is the named-row contract the board and
                    # manifest_check.validate_fleet_metrics consume
                    "rows": [{"t": r[0], "name": r[1], "value": r[2]}
                             for r in rows]},
        "slo": verdict,
    }
    return doc, _doc_etag(doc)


def _doc_etag(doc: dict) -> str:
    stable = {k: v for k, v in doc.items()
              if k not in ("generated_unix", "last_scrape_unix",
                           "scrape_seq")}
    stable["snapshot"] = {k: v for k, v in doc["snapshot"].items()
                          if k not in _VOLATILE_KEYS
                          and not k.endswith("_unix")
                          # rates divide by wall time since the last
                          # scrape, so they drift between identical
                          # polls — content, not the clock, moves the tag
                          and not k.endswith("_rps")
                          # the metrics GET is itself a response, so this
                          # counter bumps on every poll — keeping it in the
                          # tag would make an idle tier never answer 304
                          and k != "responses_total"}
    slo = doc.get("slo")
    if isinstance(slo, dict):
        stable["slo"] = {k: v for k, v in slo.items()
                         if k not in ("generated_unix", "window")}
    sig = hashlib.sha256(
        json.dumps(stable, sort_keys=True,
                   separators=(",", ":")).encode()).hexdigest()[:16]
    return f'"met-{doc["worker"]}-{sig}"'


def metrics_summary(reg: MetricsRegistry) -> dict:
    """The compact fold for ``/v1/tier`` and commit acks: enough for
    `sofa status --fleet` and agents' meta.metrics without the full
    history payload."""
    flat, _ = reg.snapshot()
    with reg.guard:
        verdict = reg.slo_verdict
        last = reg.last_scrape_unix
    out = {
        "last_scrape_unix": round(last, 3),
        "scrape_age_s": round(time.time() - last, 3) if last else None,
        "push_p99_ms": flat.get("push_p99_ms"),
        "wal_depth": flat.get("wal_depth"),
        "replica_behind": flat.get("replica_behind"),
        # admission-control observability: the refusal-rate pair rides
        # /v1/tier so chaos_tier.py can aggregate it per worker ordinal
        "refusals_total": flat.get("refusals_total"),
        "responses_total": flat.get("responses_total"),
        "slo_ok": None if verdict is None else bool(verdict.get("ok")),
        "slo_breaching": list((verdict or {}).get("breaching") or []),
    }
    return {k: v for k, v in out.items() if v is not None or
            k in ("slo_ok", "scrape_age_s")}
