"""Content-keyed ingest cache: parsed frames beside the logdir.

Re-running ``sofa preprocess`` / ``sofa report`` used to reparse every raw
collector file from scratch.  Parsed frames are pure functions of (raw file
bytes, parser version, parse parameters), so each ingest source's output is
cached under ``<logdir>/_ingest_cache/`` keyed on:

  * every raw file's (path, size, mtime_ns) — an absent file is recorded as
    absent, so a source appearing later invalidates cleanly;
  * the source's entry in :data:`PARSER_VERSIONS` — bump it whenever a
    parser's OUTPUT for the same input changes;
  * parse parameters that shape the output (time_base, strace min_time, ...).

On a key match the cached parquet loads instead of reparsing (pickle
fallback when pyarrow is absent); any mismatch reparses and overwrites.
Frames are cached PRE time-offset: ``--cpu_time_offset_ms`` /
``--tpu_time_offset_ms`` are applied by preprocess after loading, so
changing an offset never invalidates the cache.

Escape hatches: ``--no_ingest_cache`` bypasses both read and write;
``sofa clean`` removes the cache directory with the other derived files.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import pandas as pd

from sofa_tpu.concurrency import Guard
from sofa_tpu.printing import print_info, print_warning

CACHE_DIR_NAME = "_ingest_cache"

# Cache container format; a bump invalidates every cached source at once.
CACHE_FORMAT = 1

# Per-source parser versions — bump a source's entry whenever its parser's
# output for unchanged input changes (new columns, fixed math, ...).
PARSER_VERSIONS: Dict[str, int] = {
    "mpstat": 1,
    "diskstat": 1,
    "netbandwidth": 1,
    "cpuinfo": 1,
    "vmstat": 1,
    "cputrace": 1,
    "strace": 1,
    "pystacks": 1,
    "nettrace": 1,
    "tpumon": 1,
    "blktrace": 1,
    "xplane": 1,
}


def _file_sig(path: str) -> List:
    """(path, size, mtime_ns); absent files sign as (-1, -1) so presence
    changes flip the key."""
    try:
        st = os.stat(path)
        return [path, int(st.st_size), int(st.st_mtime_ns)]
    except OSError:
        return [path, -1, -1]


def make_key(source: str, raw_paths, params: "dict | None" = None) -> dict:
    return {
        "format": CACHE_FORMAT,
        "source": source,
        "parser_version": PARSER_VERSIONS.get(source, 0),
        "files": [_file_sig(p) for p in sorted(raw_paths)],
        "params": params or {},
    }


def raw_files_present(key: dict) -> bool:
    """Whether ANY raw input exists — sources with nothing on disk parse to
    an empty frame instantly and are not worth a cache entry."""
    return any(size >= 0 for _p, size, _m in key["files"])


class IngestCache:
    """One logdir's ingest cache.  ``enabled=False`` turns every operation
    into a no-op so ``--no_ingest_cache`` needs no branching in callers."""

    def __init__(self, root: str, enabled: bool = True):
        self.root = root
        self.enabled = enabled
        # One cache instance serves every ingest pool worker: the
        # hit/miss/size ledgers are cross-context shared state (SL019).
        self._ledger_guard = Guard("ingest_cache.ledgers", protects=(
            "hits", "misses", "stored_bytes"))
        self.hits: List[str] = []
        self.misses: List[str] = []
        self.stored_bytes: Dict[str, int] = {}

    def _key_path(self, source: str) -> str:
        return os.path.join(self.root, f"{source}.key.json")

    def _frame_path(self, source: str, frame: str, ext: str) -> str:
        return os.path.join(self.root, f"{source}__{frame}{ext}")

    def load(self, source: str, key: dict) -> "Optional[dict]":
        """Cached ``{"frames": {name: df}, "meta": {...}}`` on a key match,
        else None.  Any read/parse problem degrades to a miss."""
        if not self.enabled:
            return None
        try:
            with open(self._key_path(source)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            with self._ledger_guard:
                self.misses.append(source)
            return None
        if doc.get("key") != key:
            with self._ledger_guard:
                self.misses.append(source)
            return None
        from sofa_tpu.trace import _conform

        frames: Dict[str, pd.DataFrame] = {}
        try:
            for name in doc.get("frames", []):
                pq = self._frame_path(source, name, ".parquet")
                pk = self._frame_path(source, name, ".pkl")
                if os.path.isfile(pq):
                    frames[name] = _conform(pd.read_parquet(pq))
                elif os.path.isfile(pk):
                    frames[name] = _conform(pd.read_pickle(pk))
                else:
                    with self._ledger_guard:
                        self.misses.append(source)
                    return None
        except Exception as e:  # noqa: BLE001 — a corrupt cache entry is a miss
            print_warning(f"ingest cache: unreadable entry for {source} "
                          f"({e}); reparsing from raw")
            with self._ledger_guard:
                self.misses.append(source)
            return None
        with self._ledger_guard:
            self.hits.append(source)
        return {"frames": frames, "meta": doc.get("meta") or {}}

    def invalidate(self, source: str) -> None:
        """Drop every stored entry for a source.  The quarantine contract:
        a source whose raw input was quarantined must never be served warm
        (preprocess calls this even when ``enabled=False`` — a bypassed
        cache still holds files a later cached run would read)."""
        try:
            os.unlink(self._key_path(source))
        except OSError:
            pass
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            if name.startswith(source + "__"):
                try:
                    os.unlink(os.path.join(self.root, name))
                except OSError:
                    pass

    def stats(self) -> dict:
        """Hit/miss ledger + bytes written this run, for the run manifest
        (sofa_tpu/telemetry.py) — which sources reparsed, and how much
        cache the logdir is carrying because of it."""
        return {
            "enabled": self.enabled,
            "hits": sorted(self.hits),
            "misses": sorted(set(self.misses)),
            "stored_bytes": dict(self.stored_bytes),
        }

    def chunks(self) -> "ChunkStore":
        """The chunk-granular sibling store `sofa live` tails into (same
        enablement/bypass policy as the whole-source cache)."""
        return ChunkStore(self.root, enabled=self.enabled)

    def store(self, source: str, key: dict,
              frames: Dict[str, pd.DataFrame],
              meta: "dict | None" = None) -> None:
        """Persist a parse result; best-effort (a read-only logdir must not
        fail preprocess)."""
        if not self.enabled:
            return
        try:
            os.makedirs(self.root, exist_ok=True)
            stored = 0
            for name, df in frames.items():
                pq = self._frame_path(source, name, ".parquet")
                pk = self._frame_path(source, name, ".pkl")
                try:
                    df.to_parquet(pq + ".tmp", index=False)
                    os.replace(pq + ".tmp", pq)
                    if os.path.isfile(pk):
                        os.unlink(pk)  # never shadow a fresh parquet
                    stored += os.path.getsize(pq)
                except Exception as e:  # noqa: BLE001 — no pyarrow: pickle fallback
                    print_info(f"ingest cache: parquet store of "
                               f"{source}/{name} failed ({e}); "
                               "using pickle")
                    df.to_pickle(pk + ".tmp")
                    os.replace(pk + ".tmp", pk)
                    if os.path.isfile(pq):
                        os.unlink(pq)
                    stored += os.path.getsize(pk)
            with self._ledger_guard:
                self.stored_bytes[source] = stored
            doc = {"key": key, "frames": sorted(frames), "meta": meta or {}}
            # Key json LAST — a crash mid-store leaves a stale key that
            # simply mismatches, never a key pointing at missing frames.
            from sofa_tpu.durability import atomic_write

            with atomic_write(self._key_path(source), fsync=True) as f:
                json.dump(doc, f)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Chunk-granular cache — the `sofa live` re-keying of this cache.
#
# The whole-source keys above sign (size, mtime): correct for batch runs,
# but a GROWING raw file flips its key on every append and the whole file
# reparses.  `sofa live` (sofa_tpu/live.py) therefore keys at chunk
# granularity: each committed [start, end) byte range of a tailable source
# parses exactly ONCE, lands here as a parquet frame, and every later
# epoch (and every crash replay) LOADS it instead of reparsing — the
# "committed chunks are never re-parsed" contract, proven by the
# loads/parses ledger the live manifest carries.
# ---------------------------------------------------------------------------

CHUNK_DIR_NAME = "_live_chunks"


class ChunkStore:
    """Per-logdir chunk frames under ``_ingest_cache/_live_chunks/``.

    Chunk files are atomic (tmp+rename) and named by their byte range, so
    a replayed epoch overwrites its own half-written chunk
    deterministically; the offset ledger (live.OffsetLedger) is the
    commit point — a chunk file without a ledger entry is simply
    re-derived."""

    def __init__(self, root: str, enabled: bool = True):
        self.root = os.path.join(root, CHUNK_DIR_NAME)
        self.enabled = enabled
        self.loads: Dict[str, int] = {}

    def _path(self, source: str, start: int, end: int, ext: str) -> str:
        return os.path.join(self.root, source,
                            f"{int(start):012d}-{int(end):012d}{ext}")

    def store(self, source: str, start: int, end: int,
              df: pd.DataFrame) -> bool:
        """Persist one chunk's parsed frame; best-effort like the
        whole-source store (an unwritable logdir degrades to reparsing
        that chunk on the next epoch, never a failed tick)."""
        if not self.enabled:
            return False
        pq = self._path(source, start, end, ".parquet")
        pk = self._path(source, start, end, ".pkl")
        try:
            os.makedirs(os.path.dirname(pq), exist_ok=True)
            try:
                df.to_parquet(pq + ".tmp", index=False)
                os.replace(pq + ".tmp", pq)
                if os.path.isfile(pk):
                    os.unlink(pk)
            except Exception as e:  # noqa: BLE001 — no pyarrow: pickle fallback
                print_info(f"live chunk cache: parquet store of "
                           f"{source}[{start}:{end}] failed ({e}); "
                           "using pickle")
                df.to_pickle(pk + ".tmp")
                os.replace(pk + ".tmp", pk)
            return True
        except OSError:
            return False

    def load(self, source: str, start: int,
             end: int) -> "Optional[pd.DataFrame]":
        """A committed chunk's frame, or None (→ the caller reparses the
        byte range; any unreadable chunk degrades the same way)."""
        if not self.enabled:
            return None
        from sofa_tpu.trace import _conform

        pq = self._path(source, start, end, ".parquet")
        pk = self._path(source, start, end, ".pkl")
        try:
            if os.path.isfile(pq):
                df = _conform(pd.read_parquet(pq))
            elif os.path.isfile(pk):
                df = _conform(pd.read_pickle(pk))
            else:
                return None
        except Exception as e:  # noqa: BLE001 — a corrupt chunk is a miss
            print_warning(f"live chunk cache: unreadable chunk "
                          f"{source}[{start}:{end}] ({e}); reparsing")
            return None
        self.loads[source] = self.loads.get(source, 0) + 1
        return df

    def discard(self, source: str, start: int, end: int) -> None:
        """Remove one chunk's files (compaction superseded them)."""
        for ext in (".parquet", ".pkl"):
            try:
                os.unlink(self._path(source, start, end, ext))
            except OSError:
                pass

    def drop(self, source: str) -> None:
        """Forget every chunk of a source (rotation, fsck repair)."""
        import shutil

        shutil.rmtree(os.path.join(self.root, source), ignore_errors=True)
