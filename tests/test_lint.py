"""sofa-lint: per-rule positive/negative fixtures, suppressions, baseline
add/expire semantics, the exit-code contract, and the self-run gate.

The self-run (`test_self_run_tree_is_clean`) is the tier-1 smoke test the
ISSUE asks for: the shipped tree must lint clean against the checked-in
baseline, and the baseline must only ever shrink.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from sofa_tpu.lint.baseline import Baseline, fingerprint_findings
from sofa_tpu.lint.core import ProjectContext, lint_paths
from sofa_tpu.lint.cli import run_lint
from sofa_tpu.lint.rules import default_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_COLUMNS = ProjectContext.detect([]).columns  # the real schema


def run_rules(tmp_path, relname, src, columns=None):
    """Write one synthetic module and lint it; returns findings."""
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    project = ProjectContext(columns=frozenset(
        columns if columns is not None else _COLUMNS))
    return lint_paths([str(path)], default_rules(), project=project,
                      base=str(tmp_path))


def rule_ids(findings):
    return [f.rule_id for f in findings]


# --- SL001 ------------------------------------------------------------------

def test_sl001_flags_unbounded_subprocess(tmp_path):
    fs = run_rules(tmp_path, "m.py", """
        import subprocess
        subprocess.run(["ls"])
    """)
    assert rule_ids(fs) == ["SL001"]
    assert fs[0].line == 3


def test_sl001_ok_with_timeout_or_kwargs_or_alias(tmp_path):
    fs = run_rules(tmp_path, "m.py", """
        import subprocess as sp
        from subprocess import check_output
        sp.run(["ls"], timeout=5)
        check_output(["ls"], timeout=1)
        kw = {"timeout": 2}
        sp.call(["ls"], **kw)
        sp.Popen(["ls"])  # async by design: bounded at wait/stop time
    """)
    assert fs == []


def test_sl001_alias_and_from_import_detected(tmp_path):
    fs = run_rules(tmp_path, "m.py", """
        import subprocess as sp
        from subprocess import check_call
        sp.check_output(["ls"])
        check_call(["ls"])
    """)
    assert rule_ids(fs) == ["SL001", "SL001"]


def test_sl001_exempt_in_collector_base(tmp_path):
    fs = run_rules(tmp_path, "collectors/base.py", """
        import subprocess
        subprocess.run(["ls"])
    """)
    assert fs == []


# --- SL002 ------------------------------------------------------------------

def test_sl002_flags_silent_broad_except(tmp_path):
    fs = run_rules(tmp_path, "m.py", """
        try:
            x = 1
        except Exception:
            pass
        try:
            x = 2
        except:
            x = 0
    """)
    assert rule_ids(fs) == ["SL002", "SL002"]


def test_sl002_ok_when_routed_or_reraised(tmp_path):
    fs = run_rules(tmp_path, "m.py", """
        from sofa_tpu.printing import print_warning
        try:
            x = 1
        except Exception as e:
            print_warning(f"degraded: {e}")
        try:
            x = 2
        except Exception:
            raise
        try:
            x = 3
        except (ValueError, OSError):
            pass  # narrow except: the rule only polices broad ones
    """)
    assert fs == []


# --- SL003 ------------------------------------------------------------------

def test_sl003_flags_deadline_math(tmp_path):
    fs = run_rules(tmp_path, "m.py", """
        import time
        t0 = time.time()          # plain anchor: allowed
        while time.time() - t0 < 5.0:   # comparison: flagged
            pass
        retry_at = time.time() + 2.0    # backoff arithmetic: flagged
    """)
    assert rule_ids(fs) == ["SL003", "SL003"]


def test_sl003_allows_wall_anchors(tmp_path):
    fs = run_rules(tmp_path, "m.py", """
        import time
        stamp = time.time()
        doc = {"t": time.time(), "pid": 1}
        wall = round(time.time() - stamp, 6)  # no deadline words: allowed
    """)
    assert fs == []


# --- SL004 ------------------------------------------------------------------

def test_sl004_flags_schema_drift_in_ingest(tmp_path):
    fs = run_rules(tmp_path, "ingest/foo.py", """
        rows = [{"timestamp": 1.0, "duration": 0.1, "nmae": "x"}]
    """)
    assert rule_ids(fs) == ["SL004"]
    assert "'nmae'" in fs[0].message


def test_sl004_ok_outside_ingest_and_without_anchor(tmp_path):
    good = """
        rows = [{"timestamp": 1.0, "duration": 0.1, "name": "x"}]
        internal = {"flops": 1, "phase": "fw", "kind": 3}  # no anchor key
    """
    assert run_rules(tmp_path, "ingest/foo.py", good) == []
    drifted = 'rows = [{"timestamp": 1.0, "duration": 0.1, "nmae": "x"}]'
    assert run_rules(tmp_path, "analysis/foo.py", drifted) == []


# --- SL005 ------------------------------------------------------------------

def test_sl005_flags_incomplete_collector(tmp_path):
    fs = run_rules(tmp_path, "collectors/foo.py", """
        from sofa_tpu.collectors.base import Collector
        class FooCollector(Collector):
            name = "foo"
            def probe(self):
                return None
    """)
    assert sorted(rule_ids(fs)) == ["SL005", "SL005"]  # outputs + hooks


def test_sl005_ok_with_surface(tmp_path):
    fs = run_rules(tmp_path, "collectors/foo.py", """
        from sofa_tpu.collectors.base import ProcessCollector
        class FooCollector(ProcessCollector):
            name = "foo"
            def start(self):
                pass
            def outputs(self):
                return []
        class Helper:  # not a collector: ignored
            pass
    """)
    assert fs == []


# --- SL006 ------------------------------------------------------------------

def test_sl006_flags_worker_global_write(tmp_path):
    fs = run_rules(tmp_path, "ingest/foo.py", """
        _CACHE = None
        def parse(text):
            global _CACHE
            _CACHE = text
    """)
    assert rule_ids(fs) == ["SL006"]
    assert fs[0].severity == "warn"


def test_sl006_ignores_driver_modules(tmp_path):
    fs = run_rules(tmp_path, "faults.py", """
        _PLAN = None
        def install(plan):
            global _PLAN
            _PLAN = plan
    """)
    assert fs == []


# --- SL007 ------------------------------------------------------------------

def test_sl007_flags_raw_open_outside_ingest(tmp_path):
    fs = run_rules(tmp_path, "analysis/foo.py", """
        import os
        def load(logdir):
            with open(os.path.join(logdir, "perf.script")) as f:
                return f.read()
    """)
    assert rule_ids(fs) == ["SL007"]


def test_sl007_allows_ingest_and_derived_files(tmp_path):
    raw = """
        import os
        def load(logdir):
            with open(os.path.join(logdir, "perf.script")) as f:
                return f.read()
    """
    assert run_rules(tmp_path, "ingest/foo.py", raw) == []
    fs = run_rules(tmp_path, "analysis/foo.py", """
        def load(logdir):
            with open(logdir + "/cputrace.csv") as f:  # derived: allowed
                return f.read()
    """)
    assert fs == []


# --- SL008 ------------------------------------------------------------------

def test_sl008_flags_direct_kills(tmp_path):
    fs = run_rules(tmp_path, "collectors/foo.py", """
        import os, signal
        def die(proc):
            os.kill(proc.pid, signal.SIGKILL)
            proc.kill()
    """)
    assert rule_ids(fs) == ["SL008", "SL008"]


def test_sl008_exempt_in_signal_tree_owners(tmp_path):
    src = """
        import os, signal
        def die(proc):
            os.killpg(proc.pid, signal.SIGTERM)
    """
    assert run_rules(tmp_path, "record.py", src) == []
    assert run_rules(tmp_path, "collectors/base.py", src) == []


# --- SL009 ------------------------------------------------------------------

def test_sl009_flags_bare_derived_writes(tmp_path):
    fs = run_rules(tmp_path, "tiles.py", """
        import gzip
        def write(path, blob, doc):
            with open(path, "w") as f:
                f.write(doc)
            with gzip.open(path + ".gz", mode="wb") as f:
                f.write(blob)
    """)
    assert rule_ids(fs) == ["SL009", "SL009"]


def test_sl009_allows_reads_helper_and_out_of_scope(tmp_path):
    # reads never trip it, and the helper module itself is exempt
    src_read = """
        def load(path):
            with open(path) as f:
                return f.read()
    """
    assert run_rules(tmp_path, "tiles.py", src_read) == []
    src_write = """
        def write(path, doc):
            with open(path, "w") as f:
                f.write(doc)
    """
    assert run_rules(tmp_path, "durability.py", src_write) == []
    # raw-file producers (collectors/record) are out of scope by design
    assert run_rules(tmp_path, "collectors/foo.py", src_write) == []


# --- engine: suppressions, parse errors ------------------------------------

def test_inline_suppression_silences_one_line(tmp_path):
    fs = run_rules(tmp_path, "m.py", """
        import subprocess
        subprocess.run(["a"])  # sofa-lint: disable=SL001 — probe, bounded by caller
        subprocess.run(["b"])
    """)
    assert [(f.rule_id, f.line) for f in fs] == [("SL001", 4)]


def test_file_level_suppression_and_all(tmp_path):
    fs = run_rules(tmp_path, "m.py", """
        # sofa-lint: disable-file=SL001
        import subprocess
        subprocess.run(["a"])
        try:
            pass
        except Exception:  # sofa-lint: disable=all — suppressions anchor to the reported line
            pass
    """)
    assert fs == []


def test_suppression_marker_in_string_does_not_suppress(tmp_path):
    fs = run_rules(tmp_path, "m.py", """
        import subprocess
        subprocess.run(["sofa-lint: disable=SL001"])
    """)
    assert rule_ids(fs) == ["SL001"]


def test_syntax_error_becomes_sl000_finding(tmp_path):
    fs = run_rules(tmp_path, "m.py", "def broken(:\n")
    assert rule_ids(fs) == ["SL000"]


# --- baseline semantics -----------------------------------------------------

def _lint_cli(tmp_path, *extra):
    """run_lint over tmp_path with a tmp baseline; returns (rc, baseline)."""
    bl = str(tmp_path / "lint_baseline.json")
    rc = run_lint([str(tmp_path), "--baseline", bl,
                   "--base", str(tmp_path), *extra])
    return rc, bl


def test_baseline_grandfathers_then_catches_new(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text("import subprocess\nsubprocess.run(['a'])\n")
    rc, bl = _lint_cli(tmp_path)
    assert rc == 1  # no baseline yet: the finding is new
    rc, _ = _lint_cli(tmp_path, "--update-baseline")
    assert rc == 0
    doc = json.load(open(bl))
    assert len(doc["entries"]) == 1
    rc, _ = _lint_cli(tmp_path)
    assert rc == 0  # grandfathered
    # A NEW violation fails even though the old one stays grandfathered.
    mod.write_text("import subprocess\nsubprocess.run(['a'])\n"
                   "subprocess.check_call(['b'])\n")
    rc, _ = _lint_cli(tmp_path)
    assert rc == 1


def test_baseline_entry_expires_when_fixed(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text("import subprocess\nsubprocess.run(['a'])\n"
                   "subprocess.run(['b'])\n")
    _lint_cli(tmp_path, "--update-baseline")
    mod.write_text("import subprocess\nsubprocess.run(['a'], timeout=5)\n"
                   "subprocess.run(['b'])\n")
    rc, bl = _lint_cli(tmp_path, "--update-baseline")
    assert rc == 0
    doc = json.load(open(bl))
    assert len(doc["entries"]) == 1  # the fixed site expired
    assert "['b']" in open(str(mod)).read()


def test_editing_a_baselined_line_resurfaces_it(tmp_path):
    """Fingerprints key on the line's TEXT: touching a grandfathered call
    (e.g. deleting its argument) must fail, not stay hidden."""
    mod = tmp_path / "m.py"
    mod.write_text("import subprocess\nsubprocess.run(['a'])\n")
    _lint_cli(tmp_path, "--update-baseline")
    mod.write_text("import subprocess\nsubprocess.run(['a', '-v'])\n")
    rc, _ = _lint_cli(tmp_path)
    assert rc == 1


def test_line_moves_do_not_churn_baseline(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text("import subprocess\nsubprocess.run(['a'])\n")
    _lint_cli(tmp_path, "--update-baseline")
    mod.write_text("import subprocess\n\n\n# moved down\n"
                   "subprocess.run(['a'])\n")
    rc, _ = _lint_cli(tmp_path)
    assert rc == 0


def test_cli_json_and_internal_error_rc(tmp_path, capsys):
    (tmp_path / "m.py").write_text("import subprocess\nsubprocess.run(['a'])\n")
    rc = run_lint([str(tmp_path), "--no-baseline", "--json",
                   "--base", str(tmp_path)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and len(doc["new"]) == 1
    bad = tmp_path / "bad_baseline.json"
    bad.write_text("{not json")
    rc = run_lint([str(tmp_path), "--baseline", str(bad)])
    assert rc == 2


# --- the gate: self-run over the shipped tree ------------------------------

def test_self_run_tree_is_clean():
    """The shipped sofa_tpu/ must have zero non-baselined findings — this
    is the tier-1 lint smoke the CI satellite asks for."""
    rc = run_lint([os.path.join(REPO, "sofa_tpu"),
                   "--baseline", os.path.join(REPO, "lint_baseline.json"),
                   "--base", REPO])
    assert rc == 0


def test_self_run_baseline_only_shrinks():
    """Every baseline entry must still correspond to a live finding:
    stale entries mean someone fixed a site without --update-baseline
    (fine) — but entries must never exceed the current finding count,
    and every current finding must be grandfathered (no new debt)."""
    base = REPO
    findings = lint_paths([os.path.join(REPO, "sofa_tpu")], default_rules(),
                          base=base)

    def text_for(f):
        with open(os.path.join(base, f.file), errors="replace") as fh:
            lines = fh.read().splitlines()
        return lines[f.line - 1] if 1 <= f.line <= len(lines) else ""

    fps = fingerprint_findings(findings, text_for)
    baseline = Baseline.load(os.path.join(REPO, "lint_baseline.json"))
    new, old = baseline.split(fps)
    assert new == []
    assert len(old) <= len(baseline.entries)


def test_exit_code_contract_subprocess():
    """tools/sofa_lint.py exit codes through a real process: 0 clean."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sofa_lint.py"),
         os.path.join(REPO, "sofa_tpu")],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


def test_cli_verb_lint():
    from sofa_tpu.cli import main

    assert main(["lint", os.path.join(REPO, "sofa_tpu")]) == 0


def test_mutation_is_caught(tmp_path):
    """Acceptance check: copying one shipped module and deleting a
    timeout= yields a fresh file:line finding."""
    src = open(os.path.join(REPO, "sofa_tpu", "ingest",
                            "native_scan.py")).read()
    assert "timeout=_scan_timeout_s()" in src
    mut = tmp_path / "ingest" / "native_scan.py"
    mut.parent.mkdir()
    mut.write_text(src.replace(", timeout=_scan_timeout_s()", ""))
    findings = lint_paths([str(mut)], default_rules(), base=str(tmp_path))
    assert any(f.rule_id == "SL001" and f.line > 0 for f in findings)


# --- SL010-SL013: analysis-pass contract rules ------------------------------

def run_pass_rules(tmp_path, files):
    """Write {relname: src} fixture modules, detect the pass declarations
    across all of them, lint them all; returns SL01x findings only (the
    fixtures may incidentally trip unrelated rules)."""
    paths = []
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
        paths.append(str(p))
    project = ProjectContext.detect(paths, base=str(tmp_path))
    fs = lint_paths(paths, default_rules(), project=project,
                    base=str(tmp_path))
    return [f for f in fs if f.rule_id in ("SL010", "SL011", "SL012",
                                           "SL013")]


def test_sl010_flags_undeclared_frame_column_feature_access(tmp_path):
    fs = run_pass_rules(tmp_path, {"p.py": '''
        from sofa_tpu.analysis.registry import analysis_pass

        @analysis_pass(name="leaky", reads_frames=("tputrace",),
                       reads_columns=("timestamp",),
                       provides_features=("leaky_metric",))
        def leaky(frames, cfg, features):
            df = frames.get("cputrace")          # undeclared frame
            x = frames["mpstat"]                 # undeclared frame
            y = df["duration"]                   # undeclared column
            features.add("other_metric", 1.0)    # undeclared write
            features.get("foreign_metric")       # undeclared read
            features.add("leaky_metric", 1.0)
    '''})
    msgs = [f.message for f in fs if f.rule_id == "SL010"]
    assert len(msgs) == 5, msgs
    assert any("'cputrace'" in m for m in msgs)
    assert any("'mpstat'" in m for m in msgs)
    assert any("'duration'" in m for m in msgs)
    assert any("'other_metric'" in m for m in msgs)
    assert any("'foreign_metric'" in m for m in msgs)


def test_sl010_clean_when_declared_including_patterns(tmp_path):
    fs = run_pass_rules(tmp_path, {"p.py": '''
        from sofa_tpu.analysis.registry import analysis_pass

        @analysis_pass(name="tidy", reads_frames=("tputrace",),
                       reads_columns=("timestamp", "duration", "deviceId"),
                       provides_features=("tpu*_op_time", "tidy_total"))
        def tidy(frames, cfg, features):
            df = frames.get("tputrace")
            for device_id, dev in df.groupby("deviceId"):
                features.add(f"tpu{device_id}_op_time",
                             float(dev["duration"].sum()))
            features.add("tidy_total", features.get("tidy_total") or 0.0)
            features.get("elapsed_time")  # ambient: driver-provided
            rows = features.by_regex(r"tpu\\d+_op_time")  # own output
    '''})
    assert fs == []


def test_sl011_flags_phantom_outputs(tmp_path):
    fs = run_pass_rules(tmp_path, {"p.py": '''
        from sofa_tpu.analysis.registry import analysis_pass

        @analysis_pass(name="phantom",
                       provides_features=("written_metric", "ghost_metric"),
                       provides_artifacts=("ghost.csv",))
        def phantom(frames, cfg, features):
            features.add("written_metric", 1.0)
    '''})
    msgs = [f.message for f in fs if f.rule_id == "SL011"]
    assert len(msgs) == 2, msgs
    assert any("'ghost_metric'" in m for m in msgs)
    assert any("'ghost.csv'" in m for m in msgs)


def test_sl011_trusts_forwarded_features(tmp_path):
    """A wrapper that hands the features object to a helper delegates its
    writes (the aisi/hsg pattern) — the declaration is trusted."""
    fs = run_pass_rules(tmp_path, {"p.py": '''
        from sofa_tpu.analysis.registry import analysis_pass

        @analysis_pass(name="wrapper",
                       provides_features=("delegated_metric",))
        def wrapper(frames, cfg, features):
            from helpers import compute
            compute(frames, cfg, features)
    '''})
    assert fs == []


def test_sl012_flags_unprovided_read_unknown_after_and_cycle(tmp_path):
    fs = run_pass_rules(tmp_path, {"p.py": '''
        from sofa_tpu.analysis.registry import analysis_pass

        @analysis_pass(name="orphan", reads_features=("nobody_makes_this",))
        def orphan(frames, cfg, features):
            features.get("nobody_makes_this")

        @analysis_pass(name="dangling", after=("no_such_pass",))
        def dangling(frames, cfg, features):
            pass

        @analysis_pass(name="loop_a", after=("loop_b",))
        def loop_a(frames, cfg, features):
            pass

        @analysis_pass(name="loop_b", after=("loop_a",))
        def loop_b(frames, cfg, features):
            pass
    '''})
    msgs = [f.message for f in fs if f.rule_id == "SL012"]
    assert any("'nobody_makes_this'" in m and "no registered pass" in m
               for m in msgs)
    assert any("'no_such_pass'" in m for m in msgs)
    assert sum("cycle" in m for m in msgs) == 2  # loop_a and loop_b


def test_sl012_sees_cross_file_providers(tmp_path):
    """A read is satisfied by a provider declared in ANOTHER module: the
    graph is validated across the whole linted tree."""
    fs = run_pass_rules(tmp_path, {
        "producer.py": '''
            from sofa_tpu.analysis.registry import analysis_pass

            @analysis_pass(name="maker", provides_features=("shared_*",))
            def maker(frames, cfg, features):
                features.add("shared_count", 1.0)
        ''',
        "consumer.py": '''
            from sofa_tpu.analysis.registry import analysis_pass

            @analysis_pass(name="taker", reads_features=("shared_count",))
            def taker(frames, cfg, features):
                features.get("shared_count")
        ''',
    })
    assert fs == []


def test_sl013_flags_direct_pass_call(tmp_path):
    fs = run_pass_rules(tmp_path, {"p.py": '''
        from sofa_tpu.analysis.registry import analysis_pass

        @analysis_pass(name="first", provides_features=("first_metric",))
        def first(frames, cfg, features):
            features.add("first_metric", 1.0)

        @analysis_pass(name="second", reads_features=("first_metric",))
        def second(frames, cfg, features):
            first(frames, cfg, features)  # composition outside the scheduler
            features.get("first_metric")
    '''})
    msgs = [f.message for f in fs if f.rule_id == "SL013"]
    assert len(msgs) == 1
    assert "'first'" in msgs[0] and "directly" in msgs[0]


def test_sl013_allows_helper_calls(tmp_path):
    fs = run_pass_rules(tmp_path, {"p.py": '''
        from sofa_tpu.analysis.registry import analysis_pass

        def shared_helper(df):
            return df

        @analysis_pass(name="caller", reads_frames=("tputrace",))
        def caller(frames, cfg, features):
            shared_helper(frames.get("tputrace"))
    '''})
    assert fs == []


def test_pass_rules_catch_seeded_mutation_of_shipped_pass(tmp_path):
    """ISSUE 8 acceptance: copy the shipped sol.py pass and sneak in an
    undeclared column read + an undeclared feature write — both must
    surface as fresh SL010 findings."""
    src = open(os.path.join(REPO, "sofa_tpu", "analysis", "sol.py")).read()
    assert 'features.add_info("sol_peak_source"' in src
    mut = src.replace('features.add_info("sol_peak_source"',
                      'features.add("sol_sneaky_metric", 1.0)\n'
                      '    features.add_info("sol_peak_source"')
    mut = mut.replace('rows.empty', 'rows["groups"].empty')
    p = tmp_path / "sol.py"
    p.write_text(mut)
    project = ProjectContext.detect([str(p)], base=str(tmp_path))
    fs = [f for f in lint_paths([str(p)], default_rules(), project=project,
                                base=str(tmp_path))
          if f.rule_id == "SL010"]
    assert any("'sol_sneaky_metric'" in f.message for f in fs)
    assert any("'groups'" in f.message for f in fs)
