"""Static chart export (`sofa export`) — reference parity for
network_report.pdf / blktrace scatter (sofa_analyze.py:531-638), rendered
from the unified-schema frames without serving HTTP."""

import os

from sofa_tpu.config import SofaConfig
from sofa_tpu.record import sofa_record


def test_export_static_renders_pdf(logdir):
    from sofa_tpu.analyze import sofa_analyze
    from sofa_tpu.export_static import export_static
    from sofa_tpu.preprocess import sofa_preprocess

    cfg = SofaConfig(logdir=logdir, enable_xprof=False, sys_mon_rate=50)
    sofa_record("sleep 1.2", cfg)  # long enough for >=2 netstat samples
    sofa_preprocess(cfg)
    sofa_analyze(cfg)
    written = export_static(cfg)
    assert cfg.path("sofa_report.pdf") in written
    assert cfg.path("overview.png") in written
    assert os.path.getsize(cfg.path("sofa_report.pdf")) > 2000
    assert os.path.getsize(cfg.path("overview.png")) > 2000
    # PDF really is multi-page (overview + host-network at minimum)
    import re

    raw = open(cfg.path("sofa_report.pdf"), "rb").read()
    assert raw.startswith(b"%PDF")
    counts = [int(m) for m in re.findall(rb"/Count (\d+)", raw)]
    assert counts and max(counts) >= 2, counts

    # `sofa clean` treats the exports as derived artifacts
    from sofa_tpu.record import sofa_clean

    sofa_clean(cfg)
    assert not os.path.exists(cfg.path("sofa_report.pdf"))
    assert not os.path.exists(cfg.path("overview.png"))


def test_export_empty_logdir_degrades(tmp_path):
    from sofa_tpu.export_static import export_static

    d = str(tmp_path / "empty") + "/"
    os.makedirs(d)
    written = export_static(SofaConfig(logdir=d))
    assert written == []
    assert not os.path.exists(os.path.join(d, "sofa_report.pdf"))
