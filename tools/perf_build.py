#!/usr/bin/env python3
"""Build `perf` from the kernel source matching the running kernel.

The reference downloads the kernel tarball over the network
(/root/reference/tools/perf_build.py:14-24); many TPU hosts are egress-less,
so this version looks for already-present sources (/usr/src, apt archives)
and degrades with actionable instructions instead of failing silently.

Usage: tools/perf_build.py [--jobs N] [--dest DIR]
"""

from __future__ import annotations

import argparse
import glob
import os
import platform
import shutil
import subprocess
import sys


def find_kernel_source() -> str | None:
    release = platform.release()
    base = release.split("-")[0]
    candidates = sorted(
        glob.glob(f"/usr/src/linux-source-{base}*")
        + glob.glob(f"/usr/src/linux-{base}*")
        + glob.glob("/usr/src/linux-source-*")
    )
    for c in candidates:
        if os.path.isdir(os.path.join(c, "tools", "perf")):
            return c
        for tarball in glob.glob(os.path.join(c, "*.tar.*")):
            out = c
            subprocess.run(["tar", "-xf", tarball, "-C", out], check=False)
            inner = glob.glob(os.path.join(out, "linux-*", "tools", "perf"))
            if inner:
                return os.path.dirname(os.path.dirname(inner[0]))
    return None


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--jobs", type=int, default=os.cpu_count() or 2)
    p.add_argument("--dest", default=os.path.dirname(os.path.abspath(__file__)))
    args = p.parse_args()

    if shutil.which("perf"):
        print(f"perf already installed at {shutil.which('perf')}; nothing to do")
        return 0
    src = find_kernel_source()
    if src is None:
        print(
            "no kernel source found.  On Debian/Ubuntu either:\n"
            "  apt install linux-tools-$(uname -r)     # prebuilt perf\n"
            "  apt install linux-source && tools/perf_build.py\n"
            "On an egress-less host, copy the kernel tarball for "
            f"{platform.release()} into /usr/src first.",
            file=sys.stderr,
        )
        return 1
    perf_dir = os.path.join(src, "tools", "perf")
    print(f"building perf from {perf_dir}")
    rc = subprocess.run(
        ["make", f"-j{args.jobs}", "NO_LIBTRACEEVENT=1"], cwd=perf_dir
    ).returncode
    if rc != 0:
        return rc
    built = os.path.join(perf_dir, "perf")
    dest = os.path.join(args.dest, "perf")
    shutil.copy2(built, dest)
    print(f"perf -> {dest}; put it on PATH to enable the perf collector")
    return 0


if __name__ == "__main__":
    sys.exit(main())
