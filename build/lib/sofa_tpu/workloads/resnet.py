"""JAX/Flax ResNet-50: the single-chip profiling target (BASELINE config #2).

The reference validated against tf_cnn_benchmarks resnet50
(/root/reference/validation/framework_eval.py:56-64); the TPU build ships its
own Flax implementation so `sofa record "python -m sofa_tpu.workloads.resnet"`
works with no external checkout.  NHWC layout and bfloat16 compute (float32
batch-norm statistics) — the conv layout and dtype the TPU convolution
lowering wants; batch is sharded over a "data" mesh axis when more than one
device is present.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class Bottleneck(nn.Module):
    filters: int
    strides: int = 1
    projection: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype,
                       param_dtype=jnp.float32)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if self.projection:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # ResNet-50
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 dtype=self.dtype,
                                 param_dtype=jnp.float32)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            filters = 64 * 2 ** i
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = Bottleneck(filters, strides, projection=(j == 0),
                               dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32)(x)


def create(batch: int, image_size: int = 224, num_classes: int = 1000,
           stage_sizes=(3, 4, 6, 3), seed: int = 0):
    """Returns (model, variables, example_batch)."""
    model = ResNet(stage_sizes=tuple(stage_sizes), num_classes=num_classes)
    key = jax.random.PRNGKey(seed)
    x = jax.random.normal(key, (batch, image_size, image_size, 3), jnp.float32)
    variables = model.init(key, x, train=False)
    return model, variables, x


def make_infer_step(model):
    @jax.jit
    def infer(variables, x):
        return model.apply(variables, x, train=False)
    return infer


def make_train_step(model, learning_rate: float = 0.1):
    import optax

    tx = optax.sgd(learning_rate, momentum=0.9)

    @jax.jit
    def step(params, batch_stats, opt_state, x, labels):
        def loss_fn(p):
            logits, updated = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            onehot = jax.nn.one_hot(labels, logits.shape[-1])
            loss = jnp.mean(jnp.sum(
                -onehot * jax.nn.log_softmax(logits), axis=-1))
            return loss, updated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    return tx, step


def main(argv=None):
    from sofa_tpu.workloads.common import (make_mesh, parse_workload_args,
                                           steps_per_sec)

    args = parse_workload_args(argv, {
        "batch": 64, "image_size": 224, "steps": 20, "train": False,
        "num_classes": 1000,
    })
    model, variables, x = create(args.batch, args.image_size,
                                 args.num_classes)
    n = len(jax.devices())
    if n > 1 and args.batch % n == 0:
        mesh = make_mesh(("data",))
        put = lambda a, s: jax.device_put(a, NamedSharding(mesh, s))
        x = put(x, P("data"))
        variables = jax.tree.map(lambda a: put(a, P()), variables)

    if args.train:
        labels = jnp.zeros((args.batch,), jnp.int32)
        tx, step = make_train_step(model)
        opt_state = tx.init(variables["params"])

        def one(state):
            p, bs, o, _ = state
            return step(p, bs, o, x, labels)

        state0 = (variables["params"], variables["batch_stats"], opt_state, 0.0)
        sps, state = steps_per_sec(one, state0, args.steps)
        print(f"resnet50 train: {sps:.3f} steps/s  "
              f"{sps * args.batch:.1f} images/s  loss={float(state[3]):.3f}")
    else:
        infer = make_infer_step(model)

        def one(state):
            return infer(variables, x)

        sps, _ = steps_per_sec(one, None, args.steps)
        print(f"resnet50 infer: {sps:.3f} steps/s  "
              f"{sps * args.batch:.1f} images/s")


if __name__ == "__main__":
    main()
