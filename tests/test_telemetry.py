"""Self-telemetry contract (sofa_tpu/telemetry.py + ISSUE 2 acceptance).

run_manifest.json must cover every collector and ingest source, survive
collector-lifecycle edge cases (start failure, kill-all epilogue, reverse
stop order), render via `sofa status` (nonzero on failed collectors),
validate against tools/manifest_check.py, and sofa_self_trace.json must be
a loadable Chrome trace that rides the perfetto export.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys

import pytest

from sofa_tpu import telemetry
from sofa_tpu.config import SofaConfig
from sofa_tpu.preprocess import sofa_preprocess
from sofa_tpu.record import build_collectors, sofa_clean, sofa_record

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_manifest_check():
    spec = importlib.util.spec_from_file_location(
        "manifest_check", os.path.join(_ROOT, "tools", "manifest_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _manifest(logdir):
    doc = telemetry.load_manifest(logdir)
    assert doc is not None, "run_manifest.json missing"
    return doc


def _assert_valid_chrome_trace(path):
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    phases = set()
    for e in events:
        assert isinstance(e.get("name"), str) and e["name"]
        assert e.get("ph") in ("X", "M", "C", "B", "E", "i")
        phases.add(e["ph"])
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    assert "X" in phases, "no span events"
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               for e in events)
    return doc


def _record(logdir, command="true", **cfg_kw):
    cfg = SofaConfig(logdir=logdir, enable_xprof=False, **cfg_kw)
    rc = sofa_record(command, cfg)
    return rc, cfg


# --- manifest coverage ------------------------------------------------------

def test_record_manifest_covers_every_collector(logdir):
    rc, cfg = _record(logdir)
    assert rc == 0
    doc = _manifest(logdir)
    assert doc["schema"] == telemetry.MANIFEST_SCHEMA
    assert doc["schema_version"] == telemetry.MANIFEST_VERSION
    expected = {c.name for c in build_collectors(cfg)}
    assert set(doc["collectors"]) == expected
    for name, ent in doc["collectors"].items():
        assert ent["status"] in telemetry.COLLECTOR_STATUSES, name
        if ent["status"] == "skipped":
            assert ent.get("reason"), name
        if ent["status"] == "stopped":
            assert isinstance(ent.get("bytes_captured"), int), name
    run = doc["runs"]["record"]
    assert run["rc"] == 0
    assert run["wall_s"] > 0
    assert run["counters"]["warnings"] >= 0
    stage_names = {s["name"] for s in doc["stages"]
                   if s["verb"] == "record"}
    assert {"prologue", "launch", "epilogue"} <= stage_names
    # recorder-side collectors that actually ran captured real bytes
    assert doc["collectors"]["timebase"]["bytes_captured"] > 0
    assert doc["env"]["sofa_tpu_version"]
    assert doc["config"]["logdir"] == cfg.logdir


def test_manifest_and_self_trace_are_derived_files(logdir):
    _record(logdir)
    assert os.path.isfile(os.path.join(logdir, telemetry.MANIFEST_NAME))
    assert os.path.isfile(os.path.join(logdir, telemetry.SELF_TRACE_NAME))
    cfg = SofaConfig(logdir=logdir)
    sofa_clean(cfg)
    assert not os.path.exists(os.path.join(logdir, telemetry.MANIFEST_NAME))
    assert not os.path.exists(
        os.path.join(logdir, telemetry.SELF_TRACE_NAME))


# --- collector lifecycle edge cases ----------------------------------------

def test_collector_start_failure_is_degradation_not_abort(logdir,
                                                          monkeypatch):
    """One collector failing to start costs its series, never the
    recording — and the manifest records the failed outcome."""
    from sofa_tpu.collectors.procmon import ProcMonCollector

    def boom(self):
        raise RuntimeError("synthetic start failure")

    monkeypatch.setattr(ProcMonCollector, "start", boom)
    rc, _cfg = _record(logdir)
    assert rc == 0  # the profiled command still ran
    ent = _manifest(logdir)["collectors"]["procmon"]
    assert ent["status"] == "failed"
    assert ent["phase"] == "start"
    assert "synthetic start failure" in ent["error"]
    # the OTHER collectors were unaffected
    assert _manifest(logdir)["collectors"]["timebase"]["status"] == "stopped"


def test_collectors_stop_in_reverse_start_order(logdir):
    _record(logdir)
    cols = _manifest(logdir)["collectors"]
    started = [(name, ent) for name, ent in cols.items()
               if "start_seq" in ent and "stop_seq" in ent]
    assert len(started) >= 3  # timebase + procmon + xprof at minimum
    by_start = sorted(started, key=lambda kv: kv[1]["start_seq"])
    stop_seqs = [ent["stop_seq"] for _n, ent in by_start]
    assert stop_seqs == sorted(stop_seqs, reverse=True), (
        "epilogue must stop collectors in reverse start order")


def test_kill_all_on_error_epilogue_recorded(logdir, monkeypatch):
    """A mid-record failure kills every started collector; the manifest
    keeps the killed status even though the epilogue's stop/flush still
    runs afterwards (failed/killed are sticky)."""
    import sofa_tpu.record as record_mod

    def explode(child, cfg):
        raise RuntimeError("synthetic launch failure")

    monkeypatch.setattr(record_mod, "_wait_epilogue_bounded", explode)
    with pytest.raises(RuntimeError, match="synthetic launch"):
        _record(logdir)
    doc = _manifest(logdir)  # written on the error path too
    killed = [n for n, ent in doc["collectors"].items()
              if ent["status"] == "killed"]
    assert "timebase" in killed and "procmon" in killed
    # the epilogue still ran (stop_seq present) without whitewashing
    assert "stop_seq" in doc["collectors"]["timebase"]
    assert doc["runs"]["record"]["counters"]["errors"] >= 1


# --- sofa status ------------------------------------------------------------

def test_status_cli_healthy_and_failed(logdir, monkeypatch, capsys):
    from sofa_tpu.cli import main

    rc, _cfg = _record(logdir)
    assert main(["status", logdir]) == 0
    out = capsys.readouterr()
    text = out.out + out.err
    assert "COLLECTOR" in text and "timebase" in text

    # injected collector failure -> nonzero exit
    from sofa_tpu.collectors.procmon import ProcMonCollector

    def boom(self):
        raise RuntimeError("injected failure")

    monkeypatch.setattr(ProcMonCollector, "start", boom)
    _record(logdir)
    assert main(["status", "--logdir", logdir]) == 1
    text = "".join(capsys.readouterr())
    assert "failed" in text

    # no manifest at all
    assert main(["status", str(logdir) + "_nope/"]) == 2


# --- preprocess sources -----------------------------------------------------

def _small_logdir(tmp_path, name="plog"):
    d = str(tmp_path / name) + "/"
    os.makedirs(d)
    with open(d + "mpstat.txt", "w") as f:
        f.write("1700000000.0 cpu0 100 0 50 800 10 5 5 0\n"
                "1700000000.5 cpu0 140 0 60 830 12 6 6 0\n")
    with open(d + "sofa_time.txt", "w") as f:
        f.write("1700000000.0\n")
    return d


def test_preprocess_manifest_covers_every_source(tmp_path):
    d = _small_logdir(tmp_path)
    cfg = SofaConfig(logdir=d)
    sofa_preprocess(cfg)
    doc = _manifest(d)
    from sofa_tpu.ingest.cache import PARSER_VERSIONS

    assert set(doc["sources"]) == set(PARSER_VERSIONS)
    for name, ent in doc["sources"].items():
        assert ent["status"] in telemetry.SOURCE_STATUSES, name
        assert ent["cache"] in telemetry.CACHE_OUTCOMES, name
        assert ent["wall_s"] >= 0 and ent["events"] >= 0, name
    assert doc["sources"]["mpstat"]["status"] == "parsed"
    assert doc["sources"]["mpstat"]["events"] > 0
    assert doc["meta"]["pool"]["jobs"] >= 1
    # warm re-run flips mpstat to a recorded cache hit
    sofa_preprocess(cfg)
    doc2 = _manifest(d)
    assert doc2["sources"]["mpstat"]["cache"] == "hit"
    assert doc2["sources"]["mpstat"]["status"] == "cached"
    assert doc2["meta"]["ingest_cache"]["hits"].count("mpstat") == 1


def test_preprocess_degraded_source_recorded(tmp_path, monkeypatch):
    from sofa_tpu.ingest import procfs

    def boom(text, time_base=0.0, **kw):
        raise ValueError("synthetic parse failure")

    monkeypatch.setattr(procfs, "parse_mpstat", boom)
    d = _small_logdir(tmp_path)
    sofa_preprocess(SofaConfig(logdir=d, ingest_cache=False))
    ent = _manifest(d)["sources"]["mpstat"]
    assert ent["status"] == "degraded"
    assert "synthetic parse failure" in ent["error"]


def test_analyze_folds_manifest_warnings_into_hints(tmp_path, monkeypatch):
    from sofa_tpu.analyze import sofa_analyze
    from sofa_tpu.ingest import procfs

    def boom(text, time_base=0.0, **kw):
        raise ValueError("synthetic parse failure")

    monkeypatch.setattr(procfs, "parse_mpstat", boom)
    d = _small_logdir(tmp_path)
    cfg = SofaConfig(logdir=d, ingest_cache=False)
    sofa_analyze(cfg, frames=sofa_preprocess(cfg))
    hints = open(os.path.join(d, "hints.txt")).read()
    assert "[self]" in hints
    assert "mpstat" in hints
    # analyze's own run landed in the manifest too
    assert "analyze" in _manifest(d)["runs"]
    assert any(s["verb"] == "analyze" and s["cat"] == "analyze"
               for s in _manifest(d)["stages"])


# --- self-trace + export ----------------------------------------------------

def test_self_trace_is_valid_chrome_trace(logdir):
    _record(logdir)
    cfg = SofaConfig(logdir=logdir)
    sofa_preprocess(cfg)
    doc = _assert_valid_chrome_trace(
        os.path.join(logdir, telemetry.SELF_TRACE_NAME))
    verbs = {(e.get("args") or {}).get("verb")
             for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"record", "preprocess"} <= verbs
    # anchored to the capture's own time zero
    tb = float(open(cfg.path("sofa_time.txt")).read().split()[0])
    assert doc["otherData"]["ts_zero_unix"] == pytest.approx(tb)


def test_perfetto_export_includes_self_trace(logdir):
    import gzip

    from sofa_tpu.export_perfetto import _SELF_PID, export_perfetto

    _record(logdir, sys_mon_rate=50, command="sleep 0.2")
    cfg = SofaConfig(logdir=logdir)
    sofa_preprocess(cfg)
    path = export_perfetto(cfg)
    assert path is not None
    with gzip.open(path, "rt") as f:
        doc = json.load(f)
    self_events = [e for e in doc["traceEvents"]
                   if e.get("pid") == _SELF_PID]
    assert any(e.get("name") == "prologue" for e in self_events)
    assert any(e.get("ph") == "M" for e in self_events)


# --- manifest_check tool ----------------------------------------------------

def test_manifest_check_validates_and_rejects(logdir, tmp_path):
    mc = _load_manifest_check()
    _record(logdir)
    sofa_preprocess(SofaConfig(logdir=logdir))
    assert mc.check_path(logdir) == 0
    doc = _manifest(logdir)
    assert mc.validate_manifest(doc) == []

    # corruption is caught
    bad = json.loads(json.dumps(doc))
    bad["schema_version"] = 99
    bad["collectors"]["timebase"]["status"] = "exploded"
    del bad["runs"]["record"]["wall_s"]
    probs = mc.validate_manifest(bad)
    assert len(probs) >= 3
    assert any("schema_version" in p for p in probs)
    assert any("exploded" in p for p in probs)

    # --require-healthy flags failed collectors
    sick = json.loads(json.dumps(doc))
    sick["collectors"]["timebase"]["status"] = "failed"
    assert mc.validate_manifest(sick) == []
    assert any("unhealthy" in p
               for p in mc.validate_manifest(sick, require_healthy=True))

    # missing path exit code
    assert mc.check_path(str(tmp_path / "nothing")) == 2


# --- printing satellites ----------------------------------------------------

def test_log_level_env_filters_display_not_counters(monkeypatch, capsys):
    from sofa_tpu.printing import print_warning

    monkeypatch.setenv("SOFA_LOG_LEVEL", "error")
    tel = telemetry.begin("record")
    try:
        print_warning("suppressed but counted")
    finally:
        telemetry.end(tel)
    out = capsys.readouterr()
    assert "suppressed but counted" not in out.out + out.err
    assert tel.counters["warnings"] == 1
    assert "suppressed but counted" in tel.warning_tail[0]

    monkeypatch.setenv("SOFA_LOG_LEVEL", "warn")
    print_warning("now visible")
    assert "now visible" in capsys.readouterr().err


def test_log_level_debug_shows_info_without_verbose(monkeypatch, capsys):
    from sofa_tpu import printing

    monkeypatch.setattr(printing, "verbose", False)
    monkeypatch.delenv("SOFA_LOG_LEVEL", raising=False)
    printing.print_info("hidden by default")
    assert "hidden by default" not in capsys.readouterr().out
    monkeypatch.setenv("SOFA_LOG_LEVEL", "debug")
    printing.print_info("debug shows me")
    assert "debug shows me" in capsys.readouterr().out


def test_log_timestamps_env(monkeypatch, capsys):
    import re

    monkeypatch.setenv("SOFA_LOG_TIMESTAMPS", "1")
    from sofa_tpu.printing import print_progress

    print_progress("stamped")
    out = capsys.readouterr().out
    assert re.search(r"\d{2}:\d{2}:\d{2}\.\d{3} \[PROGRESS\] stamped", out)


# --- acceptance e2e: pod_synth --raw harness --------------------------------

def test_e2e_pod_synth_raw_manifest(tmp_path):
    """ISSUE 2 acceptance: `sofa record` + `sofa preprocess` over the
    pod_synth --raw collector files leaves a schema-valid manifest
    covering every collector and ingest source, `sofa status` renders it
    with exit 0, and the self-trace loads as a valid Chrome trace."""
    logdir = str(tmp_path / "podlog") + "/"
    rc, cfg = _record(logdir, command="sleep 0.2", sys_mon_rate=50)
    assert rc == 0
    synth = str(tmp_path / "synth") + "/"
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "pod_synth.py"),
         synth, "--raw"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr
    # overlay the raw collector harness files; keep record's clock files
    for name in ("perf.script", "strace.txt", "pystacks.txt", "mpstat.txt",
                 "cpuinfo.txt", "netstat.txt", "vmstat.txt", "tpumon.txt",
                 "misc.txt"):
        shutil.copy(synth + name, logdir + name)
    sofa_preprocess(cfg)

    mc = _load_manifest_check()
    assert mc.check_path(logdir, require_healthy=True) == 0
    doc = _manifest(logdir)
    from sofa_tpu.ingest.cache import PARSER_VERSIONS

    assert set(doc["collectors"]) == {c.name for c in build_collectors(cfg)}
    assert set(doc["sources"]) == set(PARSER_VERSIONS)
    # the big text parsers really parsed (not empty-degraded)
    for src in ("cputrace", "strace", "pystacks", "mpstat", "tpumon"):
        assert doc["sources"][src]["status"] == "parsed", src
        assert doc["sources"][src]["events"] > 0, src

    from sofa_tpu.cli import main

    assert main(["status", logdir]) == 0
    _assert_valid_chrome_trace(
        os.path.join(logdir, telemetry.SELF_TRACE_NAME))
