"""Declared-guard concurrency primitives.

The repo already carries real concurrency — the supervisor watchdog,
collector sampler threads, pool workers, ThreadingHTTPServer handlers —
and every frontier on the ROADMAP (`sofa live` tail-ingest, the `sofa
agent` fleet daemon, the out-of-core columnar engine) adds more.  Until
now each lock was an anonymous ``threading.Lock`` whose protected state
lived only in the author's head; nothing could check that a new write
site took the right lock, or any lock at all.

:class:`Guard` is a named lock that *declares* the state it protects::

    _REGISTRY_GUARD = Guard("telemetry.registry", protects=("_active",))
    ...
    with _REGISTRY_GUARD:
        _active.append(tel)

The declaration is machine-checked two ways:

* **statically** — sofa-lint rule SL019 (sofa_tpu/lint/concurrency_rules)
  extracts every ``Guard(...)`` declaration and verifies that each write
  to a protected name happens inside a ``with <that guard>:`` block, and
  that state written from two execution contexts has a declared guard at
  all;
* **at runtime (debug mode)** — with ``SOFA_DEBUG_GUARDS=1`` in the
  environment, :meth:`Guard.assert_held` raises when called off the
  owning thread, so a race a reviewer missed fails a test instead of
  corrupting a manifest.  Outside debug mode the assert is a no-op
  attribute check — guards add no measurable cost to the hot path.

Guards are reentrant by default (the converted call sites — telemetry's
merge-by-verb ledgers — re-enter through helper methods) and expose the
context-manager protocol plus ``acquire``/``release`` for the rare
non-lexical holder.
"""

from __future__ import annotations

import os
import random
import threading

__all__ = ["Guard", "debug_guards_enabled", "jittered_backoff"]


def jittered_backoff(attempt: int, base_s: float = 0.5,
                     cap_s: float = 30.0, rng=random) -> float:
    """Capped exponential backoff with jitter: the fleet-wide retry
    policy (supervisor collector restarts, `sofa agent` push retries).

    ``base_s * 2^attempt`` capped at ``cap_s``, then scaled by a random
    factor in [0.5, 1.0] — a fleet of agents (or a host's worth of
    collectors) that failed in lockstep must NOT retry in lockstep: the
    synchronized retry wave is the thundering herd that keeps a barely
    recovered service down.  The return value is always in
    ``[min(base_s, cap_s) * 0.5, cap_s]``; pass a seeded ``rng`` for
    deterministic tests."""
    raw = min(base_s * (2 ** max(int(attempt), 0)), cap_s)
    return raw * (0.5 + 0.5 * rng.random())


def debug_guards_enabled() -> bool:
    """Read the debug switch at call time (not import time) so tests can
    flip SOFA_DEBUG_GUARDS without re-importing the module."""
    return os.environ.get("SOFA_DEBUG_GUARDS", "") == "1"


class Guard:
    """A named lock that declares the state it protects.

    ``protects`` names the attributes / module globals whose every write
    must happen under this guard — the contract SL019 enforces statically.
    The names are data for the linter and the debug assert's error
    message; the guard itself is an ordinary (re-entrant) lock.
    """

    __slots__ = ("name", "protects", "_lock", "_owner", "_depth")

    def __init__(self, name: str, protects=(), reentrant: bool = True):
        if not name or not isinstance(name, str):
            raise ValueError(f"Guard needs a non-empty name, got {name!r}")
        self.name = name
        self.protects = tuple(protects)
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self._owner: "int | None" = None
        self._depth = 0

    # -- lock protocol -----------------------------------------------------
    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            self._depth += 1
        return got

    def release(self) -> None:
        self._depth -= 1
        if self._depth <= 0:
            self._owner = None
            self._depth = 0
        self._lock.release()

    def __enter__(self) -> "Guard":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    # -- introspection / debug asserts ------------------------------------
    def held(self) -> bool:
        """True when the CALLING thread currently holds the guard."""
        return self._owner == threading.get_ident()

    def assert_held(self) -> None:
        """Debug-mode invariant: the caller must hold the guard.

        Cheap by contract — a single env-flag check when debug guards are
        off.  Writers of guard-protected state call this at the top of
        the mutation so an unguarded access found in review (or seeded by
        the race-marked tests) fails loudly instead of racing."""
        if not debug_guards_enabled():
            return
        if not self.held():
            raise AssertionError(
                f"guard {self.name!r} (protects {list(self.protects)}) is "
                "not held by this thread — an unguarded access to declared "
                "shared state")

    def __repr__(self) -> str:
        state = "held" if self._owner is not None else "free"
        return (f"Guard({self.name!r}, protects={list(self.protects)}, "
                f"{state})")
