#!/usr/bin/env python3
"""One-command real-chip validation of everything CPU tests cannot prove.

Run whenever the TPU tunnel is healthy (it died for 9+ hours mid round-2,
so these were last verified on the pre-streaming kernel):

  1. streaming flash kernel compiles under Mosaic (fwd + custom-VJP bwd)
  2. numerics vs plain attention on-chip
  3. long-context: T=16384 forward (the old full-KV kernel OOM'd VMEM here)
  4. fwd/bwd timing vs the unfused path (expect ~10-30 % wins)
  5. entry() compile check with the fused path active
  6. profiled train loop end-to-end: device Steps spans, fw/bw phase
     attribution, op_path provenance, live tpumon HBM series
  7. optionally captures a real device-plane XPlane fixture
     (--capture-fixture) trimmed into tests/fixtures/

Exits non-zero on any failure; prints one PASS/FAIL line per check.
"""

from __future__ import annotations

import argparse
import sys
import time

RESULTS = []


def check(name):
    def deco(fn):
        def run(*a, **kw):
            t0 = time.time()
            try:
                detail = fn(*a, **kw) or ""
                RESULTS.append((name, True, detail))
                print(f"PASS {name} ({time.time() - t0:.1f}s) {detail}")
            except Exception as e:  # noqa: BLE001
                RESULTS.append((name, False, repr(e)))
                print(f"FAIL {name}: {e!r}")
        return run
    return deco


@check("kernel_compiles")
def kernel_compiles():
    import jax
    import jax.numpy as jnp

    from sofa_tpu.workloads.flash_pallas import flash_attention

    z = jnp.zeros((4, 2048, 8, 128), jnp.bfloat16)
    flash_attention.lower(z, z, z).compile()


@check("numerics_on_chip")
def numerics_on_chip():
    import jax
    import jax.numpy as jnp

    from sofa_tpu.workloads.flash_pallas import (
        flash_attention, flash_causal_attention)
    from sofa_tpu.workloads.ring_attention import plain_causal_attention

    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(kk, (2, 512, 4, 64), jnp.float32)
               for kk in jax.random.split(key, 3))
    with jax.default_matmul_precision("highest"):
        err = float(jnp.abs(flash_attention(q, k, v)
                            - plain_causal_attention(q, k, v)).max())
        gf = jax.grad(lambda *a: (flash_causal_attention(*a) ** 2).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        gp = jax.grad(lambda *a: (plain_causal_attention(*a) ** 2).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        gerr = max(float(jnp.abs(a - b).max()) for a, b in zip(gf, gp))
    assert err < 1e-4 and gerr < 1e-2, (err, gerr)
    return f"fwd_err={err:.2e} grad_err={gerr:.2e}"


@check("long_context_16k")
def long_context_16k():
    import jax
    import jax.numpy as jnp

    from sofa_tpu.workloads.flash_pallas import flash_causal_attention

    from sofa_tpu.workloads.common import fence

    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(kk, (1, 16384, 8, 128), jnp.bfloat16)
               for kk in jax.random.split(key, 3))
    f = jax.jit(lambda q, k, v: flash_causal_attention(q, k, v))
    fence(f(q, k, v))   # compile + settle (block_until_ready lies on axon)
    t0 = time.perf_counter()
    for _ in range(3):
        o = f(q, k, v)
    fence(o)
    ms = (time.perf_counter() - t0) / 3 * 1e3
    tf = (1 * 8 * 16384 * 16384 * 128 * 2 * 2 / 2) / (ms / 1e3) / 1e12
    return f"{ms:.1f} ms/fwd, {tf:.2f} TFLOP/s"


@check("fwd_bwd_vs_unfused")
def fwd_bwd_vs_unfused():
    import jax
    import jax.numpy as jnp

    from sofa_tpu.workloads.flash_pallas import flash_causal_attention
    from sofa_tpu.workloads.ring_attention import plain_causal_attention

    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(kk, (4, 2048, 8, 128), jnp.bfloat16)
               for kk in jax.random.split(key, 3))

    from sofa_tpu.workloads.common import fence

    def bench(f, n=20):
        fence(f(q, k, v))   # block_until_ready lies on axon; fence pulls
        t0 = time.perf_counter()
        for _ in range(n):
            o = f(q, k, v)
        fence(o)
        return (time.perf_counter() - t0) / n * 1e3

    gf = jax.jit(jax.grad(lambda *a: (flash_causal_attention(*a).astype(
        jnp.float32) ** 2).sum(), argnums=(0, 1, 2)))
    gp = jax.jit(jax.grad(lambda *a: (plain_causal_attention(*a).astype(
        jnp.float32) ** 2).sum(), argnums=(0, 1, 2)))
    tf, tp = bench(gf), bench(gp)
    return f"flash {tf:.2f} ms vs plain {tp:.2f} ms ({tp / tf - 1:+.0%})"


@check("kernel_perf_floor")
def kernel_perf_floor():
    """Regenerate docs/KERNEL_PERF.md (TFLOP/s + %-of-peak sweep) in this
    window and assert the 16k forward clears the floor — a tool-owned MFU
    trail instead of absolutes buried in prose."""
    import json
    import os
    import subprocess
    import tempfile

    import shutil

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tmpd = tempfile.mkdtemp(prefix="sofa_kperf_")
    out_json = os.path.join(tmpd, "kperf.json")
    # fast mode (bench's unattended-window hook): fewer reps + tighter
    # timeout so the checklist cannot eat the driver's whole bench window
    fast = os.environ.get("SOFA_VALIDATE_FAST") == "1"
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "kernel_perf.py"),
             "--json", out_json, "--reps", "3" if fast else "5"],
            capture_output=True, text=True, timeout=420 if fast else 1200,
            cwd=repo)
        assert r.returncode == 0, r.stderr[-400:]
        with open(out_json) as f:
            doc = json.load(f)
    finally:
        shutil.rmtree(tmpd, ignore_errors=True)
    f16 = next(row for row in doc["rows"]
               if row["kernel"] == "flash fwd" and row["T"] == 16384
               and not row["gqa"])
    # conservative: absolutes swing ~2x with tunnel load between windows
    floor = 4.0
    assert f16["tflops"] >= floor, \
        f"16k fwd {f16['tflops']:.2f} TFLOP/s under the {floor} floor"
    peak = doc.get("peak_tflops")
    mfu = f", {100 * f16['tflops'] / peak:.1f}% of peak" if peak else ""
    return f"16k fwd {f16['tflops']:.2f} TFLOP/s{mfu}; KERNEL_PERF.md written"


@check("segmented_kernels_on_chip")
def segmented_kernels_on_chip():
    """Packed-sequence (segment-id) masking compiles under Mosaic and
    matches the explicitly-masked reference on-chip, fwd and bwd — the
    CPU suite only proves the interpreter path."""
    import jax
    import jax.numpy as jnp

    from sofa_tpu.workloads.flash_pallas import (
        flash_causal_segmented_attention,
    )
    from sofa_tpu.workloads.ring_attention import (
        plain_segmented_causal_attention,
    )

    key = jax.random.PRNGKey(0)
    b, t, h, d = 2, 512, 4, 64
    q, k, v = (jax.random.normal(kk, (b, t, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))
    seg = jnp.concatenate([jnp.zeros((b, 200), jnp.int32),
                           jnp.ones((b, 312), jnp.int32)], axis=1)

    with jax.default_matmul_precision("highest"):
        err = float(jnp.abs(
            flash_causal_segmented_attention(q, k, v, seg)
            - plain_segmented_causal_attention(q, k, v, seg)).max())
        gf = jax.grad(lambda *a: (flash_causal_segmented_attention(
            *a, seg) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        gp = jax.grad(lambda *a: (plain_segmented_causal_attention(
            *a, seg) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        gerr = max(float(jnp.abs(a - b).max()) for a, b in zip(gf, gp))
    assert err < 1e-4 and gerr < 1e-2, (err, gerr)
    return f"fwd_err={err:.2e} grad_err={gerr:.2e}"


@check("entry_compiles_fused")
def entry_compiles_fused():
    import jax

    from __graft_entry__ import entry

    fn, args = entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    return f"out {out.shape}"


@check("trace_pipeline_train")
def trace_pipeline_train():
    """One profiled train loop must yield: device Steps spans, fw/bw phase
    attribution, op_path provenance, and a live tpumon HBM series —
    everything round 2 added on top of the raw op trace."""
    import shutil
    import tempfile
    import time

    import jax

    import sofa_tpu.api as sofa
    from sofa_tpu.config import SofaConfig
    from sofa_tpu.ingest.tpumon_parse import ingest_tpumon
    from sofa_tpu.ingest.xplane import ingest_xprof_dir
    from sofa_tpu.workloads.common import step_annotation
    from sofa_tpu.workloads.transformer import TransformerConfig, build

    cfg = TransformerConfig.tiny(seq=128)
    params, opt, step, tokens = build(cfg, None, batch=4, seq=128)
    params, opt, loss = step(params, opt, tokens)
    jax.block_until_ready(loss)

    logdir = tempfile.mkdtemp(prefix="sofa_val_train_") + "/"
    try:
        # profile() runs the built-in tpumon sampler; 20 Hz so even this
        # sub-second loop collects several HBM samples.
        with sofa.profile(logdir, cfg=SofaConfig(logdir=logdir,
                                                 tpu_mon_rate=20)):
            for i in range(5):
                with step_annotation(i):
                    params, opt, loss = step(params, opt, tokens)
            jax.block_until_ready(loss)
        frames = ingest_xprof_dir(logdir + "xprof/", time.time())
        assert frames, "no xplane files captured (profiler failed to flush?)"
        ops = frames["tputrace"]
        sync = ops[ops["category"] == 0]
        # This libtpu emits device Steps spans for annotated loops (verified
        # on the real chip 2026-07-30); their absence is a regression.
        assert len(frames["tpusteps"]) >= 5, "no device Steps spans"
        fw = (sync["phase"] == "fw").sum()
        bw = (sync["phase"] == "bw").sum()
        assert fw > 0 and bw > 0, f"phase split missing (fw={fw} bw={bw})"
        assert (sync["op_path"] != "").mean() > 0.3, "op_path mostly empty"
        mon = ingest_tpumon(logdir, time.time() - 30)
        assert (mon["name"] == "hbm_used_gb").any(), "no live HBM series"
        return (f"steps={len(frames['tpusteps'])} fw={fw} bw={bw} "
                f"hbm_pts={(mon['name'] == 'hbm_used_gb').sum()}")
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


@check("memprof_on_chip")
def memprof_on_chip():
    """HBM attribution on the real allocator: a profiled loop that holds a
    ~256MB buffer must leave a parseable memprof snapshot whose buffer
    samples carry real TPU device labels and cover the held bytes.  (CPU
    runs only prove the mechanics; memory_stats + peak-trigger semantics
    exist on the TPU runtime alone.)"""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    import sofa_tpu.api as sofa
    from sofa_tpu.config import SofaConfig
    from sofa_tpu.ingest.memprof import aggregate_sites, load_memprof

    logdir = tempfile.mkdtemp(prefix="sofa_val_mem_") + "/"
    try:
        with sofa.profile(logdir, cfg=SofaConfig(logdir=logdir,
                                                 tpu_mon_rate=20)):
            import time as _time

            big = jnp.ones((8192, 8192), jnp.float32)       # 256 MB
            out = jax.jit(lambda x: (x @ x).sum())(big)
            jax.block_until_ready(out)
            # Hold the buffer past the sampler's 2s snapshot rate limit: an
            # early first-tick snapshot (backend warm from prior checks)
            # would otherwise rate-limit the tick that sees the 256MB and
            # its presence suppresses the final-at-exit fallback.
            _time.sleep(2.5)
        df, meta = load_memprof(logdir)
        assert df is not None and not df.empty, "no memprof snapshot"
        buf = df[df["kind"] == "buffer"]
        held = int(buf["bytes"].sum())
        assert held >= 256 << 20, f"buffer bytes {held} < the held 256MB"
        devs = set(buf.loc[buf["device"] != "", "device"])
        assert any("TPU" in d.upper() for d in devs), f"no TPU labels: {devs}"
        top = aggregate_sites(buf).iloc[0]
        return (f"trigger={meta.get('trigger')} held={held / 2**20:.0f}MB "
                f"devices={len(devs)} top={top['site'][:40]}")
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


@check("clock_residual")
def clock_residual():
    """Within-capture marker-bridge consistency.

    The in-trace marker is the PRIMARY clock bridge (ingest aligns device
    time with the earliest one; the native timebase table and
    --tpu_time_offset_ms are the fallback).  api.profile emits a marker at
    trace start AND stop; alignment is correct iff both yield the same
    unix-minus-session offset — the session clock runs at wall rate over
    the capture.  Cross-capture offsets are NOT comparable (each axon
    session has its own origin; observed 2026-07-31: ~997 s apparent skew
    vs the local clock table and ~2.5 s movement between captures — both
    irrelevant to a bridge that re-anchors per capture).  The residual vs
    the local posix-clock table is reported for operator context only."""
    import glob
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    import sofa_tpu.api as sofa
    from sofa_tpu.ingest.timebase_align import load_timebase
    from sofa_tpu.ingest.xplane import find_marker_offsets_ns, load_xspace

    logdir = tempfile.mkdtemp(prefix="sofa_val_clk_") + "/"
    try:
        f = jax.jit(lambda v: v @ v)
        x = jnp.ones((256, 256))
        jax.block_until_ready(f(x))
        with sofa.profile(logdir):
            jax.block_until_ready(f(x))
            time.sleep(2.0)
            jax.block_until_ready(f(x))
        pbs = glob.glob(logdir + "xprof/**/*.xplane.pb", recursive=True)
        assert pbs, "no capture"
        offs = find_marker_offsets_ns(load_xspace(pbs[0]))
        assert len(offs) >= 2, f"expected start+stop markers, got {len(offs)}"
        span_s = (offs[-1][0] - offs[0][0]) / 1e9
        drift = abs(offs[-1][1] - offs[0][1])
        assert span_s > 1.0, f"markers only {span_s:.3f}s apart"
        assert drift < 5e6, (f"marker offsets disagree by {drift / 1e6:.3f} "
                             f"ms across a {span_s:.1f}s capture — session "
                             "clock rate or marker stamping is broken")
        table = load_timebase(logdir + "timebase.txt")
        assert table is not None, "timebase.txt missing"
        res = min(abs(offs[0][1]
                      - float((table[:, 0] - table[:, c]).mean()))
                  for c in (1, 2, 3))
        note = (f"local-clock residual {res / 1e6:.3f} ms"
                if res < 1e6 else
                f"remote session origin {res / 1e9:.3f} s from local "
                "clocks (tunneled device; re-anchored per capture)")
        return (f"start/stop offsets agree to {drift / 1e6:.3f} ms over "
                f"{span_s:.1f}s; {note}")
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


@check("overhead_budget")
def overhead_budget():
    """Measure the per-collector overhead table on the real chip and land
    it in docs/OVERHEAD_BUDGET.md (VERDICT r2 next #8: the knobs existed,
    the numbers did not)."""
    import os
    import sys

    here = os.path.dirname(os.path.abspath(__file__))
    if here not in sys.path:
        sys.path.insert(0, here)
    import overhead_budget as mod

    out = os.path.join(os.path.dirname(here), "docs", "OVERHEAD_BUDGET.md")
    # 100-step loops: 50-step runs sit inside the tunnel's RPC jitter and
    # the table printed negative "overheads" (r4, first capture attempts).
    # >=20 interleaved pairs per row, adaptive until the 95% CI of the
    # median marginal resolves ±2% (r4 weak#2: ±26% floor, every row
    # "within noise" — the per-collector budget was unmeasured).  Fast
    # mode (bench's unattended hook) halves the pairs so the whole
    # checklist fits the driver's bench window; rows then say UNRESOLVED
    # honestly and a manual full run upgrades them.
    fast = os.environ.get("SOFA_VALIDATE_FAST") == "1"
    mod.run_budget(steps=100, reps=10 if fast else 20,
                   max_reps=14 if fast else 28, out=out)
    return out


@check("capture_fixture")
def capture_fixture():
    """Capture tests/fixtures/tpu_device.xplane.pb from the real chip.

    v2 capture: ONE trace holding both the 1024^3 bf16 matmul (keeps the
    flops/bytes metadata-stats assertions meaningful) and a 5-step
    StepTraceAnnotation'd tiny-transformer train loop, so the fixture has a
    real device "Steps" line and fw/bw provenance — the round-2 fixture had
    neither, leaving the Steps-span and CUSTOM-plane ingest validated only
    by self-made protos.  A sidecar .meta.json records what the capture
    contains so fixture tests can gate their assertions on it.
    """
    import glob
    import json
    import os
    import shutil
    import tempfile
    import time as _time

    import jax
    import jax.numpy as jnp

    import sofa_tpu.api as sofa
    from sofa_tpu.ingest.xplane import ingest_xprof_dir
    from sofa_tpu.workloads.common import step_annotation
    from sofa_tpu.workloads.transformer import TransformerConfig, build

    cfg = TransformerConfig.tiny(seq=128)
    params, opt, step, tokens = build(cfg, None, batch=4, seq=128)
    params, opt, loss = step(params, opt, tokens)   # compile outside trace
    x = jax.random.normal(jax.random.PRNGKey(0), (1024, 1024), jnp.bfloat16)
    mm = jax.jit(lambda x: (x @ x).sum())
    jax.block_until_ready((mm(x), loss))

    logdir = tempfile.mkdtemp(prefix="sofa_val_") + "/"
    try:
        with sofa.profile(logdir):
            y = mm(x)
            for i in range(5):
                with step_annotation(i):
                    params, opt, loss = step(params, opt, tokens)
            jax.block_until_ready((y, loss))
        pbs = glob.glob(os.path.join(logdir, "xprof", "**", "*.xplane.pb"),
                        recursive=True)
        assert pbs, "no xplane.pb captured"
        size = os.path.getsize(pbs[0])
        # Validate size BEFORE replacing the committed fixture; this trace
        # should be well under 8 MB.
        assert size < 8_000_000, f"capture too large ({size} B), trim first"
        # Ingest the candidate BEFORE replacing the committed fixture — a
        # capture that lost the Steps line or the matmul must not demote
        # the fixture.
        frames = ingest_xprof_dir(os.path.join(logdir, "xprof"), _time.time())
        n_steps = len(frames["tpusteps"])
        assert n_steps >= 5, f"capture has {n_steps} Steps spans, need >= 5"
        assert frames["tputrace"]["flops"].max() > 1e9, "matmul flops lost"
        fixdir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tests", "fixtures")
        dest = os.path.join(fixdir, "tpu_device.xplane.pb")
        shutil.copy(pbs[0], dest)
        meta = {
            "version": 2,
            "captured_utc": _time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           _time.gmtime()),
            "steps_spans": int(n_steps),
            "has_fw_bw": bool((frames["tputrace"]["phase"] == "bw").any()),
            "custom_planes": sorted(
                frames["customtrace"]["module"].unique().tolist())
            if len(frames.get("customtrace", [])) else [],
        }
        with open(os.path.join(fixdir, "tpu_device.xplane.meta.json"),
                  "w") as f:
            json.dump(meta, f, indent=1)
        return f"{dest} ({size // 1024} KiB, steps={n_steps})"
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--capture-fixture", action="store_true",
                   help="also capture tests/fixtures/tpu_device.xplane.pb")
    args = p.parse_args()

    import os

    import jax

    # Env-over-config: the image's sitecustomize force-prepends the TPU
    # platform; honor an explicit JAX_PLATFORMS (e.g. cpu smoke of this
    # script) so a dead tunnel can't hang us before the backend check.
    env_platforms = os.environ.get("JAX_PLATFORMS", "")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)

    print(f"backend: {jax.default_backend()} devices: {jax.devices()}")
    if jax.default_backend() != "tpu":
        print("FAIL not running on a TPU backend")
        return 1

    kernel_compiles()
    numerics_on_chip()
    long_context_16k()
    kernel_perf_floor()
    fwd_bwd_vs_unfused()
    segmented_kernels_on_chip()
    entry_compiles_fused()
    trace_pipeline_train()
    memprof_on_chip()
    clock_residual()
    overhead_budget()
    if args.capture_fixture:
        capture_fixture()

    failed = [n for n, ok, _ in RESULTS if not ok]
    print(f"\n{len(RESULTS) - len(failed)}/{len(RESULTS)} checks passed"
          + (f"; FAILED: {', '.join(failed)}" if failed else ""))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.path.insert(0, ".")
    sys.exit(main())
