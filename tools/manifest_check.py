#!/usr/bin/env python3
"""Validate a sofa_tpu ``run_manifest.json`` against its schema.

CI/tooling companion of sofa_tpu/telemetry.py: bench.py runs this after its
preprocess-path evidence so every bench run also asserts the self-telemetry
ledger is present, schema-valid, and (with --require-healthy) free of
failed collectors.

    python tools/manifest_check.py <logdir-or-manifest.json> [--require-healthy]

Exit codes: 0 valid, 1 invalid (problems printed one per line), 2 missing /
unreadable.  ``validate_manifest`` is importable for tests.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from sofa_tpu.telemetry import (  # noqa: E402
    CACHE_OUTCOMES,
    COLLECTOR_STATUSES,
    MANIFEST_NAME,
    MANIFEST_SCHEMA,
    MANIFEST_VERSION,
    PASS_STATUSES,
    SOURCE_STATUSES,
)

_KNOWN_VERBS = ("record", "preprocess", "analyze", "archive", "regress",
                "whatif", "agent", "live")
_VERDICTS = ("regressed", "improved", "noise")
# Version pins per schema id: sofa-lint SL018 verifies these literals
# agree with the writers' *_VERSION constants and the schema registry
# table in docs/OBSERVABILITY.md — bump all three together.
_VERDICT_SCHEMA = "sofa_tpu/regress_verdict"
_VERDICT_VERSION = 1
_WHATIF_SCHEMA = "sofa_tpu/whatif_report"
_WHATIF_VERSION = 1
_INVENTORY_SCHEMA = "sofa_tpu/artifact_inventory"
_INVENTORY_VERSION = 1
_PROTOCOL_SCHEMA = "sofa_tpu/protocol_inventory"
_PROTOCOL_VERSION = 1
_WHATIF_CALIBRATION = ("calibrated", "uncalibrated")
_WHATIF_SCENARIO_STATUSES = ("parsed", "unknown")
_WHATIF_ATTRIBUTION_STATUSES = ("applied", "no_match", "unknown")
# `sofa live` per-source statuses (sofa_tpu/live.py LIVE_SOURCE_STATUSES;
# keep the vocabularies in sync) + the watermark staleness gate.
_LIVE_SOURCE_STATUSES = ("streaming", "idle", "stalled", "rotated",
                        "torn", "absent")
_LIVE_STALE_S = 600.0
# The live offset ledger beside the manifest (sofa_tpu/live.py writes
# it fsync'd every epoch; checking a logdir validates it too).
_LIVE_OFFSETS_NAME = "_live_offsets.json"
_LIVE_OFFSETS_SCHEMA = "sofa_tpu/live_offsets"
_LIVE_OFFSETS_VERSION = 1

_FRAMES_DIR_NAME = "_frames"
_FRAME_INDEX_NAME = "frame_index.json"
_FRAME_INDEX_SCHEMA = "sofa_tpu/frame_index"
_FRAME_INDEX_VERSION = 1
_FRAME_FORMATS = ("csv", "parquet", "columnar")

# The archive's columnar catalog index (sofa_tpu/archive/index.py):
# checking an archive root validates its commit manifest + the three
# column families' frame indexes.
_ARCHIVE_MARKER_NAME = "sofa_archive.json"
_ARCHIVE_INDEX_DIR = "_index"
_ARCHIVE_INDEX_COMMIT = "index_commit.json"
_ARCHIVE_INDEX_SCHEMA = "sofa_tpu/archive_index"
_ARCHIVE_INDEX_VERSION = 1
_ARCHIVE_INDEX_FAMILIES = ("catalog", "runs", "features")

# The incremental fleet-pass engine (sofa_tpu/analysis/fleet.py):
# checking an archive root validates the served cross-run report and the
# fold-state memo behind it under _fleet/.  Neither carries a wall-clock
# stamp by design — both are pure functions of the index commit, so a
# killed-and-resumed analyze converges byte-identical.
_FLEET_DIR = "_fleet"
_FLEET_REPORT_NAME = "fleet_report.json"
_FLEET_REPORT_SCHEMA = "sofa_tpu/fleet_report"
_FLEET_REPORT_VERSION = 1
_FLEET_STATE_NAME = "fleet_state.json"
_FLEET_STATE_SCHEMA = "sofa_tpu/fleet_state"
_FLEET_STATE_VERSION = 1
_FLEET_PASS_STATUSES = ("ok", "failed")

# The scaled-tier commit stamp (sofa_tpu/archive/tier.py TIER_SCHEMA):
# which pool worker committed the run, out of how many, at what queue
# depth — written into meta.tier by `sofa agent` from the commit ack.
_TIER_SCHEMA = "sofa_tpu/fleet_tier"
_TIER_VERSION = 1

# The tier observability plane (sofa_tpu/metrics.py): the /v1/metrics
# document and the per-window SLO verdict at _metrics/slo_verdict.json.
# meta.metrics / meta.slo are the agent-side folds of the commit ack.
_METRICS_SCHEMA = "sofa_tpu/fleet_metrics"
_METRICS_VERSION = 1
_SLO_SCHEMA = "sofa_tpu/slo_verdict"
_SLO_VERSION = 1
_SLO_OPS = ("<", "<=", ">", ">=")
_SLO_STATUSES = ("ok", "breach", "no_data")

# The self-healing tier's client failover record (sofa_tpu/archive/
# client.py HEALTH_SCHEMA): which endpoint served the push, how many
# failovers the client took, which breakers stand open — written into
# meta.health by `sofa agent` after the push.
_HEALTH_SCHEMA = "sofa_tpu/fleet_health"
_HEALTH_VERSION = 1

# The incremental content-addressed archive backup (sofa_tpu/archive/
# store.py BACKUP_SCHEMA): `sofa archive backup` stamps the snapshot it
# took into meta.backup when a logdir is in scope.
_BACKUP_SCHEMA = "sofa_tpu/archive_backup"
_BACKUP_VERSION = 1

# The merged cross-process push trace (sofa_tpu/metrics.py
# export_fleet_trace) — Chrome-trace JSON that Perfetto must accept.
_FLEET_TRACE_NAME = "fleet_trace.json"
_FLEET_TRACE_DIR = "fleet_trace"
_METRICS_DIR = "_metrics"


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_manifest(doc, require_healthy: bool = False) -> List[str]:
    """All schema problems found (empty list == valid).

    Validation tracks the versioning policy in docs/OBSERVABILITY.md: keys
    beyond the ones checked here are ALLOWED (additive evolution does not
    bump schema_version), so this only rejects missing/mistyped required
    structure and out-of-vocabulary enum values.
    """
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["manifest is not a JSON object"]
    if doc.get("schema") != MANIFEST_SCHEMA:
        probs.append(f"schema: expected {MANIFEST_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("schema_version") != MANIFEST_VERSION:
        probs.append(f"schema_version: expected {MANIFEST_VERSION}, "
                     f"got {doc.get('schema_version')!r}")
    if not _is_num(doc.get("generated_unix")):
        probs.append("generated_unix: missing or not a number")

    runs = doc.get("runs")
    if not isinstance(runs, dict) or not runs:
        probs.append("runs: missing or empty")
        runs = {}
    for verb, run in runs.items():
        where = f"runs.{verb}"
        if not isinstance(run, dict):
            probs.append(f"{where}: not an object")
            continue
        if not _is_num(run.get("started_unix")):
            probs.append(f"{where}.started_unix: missing or not a number")
        if not _is_num(run.get("wall_s")) or run.get("wall_s", 0) < 0:
            probs.append(f"{where}.wall_s: missing or negative")
        rc = run.get("rc")
        if rc is not None and not isinstance(rc, int):
            probs.append(f"{where}.rc: not an int or null")
        counters = run.get("counters")
        if not isinstance(counters, dict):
            probs.append(f"{where}.counters: missing")
        else:
            for key in ("warnings", "errors"):
                v = counters.get(key, 0)
                if not isinstance(v, int) or v < 0:
                    probs.append(f"{where}.counters.{key}: not a "
                                 "non-negative int")

    env = doc.get("env")
    if not isinstance(env, dict) or "sofa_tpu_version" not in env:
        probs.append("env: missing or lacks sofa_tpu_version")

    collectors = doc.get("collectors", {})
    if not isinstance(collectors, dict):
        probs.append("collectors: not an object")
        collectors = {}
    for name, ent in collectors.items():
        where = f"collectors.{name}"
        if not isinstance(ent, dict):
            probs.append(f"{where}: not an object")
            continue
        if ent.get("status") not in COLLECTOR_STATUSES:
            probs.append(f"{where}.status: {ent.get('status')!r} not in "
                         f"{COLLECTOR_STATUSES}")
        for key in ("bytes_captured", "exit_code", "restarts", "deaths",
                    "rotated_files", "budget_bytes"):
            if key in ent and not isinstance(ent[key], int):
                probs.append(f"{where}.{key}: not an int")
        if "bytes_captured" in ent and ent["bytes_captured"] < 0:
            probs.append(f"{where}.bytes_captured: negative")
        for key in ("restarts", "deaths", "rotated_files", "budget_bytes"):
            if key in ent and isinstance(ent[key], int) and ent[key] < 0:
                probs.append(f"{where}.{key}: negative")
        for key in ("died", "timed_out", "output_stalled"):
            if key in ent and not isinstance(ent[key], bool):
                probs.append(f"{where}.{key}: not a bool")

    sources = doc.get("sources", {})
    if not isinstance(sources, dict):
        probs.append("sources: not an object")
        sources = {}
    for name, ent in sources.items():
        where = f"sources.{name}"
        if not isinstance(ent, dict):
            probs.append(f"{where}: not an object")
            continue
        if ent.get("status") not in SOURCE_STATUSES:
            probs.append(f"{where}.status: {ent.get('status')!r} not in "
                         f"{SOURCE_STATUSES}")
        if ent.get("cache") not in CACHE_OUTCOMES:
            probs.append(f"{where}.cache: {ent.get('cache')!r} not in "
                         f"{CACHE_OUTCOMES}")
        if not _is_num(ent.get("wall_s")) or ent.get("wall_s", 0) < 0:
            probs.append(f"{where}.wall_s: missing or negative")
        if not isinstance(ent.get("events"), int) or ent.get("events", 0) < 0:
            probs.append(f"{where}.events: missing or negative")
        if "quarantined_file" in ent and \
                not isinstance(ent["quarantined_file"], str):
            probs.append(f"{where}.quarantined_file: not a string")

    # meta.tiles (additive, written when the LOD tile pyramid builds —
    # sofa_tpu/tiles.py): counts and bytes must be sane when present.
    tiles = (doc.get("meta") or {}).get("tiles")
    if tiles is not None:
        if not isinstance(tiles, dict):
            probs.append("meta.tiles: not an object")
        else:
            for key in ("series", "cached", "tile_count", "bytes"):
                v = tiles.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(f"meta.tiles.{key}: missing or not a "
                                 "non-negative int")
            if isinstance(tiles.get("cached"), int) and \
                    isinstance(tiles.get("series"), int) and \
                    tiles["cached"] > tiles["series"]:
                probs.append("meta.tiles: cached exceeds series")

    # digests (additive in v4 — sofa_tpu/durability.py): the sha256
    # integrity ledger `sofa fsck` verifies.
    digests = doc.get("digests")
    if digests is not None:
        if not isinstance(digests, dict) or \
                not isinstance(digests.get("files"), dict):
            probs.append("digests: not an object with a files map")
        else:
            if not isinstance(digests.get("algo"), str):
                probs.append("digests.algo: missing or not a string")
            for rel, ent in digests["files"].items():
                where = f"digests.files[{rel!r}]"
                if not isinstance(ent, dict):
                    probs.append(f"{where}: not an object")
                    continue
                sha = ent.get("sha256")
                if not (isinstance(sha, str) and len(sha) == 64):
                    probs.append(f"{where}.sha256: not a 64-hex digest")
                for key in ("bytes", "mtime_ns"):
                    v = ent.get(key)
                    if not isinstance(v, int) or isinstance(v, bool) \
                            or v < 0:
                        probs.append(f"{where}.{key}: missing or not a "
                                     "non-negative int")
                if ent.get("kind") not in ("raw", "derived"):
                    probs.append(f"{where}.kind: {ent.get('kind')!r} not "
                                 "raw/derived")

    # meta.disk_budget (written when --disk_budget/--collector_disk_budget
    # is on) and meta.fsck (written by `sofa fsck`).
    budget = (doc.get("meta") or {}).get("disk_budget")
    if budget is not None:
        if not isinstance(budget, dict):
            probs.append("meta.disk_budget: not an object")
        else:
            for key in ("budget_mb", "collector_budget_mb"):
                v = budget.get(key)
                if v is not None and (not _is_num(v) or v < 0):
                    probs.append(f"meta.disk_budget.{key}: not a "
                                 "non-negative number or null")
            v = budget.get("rotated_files")
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                probs.append("meta.disk_budget.rotated_files: missing or "
                             "not a non-negative int")
            t = budget.get("truncated")
            if not isinstance(t, list) or \
                    any(not isinstance(n, str) for n in t):
                probs.append("meta.disk_budget.truncated: not a list of "
                             "collector names")
    fsck = (doc.get("meta") or {}).get("fsck")
    if fsck is not None:
        if not isinstance(fsck, dict) or \
                not isinstance(fsck.get("ok"), bool):
            probs.append("meta.fsck: not an object with a bool ok")
        elif not isinstance(fsck.get("problems"), dict):
            probs.append("meta.fsck.problems: missing verdict counts")

    # meta.pool (preprocess's pool sizing) and meta.ingest_cache (the
    # content-keyed cache's hit/miss ledger): small, but their rot is how
    # a perf regression hides — jobs silently stuck at 1, a cache that
    # never hits.
    pool = (doc.get("meta") or {}).get("pool")
    if pool is not None:
        if not isinstance(pool, dict):
            probs.append("meta.pool: not an object")
        else:
            for key in ("jobs", "cpu_count"):
                v = pool.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                    probs.append(f"meta.pool.{key}: missing or not a "
                                 "positive int")
    icache = (doc.get("meta") or {}).get("ingest_cache")
    if icache is not None:
        if not isinstance(icache, dict) or \
                not isinstance(icache.get("enabled"), bool):
            probs.append("meta.ingest_cache: not an object with a bool "
                         "enabled")
        else:
            for key in ("hits", "misses"):
                v = icache.get(key)
                if not isinstance(v, list) or \
                        any(not isinstance(s, str) for s in v):
                    probs.append(f"meta.ingest_cache.{key}: not a list of "
                                 "source names")
            if not isinstance(icache.get("stored_bytes", {}), dict):
                probs.append("meta.ingest_cache.stored_bytes: not an "
                             "object")

    # meta.archive / meta.regress (written by the `sofa archive` /
    # `sofa regress` verbs, sofa_tpu/archive/): ingest summary + verdict
    # pointer must be sane when present.
    archive = (doc.get("meta") or {}).get("archive")
    if archive is not None:
        if not isinstance(archive, dict):
            probs.append("meta.archive: not an object")
        else:
            run = archive.get("run")
            if not (isinstance(run, str) and len(run) == 64):
                probs.append("meta.archive.run: not a 64-hex run id")
            for key in ("files", "new_objects", "bytes_added"):
                v = archive.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(f"meta.archive.{key}: missing or not a "
                                 "non-negative int")
    # meta.passes (schema v5): the analysis-pass ledger written by the
    # registry executor (sofa_tpu/analysis/registry.py).  Statuses must
    # stay in-vocabulary and the resolved schedule must cover the ledger.
    passes_meta = (doc.get("meta") or {}).get("passes")
    pass_ledger = {}
    if passes_meta is not None:
        if not isinstance(passes_meta, dict):
            probs.append("meta.passes: not an object")
        else:
            sched = passes_meta.get("schedule")
            if not isinstance(sched, list) or any(
                    not isinstance(w, list)
                    or any(not isinstance(n, str) for n in w)
                    for w in sched):
                probs.append("meta.passes.schedule: not a list of "
                             "name-list waves")
                sched = []
            if not isinstance(passes_meta.get("jobs"), int) \
                    or isinstance(passes_meta.get("jobs"), bool):
                probs.append("meta.passes.jobs: missing or not an int")
            pass_ledger = passes_meta.get("passes")
            if not isinstance(pass_ledger, dict):
                probs.append("meta.passes.passes: missing per-pass ledger")
                pass_ledger = {}
            scheduled = {n for w in sched for n in w}
            for name, ent in sorted(pass_ledger.items()):
                if not isinstance(ent, dict):
                    probs.append(f"meta.passes.passes.{name}: not an object")
                    continue
                if ent.get("status") not in PASS_STATUSES:
                    probs.append(f"meta.passes.passes.{name}.status: "
                                 f"{ent.get('status')!r} not in "
                                 f"{PASS_STATUSES}")
                if ent.get("status") != "skipped":
                    if not _is_num(ent.get("wall_s")):
                        probs.append(f"meta.passes.passes.{name}.wall_s: "
                                     "missing or not a number")
                    if name not in scheduled:
                        probs.append(f"meta.passes.passes.{name}: ran but "
                                     "absent from meta.passes.schedule")

    # meta.whatif (written by the `sofa whatif` verb, sofa_tpu/whatif/):
    # the calibration verdict + identity error the report carries in full.
    whatif = (doc.get("meta") or {}).get("whatif")
    if whatif is not None:
        if not isinstance(whatif, dict):
            probs.append("meta.whatif: not an object")
            whatif = None
        else:
            if whatif.get("verdict") not in _WHATIF_CALIBRATION:
                probs.append(f"meta.whatif.verdict: "
                             f"{whatif.get('verdict')!r} not in "
                             f"{_WHATIF_CALIBRATION}")
            v = whatif.get("identity_error_pct")
            if not _is_num(v) or v < 0:
                probs.append("meta.whatif.identity_error_pct: missing or "
                             "not a non-negative number")
            for key in ("n_steps", "scenarios"):
                v = whatif.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(f"meta.whatif.{key}: missing or not a "
                                 "non-negative int")
            if not isinstance(whatif.get("report"), str):
                probs.append("meta.whatif.report: missing report filename")

    # meta.agent / meta.serve (written by `sofa agent`, sofa_tpu/agent.py
    # — the fleet transport leg, docs/FLEET.md): the spool/push record
    # and, once the service acks the commit, the serve-side acceptance.
    agent = (doc.get("meta") or {}).get("agent")
    if agent is not None:
        if not isinstance(agent, dict):
            probs.append("meta.agent: not an object")
        else:
            if not isinstance(agent.get("spool"), str):
                probs.append("meta.agent.spool: missing spool root")
            run = agent.get("run")
            if not (isinstance(run, str) and len(run) == 64):
                probs.append("meta.agent.run: not a 64-hex run id")
            svc = agent.get("service")
            if svc is not None and not isinstance(svc, str):
                probs.append("meta.agent.service: not a string or null")
            push = agent.get("push")
            if push is not None:
                if not isinstance(push, dict) or push.get("status") not in (
                        "pushed", "spooled", "rejected"):
                    probs.append("meta.agent.push.status: not in "
                                 "('pushed', 'spooled', 'rejected')")
                else:
                    for key in ("attempts",):
                        v = push.get(key)
                        if not isinstance(v, int) or isinstance(v, bool) \
                                or v < 0:
                            probs.append(f"meta.agent.push.{key}: missing "
                                         "or not a non-negative int")
                    if not _is_num(push.get("wall_s")) \
                            or push.get("wall_s", 0) < 0:
                        probs.append("meta.agent.push.wall_s: missing or "
                                     "negative")
    serve = (doc.get("meta") or {}).get("serve")
    if serve is not None:
        if not isinstance(serve, dict):
            probs.append("meta.serve: not an object")
        else:
            for key in ("url", "tenant"):
                if not isinstance(serve.get(key), str) or not serve[key]:
                    probs.append(f"meta.serve.{key}: missing or empty")
            run = serve.get("run")
            if not (isinstance(run, str) and len(run) == 64):
                probs.append("meta.serve.run: not a 64-hex run id")
            if not _is_num(serve.get("committed_unix")):
                probs.append("meta.serve.committed_unix: missing or not "
                             "a number")

    # meta.tier (stamped by `sofa agent` from the scaled tier's commit
    # ack, sofa_tpu/archive/tier.py): the placement record — which pool
    # worker committed the run and the WAL depth it saw.
    tier = (doc.get("meta") or {}).get("tier")
    if tier is not None:
        if not isinstance(tier, dict):
            probs.append("meta.tier: not an object")
        else:
            if tier.get("schema") != _TIER_SCHEMA:
                probs.append(f"meta.tier.schema: expected "
                             f"{_TIER_SCHEMA!r}, got {tier.get('schema')!r}")
            if tier.get("version") != _TIER_VERSION:
                probs.append(f"meta.tier.version: expected "
                             f"{_TIER_VERSION}, got {tier.get('version')!r}")
            if not isinstance(tier.get("url"), str) or not tier.get("url"):
                probs.append("meta.tier.url: missing or empty")
            worker = tier.get("worker")
            workers = tier.get("workers")
            for key, v in (("worker", worker), ("workers", workers),
                           ("wal_depth", tier.get("wal_depth"))):
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(f"meta.tier.{key}: missing or not a "
                                 "non-negative int")
            if isinstance(worker, int) and isinstance(workers, int) \
                    and not isinstance(worker, bool) \
                    and not isinstance(workers, bool) \
                    and (workers < 1 or not 0 <= worker < workers):
                probs.append(f"meta.tier: worker {worker} out of range "
                             f"for {workers} worker(s)")

    # meta.metrics / meta.slo (stamped by `sofa agent` from the tier's
    # commit ack, sofa_tpu/metrics.py): the push's trace id and the
    # committing worker's scrape/SLO state at commit time.
    mmet = (doc.get("meta") or {}).get("metrics")
    if mmet is not None:
        if not isinstance(mmet, dict):
            probs.append("meta.metrics: not an object")
        else:
            if not isinstance(mmet.get("trace"), str):
                probs.append("meta.metrics.trace: missing or not a string")
            for key in ("last_scrape_unix", "scrape_age_s",
                        "push_wall_s", "push_p99_ms", "wal_depth",
                        "replica_behind"):
                v = mmet.get(key)
                if v is not None and key in mmet and not _is_num(v):
                    probs.append(f"meta.metrics.{key}: not a number "
                                 "or null")
            if "slo_ok" in mmet and mmet["slo_ok"] is not None \
                    and not isinstance(mmet["slo_ok"], bool):
                probs.append("meta.metrics.slo_ok: not a bool or null")
            br = mmet.get("slo_breaching")
            if br is not None and (
                    not isinstance(br, list)
                    or any(not isinstance(n, str) for n in br)):
                probs.append("meta.metrics.slo_breaching: not a list of "
                             "metric names")
    mslo = (doc.get("meta") or {}).get("slo")
    if mslo is not None:
        if not isinstance(mslo, dict) or \
                not isinstance(mslo.get("ok"), bool):
            probs.append("meta.slo: not an object with a bool ok")
        else:
            br = mslo.get("breaching")
            if not isinstance(br, list) or \
                    any(not isinstance(n, str) for n in br):
                probs.append("meta.slo.breaching: not a list of metric "
                             "names")
            elif mslo["ok"] is False and not br:
                probs.append("meta.slo: ok is false but breaching names "
                             "no metric")

    # meta.health (stamped by `sofa agent` after the push,
    # sofa_tpu/archive/client.py): the client-side failover record —
    # which endpoint served, how many failovers, which breakers stand
    # open.  Failover must leave a durable manifest record, never just
    # a log line.
    mh = (doc.get("meta") or {}).get("health")
    if mh is not None:
        if not isinstance(mh, dict):
            probs.append("meta.health: not an object")
        else:
            if mh.get("schema") != _HEALTH_SCHEMA:
                probs.append(f"meta.health.schema: expected "
                             f"{_HEALTH_SCHEMA!r}, got {mh.get('schema')!r}")
            if mh.get("version") != _HEALTH_VERSION:
                probs.append(f"meta.health.version: expected "
                             f"{_HEALTH_VERSION}, got {mh.get('version')!r}")
            eps = mh.get("endpoints")
            if not isinstance(eps, list) or not eps or any(
                    not isinstance(u, str) or not u for u in eps):
                probs.append("meta.health.endpoints: not a non-empty "
                             "list of URLs")
            active = mh.get("active")
            if not isinstance(active, str) or not active:
                probs.append("meta.health.active: missing or empty")
            elif isinstance(eps, list) and eps and active not in eps:
                probs.append(f"meta.health.active: {active!r} not in "
                             "endpoints")
            fo = mh.get("failovers")
            if not isinstance(fo, int) or isinstance(fo, bool) or fo < 0:
                probs.append("meta.health.failovers: missing or not a "
                             "non-negative int")
            bo = mh.get("breakers_open")
            if not isinstance(bo, list) or any(
                    not isinstance(u, str) for u in bo):
                probs.append("meta.health.breakers_open: not a list of "
                             "endpoint URLs")

    # meta.backup (stamped by `sofa archive backup`,
    # sofa_tpu/archive/store.py): the incremental content-addressed
    # snapshot record — which snapshot, where it landed, and the index
    # commit sha the restore must reproduce byte-identically.
    mb = (doc.get("meta") or {}).get("backup")
    if mb is not None:
        if not isinstance(mb, dict):
            probs.append("meta.backup: not an object")
        else:
            if mb.get("schema") != _BACKUP_SCHEMA:
                probs.append(f"meta.backup.schema: expected "
                             f"{_BACKUP_SCHEMA!r}, got {mb.get('schema')!r}")
            if mb.get("version") != _BACKUP_VERSION:
                probs.append(f"meta.backup.version: expected "
                             f"{_BACKUP_VERSION}, got {mb.get('version')!r}")
            snap = mb.get("snapshot")
            if not isinstance(snap, int) or isinstance(snap, bool) \
                    or snap < 1:
                probs.append("meta.backup.snapshot: missing or not a "
                             "positive int")
            for key in ("dest", "source_root"):
                if not isinstance(mb.get(key), str) or not mb[key]:
                    probs.append(f"meta.backup.{key}: missing or empty")
            for key in ("files", "new_objects", "bytes_added"):
                v = mb.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(f"meta.backup.{key}: missing or not a "
                                 "non-negative int")
            sha = mb.get("commit_sha")
            if not isinstance(sha, str) or (sha and len(sha) != 40):
                probs.append("meta.backup.commit_sha: not a 40-hex sha "
                             "or empty string")
            if not _is_num(mb.get("taken_unix")):
                probs.append("meta.backup.taken_unix: missing or not a "
                             "number")

    # meta.frames (written by preprocess, sofa_tpu/frames.py +
    # preprocess.py): which interchange format the run's frames landed
    # in, and — for the chunked columnar store — the chunk/reuse/byte
    # accounting that proves the content-keyed incremental writes.
    fmeta = (doc.get("meta") or {}).get("frames")
    if fmeta is not None:
        if not isinstance(fmeta, dict):
            probs.append("meta.frames: not an object")
        else:
            if fmeta.get("format") not in _FRAME_FORMATS:
                probs.append(f"meta.frames.format: "
                             f"{fmeta.get('format')!r} not in "
                             f"{_FRAME_FORMATS}")
            for key in ("frames", "chunks", "reused", "bytes"):
                v = fmeta.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(f"meta.frames.{key}: missing or not a "
                                 "non-negative int")
            if isinstance(fmeta.get("chunks"), int) \
                    and isinstance(fmeta.get("reused"), int) \
                    and fmeta.get("reused", 0) > fmeta.get("chunks", 0):
                probs.append("meta.frames: reused exceeds chunks")

    # meta.live (written every `sofa live` epoch, sofa_tpu/live.py): the
    # streaming-freshness manifest the board polls — epoch seq,
    # per-source offsets/lag/status, watermark, no-reparse counters.
    live = (doc.get("meta") or {}).get("live")
    if live is not None:
        if not isinstance(live, dict):
            probs.append("meta.live: not an object")
            live = None
        else:
            if not isinstance(live.get("active"), bool):
                probs.append("meta.live.active: missing or not a bool")
            ep = live.get("epoch")
            if not isinstance(ep, int) or isinstance(ep, bool) or ep < 1:
                probs.append("meta.live.epoch: missing or not a "
                             "positive int")
            if not _is_num(live.get("updated_unix")):
                probs.append("meta.live.updated_unix: missing or not a "
                             "number")
            wm = live.get("watermark_s")
            if wm is not None and not _is_num(wm):
                probs.append("meta.live.watermark_s: not a number or "
                             "null")
            for key in ("chunks_parsed", "chunks_loaded"):
                v = live.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    probs.append(f"meta.live.{key}: missing or not a "
                                 "non-negative int")
            lsources = live.get("sources")
            if not isinstance(lsources, dict):
                probs.append("meta.live.sources: missing per-source map")
                lsources = {}
            for name, ent in sorted(lsources.items()):
                where = f"meta.live.sources.{name}"
                if not isinstance(ent, dict):
                    probs.append(f"{where}: not an object")
                    continue
                if ent.get("status") not in _LIVE_SOURCE_STATUSES:
                    probs.append(f"{where}.status: {ent.get('status')!r} "
                                 f"not in {_LIVE_SOURCE_STATUSES}")
                for key in ("offset", "lag_bytes", "chunks",
                            "chunks_parsed", "chunks_loaded", "events"):
                    v = ent.get(key)
                    if not isinstance(v, int) or isinstance(v, bool) \
                            or v < 0:
                        probs.append(f"{where}.{key}: missing or not a "
                                     "non-negative int")
            ltiles = live.get("tiles")
            if ltiles is not None and (
                    not isinstance(ltiles, dict) or any(
                        not isinstance(ltiles.get(k), int)
                        or isinstance(ltiles.get(k), bool)
                        or ltiles.get(k) < 0
                        for k in ("rebuilt", "kept", "full_rebuilds"))):
                probs.append("meta.live.tiles: needs non-negative "
                             "rebuilt/kept/full_rebuilds ints")

    regress = (doc.get("meta") or {}).get("regress")
    if regress is not None:
        if not isinstance(regress, dict) or \
                regress.get("verdict") not in _VERDICTS:
            probs.append(f"meta.regress.verdict: not in {_VERDICTS}")
        elif not isinstance(regress.get("counts"), dict):
            probs.append("meta.regress.counts: missing verdict counts")

    stages = doc.get("stages", [])
    if not isinstance(stages, list):
        probs.append("stages: not a list")
        stages = []
    for i, s in enumerate(stages):
        if not isinstance(s, dict) or not isinstance(s.get("name"), str) \
                or not _is_num(s.get("t0_unix")) or not _is_num(s.get("dur_s")):
            probs.append(f"stages[{i}]: needs name + numeric t0_unix/dur_s")
        elif s.get("dur_s") < 0:
            probs.append(f"stages[{i}].dur_s: negative")

    if "record" in runs and not collectors:
        probs.append("a record run is present but the collectors ledger "
                     "is empty")
    if "preprocess" in runs and not sources:
        probs.append("a preprocess run is present but the sources ledger "
                     "is empty")

    if require_healthy:
        for name, ent in collectors.items():
            if ent.get("status") in ("failed", "killed", "died",
                                     "timed_out", "truncated_by_budget"):
                probs.append(f"unhealthy: collector {name} "
                             f"{ent.get('status')}")
        if isinstance(fsck, dict) and fsck.get("ok") is False:
            probs.append("unhealthy: the last `sofa fsck` found damaged "
                         "artifacts")
        for name, ent in sources.items():
            if ent.get("status") in ("quarantined", "failed"):
                probs.append(f"unhealthy: source {name} "
                             f"{ent.get('status')}")
        for name, ent in sorted(pass_ledger.items()):
            if isinstance(ent, dict) and ent.get("status") == "failed":
                probs.append(f"unhealthy: analysis pass {name} failed"
                             + (f" ({ent['error']})"
                                if ent.get("error") else ""))
        if isinstance(agent, dict) and \
                isinstance(agent.get("push"), dict) and \
                agent["push"].get("status") != "pushed":
            probs.append("unhealthy: the agent could not deliver this "
                         f"run ({agent['push'].get('status')}) — it is "
                         "spooled locally, not in the fleet archive")
        if isinstance(mslo, dict) and mslo.get("ok") is False:
            probs.append("unhealthy: the tier was breaching its declared "
                         "SLO ("
                         + ", ".join(str(n) for n in
                                     (mslo.get("breaching") or []))
                         + ") when this run committed")
        if isinstance(whatif, dict) and \
                whatif.get("verdict") == "uncalibrated":
            probs.append("unhealthy: the what-if identity gate is "
                         "uncalibrated — the replay model does not "
                         "reproduce this run's measured step times")
        if isinstance(live, dict):
            for name, ent in sorted((live.get("sources") or {}).items()):
                if isinstance(ent, dict) and \
                        ent.get("status") == "stalled":
                    probs.append(f"unhealthy: live source {name} stalled "
                                 "— it stopped growing while siblings "
                                 "kept streaming")
            import time as _time

            upd = live.get("updated_unix")
            if live.get("active") and _is_num(upd) and \
                    _time.time() - upd > _LIVE_STALE_S:
                probs.append("unhealthy: meta.live says the stream is "
                             "active but its watermark is stale "
                             f"(last epoch {_time.time() - upd:.0f}s ago "
                             f"> {_LIVE_STALE_S:.0f}s) — the live loop "
                             "died without draining")
        for verb, run in runs.items():
            if isinstance(run, dict) and (run.get("counters") or {}).get(
                    "errors"):
                probs.append(f"unhealthy: `sofa {verb}` logged error lines")
    return probs


def validate_verdict(doc, require_passing: bool = False) -> List[str]:
    """Schema problems in a ``regress_verdict.json``
    (sofa_tpu/archive/verdict.py).  ``require_passing`` additionally
    fails on an overall ``regressed`` verdict — the CI-gate mode."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["verdict is not a JSON object"]
    if doc.get("schema") != _VERDICT_SCHEMA:
        probs.append(f"schema: expected {_VERDICT_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("version") != _VERDICT_VERSION:
        probs.append(f"version: expected {_VERDICT_VERSION}, "
                     f"got {doc.get('version')!r}")
    if not _is_num(doc.get("generated_unix")):
        probs.append("generated_unix: missing or not a number")
    if doc.get("verdict") not in _VERDICTS:
        probs.append(f"verdict: {doc.get('verdict')!r} not in {_VERDICTS}")
    counts = doc.get("counts")
    if not isinstance(counts, dict) or any(
            not isinstance(counts.get(v), int) for v in _VERDICTS):
        probs.append("counts: missing per-verdict int counters")
    for section in ("features", "clusters"):
        rows = doc.get(section)
        if not isinstance(rows, list):
            probs.append(f"{section}: not a list")
            continue
        for i, r in enumerate(rows):
            if not isinstance(r, dict) or \
                    not isinstance(r.get("name"), str) or \
                    r.get("verdict") not in _VERDICTS:
                probs.append(f"{section}[{i}]: needs a name and a typed "
                             f"verdict in {_VERDICTS}")
            elif r.get("verdict") != "noise" and \
                    not isinstance(r.get("reason"), str):
                probs.append(f"{section}[{i}]: a non-noise verdict must "
                             "state its reason")
    base = doc.get("baseline")
    if not isinstance(base, dict) or base.get("mode") not in (
            "pairwise", "rolling"):
        probs.append("baseline.mode: not pairwise/rolling")
    if require_passing and doc.get("verdict") == "regressed":
        probs.append("gate: overall verdict is regressed")
    return probs


def validate_whatif(doc, require_healthy: bool = False) -> List[str]:
    """Schema problems in a ``whatif_report.json`` (sofa_tpu/whatif/).
    ``require_healthy`` additionally fails on an ``uncalibrated``
    identity gate — a prediction the model cannot vouch for."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["whatif report is not a JSON object"]
    if doc.get("schema") != _WHATIF_SCHEMA:
        probs.append(f"schema: expected {_WHATIF_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("version") != _WHATIF_VERSION:
        probs.append(f"version: expected {_WHATIF_VERSION}, "
                     f"got {doc.get('version')!r}")
    if not _is_num(doc.get("generated_unix")):
        probs.append("generated_unix: missing or not a number")
    calib = doc.get("calibration")
    if not isinstance(calib, dict):
        probs.append("calibration: missing")
        calib = {}
    verdict = calib.get("verdict")
    if verdict not in _WHATIF_CALIBRATION:
        probs.append(f"calibration.verdict: {verdict!r} not in "
                     f"{_WHATIF_CALIBRATION}")
    if not isinstance(calib.get("reason"), str):
        probs.append("calibration.reason: a verdict must state its reason")
    n = calib.get("n_steps")
    if not isinstance(n, int) or isinstance(n, bool) or n < 0:
        probs.append("calibration.n_steps: missing or not a "
                     "non-negative int")
    elif n > 0:
        for key in ("measured_mean_s", "measured_median_s",
                    "identity_mean_s", "identity_error_pct"):
            if not _is_num(calib.get(key)):
                probs.append(f"calibration.{key}: missing or not a number")
        ci = calib.get("ci")
        if ci is not None and not (
                isinstance(ci, list) and len(ci) == 2
                and all(_is_num(v) for v in ci)):
            probs.append("calibration.ci: not null or a [lo, hi] pair")
    scenarios = doc.get("scenarios")
    if not isinstance(scenarios, list):
        probs.append("scenarios: not a list")
        scenarios = []
    for i, s in enumerate(scenarios):
        if not isinstance(s, dict) or not isinstance(s.get("spec"), str) \
                or s.get("status") not in _WHATIF_SCENARIO_STATUSES:
            probs.append(f"scenarios[{i}]: needs a spec and a status in "
                         f"{_WHATIF_SCENARIO_STATUSES}")
    pred = doc.get("predicted")
    if not isinstance(pred, dict):
        probs.append("predicted: missing")
        pred = {}
    if not _is_num(pred.get("step_time_mean_s")):
        probs.append("predicted.step_time_mean_s: missing or not a number")
    bars = pred.get("error_bars")
    if bars is not None and not (
            isinstance(bars, list) and len(bars) == 2
            and all(_is_num(v) for v in bars)):
        probs.append("predicted.error_bars: not null or a [lo, hi] pair")
    att = pred.get("attribution")
    if not isinstance(att, list):
        probs.append("predicted.attribution: not a list")
        att = []
    for i, a in enumerate(att):
        if not isinstance(a, dict) \
                or not isinstance(a.get("scenario"), str) \
                or a.get("status") not in _WHATIF_ATTRIBUTION_STATUSES \
                or not _is_num(a.get("delta_s")):
            probs.append(f"predicted.attribution[{i}]: needs scenario, a "
                         f"status in {_WHATIF_ATTRIBUTION_STATUSES}, and "
                         "a numeric delta_s")
    steps = doc.get("steps")
    if not isinstance(steps, list):
        probs.append("steps: not a list")
        steps = []
    for i, s in enumerate(steps):
        if not isinstance(s, dict) or not all(
                _is_num(s.get(k)) for k in ("deviceId", "step",
                                            "measured_s", "predicted_s")):
            probs.append(f"steps[{i}]: needs numeric deviceId/step/"
                         "measured_s/predicted_s")
            break  # one line for a malformed overlay, not thousands
    if require_healthy and verdict == "uncalibrated":
        probs.append("gate: the identity replay is uncalibrated ("
                     + str(calib.get("reason", "?")) + ")")
    return probs


def validate_inventory(doc, require_healthy: bool = False) -> List[str]:
    """Schema problems in a ``sofa artifacts --json`` document
    (sofa_tpu/artifacts.py).  ``require_healthy`` additionally fails on
    closure violations — the CI-gate mode bench.py rides."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["inventory is not a JSON object"]
    if doc.get("schema") != _INVENTORY_SCHEMA:
        probs.append(f"schema: expected {_INVENTORY_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("version") != _INVENTORY_VERSION:
        probs.append(f"version: expected {_INVENTORY_VERSION}, "
                     f"got {doc.get('version')!r}")
    if not _is_num(doc.get("generated_unix")):
        probs.append("generated_unix: missing or not a number")
    if not isinstance(doc.get("ok"), bool):
        probs.append("ok: missing or not a bool")
    rows = doc.get("artifacts")
    if not isinstance(rows, list) or not rows:
        probs.append("artifacts: missing or empty")
        rows = []
    for i, r in enumerate(rows):
        if not isinstance(r, dict) or not isinstance(r.get("name"), str) \
                or r.get("kind") not in ("raw", "derived") \
                or not isinstance(r.get("clean"), str) \
                or not isinstance(r.get("digest"), str) \
                or not isinstance(r.get("read"), bool) \
                or not isinstance(r.get("writers"), list):
            probs.append(f"artifacts[{i}]: needs name, kind raw/derived, "
                         "clean/digest coverage strings, a bool read, "
                         "and a writers list")
            break  # one line for a malformed table, not eighty
    viol = doc.get("violations")
    if not isinstance(viol, list):
        probs.append("violations: not a list")
        viol = []
    counts = doc.get("counts")
    if not isinstance(counts, dict) or not all(
            isinstance(counts.get(k), int)
            for k in ("artifacts", "writers", "violations")):
        probs.append("counts: missing artifact/writer/violation counters")
    audit = doc.get("logdir")
    if audit is not None and (
            not isinstance(audit, dict)
            or not isinstance(audit.get("unaccounted"), list)):
        probs.append("logdir: not an object with an unaccounted list")
    if require_healthy:
        if viol:
            probs.append(f"gate: {len(viol)} closure violation(s)")
        if audit and audit.get("unaccounted"):
            probs.append("gate: on-disk files no registry accounts for: "
                         + ", ".join(audit["unaccounted"][:8]))
    return probs


def validate_protocol_inventory(doc,
                                require_healthy: bool = False) -> List[str]:
    """Schema problems in a ``sofa protocol --json`` document
    (sofa_tpu/protocol.py).  ``require_healthy`` additionally fails on
    closure violations — the CI-gate mode bench.py rides."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["protocol inventory is not a JSON object"]
    if doc.get("schema") != _PROTOCOL_SCHEMA:
        probs.append(f"schema: expected {_PROTOCOL_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("version") != _PROTOCOL_VERSION:
        probs.append(f"version: expected {_PROTOCOL_VERSION}, "
                     f"got {doc.get('version')!r}")
    if not _is_num(doc.get("generated_unix")):
        probs.append("generated_unix: missing or not a number")
    if not isinstance(doc.get("ok"), bool):
        probs.append("ok: missing or not a bool")
    routes = doc.get("routes")
    if not isinstance(routes, list) or not routes:
        probs.append("routes: missing or empty")
        routes = []
    for i, r in enumerate(routes):
        if not isinstance(r, dict) or not isinstance(r.get("method"), str) \
                or not isinstance(r.get("path"), str) \
                or not r.get("path", "").startswith("/v1/") \
                or not isinstance(r.get("clients"), list):
            probs.append(f"routes[{i}]: needs method, a /v1/ path, and a "
                         "clients list")
            break
    statuses = doc.get("statuses")
    if not isinstance(statuses, list) or not statuses:
        probs.append("statuses: missing or empty")
        statuses = []
    for i, s in enumerate(statuses):
        if not isinstance(s, dict) \
                or not isinstance(s.get("status"), int) \
                or not isinstance(s.get("errors"), list) \
                or not isinstance(s.get("retry_after"), bool) \
                or s.get("client") not in ("fatal", "resume", "retry", "-"):
            probs.append(f"statuses[{i}]: needs an int status, an errors "
                         "list, a retry_after bool, and a client "
                         "handling class")
            break
        if s.get("retry_after") and s.get("client") == "fatal":
            probs.append(f"statuses[{i}]: HTTP {s.get('status')} carries "
                         "Retry-After but the client treats it as fatal")
    for section in ("errors", "knobs", "fault_kinds", "violations"):
        if not isinstance(doc.get(section), list):
            probs.append(f"{section}: not a list")
    counts = doc.get("counts")
    if not isinstance(counts, dict) or not all(
            isinstance(counts.get(k), int)
            for k in ("routes", "statuses", "errors", "knobs",
                      "fault_kinds", "violations")):
        probs.append("counts: missing route/status/error/knob/fault/"
                     "violation counters")
    if require_healthy:
        viol = doc.get("violations")
        if isinstance(viol, list) and viol:
            probs.append(f"gate: {len(viol)} closure violation(s)")
        undocumented = [k.get("knob") for k in doc.get("knobs") or []
                        if isinstance(k, dict) and not k.get("documented")
                        and k.get("read_by")]
        if undocumented:
            probs.append("gate: undocumented knobs: "
                         + ", ".join(undocumented[:8]))
    return probs


def validate_slo_verdict(doc, require_passing: bool = False) -> List[str]:
    """Schema problems in a ``_metrics/slo_verdict.json``
    (sofa_tpu/metrics.py evaluate_slo) — the typed per-window judgement
    of the tier's declared objectives.  ``require_passing`` additionally
    fails on an actively breaching verdict — the CI-gate mode."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["slo verdict is not a JSON object"]
    if doc.get("schema") != _SLO_SCHEMA:
        probs.append(f"schema: expected {_SLO_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("version") != _SLO_VERSION:
        probs.append(f"version: expected {_SLO_VERSION}, "
                     f"got {doc.get('version')!r}")
    if not _is_num(doc.get("generated_unix")):
        probs.append("generated_unix: missing or not a number")
    w = doc.get("window")
    if not isinstance(w, int) or isinstance(w, bool) or w < 0:
        probs.append("window: missing or not a non-negative int")
    if not isinstance(doc.get("ok"), bool):
        probs.append("ok: missing or not a bool")
    breaching = doc.get("breaching")
    if not isinstance(breaching, list) or \
            any(not isinstance(n, str) for n in breaching):
        probs.append("breaching: not a list of metric names")
        breaching = []
    targets = doc.get("targets")
    if not isinstance(targets, list):
        probs.append("targets: not a list")
        targets = []
    breached_names = []
    for i, t in enumerate(targets):
        if not isinstance(t, dict) \
                or not isinstance(t.get("name"), str) \
                or t.get("op") not in _SLO_OPS \
                or not _is_num(t.get("value")) \
                or t.get("status") not in _SLO_STATUSES:
            probs.append(f"targets[{i}]: needs name, an op in {_SLO_OPS}, "
                         f"a numeric value, and a status in "
                         f"{_SLO_STATUSES}")
            continue
        obs = t.get("observed")
        if t.get("status") != "no_data" and not _is_num(obs):
            probs.append(f"targets[{i}].observed: a judged target must "
                         "carry its observed number")
        if t.get("status") == "breach":
            breached_names.append(t["name"])
    if isinstance(doc.get("ok"), bool) and targets and \
            not probs and doc["ok"] == bool(breached_names):
        probs.append(f"ok: {doc['ok']} disagrees with the target "
                     f"statuses ({len(breached_names)} breach(es))")
    if sorted(breaching) != sorted(breached_names) and not probs:
        probs.append("breaching: disagrees with the per-target statuses")
    if require_passing and doc.get("ok") is False:
        probs.append("gate: the tier is actively breaching its SLO ("
                     + ", ".join(breaching) + ")")
    return probs


def validate_fleet_metrics(doc) -> List[str]:
    """Schema problems in a ``GET /v1/metrics`` document
    (sofa_tpu/metrics.py metrics_doc) — the board/test contract."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["metrics document is not a JSON object"]
    if doc.get("schema") != _METRICS_SCHEMA:
        probs.append(f"schema: expected {_METRICS_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("version") != _METRICS_VERSION:
        probs.append(f"version: expected {_METRICS_VERSION}, "
                     f"got {doc.get('version')!r}")
    if not _is_num(doc.get("generated_unix")):
        probs.append("generated_unix: missing or not a number")
    if doc.get("role") not in ("primary", "replica"):
        probs.append(f"role: {doc.get('role')!r} not primary/replica")
    worker = doc.get("worker")
    if not isinstance(worker, int) or isinstance(worker, bool) \
            or worker < 0:
        probs.append("worker: missing or not a non-negative int")
    seq = doc.get("scrape_seq")
    if not isinstance(seq, int) or isinstance(seq, bool) or seq < 0:
        probs.append("scrape_seq: missing or not a non-negative int")
    snap = doc.get("snapshot")
    if not isinstance(snap, dict) or any(
            not isinstance(k, str) or (v is not None and not _is_num(v))
            for k, v in snap.items()):
        probs.append("snapshot: not a flat name -> number map")
    hist = doc.get("history")
    if not isinstance(hist, dict) \
            or not isinstance(hist.get("rows"), list) \
            or not isinstance(hist.get("total"), int) \
            or isinstance(hist.get("total"), bool):
        probs.append("history: needs a rows list and an int total")
    else:
        for i, r in enumerate(hist["rows"]):
            if not isinstance(r, dict) or not _is_num(r.get("t")) \
                    or not isinstance(r.get("name"), str) \
                    or not _is_num(r.get("value")):
                probs.append(f"history.rows[{i}]: needs numeric t, a "
                             "name, and a numeric value")
                break  # one line for a malformed table, not thousands
    slo = doc.get("slo")
    if slo is not None:
        probs.extend(f"slo: {p}" for p in validate_slo_verdict(slo))
    return probs


def validate_fleet_trace(doc) -> List[str]:
    """Schema problems in a merged ``fleet_trace.json``
    (sofa_tpu/metrics.py export_fleet_trace) — the Chrome-trace shape
    Perfetto accepts: a ``traceEvents`` list of M metadata events and
    complete (``ph == "X"``) spans with numeric ts/dur and a pid, so
    the cross-process join stays loadable."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["fleet trace is not a JSON object"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents: missing or not a list"]
    saw_span = False
    for i, e in enumerate(events):
        if not isinstance(e, dict) or not isinstance(e.get("name"), str):
            probs.append(f"traceEvents[{i}]: not a named event object")
            break
        ph = e.get("ph")
        if ph == "M":
            continue
        if ph != "X":
            probs.append(f"traceEvents[{i}]: ph {ph!r} is neither "
                         "metadata (M) nor a complete span (X)")
            break
        saw_span = True
        if not _is_num(e.get("ts")) or e["ts"] < 0 \
                or not _is_num(e.get("dur")) or e["dur"] < 0:
            probs.append(f"traceEvents[{i}]: span needs non-negative "
                         "numeric ts and dur")
            break
        if not isinstance(e.get("pid"), int):
            probs.append(f"traceEvents[{i}]: span has no integer pid — "
                         "the cross-process join is lost")
            break
    if not probs and not saw_span:
        probs.append("traceEvents: no complete (X) span in the merge")
    return probs


def _check_fleet_trace(root: str) -> List[str]:
    """Validate ``_metrics/fleet_trace/fleet_trace.json`` under an
    archive root when an export has been written (absent = no export
    yet, healthy)."""
    path = os.path.join(root, _METRICS_DIR, _FLEET_TRACE_DIR,
                        _FLEET_TRACE_NAME)
    if not os.path.isfile(path):
        return []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{_FLEET_TRACE_NAME}: unreadable ({e})"]
    return [f"{_FLEET_TRACE_NAME}: {p}"
            for p in validate_fleet_trace(doc)]


def validate_live_offsets(doc) -> List[str]:
    """Schema problems in a ``_live_offsets.json`` ledger
    (sofa_tpu/live.py OffsetLedger) — the fsync'd commit point of every
    `sofa live` epoch."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["offset ledger is not a JSON object"]
    if doc.get("schema") != _LIVE_OFFSETS_SCHEMA:
        probs.append(f"schema: expected {_LIVE_OFFSETS_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("version") != _LIVE_OFFSETS_VERSION:
        probs.append(f"version: expected {_LIVE_OFFSETS_VERSION}, "
                     f"got {doc.get('version')!r}")
    ep = doc.get("epoch")
    if not isinstance(ep, int) or isinstance(ep, bool) or ep < 0:
        probs.append("epoch: missing or not a non-negative int")
    sources = doc.get("sources")
    if not isinstance(sources, dict):
        probs.append("sources: missing per-source map")
        sources = {}
    for name, ent in sorted(sources.items()):
        where = f"sources.{name}"
        if not isinstance(ent, dict):
            probs.append(f"{where}: not an object")
            continue
        off = ent.get("offset")
        if not isinstance(off, int) or isinstance(off, bool) or off < 0:
            probs.append(f"{where}.offset: missing or not a "
                         "non-negative int")
        chunks = ent.get("chunks")
        if not isinstance(chunks, list) or any(
                not (isinstance(c, list) and len(c) == 3
                     and all(isinstance(v, int) for v in c))
                for c in chunks):
            probs.append(f"{where}.chunks: not a list of "
                         "[start, end, rows] triples")
            continue
        prev_end = None
        for c in chunks:
            if c[0] >= c[1]:
                probs.append(f"{where}.chunks: empty/inverted range {c}")
            if prev_end is not None and c[0] != prev_end:
                probs.append(f"{where}.chunks: gap/overlap at {c} "
                             f"(previous chunk ended at {prev_end})")
            prev_end = c[1]
        if chunks and isinstance(off, int) and chunks[-1][1] != off:
            probs.append(f"{where}: offset {off} disagrees with the "
                         f"last chunk end {chunks[-1][1]}")
    return probs


def validate_frame_index(doc) -> List[str]:
    """Schema problems in a ``_frames/<name>/frame_index.json`` manifest
    (sofa_tpu/frames.py) — the commit point of one frame's chunked
    columnar store."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["frame index is not a JSON object"]
    if doc.get("schema") != _FRAME_INDEX_SCHEMA:
        probs.append(f"schema: expected {_FRAME_INDEX_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("version") != _FRAME_INDEX_VERSION:
        probs.append(f"version: expected {_FRAME_INDEX_VERSION}, "
                     f"got {doc.get('version')!r}")
    if not isinstance(doc.get("name"), str) or not doc.get("name"):
        probs.append("name: missing or empty")
    cols = doc.get("columns")
    if not isinstance(cols, list) or not cols \
            or not all(isinstance(c, str) for c in cols):
        probs.append("columns: missing or not a list of column names")
    rows = doc.get("rows")
    if not isinstance(rows, int) or isinstance(rows, bool) or rows < 0:
        probs.append("rows: missing or not a non-negative int")
    step = doc.get("chunk_rows")
    if not isinstance(step, int) or isinstance(step, bool) or step < 1:
        probs.append("chunk_rows: missing or not a positive int")
    if doc.get("format") != "arrow":
        probs.append(f"format: expected 'arrow', got {doc.get('format')!r}")
    chunks = doc.get("chunks")
    if not isinstance(chunks, list):
        probs.append("chunks: not a list")
        chunks = []
    total = 0
    for i, c in enumerate(chunks):
        # t_min/t_max are null (together) when every timestamp in the
        # chunk is NaN — readers then include the chunk conservatively
        t_ok = ((_is_num(c.get("t_min")) and _is_num(c.get("t_max")))
                or (c.get("t_min") is None and c.get("t_max") is None)) \
            if isinstance(c, dict) else False
        if not isinstance(c, dict) or not isinstance(c.get("file"), str) \
                or not isinstance(c.get("sha"), str) \
                or not isinstance(c.get("rows"), int) \
                or isinstance(c.get("rows"), bool) or c.get("rows") < 1 \
                or not t_ok:
            probs.append(f"chunks[{i}]: needs file, sha, positive rows, "
                         "and numeric (or paired-null) t_min/t_max")
            continue
        total += c["rows"]
        if isinstance(step, int) and step >= 1:
            if i < len(chunks) - 1 and c["rows"] != step:
                probs.append(f"chunks[{i}].rows: {c['rows']} — every "
                             f"non-final chunk must hold exactly "
                             f"chunk_rows ({step}) rows")
    if chunks and isinstance(rows, int) and total != rows:
        probs.append(f"rows: {rows} disagrees with the chunk-table sum "
                     f"{total}")
    return probs


def _check_frame_indexes(logdir: str) -> List[str]:
    """Validate every committed frame_index.json under a logdir's
    ``_frames/`` store (missing store = nothing to check: the CSV
    path)."""
    root = os.path.join(logdir, _FRAMES_DIR_NAME)
    probs: List[str] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    for name in names:
        path = os.path.join(root, name, _FRAME_INDEX_NAME)
        if not os.path.isfile(path):
            continue
        where = f"{_FRAMES_DIR_NAME}/{name}/{_FRAME_INDEX_NAME}"
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            probs.append(f"{where}: unreadable ({e})")
            continue
        probs.extend(f"{where}: {p}" for p in validate_frame_index(doc))
    return probs


def validate_index_commit(doc) -> List[str]:
    """Schema problems in an archive's ``_index/index_commit.json``
    (sofa_tpu/archive/index.py) — the fsync'd-last commit point of the
    columnar catalog index."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["index commit is not a JSON object"]
    if doc.get("schema") != _ARCHIVE_INDEX_SCHEMA:
        probs.append(f"schema: expected {_ARCHIVE_INDEX_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("version") != _ARCHIVE_INDEX_VERSION:
        probs.append(f"version: expected {_ARCHIVE_INDEX_VERSION}, "
                     f"got {doc.get('version')!r}")
    for key in ("catalog_offset", "catalog_gen", "events",
                "ingest_events", "bench_events", "runs",
                "features_rows"):
        v = doc.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            probs.append(f"{key}: missing or not a non-negative int")
    if not isinstance(doc.get("catalog_head_sha"), str):
        probs.append("catalog_head_sha: missing")
    if not isinstance(doc.get("commit_sha"), str) \
            or not doc.get("commit_sha"):
        probs.append("commit_sha: missing (the /v1/query ETag key)")
    fams = doc.get("families")
    if not isinstance(fams, dict) \
            or sorted(fams) != sorted(_ARCHIVE_INDEX_FAMILIES):
        probs.append("families: expected exactly "
                     f"{sorted(_ARCHIVE_INDEX_FAMILIES)}, got "
                     f"{sorted(fams) if isinstance(fams, dict) else fams}")
        fams = {}
    for name, ent in sorted(fams.items()):
        if not isinstance(ent, dict) \
                or not isinstance(ent.get("rows"), int) \
                or isinstance(ent.get("rows"), bool) \
                or not isinstance(ent.get("chunks"), int) \
                or isinstance(ent.get("chunks"), bool):
            probs.append(f"families.{name}: needs int rows + chunks")
    return probs


def _check_archive_index(root: str) -> List[str]:
    """Validate an archive root's columnar index: the commit manifest
    plus each family's frame_index.json.  No index at all is healthy
    (queries scan); a HALF-index is not."""
    idir = os.path.join(root, _ARCHIVE_INDEX_DIR)
    cpath = os.path.join(idir, _ARCHIVE_INDEX_COMMIT)
    if not os.path.isdir(idir):
        return []
    where = f"{_ARCHIVE_INDEX_DIR}/{_ARCHIVE_INDEX_COMMIT}"
    try:
        with open(cpath) as f:
            doc = json.load(f)
    except OSError:
        return [f"{where}: missing (an _index/ dir with no commit — "
                "`sofa archive fsck --repair` rebuilds)"]
    except ValueError as e:
        return [f"{where}: not JSON: {e}"]
    probs = [f"{where}: {p}" for p in validate_index_commit(doc)]
    for family in _ARCHIVE_INDEX_FAMILIES:
        fpath = os.path.join(idir, family, _FRAME_INDEX_NAME)
        fwhere = f"{_ARCHIVE_INDEX_DIR}/{family}/{_FRAME_INDEX_NAME}"
        try:
            with open(fpath) as f:
                fdoc = json.load(f)
        except (OSError, ValueError) as e:
            probs.append(f"{fwhere}: unreadable ({e})")
            continue
        probs.extend(f"{fwhere}: {p}" for p in validate_frame_index(fdoc))
        want = ((doc.get("families") or {}).get(family) or {}) \
            if isinstance(doc, dict) else {}
        if isinstance(want.get("rows"), int) \
                and fdoc.get("rows") != want["rows"]:
            probs.append(f"{fwhere}: rows {fdoc.get('rows')} disagrees "
                         f"with the commit manifest ({want['rows']})")
    return probs


def validate_fleet_report(doc, require_healthy: bool = False) -> List[str]:
    """Schema problems in a ``_fleet/fleet_report.json``
    (sofa_tpu/analysis/fleet.py analyze) — the served cross-run pass
    artifact behind ``GET /v1/<tenant>/fleet``.  ``require_healthy``
    additionally fails on any failed pass — the CI-gate mode."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["fleet report is not a JSON object"]
    if doc.get("schema") != _FLEET_REPORT_SCHEMA:
        probs.append(f"schema: expected {_FLEET_REPORT_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("version") != _FLEET_REPORT_VERSION:
        probs.append(f"version: expected {_FLEET_REPORT_VERSION}, "
                     f"got {doc.get('version')!r}")
    sha = doc.get("commit_sha")
    if not isinstance(sha, str) or not sha:
        probs.append("commit_sha: missing (the /v1/fleet ETag key)")
    for key in ("catalog_gen", "runs", "ingest_events", "features_rows"):
        v = doc.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            probs.append(f"{key}: missing or not a non-negative int")
    sched = doc.get("schedule")
    if not isinstance(sched, list) or any(
            not isinstance(w, list)
            or any(not isinstance(n, str) for n in w) for w in sched):
        probs.append("schedule: not a list of name-list waves")
        sched = []
    order = doc.get("order")
    if not isinstance(order, list) or \
            any(not isinstance(n, str) for n in order):
        probs.append("order: not a list of pass names")
        order = []
    passes = doc.get("passes")
    if not isinstance(passes, dict):
        probs.append("passes: missing per-pass ledger")
        passes = {}
    if sorted(passes) != sorted(order):
        probs.append("passes: ledger disagrees with order "
                     f"({sorted(passes)} vs {sorted(order)})")
    scheduled = {n for w in sched for n in w}
    for name, ent in sorted(passes.items()):
        where = f"passes.{name}"
        if not isinstance(ent, dict):
            probs.append(f"{where}: not an object")
            continue
        if ent.get("status") not in _FLEET_PASS_STATUSES:
            probs.append(f"{where}.status: {ent.get('status')!r} not in "
                         f"{_FLEET_PASS_STATUSES}")
        if not isinstance(ent.get("fingerprint"), str) \
                or not ent.get("fingerprint"):
            probs.append(f"{where}.fingerprint: missing contract "
                         "fingerprint")
        wave = ent.get("wave")
        if not isinstance(wave, int) or isinstance(wave, bool) or wave < 0:
            probs.append(f"{where}.wave: missing or not a non-negative "
                         "int")
        if ent.get("status") == "ok" and \
                not isinstance(ent.get("report"), (dict, type(None))):
            probs.append(f"{where}.report: not an object or null")
        if ent.get("status") == "failed" and \
                not isinstance(ent.get("error"), str):
            probs.append(f"{where}.error: a failed pass must carry its "
                         "error")
        if name not in scheduled:
            probs.append(f"{where}: absent from the resolved schedule")
    feats = doc.get("features")
    if not isinstance(feats, dict) or any(
            not isinstance(k, str) or not _is_num(v)
            for k, v in feats.items()):
        probs.append("features: not a flat name -> number map")
    if require_healthy:
        for name, ent in sorted(passes.items()):
            if isinstance(ent, dict) and ent.get("status") == "failed":
                probs.append(f"gate: fleet pass {name} failed"
                             + (f" ({ent['error']})"
                                if ent.get("error") else ""))
    return probs


def validate_fleet_state(doc) -> List[str]:
    """Schema problems in a ``_fleet/fleet_state.json``
    (sofa_tpu/analysis/fleet.py) — the fold-state memo written LAST as
    the incremental engine's commit point."""
    probs: List[str] = []
    if not isinstance(doc, dict):
        return ["fleet state is not a JSON object"]
    if doc.get("schema") != _FLEET_STATE_SCHEMA:
        probs.append(f"schema: expected {_FLEET_STATE_SCHEMA!r}, "
                     f"got {doc.get('schema')!r}")
    if doc.get("version") != _FLEET_STATE_VERSION:
        probs.append(f"version: expected {_FLEET_STATE_VERSION}, "
                     f"got {doc.get('version')!r}")
    if not isinstance(doc.get("commit_sha"), str) \
            or not doc.get("commit_sha"):
        probs.append("commit_sha: missing memoization key")
    for key in ("catalog_gen", "chunk_rows"):
        v = doc.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            probs.append(f"{key}: missing or not a non-negative int")
    fams = doc.get("families")
    if not isinstance(fams, dict):
        probs.append("families: missing append-only family signatures")
        fams = {}
    for name, ent in sorted(fams.items()):
        if not isinstance(ent, dict) \
                or not isinstance(ent.get("rows"), int) \
                or isinstance(ent.get("rows"), bool) \
                or not isinstance(ent.get("chunks"), list) \
                or any(not isinstance(s, str) for s in ent["chunks"]):
            probs.append(f"families.{name}: needs int rows + a chunk-sha "
                         "list")
    passes = doc.get("passes")
    if not isinstance(passes, dict):
        probs.append("passes: missing per-pass memo")
        passes = {}
    for name, ent in sorted(passes.items()):
        if not isinstance(ent, dict) \
                or not isinstance(ent.get("fingerprint"), str):
            probs.append(f"passes.{name}: needs a contract fingerprint")
            continue
        feats = ent.get("features")
        if not isinstance(feats, list) or any(
                not (isinstance(p, list) and len(p) == 2
                     and isinstance(p[0], str) and _is_num(p[1]))
                for p in feats):
            probs.append(f"passes.{name}.features: not a list of "
                         "[name, value] pairs")
    return probs


def _check_fleet_dir(root: str) -> List[str]:
    """Validate an archive root's ``_fleet/`` tier: report + memo when
    present.  An absent dir (or a report ahead of its memo — the crash
    window the next analyze converges) is healthy; unreadable or
    schema-invalid documents are not."""
    fdir = os.path.join(root, _FLEET_DIR)
    if not os.path.isdir(fdir):
        return []
    probs: List[str] = []
    docs = {}
    for name, validate in ((_FLEET_REPORT_NAME, validate_fleet_report),
                           (_FLEET_STATE_NAME, validate_fleet_state)):
        path = os.path.join(fdir, name)
        if not os.path.isfile(path):
            continue
        where = f"{_FLEET_DIR}/{name}"
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            probs.append(f"{where}: unreadable ({e})")
            continue
        docs[name] = doc
        probs.extend(f"{where}: {p}" for p in validate(doc))
    report = docs.get(_FLEET_REPORT_NAME)
    state = docs.get(_FLEET_STATE_NAME)
    if isinstance(state, dict) and not isinstance(report, dict):
        # the inverse tear (memo ahead of report) cannot come from the
        # report-first write order — a memo with no report is damage
        probs.append(f"{_FLEET_DIR}/{_FLEET_STATE_NAME}: memo present "
                     "but the report is missing — the write order is "
                     "report first, memo last")
    return probs


def _check_live_offsets(logdir: str) -> List[str]:
    path = os.path.join(logdir, _LIVE_OFFSETS_NAME)
    if not os.path.isfile(path):
        return []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{_LIVE_OFFSETS_NAME}: unreadable ({e})"]
    return [f"{_LIVE_OFFSETS_NAME}: {p}"
            for p in validate_live_offsets(doc)]


def check_path(path: str, require_healthy: bool = False) -> int:
    """0 valid / 1 invalid / 2 missing; problems go to stderr.  A path
    that is (or holds only) a ``regress_verdict.json`` /
    ``whatif_report.json``, or whose document carries one of their
    schemas, is validated as that document instead.  A logdir whose
    `sofa live` offset ledger is present gets that validated too."""
    live_probs: List[str] = []
    if os.path.isdir(path) and os.path.isfile(
            os.path.join(path, _ARCHIVE_MARKER_NAME)):
        # an archive root: the document to validate is its columnar
        # catalog index (absent index = healthy, queries scan), plus
        # the merged fleet trace when the tier has exported one and the
        # fleet-pass report/memo when an analyze has committed one
        probs = _check_archive_index(path) + _check_fleet_trace(path) \
            + _check_fleet_dir(path)
        for p in probs:
            print(f"manifest_check: archive index: {p}", file=sys.stderr)
        if not probs:
            has = os.path.isfile(os.path.join(
                path, _ARCHIVE_INDEX_DIR, _ARCHIVE_INDEX_COMMIT))
            print(f"manifest_check: OK ({path}; archive index: "
                  f"{'committed' if has else 'absent (scan mode)'})")
        return 1 if probs else 0
    if os.path.isdir(path):
        live_probs = _check_live_offsets(path) + _check_frame_indexes(path)
        mpath = os.path.join(path, MANIFEST_NAME)
        if not os.path.isfile(mpath):
            for alt in ("regress_verdict.json", "whatif_report.json"):
                if os.path.isfile(os.path.join(path, alt)):
                    mpath = os.path.join(path, alt)
                    break
        path = mpath
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        print(f"manifest_check: cannot read {path}: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"manifest_check: {path} is not JSON: {e}", file=sys.stderr)
        return 1
    if isinstance(doc, dict) and doc.get("schema") == _INVENTORY_SCHEMA:
        probs = validate_inventory(doc, require_healthy=require_healthy)
        for p in probs:
            print(f"manifest_check: inventory: {p}", file=sys.stderr)
        if not probs:
            print(f"manifest_check: OK ({path}; "
                  f"{(doc.get('counts') or {}).get('artifacts')} "
                  f"artifact(s), ok={doc.get('ok')})")
        return 1 if probs else 0
    if isinstance(doc, dict) and doc.get("schema") == _PROTOCOL_SCHEMA:
        probs = validate_protocol_inventory(doc,
                                            require_healthy=require_healthy)
        for p in probs:
            print(f"manifest_check: protocol: {p}", file=sys.stderr)
        if not probs:
            print(f"manifest_check: OK ({path}; "
                  f"{(doc.get('counts') or {}).get('routes')} "
                  f"route(s), ok={doc.get('ok')})")
        return 1 if probs else 0
    if isinstance(doc, dict) and doc.get("schema") == _SLO_SCHEMA:
        probs = validate_slo_verdict(doc, require_passing=require_healthy)
        for p in probs:
            print(f"manifest_check: slo: {p}", file=sys.stderr)
        if not probs:
            print(f"manifest_check: OK ({path}; slo: "
                  f"{'ok' if doc.get('ok') else 'BREACHING '}"
                  + ("" if doc.get("ok")
                     else ",".join(doc.get("breaching") or [])) + ")")
        return 1 if probs else 0
    if isinstance(doc, dict) and doc.get("schema") == _METRICS_SCHEMA:
        probs = validate_fleet_metrics(doc)
        for p in probs:
            print(f"manifest_check: metrics: {p}", file=sys.stderr)
        if not probs:
            print(f"manifest_check: OK ({path}; metrics: worker "
                  f"{doc.get('worker')}, scrape_seq "
                  f"{doc.get('scrape_seq')})")
        return 1 if probs else 0
    if isinstance(doc, dict) and doc.get("schema") == _WHATIF_SCHEMA:
        probs = validate_whatif(doc, require_healthy=require_healthy)
        for p in probs:
            print(f"manifest_check: whatif: {p}", file=sys.stderr)
        if not probs:
            print(f"manifest_check: OK ({path}; identity gate: "
                  f"{(doc.get('calibration') or {}).get('verdict')})")
        return 1 if probs else 0
    if isinstance(doc, dict) and doc.get("schema") == _VERDICT_SCHEMA:
        probs = validate_verdict(doc, require_passing=require_healthy)
        for p in probs:
            print(f"manifest_check: verdict: {p}", file=sys.stderr)
        if not probs:
            print(f"manifest_check: OK ({path}; verdict: "
                  f"{doc.get('verdict')})")
        return 1 if probs else 0
    if isinstance(doc, dict) and doc.get("schema") == _FLEET_REPORT_SCHEMA:
        probs = validate_fleet_report(doc, require_healthy=require_healthy)
        for p in probs:
            print(f"manifest_check: fleet report: {p}", file=sys.stderr)
        if not probs:
            print(f"manifest_check: OK ({path}; fleet report: "
                  f"{len(doc.get('order') or [])} pass(es) at commit "
                  f"{str(doc.get('commit_sha'))[:12]})")
        return 1 if probs else 0
    if isinstance(doc, dict) and doc.get("schema") == _FLEET_STATE_SCHEMA:
        probs = validate_fleet_state(doc)
        for p in probs:
            print(f"manifest_check: fleet state: {p}", file=sys.stderr)
        if not probs:
            print(f"manifest_check: OK ({path}; fleet memo at commit "
                  f"{str(doc.get('commit_sha'))[:12]})")
        return 1 if probs else 0
    probs = validate_manifest(doc, require_healthy=require_healthy) \
        + live_probs
    for p in probs:
        print(f"manifest_check: {p}", file=sys.stderr)
    if not probs:
        verbs = ",".join(v for v in _KNOWN_VERBS if v in doc.get("runs", {}))
        print(f"manifest_check: OK ({path}; verbs: {verbs or '?'})")
    return 1 if probs else 0


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="logdir or run_manifest.json path")
    p.add_argument("--require-healthy", action="store_true",
                   help="also fail on failed/killed collectors or logged "
                        "error lines")
    args = p.parse_args(argv)
    return check_path(args.path, require_healthy=args.require_healthy)


if __name__ == "__main__":
    sys.exit(main())
