"""The fleet-pass engine — incremental cross-run analysis over ``_index/``.

Second registry domain beside the per-run analysis passes: a
``@fleet_pass`` reads declared slices of the archive's column families
(``catalog``/``features``, plus point lookups into ``runs``) and folds
them into a schema-versioned fleet report under ``<root>/_fleet/``.
Scheduling, contract validation and the determinism discipline are the
per-run registry's (``sofa_tpu/analysis/registry.py``): literal
contracts, Kahn waves from the declarations alone, private feature
buffers merged in canonical order — so ``--jobs 1`` and ``--jobs 4``
produce byte-identical reports.

The perf core is **incrementality** (the ``_index/`` suffix discipline
lifted to analysis).  Every pass is a *fold*::

    @fleet_pass(name=..., reads_frames=("features",),
                reads_columns=("features.name", "features.value"), ...)
    def my_pass(state, tables, ctx, features):
        ...
        return {"state": new_state, "report": section}

``state`` is the pass's previous JSON state (None on a cold run) and
``tables`` holds exactly the declared families projected to the declared
columns — on a cold run every row, on a warm run only the rows from the
first *dirty* index chunk onward (the committed full chunks before it
are immutable under append, so their folded partials are reusable
verbatim).  ``fold_chunks`` is the canonical state shape: one partial
per index chunk, combined at render time with ``math.fsum`` — chunk
partials are a pure function of the chunk bytes and ``fsum`` is exactly
rounded, so a warm fold is byte-identical to a cold recompute.

Results are memoized in ``_fleet/fleet_state.json`` keyed on the index
``commit_sha`` and each pass's contract fingerprint; a refresh after N
new ingests touches only the delta chunks.  A ``catalog.gen`` bump or a
fingerprint change falls back to a full recompute — never a silently
stale fold.  Layout::

    _fleet/fleet_report.json   the served artifact (schema
                               ``sofa_tpu/fleet_report`` v1): per-pass
                               report sections + fleet features, stamped
                               with the index commit sha it covers (the
                               /v1/<tenant>/fleet ETag)
    _fleet/fleet_state.json    the memo (schema ``sofa_tpu/fleet_state``
                               v1, written LAST): per-pass fold state +
                               fingerprints + the per-family chunk shas
                               the next delta window is validated
                               against

Both land via fsync'd ``atomic_write`` with no wall clock anywhere, so a
SIGKILL between the two (the ``SOFA_FLEET_EXIT_AFTER`` chaos knob)
leaves a report the next run reproduces byte-identically.  Everything
under ``_fleet/`` is derived state: :func:`drop` + :func:`analyze` is
always safe, and the tier's post-drain refresh hook keeps served
tenants warm (docs/FLEET.md).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from sofa_tpu.analysis.features import Features
from sofa_tpu.analysis.registry import (
    PassSpec,
    RegistryError,
    _as_tuple,
    resolve_schedule,
)
from sofa_tpu.concurrency import Guard
from sofa_tpu.printing import print_title, print_warning

FLEET_DIR_NAME = "_fleet"
FLEET_REPORT_NAME = "fleet_report.json"
FLEET_REPORT_SCHEMA = "sofa_tpu/fleet_report"
# Bumps on any BREAKING report-shape change (the run-manifest policy,
# docs/OBSERVABILITY.md); additive keys do not.
FLEET_REPORT_VERSION = 1

FLEET_STATE_NAME = "fleet_state.json"
FLEET_STATE_SCHEMA = "sofa_tpu/fleet_state"
FLEET_STATE_VERSION = 1

#: Families a fleet pass may read as TABLES.  The refresh builds both by
#: appending conformed suffix rows to the committed prefix, so committed
#: full chunks are immutable and a delta window is sound.  The ``runs``
#: family is rebuilt (deduped, re-sorted) every refresh — passes reach
#: it through ``ctx.runs_meta`` point lookups only, never as a delta.
APPEND_ONLY_FAMILIES = ("catalog", "features")

#: Part of every contract fingerprint — bump to force a fleet-wide full
#: recompute when the fold/render semantics change without any
#: declaration changing.
ENGINE_FOLD_VERSION = 1


class FleetError(RegistryError):
    """A broken fleet-pass declaration or an unusable fleet substrate."""


# Mirrors the analysis registry's guard discipline: decorators at import
# time, scoped()/clear() from tests and chaos cells (SL019).
_lock = Guard("analysis.fleet",
              protects=("_registry", "_declared_builtins"))
_registry: Dict[str, PassSpec] = {}
_declared_builtins: Dict[str, PassSpec] = {}
_seq = 0


def fleet_dir(root: str) -> str:
    return os.path.join(root, FLEET_DIR_NAME)


def report_path(root: str) -> str:
    return os.path.join(root, FLEET_DIR_NAME, FLEET_REPORT_NAME)


def state_path(root: str) -> str:
    return os.path.join(root, FLEET_DIR_NAME, FLEET_STATE_NAME)


def _chaos_tick() -> None:
    """``SOFA_FLEET_EXIT_AFTER=<n>`` hard-exits at the n-th fleet commit
    point of this process — between the report write and the memo
    write, the widest crash window: the kill-mid-fleet-analyze chaos
    cell (tools/chaos_matrix.py) drives it to prove the re-run converges
    to the byte-identical artifact."""
    try:
        n = int(os.environ.get("SOFA_FLEET_EXIT_AFTER", "0"))
    except ValueError:
        n = 0
    if not n:
        return
    count = int(os.environ.get("_SOFA_FLEET_TICKS", "0")) + 1
    os.environ["_SOFA_FLEET_TICKS"] = str(count)
    if count >= n:
        os._exit(86)


# ---------------------------------------------------------------------------
# Registration (the @fleet_pass domain).
# ---------------------------------------------------------------------------

def _family_columns() -> Dict[str, List[str]]:
    from sofa_tpu.archive import index as aindex

    return {aindex.CATALOG_FAMILY: aindex.CATALOG_COLUMNS,
            aindex.RUNS_FAMILY: aindex.RUNS_COLUMNS,
            aindex.FEATURES_FAMILY: aindex.FEATURE_COLUMNS}


def register_fleet_pass(fn: Callable, *, name: str, order: int = 0,
                        reads_frames=(), reads_columns=(),
                        reads_features=(), provides_features=(),
                        provides_artifacts=(), after=(),
                        enabled_when=()) -> PassSpec:
    """Register a fleet fold ``fn(state, tables, ctx, features)``.

    The contract vocabulary is the analysis domain's, re-anchored on the
    index: ``reads_frames`` names column FAMILIES, ``reads_columns``
    entries are family-qualified (``"features.value"``) and validated
    against the pinned family schemas — sofa-lint SL010 enforces the
    body against the same declarations.  ``after`` edges may only name
    other FLEET passes; an edge into the per-run analysis domain is a
    category error the lint (SL012) and this validation both reject."""
    global _seq
    from sofa_tpu.analysis import registry as analysis_registry

    if not name or not isinstance(name, str):
        raise FleetError(f"fleet pass name must be a non-empty string: "
                         f"{name!r}")
    fam_cols = _family_columns()
    spec_frames = _as_tuple(reads_frames,
                            f"fleet pass {name}: reads_frames")
    unknown = [f for f in spec_frames if f not in fam_cols]
    if unknown:
        raise FleetError(
            f"fleet pass {name}: reads_frames {unknown} not an index "
            f"family {tuple(sorted(fam_cols))} — fix the declaration")
    spec_cols = _as_tuple(reads_columns,
                          f"fleet pass {name}: reads_columns")
    for qual in spec_cols:
        fam, _, col = qual.partition(".")
        if fam not in spec_frames or col not in fam_cols.get(fam, ()):
            raise FleetError(
                f"fleet pass {name}: reads_columns entry {qual!r} is not "
                "a declared-family column (spell it <family>.<column> "
                "against the pinned schemas in archive/index.py)")
    spec_after = _as_tuple(after, f"fleet pass {name}: after")
    for dep in spec_after:
        if analysis_registry.get(dep) is not None and dep not in _registry:
            raise FleetError(
                f"fleet pass {name}: after={dep!r} crosses into the "
                "per-run analysis domain — fleet passes schedule only "
                "against fleet passes")
    with _lock:
        if name in _registry:
            raise FleetError(f"fleet pass {name!r} is already registered "
                             f"(by {_registry[name].origin})")
        _seq += 1
        spec = PassSpec(
            name=name, fn=fn,
            order=order if order else 1000 + _seq,
            reads_frames=spec_frames,
            reads_columns=spec_cols,
            reads_features=_as_tuple(
                reads_features, f"fleet pass {name}: reads_features"),
            provides_features=_as_tuple(
                provides_features,
                f"fleet pass {name}: provides_features"),
            provides_artifacts=_as_tuple(
                provides_artifacts,
                f"fleet pass {name}: provides_artifacts"),
            after=spec_after,
            enabled_when=_as_tuple(
                enabled_when, f"fleet pass {name}: enabled_when"),
            origin="fleet", seq=_seq)
        _registry[name] = spec
        if (getattr(fn, "__module__", "") or "").startswith("sofa_tpu."):
            _declared_builtins[name] = spec
    return spec


def fleet_pass(**contract):
    """Decorator form of :func:`register_fleet_pass` — THE spelling
    sofa-lint's SL010–SL013 extract fleet contracts from; keep every
    argument a literal."""
    def deco(fn: Callable) -> Callable:
        register_fleet_pass(fn, **contract)
        return fn
    return deco


@contextlib.contextmanager
def scoped():
    """Snapshot the fleet registry and restore on exit (tests, chaos)."""
    with _lock:
        before = dict(_registry)
    try:
        yield
    finally:
        with _lock:
            _registry.clear()
            _registry.update(before)


def clear() -> None:
    with _lock:
        _registry.clear()


def registered() -> List[PassSpec]:
    with _lock:
        specs = list(_registry.values())
    return sorted(specs, key=lambda s: (s.order, s.seq))


def get(name: str) -> Optional[PassSpec]:
    with _lock:
        return _registry.get(name)


def load_builtin_passes() -> None:
    """Import the builtin fleet passes (idempotent; the declaration
    archive restores them after a ``clear``/``scoped``, exactly the
    analysis registry's rule)."""
    import sofa_tpu.analysis.fleet_passes  # noqa: F401
    with _lock:
        for name, spec in _declared_builtins.items():
            _registry.setdefault(name, spec)


def fingerprint(spec: PassSpec) -> str:
    """The contract fingerprint a pass's memoized state is keyed on: a
    pure function of the DECLARATION (plus the engine fold version), so
    editing any contract — or bumping ENGINE_FOLD_VERSION — forces that
    pass onto the full-recompute path."""
    doc = {"name": spec.name, "order": spec.order,
           "reads_frames": list(spec.reads_frames),
           "reads_columns": list(spec.reads_columns),
           "reads_features": list(spec.reads_features),
           "provides_features": list(spec.provides_features),
           "after": list(spec.after),
           "fold": ENGINE_FOLD_VERSION}
    return hashlib.sha1(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()


# ---------------------------------------------------------------------------
# The fold substrate.
# ---------------------------------------------------------------------------

@dataclass
class FleetContext:
    """What a fleet pass sees beside its tables: where the fold window
    starts (``base``, the first index-chunk ordinal each provided table
    begins at) and bounded point lookups into the runs family."""

    root: str
    commit: dict
    mode: str                      # "full" | "delta"
    chunk_rows: int
    base: Dict[str, int] = field(default_factory=dict)
    _meta_cache: Dict[str, dict] = field(default_factory=dict)
    _meta_absent: set = field(default_factory=set)

    def runs_meta(self, run_ids) -> Dict[str, dict]:
        """Provenance rows for a SET of runs — O(result) projected
        lookups into the runs family (newest ingest wins).  Lookups at
        *render* time are byte-identity safe (warm and cold render
        against the same commit); a fold baking lookups into memoized
        partials accepts that a re-ingest which CHANGES a run's
        label/host re-attributes its old rows only on the next full
        recompute.

        Memoized per context (the commit is immutable for the life of
        an analyze): a full fan-out's per-chunk folds ask for largely
        disjoint id sets, and without the cache each call re-read the
        whole run column."""
        from sofa_tpu.archive import index as aindex

        ids = set(run_ids)
        miss = ids - self._meta_cache.keys() - self._meta_absent
        if miss:
            got = aindex._runs_meta(self.root, self.commit, miss)
            self._meta_cache.update(got)
            self._meta_absent.update(miss - got.keys())
        return {r: self._meta_cache[r] for r in ids
                if r in self._meta_cache}


def fold_chunks(parts: Dict[str, dict], tbl, base: int, chunk_rows: int,
                fn: Callable) -> None:
    """The canonical incremental state shape: one partial per index
    chunk, keyed by the chunk ordinal (as a string — JSON state).

    Drops every partial at or past ``base`` (the store rewrote its tail
    chunk, so those partials are stale) and recomputes one partial per
    ``chunk_rows`` slice of ``tbl`` — slices align with the store's
    fixed chunk boundaries, so a partial is a pure function of the chunk
    bytes and a warm fold reproduces the cold fold's partials exactly.
    Combine partials at render time with :func:`math.fsum` (exactly
    rounded, hence order- and split-invariant)."""
    for key in [k for k in parts if int(k) >= base]:
        del parts[key]
    for i in range((tbl.num_rows + chunk_rows - 1) // chunk_rows):
        parts[str(base + i)] = fn(tbl.slice(i * chunk_rows, chunk_rows))


def parts_in_order(parts: Dict[str, dict]) -> List[dict]:
    """Chunk partials in chunk order — combine their per-chunk sums with
    ``math.fsum`` (exactly rounded, hence split-invariant: a warm fold's
    partial list is identical to a cold recompute's, so so are the
    combined totals)."""
    return [parts[k] for k in sorted(parts, key=int)]


# ---------------------------------------------------------------------------
# Memo + report I/O.
# ---------------------------------------------------------------------------

def load_report(root: str) -> Optional[dict]:
    """The committed fleet report, or None when absent/unreadable/not a
    v1 doc (the /v1/<tenant>/fleet route then answers 404 and the board
    falls back)."""
    try:
        with open(report_path(root)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) \
            or doc.get("schema") != FLEET_REPORT_SCHEMA \
            or doc.get("version") != FLEET_REPORT_VERSION:
        return None
    return doc


def _load_state(root: str) -> Optional[dict]:
    try:
        with open(state_path(root)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) \
            or doc.get("schema") != FLEET_STATE_SCHEMA \
            or doc.get("version") != FLEET_STATE_VERSION:
        return None
    return doc


def drop(root: str) -> None:
    """Remove the fleet tier wholesale — everything under ``_fleet/`` is
    derived from the index; the next :func:`analyze` rebuilds it."""
    shutil.rmtree(fleet_dir(root), ignore_errors=True)


def verify(root: str) -> List[str]:
    """fsck's view: a PRESENT but unreadable report/memo is damage; an
    absent or torn-in-between ``_fleet/`` (report ahead of memo — the
    chaos window) is a healthy pending state the next analyze converges.
    Returns root-relative damage paths."""
    bad: List[str] = []
    if not os.path.isdir(fleet_dir(root)):
        return bad
    if os.path.exists(report_path(root)) and load_report(root) is None:
        bad.append(f"{FLEET_DIR_NAME}/{FLEET_REPORT_NAME}")
    if os.path.exists(state_path(root)) and _load_state(root) is None:
        bad.append(f"{FLEET_DIR_NAME}/{FLEET_STATE_NAME}")
    return bad


# ---------------------------------------------------------------------------
# The incremental engine.
# ---------------------------------------------------------------------------

def _family_index(root: str, family: str) -> Optional[dict]:
    from sofa_tpu import frames
    from sofa_tpu.archive import index as aindex

    return frames._load_index(os.path.join(
        aindex.family_dir(root, family), frames.FRAME_INDEX_NAME))


def _family_sig(root: str) -> Dict[str, dict]:
    """Per-append-only-family {rows, chunk shas} — what the memo records
    and the next run's delta window is validated against."""
    sig: Dict[str, dict] = {}
    for family in APPEND_ONLY_FAMILIES:
        doc = _family_index(root, family) or {}
        sig[family] = {
            "rows": int(doc.get("rows") or 0),
            "chunks": [c.get("sha") for c in doc.get("chunks") or []],
        }
    return sig


def _delta_base(prev: dict, cur: dict, chunk_rows: int) -> Optional[int]:
    """The first dirty chunk ordinal for one family, or None when the
    committed prefix moved (a full rebuild changed history) and only a
    full recompute is sound.  Full chunks before the memo's tail are
    immutable under append — their shas must match exactly."""
    prev_rows = int(prev.get("rows") or 0)
    if int(cur.get("rows") or 0) < prev_rows:
        return None
    base = prev_rows // chunk_rows
    prev_chunks = prev.get("chunks") or []
    cur_chunks = cur.get("chunks") or []
    if len(cur_chunks) < base or prev_chunks[:base] != cur_chunks[:base]:
        return None
    return base


def _pass_columns(spec: PassSpec, family: str) -> Optional[List[str]]:
    cols = [c.split(".", 1)[1] for c in spec.reads_columns
            if c.startswith(family + ".")]
    return cols or None


def _read_window(root: str, family: str, base: int,
                 columns: Optional[List[str]]):
    """The family's rows from chunk ``base`` onward as one Arrow table —
    chunk boundaries preserved (the concat keeps each feather chunk a
    distinct buffer), so downstream per-chunk slices see the exact
    standalone-chunk data a cold run sees."""
    import pyarrow as pa

    from sofa_tpu import frames
    from sofa_tpu.archive import index as aindex

    handle = frames.open_chunk_store(aindex.family_dir(root, family))
    if handle is None:
        return pa.table({c: pa.array([], type=pa.string())
                         for c in (columns or [])})
    if base <= 0:
        return handle.read_table(columns=columns)
    n = len(handle.index.get("chunks") or [])
    tables = [handle.read_chunk_table(i, columns=columns)
              for i in range(base, n)]
    if not tables:
        return handle.read_chunk_table(0, columns=columns).slice(0, 0)
    return pa.concat_tables(tables)


def analyze(root: str, jobs: int = 0, select=None,
            refresh_index: bool = True) -> dict:
    """Run every registered fleet pass over the archive index and commit
    ``_fleet/``; returns the report doc with a transient ``_stats`` key
    (per-pass mode + wall, not part of the artifact — the artifact
    carries no wall clock, so warm/cold/resumed runs are byte-identical).

    Modes per pass, cheapest wins:

    * ``memo``  — index commit sha and contract fingerprint both match
      the memo: the pass does not run at all; its report section and
      fleet features replay from the memo.
    * ``delta`` — fingerprint matches and every table family's committed
      chunk prefix is intact: the pass folds only the rows from the
      first dirty chunk onward over its previous state.
    * ``full``  — anything else (first run, ``catalog.gen`` bump,
      contract edit, rebuilt history): state starts from None over every
      row.

    A wholly-memoized run whose on-disk report already matches is a
    no-op: 0 bytes written, untouched mtimes (the index refresh rule).
    """
    from sofa_tpu import pool
    from sofa_tpu.archive import index as aindex

    if not aindex.available():
        raise FleetError("fleet analyze needs the columnar index "
                         "(pyarrow) — unavailable here")
    t_total = time.perf_counter()
    commit = aindex.refresh(root, jobs=jobs) if refresh_index \
        else aindex.load_commit(root)
    if commit is None or not aindex.is_current(root, commit):
        raise FleetError("no current archive index under "
                         f"{root!r} — ingest something (or run "
                         "`sofa archive fsck --repair`) first")
    commit = {k: v for k, v in commit.items() if k != "_stats"}

    load_builtin_passes()
    specs = registered()
    enabled = [s for s in specs
               if select is None or s.name in select]
    waves = resolve_schedule(enabled, ambient=())
    wave_of = {s.name: i for i, wave in enumerate(waves) for s in wave}
    fps = {s.name: fingerprint(s) for s in enabled}
    order = [s.name for s in sorted(enabled,
                                    key=lambda s: (s.order, s.seq))]

    memo = _load_state(root)
    cur_sig = _family_sig(root)
    chunk_rows = int((_family_index(root, "catalog") or {})
                     .get("chunk_rows") or aindex.INDEX_CHUNK_ROWS)
    memo_ok = memo is not None \
        and memo.get("catalog_gen") == commit.get("catalog_gen") \
        and int(memo.get("chunk_rows") or 0) == chunk_rows
    bases: Dict[str, Optional[int]] = {}
    for family in APPEND_ONLY_FAMILIES:
        prev = ((memo or {}).get("families") or {}).get(family) or {}
        bases[family] = _delta_base(prev, cur_sig[family], chunk_rows) \
            if memo_ok else None
    memo_passes = (memo or {}).get("passes") or {} if memo_ok else {}
    memo_hit = memo_ok and memo.get("commit_sha") == commit["commit_sha"]

    plan: Dict[str, str] = {}
    for s in enabled:
        prev = memo_passes.get(s.name) or {}
        if prev.get("fingerprint") != fps[s.name]:
            plan[s.name] = "full"
        elif memo_hit:
            plan[s.name] = "memo"
        elif all(bases.get(f) is not None for f in s.reads_frames
                 if f in APPEND_ONLY_FAMILIES):
            plan[s.name] = "delta"
        else:
            plan[s.name] = "full"

    # short-circuit: everything memoized AND the on-disk report already
    # covers this commit with these contracts — touch nothing
    existing = load_report(root)
    if existing is not None \
            and all(m == "memo" for m in plan.values()) \
            and existing.get("commit_sha") == commit["commit_sha"] \
            and existing.get("order") == order \
            and all((existing.get("passes") or {}).get(n, {})
                    .get("fingerprint") == fps[n] for n in order):
        existing["_stats"] = {
            "noop": True, "jobs": 0,
            "wall_s": round(time.perf_counter() - t_total, 6),
            "passes": {n: {"mode": "memo", "wall_s": 0.0} for n in order}}
        return existing

    # shared table cache: one read per (family, base), union columns —
    # passes then select their declared projection
    union_cols: Dict[Tuple[str, int], set] = {}
    for s in enabled:
        if plan[s.name] == "memo":
            continue
        for family in s.reads_frames:
            if family not in APPEND_ONLY_FAMILIES:
                continue
            base = 0 if plan[s.name] == "full" else bases[family]
            key = (family, int(base or 0))
            cols = _pass_columns(s, family)
            union_cols.setdefault(key, set()).update(
                cols or _family_columns()[family])
    cache = {key: _read_window(root, family, base, sorted(cols))
             for (family, base), cols in union_cols.items()
             for key in [(family, base)]}

    jobs_n = pool.resolve_jobs(jobs)
    report_entries: Dict[str, dict] = {}
    stats_passes: Dict[str, dict] = {}
    new_memo_passes: Dict[str, dict] = {}
    buffers: Dict[str, Features] = {}
    completed: List[Features] = []
    spec_of = {s.name: s for s in enabled}

    def run_one(spec: PassSpec) -> None:
        mode = plan[spec.name]
        entry = {"origin": spec.origin, "wave": wave_of[spec.name],
                 "fingerprint": fps[spec.name]}
        t0 = time.perf_counter()
        prev = memo_passes.get(spec.name) or {}
        if mode == "memo":
            buf = Features()
            for fname, fvalue in prev.get("features") or []:
                buf.add(fname, fvalue)
            buffers[spec.name] = buf
            entry.update(status="ok", report=prev.get("report"))
            report_entries[spec.name] = entry
            new_memo_passes[spec.name] = prev
            stats_passes[spec.name] = {
                "mode": mode,
                "wall_s": round(time.perf_counter() - t0, 6)}
            return
        state = None if mode == "full" else prev.get("state")
        ctx = FleetContext(root=root, commit=commit, mode=mode,
                           chunk_rows=chunk_rows)
        tables = {}
        for family in spec.reads_frames:
            if family not in APPEND_ONLY_FAMILIES:
                continue
            base = 0 if mode == "full" else int(bases[family] or 0)
            cols = _pass_columns(spec, family)
            tbl = cache[(family, base)]
            tables[family] = tbl.select(cols) if cols else tbl
            ctx.base[family] = base
        view = _PassView(completed, buffers, spec.name)
        try:
            out = spec.fn(state, tables, ctx, view) or {}
            entry.update(status="ok", report=out.get("report"))
            new_memo_passes[spec.name] = {
                "fingerprint": fps[spec.name],
                "state": out.get("state"),
                "report": out.get("report"),
                "features": [[n, v] for n, v in view.buf._rows],
            }
        except Exception as e:  # noqa: BLE001 — per-pass fault isolation
            print_warning(f"fleet pass {spec.name}: {e}")
            entry.update(status="failed",
                         error=f"{type(e).__name__}: {e}"[:300])
        report_entries[spec.name] = entry
        stats_passes[spec.name] = {
            "mode": mode, "wall_s": round(time.perf_counter() - t0, 6)}

    for wave in waves:
        pool.thread_map(run_one, wave, jobs_n)
        completed = [buffers[n] for n in order if n in buffers]

    features: Dict[str, float] = {}
    for name in order:
        buf = buffers.get(name)
        if buf is not None:
            for fname, fvalue in buf._rows:
                features[fname] = fvalue

    report = {
        "schema": FLEET_REPORT_SCHEMA, "version": FLEET_REPORT_VERSION,
        "commit_sha": commit["commit_sha"],
        "catalog_gen": commit.get("catalog_gen"),
        "runs": commit.get("runs"),
        "ingest_events": commit.get("ingest_events"),
        "features_rows": commit.get("features_rows"),
        "schedule": [[s.name for s in wave] for wave in waves],
        "order": order,
        "passes": report_entries,
        "features": features,
    }
    state_doc = {
        "schema": FLEET_STATE_SCHEMA, "version": FLEET_STATE_VERSION,
        "commit_sha": commit["commit_sha"],
        "catalog_gen": commit.get("catalog_gen"),
        "chunk_rows": chunk_rows,
        "families": cur_sig,
        "passes": new_memo_passes,
    }
    # No wall clock in either doc: both are pure functions of the index
    # commit + the contracts, so a killed-and-resumed analyze converges
    # byte-identical.  Report first, memo LAST: a crash in between (the
    # chaos knob) leaves a fresh report and a stale memo — the re-run
    # folds again and rewrites the same bytes.
    from sofa_tpu.durability import atomic_write

    os.makedirs(fleet_dir(root), exist_ok=True)
    with atomic_write(report_path(root), fsync=True) as f:
        json.dump(report, f, indent=1, sort_keys=True)
    _chaos_tick()
    # the memo is machine-read only and holds a partial per chunk —
    # compact one-shot dumps (the C encoder; json.dump streaming to a
    # file never takes it) keep the per-refresh rewrite cheap at fleet
    # scale: the pretty-printed write dominated the warm wall
    with atomic_write(state_path(root), fsync=True) as f:
        f.write(json.dumps(state_doc, sort_keys=True,
                           separators=(",", ":")))
    report["_stats"] = {
        "noop": False, "jobs": jobs_n,
        "wall_s": round(time.perf_counter() - t_total, 6),
        "passes": stats_passes,
    }
    return report


class _PassView:
    """The features facade handed to one fleet pass: writes land in a
    private buffer, reads see completed earlier-wave passes' buffers in
    canonical order — `--jobs` width cannot reorder anything."""

    def __init__(self, completed: List[Features],
                 buffers: Dict[str, Features], name: str):
        self._completed = list(completed)
        self.buf = Features()
        buffers[name] = self.buf

    def add(self, name: str, value: float) -> None:
        self.buf.add(name, value)

    def get(self, name: str) -> Optional[float]:
        for layer in reversed(self._completed + [self.buf]):
            v = layer.get(name)
            if v is not None:
                return v
        return None


def refresh_after_ingest(root: str, jobs: int = 0) -> Optional[dict]:
    """The tier's post-drain hook (archive/tier.py refresh_tenant):
    refresh the fleet report right after the index commit so
    /v1/<tenant>/fleet reads are always warm — degrading to a warning on
    ANY failure, because fleet state is derived and must never fail the
    write path.  ``SOFA_FLEET_REFRESH=0`` opts a deployment out."""
    from sofa_tpu.archive import index as aindex

    if os.environ.get("SOFA_FLEET_REFRESH", "1") == "0" \
            or not aindex.enabled():
        return None
    try:
        return analyze(root, jobs=jobs, refresh_index=False)
    except Exception as e:  # noqa: BLE001 — derived state: degrade, never fail the drain
        print_warning(f"fleet analyze: refresh failed ({e}) — the "
                      "report stays at its last commit until the next "
                      "refresh; `sofa fleet analyze` rebuilds")
        return None


# ---------------------------------------------------------------------------
# `sofa fleet` (the CLI verb).
# ---------------------------------------------------------------------------

def sofa_fleet(cfg, usr_command: str, root: str) -> int:
    """`sofa fleet analyze <root>` — run the fleet passes over an
    archive and print the per-pass table.  Exit 0 on success, 1 when any
    pass failed (the report still commits — fault isolation), 2 on
    usage/substrate errors (no pyarrow, no index, unschedulable)."""
    from sofa_tpu import pool
    from sofa_tpu.telemetry import _table

    if usr_command != "analyze" or not root:
        print_warning("usage: sofa fleet analyze <archive-root>")
        return 2
    if not os.path.isdir(root):
        print_warning(f"sofa fleet: no archive at {root!r}")
        return 2
    try:
        report = analyze(root, jobs=pool.cfg_jobs(cfg))
    except FleetError as e:
        print_warning(str(e))
        return 2
    stats = report.get("_stats") or {}
    print_title(f"SOFA fleet analyze — {len(report['order'])} pass(es), "
                f"commit {str(report['commit_sha'])[:12]}"
                + (" (memoized no-op)" if stats.get("noop") else ""))
    rows = [["pass", "status", "mode", "wall"]]
    failed = 0
    for name in report["order"]:
        entry = (report["passes"] or {}).get(name) or {}
        pstat = (stats.get("passes") or {}).get(name) or {}
        if entry.get("status") != "ok":
            failed += 1
        rows.append([name, entry.get("status", "?"),
                     pstat.get("mode", "?"),
                     f"{pstat.get('wall_s', 0):.3f}s"])
    for line in _table(rows):
        print(line)
    print(f"fleet features: {len(report.get('features') or {})}  "
          f"report: {report_path(root)}  "
          f"total {stats.get('wall_s', 0):.3f}s")
    return 1 if failed else 0
