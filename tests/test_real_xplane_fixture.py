"""Ingest tests against a REAL jax.profiler capture (tests/fixtures/).

Round-1 verdict: every XPlane test built its own protos, so plane-name and
stat-name assumptions were validated circularly.  The checked-in fixture is a
genuine `jax.profiler.start_trace` XSpace (CPU backend host plane, trimmed to
the marker + step annotations + a sample of runtime events); the TPU device
planes still need a real-chip capture, but the proto layout, marker
resolution, and host-plane semantics here come from the real profiler.
"""

import os

import pytest

from sofa_tpu.ingest.xplane import (
    find_marker_offset_ns,
    load_xspace,
    xspace_to_frames,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "cpu_host.xplane.pb")


@pytest.fixture(scope="module")
def xspace():
    return load_xspace(FIXTURE)


def test_real_capture_marker_resolves(xspace):
    off = find_marker_offset_ns(xspace)
    assert off is not None
    # offset = unix_ns - session_ns must be epoch-scale (the session clock
    # starts near zero or at boottime, both far below unix time)
    assert 1e18 < off < 3e18


def test_real_capture_host_plane_ingests(xspace):
    off = find_marker_offset_ns(xspace)
    time_base = (off or 0) / 1e9  # pretend record started at marker time
    frames = xspace_to_frames(xspace, time_base)
    host = frames["hosttrace"]
    assert not host.empty
    # step annotations from the profiled loop survive ingest...
    names = set(host["name"])
    assert {"sofa_step_0", "sofa_step_1", "sofa_step_2"} <= names
    # ...the marker annotation itself is excluded
    assert not any("sofa_timebase_marker" in n for n in names)
    # timestamps are marker-aligned: everything lands within seconds of it
    assert host["timestamp"].abs().max() < 60.0
    # thread lanes are small ordinals, not hashes
    assert host["event"].max() < len(set(host["tid"]))


def test_real_capture_drives_marker_iterations(xspace):
    from sofa_tpu.ml.aisi import _iterations_from_markers

    off = find_marker_offset_ns(xspace)
    frames = xspace_to_frames(xspace, (off or 0) / 1e9)
    out = _iterations_from_markers(frames)
    assert out is not None
    begins, ends = out
    assert len(begins) == 3
    assert all(e > b for b, e in zip(begins, ends))
