"""The agent's durable on-disk spool: a local archive root + push state.

Spool-and-forward is what lets `sofa agent` promise "a finished run is
never lost": every discovered run is first ingested into a LOCAL
content-addressed archive (the exact store.py machinery — dedup, fsync'd
catalog, ``archive_fsck``), and only then pushed to the fleet service.
The service being down, slow, or over quota therefore costs nothing but
latency: the bytes are already safe, and the next drain pass re-pushes
from the server's have-list with zero re-sent committed objects.

Durability bookkeeping:

* the **spool journal** (``<spool>/_journal.jsonl``, durability.Journal's
  fsync'd begin/commit discipline) brackets every push — a SIGKILLed
  agent leaves a ``push`` begun-not-committed, and the next pass simply
  re-runs it (the protocol makes the replay free);
* **push state** (``<spool>/agent_state.json``, tmp+rename atomic) maps
  source logdirs to their spooled run id, manifest fingerprint, and
  delivery status, so a quiet logdir is not re-ingested every poll tick
  and a delivered run is not re-pushed every restart.

The spool is retained after delivery (it IS the local archive — `sofa
regress`/`sofa archive ls` work against it); `sofa archive gc
--archive_root <spool>` is the retention policy, exactly as for any
other archive root.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

from sofa_tpu.archive.store import ArchiveStore, ingest_run
from sofa_tpu.printing import print_warning

STATE_NAME = "agent_state.json"
STATE_SCHEMA = "sofa_tpu/agent_state"
STATE_VERSION = 1

DEFAULT_SPOOL = "sofa_spool"


def resolve_spool(cfg=None) -> str:
    """The spool root: ``--spool``, else SOFA_AGENT_SPOOL, else
    ``./sofa_spool`` (a sibling default like the archive's)."""
    root = getattr(cfg, "agent_spool", "") if cfg is not None else ""
    return root or os.environ.get("SOFA_AGENT_SPOOL", "") or DEFAULT_SPOOL


class Spool:
    """One spool root: local store + state ledger + push journal."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.store = ArchiveStore(self.root, create=True)
        from sofa_tpu.durability import Journal

        self.journal = Journal(self.root)
        self._state = self._load_state()

    # -- state ledger ------------------------------------------------------
    def _load_state(self) -> dict:
        try:
            with open(os.path.join(self.root, STATE_NAME)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {"schema": STATE_SCHEMA, "version": STATE_VERSION,
                    "logdirs": {}}
        if not isinstance(doc, dict) or doc.get("schema") != STATE_SCHEMA \
                or not isinstance(doc.get("logdirs"), dict):
            return {"schema": STATE_SCHEMA, "version": STATE_VERSION,
                    "logdirs": {}}
        return doc

    def _save_state(self) -> None:
        from sofa_tpu.durability import atomic_write

        self._state["generated_unix"] = round(time.time(), 3)
        try:
            with atomic_write(os.path.join(self.root, STATE_NAME),
                              fsync=True) as f:
                json.dump(self._state, f, indent=1, sort_keys=True)
        except OSError as e:
            print_warning(f"spool: cannot persist {STATE_NAME}: {e} — "
                          "state will be recomputed next pass")

    def entry(self, logdir: str) -> dict:
        return self._state["logdirs"].setdefault(
            os.path.abspath(logdir), {})

    def pending_runs(self) -> Dict[str, str]:
        """{run_id: source logdir} for every spooled-but-undelivered run."""
        out: Dict[str, str] = {}
        for logdir, ent in sorted(self._state["logdirs"].items()):
            run = ent.get("run")
            if isinstance(run, str) and not ent.get("pushed"):
                out[run] = logdir
        return out

    # -- spooling ----------------------------------------------------------
    def needs_ingest(self, logdir: str) -> bool:
        """Whether the logdir changed since it was last spooled (manifest
        fingerprint comparison — re-ingest of an unchanged run would be a
        cheap no-op, but the daemon polls every few seconds and must not
        grow the catalog by a line per tick)."""
        ent = self.entry(logdir)
        return ent.get("manifest_mtime_ns") != _manifest_mtime(logdir) \
            or "run" not in ent

    def spool(self, cfg) -> Optional[dict]:
        """Ingest ``cfg.logdir`` into the spool store (journaled in the
        LOGDIR's journal like any archive ingest, so `sofa resume`
        replays a killed spooling).  Returns the ingest summary or None
        on failure (the run stays discoverable next pass)."""
        logdir = cfg.logdir
        mtime = _manifest_mtime(logdir)
        try:
            summary = ingest_run(cfg, self.root)
        except OSError as e:
            print_warning(f"spool: cannot ingest {logdir}: {e} — "
                          "will retry next pass")
            return None
        ent = self.entry(logdir)
        ent.update(run=summary["run"], manifest_mtime_ns=mtime,
                   spooled_unix=round(time.time(), 3))
        # a changed run id means new content: the previous delivery does
        # not cover it
        if ent.get("pushed_run") != summary["run"]:
            ent["pushed"] = False
        self._save_state()
        return summary

    def refresh_fingerprint(self, logdir: str) -> None:
        """Absorb the agent's OWN manifest write (meta.agent/meta.serve)
        into the fingerprint — without this every tick would read its
        own stamp as a changed run and re-ingest forever.  (The run ID
        is immune either way: ingest normalization strips the transport
        sections — store._SELF_VERBS.)"""
        self.entry(logdir)["manifest_mtime_ns"] = _manifest_mtime(logdir)
        self._save_state()

    # -- delivery ----------------------------------------------------------
    def mark_pushed(self, logdir: str, run_id: str, server: dict) -> None:
        ent = self.entry(logdir)
        ent.update(pushed=True, pushed_run=run_id,
                   pushed_unix=round(time.time(), 3),
                   server_run=str((server or {}).get("run", "")))
        self._save_state()

    def push(self, run_id: str, client) -> dict:
        """Journaled push of one spooled run: begin -> protocol ->
        commit.  The journal is the audit trail; resumability itself
        comes from the have-list (client.push_run)."""
        from sofa_tpu.archive.client import push_run

        self.journal.begin("push", run=run_id, service=client.base,
                           tenant=client.tenant)
        result = push_run(self.store, run_id, client)
        self.journal.commit("push", run=run_id,
                            status=result.get("status"))
        return result


def _manifest_mtime(logdir: str) -> Optional[int]:
    from sofa_tpu.telemetry import MANIFEST_NAME

    try:
        return os.stat(os.path.join(logdir, MANIFEST_NAME)).st_mtime_ns
    except OSError:
        return None
