"""Reader for the native columnar XPlane scan (native/xplane_scan.cc).

Pod-scale ingest is bounded by the per-event Python loop over proto
objects; the native scanner walks the wire format once and hands back flat
numpy arrays per line, so `ingest/xplane.py` can assemble the op frame
vectorized (metadata-derived fields are computed once per metadata id and
gathered with a searchsorted index).

Everything degrades: no compiler / failed scan / mismatched layout all
return None and the caller keeps the pure-Python path.  Set
``SOFA_NATIVE_SCAN=0`` to force the Python path (the equivalence tests use
this to produce the reference frames).
"""

from __future__ import annotations

import os
import struct
import subprocess
import tempfile
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from sofa_tpu.printing import print_info, print_warning

_MAGIC = 0x31584653  # "SFX1" little-endian
_VERSION = 1

# Deadline for one scanner invocation (SL001): a wedged scan degrades to
# the Python ingest path instead of hanging preprocess.  Env-tunable for
# pod-scale captures on slow disks.
_SCAN_TIMEOUT_S = 300.0


def _scan_timeout_s() -> float:
    try:
        return float(os.environ.get("SOFA_NATIVE_SCAN_TIMEOUT_S",
                                    _SCAN_TIMEOUT_S))
    except ValueError:
        return _SCAN_TIMEOUT_S


@dataclass
class ScanLine:
    line_id: int
    timestamp_ns: int
    name: str
    metadata_ids: np.ndarray   # i64[n]
    offsets_ps: np.ndarray     # i64[n]
    durations_ps: np.ndarray   # i64[n]
    flags: np.ndarray          # u8[n]; bit0 = derived per-event stats


@dataclass
class ScanPlane:
    name: str
    lines: List[ScanLine]


def enabled() -> bool:
    return os.environ.get("SOFA_NATIVE_SCAN", "1") != "0"


def ensure_scanner() -> Optional[str]:
    """Build (lazily) and return the scanner binary path, or None."""
    if not enabled():
        return None
    from sofa_tpu.collectors.native_build import ensure_built

    return ensure_built("xplane_scan")


def _parse(buf: bytes) -> Optional[List[ScanPlane]]:
    try:
        return _parse_inner(buf)
    except (struct.error, IndexError, ValueError):
        # Truncated scanner output (e.g. disk-full short write) must land
        # on the Python fallback, never abort the ingest.
        return None


def _parse_inner(buf: bytes) -> Optional[List[ScanPlane]]:
    if len(buf) < 8:
        return None
    magic, version = struct.unpack_from("<II", buf, 0)
    if magic != _MAGIC or version != _VERSION:
        return None
    planes: List[ScanPlane] = []
    off = 8
    n_buf = len(buf)
    while off < n_buf:
        tag = buf[off]
        off += 1
        if tag == 1:
            (nlen,) = struct.unpack_from("<I", buf, off)
            off += 4
            name = buf[off:off + nlen].decode(errors="replace")
            off += nlen
            planes.append(ScanPlane(name, []))
        elif tag == 2:
            line_id, ts_ns = struct.unpack_from("<qq", buf, off)
            off += 16
            (nlen,) = struct.unpack_from("<I", buf, off)
            off += 4
            name = buf[off:off + nlen].decode(errors="replace")
            off += nlen
            if not planes:
                return None
            planes[-1].lines.append(
                ScanLine(line_id, ts_ns, name,
                         np.empty(0, np.int64), np.empty(0, np.int64),
                         np.empty(0, np.int64), np.empty(0, np.uint8)))
        elif tag == 3:
            (n,) = struct.unpack_from("<Q", buf, off)
            off += 8
            need = n * 8 * 3 + n
            if off + need > n_buf or not planes or not planes[-1].lines:
                return None
            line = planes[-1].lines[-1]
            line.metadata_ids = np.frombuffer(buf, np.int64, n, off)
            off += n * 8
            line.offsets_ps = np.frombuffer(buf, np.int64, n, off)
            off += n * 8
            line.durations_ps = np.frombuffer(buf, np.int64, n, off)
            off += n * 8
            line.flags = np.frombuffer(buf, np.uint8, n, off)
            off += n
        else:
            return None
    return planes


def scan_file(path: str, derived_stat_names) -> Optional[List[ScanPlane]]:
    """Run the native scanner over one .xplane.pb; None on any failure."""
    exe = ensure_scanner()
    if exe is None:
        return None
    fd, out_path = tempfile.mkstemp(prefix="sofa_xscan_", suffix=".bin")
    os.close(fd)
    try:
        r = subprocess.run(
            [exe, path, out_path, ",".join(sorted(derived_stat_names))],
            capture_output=True, text=True, timeout=_scan_timeout_s())
        if r.returncode != 0:
            print_warning(f"native scan failed ({r.stderr.strip()[:120]}); "
                          "using Python ingest")
            return None
        with open(out_path, "rb") as f:
            planes = _parse(f.read())
        if planes is None:
            print_warning("native scan produced an unreadable layout; "
                          "using Python ingest")
        else:
            print_info(f"native scan: {os.path.basename(path)} "
                       f"({sum(len(p.lines) for p in planes)} lines)")
        return planes
    except (OSError, subprocess.SubprocessError) as e:
        print_warning(f"native scan unavailable ({e}); using Python ingest")
        return None
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass
