"""The what-if scenario vocabulary.

A scenario is a small typed edit to the step-timeline model
(``whatif/model.py``) that the replayer (``whatif/replay.py``) applies
analytically — no hardware run involved.  Four kinds, parsed from
``sofa whatif --apply <spec>[,<spec>...]`` (or a TOML ``whatif_apply``):

  overlap:<pattern>          hide serialized collectives whose class
                             matches <pattern> behind the step's compute
                             (bounded by the compute actually available)
  scale:<pattern>=<factor>   rescale matching compute classes' time by
                             <factor> (0.5 = twice as fast)
  scale:<pattern>=sol        rescale matching compute classes to their
                             measured speed-of-light attainable time
                             (per-device headroom from sol_roofline.csv,
                             the ``sol_roofline`` analysis pass)
  link:<factor>              interconnect <factor>x faster: every exposed
                             collective term shrinks by 1/<factor>
  batch:<factor>             rescale every compute term by <factor> while
                             communication terms stay (the weak-scaling
                             "bigger per-chip batch" approximation)

Patterns are case-insensitive fnmatch over the model's component classes
(HLO categories: ``all-reduce``, ``fusion``, ...).  An unknown or
malformed spec **degrades** — it is kept in the parse result with kind
``unknown`` and surfaces in the report with status ``unknown`` — instead
of aborting the replay: a typo in one scenario must not cost the answer
to the other three.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple, Union

#: The scenario kinds the replayer knows how to apply.
KINDS = ("overlap", "scale", "link", "batch")

#: The factor spelling that pulls measured roofline headroom instead of a
#: literal number (``scale:<pattern>=sol``).
SOL = "sol"


@dataclass(frozen=True)
class Scenario:
    """One parsed scenario.  ``kind == "unknown"`` marks a spec the
    parser could not type — carried through so the report can state it."""

    kind: str
    spec: str
    pattern: str = "*"
    factor: Union[float, str] = 1.0
    problem: str = ""

    @property
    def known(self) -> bool:
        return self.kind in KINDS


def _unknown(spec: str, why: str) -> Scenario:
    return Scenario(kind="unknown", spec=spec, problem=why)


def _parse_factor(text: str, spec: str) -> "Tuple[float, str]":
    try:
        f = float(text)
    except ValueError:
        return 1.0, (f"{spec!r}: factor {text!r} is not a number")
    if not (f > 0):
        return 1.0, (f"{spec!r}: factor must be > 0, got {f:g}")
    return f, ""


def parse_scenario(spec: str) -> Scenario:
    """One ``kind:args`` spec -> a Scenario (possibly ``unknown``)."""
    spec = spec.strip()
    kind, sep, rest = spec.partition(":")
    kind = kind.strip().lower()
    rest = rest.strip()
    if kind not in KINDS:
        return _unknown(spec, f"unknown scenario kind {kind or spec!r} "
                              f"(known: {', '.join(KINDS)})")
    if not sep or not rest:
        return _unknown(spec, f"{spec!r}: missing arguments after "
                              f"{kind!r}:")
    if kind == "overlap":
        return Scenario(kind=kind, spec=spec, pattern=rest)
    if kind == "scale":
        pattern, eq, factor_s = rest.partition("=")
        pattern = pattern.strip()
        factor_s = factor_s.strip().lower()
        if not eq or not pattern or not factor_s:
            return _unknown(
                spec, f"{spec!r}: scale needs <pattern>=<factor|sol>")
        if factor_s == SOL:
            return Scenario(kind=kind, spec=spec, pattern=pattern,
                            factor=SOL)
        f, err = _parse_factor(factor_s, spec)
        if err:
            return _unknown(spec, err)
        return Scenario(kind=kind, spec=spec, pattern=pattern, factor=f)
    # link / batch: a bare factor
    f, err = _parse_factor(rest, spec)
    if err:
        return _unknown(spec, err)
    return Scenario(kind=kind, spec=spec, factor=f)


def parse_scenarios(spec: str) -> "Tuple[List[Scenario], List[str]]":
    """Comma-joined spec -> (scenarios in declared order, problems).

    Unknown/malformed entries ride along with ``kind == "unknown"`` AND
    contribute a problem line — degradation with a stated reason, the
    collector-failure contract applied to scenario parsing."""
    scenarios: List[Scenario] = []
    problems: List[str] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        s = parse_scenario(part)
        scenarios.append(s)
        if s.problem:
            problems.append(s.problem)
    return scenarios, problems
