"""Generate a synthetic 8-device x 200k-op pod-scale logdir.

The perf harness behind the pod-scale numbers in README.md: flops/bytes are
static per op name (XLA cost-model metadata is per-op, not per-occurrence),
op names cycle over 700 symbols, timestamps/durations are exponential.

    python tools/pod_synth.py /tmp/podlog/
    sofa analyze --logdir /tmp/podlog/          # report-path timing
    sofa export --logdir /tmp/podlog/ --perfetto
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from sofa_tpu.trace import make_frame, write_csv  # noqa: E402

OUT = os.path.join(sys.argv[1] if len(sys.argv) > 1 else "/tmp/podlog", "")
N_DEV, N_OPS = 8, 200_000
rng = np.random.default_rng(0)

os.makedirs(OUT, exist_ok=True)
names = np.array([f"fusion.{i % 700}" for i in range(N_OPS)])
cats = np.array(["fusion", "convolution", "all-reduce", "copy"])[
    rng.integers(0, 4, N_OPS)]
frames = []
for dev in range(N_DEV):
    ts = np.cumsum(rng.exponential(12e-6, N_OPS))
    df = make_frame({
        "timestamp": ts,
        "duration": rng.exponential(8e-6, N_OPS),
        "deviceId": dev,
        "category": rng.integers(0, 3, N_OPS) % 2,  # some async
        "name": names,
        "hlo_category": cats,
        # static per op name, like real XLA cost-model metadata
        "flops": np.array([float(1e9 + (i % 700) * 1e6) for i in range(N_OPS)]),
        "bytes_accessed": np.array([float(1e6 + (i % 700) * 1e3) for i in range(N_OPS)]),
        "copyKind": np.where(cats == "all-reduce", 21, 0),
        "payload": np.where(cats == "all-reduce", int(4e6), 0),
        "device_kind": "tpu",
        "phase": np.where(rng.random(N_OPS) < 0.5, "fw", "bw"),
        "module": "jit_train_step",
        "op_path": "jit(train_step)/transpose(jvp(main))/mul",
        "tid": 0,
        "pid": -1,
        "event": 0.0,
    })
    frames.append(df)

import pandas as pd  # noqa: E402

tput = pd.concat(frames, ignore_index=True)
write_csv(tput, OUT + "tputrace.csv")

steps = []
for dev in range(N_DEV):
    t0 = 0.0
    for s in range(50):
        steps.append({"timestamp": t0, "duration": 0.048, "deviceId": dev,
                      "name": f"step {s}", "device_kind": "tpu"})
        t0 += 0.05
write_csv(make_frame(steps), OUT + "tpusteps.csv")

util = []
for dev in range(N_DEV):
    for t in np.arange(0, 2.5, 0.01):
        util.append({"timestamp": t, "event": 60.0, "deviceId": dev,
                     "name": "tc_util", "device_kind": "tpu"})
write_csv(make_frame(util), OUT + "tpuutil.csv")

mon = []
for t in np.arange(0, 2.5, 1.0):
    mon.append({"timestamp": t, "event": 0.0, "deviceId": -1, "name": "alive"})
    for dev in range(N_DEV):
        mon.append({"timestamp": t, "event": 2.5, "deviceId": dev,
                    "name": "hbm_used_gb"})
write_csv(make_frame(mon), OUT + "tpumon.csv")

with open(OUT + "misc.txt", "w") as f:
    f.write("elapsed_time 2.5\ncores 8\npid 1\nrc 0\n")
with open(OUT + "sofa_time.txt", "w") as f:
    f.write("1700000000.0\n")
print("generated", OUT, len(tput), "op rows")
