"""Programmatic, in-process profiling API.

For users who own the Python process (the common JAX case) and do not want
the wrap-a-command CLI:

    import sofa_tpu.api as sofa

    with sofa.profile("sofalog/"):
        train_step(...)  # any JAX work

    # then: sofa report --logdir sofalog/

This records the same artifact set as `sofa record` minus the process-level
wrappers (perf/strace prefixes do not apply in-process).
"""

from __future__ import annotations

import contextlib
import json
import os
import time

from sofa_tpu.config import SofaConfig


@contextlib.contextmanager
def profile(logdir: str = "sofalog/", cfg: SofaConfig | None = None):
    import jax

    if cfg is None:
        cfg = SofaConfig(logdir=logdir)
    else:
        cfg.logdir = logdir
        cfg.__post_init__()
    os.makedirs(cfg.logdir, exist_ok=True)

    from sofa_tpu.collectors.procmon import ProcMonCollector
    from sofa_tpu.collectors.timebase import TimebaseCollector
    from sofa_tpu.collectors.tpumon import start_sampler

    timebase = TimebaseCollector(cfg)
    procmon = ProcMonCollector(cfg)
    timebase.start()
    if procmon.probe() is None:
        procmon.start()
    memprof_path = cfg.path("memprof.pb.gz") if cfg.enable_mem_prof else None
    # Drop the previous run's snapshot: the finally-block fallback keys on
    # file existence, and a stale profile would masquerade as this run's.
    for stale in (cfg.path("memprof.pb.gz"),
                  cfg.path("memprof.pb.gz.meta.json")):
        try:
            os.unlink(stale)
        except OSError:
            pass
    tpumon_stop = None
    tpumon_thread = None
    if cfg.enable_tpu_mon:
        import threading

        try:  # the sampler appends; drop any previous run's samples
            os.unlink(cfg.path("tpumon.txt"))
        except OSError:
            pass
        tpumon_stop = threading.Event()
        tpumon_thread = start_sampler(
            cfg.tpu_mon_rate, cfg.path("tpumon.txt"), tpumon_stop,
            memprof_path=memprof_path)

    kwargs = {}
    try:
        po = jax.profiler.ProfileOptions()
        po.host_tracer_level = int(cfg.xprof_host_tracer_level)
        po.python_tracer_level = 1 if cfg.xprof_python_tracer else 0
        kwargs["profiler_options"] = po
    except Exception:
        pass
    jax.profiler.start_trace(cfg.xprof_dir, **kwargs)
    t0 = time.time_ns()
    with jax.profiler.TraceAnnotation(f"sofa_timebase_marker:{t0}"):
        t1 = time.time_ns()
    with open(cfg.path("xprof_marker.txt"), "w") as f:
        f.write(f"{t0} {t1}\n")
    _snapshot_topology(jax, cfg.logdir)

    start = time.time()
    try:
        yield cfg
    finally:
        # End marker: a second (session_ns, unix_ns) anchor in the same
        # trace.  Two markers let ingest/validation confirm the session
        # clock's offset is consistent WITHIN a capture — the only
        # stability that alignment correctness needs (the session origin
        # legitimately moves between captures on tunneled backends).
        te = time.time_ns()
        with jax.profiler.TraceAnnotation(f"sofa_timebase_marker:{te}"):
            pass
        jax.profiler.stop_trace()
        try:
            with open(cfg.path("xprof_marker.txt"), "a") as f:
                f.write(f"{te} {time.time_ns()}\n")
        except OSError:
            pass
        if tpumon_stop is not None:
            tpumon_stop.set()
            # Join so the sampler's last tick can't publish a snapshot
            # after the exists-check below decides a fallback is needed
            # (tmp names are writer-unique, so corruption is impossible —
            # this is about which snapshot wins).
            tpumon_thread.join(timeout=2.0)
        if memprof_path and not os.path.exists(memprof_path):
            # Sampler off or the growth gate never fired: final snapshot so
            # the allocation-site table exists for every profiled run.
            from sofa_tpu.collectors.tpumon import snapshot_memprof

            snapshot_memprof(jax, memprof_path, "final", 0)
        procmon.stop()
        timebase.stop()  # end-of-run anchor enables the drift fit at ingest
        elapsed = time.time() - start
        with open(cfg.path("misc.txt"), "w") as f:
            f.write(f"elapsed_time {elapsed:.6f}\n")
            f.write(f"cores {os.cpu_count() or 1}\n")
            f.write(f"pid {os.getpid()}\n")
            f.write("rc 0\n")


def _snapshot_topology(jax, logdir: str) -> None:
    devs = [
        {
            "id": d.id,
            "process_index": d.process_index,
            "platform": d.platform,
            "device_kind": getattr(d, "device_kind", ""),
            "coords": list(getattr(d, "coords", []) or []),
            "core_on_chip": getattr(d, "core_on_chip", -1),
        }
        for d in jax.devices()
    ]
    info = {
        "platform": jax.default_backend(),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "device_count": jax.device_count(),
        "local_device_count": jax.local_device_count(),
        "devices": devs,
    }
    with open(os.path.join(logdir, "tpu_topo.json"), "w") as f:
        json.dump(info, f, indent=1)
