"""Test configuration.

Tests never require real TPU hardware: JAX is pinned to the CPU backend with
8 virtual devices so sharding/collective paths (device meshes, pjit,
shard_map) compile and execute anywhere.  Set SOFA_TPU_TEST_REAL=1 to run the
few opt-in tests that want the real chip.
"""

import os
import sys

# The image may force-register a TPU backend via sitecustomize regardless of
# JAX_PLATFORMS (and that backend's init can hang if the device tunnel is
# busy), so the env var alone is not enough: pin the platform at the jax
# config level below, before any backend initializes.  Tests that need the
# 8-device mesh build it via make_mesh(..., platform="cpu"); the
# virtual-device flag guarantees the CPU backend always has 8.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

if not os.environ.get("SOFA_TPU_TEST_REAL"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

import pytest  # noqa: E402

# Shared synthetic-XSpace builders (used by test_ingest_xplane and
# test_multichip_report): stat-metadata interning + oneof dispatch must
# match how the real profiler writes protos, in exactly one place.
MARKER_UNIX_NS = 1_700_000_000_000_000_000


def add_stat(plane, holder, name, value):
    sid = None
    for k, v in plane.stat_metadata.items():
        if v.name == name:
            sid = k
    if sid is None:
        sid = len(plane.stat_metadata) + 1
        plane.stat_metadata[sid].id = sid
        plane.stat_metadata[sid].name = name
    stat = holder.stats.add()
    stat.metadata_id = sid
    if isinstance(value, float):
        stat.double_value = value
    elif isinstance(value, int):
        stat.int64_value = value
    else:
        stat.str_value = str(value)
    return stat


def add_event(plane, line, name, offset_ns, dur_ns, display="", stats=(),
              mstats=()):
    """Append an event; ``stats`` go on the event, ``mstats`` on its
    metadata (where real libtpu puts flops/categories/tf_op)."""
    mid = None
    for k, v in plane.event_metadata.items():
        if v.name == name:
            mid = k
    if mid is None:
        mid = len(plane.event_metadata) + 1
        plane.event_metadata[mid].id = mid
        plane.event_metadata[mid].name = name
        if display:
            plane.event_metadata[mid].display_name = display
        for sname, sval in mstats:
            add_stat(plane, plane.event_metadata[mid], sname, sval)
    ev = line.events.add()
    ev.metadata_id = mid
    ev.offset_ps = offset_ns * 1000
    ev.duration_ps = dur_ns * 1000
    for sname, sval in stats:
        add_stat(plane, ev, sname, sval)
    return ev


@pytest.fixture
def logdir(tmp_path):
    d = tmp_path / "sofalog"
    d.mkdir()
    return str(d) + "/"


def pytest_configure(config):
    config.addinivalue_line("markers", "real_tpu: needs the real TPU chip")
    config.addinivalue_line(
        "markers", "slow: long-running regression test (tier-1 runs "
        "-m 'not slow')")
    config.addinivalue_line(
        "markers", "race: Guard-protected concurrency test re-run under a "
        "tiny sys.setswitchinterval so real races surface in CI "
        "(tests/test_concurrency_lint.py)")


@pytest.fixture(autouse=True)
def _race_amplifier(request):
    """Tests marked ``race`` run with sys.setswitchinterval(1e-6): the
    interpreter preempts threads every few bytecodes instead of every
    5 ms, turning a latent data race on Guard-protected state from a
    one-in-a-million flake into a near-certain assertion failure."""
    if request.node.get_closest_marker("race") is None:
        yield
        return
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)
    try:
        yield
    finally:
        sys.setswitchinterval(old)


def pytest_collection_modifyitems(config, items):
    if os.environ.get("SOFA_TPU_TEST_REAL"):
        return
    skip = pytest.mark.skip(reason="set SOFA_TPU_TEST_REAL=1 to run on real TPU")
    for item in items:
        if "real_tpu" in item.keywords:
            item.add_marker(skip)
