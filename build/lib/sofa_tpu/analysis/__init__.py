"""Analysis passes: unified-schema frames -> performance feature vector.

Each pass is a pure function ``(frames, cfg, features) -> None`` appending
(name, value) rows to the Features accumulator and optionally writing derived
artifacts (comm.csv, netrank.csv, performance.csv, hint files).  The
reference's equivalents live in sofa_analyze.py/sofa_common.py (SURVEY §2.5).
"""
