"""Windowed concurrency breakdown — what dominates each slice of wall time.

Reference: concurrency_breakdown (sofa_analyze.py:75-243) classifies each
1/sys_mon_rate window into usr/sys/gpu/iow/idle by the dominant activity and
correlates GPU activity with host metrics.  Retarget: `gpu` becomes `tpu`
(TensorCore duty cycle) and the correlation set gains HBM bandwidth.
Writes performance.csv (per-window class + metrics).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from sofa_tpu.analysis.features import Features
from sofa_tpu.analysis.registry import analysis_pass
from sofa_tpu.printing import print_title


def _window_series(df, name_filter, t0, t1, window, value_col="event"):
    """Mean of a metric per window, aligned to edges [t0, t1)."""
    edges = np.arange(t0, t1 + window, window)
    out = np.zeros(len(edges) - 1)
    rows = df[df["name"] == name_filter] if name_filter else df
    # Drop samples outside [t0, t1): clamping them into the edge windows
    # would pollute window 0 with all pre-ROI history.
    rows = rows[(rows["timestamp"] >= t0) & (rows["timestamp"] < t1)]
    if rows.empty:
        return edges, out
    idx = np.clip(((rows["timestamp"] - t0) / window).astype(int), 0, len(out) - 1)
    sums = np.zeros(len(out))
    counts = np.zeros(len(out))
    np.add.at(sums, idx, rows[value_col].to_numpy(dtype=float))
    np.add.at(counts, idx, 1)
    mask = counts > 0
    out[mask] = sums[mask] / counts[mask]
    return edges, out


@analysis_pass(
    name="concurrency_breakdown", order=230,
    reads_frames=("mpstat", "tpuutil", "netbandwidth"),
    # "event" rides through the _window_series helper's value_col default
    # — the projection loader materializes exactly this set, so the
    # declaration must name every column the body reaches, helpers
    # included (the first dishonest declaration the pushdown path found).
    reads_columns=("timestamp", "deviceId", "name", "event"),
    provides_features=("elapsed_*_ratio", "breakdown_windows",
                       "breakdown_elapsed", "corr_tpu_*"),
    provides_artifacts=("performance.csv",),
    after=("spotlight",),
)
def concurrency_breakdown(frames, cfg, features: Features) -> None:
    mpstat = frames.get("mpstat")
    if mpstat is None or mpstat.empty:
        return
    agg = mpstat[mpstat["deviceId"] == -1]
    if agg.empty:
        return
    window = 1.0 / max(cfg.sys_mon_rate, 1)
    t0 = float(agg["timestamp"].min())
    t1 = float(agg["timestamp"].max())
    if cfg.roi_end > cfg.roi_begin > 0:
        t0, t1 = cfg.roi_begin, cfg.roi_end
    if t1 <= t0:
        return

    edges, usr = _window_series(agg, "usr", t0, t1, window)
    _, sys_ = _window_series(agg, "sys", t0, t1, window)
    _, iow = _window_series(agg, "iow", t0, t1, window)
    tpuutil = frames.get("tpuutil")
    if tpuutil is not None and not tpuutil.empty:
        _, tpu = _window_series(tpuutil, "tc_util", t0, t1, window)
        _, hbm = _window_series(tpuutil, "hbm_gbps", t0, t1, window)
    else:
        tpu = np.zeros(len(edges) - 1)
        hbm = np.zeros(len(edges) - 1)
    net = frames.get("netbandwidth")
    if net is not None and not net.empty:
        tx_rows = net[net["name"].str.endswith(".tx")]
        _, tx = _window_series(tx_rows, None, t0, t1, window)
        rx_rows = net[net["name"].str.endswith(".rx")]
        _, rx = _window_series(rx_rows, None, t0, t1, window)
    else:
        tx = np.zeros(len(edges) - 1)
        rx = np.zeros(len(edges) - 1)

    idle_floor = cfg.is_idle_threshold * 100.0
    classes = []
    for i in range(len(edges) - 1):
        candidates = {
            "tpu": tpu[i],
            "usr": usr[i],
            "sys": sys_[i],
            "iow": iow[i],
        }
        dominant = max(candidates, key=candidates.get)
        if candidates[dominant] < idle_floor:
            dominant = "idl"
        classes.append(dominant)

    perf = pd.DataFrame(
        {
            "timestamp": edges[:-1],
            "class": classes,
            "usr": usr,
            "sys": sys_,
            "iow": iow,
            "tpu_util": tpu,
            "hbm_gbps": hbm,
            "net_tx": tx,
            "net_rx": rx,
        }
    )
    perf.to_csv(cfg.path("performance.csv"), index=False)

    elapsed = t1 - t0
    counts = pd.Series(classes).value_counts()
    for cls in ("tpu", "usr", "sys", "iow", "idl"):
        ratio = counts.get(cls, 0) / len(classes) if classes else 0.0
        features.add(f"elapsed_{cls}_ratio", ratio)
    features.add("breakdown_windows", len(classes))
    features.add("breakdown_elapsed", elapsed)

    # Pearson correlation of TPU activity vs host metrics
    # (reference correlates gpu vs usr/sys/iow/tx/rx, sofa_analyze.py:200-243).
    if tpu.any():
        for name, arr in (("usr", usr), ("sys", sys_), ("iow", iow),
                          ("net_tx", tx), ("net_rx", rx), ("hbm", hbm)):
            if arr.any() and np.std(arr) > 0 and np.std(tpu) > 0:
                corr = float(np.corrcoef(tpu, arr)[0, 1])
                features.add(f"corr_tpu_{name}", corr)
    if cfg.verbose:
        print_title("Concurrency breakdown (dominant class per window)")
        print(counts.to_string())
