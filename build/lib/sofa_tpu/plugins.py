"""Plugin loading.

The reference imports any module named on PYTHONPATH and calls
``<name>(cfg)`` at CLI start (/root/reference/bin/sofa:21,322 with
plugins/dummy_plugin.py).  We generalize: ``--plugin mod`` or ``--plugin
mod:func`` — the callable receives the SofaConfig before the pipeline runs and
may mutate it (register filters, tweak collector knobs, ...).
"""

from __future__ import annotations

import importlib

from sofa_tpu.printing import print_error, print_info


def load_plugins(cfg) -> None:
    for spec in cfg.plugins:
        mod_name, _, func_name = spec.partition(":")
        try:
            mod = importlib.import_module(mod_name)
        except ImportError as e:
            print_error(f"plugin {spec!r}: cannot import {mod_name!r}: {e}")
            continue
        func = getattr(mod, func_name or mod_name.rsplit(".", 1)[-1], None)
        if not callable(func):
            print_error(f"plugin {spec!r}: no callable entry point")
            continue
        print_info(f"plugin {spec!r} loaded")
        func(cfg)
