"""Finding baseline: grandfather existing violations, fail only new ones.

The checked-in ``lint_baseline.json`` is a ratchet — it records every
finding that existed when the linter landed, keyed by a content
fingerprint, so the tree lints clean today while any NEW violation fails
immediately.  The workflow contract (docs/STATIC_ANALYSIS.md):

* the baseline only ever **shrinks** over PRs: fix a grandfathered finding
  and ``--update-baseline`` expires its entry; adding entries for new code
  is a review smell (suppress inline with a justification instead, or fix);
* fingerprints key on (rule, file, normalized source line, occurrence
  index) — NOT the line number — so unrelated edits above a grandfathered
  site do not churn the file, while any edit to the flagged line itself
  (e.g. deleting its ``timeout=``) produces a fresh fingerprint and fails.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Dict, List, Sequence, Tuple

from sofa_tpu.lint.core import Finding

BASELINE_NAME = "lint_baseline.json"
_WS = re.compile(r"\s+")


def fingerprint(f: Finding, line_text: str, occurrence: int) -> str:
    norm = _WS.sub(" ", line_text.strip())
    raw = f"{f.rule_id}|{f.file}|{norm}|{occurrence}"
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


def fingerprint_findings(findings: Sequence[Finding],
                         line_text_for) -> List[Tuple[str, Finding]]:
    """[(fingerprint, finding)] with duplicate (rule, file, text) sites
    disambiguated by an occurrence counter in file order.  ``line_text_for``
    maps a Finding to its source line's text."""
    counts: Dict[Tuple[str, str, str], int] = {}
    out: List[Tuple[str, Finding]] = []
    for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule_id)):
        text = _WS.sub(" ", line_text_for(f).strip())
        key = (f.rule_id, f.file, text)
        occ = counts.get(key, 0)
        counts[key] = occ + 1
        out.append((fingerprint(f, text, occ), f))
    return out


class Baseline:
    """Load/compare/write the grandfather ledger."""

    def __init__(self, entries: Dict[str, dict], path: str = ""):
        self.entries = entries
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return cls({}, path)
        if not isinstance(doc, dict) or \
                not isinstance(doc.get("entries"), list):
            raise ValueError(f"{path}: not a sofa-lint baseline")
        entries = {e["fingerprint"]: e for e in doc["entries"]
                   if isinstance(e, dict) and "fingerprint" in e}
        return cls(entries, path)

    def split(self, fingerprinted: Sequence[Tuple[str, Finding]]
              ) -> Tuple[List[Finding], List[Finding]]:
        """(new, grandfathered) partition of the current findings."""
        new, old = [], []
        for fp, f in fingerprinted:
            (old if fp in self.entries else new).append(f)
        return new, old

    @staticmethod
    def write(path: str,
              fingerprinted: Sequence[Tuple[str, Finding]]) -> dict:
        """Regenerate the baseline from the current findings: entries for
        findings that disappeared expire, current ones are (re)recorded.
        The review contract that the file never grows lives in code review
        and the self-run test, not here — --update-baseline must be able
        to seed the initial ledger."""
        entries = [
            {"fingerprint": fp, "rule": f.rule_id, "file": f.file,
             "line": f.line, "message": f.message[:120]}
            for fp, f in sorted(fingerprinted,
                                key=lambda p: (p[1].file, p[1].line,
                                               p[1].rule_id))
        ]
        doc = {"tool": "sofa-lint", "version": 1, "entries": entries}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
        return doc


def locate_baseline(start: str) -> str:
    """Walk up from ``start`` to find the checked-in baseline; falls back
    to ``<repo root>/lint_baseline.json`` next to the sofa_tpu package so
    the tool works from any cwd."""
    cur = os.path.abspath(start if os.path.isdir(start)
                          else os.path.dirname(start) or ".")
    while True:
        cand = os.path.join(cur, BASELINE_NAME)
        if os.path.isfile(cand):
            return cand
        nxt = os.path.dirname(cur)
        if nxt == cur:
            break
        cur = nxt
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(pkg_root, BASELINE_NAME)
