"""`sofa live` — crash-tolerant streaming profiling (sofa_tpu/live.py).

Covers the tentpole contracts: offset-ledger roundtrip, torn-tail
backoff per tailable parser, the chunk-cache no-reparse proof,
dirty-tile-only rebuilds, the incremental pass re-run window,
SIGKILL-mid-epoch -> resume -> drain byte-identity, stalled-source
degradation, stream-fault grammar, rotation, and CLI exit codes.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from sofa_tpu import faults  # noqa: E402
from sofa_tpu.config import SofaConfig  # noqa: E402
from sofa_tpu.live import (  # noqa: E402
    OFFSETS_NAME,
    OFFSETS_SCHEMA,
    OFFSETS_VERSION,
    TAILABLE_SOURCES,
    OffsetLedger,
    sofa_live,
    whole_records,
)
from sofa_tpu.telemetry import load_manifest  # noqa: E402

TB = 1_700_000_000.0


def _mc():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "manifest_check", os.path.join(_ROOT, "tools", "manifest_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def seed_logdir(path) -> str:
    log = os.path.join(str(path), "log") + "/"
    os.makedirs(log, exist_ok=True)
    with open(log + "sofa_time.txt", "w") as f:
        f.write(f"{TB}\n")
    with open(log + "misc.txt", "w") as f:
        f.write("elapsed_time 2.5\ncores 8\npid 1\nrc 0\n")
    return log


def tpumon_lines(t0: int, t1: int, devs: int = 2) -> str:
    rows = []
    for t in range(t0, t1):
        ts_ns = int((TB + t * 0.001) * 1e9)
        rows.append(f"{ts_ns} -1 0 0 0\n")
        for dev in range(devs):
            rows.append(f"{ts_ns} {dev} {2500000000 + t * 1000} "
                        "8000000000 0\n")
    return "".join(rows)


def pystacks_lines(t0: int, t1: int) -> str:
    return "".join(
        f"{TB + i * 0.001:.6f} {1 + i % 4} main;train;step_{i % 50};kernel\n"
        for i in range(t0, t1))


def strace_lines(t0: int, t1: int) -> str:
    import datetime as _dt

    base_dt = _dt.datetime.fromtimestamp(TB)
    day_origin = _dt.datetime(base_dt.year, base_dt.month,
                              base_dt.day).timestamp()
    rows = []
    for i in range(t0, t1):
        tod = TB - day_origin + i * 0.001
        hh, rem = divmod(tod, 3600)
        mm, ss = divmod(rem, 60)
        rows.append(f"{100 + i % 4} {int(hh):02d}:{int(mm):02d}:{ss:09.6f} "
                    f"read(3, \"buf\", 4096) = 4096 <0.0001{i % 90:02d}>\n")
    return "".join(rows)


def cpuinfo_lines(t0: int, t1: int) -> str:
    return "".join(f"{TB + t * 0.1:.2f} " + " ".join(["2000.0"] * 4) + "\n"
                   for t in range(t0, t1))


_WRITERS = {
    "tpumon": ("tpumon.txt", tpumon_lines),
    "pystacks": ("pystacks.txt", pystacks_lines),
    "strace": ("strace.txt", strace_lines),
    "cpuinfo": ("cpuinfo.txt", cpuinfo_lines),
}


def live_cfg(log: str, **kw) -> SofaConfig:
    kw.setdefault("live_interval_s", 0.0)
    return SofaConfig(logdir=log, **kw)


def meta_live(log: str) -> dict:
    return ((load_manifest(log) or {}).get("meta") or {}).get("live") or {}


# --- offset ledger -----------------------------------------------------------

def test_offset_ledger_roundtrip(tmp_path):
    log = seed_logdir(tmp_path)
    ledger = OffsetLedger.load(log)
    assert ledger.doc["epoch"] == 0  # fresh
    ent = ledger.source("tpumon")
    ent["offset"] = 1234
    ent["chunks"].append([0, 1234, 99])
    ledger.doc["epoch"] = 3
    ledger.commit()
    again = OffsetLedger.load(log)
    assert again.doc["epoch"] == 3
    assert again.doc["sources"]["tpumon"]["offset"] == 1234
    assert again.doc["sources"]["tpumon"]["chunks"] == [[0, 1234, 99]]
    assert again.doc["schema"] == OFFSETS_SCHEMA
    assert again.doc["version"] == OFFSETS_VERSION


def test_offset_ledger_rejects_foreign_schema(tmp_path):
    log = seed_logdir(tmp_path)
    with open(log + OFFSETS_NAME, "w") as f:
        json.dump({"schema": "something/else", "version": 9}, f)
    assert OffsetLedger.load(log).doc["epoch"] == 0


def test_torn_ledger_degrades_to_fresh(tmp_path):
    log = seed_logdir(tmp_path)
    with open(log + OFFSETS_NAME, "w") as f:
        f.write('{"schema": "sofa_tpu/live_off')  # torn mid-write
    assert OffsetLedger.load(log).doc["epoch"] == 0


def test_live_offsets_in_lifecycle_registries():
    from sofa_tpu.trace import DERIVED_FILES, DIGEST_SKIP_FILES

    assert OFFSETS_NAME in DERIVED_FILES  # `sofa clean` sweeps it
    assert OFFSETS_NAME in DIGEST_SKIP_FILES  # fsck never flags its churn


# --- torn-tail backoff -------------------------------------------------------

def test_whole_records_backoff():
    assert whole_records(b"a 1\nb 2\nc 3") == b"a 1\nb 2\n"
    assert whole_records(b"a 1\nb 2\n") == b"a 1\nb 2\n"
    assert whole_records(b"half a record") == b""
    assert whole_records(b"") == b""


@pytest.mark.parametrize("source", TAILABLE_SOURCES)
def test_torn_tail_backoff_and_chunk_concat_equals_batch(tmp_path, source):
    """Per tailable parser: a torn trailing record is never consumed, and
    the chunk-concatenated frame written across two epochs is identical
    to one whole-file batch parse (the chunk-composability contract)."""
    import pandas as pd

    log = seed_logdir(tmp_path)
    fname, gen = _WRITERS[source]
    first, second = gen(0, 40), gen(40, 80)
    torn = second[:-9]  # cut mid final record
    with open(log + fname, "w") as f:
        f.write(first)
    cfg = live_cfg(log)
    assert sofa_live(cfg, epochs=1) == 0
    with open(log + fname, "a") as f:
        f.write(torn)
    assert sofa_live(cfg, epochs=1) == 0
    led = json.load(open(log + OFFSETS_NAME))
    ent = led["sources"][source]
    want_offset = len(first.encode()) + len(
        torn[:torn.rfind("\n") + 1].encode())
    assert ent["offset"] == want_offset, "torn tail was consumed"
    # complete the record; the next epoch folds it in
    with open(log + fname, "a") as f:
        f.write(second[len(torn):])
    assert sofa_live(cfg, epochs=1) == 0
    led = json.load(open(log + OFFSETS_NAME))
    assert led["sources"][source]["offset"] == len((first + second).encode())
    # chunk-concat == one batch parse, byte-for-byte through the CSV
    from sofa_tpu.live import _tail_parsers
    from sofa_tpu.trace import read_csv, write_csv

    parser = dict((s, p) for s, _r, p in _tail_parsers(cfg))[source]
    batch = parser(first + second, TB)
    if source == "cpuinfo":
        # cpuinfo never lands as a CSV frame (batch preprocess excludes
        # it too): compare the chunk-concat directly
        from sofa_tpu.ingest.cache import CACHE_DIR_NAME, IngestCache
        from sofa_tpu.trace import _conform

        store = IngestCache(log + CACHE_DIR_NAME).chunks()
        parts = [store.load(source, s, e)
                 for s, e, _r in led["sources"][source]["chunks"]]
        assert all(p is not None for p in parts)
        got_df = _conform(pd.concat(parts, ignore_index=True))
        pd.testing.assert_frame_equal(got_df, batch, check_dtype=False)
        return
    write_csv(batch, str(tmp_path / "batch.csv"))
    with open(tmp_path / "batch.csv", "rb") as f:
        want = f.read()
    with open(log + f"{source}.csv", "rb") as f:
        got = f.read()
    assert got == want
    # value-level round trip too (dtype-lax: CSV re-inference may read a
    # whole-valued float column back as int, same as any batch frame)
    pd.testing.assert_frame_equal(read_csv(log + f"{source}.csv"), batch,
                                  check_dtype=False)


# --- chunk cache: committed chunks never reparse -----------------------------

def test_chunk_cache_no_reparse_proof(tmp_path, monkeypatch):
    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 200))
    with open(log + "pystacks.txt", "w") as f:
        f.write(pystacks_lines(0, 200))
    cfg = live_cfg(log)
    assert sofa_live(cfg, epochs=1) == 0
    ml = meta_live(log)
    assert ml["chunks_parsed"] == 2  # one chunk per source
    # epoch 2: only tpumon grows — pystacks' committed chunk must LOAD
    with open(log + "tpumon.txt", "a") as f:
        f.write(tpumon_lines(200, 400))
    # hard proof on top of the ledger: the pystacks parser must not run
    from sofa_tpu.ingest import strace_parse

    def _boom(*a, **kw):
        raise AssertionError("committed pystacks chunk was re-parsed")

    monkeypatch.setattr(strace_parse, "parse_pystacks", _boom)
    assert sofa_live(cfg, epochs=1) == 0
    ml = meta_live(log)
    assert ml["chunks_parsed"] == 1  # exactly the appended tpumon chunk
    assert ml["sources"]["pystacks"]["chunks_parsed"] == 0
    assert ml["sources"]["pystacks"]["chunks_loaded"] >= 1
    assert ml["sources"]["tpumon"]["status"] == "streaming"


def test_chunk_compaction_is_load_store_only(tmp_path, monkeypatch):
    from sofa_tpu import live as live_mod

    monkeypatch.setattr(live_mod, "CHUNK_COMPACT_COUNT", 3)
    log = seed_logdir(tmp_path)
    cfg = live_cfg(log)
    for i in range(5):
        with open(log + "tpumon.txt", "a") as f:
            f.write(tpumon_lines(i * 50, (i + 1) * 50))
        assert sofa_live(cfg, epochs=1) == 0
    led = json.load(open(log + OFFSETS_NAME))
    ent = led["sources"]["tpumon"]
    assert len(ent["chunks"]) <= 3 + 1  # compacted under the cap
    # the events survived the merges intact: per tick 1 heartbeat row +
    # 2 devices x (hbm_used + hbm_occupancy) rows
    assert ent["events"] == 250 * 5


# --- dirty-tile-only rebuild -------------------------------------------------

def test_dirty_tile_only_rebuild(tmp_path):
    import glob

    log = seed_logdir(tmp_path)
    with open(log + "pystacks.txt", "w") as f:
        f.write(pystacks_lines(0, 12000))
    cfg = live_cfg(log, viz_downsample_to=800)
    assert sofa_live(cfg, epochs=1) == 0
    assert meta_live(log)["tiles"]["full_rebuilds"] == 1
    mtimes = {p: os.stat(p).st_mtime_ns
              for p in glob.glob(log + "_tiles/**/*.json.gz",
                                 recursive=True)}
    assert mtimes, "no pyramid built"
    with open(log + "pystacks.txt", "a") as f:
        f.write(pystacks_lines(12000, 13000))
    assert sofa_live(cfg, epochs=1) == 0
    ml = meta_live(log)
    assert ml["tiles"]["full_rebuilds"] == 0
    assert ml["tiles"]["kept"] > 0 and ml["tiles"]["rebuilt"] > 0
    untouched = [p for p, t in mtimes.items()
                 if os.path.exists(p) and os.stat(p).st_mtime_ns == t]
    assert len(untouched) == ml["tiles"]["kept"] or len(untouched) > 0


def test_unchanged_series_skip_wholesale(tmp_path):
    log = seed_logdir(tmp_path)
    with open(log + "pystacks.txt", "w") as f:
        f.write(pystacks_lines(0, 12000))
    cfg = live_cfg(log, viz_downsample_to=800)
    assert sofa_live(cfg, epochs=1) == 0
    # nothing grows: the whole epoch is a no-op (no dirty frames)
    assert sofa_live(cfg, epochs=1) == 0
    ml = meta_live(log)
    assert ml["tiles"] == {"rebuilt": 0, "kept": 0, "full_rebuilds": 0}
    assert ml["passes"] == {"ran": 0, "skipped_clean": 0}


# --- incremental pass window -------------------------------------------------

def test_incremental_pass_window(tmp_path):
    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 200))
    with open(log + "pystacks.txt", "w") as f:
        f.write(pystacks_lines(0, 200))
    cfg = live_cfg(log)
    assert sofa_live(cfg, epochs=1) == 0
    doc = load_manifest(log)
    ledger0 = doc["meta"]["passes"]["passes"]
    ran0 = {n for n, e in ledger0.items() if e.get("status") == "ok"}
    assert "tpu_mon" in ran0 or any("mon" in n for n in ran0)
    import pandas as pd

    feats0 = pd.read_csv(log + "features.csv")
    assert "py_samples" in set(feats0["name"])
    # epoch 2: only tpumon dirty -> passes reading pystacks skip clean,
    # but their features survive via the previous-features injection
    with open(log + "tpumon.txt", "a") as f:
        f.write(tpumon_lines(200, 400))
    assert sofa_live(cfg, epochs=1) == 0
    doc = load_manifest(log)
    ledger = doc["meta"]["passes"]["passes"]
    clean = {n for n, e in ledger.items()
             if e.get("status") == "skipped"
             and "unchanged" in str(e.get("skip_reason", ""))}
    ran = {n for n, e in ledger.items() if e.get("status") == "ok"}
    assert clean, "no pass skipped clean on an incremental epoch"
    assert ran, "no pass re-ran for the dirty frame"
    assert all("tpumon" not in " ".join(
        getattr(_spec(n), "reads_frames", ())) for n in clean)
    feats = pd.read_csv(log + "features.csv")
    assert "py_samples" in set(feats["name"])  # injected, not recomputed
    tm0 = feats0.set_index("name")["value"]
    tm1 = feats.set_index("name")["value"]
    assert tm1["tpumon_samples"] == 2 * tm0["tpumon_samples"]  # recomputed
    assert tm1["py_samples"] == tm0["py_samples"]


def _spec(name):
    from sofa_tpu.analysis import registry

    registry.load_builtin_passes()
    return registry.get(name)


def test_select_for_dirty_transitive_closure():
    from sofa_tpu.analysis import registry

    registry.load_builtin_passes()
    cfg = SofaConfig()
    sel = registry.select_for_dirty(cfg, {"tputrace"})
    assert any(s for s in sel)
    # every selected pass either reads the dirty frame or depends
    # (transitively) on one that does
    specs = {s.name: s for s in registry.registered() if s.enabled(cfg)}
    deps = registry.pass_dependencies(list(specs.values()))
    for name in sel:
        ok = "tputrace" in specs[name].reads_frames or any(
            d in sel for d in deps.get(name, ()))
        assert ok, f"{name} selected without a path to the dirty frame"
    assert registry.select_for_dirty(cfg, set()) == set()


# --- stream faults -----------------------------------------------------------

def test_stream_fault_grammar():
    plan = faults.parse("tpumon:tail_torn@2,strace:rotate,"
                        "pystacks:stall@always,service:stall@start,"
                        "pcap:tail_truncate")
    assert plan.stream_fault("tpumon", 2).kind == "tail_torn"
    assert plan.stream_fault("tpumon", 1) is None
    assert plan.stream_fault("strace", 1).kind == "rotate"
    assert plan.stream_fault("strace", 2) is None
    assert plan.stream_fault("pystacks", 7).kind == "stall"
    assert plan.stream_fault("nettrace", 1).kind == "tail_truncate"
    # `stall` against `service` stays a transport fault
    assert plan.service_fault("service", "put", "k").kind == "stall"
    with pytest.raises(ValueError):
        faults.parse("x:tail_torn@bogus")
    with pytest.raises(ValueError):
        faults.parse("x:rotate@0")


def test_tail_torn_fault_backs_off(tmp_path):
    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 100))
    cfg = live_cfg(log, inject_faults="tpumon:tail_torn@1")
    assert sofa_live(cfg, epochs=1) == 0
    led = json.load(open(log + OFFSETS_NAME))
    size = os.path.getsize(log + "tpumon.txt")
    assert 0 < led["sources"]["tpumon"]["offset"] < size
    # next epoch (no fault) catches up to the full file
    cfg2 = live_cfg(log)
    assert sofa_live(cfg2, epochs=1) == 0
    led = json.load(open(log + OFFSETS_NAME))
    assert led["sources"]["tpumon"]["offset"] == size


def test_tail_truncate_fault(tmp_path):
    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 100))
    cfg = live_cfg(log, inject_faults="tpumon:tail_truncate@1")
    assert sofa_live(cfg, epochs=1) == 0
    led = json.load(open(log + OFFSETS_NAME))
    size = os.path.getsize(log + "tpumon.txt")
    assert 0 < led["sources"]["tpumon"]["offset"] <= size // 2 + 64


def test_rotation_reingests_from_zero(tmp_path):
    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 200))
    cfg = live_cfg(log)
    assert sofa_live(cfg, epochs=1) == 0
    rotated = tpumon_lines(500, 600)
    with open(log + "tpumon.txt", "w") as f:
        f.write(rotated)
    assert sofa_live(cfg, epochs=1) == 0
    ml = meta_live(log)
    assert ml["sources"]["tpumon"]["status"] == "rotated"
    led = json.load(open(log + OFFSETS_NAME))
    assert led["sources"]["tpumon"]["offset"] == len(rotated.encode())
    assert led["sources"]["tpumon"]["chunks"][0][0] == 0
    # the stale pre-rotation events are gone from the frame (per tick:
    # 1 heartbeat row + 2 devices x 2 metric rows)
    assert ml["sources"]["tpumon"]["events"] == 100 * 5


def test_rotate_fault_forces_the_path(tmp_path):
    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 100))
    cfg = live_cfg(log)
    assert sofa_live(cfg, epochs=1) == 0
    cfg2 = live_cfg(log, inject_faults="tpumon:rotate@2")
    assert sofa_live(cfg2, epochs=1) == 0
    assert meta_live(log)["sources"]["tpumon"]["status"] == "rotated"


# --- stalled-source degradation ----------------------------------------------

def test_stalled_source_degrades_while_siblings_stream(tmp_path):
    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 100))
    with open(log + "pystacks.txt", "w") as f:
        f.write(pystacks_lines(0, 100))
    cfg = live_cfg(log, live_stall_s=0.01,
                   inject_faults="pystacks:stall@always")
    assert sofa_live(cfg, epochs=1) == 0
    time.sleep(0.05)
    with open(log + "tpumon.txt", "a") as f:
        f.write(tpumon_lines(100, 200))
    with open(log + "pystacks.txt", "a") as f:
        f.write(pystacks_lines(100, 200))  # grows, but the fault freezes it
    rc = sofa_live(cfg, epochs=1)
    ml = meta_live(log)
    assert ml["sources"]["pystacks"]["status"] == "stalled"
    assert ml["sources"]["tpumon"]["status"] == "streaming"
    assert rc == 1  # degraded at exit, stated
    probs = _mc().validate_manifest(load_manifest(log),
                                    require_healthy=True)
    assert any("stalled" in p for p in probs)
    assert _mc().validate_manifest(load_manifest(log)) == []


def test_all_quiet_is_idle_not_stalled(tmp_path):
    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 100))
    cfg = live_cfg(log, live_stall_s=0.01)
    assert sofa_live(cfg, epochs=1) == 0
    time.sleep(0.05)
    assert sofa_live(cfg, epochs=1) == 0  # nothing grows: idle, rc 0
    assert meta_live(log)["sources"]["tpumon"]["status"] == "idle"


# --- crash / resume / drain convergence --------------------------------------

_KILL_SNIPPET = """
import os, signal, sys
sys.path.insert(0, sys.argv[2])
from sofa_tpu import tiles
orig = tiles._write_tile
count = [0]
def hook(*a, **kw):
    count[0] += 1
    if count[0] >= 2:
        os.kill(os.getpid(), signal.SIGKILL)
    return orig(*a, **kw)
tiles._write_tile = hook
from sofa_tpu.config import SofaConfig
from sofa_tpu.live import sofa_live
cfg = SofaConfig(logdir=sys.argv[1], live_interval_s=0.0,
                 viz_downsample_to=800)
sofa_live(cfg, epochs=1)
"""


def test_sigkill_mid_epoch_drain_byte_identical_to_batch(tmp_path):
    """The acceptance spine: SIGKILL inside a live epoch's tile refresh,
    `sofa resume` replays the uncommitted epoch, `sofa live --drain`
    converges to artifacts byte-identical to a batch run."""
    from sofa_tpu.analyze import sofa_analyze
    from sofa_tpu.durability import sofa_resume
    from sofa_tpu.preprocess import sofa_preprocess
    from sofa_tpu.record import sofa_clean

    log = seed_logdir(tmp_path)
    with open(log + "pystacks.txt", "w") as f:
        f.write(pystacks_lines(0, 12000))
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 300))
    # control: batch over the FINAL raw state
    ctrl = SofaConfig(logdir=log, viz_downsample_to=800)
    sofa_analyze(ctrl, frames=sofa_preprocess(ctrl))
    want = {}
    for rel in ("report.js", "features.csv"):
        with open(log + rel, "rb") as f:
            want[rel] = f.read()
    sofa_clean(ctrl)

    # live: epoch over a truncated tail, then the killed catch-up epoch
    cfg = live_cfg(log, viz_downsample_to=800)
    with open(log + "pystacks.txt", "rb") as f:
        data = f.read()
    cut = data[:len(data) // 2]
    cut = cut[:cut.rfind(b"\n") + 1]
    with open(log + "pystacks.txt", "wb") as f:
        f.write(cut)
    assert sofa_live(cfg, epochs=1) == 0
    with open(log + "pystacks.txt", "ab") as f:
        f.write(data[len(cut):])
    r = subprocess.run(
        [sys.executable, "-c", _KILL_SNIPPET, log, _ROOT],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == -signal.SIGKILL, r.stderr[-300:]
    assert sofa_resume(SofaConfig(logdir=log)) == 0
    ml = meta_live(log)
    assert ml["epoch"] == 2  # the replayed epoch committed
    assert sofa_live(SofaConfig(logdir=log, viz_downsample_to=800),
                     epochs=0, drain=True) == 0
    for rel, want_bytes in want.items():
        with open(log + rel, "rb") as f:
            assert f.read() == want_bytes, f"{rel} diverged from batch"
    assert meta_live(log)["active"] is False
    assert _mc().validate_manifest(load_manifest(log)) == []


def test_resume_replays_uncommitted_epoch(tmp_path):
    from sofa_tpu.durability import JOURNAL_NAME, sofa_resume

    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 100))
    cfg = live_cfg(log)
    assert sofa_live(cfg, epochs=1) == 0
    # drop the live commit marker: a crash one instruction before commit
    with open(log + JOURNAL_NAME) as f:
        lines = [ln for ln in f.read().splitlines()
                 if '"commit"' not in ln or '"live"' not in ln]
    with open(log + JOURNAL_NAME, "w") as f:
        f.write("\n".join(lines) + "\n")
    assert sofa_resume(SofaConfig(logdir=log)) == 0
    assert meta_live(log)["epoch"] == 2  # one replayed epoch, committed


def test_resume_noop_when_epoch_committed(tmp_path, capsys):
    from sofa_tpu.durability import sofa_resume

    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 50))
    assert sofa_live(live_cfg(log), epochs=1) == 0
    # raw may keep growing between epochs — that is the next tick's
    # business, not an uncommitted suffix
    with open(log + "tpumon.txt", "a") as f:
        f.write(tpumon_lines(50, 60))
    assert sofa_resume(SofaConfig(logdir=log)) == 0
    assert meta_live(log)["epoch"] == 1  # no replay happened


# --- mid-epoch reads ---------------------------------------------------------

def test_no_write_sentinel_during_live_epochs(tmp_path):
    """Live writes are atomic: the derived_write_guard sentinel is never
    raised, so a concurrent board reader is never 503'd."""
    from sofa_tpu import live as live_mod
    from sofa_tpu.trace import WRITING_SENTINEL

    seen = []
    orig = live_mod._run_epoch

    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 100))

    import sofa_tpu.tiles as tiles_mod

    orig_write = tiles_mod._write_tile

    def spy(path, doc):
        seen.append(os.path.exists(log + WRITING_SENTINEL))
        return orig_write(path, doc)

    tiles_mod._write_tile = spy
    try:
        assert sofa_live(live_cfg(log, viz_downsample_to=50), epochs=1) == 0
    finally:
        tiles_mod._write_tile = orig_write
    assert not os.path.exists(log + WRITING_SENTINEL)
    assert seen and not any(seen)
    assert orig is live_mod._run_epoch


# --- CLI ---------------------------------------------------------------------

def test_cli_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "sofa_tpu", "live",
         str(tmp_path / "nope")],
        capture_output=True, text=True, timeout=120, env=env, cwd=_ROOT)
    assert r.returncode == 1  # curated usage error, no traceback
    assert "does not exist" in r.stdout + r.stderr
    assert "Traceback" not in r.stderr

    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 50))
    r = subprocess.run(
        [sys.executable, "-m", "sofa_tpu", "live", log,
         "--live_epochs", "1", "--live_interval_s", "0"],
        capture_output=True, text=True, timeout=300, env=env, cwd=_ROOT)
    assert r.returncode == 0, r.stderr[-400:]
    assert os.path.isfile(log + OFFSETS_NAME)
    r = subprocess.run(
        [sys.executable, "-m", "sofa_tpu", "live", log, "--drain"],
        capture_output=True, text=True, timeout=300, env=env, cwd=_ROOT)
    assert r.returncode == 0, r.stderr[-400:]
    assert meta_live(log)["active"] is False


def test_clean_sweeps_live_state(tmp_path):
    from sofa_tpu.record import sofa_clean

    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 50))
    cfg = live_cfg(log)
    assert sofa_live(cfg, epochs=1) == 0
    assert os.path.isfile(log + OFFSETS_NAME)
    sofa_clean(cfg)
    assert not os.path.exists(log + OFFSETS_NAME)
    assert not os.path.exists(log + "_ingest_cache")
    assert os.path.isfile(log + "tpumon.txt")  # raw stays


def test_clean_keeps_perf_script_without_perf_data(tmp_path):
    """The PR 12 resume defect: on a logdir holding only the
    pre-converted perf.script (no perf.data to regenerate it from), the
    text IS the raw evidence and `sofa clean` must keep it."""
    from sofa_tpu.record import sofa_clean

    log = seed_logdir(tmp_path)
    with open(log + "perf.script", "w") as f:
        f.write("python 100/100 [0] 1.0: 1 cycles: 400000 f+0x10 (/b)\n")
    sofa_clean(SofaConfig(logdir=log))
    assert os.path.isfile(log + "perf.script")
    # with perf.data present it is a regenerable conversion again
    with open(log + "perf.data", "wb") as f:
        f.write(b"PERFILE2")
    sofa_clean(SofaConfig(logdir=log))
    assert not os.path.exists(log + "perf.script")
    assert os.path.isfile(log + "perf.data")


# --- manifest schema ---------------------------------------------------------

def test_manifest_check_meta_live_vocabulary(tmp_path):
    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 50))
    assert sofa_live(live_cfg(log), epochs=1) == 0
    mc = _mc()
    doc = load_manifest(log)
    assert mc.validate_manifest(doc) == []
    bad = json.loads(json.dumps(doc))
    bad["meta"]["live"]["sources"]["tpumon"]["status"] = "vibing"
    assert any("status" in p for p in mc.validate_manifest(bad))
    bad = json.loads(json.dumps(doc))
    bad["meta"]["live"]["epoch"] = 0
    assert any("epoch" in p for p in mc.validate_manifest(bad))
    # an active stream whose watermark went stale is unhealthy
    stale = json.loads(json.dumps(doc))
    stale["meta"]["live"]["updated_unix"] = time.time() - 3600
    probs = mc.validate_manifest(stale, require_healthy=True)
    assert any("stale" in p for p in probs)
    # a drained one is not
    drained = json.loads(json.dumps(stale))
    drained["meta"]["live"]["active"] = False
    probs = mc.validate_manifest(drained, require_healthy=True)
    assert not any("stale" in p for p in probs)


def test_status_renders_live_line(tmp_path, capsys):
    from sofa_tpu.telemetry import sofa_status

    log = seed_logdir(tmp_path)
    with open(log + "tpumon.txt", "w") as f:
        f.write(tpumon_lines(0, 50))
    assert sofa_live(live_cfg(log), epochs=1) == 0
    assert sofa_status(SofaConfig(logdir=log)) == 0
    out = capsys.readouterr().out
    assert "live: epoch 1 active" in out


# --- board contract ----------------------------------------------------------

def test_board_live_poll_helpers_shipped():
    board = os.path.join(_ROOT, "sofa_tpu", "board")
    with open(os.path.join(board, "sofa_board.js")) as f:
        js = f.read()
    assert "function initLivePoll" in js
    assert "run_manifest.json" in js
    assert "liveStatusText" in js
    with open(os.path.join(board, "index.html")) as f:
        html = f.read()
    assert "initLivePoll" in html


# --- slow e2e over the pod_synth harness -------------------------------------

@pytest.mark.slow
def test_live_chaos_cells_end_to_end(tmp_path):
    """kill-mid-live-epoch + source-rotate-mid-tail over pod_synth --raw
    (tools/chaos_matrix.py) — the full acceptance convergence proof."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "chaos_matrix", os.path.join(_ROOT, "tools", "chaos_matrix.py"))
    cm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cm)
    mc = cm._load_manifest_check()
    synth = cm._synth(str(tmp_path))
    problems = cm._run_live_kill_cell(str(tmp_path), synth, mc)
    assert problems == [], f"kill-mid-live-epoch: {problems}"
    problems = cm._run_live_rotate_cell(str(tmp_path), synth, mc)
    assert problems == [], f"source-rotate-mid-tail: {problems}"
