"""Clock-domain conversion built from timebase.txt.

timebase.txt rows are simultaneous (realtime, monotonic, boottime,
monotonic_raw) nanosecond samples (sofa_tpu/native/timebase.cc), taken at
record start AND record end (collectors/timebase.py).  When the samples span
enough wall time, a least-squares linear fit captures clock drift/NTP slew
(long runs, multi-host skew); clustered samples fall back to a mean offset.
Replaces the reference's perf_timebase.txt parsing
(/root/reference/bin/sofa_preprocess.py:1765-1784).
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import numpy as np

CLOCKS = {"realtime": 0, "monotonic": 1, "boottime": 2, "monotonic_raw": 3}


def load_timebase(path: str) -> Optional[np.ndarray]:
    if not os.path.isfile(path):
        return None
    rows = []
    with open(path) as f:
        for line in f:
            p = line.split()
            if len(p) == 4:
                try:
                    rows.append([int(v) for v in p])
                except ValueError:
                    continue
    if not rows:
        return None
    return np.array(rows, dtype=np.int64)


# Minimum sample spread for a slope fit: below this, noise in the bracketing
# reads dominates and an offset is strictly better.
_MIN_FIT_SPREAD_NS = 1e9
# Real clock drift is ppm-scale; a fit outside this band means bad samples.
_MAX_DRIFT = 1e-3


def converter(path: str, source_clock: str = "monotonic") -> Optional[Callable[[float], float]]:
    """Return f(seconds in source clock) -> unix seconds, or None."""
    table = load_timebase(path)
    if table is None:
        return None
    col = CLOCKS[source_clock]
    x = table[:, col].astype(np.float64)
    y = table[:, 0].astype(np.float64)
    offset_ns = float(np.mean(y - x))
    slope = 1.0
    spread = float(x.max() - x.min())
    if len(x) >= 2 and spread >= _MIN_FIT_SPREAD_NS:
        xc = x - x.mean()
        fit = float((xc * (y - y.mean())).sum() / (xc * xc).sum())
        if abs(fit - 1.0) <= _MAX_DRIFT:
            slope = fit
    x0, y0 = float(x.mean()), float(y.mean())

    def f(t_s: float) -> float:
        if slope == 1.0:
            return t_s + offset_ns / 1e9
        return (y0 + slope * (t_s * 1e9 - x0)) / 1e9

    return f
