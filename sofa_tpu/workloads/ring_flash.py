"""Ring attention with the fused Pallas kernel on every hop.

Combines the two long-context mechanisms in this package: sequence
parallelism (K/V blocks rotate around a mesh axis over `lax.ppermute`,
riding ICI neighbor links — sofa_tpu/workloads/ring_attention.py) and the
streaming flash kernel (sofa_tpu/workloads/flash_pallas.py).  Each hop runs
the kernel over the visiting K/V block with a *dynamic causal shift*
(hop i on device r sees shift (i - n·[i>r])·T_local: aligned-causal for the
home block, full for blocks from earlier shards, fully-masked for later
shards), and hops are folded together by their per-row logsumexp — so
neither the per-hop [T_local, T_local] score matrix nor any cross-shard
gather ever materializes.  Per-chip live memory is O(B·H·T_local·block).

The backward is the ring form of the flash gradient: dK/dV accumulators
rotate around the ring *with* their K/V blocks, each device adds its
blockwise contribution (recomputed from the saved global logsumexp), and
after axis_size hops every accumulator is home.  One extra round-trip of
ppermute traffic, no replay of the forward.

The reference profiler only *observed* such traffic (P2P copy matrices,
/root/reference/bin/sofa_common.py:97-157); here the canonical generator of
ICI collective-permute traffic is also memory-optimal.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from sofa_tpu.workloads.flash_pallas import _flash_forward, _grad_block
from sofa_tpu.workloads.ring_attention import NEG_INF


def _hop_shift(i, r, n, t_local):
    """Causal shift for hop i on ring position r: the visiting block came
    from shard (r - i) mod n, so its keys sit (i mod n) shards *behind* the
    local queries — except when i > r, where the wrap makes them later
    shards (fully masked, negative shift)."""
    return (i - jnp.where(i > r, n, 0)) * t_local


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def ring_flash_attention_local(q, k, v, axis_name: str):
    """Exact causal attention over the ``axis_name``-sharded sequence.

    q, k, v: [B, T_local, H, D] — this chip's shard.  Runs inside shard_map.
    """
    out, _ = _ring_fwd_impl(q, k, v, axis_name)
    return out


def _ring_fwd_impl(q, k, v, axis_name):
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    zero = q.astype(jnp.float32) * 0.0                 # carries q's VMA type
    o0 = zero
    lse0 = zero[..., 0].transpose(0, 2, 1) + NEG_INF   # [B, H, T]

    def hop(carry, i):
        o, lse, k_blk, v_blk = carry
        shift = _hop_shift(i, r, n, t)
        o_i, lse_i = _flash_forward(q, k_blk, v_blk, shift, 128, 128, None)
        new_lse = jnp.logaddexp(lse, lse_i)
        a = jnp.exp(lse - new_lse).transpose(0, 2, 1)[..., None]
        bb = jnp.exp(lse_i - new_lse).transpose(0, 2, 1)[..., None]
        o = o * a + o_i.astype(jnp.float32) * bb
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o, new_lse, k_blk, v_blk), None

    (o, lse, _, _), _ = lax.scan(hop, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype), lse


def _ring_fwd(q, k, v, axis_name):
    out, lse = _ring_fwd_impl(q, k, v, axis_name)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, res, g):
    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    t = q.shape[1]
    perm = [(j, (j + 1) % n) for j in range(n)]
    delta = jnp.einsum("bqhd,bqhd->bhq", g.astype(jnp.float32),
                       out.astype(jnp.float32))

    zero_kv = k.astype(jnp.float32) * 0.0

    def hop(carry, i):
        dq, k_blk, v_blk, dk_acc, dv_acc = carry
        shift = _hop_shift(i, r, n, t)
        dq_i, dk_i, dv_i = _grad_block(q, k_blk, v_blk, g, delta, lse, shift)
        dq = dq + dq_i
        dk_acc = dk_acc + dk_i
        dv_acc = dv_acc + dv_i
        # Rotate the K/V blocks and their gradient accumulators together:
        # after n hops each accumulator is back on its home shard carrying
        # every device's contribution.
        k_blk, v_blk, dk_acc, dv_acc = (
            lax.ppermute(x, axis_name, perm)
            for x in (k_blk, v_blk, dk_acc, dv_acc))
        return (dq, k_blk, v_blk, dk_acc, dv_acc), None

    dq0 = q.astype(jnp.float32) * 0.0
    (dq, _, _, dk, dv), _ = lax.scan(
        hop, (dq0, k, v, zero_kv, zero_kv), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_flash_attention_local.defvjp(_ring_fwd, _ring_bwd)


def ring_flash_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                         batch_axis: Optional[str] = "data",
                         head_axis: Optional[str] = "model"):
    """shard_map-wrapped ring flash attention over a global [B, T, H, D].

    Drop-in for ring_attention() when the per-hop score matrix must not
    materialize (long T_local); heads shard over ``head_axis`` (TP), batch
    over ``batch_axis``, sequence over ``seq_axis``.
    """
    spec = P(batch_axis, seq_axis, head_axis, None)

    def fn(q, k, v):
        return ring_flash_attention_local(q, k, v, seq_axis)

    # check_vma=False: pallas_call's out_shape carries no varying-manual-axes
    # type, which the VMA checker (rightly) rejects; the kernel output is
    # per-shard by construction here.
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
