"""The examples/ profiling targets must keep running (they are the first
thing a new user points `sofa stat` at)."""

import os
import runpy
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _example(name):
    return os.path.join(_ROOT, "examples", name)


def test_io_churn_runs(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    sys.path.insert(0, os.path.dirname(_example("io_churn.py")))
    try:
        mod = runpy.run_path(_example("io_churn.py"), run_name="not_main")
        mod["main"](mb=4)
    finally:
        sys.path.pop(0)
    assert "wrote+read 4 MiB" in capsys.readouterr().out


def test_train_tiny_runs(capsys):
    mod = runpy.run_path(_example("train_tiny.py"), run_name="not_main")
    mod["main"](steps=2)
    assert "final loss" in capsys.readouterr().out


def test_long_context_runs(capsys):
    mod = runpy.run_path(_example("long_context.py"), run_name="not_main")
    mod["main"](steps=2, seq=64)
    out = capsys.readouterr().out
    assert "final loss" in out and "remat=on" in out


def test_serve_tiny_runs(capsys):
    mod = runpy.run_path(_example("serve_tiny.py"), run_name="not_main")
    mod["main"](requests=2, prompt=16, new_tokens=4)
    assert "served 2 requests" in capsys.readouterr().out


def test_matmul_burn_runs(capsys):
    mod = runpy.run_path(_example("matmul_burn.py"), run_name="not_main")
    mod["main"](seconds=0.5, n=128)
    assert "burns in" in capsys.readouterr().out
