"""Optimization advice: device-mesh/ring recommendations + rule-based hints.

Two reference features re-imagined for TPU:

* xring (sofa_analyze.py:825-869 + tools/xring.py): NVLink-topology ring
  search producing a CUDA_VISIBLE_DEVICES order.  TPU equivalent: order chips
  along the ICI torus by their (x,y,z) coords and propose `jax.sharding.Mesh`
  axis shapes that keep collectives on ICI; written to
  sofa_hints/mesh_advice.txt.

* POTATO hint service (sofa_analyze.py:49-73,1007-1048): remote gRPC advice
  on the feature vector.  Local rules below give instant advice; the optional
  gRPC client/server lives in analysis/hint_service.py.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from sofa_tpu.analysis.features import Features
from sofa_tpu.analysis.comm import load_topology
from sofa_tpu.analysis.registry import analysis_pass
from sofa_tpu.printing import print_hint


def _factorizations(n: int) -> List[Tuple[int, ...]]:
    """All 2D factor pairs of n, most-square first (good default meshes)."""
    out = []
    for a in range(1, int(n ** 0.5) + 1):
        if n % a == 0:
            out.append((a, n // a))
    return sorted(out, key=lambda p: abs(p[0] - p[1]))


@analysis_pass(
    name="mesh_advice", order=240,
    provides_features=("mesh_advice",),
    provides_artifacts=("mesh_advice.txt",),
)
def mesh_advice(frames, cfg, features: Features) -> None:
    topo = load_topology(cfg)
    if topo is None:
        return
    devices = topo.get("devices", [])
    n = len(devices)
    if n == 0:
        return
    lines = []
    have_coords = all(d.get("coords") for d in devices)
    ring = sorted(
        devices,
        key=lambda d: (_snake_key(d.get("coords") or [d["id"]]), d.get("core_on_chip", 0)),
    )
    ring_ids = [d["id"] for d in ring]
    lines.append("# sofa_tpu mesh advice")
    lines.append(f"device_count = {n}")
    if have_coords:
        lines.append(f"ici_ring_order = {ring_ids}  # snake order over torus coords")
    else:
        lines.append(f"ring_order = {ring_ids}  # by device id (no coords available)")
    if n > 1:
        shapes = _factorizations(n)[:3]
        lines.append("suggested 2D meshes (data, model):")
        for dp, tp in shapes:
            lines.append(
                f"  jax.make_mesh(({dp}, {tp}), ('data', 'model'))"
            )
        lines.append(
            "put the model axis on the inner (fastest-varying, coord-adjacent)"
            " chips so tensor-parallel collectives stay on shortest ICI paths"
        )
    hints_dir = cfg.path("sofa_hints")
    os.makedirs(hints_dir, exist_ok=True)
    from sofa_tpu.durability import atomic_write

    with atomic_write(os.path.join(hints_dir, "mesh_advice.txt")) as f:
        f.write("\n".join(lines) + "\n")
    features.add_info("mesh_advice", f"{hints_dir}/mesh_advice.txt")


def _snake_key(coords):
    """Snake (boustrophedon) order over torus coords: traverse the innermost
    dimension forward or backward depending on the parity of the outer
    coordinates, so consecutive devices in the sort are nearest neighbors."""
    key = []
    parity = 0
    for c in coords:
        key.append(-c if parity % 2 else c)
        parity += c
    return tuple(key)


# ---------------------------------------------------------------------------
# Rule-based hints on the feature vector (local POTATO).

def _pct(v: Optional[float]) -> float:
    return float(v) if v is not None else 0.0


def generate_hints(features: Features, cfg) -> List[str]:
    hints: List[str] = []
    get = features.get

    comm_ratio = _pct(get("comm_ratio"))
    if comm_ratio >= 0.15:
        # The reference's compute- vs communication-bound verdict threshold
        # (sofa_aisi.py:503-507).
        hints.append(
            f"communication-bound: collectives take {comm_ratio:.0%} of device"
            " time — try larger per-chip batch, gradient-accumulation, or a"
            " mesh shape that shortens the all-reduce ring (see mesh_advice)"
        )
    elif get("tpu_ops") is not None:
        hints.append(f"compute-bound: collectives take {comm_ratio:.0%} of device time")

    # Per-device rules scan tpu<N>_* (NOT hardcoded tpu0): multi-host device
    # ids start at host_index*256, so there may be no device 0.  The worst
    # device drives each hint.
    effs = features.by_regex(r"tpu\d+_roofline_efficiency")
    if effs:
        name, eff = min(effs, key=lambda nv: nv[1])
        dev = name.split("_", 1)[0]
        if eff < 0.4:
            mem_t = get(f"{dev}_memory_bound_time")
            cmp_t = get(f"{dev}_compute_bound_time")
            dominant = ("memory" if (mem_t or 0) >= (cmp_t or 0)
                        else "compute")
            fix = ("fuse elementwise chains into matmuls and raise arithmetic"
                   " intensity (larger batch/tiles)" if dominant == "memory"
                   else
                   "check matmul shapes against the 128x128 MXU tile and"
                   " prefer bf16 inputs")
            hints.append(
                f"ops on {dev} run at {eff:.0%} of their roofline bound and"
                f" {dominant}-bound time dominates — {fix} (see roofline.csv)"
            )

    exposed = []
    for name, hidden in features.by_regex(r"tpu\d+_async_hidden_pct"):
        dev = name.split("_", 1)[0]
        atime = get(f"{dev}_async_time")
        optime = get(f"{dev}_op_time")
        if (hidden < 50.0 and atime and optime
                and atime > 0.05 * optime):
            exposed.append((hidden, dev))
    if exposed:
        hidden, dev = min(exposed)
        hints.append(
            f"exposed DMA latency on {dev}: only {hidden:.0f}% of async copy"
            " time overlaps TensorCore compute — enable/raise prefetching"
            " (double-buffer inputs, jax.block_until_ready placement) or"
            " fuse small transfers"
        )

    gaps = features.by_regex(r"tpu\d+_step_gap_pct")
    if gaps:
        name, gap = max(gaps, key=lambda nv: nv[1])
        dev = name.split("_", 1)[0]
        if gap > 15.0:
            h2d = get(f"{dev}_step_h2d_pct") or 0.0
            cause = (
                f"host->device transfers cover {h2d:.0f}% of step time — the"
                " input pipeline is the likely gate; prefetch batches to"
                " device (double-buffer) or move preprocessing off the host"
                if h2d > 0.2 * gap else
                "little H2D activity fills the gaps — look at collective"
                " waits, host callbacks, or synchronous eval between steps")
            hints.append(
                f"device idle inside steps on {dev}: TensorCore covers only"
                f" {100.0 - gap:.0f}% of step time — {cause}"
                " (see tpu_input_pipeline.csv)")

    unattr = get("tpu_customcall_unattributed_time")
    if unattr:
        op_total = sum(v for _, v in features.by_regex(r"tpu\d+_op_time"))
        if op_total and unattr > 0.05 * op_total:
            hints.append(
                f"unattributed kernel time: custom-call ops take "
                f"{unattr / op_total:.0%} of device time but carry no "
                "flops/bytes metadata — XLA cannot cost hand-written "
                "(Mosaic/Pallas) kernels, so the roofline and top-ops "
                "flops undercount exactly the hottest ops; annotate "
                "pallas_call with name= and pl.CostEstimate "
                "(docs/KERNELS.md)"
            )

    skew = get("step_skew_mean")
    step_mean = get("step_time_mean") or get("aisi_step_time_mean")
    if skew is not None and step_mean and skew > 0.05 * step_mean:
        hints.append(
            f"straggler skew: devices start the same step {skew * 1e3:.2f} ms"
            " apart on average — check uneven sharding, host input pipelines,"
            " or DCN interference (see tpu_step_skew.csv)"
        )

    mxu = get("mxu_util_mean")
    if mxu is not None and mxu < 30.0:
        hints.append(
            f"MXU utilization is low ({mxu:.1f}% mean) — check for small"
            " matmul shapes, fp32 where bf16 would do, or excessive"
            " elementwise ops that cannot use the systolic array"
        )
    infeed = get("comm_h2d_time")
    tpu_busy = get("tpu0_op_time")
    if infeed and tpu_busy and infeed > 0.2 * tpu_busy:
        hints.append(
            "input-bound: host->device transfer is a large fraction of device"
            " time — prefetch batches (double buffering) or move preprocessing"
            " off the host"
        )
    iow = _pct(get("elapsed_iow_ratio"))
    if iow > 0.2:
        hints.append(
            f"I/O-wait dominates {iow:.0%} of wall time — data loading is"
            " likely the bottleneck (consider caching or faster storage)"
        )
    idl = _pct(get("elapsed_idl_ratio"))
    if idl > 0.5:
        hints.append(
            f"{idl:.0%} of wall time is idle — the accelerator is starved or"
            " the workload is tiny relative to the recording window"
        )
    cpu_util = get("cpu_util")
    ncores = get("num_cores")
    if cpu_util is not None and ncores and cpu_util > 0.85:
        hints.append(
            "host CPU is saturated — data pipeline or Python overhead may be"
            " gating the TPU"
        )

    # What-if payoffs (the whatif_model pass priced the two canonical
    # scenarios over this run's own step timeline — sofa_tpu/whatif/):
    # rank the predicted savings, largest first, and point at the verb
    # that previews the full composition with calibrated error bars.
    payoffs = []
    for name, scenario, story in (
        ("whatif_overlap_payoff_pct", "overlap:*",
         "hiding serialized collectives behind step compute"),
        ("whatif_sol_payoff_pct", "scale:*=sol",
         "running every kernel class at its measured speed-of-light "
         "headroom"),
    ):
        pct = get(name)
        if pct is not None and pct >= 2.0:
            payoffs.append((pct, scenario, story))
    for pct, scenario, story in sorted(payoffs, reverse=True):
        hints.append(
            f"[whatif] {story} is predicted to cut mean step time by "
            f"{pct:.1f}% — preview with `sofa whatif <logdir> --apply "
            f"{scenario}` (calibrated error bars in whatif_report.json)")
    return hints


def hint_report(features: Features, cfg) -> None:
    hints = generate_hints(features, cfg)
    # Self-health rides the same hints channel: the run manifest's failed /
    # degraded collectors and sources (sofa_tpu/telemetry.py) are warnings
    # the user should read BEFORE trusting the workload-level hints above —
    # a hint computed from a half-captured trace is advice about the gap.
    from sofa_tpu import telemetry

    for w in telemetry.manifest_warnings(telemetry.load_manifest(cfg.logdir)):
        hints.append(f"[self] {w}")
    for h in hints:
        print_hint(h)
    if hints:
        from sofa_tpu.durability import atomic_write

        with atomic_write(cfg.path("hints.txt")) as f:
            f.write("\n".join(hints) + "\n")
