"""LOD timeline tile pyramid (sofa_tpu/tiles.py) + viz data server tests.

Pyramid invariants the board relies on:
  * the per-tile min/max envelope contains every raw point in the window;
  * level N+1 is a refinement of level N (same windows, split in two,
    same total event counts);
  * leaf tiles are exact — deepest zoom returns the raw events, lossless;
  * the build is deterministic under --jobs 1 vs --jobs 4 and content-
    keyed cached (a re-run over unchanged data rewrites nothing).

Server contract (sofa_tpu/viz.py): ETag/If-None-Match 304s, gzip
negotiation for the pre-compressed tiles, the /tiles/ route, 503 +
Retry-After while a writer holds the derived-write sentinel, and the
port-retry loop.
"""

import gzip
import http.client
import json
import os
import threading

import numpy as np
import pytest

from sofa_tpu import tiles
from sofa_tpu.config import SofaConfig
from sofa_tpu.trace import SofaSeries, make_frame

N_POINTS = 30000


def _series(n=N_POINTS, seed=0, name="tputrace"):
    rng = np.random.default_rng(seed)
    df = make_frame({
        "timestamp": np.sort(rng.uniform(0.0, 10.0, n)),
        "event": rng.normal(5.0, 2.0, n),
        "duration": rng.exponential(1e-4, n),
        "name": [f"op.{i % 50}" for i in range(n)],
    })
    return SofaSeries(name, "TPU HLO ops", "darkorchid", df)


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tiles")) + "/"
    cfg = SofaConfig(logdir=d)
    s = _series()
    manifest = tiles.build_tiles(cfg, [s])
    return cfg, s, manifest


def _all_tiles(cfg, ent):
    for level in range(ent["levels"]):
        for i in range(1 << level):
            t = tiles.read_tile(cfg.logdir, ent["path"], level, i)
            if t is not None:
                yield level, i, t


def _sorted_raw(s):
    df = s.data
    order = np.argsort(df["timestamp"].to_numpy(), kind="stable")
    return (df["timestamp"].to_numpy()[order],
            df["event"].to_numpy()[order],
            df["name"].astype(str).to_numpy()[order])


def test_envelope_contains_every_raw_point(built):
    cfg, s, manifest = built
    ent = manifest["series"]["tputrace"]
    xs, ys, _ = _sorted_raw(s)
    n_checked = 0
    for _level, _i, t in _all_tiles(cfg, ent):
        a, b = np.searchsorted(xs, [t["x0"], t["x1"]], side="left")
        seg = ys[a:a + t["count"]]
        assert len(seg) == t["count"]
        # tile values are rounded at 1e-6 before the envelope is taken
        assert t["ymin"] <= seg.min() + 1e-5
        assert t["ymax"] >= seg.max() - 1e-5
        n_checked += 1
    assert n_checked == ent["tile_count"]


def test_decimated_tile_keeps_per_bucket_extrema(built):
    """The kept points of a decimated tile trace the same outline as the
    raw data: every occupied bucket's true min and max y survive."""
    cfg, s, manifest = built
    ent = manifest["series"]["tputrace"]
    t = tiles.read_tile(cfg.logdir, ent["path"], 0, 0)
    assert not t["exact"] and t["buckets"] > 0
    xs, ys, _ = _sorted_raw(s)
    pts = tiles.tile_points(t)
    width = t["x1"] - t["x0"]
    raw_b = np.clip(((xs - t["x0"]) / width * t["buckets"]).astype(int),
                    0, t["buckets"] - 1)
    kept_b = np.clip(((pts["x"] - t["x0"]) / width * t["buckets"])
                     .astype(int), 0, t["buckets"] - 1)
    assert sum(t["density"]) == t["count"] == len(xs)
    for b in range(t["buckets"]):
        raw = ys[raw_b == b]
        if raw.size == 0:
            assert t["density"][b] == 0
            continue
        kept = pts["y"][kept_b == b]
        assert t["density"][b] == raw.size
        assert kept.size, f"bucket {b} lost all its points"
        assert kept.min() == pytest.approx(raw.min(), abs=1e-5)
        assert kept.max() == pytest.approx(raw.max(), abs=1e-5)


def test_levels_refine(built):
    """Tile (L, i) covers exactly tiles (L+1, 2i) and (L+1, 2i+1): same
    window, same total event count; leaf level partitions the series."""
    cfg, s, manifest = built
    ent = manifest["series"]["tputrace"]
    for level in range(ent["levels"] - 1):
        for i in range(1 << level):
            t = tiles.read_tile(cfg.logdir, ent["path"], level, i)
            if t is None:
                continue
            kids = [tiles.read_tile(cfg.logdir, ent["path"], level + 1, k)
                    for k in (2 * i, 2 * i + 1)]
            assert t["count"] == sum(k["count"] for k in kids if k)
            present = [k for k in kids if k]
            assert present[0]["x0"] == pytest.approx(t["x0"]) \
                or kids[0] is None
            assert present[-1]["x1"] == pytest.approx(t["x1"]) \
                or kids[1] is None
    leaf = ent["levels"] - 1
    total = sum(t["count"] for lv, _i, t in _all_tiles(cfg, ent)
                if lv == leaf)
    assert total == ent["count"] == N_POINTS


def test_deepest_zoom_is_exact(built):
    """Leaf tiles carry the raw events for their window — x, y, duration
    and names round-trip with no downsampling loss."""
    cfg, s, manifest = built
    ent = manifest["series"]["tputrace"]
    xs, ys, names = _sorted_raw(s)
    leaf = ent["levels"] - 1
    got_x, got_y, got_names = [], [], []
    for _lv, _i, t in ((lv, i, t) for lv, i, t in _all_tiles(cfg, ent)
                       if lv == leaf):
        assert t["exact"]
        pts = tiles.tile_points(t)
        got_x.extend(pts["x"])
        got_y.extend(pts["y"])
        got_names.extend(pts["name"])
    assert len(got_x) == len(xs)
    np.testing.assert_allclose(got_x, xs, atol=1e-6)
    np.testing.assert_allclose(got_y, ys, atol=1e-5)
    assert got_names == list(names)


def test_build_deterministic_jobs_1_vs_4(tmp_path):
    """--jobs must not leak into tile bytes: identical trees, bit for bit
    (gzip mtime pinned, stable decimation, deterministic interning)."""
    trees = {}
    for jobs in (1, 4):
        d = str(tmp_path / f"j{jobs}") + "/"
        cfg = SofaConfig(logdir=d, jobs=jobs)
        tiles.build_tiles(cfg, [_series(), _series(7000 + 8000, seed=3,
                                                   name="cputrace")],
                          jobs=jobs)
        tree = {}
        root = cfg.path(tiles.TILES_DIR_NAME)
        for base, _dirs, files in os.walk(root):
            for f in files:
                p = os.path.join(base, f)
                with open(p, "rb") as fh:
                    tree[os.path.relpath(p, root)] = fh.read()
        trees[jobs] = tree
    assert set(trees[1]) == set(trees[4])
    diff = [k for k in trees[1] if trees[1][k] != trees[4][k]]
    assert not diff, f"jobs-dependent tile bytes: {diff}"


def test_warm_rebuild_is_content_keyed_noop(built):
    cfg, s, manifest = built
    ent = manifest["series"]["tputrace"]
    tile0 = os.path.join(cfg.path(tiles.TILES_DIR_NAME), ent["path"],
                         "0", "0.json.gz")
    before = os.stat(tile0).st_mtime_ns
    manifest2 = tiles.build_tiles(cfg, [s])
    assert manifest2 == manifest
    assert os.stat(tile0).st_mtime_ns == before, "warm build rewrote tiles"
    # data change -> key miss -> rebuild
    s2 = _series(seed=9)
    manifest3 = tiles.build_tiles(cfg, [s2])
    assert os.stat(tile0).st_mtime_ns != before


def test_small_series_has_no_pyramid(tmp_path):
    d = str(tmp_path / "small") + "/"
    cfg = SofaConfig(logdir=d)
    manifest = tiles.build_tiles(cfg, [_series(n=500)])
    assert manifest["series"] == {}  # the overview is already exact


def test_tile_levels_cap_keeps_leaves_exact(tmp_path):
    d = str(tmp_path / "cap") + "/"
    cfg = SofaConfig(logdir=d, tile_levels=2)
    manifest = tiles.build_tiles(cfg, [_series()])
    ent = manifest["series"]["tputrace"]
    assert ent["levels"] == 2
    leaf_counts = 0
    for i in range(2):
        t = tiles.read_tile(d, ent["path"], 1, i)
        assert t["exact"], "capped pyramids must still bottom out exact"
        leaf_counts += t["count"]
    assert leaf_counts == N_POINTS


def test_series_dir_name_sanitizes_user_keywords():
    assert os.sep not in tiles.series_dir_name("tpu_a/b")
    assert tiles.series_dir_name("tpu_a/b") != tiles.series_dir_name("tpu_a_b")
    assert not tiles.series_dir_name("../evil").startswith(".")
    assert tiles.series_dir_name("cputrace") == "cputrace"


def test_derived_writing_sentinel(tmp_path):
    from sofa_tpu.trace import derived_write_guard, derived_writing

    d = str(tmp_path)
    assert not derived_writing(d)
    with derived_write_guard(d):
        assert derived_writing(d)
    assert not derived_writing(d)
    # a sentinel left by a dead writer must not wedge the server forever
    with open(os.path.join(d, "_derived.writing"), "w") as f:
        f.write("999999999")
    assert not derived_writing(d)
    # a torn sentinel (no pid yet) still reads as mid-write
    with open(os.path.join(d, "_derived.writing"), "w") as f:
        f.write("")
    assert derived_writing(d)


# --------------------------------------------------------------------------
# viz server
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def served(tmp_path_factory):
    from sofa_tpu.preprocess import build_series
    from sofa_tpu.trace import series_to_report_js
    from sofa_tpu.viz import sofa_viz

    d = str(tmp_path_factory.mktemp("served")) + "/"
    cfg = SofaConfig(logdir=d, viz_port=8941)
    s = _series()
    manifest = tiles.build_tiles(cfg, [s])
    series_to_report_js([s], cfg.path("report.js"),
                        cfg.viz_downsample_to, {"tiles": manifest})
    with open(cfg.path("index.html"), "w") as f:
        f.write("<html>board</html>")
    httpd = sofa_viz(cfg, serve_forever=False)
    assert httpd is not None
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield cfg, httpd, manifest
    httpd.shutdown()
    httpd.server_close()


def _get(httpd, path, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1",
                                      httpd.server_address[1], timeout=10)
    try:
        conn.request("GET", path, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def test_server_etag_304(served):
    cfg, httpd, _ = served
    status, headers, body = _get(httpd, "/report.js")
    assert status == 200 and body.startswith(b"sofa_traces = ")
    assert headers.get("Cache-Control") == "no-cache"
    etag = headers["ETag"]
    status2, headers2, body2 = _get(httpd, "/report.js",
                                    {"If-None-Match": etag})
    assert status2 == 304 and body2 == b""
    assert headers2["ETag"] == etag


def test_server_tile_gzip_negotiation(served):
    cfg, httpd, manifest = served
    ent = manifest["series"]["tputrace"]
    url = f"/tiles/{ent['path']}/0/0.json.gz"
    status, headers, gz_body = _get(httpd, url,
                                    {"Accept-Encoding": "gzip"})
    assert status == 200
    assert headers.get("Content-Encoding") == "gzip"
    assert headers.get("Content-Type") == "application/json"
    assert "max-age" in headers.get("Cache-Control", "")
    doc = json.loads(gzip.decompress(gz_body))
    assert doc["count"] == N_POINTS
    # a client without gzip gets the decompressed bytes, same document
    status2, headers2, plain = _get(httpd, url)
    assert status2 == 200 and headers2.get("Content-Encoding") is None
    assert plain == gzip.decompress(gz_body)
    # the suffixless spelling negotiates the precompressed sibling
    status3, headers3, body3 = _get(
        httpd, f"/_tiles/{ent['path']}/0/0.json",
        {"Accept-Encoding": "gzip"})
    assert status3 == 200 and headers3.get("Content-Encoding") == "gzip"
    assert body3 == gz_body


def test_server_sparse_tile_404(served):
    cfg, httpd, manifest = served
    ent = manifest["series"]["tputrace"]
    status, _h, _b = _get(httpd, f"/tiles/{ent['path']}/0/999.json.gz")
    assert status == 404


def test_server_503_while_mid_write(served):
    from sofa_tpu.trace import derived_write_guard

    cfg, httpd, manifest = served
    ent = manifest["series"]["tputrace"]
    with derived_write_guard(cfg.logdir):
        for path in ("/report.js",
                     f"/tiles/{ent['path']}/0/0.json.gz"):
            status, headers, _b = _get(httpd, path)
            assert status == 503, path
            assert headers.get("Retry-After") == "1"
        # board chrome keeps serving: only data can be torn mid-write
        status, _h, body = _get(httpd, "/index.html")
        assert status == 200 and b"board" in body
    status, _h, _b = _get(httpd, "/report.js")
    assert status == 200


def test_server_port_retry(served):
    from sofa_tpu.viz import sofa_viz

    cfg, httpd, _ = served
    second = sofa_viz(cfg, serve_forever=False)
    assert second is not None
    try:
        assert second.server_address[1] != httpd.server_address[1]
    finally:
        second.server_close()


def test_preprocess_report_carries_tiles_manifest(tmp_path):
    """End to end: preprocess over raw files emits columnar report.js
    whose meta.tiles names every pyramid series, the manifest records the
    tiles stage, and `sofa clean` removes the pyramid."""
    from sofa_tpu.preprocess import sofa_preprocess
    from sofa_tpu.record import sofa_clean
    from sofa_tpu.telemetry import load_manifest

    d = str(tmp_path / "log") + "/"
    os.makedirs(d)
    with open(d + "sofa_time.txt", "w") as f:
        f.write("1700000000.0\n")
    n = 25000
    with open(d + "pystacks.txt", "w") as f:
        f.write("".join(
            f"{1700000000.0 + i * 2.5 / n:.6f} {1 + i % 8} "
            f"main;train;step_{i % 50}\n" for i in range(n)))
    cfg = SofaConfig(logdir=d)
    sofa_preprocess(cfg)
    doc = json.loads(open(d + "report.js").read()
                     [len("sofa_traces = "):].rstrip(";\n"))
    tm = doc["meta"]["tiles"]
    assert "pystacks" in tm["series"]
    assert os.path.isdir(d + "_tiles/pystacks")
    man = load_manifest(d)
    assert any(s["name"] == "tiles" and s["verb"] == "preprocess"
               for s in man["stages"])
    meta = man["meta"]["tiles"]
    assert meta["series"] == 1 and meta["tile_count"] >= 1
    # --no_tiles skips the build
    d2 = str(tmp_path / "log2") + "/"
    os.makedirs(d2)
    with open(d2 + "pystacks.txt", "w") as f:
        f.write(open(d + "pystacks.txt").read())
    sofa_preprocess(SofaConfig(logdir=d2, enable_tiles=False))
    doc2 = json.loads(open(d2 + "report.js").read()
                      [len("sofa_traces = "):].rstrip(";\n"))
    assert "tiles" not in doc2["meta"]
    assert not os.path.isdir(d2 + "_tiles")
    # sofa clean removes the pyramid
    sofa_clean(cfg)
    assert not os.path.isdir(d + "_tiles")
