"""Clock-domain anchoring.

Writes two files at record start:

  sofa_time.txt  — the run's unix zero point (every trace timestamp becomes
                   t - time_base, like the reference's sofa_time.txt,
                   sofa_record.py:244-247)
  timebase.txt   — simultaneous (realtime, monotonic, boottime,
                   monotonic_raw) ns samples from the native tool (or a
                   Python clock_gettime fallback), the bridge for any
                   collector that stamps a non-realtime clock (the
                   reference's perf_timebase.txt analogue,
                   sofa_record.py:236-237)

The XPlane session clock is anchored separately by an in-trace marker (see
collectors/xprof.py)."""

from __future__ import annotations

import time

from sofa_tpu.collectors.base import Collector
from sofa_tpu.collectors.native_build import ensure_built
import subprocess


def python_timebase_samples(n: int = 3):
    rows = []
    for _ in range(n):
        rt0 = time.clock_gettime_ns(time.CLOCK_REALTIME)
        mono = time.clock_gettime_ns(time.CLOCK_MONOTONIC)
        boot = time.clock_gettime_ns(time.CLOCK_BOOTTIME)
        raw = time.clock_gettime_ns(time.CLOCK_MONOTONIC_RAW)
        rt1 = time.clock_gettime_ns(time.CLOCK_REALTIME)
        rows.append(((rt0 + rt1) // 2, mono, boot, raw))
    return rows


class TimebaseCollector(Collector):
    name = "timebase"

    def _sample_lines(self):
        tool = ensure_built("timebase")
        if tool:
            try:
                out = subprocess.run(
                    [tool, "3"], capture_output=True, text=True, timeout=10, check=True
                ).stdout
                lines = [ln for ln in out.splitlines() if ln.strip()]
                if lines:
                    return lines
            except (subprocess.SubprocessError, OSError):
                pass
        return [" ".join(str(v) for v in row) for row in python_timebase_samples()]

    def start(self) -> None:
        cfg = self.cfg
        cfg.time_base = time.time()
        with open(cfg.path("sofa_time.txt"), "w") as f:
            f.write(f"{cfg.time_base:.9f}\n")
        with open(cfg.path("timebase.txt"), "w") as f:
            f.write("\n".join(self._sample_lines()) + "\n")

    def stop(self) -> None:
        # Second anchor at record end: with samples at both ends of the run,
        # realtime-vs-monotonic drift becomes observable and ingest can fit a
        # slope instead of a bare offset (long runs, NTP slew).
        try:
            with open(self.cfg.path("timebase.txt"), "a") as f:
                f.write("\n".join(self._sample_lines()) + "\n")
        except OSError:
            pass

    def outputs(self):
        return [self.cfg.path("sofa_time.txt"), self.cfg.path("timebase.txt")]
