"""`sofa export` — static chart artifacts for headless sharing.

The reference renders network_report.pdf and a blktrace latency scatter
(/root/reference/bin/sofa_analyze.py:531-594,596-638) so a run's results can
be attached to a ticket or mail without serving HTTP; the board is richer
but HTTP-only, which round-2's verdict flagged (missing #5).  This renders
one multi-page ``sofa_report.pdf`` (plus a PNG of the overview page) from
the unified-schema frames with matplotlib's Agg backend — no display, no
server.

Charts follow the repo-wide viz conventions: one y-axis per plot (never a
dual axis), a fixed categorical color order, single-hue sequential ramp for
magnitude (the ICI heatmap), thin marks, recessive grid.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np
import pandas as pd

from sofa_tpu.printing import print_progress, print_warning

# Fixed categorical order (validated palette; see docs/) — assigned by
# entity, never cycled.
C1, C2, C3, C4, C5 = "#2a78d6", "#eb6834", "#1baf7a", "#eda100", "#e87ba4"
INK, INK2, GRID = "#0b0b0b", "#52514e", "#e5e4e0"

STATIC_FRAMES = ["tpuutil", "mpstat", "netbandwidth", "blktrace", "tputrace"]


def _style(ax, title: str, xlabel: str = "time (s)", ylabel: str = ""):
    ax.set_title(title, color=INK, fontsize=10, loc="left")
    ax.set_xlabel(xlabel, color=INK2, fontsize=8)
    ax.set_ylabel(ylabel, color=INK2, fontsize=8)
    ax.tick_params(colors=INK2, labelsize=7)
    ax.grid(True, color=GRID, linewidth=0.5)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color(GRID)


def _series(ax, df: pd.DataFrame, names: List[str], colors: List[str],
            scale: float = 1.0) -> bool:
    drew = False
    for name, color in zip(names, colors):
        rows = df[df["name"] == name]
        if rows.empty:
            continue
        # Collapse per-core / per-device lanes sharing a timestamp into one
        # mean line — a static page can't lane-split like the board does.
        agg = rows.groupby("timestamp")["event"].mean()
        ax.plot(agg.index, agg.to_numpy() * scale, color=color,
                linewidth=1.2, label=name)
        drew = True
    if drew:
        ax.legend(fontsize=7, frameon=False, labelcolor=INK2)
    else:
        ax.annotate("no data in this capture", (0.5, 0.5),
                    xycoords="axes fraction", ha="center", color=INK2,
                    fontsize=8)
    return drew


def _page_overview(fig, frames: Dict[str, pd.DataFrame]) -> bool:
    axes = fig.subplots(3, 1, sharex=True)
    util = frames.get("tpuutil", pd.DataFrame())
    mp = frames.get("mpstat", pd.DataFrame())
    drew = _series(axes[0], util, ["tc_util", "mxu_util"], [C1, C2])
    _style(axes[0], "TPU utilization", xlabel="", ylabel="%")
    drew |= _series(axes[1], util, ["hbm_gbps"], [C3])
    _style(axes[1], "HBM bandwidth", xlabel="", ylabel="GB/s")
    drew |= _series(axes[2], mp, ["usr", "sys", "iow"], [C1, C2, C4])
    _style(axes[2], "Host CPU", ylabel="%")
    return drew


def _page_network(fig, frames: Dict[str, pd.DataFrame]) -> bool:
    net = frames.get("netbandwidth", pd.DataFrame())
    if net.empty:
        return False
    ax = fig.subplots()
    drew = False
    # Busiest five series, not the alphabetically-first five: an idle
    # docker0 must not displace the NIC carrying the training traffic.
    # Cluster-merged frames key hosts in `pid` — each (host, NIC) pair is
    # its own line, never one concatenated backtracking scribble.
    multi_host = net["pid"].nunique() > 1
    keys = list(net.groupby(["pid", "name"])["event"].sum()
                .sort_values(ascending=False).head(5).index)
    for (hpid, name), color in zip(keys, (C1, C2, C3, C4, C5)):
        rows = net[(net["pid"] == hpid)
                   & (net["name"] == name)].sort_values("timestamp")
        label = f"h{int(hpid)}:{name}" if multi_host else name
        ax.plot(rows["timestamp"], rows["event"] / 2 ** 20, color=color,
                linewidth=1.2, label=label)
        drew = True
    if drew:
        ax.legend(fontsize=7, frameon=False, labelcolor=INK2)
    _style(ax, "Network bandwidth (reference: network_report.pdf)",
           ylabel="MiB/s")
    return drew


def _page_blktrace(fig, frames: Dict[str, pd.DataFrame]) -> bool:
    blk = frames.get("blktrace", pd.DataFrame())
    if blk.empty:
        return False
    ax = fig.subplots()
    ax.scatter(blk["timestamp"], blk["duration"] * 1e3, s=9, color=C1,
               alpha=0.7, edgecolors="none")
    _style(ax, "Block IO latency (reference: blktrace scatter)",
           ylabel="latency (ms)")
    return True


def _page_ici(fig, cfg) -> bool:
    path = cfg.path("ici_matrix.csv")
    if not os.path.isfile(path):
        return False
    try:
        mat = pd.read_csv(path, index_col=0)
    except Exception as e:  # noqa: BLE001 — an unreadable matrix skips the page
        print_warning(f"export: unreadable {path} ({e}); skipping the "
                      "ICI page")
        return False
    if mat.empty:
        return False
    from matplotlib.colors import LinearSegmentedColormap

    ax = fig.subplots()
    # magnitude -> single-hue sequential ramp (surface -> slot-1 blue)
    cmap = LinearSegmentedColormap.from_list(
        "sofa_seq", ["#fcfcfb", "#bcd6f2", "#2a78d6", "#12365f"])
    arr = mat.to_numpy() / 2 ** 20
    im = ax.imshow(arr, cmap=cmap)
    ax.set_xticks(range(len(mat.columns)), mat.columns, fontsize=6,
                  rotation=45, ha="right", color=INK2)
    ax.set_yticks(range(len(mat.index)), mat.index, fontsize=6, color=INK2)
    cb = fig.colorbar(im, ax=ax, shrink=0.8)
    cb.set_label("MiB sent", color=INK2, fontsize=8)
    cb.ax.tick_params(colors=INK2, labelsize=7)
    ax.set_title("Estimated ICI traffic (src chip -> dst chip)", color=INK,
                 fontsize=10, loc="left")
    return True


def _page_top_ops(fig, frames: Dict[str, pd.DataFrame]) -> bool:
    ops = frames.get("tputrace", pd.DataFrame())
    if ops.empty:
        return False
    sync = ops[ops["category"] == 0]
    if sync.empty:
        return False
    top = (sync.groupby("name")["duration"].sum()
           .sort_values(ascending=False).head(12)[::-1])
    ax = fig.subplots()
    labels, seen = [], set()
    for n in top.index:
        lbl = n if len(n) <= 48 else n[:24] + "…" + n[-23:]
        while lbl in seen:  # equal labels share a bar on a categorical axis
            lbl += "·"
        seen.add(lbl)
        labels.append(lbl)
    ax.barh(labels, top.to_numpy() * 1e3, color=C1, height=0.6)
    _style(ax, "Top HLO ops by total device time", xlabel="total time (ms)",
           ylabel="")
    ax.grid(axis="y", visible=False)
    return True


def export_static(cfg, frames: Optional[Dict[str, pd.DataFrame]] = None
                  ) -> List[str]:
    """Render sofa_report.pdf (+ overview.png) into the logdir.

    Returns the list of files written.  Pages with no data are skipped;
    matplotlib being absent degrades with a warning, never a crash.
    """
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from matplotlib.backends.backend_pdf import PdfPages
    except ImportError as e:
        print_warning(f"export: matplotlib unavailable ({e}); "
                      "no static charts rendered")
        return []
    if frames is None:
        from sofa_tpu.analyze import load_frames

        frames = load_frames(cfg, only=STATIC_FRAMES)

    # The logdir doubles as a static board bundle (board HTML + report.js
    # + _tiles/ behind any dumb file host — the board inflates the
    # pre-gzipped tiles itself when no server negotiates the encoding).
    # Materialize missing/stale pyramids from the frames this export
    # loaded; prune=False because this may be a narrow frame subset and
    # sibling pyramids must survive.
    try:
        from sofa_tpu import tiles

        tiles.ensure_tiles(cfg, frames, prune=False)
    except Exception as e:  # noqa: BLE001 — the PDF export must not die on tiles
        print_warning(f"export: tile pyramid refresh failed ({e})")

    written: List[str] = []
    os.makedirs(cfg.logdir, exist_ok=True)  # cluster export may precede it
    pdf_path = cfg.path("sofa_report.pdf")
    png_path = cfg.path("overview.png")
    pages = [
        ("overview", lambda f: _page_overview(f, frames)),
        ("network", lambda f: _page_network(f, frames)),
        ("blktrace", lambda f: _page_blktrace(f, frames)),
        ("ici", lambda f: _page_ici(f, cfg)),
        ("top_ops", lambda f: _page_top_ops(f, frames)),
    ]
    n_pages = 0
    with PdfPages(pdf_path) as pdf:
        for name, render in pages:
            fig = plt.figure(figsize=(8.5, 5.5), facecolor="#fcfcfb")
            try:
                drew = render(fig)
            except Exception as e:  # noqa: BLE001 — per-page degradation
                print_warning(f"export: page {name}: {e}")
                drew = False
            if drew:
                fig.tight_layout()
                pdf.savefig(fig)
                n_pages += 1
                if name == "overview":
                    fig.savefig(png_path, dpi=144)
                    written.append(png_path)
            plt.close(fig)
    if n_pages == 0:
        if os.path.exists(pdf_path):  # newer matplotlib skips empty PDFs
            os.unlink(pdf_path)
        print_warning("export: no data to chart — run `sofa report` first")
        return []
    written.insert(0, pdf_path)
    print_progress(f"exported {n_pages} chart pages -> {pdf_path}")
    return written
