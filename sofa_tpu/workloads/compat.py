"""JAX API compatibility shims shared by the workload modules.

``shard_map`` has moved twice across the JAX releases this repo meets in
the wild: modern releases export it as ``jax.shard_map``, while the
0.4.x line only ships ``jax.experimental.shard_map.shard_map`` (and on
some versions the top-level name exists merely as a deprecation stub
that *raises* on access).  Every workload imports the symbol from here
so the probe happens exactly once, at import time, instead of five
copies of the try/except drifting apart.
"""

from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, **kwargs):
        """The modern ``jax.shard_map`` signature on the experimental
        implementation: the varying-manual-axes check was renamed
        ``check_rep`` -> ``check_vma``; callers write the modern
        spelling and this adapter translates for old releases."""
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _exp_shard_map(f, **kwargs)

try:
    pcast = jax.lax.pcast
except AttributeError:
    from jax.experimental.shard_map import pbroadcast as _rep_pbroadcast

    def pcast(x, axes, to):
        """Modern ``lax.pcast`` on old releases: the only direction the
        workloads use is replicated -> varying, which the check_rep era
        spelled ``shard_map.pbroadcast`` (the explicit cast its rep
        check asks for in its error messages — NOT lax.pbroadcast, the
        from-source collective)."""
        if to != "varying":
            raise NotImplementedError(
                f"pcast(to={to!r}) has no pre-jax.shard_map equivalent")
        axes = (axes,) if isinstance(axes, str) else tuple(axes)
        return _rep_pbroadcast(x, axes)

def tpu_compiler_params(**kwargs):
    """``pallas.tpu.CompilerParams`` across its rename: old releases
    ship the same dataclass as ``TPUCompilerParams``."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)


__all__ = ["shard_map", "pcast", "tpu_compiler_params"]
