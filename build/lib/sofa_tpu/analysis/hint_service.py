"""gRPC advice service — remote hints on a performance feature vector.

The reference queries a remote POTATO server
(/root/reference/bin/sofa_analyze.py:49-73: gRPC Hint(HintRequest{hostname,
pfv}) -> HintResponse) and autodiscovers it from the environment
(bin/sofa:269-271).  This module provides both sides with no grpc_tools
dependency: handlers are registered generically and messages come from the
protoc-generated hint_pb2 (sofa_tpu/native/hint.proto).

Server:  python -m sofa_tpu.analysis.hint_service [port]
Client:  sofa report --hint_server host:port   (also honors
         $SOFA_HINT_SERVER, the POTATO_SERVER_SERVICE_HOST analogue)
"""

from __future__ import annotations

import os
from typing import List

from sofa_tpu.ingest import hint_pb2

SERVICE = "sofa_tpu.hint.HintService"
METHOD = f"/{SERVICE}/Hint"


def discover_server(cfg) -> str | None:
    if cfg.hint_server:
        return cfg.hint_server
    host = os.environ.get("SOFA_HINT_SERVER")
    return host


def request_hints(server: str, features, hostname: str = "", timeout: float = 5.0) -> List[str]:
    import grpc

    if ":" not in server:
        server += ":50051"
    req = hint_pb2.HintRequest(hostname=hostname or os.uname().nodename)
    for name, value in features.to_frame().itertuples(index=False):
        req.features[name] = float(value)
    with grpc.insecure_channel(server) as channel:
        call = channel.unary_unary(
            METHOD,
            request_serializer=hint_pb2.HintRequest.SerializeToString,
            response_deserializer=hint_pb2.HintResponse.FromString,
        )
        resp = call(req, timeout=timeout)
    return list(resp.hints)


def serve(port: int = 50051, block: bool = True):
    """Run the advice server: applies the local rule engine to whatever
    feature vector a client sends."""
    import grpc

    from sofa_tpu.analysis.advice import generate_hints
    from sofa_tpu.analysis.features import Features
    from sofa_tpu.config import SofaConfig

    def hint_handler(request: hint_pb2.HintRequest, context) -> hint_pb2.HintResponse:
        features = Features()
        for name, value in request.features.items():
            features.add(name, value)
        hints = generate_hints(features, SofaConfig())
        if not hints:
            hints = ["no obvious bottleneck in the submitted feature vector"]
        return hint_pb2.HintResponse(hints=hints)

    handler = grpc.method_handlers_generic_handler(
        SERVICE,
        {
            "Hint": grpc.unary_unary_rpc_method_handler(
                hint_handler,
                request_deserializer=hint_pb2.HintRequest.FromString,
                response_serializer=hint_pb2.HintResponse.SerializeToString,
            )
        },
    )
    from concurrent import futures

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((handler,))
    bound = server.add_insecure_port(f"[::]:{port}")
    server.start()
    print(f"sofa_tpu hint service listening on :{bound}")
    if block:
        server.wait_for_termination()
    return server, bound


if __name__ == "__main__":
    import sys

    serve(int(sys.argv[1]) if len(sys.argv) > 1 else 50051)
