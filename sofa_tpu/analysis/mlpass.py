"""Registry wrappers for the optional ML analysis passes (aisi / hsg).

These were the special-cased blocks at the tail of ``analyze.py``'s
``_analyze_body``: iteration detection (``ml/aisi.py``) and hot-swarm
clustering (``ml/hsg.py``), each gated by its cfg flag and each feeding
extra board series into ``report.js``.  On the registry they are plain
passes — gated by ``enabled_when``, fault-isolated like every other
pass, and their series ride the executor's ``provides_series`` channel
instead of an ad-hoc ``extra_series`` list.

The heavy lifting stays in ``sofa_tpu/ml/`` (imported lazily so default
runs never pay for it); these wrappers forward the features object, so
the feature writes happen in the helpers — sofa-lint SL011 recognizes
the forwarding and trusts the declaration.
"""

from __future__ import annotations

from sofa_tpu.analysis.features import Features
from sofa_tpu.analysis.registry import analysis_pass


@analysis_pass(
    name="aisi", order=250,
    reads_frames=("tputrace", "tpumodules", "tpusteps", "hosttrace",
                  "pystacks"),
    provides_features=("aisi_iterations", "aisi_step_time_mean",
                       "aisi_step_time_gmean", "aisi_step_time_std",
                       "aisi_comm_ratio"),
    provides_artifacts=("iterations.csv",),
    provides_series=True,
    after=("spotlight",),
    enabled_when=("enable_aisi",),
)
def aisi(frames, cfg, features: Features):
    """Iteration detection + per-step profile (``--enable_aisi``)."""
    from sofa_tpu.ml.aisi import iteration_series, sofa_aisi

    iters = sofa_aisi(frames, cfg, features)
    marker = iteration_series(iters)
    return [marker] if marker is not None else []


@analysis_pass(
    name="hsg", order=260,
    reads_frames=("cputrace", "pystacks", "tputrace"),
    provides_features=("hsg_swarms",),
    provides_artifacts=("auto_caption.csv",),
    provides_series=True,
    after=("spotlight",),
    enabled_when=("enable_hsg", "enable_swarms"),
)
def hsg(frames, cfg, features: Features):
    """Hot-swarm clustering over sampled stacks (``--enable_hsg`` /
    ``--enable_swarms``)."""
    from sofa_tpu.ml.hsg import sofa_hsg, swarm_series

    clustered = sofa_hsg(frames, cfg, features)
    return list(swarm_series(clustered, cfg.num_swarms))
