"""`sofa top` — live terminal dashboard over a recording logdir.

The nvidia-smi / `nvidia-smi dmon` habit, TPU-side: while `sofa record`
(or any sofa.profile-instrumented process) runs, its samplers append
tpumon.txt (per-device HBM + liveness heartbeat) and the procmon text
files (mpstat/netstat/diskstat); `sofa top` tails those files and redraws
a compact ANSI dashboard every --interval seconds.  `--once` renders a
single frame and exits (what the tests drive).

The reference had no equivalent — nvidia-smi itself played this role and
sofa only recorded it; on TPU hosts there is no vendor tool to lean on,
so the dashboard ships with the profiler.
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional

import pandas as pd

from sofa_tpu.ingest import procfs
from sofa_tpu.printing import print_error

_BAR_W = 24


def _bar(pct: float) -> str:
    pct = min(max(pct, 0.0), 100.0)
    fill = int(round(pct / 100.0 * _BAR_W))
    return "[" + "#" * fill + "-" * (_BAR_W - fill) + "]"


def _fmt_bytes_rate(bps: float) -> str:
    for unit, div in (("GiB/s", 2 ** 30), ("MiB/s", 2 ** 20),
                      ("KiB/s", 2 ** 10)):
        if bps >= div:
            return f"{bps / div:.1f} {unit}"
    return f"{bps:.0f} B/s"


def _latest(df: pd.DataFrame) -> pd.DataFrame:
    """Rows of the newest sample timestamp (procfs parsers emit absolute
    timestamps when time_base=0)."""
    if df.empty:
        return df
    return df[df["timestamp"] == df["timestamp"].max()]


def _tail_text(path: str, max_bytes: int = 65536) -> Optional[str]:
    """The file's tail window, first (possibly partial) line dropped:
    sampler files grow for the lifetime of a multi-hour recording and a
    dashboard tick needs just the last samples."""
    if not os.path.isfile(path):
        return None
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - max_bytes))
        text = f.read().decode(errors="replace")
    if size > max_bytes:
        text = text.split("\n", 1)[-1]
    return text


def _tail_load(path: str, parser, max_bytes: int = 65536) -> pd.DataFrame:
    text = _tail_text(path, max_bytes)
    if text is None:
        from sofa_tpu.trace import empty_frame

        return empty_frame()
    return parser(text, time_base=0.0)


def _tpu_lines(logdir: str, now: float) -> List[str]:
    from sofa_tpu.ingest.tpumon_parse import parse_tpumon_line

    text = _tail_text(os.path.join(logdir, "tpumon.txt"))
    if text is None:
        return ["TPU    no tpumon.txt (enable_tpu_mon off, or nothing "
                "recording yet)"]
    latest = {}
    beat_ns = None
    for line in text.splitlines():
        parsed = parse_tpumon_line(line)
        if parsed is None:
            continue
        ts_ns, dev, used, limit, peak = parsed
        if dev == -1:
            beat_ns = ts_ns
        else:
            latest[dev] = (ts_ns, used, limit, peak)
    out = []
    for dev in sorted(latest):
        ts_ns, used, limit, peak = latest[dev]
        if limit:
            occ = 100.0 * used / limit
            out.append(
                f"tpu{dev}   hbm {used / 1e9:6.2f}/{limit / 1e9:.2f} GB "
                f"{_bar(occ)} {occ:5.1f}%  peak {peak / 1e9:.2f} GB")
        else:  # CPU backend / runtimes that report no bytes_limit
            out.append(
                f"tpu{dev}   hbm {used / 1e9:6.2f} GB (no limit reported)"
                f"  peak {peak / 1e9:.2f} GB")
    if beat_ns is not None:
        age = max(0.0, now - beat_ns / 1e9)
        health = "live" if age < 5.0 else f"STALE ({age:.0f}s)"
        out.append(f"tpu    heartbeat {age:4.1f}s ago — {health}")
    return out or ["TPU    tpumon.txt has no samples yet"]


_MEM_CACHE: dict = {}   # path -> ((mtime_ns, size), rendered lines)


def _mem_lines(logdir: str) -> List[str]:
    """Top HBM allocation sites from the live peak snapshot, when the
    sampler has captured one (collectors/tpumon.py overwrites
    memprof.pb.gz at each new high-water mark, so this updates mid-run).
    The decode+aggregate is cached on (mtime, size): the dashboard redraws
    every --interval but the snapshot only changes at a new peak."""
    path = os.path.join(logdir, "memprof.pb.gz")
    try:
        st = os.stat(path)
    except OSError:
        return []
    key = (st.st_mtime_ns, st.st_size)
    cached = _MEM_CACHE.get(path)
    if cached is not None and cached[0] == key:
        return cached[1]
    try:
        from sofa_tpu.ingest.memprof import aggregate_sites, load_memprof

        df, meta = load_memprof(logdir)
        sites = aggregate_sites(df, top_k=3)
    except Exception:  # noqa: BLE001 — mid-overwrite reads must not kill top
        return []      # (not cached: the finished overwrite will parse)
    held = sites[sites["bytes"] > 0]
    out = []
    if not held.empty:
        out = [f"hbm@{meta.get('trigger', 'peak')}  top sites:"]
        for row in held.itertuples(index=False):
            out.append(f"       {row.bytes / 1e9:6.2f} GB {row.share:4.0%}  "
                       f"{row.site[:48]}")
    _MEM_CACHE[path] = (key, out)
    return out


def _cpu_line(logdir: str) -> Optional[str]:
    df = _tail_load(os.path.join(logdir, "mpstat.txt"), procfs.parse_mpstat)
    rows = _latest(df)
    if rows.empty:
        return None
    vals = {n: float(rows[rows["name"] == n]["event"].mean())
            for n in ("usr", "sys", "iow", "idl")
            if not rows[rows["name"] == n].empty}
    busy = 100.0 - vals.get("idl", 100.0)
    return (f"cpu    {_bar(busy)} {busy:5.1f}%  "
            + "  ".join(f"{n} {vals[n]:4.1f}%" for n in ("usr", "sys", "iow")
                        if n in vals))


def _net_line(logdir: str) -> Optional[str]:
    df = _tail_load(os.path.join(logdir, "netstat.txt"),
                    procfs.parse_netstat)
    rows = _latest(df)
    if rows.empty:
        return None
    parts = []
    for name, sel in rows.groupby("name"):
        parts.append(f"{name} {_fmt_bytes_rate(float(sel['event'].sum()))}")
    return "net    " + "  ".join(sorted(parts)[:6])


def _disk_line(logdir: str) -> Optional[str]:
    df = _tail_load(os.path.join(logdir, "diskstat.txt"),
                    procfs.parse_diskstat)
    rows = _latest(df)
    if rows.empty:
        return None
    # parse_diskstat emits <dev>.r_bw / <dev>.w_bw (bytes/s)
    rd = float(rows[rows["name"].str.endswith(".r_bw")]["event"].sum())
    wr = float(rows[rows["name"].str.endswith(".w_bw")]["event"].sum())
    return (f"disk   read {_fmt_bytes_rate(rd)}  "
            f"write {_fmt_bytes_rate(wr)}")


def render_frame(logdir: str, now: Optional[float] = None,
                 title: Optional[str] = None) -> str:
    now = time.time() if now is None else now
    stamp = time.strftime("%H:%M:%S", time.localtime(now))
    lines = [f"sofa top — {title or logdir}   {stamp}"]
    lines += _tpu_lines(logdir, now)
    lines += _mem_lines(logdir)
    for maker in (_cpu_line, _net_line, _disk_line):
        line = maker(logdir)
        if line:
            lines.append(line)
    return "\n".join(lines)


def render_cluster_frame(cfg, now: Optional[float] = None) -> str:
    """One stacked frame over every host's logdir of a cluster recording
    (the `sofa record --cluster_hosts` layout)."""
    from sofa_tpu.analyze import cluster_host_cfgs

    now = time.time() if now is None else now  # one clock for every block
    blocks = []
    seen_any = False
    for _i, hostname, host_cfg in cluster_host_cfgs(cfg):
        if not os.path.isdir(host_cfg.logdir):
            blocks.append(f"sofa top — {hostname}   (no logdir yet)")
            continue
        seen_any = True
        blocks.append(render_frame(host_cfg.logdir, now, title=hostname))
    if not seen_any:
        from sofa_tpu.printing import SofaUserError

        raise SofaUserError(
            f"no host logdirs under {cfg.logdir.rstrip('/')}-<host>/ — "
            "start a `sofa record --cluster_hosts ...` first")
    return "\n\n".join(blocks)


def sofa_top(cfg, interval: float = 2.0, once: bool = False) -> int:
    interval = max(float(interval), 0.1)  # 0/negative would spin or raise
    if cfg.cluster_hosts:
        render = lambda: render_cluster_frame(cfg)  # noqa: E731
    elif os.path.isdir(cfg.logdir):
        render = lambda: render_frame(cfg.logdir)   # noqa: E731
    else:
        print_error(f"logdir {cfg.logdir} does not exist — start a "
                    "`sofa record` first")
        return 1
    try:
        if once:
            print(render())
            return 0
        while True:
            sys.stdout.write("\x1b[2J\x1b[H" + render() + "\n")
            sys.stdout.flush()
            time.sleep(interval)
    except FileNotFoundError as e:
        print_error(str(e))
        return 1
    except KeyboardInterrupt:
        return 0
    # BrokenPipeError (`sofa top --once | head`) propagates to cli.main's
    # global handler — every printing subcommand shares the one fix.
