"""`sofa diff` — run-to-run swarm comparison.

Reference sofa_swarm_diff (sofa_ml.py:311-415,417-539): load two
auto_caption.csv files, concatenate each cluster's function names, fuzzy-
match clusters across runs, and report per-cluster duration deltas plus the
match intersection rate.  Same shape here with difflib as the fuzzy matcher.
"""

from __future__ import annotations

import difflib
import os
from typing import Dict, Optional

import pandas as pd

from sofa_tpu.printing import print_progress, print_title, print_warning


def _cluster_signatures(df: pd.DataFrame) -> Dict[int, dict]:
    out: Dict[int, dict] = {}
    for cid, rows in df.groupby("cluster_ID"):
        names = rows["name"].astype(str)
        out[int(cid)] = {
            "names": " ".join(sorted(names.unique())[:80]),
            "name_set": set(names.unique()),
            "duration": float(rows["duration"].sum()),
            "samples": len(rows),
        }
    return out


def match_swarms(base: Dict[int, dict], match: Dict[int, dict]) -> Dict[int, Optional[int]]:
    """Greedy best-ratio matching of base clusters onto match clusters
    (reference matching_two_dicts_of_swarm, sofa_ml.py:311-341)."""
    pairs = []
    for b, bs in base.items():
        for m, ms in match.items():
            ratio = difflib.SequenceMatcher(None, bs["names"], ms["names"]).ratio()
            pairs.append((ratio, b, m))
    pairs.sort(reverse=True)
    used_b, used_m = set(), set()
    out: Dict[int, Optional[int]] = {b: None for b in base}
    for ratio, b, m in pairs:
        if ratio < 0.3:
            break
        if b in used_b or m in used_m:
            continue
        out[b] = m
        used_b.add(b)
        used_m.add(m)
    return out


def _delta_table(base: pd.DataFrame, match: pd.DataFrame, value_col: str,
                 out_path: str) -> pd.DataFrame:
    """Outer-join two per-key aggregates into the shared diff shape.

    ``delta = match - base``.  ``ratio`` carries THE inf convention every
    diff consumer (the mover filters here, the board's diff page, the
    regression engine in sofa_tpu/archive/baseline.py) relies on:

      * key only in match (base value 0, match value > 0) -> ``ratio=inf``
        — a regression that exists only in the new run must be impossible
        to miss; a finite placeholder would sort it under real movers;
      * key with zero value in BOTH runs -> ``ratio=1`` (unchanged, not a
        mover — 0/0 is "nothing happened twice", not a change);
      * key only in base (vanished in match) -> ``ratio=0``.

    Sorted by |delta| and written to out_path.
    """
    import numpy as np

    joined = base.join(match, how="outer",
                       lsuffix="_base", rsuffix="_match").fillna(0.0)
    b, m = f"{value_col}_base", f"{value_col}_match"
    joined["delta"] = joined[m] - joined[b]
    joined["ratio"] = np.where(
        joined[b] > 0,
        joined[m] / joined[b].replace(0, np.nan),
        np.where(joined[m] > 0, np.inf, 1.0))
    table = joined.reindex(
        joined["delta"].abs().sort_values(ascending=False).index
    ).reset_index()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    table.to_csv(out_path, index=False)
    return table


def sofa_tpu_diff(cfg) -> Optional[pd.DataFrame]:
    """Run-to-run HLO-op diff — the TPU-side complement to the swarm diff.

    The reference could only diff CPU swarms (its GPU table had no
    cross-run matching); HLO op names are stable across runs of the same
    program, so an exact name join gives per-op time deltas directly.
    Reads both runs' tputrace frames, writes tpu_diff.csv sorted by
    |delta|, and flags ops whose time moved more than 20 %.
    """
    from sofa_tpu.trace import read_frame, roi_clip

    base = read_frame(os.path.join(cfg.base_logdir, "tputrace"))
    match = read_frame(os.path.join(cfg.match_logdir, "tputrace"))
    if base is None or match is None or base.empty or match.empty:
        print_warning("diff: no tputrace in one of the runs — skipping "
                      "TPU op diff")
        return None

    def per_op(df):
        sync = roi_clip(df, cfg)        # same window as every other pass
        sync = sync[sync["category"] == 0]
        return sync.groupby("name").agg(
            time=("duration", "sum"), count=("duration", "count"))

    out_path = os.path.join(cfg.logdir, "tpu_diff.csv")
    table = _delta_table(per_op(base), per_op(match), "time", out_path)

    tb, tm = float(table["time_base"].sum()), float(table["time_match"].sum())
    print_title("TPU op diff (base vs match)")
    print(table.head(15).to_string(index=False))
    moved = table[(table["ratio"] > 1.2) | (table["ratio"] < 1 / 1.2)]
    print_progress(
        f"diff: device time {tb:.4f}s -> {tm:.4f}s "
        f"({(tm / tb - 1) * 100 if tb else 0:+.1f}%); "
        f"{len(moved)} ops moved >20%; wrote {out_path}")
    return table


def sofa_mem_diff(cfg) -> Optional[pd.DataFrame]:
    """Run-to-run HBM attribution diff — memory regressions by site.

    Complements sofa_tpu_diff's time deltas: joins the two runs' peak
    allocation-site tables (ingest/memprof.py) on (site, kind) and reports
    held-byte deltas, so "this commit grew the optimizer state 2x" is one
    table row instead of an OOM three days later.  No reference analogue —
    its memory signal was one nvsmi total, undiffable by construction.
    """
    from sofa_tpu.ingest.memprof import load_memprof

    base_df, _ = load_memprof(cfg.base_logdir)
    match_df, _ = load_memprof(cfg.match_logdir)
    if base_df is None or match_df is None or base_df.empty or match_df.empty:
        print_warning("diff: no memprof.pb.gz in one of the runs — "
                      "skipping memory diff")
        return None

    def per_site(df):
        return df.groupby(["site", "kind"]).agg(
            bytes=("bytes", "sum"), count=("count", "sum"))

    out_path = os.path.join(cfg.logdir, "mem_diff.csv")
    table = _delta_table(per_site(base_df), per_site(match_df), "bytes",
                         out_path)

    bb = float(table["bytes_base"].sum())
    bm = float(table["bytes_match"].sum())
    print_title("HBM attribution diff (base vs match)")
    print(table.head(15).to_string(index=False))
    grown = table[table["delta"] > 0.05 * max(bb, 1)]
    print_progress(
        f"diff: held bytes {bb / 1e9:.3f}GB -> {bm / 1e9:.3f}GB "
        f"({(bm / bb - 1) * 100 if bb else 0:+.1f}%); "
        f"{len(grown)} sites grew >5% of the base total; wrote {out_path}")
    return table


_CLUSTER_COLUMNS = ("cluster_ID", "name", "duration")


def sofa_swarm_diff(cfg) -> Optional[pd.DataFrame]:
    base_path = os.path.join(cfg.base_logdir, "auto_caption.csv")
    match_path = os.path.join(cfg.match_logdir, "auto_caption.csv")
    for p in (base_path, match_path):
        if not os.path.isfile(p):
            print_warning(f"diff: {p} missing — run with --enable_hsg or `sofa diff`")
            return None
    tables = []
    for p in (base_path, match_path):
        # One side lacking the cluster columns (an auto_caption.csv from a
        # foreign/older run, or an empty clustering) degrades the diff to
        # a warning — it must not raise out of a multi-diff `sofa diff`
        # with the TPU/mem diffs still unwritten.
        try:
            df = pd.read_csv(p)
        except Exception as e:  # noqa: BLE001 — unreadable side: skip the diff, not the verb
            print_warning(f"diff: cannot read {p} ({e}) — skipping "
                          "swarm diff")
            return None
        missing = [c for c in _CLUSTER_COLUMNS if c not in df.columns]
        if missing or df.empty:
            why = (f"missing column(s) {missing}" if missing
                   else "no clustered samples")
            print_warning(f"diff: {p} has {why} — skipping swarm diff "
                          "(re-run `sofa analyze --enable_hsg` on that "
                          "logdir)")
            return None
        tables.append(df)
    base = _cluster_signatures(tables[0])
    match = _cluster_signatures(tables[1])
    mapping = match_swarms(base, match)

    rows = []
    for b, m in mapping.items():
        bs = base[b]
        row = {
            "base_cluster": b,
            "match_cluster": m if m is not None else -1,
            "base_duration": bs["duration"],
            "base_samples": bs["samples"],
        }
        if m is not None:
            ms = match[m]
            inter = bs["name_set"] & ms["name_set"]
            union = bs["name_set"] | ms["name_set"]
            row.update(
                {
                    "match_duration": ms["duration"],
                    "duration_delta": ms["duration"] - bs["duration"],
                    "duration_ratio": (
                        ms["duration"] / bs["duration"] if bs["duration"] > 0 else 0.0
                    ),
                    "intersection_rate": len(inter) / len(union) if union else 0.0,
                }
            )
        rows.append(row)
    table = pd.DataFrame(rows).sort_values("base_duration", ascending=False)
    out_path = os.path.join(cfg.logdir, "swarm_diff.csv")
    os.makedirs(cfg.logdir, exist_ok=True)
    table.to_csv(out_path, index=False)
    print_title("Swarm diff (base vs match)")
    print(table.to_string(index=False))
    matched = table[table["match_cluster"] >= 0]
    print_progress(
        f"diff: matched {len(matched)}/{len(table)} swarms; wrote {out_path}"
    )
    return table


def sofa_diff(cfg) -> int:
    """``sofa diff --base_logdir A --match_logdir B`` — the verb driver.

    Preprocess + swarm-cluster both sides, write the three diff tables
    (swarm/tpu/mem) plus the board staging, then refresh every touched
    logdir's digest ledger: the diff REWRITES artifacts (auto_caption.csv,
    the diff tables) inside logdirs whose ledgers an earlier pipeline run
    may have sealed — without the refresh the next `sofa fsck` would read
    this verb's own output as corruption (the blind spot sofa-lint SL015
    guards).
    """
    import copy

    from sofa_tpu import durability
    from sofa_tpu.analysis.features import Features
    from sofa_tpu.ml.hsg import sofa_hsg
    from sofa_tpu.preprocess import sofa_preprocess

    for d in (cfg.base_logdir, cfg.match_logdir):
        c = copy.deepcopy(cfg)
        c.logdir = d
        c.__post_init__()
        frames = sofa_preprocess(c)
        sofa_hsg(frames, c, Features())  # writes auto_caption.csv
    sofa_swarm_diff(cfg)
    sofa_tpu_diff(cfg)
    sofa_mem_diff(cfg)
    from sofa_tpu.analyze import stage_board

    stage_board(cfg)  # `sofa viz --logdir <diff dir>` -> Diff page
    for d in {os.path.normpath(p)
              for p in (cfg.logdir, cfg.base_logdir, cfg.match_logdir)}:
        if os.path.isdir(d):
            durability.write_digests(d)
    return 0
