"""Repeated-pattern mining over symbol sequences via a suffix automaton.

The reference finds substrings repeating exactly N times with a McCreight
suffix tree walk (/root/reference/bin/STree.py:237-273).  A suffix automaton
gives the same answer with less machinery: every automaton state represents
an endpos-equivalence class of substrings; its occurrence count is the size
of that class's endpos set (computed by propagating counts up suffix links),
and its longest substring is `len(state)`.  Finding "the longest substring
occurring ~N times" is then a linear scan over states.

Works on sequences of arbitrary hashable symbols (HLO op ids), not just
characters.
"""

from __future__ import annotations

from typing import Hashable, List, Optional, Sequence, Tuple


class SuffixAutomaton:
    """Online suffix automaton over a sequence of hashable symbols."""

    def __init__(self, seq: Sequence[Hashable]):
        # state arrays: link, length, transitions, clone flag
        self.link: List[int] = [-1]
        self.length: List[int] = [0]
        self.next: List[dict] = [{}]
        self.is_clone: List[bool] = [False]
        self.first_end: List[int] = [0]   # end position of first occurrence
        self.last = 0
        for i, sym in enumerate(seq):
            self._extend(sym, i)
        self._counts: Optional[List[int]] = None
        self.n = len(seq)

    def _new_state(self, link, length, nxt, clone, first_end) -> int:
        self.link.append(link)
        self.length.append(length)
        self.next.append(nxt)
        self.is_clone.append(clone)
        self.first_end.append(first_end)
        return len(self.link) - 1

    def _extend(self, sym, pos: int) -> None:
        cur = self._new_state(-1, self.length[self.last] + 1, {}, False, pos)
        p = self.last
        while p != -1 and sym not in self.next[p]:
            self.next[p][sym] = cur
            p = self.link[p]
        if p == -1:
            self.link[cur] = 0
        else:
            q = self.next[p][sym]
            if self.length[p] + 1 == self.length[q]:
                self.link[cur] = q
            else:
                clone = self._new_state(
                    self.link[q], self.length[p] + 1, dict(self.next[q]),
                    True, self.first_end[q],
                )
                while p != -1 and self.next[p].get(sym) == q:
                    self.next[p][sym] = clone
                    p = self.link[p]
                self.link[q] = clone
                self.link[cur] = clone
        self.last = cur

    def occurrence_counts(self) -> List[int]:
        """cnt[state] = number of occurrences of the substrings in state."""
        if self._counts is not None:
            return self._counts
        n_states = len(self.link)
        cnt = [0] * n_states
        for s in range(n_states):
            if s != 0 and not self.is_clone[s]:
                cnt[s] = 1
        order = sorted(range(1, n_states), key=lambda s: self.length[s], reverse=True)
        for s in order:
            parent = self.link[s]
            if parent >= 0:
                cnt[parent] += cnt[s]
        self._counts = cnt
        return cnt

    def repeat_candidates(
        self,
        target: int,
        tolerance: int = 0,
        min_len: int = 1,
        max_candidates: int = 32,
        prefer_len: Optional[float] = None,
    ) -> List[Tuple[int, int, int]]:
        """Substrings whose (overlapping) occurrence count is target±tolerance.

        Returns up to max_candidates (start, length, count) tuples of first
        occurrences — nearest ``prefer_len`` first when given (callers that
        know the expected period, e.g. len(seq)/target, MUST pass it: on a
        long k-period sequence the in-tolerance candidates number in the
        thousands and are dominated by multi-period patterns, so a plain
        longest-first truncation would drop every single-period candidate),
        longest first otherwise.  Overlapping counts over-report periodic
        patterns (a 2-period pattern in a k-period sequence occurs k-1
        times, not k/2), so callers must re-verify candidates with a
        non-overlapping scan (find_occurrences) before trusting the count.
        """
        cnt = self.occurrence_counts()
        out = []
        for s in range(1, len(self.link)):
            c = cnt[s]
            if abs(c - target) <= tolerance and self.length[s] >= min_len:
                out.append((self.first_end[s] - self.length[s] + 1, self.length[s], c))
        if prefer_len is not None:
            out.sort(key=lambda t: (abs(t[1] - prefer_len), -t[1]))
        else:
            out.sort(key=lambda t: -t[1])
        return out[:max_candidates]

    def best_repeat(
        self,
        target: int,
        tolerance: int = 0,
        min_len: int = 1,
    ) -> Optional[Tuple[int, int, int]]:
        """Longest substring occurring target±tolerance times (overlapping
        count) — see repeat_candidates for the caveat."""
        cands = self.repeat_candidates(target, tolerance, min_len, max_candidates=1)
        return cands[0] if cands else None


def find_occurrences(seq: Sequence[Hashable], pattern: Sequence[Hashable]) -> List[int]:
    """Non-overlapping left-to-right occurrences of pattern in seq."""
    out = []
    m = len(pattern)
    if m == 0:
        return out
    pat = list(pattern)
    i = 0
    n = len(seq)
    while i + m <= n:
        if list(seq[i:i + m]) == pat:
            out.append(i)
            i += m
        else:
            i += 1
    return out


def fuzzy_occurrences(
    seq: Sequence[Hashable],
    pattern: Sequence[Hashable],
    min_ratio: float = 0.9,
    max_full_checks: int = 20_000,
) -> List[int]:
    """Non-overlapping matches allowing small edits (the reference's
    fuzzywuzzy ratio>=90 block scan, sofa_aisi.py:259-271), via difflib.

    A naive scan runs difflib at every position — O(n·m²) on the degraded
    captures (no Steps, no markers) where this fallback triggers, which can
    be ~10^5 events (r3 verdict #6).  Positions are instead pre-screened
    with an incrementally-maintained multiset bound: difflib's ratio() can
    never exceed quick_ratio() = 2·Σmin(counts)/(|window|+|pattern|), and
    that bound updates in O(1) as the window slides, so the full matcher
    only runs where a match is arithmetically possible.  A hard cap on full
    checks bounds adversarial inputs; hitting it warns and returns the
    matches found so far.
    """
    import difflib
    from collections import Counter

    out: List[int] = []
    m = len(pattern)
    if m == 0:
        return out
    pat = list(pattern)
    n = len(seq)
    pcount = Counter(pat)

    i = 0
    full_checks = 0
    wc: Optional[Counter] = None     # counts for the window at i
    common = 0                       # Σ min(wc[x], pcount[x]) for that window
    # the i < n guard matters for m == 1, where i + m//2 <= n admits i == n
    # (an empty window that can never match but whose slide would read
    # seq[n])
    while i + m // 2 <= n and i < n:
        j = min(i + m, n)
        if wc is None:  # (re)build after init or a post-match jump
            wc = Counter(seq[i:j])
            common = sum(min(c, pcount[x]) for x, c in wc.items())
        wlen = j - i
        if 2.0 * common / (wlen + m) >= min_ratio:  # quick_ratio bound
            full_checks += 1
            if full_checks > max_full_checks:
                from sofa_tpu.printing import print_warning

                print_warning(
                    f"fuzzy iteration scan capped after {max_full_checks} "
                    f"window checks ({len(out)} matches kept; sequence of "
                    f"{n} events is too noisy for the fuzzy fallback)")
                return out
            window = list(seq[i:j])
            if difflib.SequenceMatcher(None, window, pat).ratio() >= min_ratio:
                out.append(i)
                i += max(wlen, 1)
                wc = None  # window jumped; rebuild lazily
                continue
        # slide one position: drop seq[i], admit seq[i+m] if it exists
        x = seq[i]
        if wc[x] <= pcount[x]:
            common -= 1
        wc[x] -= 1
        if i + m < n:
            y = seq[i + m]
            wc[y] += 1
            if wc[y] <= pcount[y]:
                common += 1
        i += 1
    return out
