import pytest

from sofa_tpu.cli import build_parser, config_from_args


def parse(argv):
    return config_from_args(build_parser().parse_args(argv))


def test_record_flags():
    cfg = parse(["record", "sleep 1", "--logdir", "x", "--sys_mon_rate", "33",
                 "--enable_strace", "--disable_xprof"])
    assert cfg.command == "sleep 1"
    assert cfg.logdir == "x/"
    assert cfg.sys_mon_rate == 33
    assert cfg.enable_strace
    assert not cfg.enable_xprof


def test_filter_flags():
    cfg = parse(["preprocess", "--cpu_filters", "idle:black,mem:red",
                 "--tpu_filters", "all-reduce:indigo"])
    assert [f.keyword for f in cfg.cpu_filters] == ["idle", "mem"]
    assert cfg.tpu_filters[0].color == "indigo"


def test_cluster_hosts():
    cfg = parse(["report", "--cluster_hosts", "a,b,c"])
    assert cfg.cluster_hosts == ["a", "b", "c"]


def test_toml_with_cli_override(tmp_path):
    p = tmp_path / "c.toml"
    p.write_text('sys_mon_rate = 5\nviz_port = 9999\n')
    cfg = parse(["analyze", "--config", str(p), "--viz_port", "7777"])
    assert cfg.sys_mon_rate == 5       # from file
    assert cfg.viz_port == 7777        # CLI wins


def test_record_without_command_errors(capsys):
    from sofa_tpu.cli import main
    assert main(["record"]) == 2


def test_unknown_subcommand_rejected():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["explode"])
