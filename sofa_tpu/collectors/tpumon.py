"""Live TPU runtime-metrics sampler — the `nvidia-smi dmon` analogue.

The reference samples GPU utilization/memory with nvidia-smi daemons
(/root/reference/bin/sofa_record.py:300-310).  libtpu has no external query
tool and the chip is held by the profiled process, so the sampler lives
*inside* that process (delivered by the same sitecustomize injection as the
XPlane collector, or started directly by sofa_tpu.api.profile) and reads
``device.memory_stats()`` — HBM bytes in use / limit / peak — at
``tpu_mon_rate`` Hz.

This is the low-rate, always-on complement to the trace-derived tc_util
series (ingest/xplane.py:tpu_utilization): it keeps working when XPlane
tracing is off (--disable_xprof), windowed (xprof_duration_s), or lost, and
it reports *occupancy* (bytes held) which the op trace cannot.

Output format (tpumon.txt), one line per device per tick plus a liveness
heartbeat (deviceId -1):

    <unix_ns> <device_id> <bytes_in_use> <bytes_limit> <peak_bytes_in_use>

Parsed by sofa_tpu/ingest/tpumon_parse.py.

The sampler doubles as the trigger for HBM *attribution* snapshots: when the
summed bytes-in-use sets a new high-water mark, it dumps
``jax.profiler.device_memory_profile()`` (a gzipped pprof Profile keyed by
allocation call stack) to ``memprof.pb.gz``.  One total from nvsmi is all the
reference ever had (sofa_record.py:300-310); the snapshot says *which
allocation sites* hold the peak — the question OOM debugging actually asks.
A snapshot is a stop-the-world serialize of every live buffer's stack, so it
is growth-gated (>2% over the previous mark) and rate-limited, not per-tick.
"""

from __future__ import annotations

import os

# Self-contained module text written into the injection directory; it must
# not import sofa_tpu (see xprof.py for why).  The same text is exec'd below
# so the in-process API (sofa_tpu.api.profile) shares one implementation.
_SAMPLER = '''
"""sofa_tpu in-process TPU runtime-metrics sampler (auto-generated)."""
import sys
import threading
import time


def _backend_ready():
    """jax imported AND a backend actually initialized.

    Touching jax.local_devices() ourselves would *trigger* backend init and
    could reorder the profiled program's startup; instead poll the bridge's
    backend table (internal but guarded — on rename we fall back to a grace
    period after import).
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        xb = sys.modules.get("jax._src.xla_bridge")
        if xb is not None and hasattr(xb, "_backends"):
            return jax if xb._backends else None
    except Exception:
        pass
    # Internals moved: wait a grace period after the import instead.
    if getattr(_backend_ready, "_seen", None) is None:
        _backend_ready._seen = time.time()
    return jax if time.time() - _backend_ready._seen > 5.0 else None


_MEMPROF = {"snap": 0, "last": 0.0}   # bytes at / time of last snapshot


def _pprof_encode(samples):
    """Minimal pprof ``Profile`` wire encoding (proto3, unpacked repeateds).

    samples: [(frames [(func, file, line), ...leaf-first], device, kind,
    count, bytes)].  Hand-rolled so the injected sampler stays import-free;
    every conformant protobuf parser accepts unpacked repeated scalars.
    """
    def vi(n):
        out = bytearray()
        n &= (1 << 64) - 1
        while True:
            b = n & 0x7F
            n >>= 7
            out.append(b | (0x80 if n else 0))
            if not n:
                return bytes(out)

    def tagv(field, n):                       # wire type 0 (varint)
        return vi(field << 3) + vi(n)

    def tagl(field, payload):                 # wire type 2 (length-delim)
        return vi((field << 3) | 2) + vi(len(payload)) + payload

    strings = [""]
    sidx = {"": 0}

    def s(x):
        if x not in sidx:
            sidx[x] = len(strings)
            strings.append(x)
        return sidx[x]

    functions = {}                            # (name, file) -> id
    fn_msgs = []
    locations = {}                            # (name, file, line) -> id
    loc_msgs = []

    def loc_id(fr):
        if fr not in locations:
            name, fname, line = fr
            fkey = (name, fname)
            if fkey not in functions:
                fid = len(functions) + 1
                functions[fkey] = fid
                fn_msgs.append(tagl(5, tagv(1, fid) + tagv(2, s(name))
                                    + tagv(4, s(fname))))
            lid = len(locations) + 1
            locations[fr] = lid
            line_msg = tagv(1, functions[fkey]) + tagv(2, max(int(line), 0))
            loc_msgs.append(tagl(4, tagv(1, lid) + tagl(4, line_msg)))
        return locations[fr]

    out = bytearray()
    # sample_type: (allocations, count), (space, bytes) — the column pair
    # ingest/memprof.py resolves by unit.
    out += tagl(1, tagv(1, s("allocations")) + tagv(2, s("count")))
    out += tagl(1, tagv(1, s("space")) + tagv(2, s("bytes")))
    for frames, device, kind, cnt, nbytes in samples:
        msg = bytearray()
        for fr in frames:
            msg += tagv(1, loc_id(fr))
        msg += tagv(2, max(int(cnt), 0)) + tagv(2, max(int(nbytes), 0))
        msg += tagl(3, tagv(1, s("device")) + tagv(2, s(device)))
        msg += tagl(3, tagv(1, s("kind")) + tagv(2, s(kind)))
        out += tagl(2, bytes(msg))
    for m in loc_msgs:
        out += m
    for m in fn_msgs:
        out += m
    for st in strings:
        out += tagl(6, st.encode("utf-8", "replace"))
    return bytes(out)


def _live_buffer_samples(jax):
    """Aggregate live device arrays into pprof samples by allocation stack.

    Covers buffers only: PyClient::HeapProfile additionally walks live
    *executables*, and that branch calls a PJRT C-API method
    (PJRT_Executable_SizeOfGeneratedCodeInBytes) that tunneled plugins may
    leave unimplemented — absl LOG(FATAL), aborting the profiled process
    (observed on the axon tunnel 2026-07-31).  Buffers are what OOM
    attribution needs; jit temporaries/donated buffers are invisible either
    way.
    """
    agg = {}
    for a in jax.live_arrays():
        try:
            tb = getattr(a, "traceback", None)
            frames = tuple(
                (str(f.function_name), str(f.file_name), int(f.line_num))
                for f in (tb.frames if tb is not None else ())[:48])
        except Exception:
            frames = ()
        if not frames:
            frames = (("(stackless buffer)", "", 0),)
        per = {}
        try:
            for sh in a.addressable_shards:
                d = sh.device
                label = "%s:%d" % (getattr(d, "platform", "dev"),
                                   getattr(d, "id", 0))
                per[label] = per.get(label, 0) + int(sh.data.nbytes)
        except Exception:
            # non-empty sentinel: an empty string encodes as string-table
            # index 0 and decodes as the numeric label 0 -> device "0"
            per = {"unknown": int(getattr(a, "nbytes", 0) or 0)}
        for label, nb in per.items():
            key = (frames, label)
            c, b = agg.get(key, (0, 0))
            agg[key] = (c + 1, b + nb)
    return [(list(fr), dev, "buffer", c, b)
            for (fr, dev), (c, b) in sorted(agg.items(), key=str)]


def snapshot_memprof(jax, path, trigger, total_bytes):
    """Dump an HBM attribution snapshot (gzipped pprof) + a meta sidecar.

    Best-effort by contract: the profiled program must never die because an
    observability snapshot failed (chip mid-teardown, read-only logdir, ...).
    The profile is built in-process from jax.live_arrays() stacks; the
    runtime's own jax.profiler.device_memory_profile() is opt-in via
    SOFA_MEMPROF_NATIVE=1 because its executable walk can LOG(FATAL) on
    PJRT plugins that skip the code-size C-API method (see
    _live_buffer_samples) — an abort no try/except can catch.
    """
    import gzip
    import json
    import os as _os
    try:
        if _os.environ.get("SOFA_MEMPROF_NATIVE", "0") == "1":
            encoder = "native"
            blob = jax.profiler.device_memory_profile()
        else:
            encoder = "live_arrays"
            blob = gzip.compress(_pprof_encode(_live_buffer_samples(jax)))
        # Writer-unique tmp name: the sampler thread and the at-exit
        # fallback may snapshot concurrently (injection atexit order is not
        # ours to pick); each writes its own tmp and the atomic replace
        # means the published file is always ONE complete snapshot.
        tmp = "%s.tmp.%d.%d" % (path, _os.getpid(), threading.get_ident())
        with open(tmp, "wb") as f:
            f.write(blob)
        _os.replace(tmp, path)   # readers never see a half-written profile
        meta = {"unix_ns": time.time_ns(), "trigger": trigger,
                "total_bytes": int(total_bytes), "encoder": encoder}
        if encoder == "live_arrays":
            # Readers must know what this profile CANNOT show: the
            # live-arrays encoder sees only arrays this process holds —
            # executable/code memory attribution (and jit temporaries)
            # need the native profile, opt-in because its executable walk
            # can LOG(FATAL) on PJRT plugins without the code-size C-API.
            meta["note"] = ("no executable/code rows; set "
                            "SOFA_MEMPROF_NATIVE=1 on backends whose "
                            "plugin implements the code-size C-API")
        with open(path + ".meta.json", "w") as f:
            json.dump(meta, f)
        return True
    except Exception as e:
        sys.stderr.write("sofa_tpu: memprof snapshot failed: %r\\n" % (e,))
        return False


def _maybe_memprof(jax, path, total_bytes):
    """Growth-gated, rate-limited peak snapshot (see module docstring).

    The gate baseline is the bytes at the last *successful snapshot* — never
    the per-tick observation — so gradual growth (1% per tick, compounding)
    still re-triggers once it sums past 2% since the snapshot, and a
    rate-limited tick re-arms instead of silently raising the bar.
    """
    if not path or total_bytes <= 0:
        return
    if total_bytes <= _MEMPROF["snap"] * 1.02:
        return
    now = time.time()
    if now - _MEMPROF["last"] < 2.0:
        return
    if snapshot_memprof(jax, path, "peak", total_bytes):
        _MEMPROF["snap"] = total_bytes
        _MEMPROF["last"] = now


def _loop(rate_hz, out_path, stop, memprof_path=None):
    jax = None
    while jax is None:
        if stop is not None and stop.is_set():
            return
        jax = _backend_ready()
        if jax is None:
            time.sleep(0.1)
    try:
        devs = jax.local_devices()
    except Exception:
        return
    interval = 1.0 / max(rate_hz, 1e-3)
    try:
        out = open(out_path, "a", buffering=1)
    except OSError:
        return
    with out:
        while stop is None or not stop.is_set():
            ts = time.time_ns()
            try:
                out.write("%d -1 0 0 0\\n" % ts)   # liveness heartbeat
                wrote = False
                total_used = 0
                for d in devs:
                    try:
                        ms = d.memory_stats()
                    except Exception:
                        ms = None
                    if not ms:
                        continue
                    wrote = True
                    total_used += int(ms.get("bytes_in_use", 0))
                    out.write("%d %d %d %d %d\\n" % (
                        ts, d.id,
                        ms.get("bytes_in_use", 0),
                        ms.get("bytes_limit", 0),
                        ms.get("peak_bytes_in_use", 0),
                    ))
                if not wrote:
                    # PJRT clients without memory_stats (e.g. tunneled
                    # backends): approximate HBM in use with the bytes of
                    # live arrays this process holds per device.  limit=0
                    # marks the estimate; ingest emits used-only rows.
                    per = {}
                    try:
                        for a in jax.live_arrays():
                            try:
                                for sh in a.addressable_shards:
                                    did = sh.device.id
                                    per[did] = per.get(did, 0) + int(
                                        sh.data.nbytes)
                            except Exception:
                                pass
                    except Exception:
                        per = {}
                    for did, used in sorted(per.items()):
                        total_used += used
                        out.write("%d %d %d 0 0\\n" % (ts, did, used))
                _maybe_memprof(jax, memprof_path, total_used)
            except Exception:
                return
            time.sleep(interval)


def start_sampler(rate_hz, out_path, stop=None, memprof_path=None):
    """Start the sampler thread; returns it.  Waits for jax by itself, so it
    is safe to call before the profiled program imports jax.  Pass a
    threading.Event as `stop` to end the loop (in-process API use); pass
    `memprof_path` to arm peak-triggered HBM attribution snapshots."""
    own_stop = stop is None
    if own_stop:
        stop = threading.Event()
    if memprof_path:
        # Re-arm the growth gate: a previous profile() in this process left
        # its peak as the baseline, which would suppress this run's
        # snapshots unless it out-allocated the last one by 2%.
        _MEMPROF.update(snap=0, last=0.0)
    t = threading.Thread(
        target=_loop, args=(rate_hz, out_path, stop, memprof_path),
        daemon=True, name="sofa_tpu_tpumon",
    )
    t.start()
    if own_stop:
        # A daemon thread mid-PJRT-call during interpreter teardown can
        # abort the whole process (SIGABRT from the C++ layer); stop and
        # join the sampler BEFORE shutdown instead.
        import atexit
        import os

        def _shutdown():
            stop.set()
            t.join(timeout=2.0)
            # No peak ever cleared the gate (or xprof's own exit fallback is
            # absent because tracing was off): leave a final snapshot — but
            # ONLY on a strictly-initialized backend.  The merely-IMPORTED
            # jax module is not enough: live_arrays() on an uninitialized
            # backend *triggers* backend init, and with the device tunnel
            # down that is an unbounded claim loop at interpreter exit
            # (observed live: `sofa stat "python -c 'print(42)'"` printed
            # 42 then wedged forever in exactly this call).  No
            # grace-period fallback here — a wrong guess wedges the
            # process at the worst possible moment.
            jax = sys.modules.get("jax")
            try:
                xb = sys.modules.get("jax._src.xla_bridge")
                ready = (jax is not None and xb is not None
                         and bool(getattr(xb, "_backends", None)))
            except Exception:
                ready = False
            if not (memprof_path and ready
                    and not os.path.exists(memprof_path)):
                return
            # Even an initialized backend can block if the tunnel died
            # mid-run: thread-deadline the snapshot; a stuck daemon
            # thread dies with the process.
            try:
                timeout = float(os.environ.get(
                    "SOFA_TPU_STOP_TIMEOUT_S", "30") or 0)
            except ValueError:
                timeout = 30.0

            # Same breadcrumb contract as the xprof epilogue: the parent
            # `sofa record` TERM/KILLs us if this stalls past its deadline
            # (covers even a snapshot wedged while holding the GIL).
            def _mark(payload):
                try:
                    import json as _json
                    d = os.path.join(
                        os.path.dirname(os.path.abspath(memprof_path)),
                        "_inject")
                    if not os.path.isdir(d):
                        return
                    p = os.path.join(d, "atexit_stop.json")
                    with open(p + ".tmp", "w") as f:
                        _json.dump(payload, f)
                    os.replace(p + ".tmp", p)
                except Exception:
                    pass

            _mark({"pid": os.getpid(), "t": time.time(),
                   "timeout_s": timeout, "grace_s": 0})
            snap = threading.Thread(
                target=lambda: snapshot_memprof(
                    jax, memprof_path, "final", 0),
                daemon=True, name="sofa_tpu_final_memprof")
            snap.start()
            snap.join(timeout if timeout > 0 else None)
            if snap.is_alive():
                sys.stderr.write(
                    "sofa_tpu: final memprof exceeded %gs (device tunnel "
                    "down?) — skipped\\n" % timeout)
            _mark({"pid": os.getpid(), "t": time.time(),
                   "timeout_s": timeout, "grace_s": 0,
                   "done": True, "ok": not snap.is_alive()})

        atexit.register(_shutdown)
    return t
'''

# One implementation: exec the injected text for in-process callers.
_ns: dict = {}
exec(compile(_SAMPLER, "<sofa_tpu_tpumon>", "exec"), _ns)
start_sampler = _ns["start_sampler"]
snapshot_memprof = _ns["snapshot_memprof"]


def write_sampler_module(inject_dir: str) -> None:
    with open(os.path.join(inject_dir, "sofa_tpu_tpumon.py"), "w") as f:
        f.write(_SAMPLER)
