#!/usr/bin/env python3
"""Chaos matrix: every fault kind against a pod_synth --raw harness.

CI/tooling companion of sofa_tpu/faults.py: each cell records a short
command under an injected fault, overlays the pod_synth --raw collector
files, preprocesses, and asserts the run STILL yields a schema-valid
run_manifest.json (tools/manifest_check.py) and a report.js — the
"a profiling run always yields a usable trace" contract, exercised on
demand instead of waiting for production to exercise it for us.

On top of the collector-fault matrix, the **kill-sofa-itself cells**
(sofa_tpu/durability.py's acceptance proof) SIGKILL the preprocess
process at a random point — once mid CSV frame-write, once mid tile
build, once mid columnar-chunk write (sofa_tpu/frames.py: chunks on
disk, the frame_index.json commit point absent) — and assert that
`sofa resume` completes the run with a ``report.js`` byte-identical to
an uninterrupted run on the same logdir, a schema-valid manifest, and
`sofa fsck` exit 0.

    python tools/chaos_matrix.py [workdir]

Prints one PASS/FAIL row per cell; exits nonzero if any cell fails.
The slow-marked tests/test_faults.py::test_chaos_matrix_end_to_end runs
this end to end.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import traceback
from typing import List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sofa_tpu import telemetry  # noqa: E402
from sofa_tpu.config import SofaConfig  # noqa: E402
from sofa_tpu.preprocess import QUARANTINE_DIR_NAME, sofa_preprocess  # noqa: E402
from sofa_tpu.record import sofa_record  # noqa: E402

_TOOLS = os.path.dirname(os.path.abspath(__file__))

# (cell name, fault spec, extra cfg overrides).  Targets are collectors that
# exist on every machine (procmon/timebase/xprof) plus ingest sources; the
# corrupt-file cell injects REAL corruption instead of a spec.
MATRIX: List[Tuple[str, str, dict]] = [
    # die cells record long enough for detect (poll 0.5s) + backoff (0.5s)
    # + restart to land before the epilogue
    ("die+restart", "procmon:die@0.3s",
     {"collector_restarts": 1, "_cmd": "sleep 2.5"}),
    ("die-no-restart", "procmon:die@0.3s",
     {"collector_restarts": 0, "_cmd": "sleep 1.5"}),
    ("start-fail", "procmon:fail@start", {}),
    ("stop-wedge", "procmon:wedge@stop", {"collector_stop_timeout_s": 1.0}),
    ("harvest-wedge", "procmon:wedge@harvest",
     {"collector_harvest_timeout_s": 1.0}),
    ("timebase-fail", "timebase:fail@start", {}),
    ("xprof-truncate", "xprof:truncate@harvest", {}),
    ("ingest-corrupt", "mpstat:corrupt", {}),
    ("corrupt-pcap-file", "", {}),  # real on-disk corruption
]

_RAW_OVERLAY = ("perf.script", "strace.txt", "pystacks.txt", "mpstat.txt",
                "cpuinfo.txt", "netstat.txt", "vmstat.txt", "tpumon.txt",
                "misc.txt")

# Kill-sofa-itself cells: (name, crash point).  The crash point patches a
# hot write path in the child so os.kill(SIGKILL) fires mid-derived-write
# after a random number of writes — no cleanup handler of any kind runs,
# exactly like the OOM-killer / a node preemption.
KILL_CELLS = [
    ("kill-mid-preprocess", "frames"),
    ("kill-mid-tiles", "tiles"),
    # mid-write of the chunked columnar store (sofa_tpu/frames.py): some
    # column chunks on disk, the frame_index.json commit point not yet
    # written — resume must converge byte-identically and fsck 0
    ("kill-mid-frame-write", "frame_chunks"),
]

_KILL_SNIPPET = """
import os, signal, sys
sys.path.insert(0, sys.argv[4])
logdir, point, n = sys.argv[1], sys.argv[2], int(sys.argv[3])
from sofa_tpu import frames as framestore, tiles, trace
count = [0]
def arm(orig):
    def hook(*a, **kw):
        count[0] += 1
        if count[0] >= n:
            os.kill(os.getpid(), signal.SIGKILL)
        return orig(*a, **kw)
    return hook
if point == "tiles":
    tiles._write_tile = arm(tiles._write_tile)
elif point == "frame_chunks":
    framestore._chunk_sha = arm(framestore._chunk_sha)
else:
    trace.write_csv = arm(trace.write_csv)
from sofa_tpu.config import SofaConfig
from sofa_tpu.preprocess import sofa_preprocess
sofa_preprocess(SofaConfig(logdir=logdir))
"""

# Kill-mid-live-epoch: SIGKILL `sofa live` inside an epoch's tile
# refresh — with a torn-tail fault injected on the same tick — then
# prove `sofa resume` + `sofa live --drain` converge to artifacts
# byte-identical to an uninterrupted batch run over the final logdir
# (sofa_tpu/live.py's acceptance contract).
_LIVE_KILL_SNIPPET = """
import os, signal, sys
sys.path.insert(0, sys.argv[3])
logdir, n = sys.argv[1], int(sys.argv[2])
from sofa_tpu import tiles
count = [0]
orig = tiles._write_tile
def hook(*a, **kw):
    count[0] += 1
    if count[0] >= n:
        os.kill(os.getpid(), signal.SIGKILL)
    return orig(*a, **kw)
tiles._write_tile = hook
from sofa_tpu.config import SofaConfig
from sofa_tpu.live import sofa_live
cfg = SofaConfig(logdir=logdir, live_interval_s=0.0,
                 inject_faults=os.environ.get("CHAOS_LIVE_FAULTS", ""))
sofa_live(cfg, epochs=1)
"""

# Fleet cells (sofa_tpu/archive/service.py + sofa_tpu/agent.py): the
# service child binds an ephemeral port and prints its URL; the parent
# parses it.  SOFA_SERVE_EXIT_AFTER makes the child hard-exit at the n-th
# write request — the kill-service-mid-upload chaos.
_SERVE_SNIPPET = """
import os, sys
sys.path.insert(0, sys.argv[3])
from sofa_tpu.config import SofaConfig
from sofa_tpu.archive.service import sofa_serve
cfg = SofaConfig(logdir=sys.argv[1], serve_token="chaos", serve_port=0,
                 serve_workers=int(os.environ.get(
                     "SOFA_CHAOS_SERVE_WORKERS", "1")))
sys.exit(sofa_serve(cfg, root=sys.argv[2]) or 0)
"""

# Kill-mid-archive: SIGKILL during the object-store copy loop of
# `sofa archive`, then prove `sofa resume` replays the ingest and both
# the store and the logdir come out fsck-clean and catalog-consistent.
_ARCHIVE_KILL_SNIPPET = """
import os, signal, sys
sys.path.insert(0, sys.argv[4])
logdir, root, n = sys.argv[1], sys.argv[2], int(sys.argv[3])
from sofa_tpu.archive import store as astore
count = [0]
orig = astore.ArchiveStore.put_file
def hook(self, *a, **kw):
    count[0] += 1
    if count[0] >= n:
        os.kill(os.getpid(), signal.SIGKILL)
    return orig(self, *a, **kw)
astore.ArchiveStore.put_file = hook
from sofa_tpu.config import SofaConfig
astore.ingest_run(SofaConfig(logdir=logdir), root)
"""


def _load_manifest_check():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "manifest_check", os.path.join(_TOOLS, "manifest_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _synth(workdir: str) -> str:
    synth = os.path.join(workdir, "synth") + "/"
    r = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "pod_synth.py"), synth,
         "--raw"],
        capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        raise RuntimeError(f"pod_synth failed: {r.stderr}")
    return synth


def _run_cell(name: str, spec: str, overrides: dict, workdir: str,
              synth: str, mc) -> List[str]:
    """One chaos cell -> list of problems (empty == PASS)."""
    logdir = os.path.join(workdir, name) + "/"
    overrides = dict(overrides)
    cmd = overrides.pop("_cmd", "sleep 0.8")
    cfg = SofaConfig(logdir=logdir, enable_xprof=False,
                     inject_faults=spec, **overrides)
    rc = sofa_record(cmd, cfg)
    problems: List[str] = []
    if rc != 0:
        problems.append(f"record rc={rc}")
    for fname in _RAW_OVERLAY:
        src = synth + fname
        if os.path.isfile(src) and not os.path.isfile(cfg.path(fname)):
            shutil.copy(src, cfg.path(fname))
    if name == "corrupt-pcap-file":
        with open(cfg.path("sofa.pcap"), "wb") as f:
            f.write(b"chaos: positively not a pcap file")
    # preprocess inherits the fault spec (ingest-corrupt cells) via cfg
    sofa_preprocess(cfg)
    doc = telemetry.load_manifest(logdir)
    if doc is None:
        return problems + ["no run_manifest.json"]
    schema_probs = mc.validate_manifest(doc)
    problems += [f"manifest: {p}" for p in schema_probs]
    if not os.path.isfile(cfg.path("report.js")):
        problems.append("no report.js")
    # per-cell expectations: the injected fault actually landed in the ledger
    cols = doc.get("collectors") or {}
    srcs = doc.get("sources") or {}
    if name == "die+restart" and not (
            cols.get("procmon", {}).get("died")
            and cols.get("procmon", {}).get("restarts", 0) >= 1):
        problems.append("procmon died+restarts not recorded")
    if name == "die-no-restart" and cols.get("procmon", {}).get(
            "status") != "died":
        problems.append("procmon died status not sticky")
    if name == "start-fail" and cols.get("procmon", {}).get(
            "status") != "failed":
        problems.append("procmon failed status not recorded")
    if name in ("stop-wedge", "harvest-wedge") and cols.get(
            "procmon", {}).get("status") != "timed_out":
        problems.append("procmon timed_out status not recorded")
    if name in ("ingest-corrupt", "corrupt-pcap-file"):
        source = "mpstat" if name == "ingest-corrupt" else "nettrace"
        if srcs.get(source, {}).get("status") != "quarantined":
            problems.append(f"{source} not quarantined")
        if not os.path.isdir(cfg.path(QUARANTINE_DIR_NAME)):
            problems.append("no _quarantine/ directory")
    return problems


def _run_kill_cell(name: str, point: str, workdir: str, synth: str,
                   mc) -> List[str]:
    """SIGKILL sofa mid-preprocess, then prove `sofa resume` restores the
    run bit-for-bit.  Control and resumed runs share ONE logdir path (the
    report.js meta embeds it), separated by `sofa clean`."""
    import random

    from sofa_tpu.durability import sofa_fsck, sofa_resume
    from sofa_tpu.record import sofa_clean
    from sofa_tpu.trace import WRITING_SENTINEL

    logdir = os.path.join(workdir, name) + "/"
    shutil.rmtree(logdir, ignore_errors=True)
    shutil.copytree(synth, logdir)  # copy2: raw mtimes survive (cache keys)
    cfg = SofaConfig(logdir=logdir)
    problems: List[str] = []

    # 1. uninterrupted control run -> the byte-identity target
    sofa_preprocess(cfg)
    with open(cfg.path("report.js"), "rb") as f:
        want = f.read()
    sofa_clean(cfg)  # back to raw-only: derived, caches, journal all gone

    # 2. the crashing run: SIGKILL at a random point in the derived writes
    n = random.randint(1, 6)
    root = os.path.dirname(_TOOLS)
    r = subprocess.run(
        [sys.executable, "-c", _KILL_SNIPPET, logdir, point, str(n), root],
        capture_output=True, text=True, timeout=600)
    if r.returncode != -9:
        return problems + [f"crash child exited rc={r.returncode} "
                           f"(expected SIGKILL -9; kill after write #{n}); "
                           f"stderr tail: {r.stderr.strip()[-200:]}"]
    if not os.path.exists(cfg.path(WRITING_SENTINEL)):
        # both crash points sit inside derived_write_guard: the kill must
        # leave the sentinel behind, and resume must reap it
        problems.append("no mid-write sentinel left by the killed run")

    # 3. resume must complete and converge to the control bytes
    rc = sofa_resume(cfg)
    if rc != 0:
        problems.append(f"sofa resume rc={rc}")
    try:
        with open(cfg.path("report.js"), "rb") as f:
            got = f.read()
        if got != want:
            problems.append(
                f"report.js after resume differs from the uninterrupted "
                f"run ({len(got)} vs {len(want)} bytes)")
    except OSError as e:
        problems.append(f"no report.js after resume: {e}")
    doc = telemetry.load_manifest(logdir)
    if doc is None:
        problems.append("no run_manifest.json after resume")
    else:
        problems += [f"manifest: {p}" for p in mc.validate_manifest(doc)]
    if sofa_fsck(cfg) != 0:
        problems.append("sofa fsck nonzero on the resumed logdir")
    return problems


def _run_archive_kill_cell(workdir: str, synth: str, mc) -> List[str]:
    """SIGKILL sofa mid-`archive` ingest, then prove `sofa resume`
    completes it: the catalog holds the run, the store fscks clean, and
    the second (replayed) ingest deduped every object the killed one
    already committed."""
    import random

    from sofa_tpu.archive import catalog as acat
    from sofa_tpu.archive.store import ArchiveStore, archive_fsck
    from sofa_tpu.durability import sofa_resume

    logdir = os.path.join(workdir, "kill-mid-archive") + "/"
    root = os.path.join(workdir, "kill-mid-archive-store")
    shutil.rmtree(logdir, ignore_errors=True)
    shutil.rmtree(root, ignore_errors=True)
    shutil.copytree(synth, logdir)
    cfg = SofaConfig(logdir=logdir)
    problems: List[str] = []
    sofa_preprocess(cfg)  # digests + derived artifacts to archive

    n = random.randint(2, 8)
    repo = os.path.dirname(_TOOLS)
    r = subprocess.run(
        [sys.executable, "-c", _ARCHIVE_KILL_SNIPPET, logdir, root,
         str(n), repo],
        capture_output=True, text=True, timeout=600)
    if r.returncode != -9:
        return problems + [f"crash child exited rc={r.returncode} "
                           f"(expected SIGKILL -9 after put #{n}); "
                           f"stderr tail: {r.stderr.strip()[-200:]}"]
    rc = sofa_resume(cfg)
    if rc != 0:
        problems.append(f"sofa resume rc={rc}")
    report = archive_fsck(root)
    if report is None:
        return problems + ["no archive store after resume"]
    for verdict in ("corrupt", "missing", "orphaned", "uncataloged"):
        if report.get(verdict):
            problems.append(
                f"archive fsck: {len(report[verdict])} {verdict} after "
                f"resume: {report[verdict][:3]}")
    store = ArchiveStore(root)
    runs = acat.ingest_entries(acat.read_catalog(root))
    if len(runs) != 1:
        problems.append(f"catalog holds {len(runs)} run(s), expected 1")
    elif store.load_run(runs[0]["run"]) is None:
        problems.append("cataloged run doc unreadable")
    doc = telemetry.load_manifest(logdir)
    if doc is not None:
        problems += [f"manifest: {p}" for p in mc.validate_manifest(doc)]
    return problems


def _run_index_kill_cell(workdir: str, synth: str, mc) -> List[str]:
    """SIGKILL `sofa archive` between the catalog index's chunk-store
    writes (SOFA_INDEX_EXIT_AFTER, sofa_tpu/archive/index.py), then
    prove `sofa resume` replays the journaled ingest + refresh and the
    recovered index answers IDENTICALLY to a never-interrupted rebuild:
    byte-identical index_commit.json (it carries no clock by design),
    equal query answers, archive fsck 0."""
    import shutil as sh

    from sofa_tpu.archive import catalog as acat
    from sofa_tpu.archive import index as aindex
    from sofa_tpu.archive.store import ArchiveStore, archive_fsck
    from sofa_tpu.durability import sofa_resume

    logdir = os.path.join(workdir, "kill-mid-index") + "/"
    root = os.path.join(workdir, "kill-mid-index-store")
    shutil.rmtree(logdir, ignore_errors=True)
    shutil.rmtree(root, ignore_errors=True)
    shutil.copytree(synth, logdir)
    cfg = SofaConfig(logdir=logdir)
    problems: List[str] = []
    sofa_preprocess(cfg)

    repo = os.path.dirname(_TOOLS)
    env = dict(os.environ, SOFA_INDEX_EXIT_AFTER="2")
    env.pop("_SOFA_INDEX_WRITES", None)
    snippet = (
        "import sys\n"
        "sys.path.insert(0, sys.argv[3])\n"
        "from sofa_tpu.config import SofaConfig\n"
        "from sofa_tpu.archive.store import ingest_run\n"
        "ingest_run(SofaConfig(logdir=sys.argv[1]), sys.argv[2])\n")
    r = subprocess.run([sys.executable, "-c", snippet, logdir, root,
                        repo], capture_output=True, text=True,
                       timeout=600, env=env)
    if r.returncode != 87:
        return problems + [f"crash child exited rc={r.returncode} "
                           "(expected the index chaos knob's hard-exit "
                           "87 between chunk-store writes); stderr "
                           f"tail: {r.stderr.strip()[-200:]}"]
    if aindex.is_current(root):
        problems.append("interrupted refresh left a CURRENT index — "
                        "the commit should not have landed")
    rc = sofa_resume(cfg)
    if rc != 0:
        problems.append(f"sofa resume rc={rc}")
    if not aindex.is_current(root):
        problems.append("index not current after resume")
    report = archive_fsck(root)
    for verdict in ("corrupt", "missing", "orphaned", "uncataloged",
                    "index"):
        if (report or {}).get(verdict):
            problems.append(f"archive fsck: {len(report[verdict])} "
                            f"{verdict} after resume")
    # never-interrupted twin: rebuild from scratch beside it — the
    # commit docs must be byte-identical and the answers equal
    twin = root + "-twin"
    shutil.rmtree(twin, ignore_errors=True)
    sh.copytree(root, twin)
    aindex.drop(twin)
    aindex.refresh(twin)
    a = open(aindex.commit_path(root), "rb").read()
    b = open(aindex.commit_path(twin), "rb").read()
    if a != b:
        problems.append("recovered index_commit.json differs from a "
                        "never-interrupted rebuild")
    if aindex.run_entries(root) != aindex.run_entries(twin):
        problems.append("recovered run entries differ from rebuild")
    if aindex.offenders(root, "*", 50) != aindex.offenders(twin, "*", 50):
        problems.append("recovered offender ranking differs from rebuild")
    runs = acat.ingest_entries(acat.read_catalog(root))
    if len(runs) != 1:
        problems.append(f"catalog holds {len(runs)} run(s), expected 1")
    elif ArchiveStore(root).load_run(runs[0]["run"]) is None:
        problems.append("cataloged run doc unreadable")
    return problems


def _run_fleet_kill_cell(workdir: str, synth: str, mc) -> List[str]:
    """SIGKILL `sofa fleet analyze` inside the commit window — report
    written, fold memo NOT (SOFA_FLEET_EXIT_AFTER, sofa_tpu/analysis/
    fleet.py) — then prove the torn ``_fleet/`` reads as healthy-pending
    (fleet verify/fsck 0) and a plain re-run converges BYTE-IDENTICALLY
    to a drop-and-full-recompute twin: the artifact carries no wall
    clock, so crash, resume, warm, and cold all hash the same."""
    import shutil as sh

    from sofa_tpu.analysis import fleet as afleet
    from sofa_tpu.archive.store import archive_fsck, ingest_run

    logdir = os.path.join(workdir, "kill-mid-fleet") + "/"
    root = os.path.join(workdir, "kill-mid-fleet-store")
    shutil.rmtree(logdir, ignore_errors=True)
    shutil.rmtree(root, ignore_errors=True)
    shutil.copytree(synth, logdir)
    cfg = SofaConfig(logdir=logdir)
    problems: List[str] = []
    sofa_preprocess(cfg)
    ingest_run(cfg, root)

    repo = os.path.dirname(_TOOLS)
    env = dict(os.environ, SOFA_FLEET_EXIT_AFTER="1",
               SOFA_FLEET_REFRESH="0")
    env.pop("_SOFA_FLEET_TICKS", None)
    snippet = (
        "import sys\n"
        "sys.path.insert(0, sys.argv[2])\n"
        "from sofa_tpu.analysis import fleet\n"
        "fleet.analyze(sys.argv[1])\n")
    r = subprocess.run([sys.executable, "-c", snippet, root, repo],
                       capture_output=True, text=True, timeout=600,
                       env=env)
    if r.returncode != 86:
        return problems + [f"crash child exited rc={r.returncode} "
                           "(expected the fleet chaos knob's hard-exit "
                           "86 between the report and memo writes); "
                           f"stderr tail: {r.stderr.strip()[-200:]}"]
    if not os.path.isfile(afleet.report_path(root)):
        problems.append("crash window left no fleet_report.json — the "
                        "report write must precede the chaos tick")
    if os.path.isfile(afleet.state_path(root)):
        problems.append("crash window left a fleet_state.json — the "
                        "memo commit must FOLLOW the chaos tick")
    if afleet.verify(root):
        problems.append("torn _fleet/ (report ahead of memo) read as "
                        f"damage, not healthy-pending: {afleet.verify(root)}")
    # a plain re-run converges the torn state...
    afleet.analyze(root)
    recovered = open(afleet.report_path(root), "rb").read()
    if not os.path.isfile(afleet.state_path(root)):
        problems.append("re-run after crash did not commit the memo")
    # ...to the byte-identical artifact a never-interrupted cold
    # recompute writes
    twin = root + "-twin"
    shutil.rmtree(twin, ignore_errors=True)
    sh.copytree(root, twin)
    afleet.drop(twin)
    afleet.analyze(twin)
    if recovered != open(afleet.report_path(twin), "rb").read():
        problems.append("recovered fleet_report.json differs from a "
                        "never-interrupted cold recompute")
    report = archive_fsck(root)
    for verdict in ("corrupt", "missing", "orphaned", "uncataloged",
                    "index", "fleet"):
        if (report or {}).get(verdict):
            problems.append(f"archive fsck: {len(report[verdict])} "
                            f"{verdict} after fleet crash+re-run")
    doc = afleet.load_report(root)
    if doc is None:
        problems.append("recovered fleet report unreadable")
    else:
        problems += [f"fleet report: {p}"
                     for p in mc.validate_fleet_report(doc)]
    return problems


def _run_fleet_verb_cell(workdir: str, synth: str, mc) -> List[str]:
    """The `sofa fleet analyze` verb's exit-code ladder under fault
    injection (sofa_tpu/analysis/fleet.py sofa_fleet): 2 on usage and on
    a missing archive, 0 on a clean run, 1 when a registered pass
    crashes (fault isolation: the report still commits with the sticky
    ``failed`` entry and every healthy pass's artifact intact)."""
    from sofa_tpu.analysis import fleet as afleet
    from sofa_tpu.archive.store import ingest_run

    logdir = os.path.join(workdir, "fleet-verb") + "/"
    root = os.path.join(workdir, "fleet-verb-store")
    shutil.rmtree(logdir, ignore_errors=True)
    shutil.rmtree(root, ignore_errors=True)
    shutil.copytree(synth, logdir)
    cfg = SofaConfig(logdir=logdir)
    problems: List[str] = []
    sofa_preprocess(cfg)
    ingest_run(cfg, root)

    rc = afleet.sofa_fleet(cfg, "analyze", "")
    if rc != 2:
        problems.append(f"usage (no root) exited {rc}, expected 2")
    rc = afleet.sofa_fleet(cfg, "analyze",
                           os.path.join(workdir, "no-such-store"))
    if rc != 2:
        problems.append(f"missing archive exited {rc}, expected 2")
    rc = afleet.sofa_fleet(cfg, "analyze", root)
    if rc != 0:
        problems.append(f"clean analyze exited {rc}, expected 0")
    with afleet.scoped():
        afleet.load_builtin_passes()

        def chaos_fleet_crash(state, tables, ctx, features):
            raise RuntimeError("chaos: deliberate fleet pass crash")

        afleet.register_fleet_pass(chaos_fleet_crash,
                                   name="chaos_fleet_crash",
                                   reads_frames=("runs",))
        afleet.drop(root)
        rc = afleet.sofa_fleet(cfg, "analyze", root)
    if rc != 1:
        problems.append(f"crashing fleet pass exited {rc}, expected 1 "
                        "(report commits, pass entry sticky-failed)")
    doc = afleet.load_report(root)
    if doc is None:
        problems.append("no committed report after the crashing pass")
    else:
        entry = (doc.get("passes") or {}).get("chaos_fleet_crash") or {}
        if entry.get("status") != "failed":
            problems.append("crashing pass entry not sticky-failed: "
                            f"{entry.get('status')!r}")
        ok = [n for n, e in (doc.get("passes") or {}).items()
              if (e or {}).get("status") == "ok"]
        if not ok:
            problems.append("crashing pass took every other fleet "
                            "pass down with it")
    # converge back to the healthy artifact for any later consumer
    afleet.drop(root)
    if afleet.sofa_fleet(cfg, "analyze", root) != 0:
        problems.append("post-chaos reconverge analyze failed")
    return problems


def _run_crash_pass_cell(workdir: str, synth: str, mc) -> List[str]:
    """Register a deliberately crashing analysis pass, then run the full
    analyze: the registry executor must degrade it to a sticky ``failed``
    entry in meta.passes while every other pass runs and report.js + a
    schema-valid manifest still emit (sofa_tpu/analysis/registry.py)."""
    from sofa_tpu.analysis import registry
    from sofa_tpu.analyze import sofa_analyze

    logdir = os.path.join(workdir, "crash-pass") + "/"
    shutil.rmtree(logdir, ignore_errors=True)
    shutil.copytree(synth, logdir)
    cfg = SofaConfig(logdir=logdir)
    problems: List[str] = []
    frames = sofa_preprocess(cfg)
    with registry.scoped():
        registry.load_builtin_passes()

        def chaos_crash(frames, cfg, features):
            raise RuntimeError("chaos: deliberate pass crash")

        registry.register_pass(chaos_crash, name="chaos_crash")
        features = sofa_analyze(cfg, frames=frames)
    if not features.get("cpu_samples"):
        problems.append("crashing pass took the other passes' features "
                        "down with it")
    if not os.path.isfile(cfg.path("report.js")):
        problems.append("no report.js")
    doc = telemetry.load_manifest(logdir)
    if doc is None:
        return problems + ["no run_manifest.json"]
    problems += [f"manifest: {p}" for p in mc.validate_manifest(doc)]
    ledger = ((doc.get("meta") or {}).get("passes") or {}).get(
        "passes") or {}
    ent = ledger.get("chaos_crash") or {}
    if ent.get("status") != "failed":
        problems.append("chaos_crash pass not recorded as failed in "
                        "meta.passes")
    if "deliberate pass crash" not in str(ent.get("error", "")):
        problems.append("meta.passes entry lost the crash error")
    if any(e.get("status") == "failed" for n, e in ledger.items()
           if n != "chaos_crash"):
        problems.append("a healthy pass was marked failed")
    if mc.validate_manifest(doc, require_healthy=True) == []:
        problems.append("manifest_check --require-healthy missed the "
                        "failed pass")
    return problems


def _run_whatif_cell(workdir: str, synth: str, mc) -> List[str]:
    """`sofa whatif` over a degraded trace: corrupt the pcap so
    preprocess quarantines a source, then prove the replay still yields a
    schema-valid ``whatif_report.json`` with a stated calibration verdict
    and a schema-valid manifest carrying ``meta.whatif`` — a degraded
    capture must degrade the *answer's confidence*, never the report."""
    import json

    from sofa_tpu.whatif import REPORT_NAME, sofa_whatif

    logdir = os.path.join(workdir, "whatif-degraded") + "/"
    shutil.rmtree(logdir, ignore_errors=True)
    shutil.copytree(synth, logdir)
    cfg = SofaConfig(logdir=logdir)
    problems: List[str] = []
    with open(cfg.path("sofa.pcap"), "wb") as f:
        f.write(b"chaos: positively not a pcap file")
    sofa_preprocess(cfg)
    doc = telemetry.load_manifest(logdir)
    if doc is None:
        return ["no run_manifest.json"]
    if (doc.get("sources") or {}).get("nettrace", {}).get(
            "status") != "quarantined":
        problems.append("nettrace not quarantined — the cell's fault "
                        "never landed")
    # Preprocess regenerated the frame CSVs from raw collector files, and
    # the synth harness has no raw xplane — restore the device frames so
    # the replay calibrates against real step spans (as it would on a
    # capture whose xplane ingest succeeded while the pcap rotted).
    # Preprocess also committed (empty) columnar stores for them, and
    # read_frame prefers chunks over csv — drop the stores so the
    # restored CSVs are authoritative, as trace.write_frame's csv mode
    # would.
    from sofa_tpu import frames as framestore

    for fname in ("tpusteps.csv", "tputrace.csv"):
        shutil.copy(synth + fname, cfg.path(fname))
        framestore.delete_frame_store(logdir, fname[:-len(".csv")])
    rc = sofa_whatif(cfg)
    if rc not in (0, 1):
        problems.append(f"sofa whatif rc={rc} on a degraded trace "
                        "(expected 0 calibrated / 1 uncalibrated)")
    try:
        with open(cfg.path(REPORT_NAME)) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        return problems + [f"no readable {REPORT_NAME}: {e}"]
    problems += [f"report: {p}" for p in mc.validate_whatif(report)]
    if not (report.get("calibration") or {}).get("n_steps"):
        problems.append("replay saw no step spans — the restored device "
                        "frames never reached the model")
    doc = telemetry.load_manifest(logdir)
    problems += [f"manifest: {p}" for p in mc.validate_manifest(doc)]
    meta = ((doc or {}).get("meta") or {}).get("whatif")
    if not isinstance(meta, dict) or meta.get("verdict") != (
            report.get("calibration") or {}).get("verdict"):
        problems.append("meta.whatif missing or disagrees with the report")
    return problems


def _split_tail(path: str, fraction: float = 0.5) -> bytes:
    """Truncate a line-oriented raw file to its first ``fraction`` of
    lines (a mid-recording snapshot); returns the removed tail bytes so
    the caller can append them later, byte-identically."""
    with open(path, "rb") as f:
        data = f.read()
    lines = data.splitlines(keepends=True)
    keep = len(lines) // 2 if fraction == 0.5 else int(len(lines) * fraction)
    with open(path, "wb") as f:
        f.write(b"".join(lines[:keep]))
    return b"".join(lines[keep:])


def _live_control(logdir: str) -> dict:
    """Batch preprocess+analyze over the CURRENT raw state -> the
    byte-identity targets, then `sofa clean` back to raw-only."""
    from sofa_tpu.analyze import sofa_analyze
    from sofa_tpu.record import sofa_clean

    cfg = SofaConfig(logdir=logdir)
    sofa_analyze(cfg, frames=sofa_preprocess(cfg))
    want = {}
    for rel in ("report.js", "features.csv"):
        with open(cfg.path(rel), "rb") as f:
            want[rel] = f.read()
    sofa_clean(cfg)
    return want


def _live_converged_problems(logdir: str, want: dict, mc) -> List[str]:
    """Drain the live logdir and assert byte-identity + health."""
    from sofa_tpu.durability import sofa_fsck
    from sofa_tpu.live import sofa_live

    cfg = SofaConfig(logdir=logdir)
    problems: List[str] = []
    rc = sofa_live(cfg, epochs=0, drain=True)
    if rc != 0:
        problems.append(f"sofa live --drain rc={rc}")
    for rel, want_bytes in want.items():
        try:
            with open(cfg.path(rel), "rb") as f:
                got = f.read()
            if got != want_bytes:
                problems.append(
                    f"{rel} after drain differs from the batch control "
                    f"({len(got)} vs {len(want_bytes)} bytes)")
        except OSError as e:
            problems.append(f"no {rel} after drain: {e}")
    doc = telemetry.load_manifest(logdir)
    if doc is None:
        problems.append("no run_manifest.json after drain")
    else:
        problems += [f"manifest: {p}" for p in mc.validate_manifest(doc)]
        live_meta = (doc.get("meta") or {}).get("live")
        if live_meta is not None and live_meta.get("active") is not False:
            # absent is fine too (a drain over a cleaned logdir has no
            # live state left to mark)
            problems.append("meta.live.active not cleared by the drain")
    if sofa_fsck(cfg) != 0:
        problems.append("sofa fsck nonzero on the drained logdir")
    return problems


def _run_live_kill_cell(workdir: str, synth: str, mc) -> List[str]:
    """kill-mid-live-epoch: live epoch over half the tail, append the
    rest, SIGKILL the second epoch mid-tile-write with a torn-tail fault
    active, `sofa resume` the interrupted epoch, then drain — artifacts
    must converge byte-identical to a never-interrupted batch run."""
    import random

    from sofa_tpu.durability import sofa_resume
    from sofa_tpu.live import sofa_live

    logdir = os.path.join(workdir, "kill-mid-live-epoch") + "/"
    shutil.rmtree(logdir, ignore_errors=True)
    shutil.copytree(synth, logdir)
    problems: List[str] = []
    want = _live_control(logdir)

    cfg = SofaConfig(logdir=logdir, live_interval_s=0.0)
    tail = _split_tail(cfg.path("tpumon.txt"))
    rc = sofa_live(cfg, epochs=1)
    if rc != 0:
        problems.append(f"live epoch 1 rc={rc}")
    with open(cfg.path("tpumon.txt"), "ab") as f:
        f.write(tail)

    n = random.randint(1, 4)
    root = os.path.dirname(_TOOLS)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHAOS_LIVE_FAULTS="tpumon:tail_torn@2")
    r = subprocess.run(
        [sys.executable, "-c", _LIVE_KILL_SNIPPET, logdir, str(n), root],
        capture_output=True, text=True, timeout=600, env=env)
    if r.returncode != -9:
        return problems + [f"crash child exited rc={r.returncode} "
                           f"(expected SIGKILL -9 after tile #{n}); "
                           f"stderr tail: {r.stderr.strip()[-200:]}"]
    rc = sofa_resume(cfg)
    if rc != 0:
        problems.append(f"sofa resume rc={rc}")
    return problems + _live_converged_problems(logdir, want, mc)


def _run_live_rotate_cell(workdir: str, synth: str, mc) -> List[str]:
    """source-rotate-mid-tail: after a live epoch committed offsets into
    tpumon.txt, the file is rotated (new stream from byte 0).  The next
    epoch must detect it (`rotated` in meta.live), drop the stale
    chunks, re-ingest from zero, and still drain byte-identical to a
    batch run over the rotated state."""
    from sofa_tpu.live import OFFSETS_NAME, sofa_live

    logdir = os.path.join(workdir, "source-rotate-mid-tail") + "/"
    shutil.rmtree(logdir, ignore_errors=True)
    shutil.copytree(synth, logdir)
    problems: List[str] = []
    cfg = SofaConfig(logdir=logdir, live_interval_s=0.0)

    # rotation target: drop the first 60% of samples, as a restarting
    # collector would; the control is batch over this FINAL state
    with open(cfg.path("tpumon.txt"), "rb") as f:
        rotated_to = b"".join(f.read().splitlines(keepends=True)[6000:])
    import json as _json

    rc = sofa_live(cfg, epochs=1)  # commits offsets over the full file
    if rc != 0:
        problems.append(f"live epoch 1 rc={rc}")
    with open(cfg.path("tpumon.txt"), "wb") as f:
        f.write(rotated_to)
    rc = sofa_live(cfg, epochs=1)
    if rc != 0:
        problems.append(f"live epoch 2 rc={rc} after rotation")
    doc = telemetry.load_manifest(logdir) or {}
    src = (((doc.get("meta") or {}).get("live") or {})
           .get("sources") or {}).get("tpumon") or {}
    if src.get("status") != "rotated":
        problems.append(f"tpumon status {src.get('status')!r} after "
                        "rotation (expected 'rotated')")
    try:
        with open(cfg.path(OFFSETS_NAME)) as f:
            led = _json.load(f)
        if led["sources"]["tpumon"]["offset"] != len(rotated_to):
            problems.append("offset ledger did not re-ingest the rotated "
                            "file from byte 0")
    except (OSError, ValueError, KeyError) as e:
        problems.append(f"unreadable offset ledger: {e}")
    problems += [f"manifest: {p}" for p in mc.validate_manifest(doc)]

    # control AFTER the live run (same logdir discipline as the kill
    # cells): batch over the rotated state, clean, compare via drain
    want = _live_control(logdir)
    return problems + _live_converged_problems(logdir, want, mc)


def _start_service(workdir: str, store_root: str,
                   env_extra: "dict | None" = None):
    """Launch a fleet-service child on an ephemeral port; returns
    (proc, url).  Raises on a child that never prints its URL."""
    import re
    import time

    repo = os.path.dirname(_TOOLS)
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(env_extra or {}))
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", _SERVE_SNIPPET,
         os.path.join(workdir, "unused"), store_root, repo],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    deadline = time.monotonic() + 30.0
    url = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        m = re.search(r"at http://[^:/]+:(\d+)/v1/", line)
        if m:
            url = f"http://127.0.0.1:{m.group(1)}"
            break
    if url is None:
        proc.kill()
        raise RuntimeError("service child never printed its URL")
    return proc, url


def _fleet_agent_cfg(logdir: str, url: str, spool: str):
    return SofaConfig(logdir=logdir, serve_token="chaos",
                      agent_service=url, agent_spool=spool,
                      agent_settle_s=0.0, agent_retries=2,
                      agent_backoff_s=0.05, agent_backoff_cap_s=0.2)


def _fleet_store_problems(store_root: str, want_runs: int = 1) -> List[str]:
    """fsck + catalog assertions over the default tenant's store."""
    from sofa_tpu.archive import catalog as acat
    from sofa_tpu.archive.store import archive_fsck

    problems: List[str] = []
    troot = os.path.join(store_root, "tenants", "default")
    report = archive_fsck(troot)
    if report is None:
        return [f"no archive store at {troot}"]
    for verdict in ("corrupt", "missing", "orphaned", "uncataloged"):
        if report.get(verdict):
            problems.append(f"store fsck: {len(report[verdict])} "
                            f"{verdict}: {report[verdict][:3]}")
    runs = acat.ingest_entries(acat.read_catalog(troot))
    if len(runs) != want_runs:
        problems.append(f"catalog holds {len(runs)} run(s), expected "
                        f"{want_runs}")
    return problems


def _run_service_kill_cell(workdir: str, synth: str, mc) -> List[str]:
    """kill-service-mid-upload: the service hard-exits partway through
    the agent's push (SOFA_SERVE_EXIT_AFTER); the agent degrades to its
    spool, a restarted service receives the retry, and the final store
    is fsck-clean with exactly one cataloged run."""
    from sofa_tpu.agent import sofa_agent

    logdir = os.path.join(workdir, "kill-service") + "/"
    store = os.path.join(workdir, "kill-service-store")
    spool = os.path.join(workdir, "kill-service-spool")
    for path in (logdir, store, spool):
        shutil.rmtree(path, ignore_errors=True)
    shutil.copytree(synth, logdir)
    problems: List[str] = []
    sofa_preprocess(SofaConfig(logdir=logdir))
    # phase 1: service dies at its 4th write request, mid-upload
    proc, url = _start_service(workdir, store,
                               {"SOFA_SERVE_EXIT_AFTER": "4"})
    try:
        rc = sofa_agent(_fleet_agent_cfg(logdir, url, spool),
                        watch=logdir, once=True)
    finally:
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            problems.append("chaos service outlived its exit-after knob")
    if rc != 1:
        problems.append(f"agent --once rc={rc} against a dying service "
                        "(expected 1: spooled, not delivered)")
    if proc.returncode != 86:
        problems.append(f"service child exited rc={proc.returncode} "
                        "(expected the chaos hard-exit 86)")
    # the run is safe in the spool either way
    from sofa_tpu.archive import catalog as acat
    from sofa_tpu.archive.store import archive_fsck

    spool_runs = acat.ingest_entries(acat.read_catalog(spool))
    if len(spool_runs) != 1:
        problems.append(f"spool holds {len(spool_runs)} run(s) after the "
                        "service death, expected 1")
    spool_report = archive_fsck(spool) or {}
    for verdict in ("corrupt", "missing", "uncataloged"):
        if spool_report.get(verdict):
            problems.append(f"spool fsck: {verdict}: "
                            f"{spool_report[verdict][:3]}")
    # phase 2: service returns; the agent retry lands the run
    proc, url = _start_service(workdir, store)
    try:
        rc = sofa_agent(_fleet_agent_cfg(logdir, url, spool),
                        watch=logdir, once=True)
        if rc != 0:
            problems.append(f"agent retry rc={rc} (expected 0)")
        problems += _fleet_store_problems(store)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
    doc = telemetry.load_manifest(logdir)
    if doc is None:
        problems.append("no run_manifest.json after the push")
    else:
        problems += [f"manifest: {p}" for p in mc.validate_manifest(doc)]
        serve_meta = (doc.get("meta") or {}).get("serve")
        if not isinstance(serve_meta, dict):
            problems.append("meta.serve missing after a delivered push")
    return problems


def _run_agent_spool_cell(workdir: str, synth: str, mc) -> List[str]:
    """agent-offline-spool-then-drain: with no service reachable the
    agent spools (durable, fsck-clean, exit 1); when the service
    appears, the drain pass delivers the identical run."""
    from sofa_tpu.agent import sofa_agent

    logdir = os.path.join(workdir, "agent-offline") + "/"
    store = os.path.join(workdir, "agent-offline-store")
    spool = os.path.join(workdir, "agent-offline-spool")
    for path in (logdir, store, spool):
        shutil.rmtree(path, ignore_errors=True)
    shutil.copytree(synth, logdir)
    problems: List[str] = []
    sofa_preprocess(SofaConfig(logdir=logdir))
    # offline: nothing listens on the URL at all
    cfg = _fleet_agent_cfg(logdir, "http://127.0.0.1:9", spool)
    cfg.agent_retries = 0
    rc = sofa_agent(cfg, watch=logdir, once=True)
    if rc != 1:
        problems.append(f"agent --once rc={rc} offline (expected 1)")
    from sofa_tpu.archive import catalog as acat
    from sofa_tpu.archive.store import archive_fsck

    spool_runs = acat.ingest_entries(acat.read_catalog(spool))
    if len(spool_runs) != 1:
        problems.append(f"spool holds {len(spool_runs)} run(s) offline, "
                        "expected 1")
    spool_report = archive_fsck(spool) or {}
    for verdict in ("corrupt", "missing", "orphaned", "uncataloged"):
        if spool_report.get(verdict):
            problems.append(f"spool fsck: {verdict}: "
                            f"{spool_report[verdict][:3]}")
    # the service appears -> drain delivers the same run id
    proc, url = _start_service(workdir, store)
    try:
        cfg = _fleet_agent_cfg(logdir, url, spool)
        rc = sofa_agent(cfg, watch=logdir, once=True)
        if rc != 0:
            problems.append(f"agent drain rc={rc} (expected 0)")
        problems += _fleet_store_problems(store)
        troot = os.path.join(store, "tenants", "default")
        server_runs = acat.ingest_entries(acat.read_catalog(troot))
        if spool_runs and server_runs and \
                server_runs[0].get("run") != spool_runs[0].get("run"):
            problems.append("delivered run id differs from the spooled "
                            "run id — the drain did not ship the same "
                            "content")
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
    return problems


def _run_worker_kill_cell(workdir: str, synth: str, mc) -> List[str]:
    """kill-worker-mid-wal-drain: a 2-worker pool's owning drainer
    hard-exits (88) between the run-doc write and the catalog append —
    the widest replay window.  The supervisor respawns it (disarming the
    one-shot knob) and the WAL replay must converge: depth 0, exactly one
    catalog line, fsck-clean.  The push itself survives on WAL
    durability — the agent never loses the run."""
    import json as _json
    import signal
    import time
    import urllib.request

    from sofa_tpu.agent import sofa_agent

    logdir = os.path.join(workdir, "kill-worker") + "/"
    store = os.path.join(workdir, "kill-worker-store")
    spool = os.path.join(workdir, "kill-worker-spool")
    for path in (logdir, store, spool):
        shutil.rmtree(path, ignore_errors=True)
    shutil.copytree(synth, logdir)
    problems: List[str] = []
    sofa_preprocess(SofaConfig(logdir=logdir))
    proc, url = _start_service(workdir, store,
                               {"SOFA_CHAOS_SERVE_WORKERS": "2",
                                "SOFA_WAL_EXIT_AFTER": "1"})
    try:
        rc = sofa_agent(_fleet_agent_cfg(logdir, url, spool),
                        watch=logdir, once=True)
        if rc != 0:
            # the commit connection died with the worker: the run is in
            # the spool — one drain pass must deliver (WAL replay makes
            # the re-push a committed no-op)
            time.sleep(1.0)
            rc = sofa_agent(_fleet_agent_cfg(logdir, url, spool),
                            watch=logdir, once=True)
            if rc != 0:
                problems.append(f"agent drain rc={rc} after the worker "
                                "respawn (expected 0)")
        # replay proof: WAL depth for the default tenant returns to 0
        req = urllib.request.Request(
            f"{url}/v1/tier", headers={"Authorization": "Bearer chaos"})
        deadline = time.monotonic() + 30.0
        drained = False
        while time.monotonic() < deadline and not drained:
            try:
                with urllib.request.urlopen(req, timeout=5) as resp:
                    doc = _json.loads(resp.read())
                drained = bool(doc.get("tenants")) and all(
                    t.get("wal_depth") == 0 for t in doc["tenants"])
            except OSError:
                pass
            if not drained:
                time.sleep(0.2)
        if not drained:
            problems.append("WAL depth never returned to 0 after the "
                            "worker respawn")
        problems += _fleet_store_problems(store)
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            out, _ = proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate(timeout=10)
    if "exited 88" not in (out or ""):
        problems.append("no worker death observed: the pool never logged "
                        "the chaos exit-88 respawn")
    doc = telemetry.load_manifest(logdir)
    if doc is None:
        problems.append("no run_manifest.json after the push")
    else:
        problems += [f"manifest: {p}" for p in mc.validate_manifest(doc)]
    return problems


def _run_metrics_survival_cell(workdir: str, synth: str, mc) -> List[str]:
    """kill-worker-metrics-survive: a pool worker that has already
    persisted scrape windows to the ``_metrics/worker*`` chunk store is
    SIGKILLed mid-drain (SOFA_WAL_EXIT_AFTER).  After the supervisor
    respawn the history store must still open — no torn chunk, index
    consistent (the scrape's atomic-publish discipline is the claim
    under test) — a live /v1/metrics doc must validate against the
    sofa_tpu/fleet_metrics schema, and the tenant store stays
    fsck-clean."""
    import json as _json
    import signal
    import time
    import urllib.request

    from sofa_tpu import frames
    from sofa_tpu.agent import sofa_agent

    logdir = os.path.join(workdir, "metrics-survive") + "/"
    store = os.path.join(workdir, "metrics-survive-store")
    spool = os.path.join(workdir, "metrics-survive-spool")
    for path in (logdir, store, spool):
        shutil.rmtree(path, ignore_errors=True)
    shutil.copytree(synth, logdir)
    problems: List[str] = []
    sofa_preprocess(SofaConfig(logdir=logdir))
    # fast scrape so history chunks exist before AND after the kill
    proc, url = _start_service(workdir, store,
                               {"SOFA_CHAOS_SERVE_WORKERS": "2",
                                "SOFA_WAL_EXIT_AFTER": "1",
                                "SOFA_METRICS_SCRAPE_S": "0.2"})
    try:
        rc = sofa_agent(_fleet_agent_cfg(logdir, url, spool),
                        watch=logdir, once=True)
        if rc != 0:
            # commit connection died with the worker — one drain pass
            # after the respawn must deliver
            time.sleep(1.0)
            rc = sofa_agent(_fleet_agent_cfg(logdir, url, spool),
                            watch=logdir, once=True)
            if rc != 0:
                problems.append(f"agent drain rc={rc} after the worker "
                                "respawn (expected 0)")
        # let the respawned workers run a few scrape windows
        time.sleep(1.0)
        # live metrics doc from whichever worker answers
        req = urllib.request.Request(
            f"{url}/v1/metrics",
            headers={"Authorization": "Bearer chaos"})
        deadline = time.monotonic() + 30.0
        mdoc = None
        while time.monotonic() < deadline and mdoc is None:
            try:
                with urllib.request.urlopen(req, timeout=5) as resp:
                    doc = _json.loads(resp.read())
                if doc.get("scrape_seq"):
                    mdoc = doc
            except OSError:
                pass
            if mdoc is None:
                time.sleep(0.2)
        if mdoc is None:
            problems.append("no scraped /v1/metrics doc within 30s of "
                            "the worker respawn")
        else:
            problems += [f"/v1/metrics: {p}"
                         for p in mc.validate_fleet_metrics(mdoc)]
        problems += _fleet_store_problems(store)
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            out, _ = proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate(timeout=10)
    if "exited 88" not in (out or ""):
        problems.append("no worker death observed: the pool never logged "
                        "the chaos exit-88 respawn")
    # the persisted history survived the kill: every worker store opens
    # with a consistent index and no torn chunk
    mdir = os.path.join(store, "_metrics")
    stores = sorted(n for n in (os.listdir(mdir)
                                if os.path.isdir(mdir) else [])
                    if n.startswith("worker"))
    if frames.columnar_available():
        if not stores:
            problems.append("no _metrics/worker* history store persisted "
                            "before the kill")
        for name in stores:
            sdir = os.path.join(mdir, name)
            problems += [f"{name}: {p}" for p in
                         frames.verify_chunk_store(sdir, f"_metrics/{name}")]
            if frames.open_chunk_store(sdir) is None:
                problems.append(f"{name}: history chunk store does not "
                                "open after the worker kill")
    return problems


def _load_chaos_tier():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "chaos_tier", os.path.join(_TOOLS, "chaos_tier.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_kill_under_load_cell(workdir: str, synth: str, mc) -> List[str]:
    """kill-worker-under-load: chaos_tier.py's core pass at matrix
    scale — a pool worker is SIGKILLed and the whole pool rolling-
    restarted WHILE fleet_load traffic runs; zero acked pushes lost,
    run sets equal to an uninterrupted twin, every tenant fsck-clean
    with a commit sha byte-identical to an uninterrupted index build
    over the same ledger."""
    ct = _load_chaos_tier()
    doc = ct.run_chaos(workers=2, agents=4, pushes=3, pollers=1,
                       tenants=2, replica=False, disk_full_at=0)
    return list(doc["problems"])


def _run_lint_under_chaos_cell(workdir: str, synth: str, mc) -> List[str]:
    """lint-under-chaos: the protocol contract holds AFTER the tier has
    been through the kill-worker-under-load wringer — the static
    closure (`sofa protocol` + the SL024–SL028 lint slice) still exits
    0 against the tree.  Guards the class of regression where a chaos
    fix patches a handler into emitting a status/body the vocabulary
    never declared: the runtime cells above would pass while the
    contract silently forked."""
    problems: List[str] = []
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for label, cmd in (
            ("sofa protocol --json",
             [sys.executable, "-m", "sofa_tpu", "protocol", "--json"]),
            ("sofa lint --rule SL024..SL028",
             [sys.executable, "-m", "sofa_tpu", "lint",
              os.path.join(root, "sofa_tpu"),
              "--rule", "SL024,SL025,SL026,SL027,SL028"])):
        r = subprocess.run(cmd, cwd=root, capture_output=True, text=True,
                           timeout=300)
        if r.returncode != 0:
            tail = (r.stderr.strip() or r.stdout.strip()).splitlines()
            problems.append(
                f"{label} rc={r.returncode} after chaos: "
                + "; ".join(tail[-3:]))
    return problems


def _run_disk_full_wal_cell(workdir: str, synth: str, mc) -> List[str]:
    """disk-full-WAL: the service's 5th durable write (the WAL append
    behind the commit, after the synth run's 4 object puts) sees a
    fires-once ENOSPC and answers a typed 507 no_space instead of
    acking bytes it never made durable; the agent's backed-off retry
    lands the run, and the store converges fsck-clean."""
    from sofa_tpu.agent import sofa_agent

    logdir = os.path.join(workdir, "disk-full-wal") + "/"
    store = os.path.join(workdir, "disk-full-wal-store")
    spool = os.path.join(workdir, "disk-full-wal-spool")
    for path in (logdir, store, spool):
        shutil.rmtree(path, ignore_errors=True)
    shutil.copytree(synth, logdir)
    problems: List[str] = []
    sofa_preprocess(SofaConfig(logdir=logdir))
    proc, url = _start_service(workdir, store,
                               {"SOFA_FAULTS": "service:disk_full@5"})
    try:
        rc = sofa_agent(_fleet_agent_cfg(logdir, url, spool),
                        watch=logdir, once=True)
        if rc != 0:
            problems.append(f"agent rc={rc} across the disk_full "
                            "refusal (expected 0: the retry lands)")
        problems += _fleet_store_problems(store)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
    doc = telemetry.load_manifest(logdir)
    if doc is None:
        problems.append("no run_manifest.json after the push")
    else:
        problems += [f"manifest: {p}" for p in mc.validate_manifest(doc)]
    return problems


def _run_restore_then_serve_cell(workdir: str, synth: str, mc) -> List[str]:
    """restore-then-serve: push a run, back the tenant store up
    (incremental content-addressed snapshot), restore it into a FRESH
    root, and serve the restored root — /v1/query must answer the same
    run and the restore's own verification (fsck 0 + commit sha
    equality) must hold.  The disaster-recovery path proven end to end,
    not just file-by-file."""
    import json as _json
    import urllib.request

    from sofa_tpu.agent import sofa_agent
    from sofa_tpu.archive.store import backup_archive, restore_archive

    logdir = os.path.join(workdir, "restore-serve") + "/"
    store = os.path.join(workdir, "restore-serve-store")
    spool = os.path.join(workdir, "restore-serve-spool")
    backup = os.path.join(workdir, "restore-serve-backup")
    restored = os.path.join(workdir, "restore-serve-restored")
    for path in (logdir, store, spool, backup, restored):
        shutil.rmtree(path, ignore_errors=True)
    shutil.copytree(synth, logdir)
    problems: List[str] = []
    sofa_preprocess(SofaConfig(logdir=logdir))
    proc, url = _start_service(workdir, store)
    try:
        rc = sofa_agent(_fleet_agent_cfg(logdir, url, spool),
                        watch=logdir, once=True)
        if rc != 0:
            problems.append(f"agent rc={rc} (expected 0)")
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
    troot = os.path.join(store, "tenants", "default")
    summary = backup_archive(troot, backup)
    if summary.get("files", 0) <= 0:
        problems.append(f"backup copied {summary.get('files')} file(s)")
    verdict = restore_archive(backup, os.path.join(restored, "tenants",
                                                   "default"))
    if not verdict.get("ok"):
        problems.append(f"restore verification failed: {verdict}")
    # serve the RESTORED root: the run answers from the new tier
    proc, url = _start_service(workdir, restored)
    try:
        req = urllib.request.Request(
            f"{url}/v1/default/query?kind=runs&limit=10",
            headers={"Authorization": "Bearer chaos"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            doc = _json.loads(resp.read())
        rows = [r.get("run") for r in doc.get("rows") or []]
        if len(rows) != 1:
            problems.append(f"restored tier answers {len(rows)} run(s), "
                            "expected the 1 pushed run")
    except OSError as e:
        problems.append(f"restored tier query failed: {e}")
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
    problems += _fleet_store_problems(restored)
    return problems


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    workdir = os.path.abspath(args[0] if args else "/tmp/sofa_chaos")
    os.makedirs(workdir, exist_ok=True)
    mc = _load_manifest_check()
    synth = _synth(workdir)
    failures = 0
    n_cells = len(MATRIX) + len(KILL_CELLS) + 16
    width = max(len(n) for n, _s in
                [(n, None) for n, _s, _o in MATRIX] + KILL_CELLS
                + [("kill-mid-archive", None), ("whatif-degraded", None),
                   ("kill-service-mid-upload", None),
                   ("agent-offline-spool-then-drain", None),
                   ("kill-worker-mid-wal-drain", None),
                   ("kill-worker-metrics-survive", None),
                   ("kill-worker-under-load", None),
                   ("lint-under-chaos", None),
                   ("disk-full-wal", None),
                   ("restore-then-serve", None),
                   ("kill-mid-live-epoch", None),
                   ("source-rotate-mid-tail", None),
                   ("kill-mid-index-refresh", None),
                   ("kill-mid-fleet-analyze", None),
                   ("fleet-verb-exit-codes", None)])
    for name, spec, overrides in MATRIX:
        try:
            problems = _run_cell(name, spec, overrides, workdir, synth, mc)
        except Exception:  # noqa: BLE001 — a crashed cell is a failed cell
            problems = ["crashed:\n" + traceback.format_exc()]
        status = "PASS" if not problems else "FAIL"
        failures += bool(problems)
        print(f"{name.ljust(width)}  {status}  "
              f"{spec or '(real corrupt pcap)'}")
        for p in problems:
            print(f"{' ' * width}    - {p}")
    for name, point in KILL_CELLS:
        try:
            problems = _run_kill_cell(name, point, workdir, synth, mc)
        except Exception:  # noqa: BLE001 — a crashed cell is a failed cell
            problems = ["crashed:\n" + traceback.format_exc()]
        status = "PASS" if not problems else "FAIL"
        failures += bool(problems)
        print(f"{name.ljust(width)}  {status}  (SIGKILL during {point}, "
              "then sofa resume)")
        for p in problems:
            print(f"{' ' * width}    - {p}")
    try:
        problems = _run_archive_kill_cell(workdir, synth, mc)
    except Exception:  # noqa: BLE001 — a crashed cell is a failed cell
        problems = ["crashed:\n" + traceback.format_exc()]
    status = "PASS" if not problems else "FAIL"
    failures += bool(problems)
    print(f"{'kill-mid-archive'.ljust(width)}  {status}  (SIGKILL during "
          "archive ingest, then sofa resume)")
    for p in problems:
        print(f"{' ' * width}    - {p}")
    try:
        problems = _run_index_kill_cell(workdir, synth, mc)
    except Exception:  # noqa: BLE001 — a crashed cell is a failed cell
        problems = ["crashed:\n" + traceback.format_exc()]
    status = "PASS" if not problems else "FAIL"
    failures += bool(problems)
    print(f"{'kill-mid-index-refresh'.ljust(width)}  {status}  (SIGKILL "
          "between index chunk-store writes, then sofa resume)")
    for p in problems:
        print(f"{' ' * width}    - {p}")
    try:
        problems = _run_fleet_kill_cell(workdir, synth, mc)
    except Exception:  # noqa: BLE001 — a crashed cell is a failed cell
        problems = ["crashed:\n" + traceback.format_exc()]
    status = "PASS" if not problems else "FAIL"
    failures += bool(problems)
    print(f"{'kill-mid-fleet-analyze'.ljust(width)}  {status}  (SIGKILL "
          "between fleet report and memo writes, then re-analyze)")
    for p in problems:
        print(f"{' ' * width}    - {p}")
    try:
        problems = _run_fleet_verb_cell(workdir, synth, mc)
    except Exception:  # noqa: BLE001 — a crashed cell is a failed cell
        problems = ["crashed:\n" + traceback.format_exc()]
    status = "PASS" if not problems else "FAIL"
    failures += bool(problems)
    print(f"{'fleet-verb-exit-codes'.ljust(width)}  {status}  (sofa fleet "
          "analyze exit ladder, crashing registered fleet pass)")
    for p in problems:
        print(f"{' ' * width}    - {p}")
    try:
        problems = _run_crash_pass_cell(workdir, synth, mc)
    except Exception:  # noqa: BLE001 — a crashed cell is a failed cell
        problems = ["crashed:\n" + traceback.format_exc()]
    status = "PASS" if not problems else "FAIL"
    failures += bool(problems)
    print(f"{'crash-pass'.ljust(width)}  {status}  (crashing registered "
          "analysis pass, then sofa analyze)")
    for p in problems:
        print(f"{' ' * width}    - {p}")
    try:
        problems = _run_whatif_cell(workdir, synth, mc)
    except Exception:  # noqa: BLE001 — a crashed cell is a failed cell
        problems = ["crashed:\n" + traceback.format_exc()]
    status = "PASS" if not problems else "FAIL"
    failures += bool(problems)
    print(f"{'whatif-degraded'.ljust(width)}  {status}  (corrupt pcap -> "
          "quarantine, then sofa whatif)")
    for p in problems:
        print(f"{' ' * width}    - {p}")
    for name, cell in (("kill-service-mid-upload", _run_service_kill_cell),
                       ("agent-offline-spool-then-drain",
                        _run_agent_spool_cell),
                       ("kill-worker-mid-wal-drain",
                        _run_worker_kill_cell),
                       ("kill-worker-metrics-survive",
                        _run_metrics_survival_cell),
                       ("kill-worker-under-load",
                        _run_kill_under_load_cell),
                       ("lint-under-chaos", _run_lint_under_chaos_cell),
                       ("disk-full-wal", _run_disk_full_wal_cell),
                       ("restore-then-serve",
                        _run_restore_then_serve_cell)):
        try:
            problems = cell(workdir, synth, mc)
        except Exception:  # noqa: BLE001 — a crashed cell is a failed cell
            problems = ["crashed:\n" + traceback.format_exc()]
        status = "PASS" if not problems else "FAIL"
        failures += bool(problems)
        detail = ("sofa protocol + sofa lint SL024..SL028, post-chaos"
                  if name == "lint-under-chaos" else
                  "sofa serve + sofa agent, sofa_tpu/archive/service.py")
        print(f"{name.ljust(width)}  {status}  ({detail})")
        for p in problems:
            print(f"{' ' * width}    - {p}")
    for name, cell in (("kill-mid-live-epoch", _run_live_kill_cell),
                       ("source-rotate-mid-tail", _run_live_rotate_cell)):
        try:
            problems = cell(workdir, synth, mc)
        except Exception:  # noqa: BLE001 — a crashed cell is a failed cell
            problems = ["crashed:\n" + traceback.format_exc()]
        status = "PASS" if not problems else "FAIL"
        failures += bool(problems)
        print(f"{name.ljust(width)}  {status}  (sofa live streaming "
              "epochs, sofa_tpu/live.py)")
        for p in problems:
            print(f"{' ' * width}    - {p}")
    print(f"chaos matrix: {n_cells - failures}/{n_cells} cells "
          "survived with a valid manifest + report")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
