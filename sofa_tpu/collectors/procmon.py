"""System-monitor sampling: /proc/stat, /proc/diskstats, /proc/net/dev,
/proc/cpuinfo at cfg.sys_mon_rate Hz.

Prefers the native sysmon daemon (sofa_tpu/native/sysmon.cc) — one process,
no interpreter wakeups inside the measurement — and falls back to Python
daemon threads emitting byte-identical file formats (the reference's
approach, /root/reference/bin/sofa_record.py:25-135,257-289).  Formats are
documented in sysmon.cc and parsed by sofa_tpu/ingest/procfs.py.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

from sofa_tpu.collectors.base import ProcessCollector
from sofa_tpu.collectors.native_build import ensure_built
from sofa_tpu.printing import print_info


def read_proc_stat_lines(ts: float) -> List[str]:
    out = []
    try:
        with open("/proc/stat") as f:
            for line in f:
                if not line.startswith("cpu"):
                    break
                parts = line.split()
                name = "cpuall" if parts[0] == "cpu" else parts[0]
                vals = (parts[1:9] + ["0"] * 8)[:8]
                out.append(f"{ts:.6f} {name} " + " ".join(vals))
    except OSError:
        pass
    return out


def read_diskstats_lines(ts: float) -> List[str]:
    out = []
    try:
        with open("/proc/diskstats") as f:
            for line in f:
                p = line.split()
                if len(p) < 12:
                    continue
                dev = p[2]
                if dev.startswith(("loop", "ram")):
                    continue
                rd_ios, rd_sec, rd_ms = p[3], p[5], p[6]
                wr_ios, wr_sec, wr_ms = p[7], p[9], p[10]
                inflight = p[11]
                out.append(
                    f"{ts:.6f} {dev} {rd_ios} {rd_sec} {rd_ms} {wr_ios} {wr_sec} {wr_ms} {inflight}"
                )
    except OSError:
        pass
    return out


def read_netdev_lines(ts: float, iface_filter: Optional[str] = None) -> List[str]:
    out = []
    try:
        with open("/proc/net/dev") as f:
            for line in f:
                if ":" not in line:
                    continue
                iface, _, rest = line.partition(":")
                iface = iface.strip()
                if iface == "lo" or (iface_filter and iface != iface_filter):
                    continue
                p = rest.split()
                if len(p) < 10:
                    continue
                rxb, rxp, txb, txp = p[0], p[1], p[8], p[9]
                out.append(f"{ts:.6f} {iface} {rxb} {txb} {rxp} {txp}")
    except OSError:
        pass
    return out


def read_cpuinfo_line(ts: float) -> str:
    mhz = []
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    try:
                        mhz.append(f"{float(line.split(':')[1]):.3f}")
                    except (ValueError, IndexError):
                        pass
    except OSError:
        pass
    if not mhz:
        mhz = ["0"]
    return f"{ts:.6f} " + " ".join(mhz)


class ProcMonCollector(ProcessCollector):
    """Samples host system counters at sys_mon_rate Hz."""

    name = "procmon"

    def __init__(self, cfg):
        super().__init__(cfg)
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def probe(self) -> Optional[str]:
        if not os.path.isfile("/proc/stat"):
            return "no /proc filesystem"
        return None

    def start(self) -> None:
        cfg = self.cfg
        tool = ensure_built("sysmon")
        if tool:
            argv = [tool, cfg.logdir, str(cfg.sys_mon_rate)]
            if cfg.netstat_interface:
                argv.append(cfg.netstat_interface)
            self.launch(argv)
            return
        print_info("procmon: python fallback sampler threads")
        # Fresh event per (re)start: a supervisor restart after a die must
        # not inherit the stop signal that killed the previous sampler.
        self._stop_event = threading.Event()
        self._thread = threading.Thread(target=self._sample_loop, daemon=True)
        self._thread.start()

    def alive(self):
        if self.proc is not None:
            return super().alive()
        if self._thread is not None:
            return self._thread.is_alive()
        return None

    def fault_kill(self) -> None:
        if self.proc is not None:
            super().fault_kill()
        elif self._thread is not None:
            self._stop_event.set()

    def _sample_loop(self) -> None:
        cfg = self.cfg
        interval = 1.0 / max(cfg.sys_mon_rate, 1)
        files = {
            "mpstat": open(cfg.path("mpstat.txt"), "a"),
            "diskstat": open(cfg.path("diskstat.txt"), "a"),
            "netstat": open(cfg.path("netstat.txt"), "a"),
            "cpuinfo": open(cfg.path("cpuinfo.txt"), "a"),
        }
        try:
            while not self._stop_event.is_set():
                ts = time.time()
                for line in read_proc_stat_lines(ts):
                    files["mpstat"].write(line + "\n")
                for line in read_diskstats_lines(ts):
                    files["diskstat"].write(line + "\n")
                for line in read_netdev_lines(ts, cfg.netstat_interface):
                    files["netstat"].write(line + "\n")
                files["cpuinfo"].write(read_cpuinfo_line(ts) + "\n")
                for f in files.values():
                    f.flush()
                self._stop_event.wait(interval)
        finally:
            for f in files.values():
                f.close()

    def stop(self, **kwargs) -> None:
        if self._thread is not None:
            self._stop_event.set()
            self._thread.join(timeout=5)
        super().stop(**kwargs)

    def outputs(self) -> List[str]:
        cfg = self.cfg
        return [cfg.path("mpstat.txt"), cfg.path("diskstat.txt"),
                cfg.path("netstat.txt"), cfg.path("cpuinfo.txt")]
