"""Plugin loading.

The reference imports any module named on PYTHONPATH and calls
``<name>(cfg)`` at CLI start (/root/reference/bin/sofa:21,322 with
plugins/dummy_plugin.py).  We generalize: ``--plugin mod`` or ``--plugin
mod:func`` — the callable receives the SofaConfig before the pipeline runs and
may mutate it (register filters, tweak collector knobs, ...).

Third-party **analysis passes** ride the same entry point: a plugin module
(or its callable) registers passes through
``sofa_tpu.analysis.registry.analysis_pass`` / ``register_pass``; anything
registered while the plugin loads is tagged ``plugin:<spec>`` so it is
attributable in ``sofa passes`` and the manifest's ``meta.passes`` ledger,
and the registry executor fault-isolates it — a crashing third-party pass
degrades to a warning + ``failed`` status instead of aborting analyze.
A plugin that crashes while *loading* degrades here the same way.
"""

from __future__ import annotations

import importlib

from sofa_tpu.printing import print_error, print_info


def load_plugins(cfg) -> None:
    from sofa_tpu.analysis import registry

    for spec in cfg.plugins:
        mod_name, _, func_name = spec.partition(":")
        with registry.plugin_origin(spec):
            try:
                mod = importlib.import_module(mod_name)
            except ImportError as e:
                print_error(f"plugin {spec!r}: cannot import {mod_name!r}: {e}")
                continue
            func = getattr(mod, func_name or mod_name.rsplit(".", 1)[-1], None)
            if not callable(func):
                print_error(f"plugin {spec!r}: no callable entry point")
                continue
            try:
                func(cfg)
            except Exception as e:  # noqa: BLE001 — one bad plugin must not kill the verb
                print_error(f"plugin {spec!r}: entry point raised "
                            f"{type(e).__name__}: {e} — plugin skipped")
                continue
        print_info(f"plugin {spec!r} loaded")
