"""gRPC advice service — remote hints on a performance feature vector.

The reference queries a remote POTATO server
(/root/reference/bin/sofa_analyze.py:49-73: gRPC Hint(HintRequest{hostname,
pfv}) -> HintResponse) and autodiscovers it from the environment
(bin/sofa:269-271).  This module provides both sides with no grpc_tools
dependency: handlers are registered generically and messages come from the
protoc-generated hint_pb2 (sofa_tpu/native/hint.proto).

Server:  python -m sofa_tpu.analysis.hint_service [port]
Client:  sofa report --hint_server host:port   (also honors
         $SOFA_HINT_SERVER, the POTATO_SERVER_SERVICE_HOST analogue)
"""

from __future__ import annotations

import os
from typing import List

from sofa_tpu.ingest import hint_pb2

SERVICE = "sofa_tpu.hint.HintService"
METHOD = f"/{SERVICE}/Hint"


# Explicit network deadlines: analyze must not stall on an unreachable or
# wedged advice server.  Connect (channel-ready) and read (RPC) budgets are
# separate so a routable-but-dead host fails in seconds, not at the TCP
# stack's leisure; both are env-tunable for slow links.
DEFAULT_CONNECT_TIMEOUT_S = 3.0
DEFAULT_READ_TIMEOUT_S = 5.0


def _env_timeout(var: str, default: float) -> float:
    raw = os.environ.get(var, "").strip()
    try:
        val = float(raw) if raw else default
    except ValueError:
        return default
    return val if val > 0 else default


def discover_server(cfg) -> str | None:
    if cfg.hint_server:
        return cfg.hint_server
    host = os.environ.get("SOFA_HINT_SERVER")
    return host


def request_hints(server: str, features, hostname: str = "",
                  timeout: "float | None" = None,
                  connect_timeout: "float | None" = None) -> List[str]:
    import grpc

    if timeout is None:
        timeout = _env_timeout("SOFA_HINT_TIMEOUT_S",
                               DEFAULT_READ_TIMEOUT_S)
    if connect_timeout is None:
        connect_timeout = _env_timeout("SOFA_HINT_CONNECT_TIMEOUT_S",
                                       DEFAULT_CONNECT_TIMEOUT_S)
    if ":" not in server:
        server += ":50051"
    req = hint_pb2.HintRequest(hostname=hostname or os.uname().nodename)
    for name, value in features.to_frame().itertuples(index=False):
        req.features[name] = float(value)
    with grpc.insecure_channel(server) as channel:
        # Bounded connect: without this, the first RPC's deadline also
        # absorbs name-resolution/TCP stalls and the error is ambiguous.
        grpc.channel_ready_future(channel).result(timeout=connect_timeout)
        call = channel.unary_unary(
            METHOD,
            request_serializer=hint_pb2.HintRequest.SerializeToString,
            response_deserializer=hint_pb2.HintResponse.FromString,
        )
        resp = call(req, timeout=timeout)
    return list(resp.hints)


def fetch_hints(cfg, features) -> List[str]:
    """The analyze-facing entry point: discover + request with bounded
    deadlines, degrading to a telemetry-routed warning (empty result) on
    any network/service failure instead of raising into the pipeline."""
    from sofa_tpu.printing import print_warning

    server = discover_server(cfg)
    if not server:
        return []
    try:
        return request_hints(server, features)
    except Exception as e:  # noqa: BLE001 — remote advice is best-effort
        print_warning(f"hint server {server}: {type(e).__name__}: {e} — "
                      "continuing without remote hints")
        return []


def serve(port: int = 50051, block: bool = True):
    """Run the advice server: applies the local rule engine to whatever
    feature vector a client sends."""
    import grpc

    from sofa_tpu.analysis.advice import generate_hints
    from sofa_tpu.analysis.features import Features
    from sofa_tpu.config import SofaConfig

    def hint_handler(request: hint_pb2.HintRequest, context) -> hint_pb2.HintResponse:
        features = Features()
        for name, value in request.features.items():
            features.add(name, value)
        hints = generate_hints(features, SofaConfig())
        if not hints:
            hints = ["no obvious bottleneck in the submitted feature vector"]
        return hint_pb2.HintResponse(hints=hints)

    handler = grpc.method_handlers_generic_handler(
        SERVICE,
        {
            "Hint": grpc.unary_unary_rpc_method_handler(
                hint_handler,
                request_deserializer=hint_pb2.HintRequest.FromString,
                response_serializer=hint_pb2.HintResponse.SerializeToString,
            )
        },
    )
    from concurrent import futures

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((handler,))
    bound = server.add_insecure_port(f"[::]:{port}")
    server.start()
    print(f"sofa_tpu hint service listening on :{bound}")
    if block:
        server.wait_for_termination()
    return server, bound


if __name__ == "__main__":
    import sys

    serve(int(sys.argv[1]) if len(sys.argv) > 1 else 50051)
