"""Artifact-lifecycle flow analysis: SL014–SL018 fixtures, the seeded
registry mutation, the shipped-tree closure gate, and the `sofa
artifacts` inventory verb (schema, exit codes, logdir audit).

Fixture trees opt into companions per rule: a registry-bearing trace.py
activates the graph; tools/manifest_check.py enables SL016 + the SL018
validator leg; board/ enables SL017; docs/OBSERVABILITY.md enables the
SL018 docs leg.  Absent companions keep those rules inert, mirroring how
a single-file `sofa lint` run behaves.
"""

import json
import os
import sys
import textwrap

import pytest

from sofa_tpu.lint.core import ProjectContext, lint_paths
from sofa_tpu.lint.rules import default_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

ARTIFACT_IDS = ("SL014", "SL015", "SL016", "SL017", "SL018")

REGISTRY = """
    RAW_FILES = ["raw.txt"]
    DERIVED_SUFFIXES = (".csv",)
    DERIVED_FILES = ["good.json", "dead.json"]
    DERIVED_DIRS = ["_scratch"]
    DIGEST_SKIP_FILES = frozenset({"good.json"})
    DIGEST_SKIP_DIRS = frozenset({"_scratch"})
"""


def run_artifact_rules(tmp_path, files, extra_paths=()):
    """Write {relname: src} under tmp_path/pkg (registry tree), lint the
    .py files, return only the artifact-rule findings."""
    paths = []
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
        if rel.endswith(".py"):
            paths.append(str(p))
    paths.extend(str(tmp_path / rel) for rel in extra_paths)
    project = ProjectContext.detect(paths, base=str(tmp_path))
    fs = lint_paths(paths, default_rules(), project=project,
                    base=str(tmp_path))
    return [f for f in fs if f.rule_id in ARTIFACT_IDS]


# --- SL014 ------------------------------------------------------------------

def test_sl014_flags_unregistered_writer(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/m.py": """
            from sofa_tpu.durability import atomic_write
            def w(logdir):
                with atomic_write("leak.bin") as f:
                    f.write("x")
        """,
    })
    assert [f.rule_id for f in fs] == ["SL014"]
    assert fs[0].file.endswith("pkg/m.py") and "leak.bin" in fs[0].message


def test_sl014_ok_registered_suffix_dir_and_raw(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/m.py": """
            import os
            from sofa_tpu.durability import atomic_write, fsync_append
            def w(logdir):
                with atomic_write("good.json") as f:      # registered
                    f.write("x")
                with atomic_write("table.csv") as f:      # suffix
                    f.write("x")
                with atomic_write(os.path.join("_scratch", "x.bin")) as f:
                    f.write("x")                          # registered dir
                fsync_append("raw.txt", "line")           # raw file
            def r():
                open("good.json").read()
                open("dead.json").read()
        """,
    })
    assert [f.rule_id for f in fs] == []


def test_sl014_resolves_constants_and_scope_assigns(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/m.py": """
            import os
            from sofa_tpu.durability import atomic_write
            NAME = "leak2.bin"
            def w(logdir):
                path = os.path.join(logdir, NAME)
                with atomic_write(path) as f:
                    f.write("x")
        """,
    })
    assert [f.rule_id for f in fs] == ["SL014"]
    assert "leak2.bin" in fs[0].message


# --- SL015 ------------------------------------------------------------------

def test_sl015_flags_unregistered_skip_entry_and_dir(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": """
            RAW_FILES = []
            DERIVED_SUFFIXES = (".csv",)
            DERIVED_FILES = ["good.json"]
            DERIVED_DIRS = []
            DIGEST_SKIP_FILES = frozenset({"typo.json"})
            DIGEST_SKIP_DIRS = frozenset({"_ghost"})
        """,
    })
    ids = sorted(f.rule_id for f in fs)
    assert ids == ["SL015", "SL015"]
    msgs = " ".join(f.message for f in fs)
    assert "typo.json" in msgs and "_ghost" in msgs
    assert all(f.file.endswith("trace.py") for f in fs)


def test_sl015_flags_digestless_verb_writer(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/cli.py": """
            from pkg.verb import sofa_thing
        """,
        "pkg/verb.py": """
            from sofa_tpu.durability import atomic_write
            def sofa_thing(cfg):
                with atomic_write("thing.csv") as f:
                    f.write("x")
        """,
    })
    assert [f.rule_id for f in fs] == ["SL015"]
    assert fs[0].file.endswith("verb.py") and "thing.csv" in fs[0].message


def test_sl015_ok_when_skip_listed_or_digests_refreshed(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/cli.py": """
            from pkg.verb import sofa_thing
            from pkg.verb2 import sofa_other
        """,
        "pkg/verb.py": """
            from sofa_tpu.durability import atomic_write
            def sofa_thing(cfg):
                with atomic_write("good.json") as f:    # skip-listed
                    f.write("x")
        """,
        "pkg/verb2.py": """
            from sofa_tpu.durability import atomic_write, write_digests
            def sofa_other(cfg):
                with atomic_write("other.csv") as f:
                    f.write("x")
                write_digests(cfg.logdir)               # refreshes
        """,
    })
    assert [f.rule_id for f in fs] == []


# --- SL016 ------------------------------------------------------------------

MANIFEST_CHECK_FIXTURE = """
    def validate_manifest(doc):
        probs = []
        bar = (doc.get("meta") or {}).get("bar")
        if bar is None:
            probs.append("meta.bar missing")
        return probs
"""


def test_sl016_both_directions(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/t.py": """
            def write(tel):
                tel.set_meta(foo={"x": 1})
        """,
        "tools/manifest_check.py": MANIFEST_CHECK_FIXTURE,
    })
    ids = sorted(f.rule_id for f in fs)
    assert ids == ["SL016", "SL016"]
    by_msg = {f.message.split()[2]: f for f in fs}
    assert "meta.foo" in str({f.message for f in fs})
    assert "meta.bar" in str({f.message for f in fs})
    unval = next(f for f in fs if "meta.foo" in f.message)
    assert unval.file.endswith("pkg/t.py")
    unwritten = next(f for f in fs if "meta.bar" in f.message)
    assert unwritten.file.endswith("tools/manifest_check.py")


def test_sl016_inert_without_manifest_check(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/t.py": """
            def write(tel):
                tel.set_meta(foo={"x": 1})
        """,
    })
    assert [f.rule_id for f in fs] == []


# --- SL017 ------------------------------------------------------------------

def test_sl017_endpoint_without_producer_and_dead_artifact(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/m.py": """
            def r():
                open("good.json").read()
        """,
        "pkg/board/page.html": """
            <script>fetch("ghost.csv");</script>
        """,
    })
    ids = sorted(f.rule_id for f in fs)
    assert ids == ["SL017", "SL017"]
    ghost = next(f for f in fs if "ghost.csv" in f.message)
    assert ghost.severity == "error" and ghost.file.endswith("page.html")
    dead = next(f for f in fs if "dead.json" in f.message)
    assert dead.severity == "warn" and dead.file.endswith("trace.py")


def test_sl017_ok_with_producer_route_and_readers(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/viz.py": """
            ROUTE = "/tiles/"
        """,
        "pkg/m.py": """
            from sofa_tpu.durability import atomic_write
            def w():
                with atomic_write("series.csv") as f:
                    f.write("x")
            def r():
                open("good.json").read()
                open("dead.json").read()
        """,
        "pkg/board/page.html": """
            <script>
            fetch("series.csv"); fetch("good.json");
            fetch("tiles/s/0/0.json.gz"); fetch("raw.txt");
            </script>
        """,
    })
    assert [f.rule_id for f in fs] == []


# --- SL018 ------------------------------------------------------------------

def test_sl018_writer_validator_and_docs_agreement(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/w.py": """
            FOO_SCHEMA = "sofa_tpu/foo"
            FOO_VERSION = 2
        """,
        "tools/manifest_check.py": """
            _FOO_SCHEMA = "sofa_tpu/foo"
            _FOO_VERSION = 1
        """,
        "docs/OBSERVABILITY.md": """
            | schema id | version | writer | validator |
            |---|---|---|---|
            | `sofa_tpu/foo` | 3 | w.py | manifest_check |
        """,
    })
    msgs = sorted(f.message for f in fs if f.rule_id == "SL018")
    assert len(msgs) == 2
    assert any("manifest_check pins v1" in m for m in msgs)
    assert any("says v3" in m for m in msgs)


def test_sl018_missing_docs_row_and_stale_validator(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/w.py": """
            FOO_SCHEMA = "sofa_tpu/foo"
            FOO_VERSION = 1
        """,
        "tools/manifest_check.py": """
            _GONE_SCHEMA = "sofa_tpu/gone"
            _GONE_VERSION = 1
        """,
        "docs/OBSERVABILITY.md": """
            nothing tabled here
        """,
    })
    msgs = [f.message for f in fs if f.rule_id == "SL018"]
    assert any("no row in docs/OBSERVABILITY.md" in m for m in msgs)
    assert any("stale validator" in m for m in msgs)


def test_sl018_clean_when_all_three_agree(tmp_path):
    fs = run_artifact_rules(tmp_path, {
        "pkg/trace.py": REGISTRY,
        "pkg/w.py": """
            FOO_SCHEMA = "sofa_tpu/foo"
            FOO_VERSION = 2
        """,
        "tools/manifest_check.py": """
            _FOO_SCHEMA = "sofa_tpu/foo"
            _FOO_VERSION = 2
        """,
        "docs/OBSERVABILITY.md": """
            | `sofa_tpu/foo` | 2 | w.py | manifest_check |
        """,
    })
    assert [f.rule_id for f in fs] == []


# --- seeded mutation over the shipped tree ---------------------------------

def test_dropping_registry_entry_fires_sl014(tmp_path):
    """Acceptance: drop a DERIVED_FILES entry on a copy of the real
    trace.py and the real telemetry.py's writer site surfaces as SL014."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    src = open(os.path.join(REPO, "sofa_tpu", "trace.py")).read()
    assert '"run_manifest.json", "sofa_self_trace.json",' in src
    (pkg / "trace.py").write_text(src.replace(
        '"run_manifest.json", "sofa_self_trace.json",',
        '"sofa_self_trace.json",'))
    tel = open(os.path.join(REPO, "sofa_tpu", "telemetry.py")).read()
    (pkg / "telemetry.py").write_text(tel)
    paths = [str(pkg / "trace.py"), str(pkg / "telemetry.py")]
    project = ProjectContext.detect(paths, base=str(tmp_path))
    fs = lint_paths(paths, default_rules(), project=project,
                    base=str(tmp_path))
    hits = [f for f in fs if f.rule_id == "SL014"
            and "run_manifest.json" in f.message]
    assert hits and hits[0].file.endswith("telemetry.py")


# --- the shipped-tree closure gate -----------------------------------------

def test_shipped_tree_has_zero_artifact_findings():
    """Stronger than the baseline gate: the artifact rules must be fully
    burned down on the shipped tree — no grandfathering."""
    pkg = os.path.join(REPO, "sofa_tpu")
    fs = lint_paths([pkg], default_rules(), base=REPO)
    artifact = [f for f in fs if f.rule_id in ARTIFACT_IDS]
    assert artifact == [], [f.render() for f in artifact]


# --- the inventory verb -----------------------------------------------------

def test_build_inventory_full_closure():
    from sofa_tpu.artifacts import build_inventory

    doc = build_inventory()
    assert doc["ok"] is True
    assert doc["counts"]["violations"] == 0
    names = {r["name"] for r in doc["artifacts"]}
    for expected in ("report.js", "features.csv", "run_manifest.json",
                     "whatif_report.json", "regress_verdict.json",
                     "sol_roofline.csv"):
        assert expected in names
    for r in doc["artifacts"]:
        assert r["clean"] != "UNREGISTERED", r
    # every registered derived artifact fully covered by digest policy
    manifest = next(r for r in doc["artifacts"]
                    if r["name"] == "run_manifest.json")
    assert manifest["digest"] == "skip-list"
    assert manifest["writers"]


def test_inventory_schema_validates():
    from sofa_tpu.artifacts import build_inventory
    import manifest_check

    doc = build_inventory()
    assert manifest_check.validate_inventory(doc) == []
    assert manifest_check.validate_inventory(
        doc, require_healthy=True) == []
    broken = dict(doc, version=99)
    assert manifest_check.validate_inventory(broken)


def test_inventory_detects_logdir_leak(tmp_path):
    from sofa_tpu.artifacts import build_inventory, sofa_artifacts

    logdir = tmp_path / "log"
    logdir.mkdir()
    (logdir / "report.js").write_text("sofa_traces = {};")
    (logdir / "mpstat.txt").write_text("raw")
    assert sofa_artifacts(str(logdir)) == 0
    (logdir / "rogue.bin").write_text("leak me")
    assert sofa_artifacts(str(logdir)) == 2
    doc = build_inventory(str(logdir))
    assert doc["logdir"]["unaccounted"] == ["rogue.bin"]
    assert doc["ok"] is False


def test_cli_artifacts_verb_json(capsys):
    from sofa_tpu.cli import main

    assert main(["artifacts", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "sofa_tpu/artifact_inventory"
    assert doc["ok"] is True


def test_manifest_check_dispatches_inventory_doc(tmp_path, capsys):
    from sofa_tpu.artifacts import build_inventory
    import manifest_check

    path = tmp_path / "inv.json"
    path.write_text(json.dumps(build_inventory()))
    assert manifest_check.check_path(str(path)) == 0


# --- deterministic output ordering -----------------------------------------

def test_lint_output_sorted_by_rule_file_line(tmp_path, capsys):
    from sofa_tpu.lint.cli import run_lint

    (tmp_path / "b.py").write_text(
        "import subprocess\nsubprocess.run(['a'])\n"
        "try:\n    pass\nexcept Exception:\n    pass\n")
    (tmp_path / "a.py").write_text(
        "import subprocess\nsubprocess.run(['a'])\n")
    rc = run_lint([str(tmp_path), "--no-baseline", "--json",
                   "--base", str(tmp_path)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    keys = [(f["rule"], f["file"], f["line"]) for f in doc["new"]]
    assert keys == sorted(keys)
    rc = run_lint([str(tmp_path), "--no-baseline", "--base",
                   str(tmp_path)])
    out = capsys.readouterr().out.splitlines()
    rendered = [ln for ln in out if ": SL" in ln]
    parsed = [(ln.split(" ")[1], ln.split(":")[0]) for ln in rendered]
    assert parsed == sorted(parsed)


# --- registry aliasing ------------------------------------------------------

def test_record_reexports_trace_registry():
    import sofa_tpu.record as record
    import sofa_tpu.trace as trace

    assert record.DERIVED_FILES is trace.DERIVED_FILES
    assert record.RAW_FILES is trace.RAW_FILES
    assert record.DERIVED_DIRS is trace.DERIVED_DIRS
    assert "docker.cid" in trace.DERIVED_FILES


def test_durability_skip_list_is_trace_registry():
    from sofa_tpu import durability, trace

    assert durability._DIGEST_SKIP_FILES is trace.DIGEST_SKIP_FILES
    assert durability._DIGEST_SKIP_DIRS is trace.DIGEST_SKIP_DIRS


# --- pod_synth e2e (slow) ---------------------------------------------------

@pytest.mark.slow
def test_pod_synth_inventory_e2e(tmp_path):
    """Acceptance: `sofa artifacts --json <pod_synth logdir>` lists every
    derived artifact with full coverage and exits 0."""
    import subprocess

    logdir = str(tmp_path / "pod")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pod_synth.py"),
         "--raw", "--logdir", logdir],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "sofa_tpu", "report", "--logdir", logdir],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "sofa_tpu", "artifacts", logdir, "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout[r.stdout.index("{"):])
    assert doc["ok"] is True and doc["logdir"]["unaccounted"] == []
    assert doc["logdir"]["files_checked"] > 10
