"""SL024–SL028 — client↔server protocol-contract flow analysis.

PRs 12–18 grew a distributed fleet tier whose contract — routes, HTTP
statuses, typed ``{"error": ...}`` refusal bodies, Retry-After
discipline, client retry/fatal dispatch sets, fault-kind grammars, and
~40 ``SOFA_*`` environment knobs — lived only in docs/FLEET.md prose
and runtime tests.  This module extracts the whole protocol graph
statically (the artifact_rules.py playbook applied to the wire surface)
and enforces closure against the shared vocabulary both sides now
import from ``sofa_tpu/archive/protocol.py``:

SL024  route/status closure: a handler-emitted status STATUS_ERRORS
       does not declare; a client/board route no ROUTES entry shapes;
       a declared route no handler dispatches; a declared status nobody
       emits or handles; an error string nobody ever attaches
SL025  refusal discipline: RETRY_AFTER_STATUSES sends must attach
       Retry-After, NO_RETRY_AFTER_STATUSES (deadline 504) must NOT,
       every >=400 refusal carries a typed error body drawn from the
       shared vocabulary, and no raw ``send_response`` bypasses the
       typed helpers for a retryable status
SL026  env-knob registry: every SOFA_* token in the package has a row
       in docs/OBSERVABILITY.md's knob registry; a documented knob
       referenced nowhere (package/tools/tests/bench) is dead
SL027  fault-kind closure: every faults.py grammar kind has a consume
       site and a chaos/test reference; a consumed kind outside the
       grammar is a phantom the injection plan can never trigger
SL028  client retry-set soundness: the client's extracted dispatch
       sets match the declared CLIENT_* constants, every status the
       server marks retryable (Retry-After) is client-retryable, and
       fatal-error overrides stay inside FATAL_ERRORS

The graph activates only when the linted file set carries a
vocabulary module (a module-level ``STATUS_ERRORS`` dict) — fixture
trees and single-file lints opt in per rule by providing exactly the
companions a rule needs (board/, docs/OBSERVABILITY.md, tools/ +
tests/ reference text), mirroring the artifact graph's discipline.
Extraction is purely syntactic: the checked code is never imported.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from sofa_tpu.lint.core import FileContext, Finding, Rule, SEV_ERROR

#: A SOFA_* env-knob token: hard word boundaries on both sides so the
#: ``"SOFA_TPU_" + name`` template-prefix idiom and prose like
#: ``SOFA_Config`` never read as knobs.
_KNOB_RE = re.compile(r"(?<![A-Za-z0-9_])SOFA_[A-Z0-9_]*[A-Z0-9]"
                      r"(?![A-Za-z0-9_])")
#: A docs knob-registry row: ``| `SOFA_<NAME>` | ... |``.
_DOCS_KNOB_RE = re.compile(r"^\|\s*`(SOFA_[A-Z0-9_]+)`")
#: Characters a /v1/ path literal may contain — spaces/backticks reject
#: docstrings and prose that merely mention a route.
_PATH_OK_RE = re.compile(r"^[A-Za-z0-9_<>{}.:/?=&,-]*$")
#: A /v1/ path literal in a board page (double-quoted JS string); the
#: charset rejects display labels like ``"/v1/query ("``.
_BOARD_V1_RE = re.compile(r'"(/v1/[A-Za-z0-9_\-./<>]*(?:\?[^"]*)?)"')
#: The continuation literal after an open ``"/v1/" +`` prefix compose.
_BOARD_CONT_RE = re.compile(r'"(/[A-Za-z0-9_\-./?=&]*(?:\?[^"]*)?)"')
#: A short route-segment token a server dispatch compare uses.
_SEGMENT_RE = re.compile(r"^[a-z0-9_]{1,40}$")

#: Vocabulary constants build_protocol_graph reads from the vocab file.
_DECL_TUPLES = ("RETRY_AFTER_STATUSES", "NO_RETRY_AFTER_STATUSES",
                "CLIENT_RETRY_STATUSES", "CLIENT_FATAL_STATUSES",
                "CLIENT_RESUME_STATUSES")


@dataclass(frozen=True)
class Emission:
    """One typed-helper response site (``_json``/``_refuse``)."""

    relpath: str
    line: int
    status: int
    attach: bool          # Retry-After attached
    body_known: bool      # the doc arg resolved to a dict literal
    has_error: bool       # ... with an "error" key
    error: "str | None"   # ... whose value resolved to this string
    kind: str             # "json" | "refuse"


@dataclass(frozen=True)
class DispatchSite:
    """One client status-set compare (``e.code in (...)``)."""

    relpath: str
    line: int
    klass: str            # "retry" | "fatal" | "resume"
    statuses: tuple


@dataclass(frozen=True)
class ErrorOverride:
    """A client ``status == N and doc.get("error") == X`` dispatch."""

    relpath: str
    line: int
    klass: str
    status: "int | None"
    error: "str | None"


@dataclass
class ProtocolGraph:
    """The cross-file protocol facts SL024–SL028 (and the ``sofa
    protocol`` inventory verb) consult.  ``ok`` is False when the
    linted set carries no vocabulary module — every protocol rule is
    then inert."""

    ok: bool = False
    vocab_relpath: str = ""
    status_errors: Dict[int, tuple] = field(default_factory=dict)
    status_lines: Dict[int, int] = field(default_factory=dict)
    error_lines: Dict[str, int] = field(default_factory=dict)
    retry_after_statuses: tuple = ()
    no_retry_after_statuses: tuple = ()
    client_retry_statuses_decl: tuple = ()
    client_fatal_statuses_decl: tuple = ()
    client_resume_statuses_decl: tuple = ()
    client_retry_floor_decl: "int | None" = None
    fatal_errors_decl: tuple = ()
    decl_lines: Dict[str, int] = field(default_factory=dict)
    routes: tuple = ()                   # (method, path, line)
    emissions: tuple = ()                # Emission
    raw_sends: tuple = ()                # (relpath, line, status)
    client_routes: tuple = ()            # (relpath, line, normalized)
    board_routes: tuple = ()             # (relpath, line, normalized)
    server_files: frozenset = frozenset()
    server_tokens: frozenset = frozenset()
    retry_sites: tuple = ()              # DispatchSite klass=retry
    fatal_sites: tuple = ()              # DispatchSite klass=fatal
    resume_sites: tuple = ()             # DispatchSite klass=resume
    floor_sites: tuple = ()              # (relpath, line, floor)
    error_overrides: tuple = ()          # ErrorOverride
    error_uses: Dict[str, tuple] = field(default_factory=dict)
    knob_reads: tuple = ()               # (relpath, line, token)
    docs_knobs: "Dict[str, int] | None" = None
    docs_relpath: str = ""
    liveness_text: str = ""
    ref_text: str = ""
    ref_text_present: bool = False
    kinds: Dict[str, tuple] = field(default_factory=dict)
    grammar_relpath: str = ""
    kind_consumes: tuple = ()            # (relpath, line, kind)

    # -- closure helpers (shared with `sofa protocol`) ---------------------
    def client_statuses(self) -> frozenset:
        out = set()
        for site in self.retry_sites + self.fatal_sites + \
                self.resume_sites:
            out.update(site.statuses)
        out.update(ov.status for ov in self.error_overrides
                   if ov.status is not None)
        return frozenset(out)

    def client_retryable(self, status: int) -> bool:
        if any(status in s.statuses for s in self.retry_sites):
            return True
        return any(status >= fl for _r, _l, fl in self.floor_sites)

    def route_match(self, path: str) -> bool:
        """True when a normalized client/board path shapes onto a
        declared route (placeholder segments match anything)."""
        segs = _route_segments(path)
        if segs is None:
            return True
        for _method, rpath, _line in self.routes:
            rsegs = _route_segments(rpath)
            if rsegs is None or len(rsegs) != len(segs):
                continue
            if all(r.startswith("<") or r == s
                   for r, s in zip(rsegs, segs)):
                return True
        return not self.routes


def _route_segments(path: str) -> "List[str] | None":
    """Path segments after the /v1/ head, or None for a bare prefix."""
    if "/v1/" in path:
        path = path[path.index("/v1/"):]
    segs = [s for s in path.split("?", 1)[0].split("/") if s]
    if segs[:1] == ["v1"]:
        segs = segs[1:]
    return segs or None


def _normalize_route(s: str) -> "str | None":
    """A /v1/ path literal normalized for shape matching (``<>`` marks
    interpolated segments), or None when the string is prose/non-route."""
    if "/v1/" not in s or not _PATH_OK_RE.match(s):
        return None
    segs = _route_segments(s)
    if segs is None:
        return None
    return "/v1/" + "/".join(
        "<>" if ("<" in seg or "{" in seg) else seg for seg in segs)


# ---------------------------------------------------------------------------
# Per-file extraction.
# ---------------------------------------------------------------------------

class _ProtoFacts:
    """Everything one parse of one .py file contributes to the graph."""

    def __init__(self, path: str, relpath: str):
        self.relpath = relpath
        self.src = ""
        self.emissions: List[Emission] = []
        self.raw_sends: List[tuple] = []
        self.client_routes: List[tuple] = []
        self.server_tokens: set = set()
        self.is_server = False
        self.retry_sites: List[DispatchSite] = []
        self.fatal_sites: List[DispatchSite] = []
        self.resume_sites: List[DispatchSite] = []
        self.floor_sites: List[tuple] = []
        self.error_overrides: List[ErrorOverride] = []
        self.error_uses: List[tuple] = []     # (error, line)
        self.knob_reads: List[tuple] = []     # (line, token)
        self.kind_tables: Dict[str, List[tuple]] = {}
        self.kind_consumes: List[tuple] = []  # (line, kind, base name)
        self.fault_tainted: set = set()       # names assigned from faults.*
        self.imports_of: set = set()          # module stems this imports
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                self.src = f.read()
            self.tree = ast.parse(self.src, filename=path)
        except (OSError, SyntaxError, ValueError):
            self.tree = None
            return
        self._imports()
        self._module_consts()
        self._scopes()
        self._knobs()
        self._taint()

    def _imports(self):
        self.import_alias: Dict[str, str] = {}
        self.from_import: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_alias[a.asname or a.name.split(".")[0]] = \
                        a.name
                    self.imports_of.add(a.name.rsplit(".", 1)[-1])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_import[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
                    self.imports_of.add(a.name)
                self.imports_of.add(node.module.rsplit(".", 1)[-1])

    def _module_consts(self):
        self.str_consts: Dict[str, str] = {}
        self.int_consts: Dict[str, int] = {}
        self.tuple_consts: Dict[str, tuple] = {}
        for node in self.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            v = node.value
            if isinstance(v, ast.Constant):
                if isinstance(v.value, str):
                    self.str_consts[tgt.id] = v.value
                elif isinstance(v.value, int) and \
                        not isinstance(v.value, bool):
                    self.int_consts[tgt.id] = v.value
            elif isinstance(v, (ast.Tuple, ast.List)):
                vals = tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant))
                if len(vals) == len(v.elts):
                    self.tuple_consts[tgt.id] = vals
                if tgt.id == "KINDS" or tgt.id.endswith("_KINDS"):
                    self.kind_tables[tgt.id] = [
                        (e.value, e.lineno) for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]

    def _scopes(self):
        """Function-scope single-target assigns (name -> value expr) so
        a doc built locally and passed by name still resolves."""
        self.scope_assigns: Dict[tuple, ast.expr] = {}
        self.func_of: Dict[int, str] = {}

        def walk(node, func):
            for child in ast.iter_child_nodes(node):
                nf = func
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    nf = f"{func}.{child.name}" if func else child.name
                if isinstance(child, ast.Assign) and \
                        len(child.targets) == 1 and \
                        isinstance(child.targets[0], ast.Name):
                    key = (func, child.targets[0].id)
                    self.scope_assigns.setdefault(key, child.value)
                self.func_of[id(child)] = nf if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)) else func
                walk(child, nf)

        walk(self.tree, "")

    def _knobs(self):
        seen = set()
        for m in _KNOB_RE.finditer(self.src):
            tok = m.group(0)
            if tok in seen:
                continue
            seen.add(tok)
            self.knob_reads.append(
                (self.src.count("\n", 0, m.start()) + 1, tok))

    def _taint(self):
        """Names assigned from a call into the faults module — only
        these carry grammar kinds in consumer files (an _IngestTask's
        ``.kind`` is a different namespace entirely)."""
        aliases = {n for n, mod in self.import_alias.items()
                   if mod.rsplit(".", 1)[-1] == "faults"}
        aliases |= {n for n, origin in self.from_import.items()
                    if origin.rsplit(".", 1)[-1] == "faults"}
        fns = {n for n, origin in self.from_import.items()
               if ".faults." in "." + origin}
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            fn = node.value.func
            hit = (isinstance(fn, ast.Attribute)
                   and isinstance(fn.value, ast.Name)
                   and fn.value.id in aliases) or \
                  (isinstance(fn, ast.Name) and fn.id in fns)
            if hit:
                self.fault_tainted.add(node.targets[0].id)

    # -- resolution --------------------------------------------------------
    def _int_of(self, node, cross_int) -> "int | None":
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, int) and \
                not isinstance(node.value, bool):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in self.int_consts:
                return self.int_consts[node.id]
            origin = self.from_import.get(node.id)
            if origin:
                mod, _, attr = origin.rpartition(".")
                return cross_int.get((mod.rpartition(".")[-1], attr))
        return None

    def _str_of(self, node, cross_str) -> "str | None":
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in self.str_consts:
                return self.str_consts[node.id]
            origin = self.from_import.get(node.id)
            if origin:
                mod, _, attr = origin.rpartition(".")
                return cross_str.get((mod.rpartition(".")[-1], attr))
        return None

    def _tuple_of(self, node, cross_int, cross_tuple) -> "tuple | None":
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                v = self._int_of(e, cross_int)
                if v is None:
                    return None
                out.append(v)
            return tuple(out)
        if isinstance(node, ast.Name):
            if node.id in self.tuple_consts:
                return self.tuple_consts[node.id]
            origin = self.from_import.get(node.id)
            if origin:
                mod, _, attr = origin.rpartition(".")
                return cross_tuple.get((mod.rpartition(".")[-1], attr))
        return None

    def _doc_info(self, node, func, cross_str):
        """(body_known, has_error, resolved error string) for a
        response-doc argument; names resolve through enclosing-scope
        assignments.  Spread entries (``**doc``) are skipped — the
        literal keys decide."""
        d = node if isinstance(node, ast.Dict) else None
        if d is None and isinstance(node, ast.Name):
            scope, hit = func, None
            while hit is None:
                hit = self.scope_assigns.get((scope, node.id))
                if not scope:
                    break
                scope = scope.rpartition(".")[0]
            if isinstance(hit, ast.Dict):
                d = hit
        if d is None:
            return False, False, None
        for k, v in zip(d.keys, d.values):
            if isinstance(k, ast.Constant) and k.value == "error":
                return True, True, self._str_of(v, cross_str)
        return True, False, None

    def _refuse_default_attach(self) -> bool:
        """Whether this file's ``_refuse`` helper attaches Retry-After
        when the call site does not say otherwise."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.FunctionDef) or \
                    node.name != "_refuse":
                continue
            args = node.args.args
            defaults = node.args.defaults
            offset = len(args) - len(defaults)
            for i, a in enumerate(args):
                if a.arg == "retry_after" and i >= offset:
                    d = defaults[i - offset]
                    return not (isinstance(d, ast.Constant)
                                and d.value is None)
            for a, d in zip(node.args.kwonlyargs, node.args.kw_defaults):
                if a.arg == "retry_after" and d is not None:
                    return not (isinstance(d, ast.Constant)
                                and d.value is None)
        return False

    # -- the walk ----------------------------------------------------------
    def harvest(self, cross_str, cross_int, cross_tuple):
        if self.tree is None:
            return
        refuse_attach = self._refuse_default_attach()
        route_seen: set = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                s = node.value
                if s == "v1" or s.startswith("/v1/"):
                    self.is_server = True
                if _SEGMENT_RE.match(s):
                    self.server_tokens.add(s)
                norm = _normalize_route(s)
                if norm is not None:
                    for seg in norm.split("/"):
                        if _SEGMENT_RE.match(seg):
                            self.server_tokens.add(seg)
                    if norm not in route_seen:
                        route_seen.add(norm)
                        self.client_routes.append((node.lineno, norm))
                continue
            if isinstance(node, ast.JoinedStr):
                parts = []
                for v in node.values:
                    if isinstance(v, ast.Constant):
                        parts.append(str(v.value))
                    else:
                        parts.append("<>")
                norm = _normalize_route("".join(parts))
                if norm is not None and norm not in route_seen:
                    route_seen.add(norm)
                    self.client_routes.append((node.lineno, norm))
                continue
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and k.value == "error":
                        err = self._str_of(v, cross_str)
                        if err is not None:
                            self.error_uses.append((err, k.lineno))
                continue
            if isinstance(node, ast.Compare):
                self._compare(node, cross_str, cross_int, cross_tuple)
                continue
            if isinstance(node, ast.If):
                self._dispatch_if(node, cross_str, cross_int, cross_tuple)
                continue
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            tail = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            func = self.func_of.get(id(node), "")
            if tail == "send_response" and node.args:
                status = self._int_of(node.args[0], cross_int)
                if status is not None:
                    self.raw_sends.append((node.lineno, status))
            elif tail == "_json" and len(node.args) >= 2:
                self._emission(node, node.args[0], node.args[1], func,
                               False, "json", cross_str, cross_int)
            elif tail == "_refuse" and len(node.args) >= 3:
                self._emission(node, node.args[1], node.args[2], func,
                               refuse_attach, "refuse", cross_str,
                               cross_int)
            elif tail == "find" and isinstance(fn, ast.Attribute) and \
                    len(node.args) >= 2 and \
                    isinstance(node.args[1], ast.Constant) and \
                    isinstance(node.args[1].value, str):
                self.kind_consumes.append(
                    (node.lineno, node.args[1].value, None))

    def _emission(self, call, status_node, doc_node, func,
                  default_attach, kind, cross_str, cross_int):
        status = self._int_of(status_node, cross_int)
        if status is None:
            return
        attach = default_attach
        for kw in call.keywords:
            if kw.arg == "retry_after":
                attach = not (isinstance(kw.value, ast.Constant)
                              and kw.value.value is None)
        body_known, has_error, error = self._doc_info(
            doc_node, func, cross_str)
        self.emissions.append(Emission(
            self.relpath, call.lineno, status, attach,
            body_known, has_error, error, kind))

    def _compare(self, node, cross_str, cross_int, cross_tuple):
        """Fault-kind consume sites: ``x.kind <op> <literal/tuple>``."""
        if not (isinstance(node.left, ast.Attribute)
                and node.left.attr == "kind" and node.comparators):
            return
        comp = node.comparators[0]
        kinds: List[str] = []
        if isinstance(comp, ast.Constant) and isinstance(comp.value, str):
            kinds = [comp.value]
        elif isinstance(comp, (ast.Tuple, ast.List)):
            kinds = [e.value for e in comp.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str)]
        elif isinstance(comp, ast.Name):
            vals = self._tuple_of(comp, cross_int, cross_tuple)
            if vals is not None:
                kinds = [v for v in vals if isinstance(v, str)]
        base = node.left.value
        base_name = base.id if isinstance(base, ast.Name) else None
        for kind in kinds:
            self.kind_consumes.append((node.lineno, kind, base_name))

    def _dispatch_if(self, node, cross_str, cross_int, cross_tuple):
        """Client status dispatch: an ``if`` over ``.code`` compares
        whose body raises a typed transport exception."""
        klass = ""
        for st in node.body:
            if isinstance(st, ast.Raise) and st.exc is not None:
                exc = st.exc
                fn = exc.func if isinstance(exc, ast.Call) else exc
                name = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else "")
                if "Unavailable" in name:
                    klass = "retry"
                elif "Incomplete" in name:
                    klass = "resume"
                elif "Rejected" in name:
                    klass = "fatal"
                break
        if not klass:
            return
        err_cmp = None
        code_cmps = []
        for c in ast.walk(node.test):
            if not isinstance(c, ast.Compare) or not c.comparators:
                continue
            if isinstance(c.left, ast.Attribute) and c.left.attr == "code":
                code_cmps.append(c)
            elif isinstance(c.left, ast.Call) and \
                    isinstance(c.left.func, ast.Attribute) and \
                    c.left.func.attr == "get" and c.left.args and \
                    isinstance(c.left.args[0], ast.Constant) and \
                    c.left.args[0].value == "error":
                err_cmp = c
        by_klass = {"retry": self.retry_sites, "fatal": self.fatal_sites,
                    "resume": self.resume_sites}
        for c in code_cmps:
            op = c.ops[0]
            comp = c.comparators[0]
            if isinstance(op, ast.In):
                vals = self._tuple_of(comp, cross_int, cross_tuple)
                if vals is not None:
                    by_klass[klass].append(DispatchSite(
                        self.relpath, c.lineno, klass,
                        tuple(v for v in vals if isinstance(v, int))))
            elif isinstance(op, ast.Eq):
                status = self._int_of(comp, cross_int)
                if err_cmp is not None:
                    self.error_overrides.append(ErrorOverride(
                        self.relpath, c.lineno, klass, status,
                        self._str_of(err_cmp.comparators[0], cross_str)))
                elif status is not None:
                    by_klass[klass].append(DispatchSite(
                        self.relpath, c.lineno, klass, (status,)))
            elif isinstance(op, (ast.Gt, ast.GtE)) and klass == "retry":
                floor = self._int_of(comp, cross_int)
                if floor is not None:
                    if isinstance(op, ast.Gt):
                        floor += 1
                    self.floor_sites.append((self.relpath, c.lineno,
                                             floor))


# ---------------------------------------------------------------------------
# Vocabulary + companion extraction.
# ---------------------------------------------------------------------------

def _vocab_decls(mf: _ProtoFacts):
    """The shared-vocabulary declarations out of the vocab module's AST,
    or None when the module declares no STATUS_ERRORS dict."""
    decls = {"status_errors": {}, "status_lines": {}, "error_lines": {},
             "decl_lines": {}, "routes": [], "fatal_errors": (),
             "floor": None}
    for name in _DECL_TUPLES:
        decls[name] = ()
    found = False
    for node in mf.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        v = node.value
        if tgt.id == "STATUS_ERRORS" and isinstance(v, ast.Dict):
            found = True
            decls["decl_lines"]["STATUS_ERRORS"] = node.lineno
            for k, val in zip(v.keys, v.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, int)):
                    continue
                errs = []
                if isinstance(val, (ast.Tuple, ast.List)):
                    for e in val.elts:
                        s = mf._str_of(e, {})
                        if s is not None:
                            errs.append(s)
                            decls["error_lines"].setdefault(s, e.lineno)
                decls["status_errors"][k.value] = tuple(errs)
                decls["status_lines"][k.value] = k.lineno
        elif tgt.id in _DECL_TUPLES and isinstance(v, (ast.Tuple,
                                                       ast.List)):
            decls[tgt.id] = tuple(
                e.value for e in v.elts if isinstance(e, ast.Constant)
                and isinstance(e.value, int))
            decls["decl_lines"][tgt.id] = node.lineno
        elif tgt.id == "CLIENT_RETRY_FLOOR" and \
                isinstance(v, ast.Constant) and isinstance(v.value, int):
            decls["floor"] = v.value
            decls["decl_lines"][tgt.id] = node.lineno
        elif tgt.id == "FATAL_ERRORS" and isinstance(v, (ast.Tuple,
                                                         ast.List)):
            decls["fatal_errors"] = tuple(
                s for s in (mf._str_of(e, {}) for e in v.elts)
                if s is not None)
            decls["decl_lines"]["FATAL_ERRORS"] = node.lineno
        elif tgt.id == "ROUTES" and isinstance(v, (ast.Tuple, ast.List)):
            decls["decl_lines"]["ROUTES"] = node.lineno
            for e in v.elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, str) and " " in e.value:
                    method, _, path = e.value.partition(" ")
                    decls["routes"].append((method, path, e.lineno))
    return decls if found else None


def _board_routes(board_dir: str, base: str) -> List[tuple]:
    out = []
    for name in sorted(os.listdir(board_dir)):
        if not name.endswith((".html", ".js")):
            continue
        path = os.path.join(board_dir, name)
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(os.path.abspath(path), base)
        rel = rel.replace(os.sep, "/") if not rel.startswith("..") \
            else os.path.abspath(path)
        seen = set()
        for m in _BOARD_V1_RE.finditer(text):
            raw = m.group(1)
            if raw.split("?", 1)[0].endswith("/"):
                # an open prefix compose: ``"/v1/" + expr + "/rest..."``
                cont = _BOARD_CONT_RE.search(text, m.end(), m.end() + 300)
                raw = raw.split("?", 1)[0] + "<>" + \
                    (cont.group(1) if cont else "")
            norm = _normalize_route(raw)
            if norm is None or norm in seen:
                continue
            seen.add(norm)
            out.append((rel, text.count("\n", 0, m.start()) + 1, norm))
    return out


def _docs_knobs(path: str, base: str):
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            src = f.read()
    except OSError:
        return None, ""
    rel = os.path.relpath(os.path.abspath(path), base)
    rel = rel.replace(os.sep, "/") if not rel.startswith("..") \
        else os.path.abspath(path)
    rows: Dict[str, int] = {}
    for i, line in enumerate(src.splitlines(), 1):
        m = _DOCS_KNOB_RE.match(line.strip())
        if m:
            rows.setdefault(m.group(1), i)
    return (rows if rows else None), rel


def _companion_text(repo: str) -> Tuple[str, bool]:
    """Raw text of tools/*.py + tests/*.py + bench.py — the reference
    corpus for knob liveness and fault-kind chaos/test coverage."""
    chunks: List[str] = []
    present = False
    for sub in ("tools", "tests"):
        d = os.path.join(repo, sub)
        if not os.path.isdir(d):
            continue
        present = True
        for name in sorted(os.listdir(d)):
            if not name.endswith(".py"):
                continue
            try:
                with open(os.path.join(d, name), encoding="utf-8",
                          errors="replace") as f:
                    chunks.append(f.read())
            except OSError:
                pass
    bench = os.path.join(repo, "bench.py")
    if os.path.isfile(bench):
        present = True
        try:
            with open(bench, encoding="utf-8", errors="replace") as f:
                chunks.append(f.read())
        except OSError:
            pass
    return "\n".join(chunks), present


def build_protocol_graph(files, base: str) -> ProtocolGraph:
    """Assemble the graph from the linted file set.  ``files`` must
    contain a STATUS_ERRORS-bearing vocabulary module for the graph to
    activate; board pages, the docs knob registry, and the tools/tests
    reference corpus are discovered relative to it."""
    base = os.path.abspath(base)

    def rel(p):
        ab = os.path.abspath(p)
        return (os.path.relpath(ab, base).replace(os.sep, "/")
                if ab.startswith(base + os.sep) else ab)

    vocab_path, vocab_facts, decls = None, None, None
    py_files, seen = [], set()
    for f in files:
        if not f.endswith(".py"):
            continue
        ab = os.path.abspath(f)
        if ab in seen:
            continue
        seen.add(ab)
        py_files.append(f)
    for f in py_files:
        try:
            with open(f, encoding="utf-8", errors="replace") as fh:
                if "STATUS_ERRORS" not in fh.read():
                    continue
        except OSError:
            continue
        mf = _ProtoFacts(f, rel(f))
        if mf.tree is None:
            continue
        d = _vocab_decls(mf)
        if d is not None:
            vocab_path, vocab_facts, decls = os.path.abspath(f), mf, d
            break
    if vocab_path is None:
        return ProtocolGraph(ok=False)

    vocab_dir = os.path.dirname(vocab_path)
    pkg = os.path.dirname(vocab_dir) \
        if os.path.basename(vocab_dir) == "archive" else vocab_dir
    repo = os.path.dirname(pkg)

    facts: List[_ProtoFacts] = [vocab_facts]
    for f in py_files:
        if os.path.abspath(f) == vocab_path:
            continue
        facts.append(_ProtoFacts(f, rel(f)))
    cross_str: Dict[tuple, str] = {}
    cross_int: Dict[tuple, int] = {}
    cross_tuple: Dict[tuple, tuple] = {}
    for mf in facts:
        if mf.tree is None:
            continue
        stem = os.path.splitext(os.path.basename(mf.relpath))[0]
        for name, value in mf.str_consts.items():
            cross_str.setdefault((stem, name), value)
        for name, value in mf.int_consts.items():
            cross_int.setdefault((stem, name), value)
        for name, value in mf.tuple_consts.items():
            cross_tuple.setdefault((stem, name), value)
    for mf in facts:
        mf.harvest(cross_str, cross_int, cross_tuple)

    # the fault grammar: the module declaring BOTH a base KINDS tuple
    # and a NET_KINDS tuple (whatif's scenario KINDS is a different
    # vocabulary and must not activate the closure)
    grammar = next((mf for mf in facts
                    if "KINDS" in mf.kind_tables
                    and "NET_KINDS" in mf.kind_tables), None)
    kinds: Dict[str, tuple] = {}
    grammar_rel = ""
    kind_consumes: List[tuple] = []
    if grammar is not None:
        grammar_rel = grammar.relpath
        grammar_stem = os.path.splitext(
            os.path.basename(grammar.relpath))[0]
        for table, entries in sorted(grammar.kind_tables.items()):
            for kind, line in entries:
                kinds.setdefault(kind, (table, line))
        for mf in facts:
            if mf is grammar:
                kind_consumes.extend(
                    (mf.relpath, line, kind)
                    for line, kind, _base in mf.kind_consumes)
                continue
            if grammar_stem not in mf.imports_of:
                continue
            kind_consumes.extend(
                (mf.relpath, line, kind)
                for line, kind, base in mf.kind_consumes
                if base is not None and base in mf.fault_tainted)

    emissions: List[Emission] = []
    raw_sends: List[tuple] = []
    server_files: set = set()
    server_tokens: set = set()
    client_routes: List[tuple] = []
    retry_sites: List[DispatchSite] = []
    fatal_sites: List[DispatchSite] = []
    resume_sites: List[DispatchSite] = []
    floor_sites: List[tuple] = []
    overrides: List[ErrorOverride] = []
    error_uses: Dict[str, tuple] = {}
    knob_reads: List[tuple] = []
    for mf in facts:
        if mf.tree is None:
            continue
        if "lint/" in mf.relpath:
            # the lint package talks ABOUT the protocol; its own "v1"
            # literals must not make it a protocol-server file
            mf.is_server = False
        if mf.is_server:
            server_files.add(mf.relpath)
            server_tokens |= mf.server_tokens
            emissions.extend(mf.emissions)
            raw_sends.extend((mf.relpath, line, status)
                             for line, status in mf.raw_sends)
        client_routes.extend((mf.relpath, line, norm)
                             for line, norm in mf.client_routes)
        retry_sites.extend(mf.retry_sites)
        fatal_sites.extend(mf.fatal_sites)
        resume_sites.extend(mf.resume_sites)
        floor_sites.extend(mf.floor_sites)
        overrides.extend(mf.error_overrides)
        for err, line in mf.error_uses:
            error_uses.setdefault(err, (mf.relpath, line))
        knob_reads.extend((mf.relpath, line, tok)
                          for line, tok in mf.knob_reads)

    board_dir = os.path.join(pkg, "board")
    board = _board_routes(board_dir, base) \
        if os.path.isdir(board_dir) else []

    docs_knobs, docs_rel = _docs_knobs(
        os.path.join(repo, "docs", "OBSERVABILITY.md"), base)
    ref_text, ref_present = _companion_text(repo)
    liveness = "\n".join([mf.src for mf in facts] + [ref_text])

    return ProtocolGraph(
        ok=True,
        vocab_relpath=vocab_facts.relpath,
        status_errors=decls["status_errors"],
        status_lines=decls["status_lines"],
        error_lines=decls["error_lines"],
        retry_after_statuses=decls["RETRY_AFTER_STATUSES"],
        no_retry_after_statuses=decls["NO_RETRY_AFTER_STATUSES"],
        client_retry_statuses_decl=decls["CLIENT_RETRY_STATUSES"],
        client_fatal_statuses_decl=decls["CLIENT_FATAL_STATUSES"],
        client_resume_statuses_decl=decls["CLIENT_RESUME_STATUSES"],
        client_retry_floor_decl=decls["floor"],
        fatal_errors_decl=decls["fatal_errors"],
        decl_lines=decls["decl_lines"],
        routes=tuple(decls["routes"]),
        emissions=tuple(sorted(
            emissions, key=lambda e: (e.relpath, e.line, e.status))),
        raw_sends=tuple(sorted(raw_sends)),
        client_routes=tuple(sorted(client_routes)),
        board_routes=tuple(sorted(board)),
        server_files=frozenset(server_files),
        server_tokens=frozenset(server_tokens),
        retry_sites=tuple(sorted(
            retry_sites, key=lambda s: (s.relpath, s.line))),
        fatal_sites=tuple(sorted(
            fatal_sites, key=lambda s: (s.relpath, s.line))),
        resume_sites=tuple(sorted(
            resume_sites, key=lambda s: (s.relpath, s.line))),
        floor_sites=tuple(sorted(floor_sites)),
        error_overrides=tuple(sorted(
            overrides, key=lambda o: (o.relpath, o.line))),
        error_uses=error_uses,
        knob_reads=tuple(sorted(knob_reads)),
        docs_knobs=docs_knobs,
        docs_relpath=docs_rel,
        liveness_text=liveness,
        ref_text=ref_text,
        ref_text_present=ref_present,
        kinds=kinds,
        grammar_relpath=grammar_rel,
        kind_consumes=tuple(sorted(kind_consumes)),
    )


# ---------------------------------------------------------------------------
# The rules.
# ---------------------------------------------------------------------------

def _graph(ctx: FileContext) -> Optional[ProtocolGraph]:
    g = getattr(ctx.project, "protocol", None)
    return g if isinstance(g, ProtocolGraph) and g.ok else None


class _ProtocolRule(Rule):
    """Base: finish()-only rules over the shared protocol graph.
    Site-anchored findings (emissions, client compares, knob reads)
    are emitted from their own file; vocabulary/board/docs findings
    are emitted while visiting the vocab module so each appears
    exactly once."""

    node_types: tuple = ()


class RouteStatusClosure(_ProtocolRule):
    """SL024 — the route/status surface is closed over the shared
    vocabulary: every emitted status is declared, every client/board
    route shapes onto a declared route, every declared route has a
    server dispatch token, and no declared status or error string is
    dead on both sides."""

    rule_id = "SL024"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        g = _graph(ctx)
        if g is None:
            return
        for em in g.emissions:
            if em.relpath == ctx.relpath and \
                    em.status not in g.status_errors:
                yield Finding(
                    em.relpath, em.line, self.rule_id,
                    f"handler emits HTTP {em.status}, which "
                    "protocol.STATUS_ERRORS does not declare — the "
                    "client dispatch table cannot know it",
                    self.severity)
        for relpath, line, status in g.raw_sends:
            if relpath == ctx.relpath and status not in g.status_errors:
                yield Finding(
                    relpath, line, self.rule_id,
                    f"send_response({status}) emits a status "
                    "protocol.STATUS_ERRORS does not declare",
                    self.severity)
        for relpath, line, norm in g.client_routes:
            if relpath == ctx.relpath and not g.route_match(norm):
                yield Finding(
                    relpath, line, self.rule_id,
                    f"route {norm!r} matches no protocol.ROUTES entry "
                    "— the server answers it 404", self.severity)
        if ctx.relpath != g.vocab_relpath:
            return
        for relpath, line, norm in g.board_routes:
            if not g.route_match(norm):
                yield Finding(
                    relpath, line, self.rule_id,
                    f"board fetch {norm!r} matches no protocol.ROUTES "
                    "entry — the page fetches a 404", self.severity)
        if g.server_files:
            for method, path, line in g.routes:
                segs = _route_segments(path) or ()
                for seg in segs:
                    if seg.startswith("<"):
                        continue
                    if seg not in g.server_tokens:
                        yield Finding(
                            g.vocab_relpath, line, self.rule_id,
                            f"declared route {method} {path!r}: no "
                            f"handler dispatches segment {seg!r} — "
                            "dead route entry", self.severity)
        if g.emissions and (g.retry_sites or g.fatal_sites):
            emitted = {em.status for em in g.emissions} | \
                {status for _r, _l, status in g.raw_sends}
            referenced = set(g.client_statuses())
            floors = [fl for _r, _l, fl in g.floor_sites]
            for status in sorted(g.status_errors):
                if status in emitted or status in referenced:
                    continue
                if any(status >= fl for fl in floors):
                    continue
                yield Finding(
                    g.vocab_relpath, g.status_lines.get(status, 0),
                    self.rule_id,
                    f"STATUS_ERRORS declares {status} but no handler "
                    "emits it and no client dispatch handles it — "
                    "dead status", self.severity)
        for err in sorted(g.error_lines):
            if err not in g.error_uses:
                yield Finding(
                    g.vocab_relpath, g.error_lines[err], self.rule_id,
                    f"error string {err!r} is declared in "
                    "STATUS_ERRORS but never attached to any response "
                    "body — dead vocabulary", self.severity)


class RefusalDiscipline(_ProtocolRule):
    """SL025 — every refusal is typed and honest about retrying:
    RETRY_AFTER_STATUSES sends attach Retry-After, the deadline 504
    does NOT, every >=400 body carries a shared-vocabulary error
    string, and no raw send_response bypasses the helpers for a
    retryable status."""

    rule_id = "SL025"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        g = _graph(ctx)
        if g is None:
            return
        for em in g.emissions:
            if em.relpath != ctx.relpath:
                continue
            if em.status in g.retry_after_statuses and not em.attach:
                yield Finding(
                    em.relpath, em.line, self.rule_id,
                    f"HTTP {em.status} is a capacity refusal "
                    "(RETRY_AFTER_STATUSES) but this send attaches no "
                    "Retry-After — clients fall back to blind backoff",
                    self.severity)
            if em.status in g.no_retry_after_statuses and em.attach:
                yield Finding(
                    em.relpath, em.line, self.rule_id,
                    f"HTTP {em.status} is a deadline refusal "
                    "(NO_RETRY_AFTER_STATUSES) but this send attaches "
                    "Retry-After — it invites a retry nobody is "
                    "waiting for", self.severity)
            allowed = g.status_errors.get(em.status, ())
            if em.status >= 400 and allowed:
                if not em.body_known:
                    yield Finding(
                        em.relpath, em.line, self.rule_id,
                        f"HTTP {em.status} refusal body does not "
                        "resolve to a dict literal — the typed "
                        "{'error': ...} contract cannot be checked",
                        self.severity)
                elif not em.has_error:
                    yield Finding(
                        em.relpath, em.line, self.rule_id,
                        f"HTTP {em.status} refusal carries no typed "
                        "{'error': ...} body — clients cannot "
                        "dispatch on it", self.severity)
                elif em.error is None:
                    yield Finding(
                        em.relpath, em.line, self.rule_id,
                        f"HTTP {em.status} refusal's error value does "
                        "not resolve to a shared-vocabulary constant "
                        "(archive/protocol.py)", self.severity)
                elif em.error not in allowed:
                    yield Finding(
                        em.relpath, em.line, self.rule_id,
                        f"error {em.error!r} is not in "
                        f"STATUS_ERRORS[{em.status}] — undeclared "
                        "status/error pairing", self.severity)
        for relpath, line, status in g.raw_sends:
            if relpath == ctx.relpath and \
                    status in g.retry_after_statuses:
                yield Finding(
                    relpath, line, self.rule_id,
                    f"raw send_response({status}) bypasses the typed "
                    "refusal helpers — no Retry-After, no error body",
                    self.severity)


class EnvKnobRegistry(_ProtocolRule):
    """SL026 — every SOFA_* knob the package reads has a row in
    docs/OBSERVABILITY.md's env-knob registry, and every documented
    knob is still referenced somewhere (package, tools, tests, bench).
    Both directions are drift: an undocumented knob is invisible to
    operators; a dead row documents a control nobody wired."""

    rule_id = "SL026"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        g = _graph(ctx)
        if g is None or g.docs_knobs is None:
            return
        for relpath, line, token in g.knob_reads:
            if relpath == ctx.relpath and token not in g.docs_knobs:
                yield Finding(
                    relpath, line, self.rule_id,
                    f"SOFA_* knob {token} is read here but "
                    "docs/OBSERVABILITY.md's env-knob registry has no "
                    "row for it — undocumented control surface",
                    self.severity)
        if ctx.relpath == g.vocab_relpath:
            for token in sorted(g.docs_knobs):
                if token not in g.liveness_text:
                    yield Finding(
                        g.docs_relpath, g.docs_knobs[token],
                        self.rule_id,
                        f"documented knob {token} is referenced "
                        "nowhere (package, tools, tests, bench) — "
                        "dead registry row", self.severity)


class FaultKindClosure(_ProtocolRule):
    """SL027 — the fault-injection grammar and its consumers agree:
    every declared kind has a consume site (else injecting it is a
    silent no-op) and a chaos/test reference; every consumed kind
    literal is in the grammar (else the consume branch can never
    fire — a phantom)."""

    rule_id = "SL027"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        g = _graph(ctx)
        if g is None or not g.kinds:
            return
        for relpath, line, kind in g.kind_consumes:
            if relpath == ctx.relpath and kind not in g.kinds:
                yield Finding(
                    relpath, line, self.rule_id,
                    f"fault kind {kind!r} is consumed here but no "
                    "faults.py grammar tuple declares it — this "
                    "branch can never fire (phantom kind)",
                    self.severity)
        if ctx.relpath != g.grammar_relpath:
            return
        consumed = {kind for _r, _l, kind in g.kind_consumes}
        for kind in sorted(g.kinds):
            table, line = g.kinds[kind]
            if kind not in consumed:
                yield Finding(
                    g.grammar_relpath, line, self.rule_id,
                    f"fault kind {kind!r} is declared in {table} but "
                    "consumed nowhere — injecting it is a silent "
                    "no-op", self.severity)
            elif g.ref_text_present and kind not in g.ref_text:
                yield Finding(
                    g.grammar_relpath, line, self.rule_id,
                    f"fault kind {kind!r} has no chaos/test reference "
                    "(tools/, tests/, bench.py) — untested fault "
                    "path", self.severity)


class ClientRetrySoundness(_ProtocolRule):
    """SL028 — client dispatch and server Retry-After discipline tell
    one story: the client's extracted retry/fatal/resume sets match
    the declared CLIENT_* constants, every status the server marks
    retryable is client-retryable (and never client-fatal), and
    fatal-error overrides stay inside the declared FATAL_ERRORS."""

    rule_id = "SL028"
    severity = SEV_ERROR

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        g = _graph(ctx)
        if g is None:
            return
        checks = (
            (g.retry_sites, g.client_retry_statuses_decl,
             "CLIENT_RETRY_STATUSES"),
            (g.fatal_sites, g.client_fatal_statuses_decl,
             "CLIENT_FATAL_STATUSES"),
            (g.resume_sites, g.client_resume_statuses_decl,
             "CLIENT_RESUME_STATUSES"),
        )
        for sites, decl, name in checks:
            if not decl:
                continue
            for site in sites:
                if site.relpath == ctx.relpath and \
                        set(site.statuses) != set(decl):
                    yield Finding(
                        site.relpath, site.line, self.rule_id,
                        f"client {site.klass} statuses "
                        f"{sorted(set(site.statuses))} diverge from "
                        f"protocol.{name} {sorted(set(decl))}",
                        self.severity)
        if g.client_retry_floor_decl is not None:
            for relpath, line, floor in g.floor_sites:
                if relpath == ctx.relpath and \
                        floor != g.client_retry_floor_decl:
                    yield Finding(
                        relpath, line, self.rule_id,
                        f"client retry floor {floor} diverges from "
                        "protocol.CLIENT_RETRY_FLOOR "
                        f"{g.client_retry_floor_decl}", self.severity)
        for ov in g.error_overrides:
            if ov.relpath != ctx.relpath or ov.klass != "fatal":
                continue
            if ov.error is None:
                yield Finding(
                    ov.relpath, ov.line, self.rule_id,
                    "client fatal-error override does not resolve to "
                    "a shared-vocabulary string", self.severity)
                continue
            if g.fatal_errors_decl and \
                    ov.error not in g.fatal_errors_decl:
                yield Finding(
                    ov.relpath, ov.line, self.rule_id,
                    f"client treats error {ov.error!r} as fatal but "
                    "protocol.FATAL_ERRORS does not declare it",
                    self.severity)
            if ov.status is not None and \
                    g.status_errors.get(ov.status) and \
                    ov.error not in g.status_errors[ov.status]:
                yield Finding(
                    ov.relpath, ov.line, self.rule_id,
                    f"client dispatches on error {ov.error!r} for "
                    f"HTTP {ov.status}, but STATUS_ERRORS[{ov.status}] "
                    "never carries it", self.severity)
        if ctx.relpath != g.vocab_relpath:
            return
        has_client = bool(g.retry_sites or g.floor_sites)
        if has_client:
            fatal_union = {s for site in g.fatal_sites
                           for s in site.statuses}
            line = g.decl_lines.get("RETRY_AFTER_STATUSES", 0)
            for status in g.retry_after_statuses:
                if not g.client_retryable(status):
                    yield Finding(
                        g.vocab_relpath, line, self.rule_id,
                        f"server marks HTTP {status} retryable "
                        "(Retry-After) but the client never retries "
                        "it — the backpressure hint is wasted",
                        self.severity)
                if status in fatal_union:
                    yield Finding(
                        g.vocab_relpath, line, self.rule_id,
                        f"client treats HTTP {status} as fatal but "
                        "the server marks it retryable (Retry-After) "
                        "— contradictory contract", self.severity)
        if g.error_overrides:
            dispatched = {ov.error for ov in g.error_overrides
                          if ov.klass == "fatal" and ov.error}
            for err in g.fatal_errors_decl:
                if err not in dispatched:
                    yield Finding(
                        g.vocab_relpath,
                        g.decl_lines.get("FATAL_ERRORS", 0),
                        self.rule_id,
                        f"FATAL_ERRORS declares {err!r} but no client "
                        "fatal dispatch checks it — dead override",
                        self.severity)


PROTOCOL_RULES = (
    RouteStatusClosure,
    RefusalDiscipline,
    EnvKnobRegistry,
    FaultKindClosure,
    ClientRetrySoundness,
)
