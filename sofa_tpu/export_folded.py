"""Folded-stack export (`sofa export --folded`) for flame tooling.

Writes Brendan-Gregg-format collapsed stacks — ``frame;frame;leaf count``
per line — the lingua franca of speedscope.app, flamegraph.pl, and
inferno, so sampled stacks from a sofa capture drop straight into the
ecosystem's flame-graph viewers:

  pystacks.folded — the in-process Python sampler's FULL stacks
                    (collectors/pystacks.py stores them in `module`)
  cputrace.folded — perf samples; the parser keeps the leaf plus up to 3
                    callers ("leaf<-c1<-c2"), exported caller-first as a
                    partial stack
  memprof.folded  — HBM bytes held per allocation stack from the peak
                    memory snapshot (ingest/memprof.py) — a MEMORY flame
                    graph: width is bytes, not time

The reference has no flame-graph path at all; its closest artifact is the
hsg swarm clustering over the same samples.
"""

from __future__ import annotations

import os
from collections import Counter
from typing import Dict, List, Optional

import pandas as pd

from sofa_tpu.printing import print_progress, print_warning

FOLDED_FRAMES = ["pystacks", "cputrace"]


def _fold_pystacks(df: pd.DataFrame) -> Counter:
    # module carries the full semicolon stack, root-first
    return Counter(s for s in df["module"] if s)


def _fold_cputrace(df: pd.DataFrame) -> Counter:
    counts: Counter = Counter()
    for name in df["name"]:
        if not name:
            continue
        # perf_script names are "leaf<-caller1<-caller2 @ dso" where the
        # dso annotates the LEAF; split it off first or it sticks to the
        # outermost caller and fragments identical stacks.
        name, _, dso = str(name).partition(" @ ")
        frames = name.split("<-")
        if dso:
            frames[0] = f"{frames[0]} [{dso}]"
        counts[";".join(reversed(frames))] += 1
    return counts


def _fold_memprof(cfg) -> Counter:
    """HBM bytes per allocation stack — pprof stacks are leaf-first, folded
    format wants root-first.  Count = bytes held, so flame width reads as
    memory, the same convention pprof's own flame view uses.  A cluster
    export folds every host's snapshot with the hostname as the root frame
    (per-host logdirs each hold their own memprof.pb.gz)."""
    from sofa_tpu.ingest.memprof import load_memprof

    sources = [(cfg.logdir, "")]
    if getattr(cfg, "cluster_hosts", None):
        from sofa_tpu.analyze import cluster_host_cfgs

        sources = [(host_cfg.logdir, hostname + ";")
                   for _i, hostname, host_cfg in cluster_host_cfgs(cfg)]
    counts: Counter = Counter()
    for logdir, prefix in sources:
        try:
            df, _meta = load_memprof(logdir)
        except Exception as e:  # noqa: BLE001 — corrupt snapshot degrades
            print_warning(f"folded export: unreadable memprof snapshot in "
                          f"{logdir}: {e}")
            continue
        if df is None:
            continue
        held = df[(df["kind"] == "buffer") & (df["bytes"] > 0)]
        for stack, nbytes in zip(held["stack"], held["bytes"]):
            frames = [f for f in str(stack).split(";") if f]
            if frames:
                counts[prefix + ";".join(reversed(frames))] += int(nbytes)
    return counts


def _write(counts: Counter, path: str) -> bool:
    if not counts:
        return False
    from sofa_tpu.durability import atomic_write

    with atomic_write(path) as f:
        for stack, n in counts.most_common():
            f.write(f"{stack} {n}\n")
    return True


def export_folded(cfg, frames: Optional[Dict[str, pd.DataFrame]] = None
                  ) -> List[str]:
    """Write *.folded files into the logdir; returns the paths written."""
    if frames is None:
        from sofa_tpu.analyze import load_frames

        frames = load_frames(cfg, only=FOLDED_FRAMES)
    os.makedirs(cfg.logdir, exist_ok=True)  # cluster export may precede it
    written: List[str] = []
    jobs = (
        ("pystacks", _fold_pystacks),
        ("cputrace", _fold_cputrace),
    )
    for name, fold in jobs:
        df = frames.get(name)
        if df is None or df.empty:
            continue
        path = cfg.path(f"{name}.folded")
        if _write(fold(df), path):
            written.append(path)
    # Memory flame graph rides the snapshot file, not a trace frame.
    mem_path = cfg.path("memprof.folded")
    if _write(_fold_memprof(cfg), mem_path):
        written.append(mem_path)
    if written:
        print_progress(
            "folded stacks -> " + ", ".join(written)
            + "  (open in speedscope.app / flamegraph.pl)")
    else:
        print_warning("folded export: no sampled stacks in this capture "
                      "(--enable_py_stacks / perf)")
    return written
