"""Configuration for the sofa_tpu pipeline.

The reference threads a flat ``SOFA_Config`` object through every stage
(/root/reference/bin/sofa_config.py:10-74, built field-by-field from argparse
at bin/sofa:159-326). We keep that single-object design — one config travels
record -> preprocess -> analyze -> viz — but as a typed dataclass with
TOML-file support and path helpers, and with the GPU-era knobs retargeted to
TPU (xprof/libtpu) equivalents.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import List, Optional

try:  # py3.11+
    import tomllib
except ImportError:  # pragma: no cover — py3.10: same parser from PyPI
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ImportError:
        tomllib = None


@dataclass
class Filter:
    """A ``keyword:color`` timeline highlight filter.

    The reference expresses these as a colon-joined mini-DSL on the CLI
    (bin/sofa:258-291); matching trace rows get pulled out into their own
    colored series on the timeline.
    """

    keyword: str
    color: str

    @classmethod
    def parse(cls, spec: str) -> "Filter":
        if ":" in spec:
            kw, _, color = spec.partition(":")
        else:
            kw, color = spec, "orange"
        return cls(keyword=kw, color=color)


# Default highlight filters.  The reference defaults (bin/sofa:264,273-286)
# highlight idle CPU and H2D/D2H/P2P/fw/bw/AllReduce GPU kernels; the TPU
# equivalents highlight infeed/outfeed transfers, fusions and ICI collectives.
DEFAULT_CPU_FILTERS = [Filter("idle", "black")]
DEFAULT_TPU_FILTERS = [
    Filter("infeed", "red"),
    Filter("outfeed", "greenyellow"),
    Filter("copy", "royalblue"),
    Filter("fusion", "darkviolet"),
    Filter("all-reduce", "indigo"),
    Filter("all-gather", "tomato"),
    Filter("reduce-scatter", "orange"),
    Filter("all-to-all", "forestgreen"),
    Filter("collective-permute", "deeppink"),
]


@dataclass
class SofaConfig:
    # --- core pipeline -----------------------------------------------------
    logdir: str = "sofalog/"
    command: str = ""
    verbose: bool = False
    skip_preprocess: bool = False
    # Worker count for every pipeline pool (ingest fan-out, frame writes,
    # analyze reads, per-host cluster analysis, xplane per-file processes).
    # 0 = auto: os.cpu_count() capped, SOFA_JOBS env override — resolution
    # lives in sofa_tpu/pool.py so the policy exists in exactly one place.
    jobs: int = 0
    # Content-keyed ingest cache (ingest/cache.py): re-runs over unchanged
    # raw files load cached parquet instead of reparsing.  --no_ingest_cache
    # bypasses; `sofa clean` removes the cache directory.
    ingest_cache: bool = True

    # --- record: host collectors ------------------------------------------
    perf_events: str = ""            # extra `perf record -e` events
    no_perf_events: bool = False     # skip perf entirely (fallback to time -v)
    cpu_sample_rate: int = 99        # perf -F (reference: 99 Hz fixed)
    # Call-graph capture: "off" (default — DWARF unwinding at 99 Hz costs
    # ~16 KB stack copy per sample, which fights the <5 % overhead budget),
    # "fp" (frame pointers, cheap but needs -fno-omit-frame-pointer), or
    # "dwarf" (accurate, expensive).
    perf_call_graph: str = "off"
    sys_mon_rate: int = 10           # /proc sampler Hz (reference default 10)
    enable_strace: bool = False
    strace_min_time: float = 1e-6    # drop syscalls shorter than this (s)
    enable_py_stacks: bool = False   # in-process Python stack sampler
    py_stack_rate: int = 67          # Hz for the Python stack sampler
    enable_tcpdump: bool = False
    netstat_interface: Optional[str] = None
    blkdev: Optional[str] = None     # block device for blktrace (opt-in)
    enable_vmstat: bool = True
    pid: Optional[int] = None        # attach mode (reference latent feature)

    # --- record: TPU collectors -------------------------------------------
    enable_xprof: bool = True        # jax.profiler XPlane capture (injected)
    xprof_host_tracer_level: int = 2
    xprof_python_tracer: bool = False
    xprof_delay_s: float = 0.0       # delay trace start after launch
    xprof_duration_s: float = 0.0    # 0 = whole run
    enable_tpu_mon: bool = True      # live HBM/liveness sampler (in-process)
    tpu_mon_rate: int = 1            # TPU runtime metrics sampler Hz
    enable_mem_prof: bool = True     # HBM attribution snapshot (pprof) at
                                     # the observed occupancy peak
    epilogue_deadline_s: Optional[float] = None
                                     # override the wedge-detection allowance
                                     # after the child's atexit trace-stop
                                     # breadcrumb appears (None = derive from
                                     # the breadcrumb's own timeouts)

    # --- record: fault tolerance / chaos -----------------------------------
    inject_faults: str = ""          # fault-injection spec (sofa_tpu/faults.py
                                     # grammar; SOFA_FAULTS env equivalent) —
                                     # empty = all hooks are no-ops
    collector_restarts: int = 1      # supervisor restart budget per collector
                                     # that dies mid-run (0 = never restart)
    collector_stop_timeout_s: float = 15.0
                                     # per-collector stop deadline; a wedged
                                     # flush is TERM/KILLed + abandoned past
                                     # it (0 = unbounded)
    collector_harvest_timeout_s: float = 120.0
                                     # per-collector harvest deadline
                                     # (0 = unbounded)
    disk_budget_mb: float = 0.0      # --disk_budget: total raw-output cap
                                     # in MB across all watched collectors;
                                     # the supervisor rotates oldest files /
                                     # truncates the worst offender instead
                                     # of letting record ENOSPC (0 = off)
    collector_disk_budget_mb: float = 0.0
                                     # --collector_disk_budget: per-collector
                                     # raw-output cap in MB (0 = off)

    # --- preprocess --------------------------------------------------------
    cpu_time_offset_ms: int = 0      # manual host-clock fudge (bin/sofa:111)
    tpu_time_offset_ms: float = 0.0  # manual device/XPlane-clock fudge: the
                                     # escape hatch when marker/timebase
                                     # alignment is wrong and re-recording is
                                     # not an option (VERDICT r2 missing #3)
    viz_downsample_to: int = 10000   # max points per _viz series
    trace_format: str = ""           # csv | parquet | columnar; "" = auto:
                                     # SOFA_TRACE_FORMAT env, else columnar
                                     # (the chunked _frames/ store,
                                     # docs/FRAMES.md) — resolution lives in
                                     # trace.resolve_trace_format so the
                                     # policy exists in exactly one place
    network_filters: List[str] = field(default_factory=list)
    # Level-of-detail timeline tiles (sofa_tpu/tiles.py): per-series
    # min/max+density pyramid under <logdir>/_tiles/ so deep zoom regains
    # full event fidelity.  --no_tiles skips the build (overview only);
    # tile_levels caps pyramid depth (0 = auto until every leaf tile is
    # exact, bounded by tiles.MAX_LEVELS).
    enable_tiles: bool = True
    tile_levels: int = 0

    # --- analyze -----------------------------------------------------------
    num_iterations: int = 20         # AISI expected iteration count
    num_swarms: int = 10             # HSG cluster count
    enable_aisi: bool = False
    enable_hsg: bool = False
    enable_swarms: bool = False
    is_idle_threshold: float = 0.01  # concurrency_breakdown dominator floor
    profile_region: str = ""         # "begin:end" manual ROI (seconds)
    spotlight: bool = False          # auto-ROI from TPU utilization
    hint_server: Optional[str] = None  # gRPC advice service host:port
    # AISI boundary source: auto = device-plane "Steps" spans when traced,
    # else explicit sofa_step markers, else module-launch mining; steps |
    # marker require that source; module | op force mining on that symbol
    # sequence.
    iterations_from: str = "auto"

    # --- diff --------------------------------------------------------------
    base_logdir: Optional[str] = None
    match_logdir: Optional[str] = None

    # --- archive / regress (sofa_tpu/archive/) ------------------------------
    archive_root: str = ""           # --archive_root; empty = SOFA_ARCHIVE_ROOT
                                     # env, else ./sofa_archive
    archive_label: str = ""          # --label tag on `sofa archive <logdir>`;
                                     # also the `archive ls --label` filter
    archive_keep: int = 0            # `sofa archive gc --keep N`
    archive_keep_days: float = 0.0   # `sofa archive gc --keep_days D`
    archive_limit: int = 0           # `archive ls --limit N` newest runs
                                     # (0 = all)
    archive_since: str = ""          # `archive ls --since <unix|7d|12h|30m>`
    archive_host: str = ""           # `archive ls --host <hostname>` filter
    regress_rolling: int = 0         # `sofa regress --rolling N` catalog
                                     # baseline (0 = pairwise only)
    regress_pct: float = 50.0        # rolling-baseline percentile
    regress_threshold: float = 10.0  # relative % move a verdict requires

    # --- fleet transport (sofa serve / sofa agent) --------------------------
    # The resilient ingest layer between recording hosts and a served
    # archive (sofa_tpu/archive/service.py + sofa_tpu/agent.py; see
    # docs/FLEET.md).
    serve_bind: str = "127.0.0.1"    # like viz: loopback unless opted open
    serve_port: int = 8044           # 0 = OS-assigned (tests / bench)
    serve_token: str = ""            # --token; SOFA_SERVE_TOKEN env fallback
    serve_quota_mb: float = 0.0      # per-tenant object-store quota (0 = off)
    serve_max_inflight: int = 8      # concurrent write requests before a
                                     # 503 + Retry-After backpressure answer
    serve_workers: int = 1           # --workers: pool processes sharing the
                                     # port (SO_REUSEPORT; dispatcher
                                     # fallback), tenants hash-sharded
    serve_replica_of: str = ""       # --replica-of: run as a read-only
                                     # query replica of this primary URL
    serve_slo: str = ""              # --slo: declared SLO targets, e.g.
                                     # 'push_p99_ms<50,wal_depth<1000' —
                                     # evaluated per scrape window
                                     # (metrics.parse_slo grammar)
    serve_rolling_restart: bool = False  # --rolling-restart: signal the
                                     # running supervisor (SIGHUP via its
                                     # pidfile) to restart workers one at
                                     # a time, then exit
    status_fleet: str = ""           # status --fleet: render /v1/tier
                                     # topology from this service URL
    fleet_tenant: str = "default"    # tenant namespace for agent pushes
    agent_service: str = ""          # service URL (SOFA_AGENT_SERVICE env);
                                     # empty = spool-only (air-gapped) mode
    agent_spool: str = ""            # durable spool root (SOFA_AGENT_SPOOL
                                     # env, else ./sofa_spool)
    agent_poll_s: float = 5.0        # daemon scan period
    agent_settle_s: float = 0.5      # a logdir must be quiet this long
                                     # before it counts as finished
    agent_timeout_s: float = 10.0    # per-request transport deadline
    agent_retries: int = 4           # per-operation retry budget
    agent_backoff_s: float = 0.5     # retry backoff base (jittered)
    agent_backoff_cap_s: float = 30.0  # retry backoff cap

    # --- live streaming (sofa_tpu/live.py) ----------------------------------
    live_interval_s: float = 2.0     # epoch poll period between live ticks
    live_epochs: int = 0             # --live_epochs: run exactly N epochs
                                     # then exit (0 = until interrupted);
                                     # tests/bench drive finite loops
    live_stall_s: float = 30.0       # a source that stops growing for this
                                     # long while siblings keep streaming
                                     # degrades to `stalled` in meta.live
                                     # (0 = never flag)

    # --- whatif (sofa_tpu/whatif/) ------------------------------------------
    whatif_apply: str = ""           # --apply: comma-joined scenario specs
                                     # (overlap:<pat> | scale:<pat>=<f|sol>
                                     # | link:<f> | batch:<f>); empty =
                                     # identity replay only (the
                                     # calibration gate)

    # --- viz ---------------------------------------------------------------
    viz_port: int = 8000
    # Bind address.  Unlike the reference (http.server on all interfaces,
    # sofa_viz.py:18) the default is loopback: a logdir holds command
    # lines, hostnames, and packet metadata.  --viz_bind 0.0.0.0 opens it.
    viz_bind: str = "127.0.0.1"

    # --- cluster (multi-host) ---------------------------------------------
    cluster_hosts: List[str] = field(default_factory=list)

    # --- filters -----------------------------------------------------------
    cpu_filters: List[Filter] = field(default_factory=lambda: list(DEFAULT_CPU_FILTERS))
    tpu_filters: List[Filter] = field(default_factory=lambda: list(DEFAULT_TPU_FILTERS))

    # --- plugins -----------------------------------------------------------
    plugins: List[str] = field(default_factory=list)

    # --- runtime state (filled during a run, not user-facing) --------------
    time_base: float = 0.0           # unix zero point of this run
    roi_begin: float = 0.0
    roi_end: float = 0.0

    # ----------------------------------------------------------------------
    def __post_init__(self) -> None:
        if not self.logdir.endswith("/"):
            self.logdir += "/"

    # Path helpers: files-on-disk are the inter-stage contract (SURVEY §1).
    def path(self, *parts: str) -> str:
        return os.path.join(self.logdir, *parts)

    @property
    def xprof_dir(self) -> str:
        return self.path("xprof")

    @property
    def inject_dir(self) -> str:
        return self.path("_inject")

    @classmethod
    def from_toml(cls, path: str) -> "SofaConfig":
        """Load a config file; unknown keys are rejected loudly."""
        if tomllib is None:  # pragma: no cover
            raise RuntimeError("no TOML parser: need python >= 3.11 "
                               "(stdlib tomllib) or the tomli package")
        with open(path, "rb") as f:
            data = tomllib.load(f)
        return cls.from_dict(data)

    @classmethod
    def from_dict(cls, data: dict) -> "SofaConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        kwargs = dict(data)
        for key in ("cpu_filters", "tpu_filters"):
            if key in kwargs:
                kwargs[key] = [
                    Filter.parse(v) if isinstance(v, str) else Filter(**v)
                    for v in kwargs[key]
                ]
        # Type-check against the field defaults so a mistyped TOML value
        # ("logdir = 123") is a curated config error at load time, not an
        # AttributeError deep in whatever touches the field first.  int is
        # acceptable where the default is float; None-defaulted (Optional)
        # and container fields take whatever TOML produced.
        defaults = cls()
        for key, value in kwargs.items():
            if key in ("cpu_filters", "tpu_filters"):
                continue
            default = getattr(defaults, key)
            if default is None or isinstance(default, (list, dict)):
                continue
            want = type(default)
            if want is float and isinstance(value, int) \
                    and not isinstance(value, bool):
                continue
            if not isinstance(value, want) or (
                    want is not bool and isinstance(value, bool)):
                raise ValueError(
                    f"config key {key!r}: expected {want.__name__}, "
                    f"got {type(value).__name__} ({value!r})")
        return cls(**kwargs)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)
