"""XPlane ingest tests on a synthetic XSpace (no TPU needed)."""

import pytest

from sofa_tpu.ingest import xplane_pb2
from sofa_tpu.ingest.xplane import (
    find_marker_offset_ns,
    tpu_utilization,
    xspace_to_frames,
)
from sofa_tpu.trace import CopyKind


from conftest import MARKER_UNIX_NS, add_event as _add_event, \
    add_stat as _add_stat

SESSION_MARKER_NS = 1_000_000  # marker occurs 1 ms into the session


def build_xspace():
    xs = xplane_pb2.XSpace()
    xs.hostnames.append("testhost")

    host = xs.planes.add()
    host.name = "/host:CPU"
    hline = host.lines.add()
    hline.id = 7
    hline.name = "python"
    hline.timestamp_ns = 0
    _add_event(host, hline, f"sofa_timebase_marker:{MARKER_UNIX_NS}",
               SESSION_MARKER_NS, 1000)
    _add_event(host, hline, "train_step", 2_000_000, 500_000)

    dev = xs.planes.add()
    dev.name = "/device:TPU:0"
    _add_stat(dev, dev, "peak_teraflops_per_second", 100.0)
    sline = dev.lines.add()
    sline.name = "Steps"
    _add_event(dev, sline, "0", 2_000_000, 1_000_000)
    _add_event(dev, sline, "1", 3_000_000, 1_000_000)
    mline = dev.lines.add()
    mline.name = "XLA Modules"
    _add_event(dev, mline, "jit_train_step(12345)", 2_000_000, 1_000_000,
               stats=[("run_id", 1), ("program_id", 9)])
    oline = dev.lines.add()
    oline.name = "XLA Ops"
    _add_event(dev, oline, "%fusion.1 = ...", 2_100_000, 400_000, "fusion.1",
               stats=[("hlo_category", "convolution"), ("flops", 8_000_000),
                      ("bytes_accessed", 1_000_000),
                      ("tf_op", "jit(train_step)/jvp(main)/conv_general")])
    _add_event(dev, oline, "%all-reduce.2 = ...", 2_600_000, 200_000,
               "all-reduce.2",
               stats=[("hlo_category", "all-reduce"),
                      ("bytes_accessed", 4_000_000),
                      ("long_name",
                       "%all-reduce.2 = f32[] all-reduce(...), "
                       "replica_groups={{0,1},{2,3}}, to_apply=%add")])
    _add_event(dev, oline, "%fusion.3 = ...", 2_850_000, 100_000, "fusion.3",
               stats=[("hlo_category", "fusion"), ("flops", 2_000_000),
                      ("bytes_accessed", 500_000),
                      ("tf_op",
                       "jit(train_step)/transpose(jvp(main))/dot_general")])
    return xs


TIME_BASE = MARKER_UNIX_NS / 1e9 - 10.0  # marker fired 10 s after record start


def test_device_step_spans_ingest():
    xs = build_xspace()
    frames = xspace_to_frames(xs, TIME_BASE)
    steps = frames["tpusteps"]
    assert len(steps) == 2
    assert list(steps["event"]) == [0.0, 1.0]
    assert steps.iloc[0]["timestamp"] == pytest.approx(10.001, abs=1e-6)
    assert steps.iloc[0]["duration"] == pytest.approx(1e-3)


def test_aisi_prefers_device_steps():
    from sofa_tpu.ml.aisi import _iterations_from_steps

    xs = build_xspace()
    frames = xspace_to_frames(xs, TIME_BASE)
    out = _iterations_from_steps(frames)
    assert out is not None
    begins, ends = out
    assert len(begins) == 2
    assert begins[0] == pytest.approx(10.001, abs=1e-6)
    assert ends[0] == pytest.approx(10.002, abs=1e-6)


def test_marker_offset():
    xs = build_xspace()
    off = find_marker_offset_ns(xs)
    assert off == MARKER_UNIX_NS - SESSION_MARKER_NS


def test_custom_call_display_enrichment():
    """Opaque custom calls get readable, groupable names: Mosaic (Pallas)
    kernels attribute to their launching Python line via the `source`
    stat; runtime allocs group under their target."""
    xs = build_xspace()
    dev = xs.planes[1]
    oline = dev.lines[2]
    _add_event(dev, oline,
               '%closed_call.6 = bf16[8]{0} custom-call(), '
               'custom_call_target="tpu_custom_call"',
               2_950_000, 10_000, "closed_call.6",
               mstats=[("hlo_category", "custom-call"),
                       ("source", "/repo/sofa_tpu/workloads/x.py:42")])
    _add_event(dev, oline,
               '%custom-call.9 = f32[4]{0} custom-call(), '
               'custom_call_target="AllocateBuffer"',
               2_960_000, 1_000, "custom-call.9",
               mstats=[("hlo_category", "custom-call")])
    frames = xspace_to_frames(xs, TIME_BASE)
    names = set(frames["tputrace"]["name"])
    assert "pallas@x.py:42" in names
    assert "AllocateBuffer" in names
    assert "closed_call.6" not in names and "custom-call.9" not in names


def test_marker_offsets_start_and_stop():
    """api.profile emits start AND stop markers; all are returned sorted by
    session time and alignment anchors on the earliest."""
    from sofa_tpu.ingest.xplane import find_marker_offsets_ns

    xs = build_xspace()
    host = xs.planes[0]
    # stop marker 3 s later in session time, 2 us of offset disagreement
    stop_unix = MARKER_UNIX_NS + 3_000_000_000 + 2_000
    _add_event(host, host.lines[0], f"sofa_timebase_marker:{stop_unix}",
               SESSION_MARKER_NS + 3_000_000_000, 1000)
    offs = find_marker_offsets_ns(xs)
    assert [s for s, _ in offs] == [SESSION_MARKER_NS,
                                    SESSION_MARKER_NS + 3_000_000_000]
    assert offs[0][1] == MARKER_UNIX_NS - SESSION_MARKER_NS
    assert offs[1][1] - offs[0][1] == 2_000      # within-capture drift
    assert find_marker_offset_ns(xs) == offs[0][1]


def test_xspace_to_frames_alignment_and_stats():
    xs = build_xspace()
    frames = xspace_to_frames(xs, TIME_BASE)
    ops = frames["tputrace"]
    assert len(ops) == 3
    fusion = ops[ops["name"] == "fusion.1"].iloc[0]
    # marker at session 1 ms == unix marker time == time_base + 10 s;
    # fusion starts at session 2.1 ms -> 10.0011 s after time_base.
    assert fusion["timestamp"] == pytest.approx(10.0011, abs=1e-6)
    assert fusion["duration"] == pytest.approx(400e-6)
    assert fusion["copyKind"] == int(CopyKind.KERNEL)
    assert fusion["hlo_category"] == "convolution"
    assert fusion["flops"] == 8e6
    assert fusion["module"] == "jit_train_step"

    ar = ops[ops["name"] == "all-reduce.2"].iloc[0]
    assert ar["copyKind"] == int(CopyKind.ALL_REDUCE)
    assert ar["payload"] == 4_000_000
    assert ar["bandwidth"] == pytest.approx(4_000_000 / 200e-6)
    # replica groups parsed from the HLO long name into the groups column
    import json

    assert json.loads(ar["groups"]) == [[0, 1], [2, 3]]

    # fw/bw phase from the JAX provenance path (transpose(jvp) => backward)
    assert fusion["phase"] == "fw"
    assert ops[ops["name"] == "fusion.3"].iloc[0]["phase"] == "bw"

    mods = frames["tpumodules"]
    assert mods.iloc[0]["name"] == "jit_train_step"
    assert mods.iloc[0]["pid"] == 9

    host = frames["hosttrace"]
    assert list(host["name"]) == ["train_step"]  # marker excluded
    assert frames["_meta"]["0"]["peak_teraflops_per_second"] == 100.0


def test_missing_marker_falls_back_to_time_base():
    xs = build_xspace()
    # strip the marker event metadata name
    for plane in xs.planes:
        for k, v in plane.event_metadata.items():
            if "sofa_timebase_marker" in v.name:
                v.name = "not_a_marker"
    frames = xspace_to_frames(xs, 5.0)
    ops = frames["tputrace"]
    # session 2.1 ms aligned to time_base -> timestamp == 0.0021
    assert ops.iloc[0]["timestamp"] == pytest.approx(0.0021, abs=1e-6)


def test_tpu_utilization_windows():
    xs = build_xspace()
    frames = xspace_to_frames(xs, TIME_BASE)
    util = tpu_utilization(frames["tputrace"], window_s=0.001,
                           device_meta=frames["_meta"])
    tc = util[util["name"] == "tc_util"]
    assert not tc.empty
    # ops cover 700 us of a 1 ms window -> 70 %
    assert tc["event"].max() == pytest.approx(70.0, rel=0.05)
    mxu = util[util["name"] == "mxu_util"]
    # 10 MFLOP in 1 ms = 10 GFLOP/s of a 100 TFLOP/s peak = 0.01 %
    assert mxu["event"].max() == pytest.approx(0.01, rel=0.05)
    hbm = util[util["name"] == "hbm_gbps"]
    assert hbm["event"].max() == pytest.approx(5.5e6 / 1e-3 / 1e9, rel=0.05)


def test_windowed_integral_matches_bruteforce():
    """The O(N+W) difference-array windowing must agree exactly with the
    per-window interval clipping it replaced (VERDICT r2 weak #7)."""
    import numpy as np

    from sofa_tpu.ingest.xplane import _windowed_integral

    rng = np.random.default_rng(7)
    for window_s in (0.37, 0.05):
        n = 300
        starts = rng.uniform(0.0, 10.0, n)
        ends = starts + rng.uniform(1e-5, 3.0, n)
        rates = rng.uniform(0.0, 5.0, n)
        t0 = float(starts.min())
        edges = np.arange(t0, float(ends.max()) + window_s, window_s)
        n_win = len(edges) - 1
        got = _windowed_integral(starts, ends, rates, t0, n_win, window_s)
        exp = np.array([
            (rates * np.maximum(
                np.minimum(ends, w1) - np.maximum(starts, w0), 0.0)).sum()
            for w0, w1 in zip(edges[:-1], edges[1:])])
        np.testing.assert_allclose(got, exp, rtol=1e-9, atol=1e-9)
