"""Parallel preprocess contract: ``--jobs 1`` and ``--jobs N`` produce
frame-identical output, and a raising parser still degrades to an empty
frame without killing the run (the per-source try/except semantics the
fan-out must preserve)."""

import os
import shutil

import pandas as pd
import pytest

from sofa_tpu.config import SofaConfig
from sofa_tpu.ingest import procfs
from sofa_tpu.preprocess import sofa_preprocess

_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                        "cpu_host.xplane.pb")


def _build_logdir(root, name):
    """A logdir exercising procfs + text + xplane parsers at once."""
    d = str(root / name) + "/"
    prof = os.path.join(d, "xprof", "plugins", "profile", "run1")
    os.makedirs(prof)
    shutil.copy(_FIXTURE, os.path.join(prof, "host.xplane.pb"))
    files = {
        "sofa_time.txt": "1700000000.0\n",
        "misc.txt": "elapsed_time 1.0\ncores 8\n",
        "mpstat.txt": (
            "1700000000.0 cpuall 100 0 50 800 10 5 5 0\n"
            "1700000000.5 cpuall 150 0 70 830 12 6 6 0\n"
            "1700000001.0 cpuall 210 0 85 865 15 7 7 0\n"
            "1700000000.0 cpu0 100 0 50 800 10 5 5 0\n"
            "1700000000.5 cpu0 150 0 70 830 12 6 6 0\n"
        ),
        "netstat.txt": (
            "1700000000.0 eth0 1000 2000 10 20\n"
            "1700000000.5 eth0 5000 9000 40 70\n"
            "1700000001.0 eth0 9000 16000 70 120\n"
        ),
        "vmstat.txt": (
            "r b swpd free buff cache si so bi bo in cs us sy id wa st\n"
            "1 0 0 100 10 10 0 0 5 6 100 200 10 5 84 1 0\n"
            "2 0 0 100 10 10 0 0 7 8 120 220 12 6 81 1 0\n"
        ),
        "pystacks.txt": (
            "1700000000.2 1 main;loop;work\n"
            "1700000000.4 1 main;loop;sleep\n"
        ),
        "tpumon.txt": (
            "1700000000200000000 -1 0 0 0\n"
            "1700000000200000000 0 2500000000 8000000000 2600000000\n"
            "1700000001200000000 0 2600000000 8000000000 2700000000\n"
        ),
    }
    for fname, text in files.items():
        with open(d + fname, "w") as f:
            f.write(text)
    return d


def _run(root, name, **cfg_kw):
    d = _build_logdir(root, name)
    cfg = SofaConfig(logdir=d, ingest_cache=False, **cfg_kw)
    return sofa_preprocess(cfg), cfg


def test_parallel_matches_serial(tmp_path):
    f1, cfg1 = _run(tmp_path, "serial", jobs=1)
    f4, cfg4 = _run(tmp_path, "parallel", jobs=4)
    assert set(f1) == set(f4)
    assert list(f1) == list(f4), "frame ordering must be deterministic"
    nonempty = 0
    for key in f1:
        pd.testing.assert_frame_equal(
            f1[key].reset_index(drop=True), f4[key].reset_index(drop=True),
            obj=key)
        nonempty += int(not f1[key].empty)
    # the comparison must actually cover real data, not 16 empty frames
    assert nonempty >= 5
    # and the CSV artifacts byte-match (the files-on-disk contract)
    for key in ("mpstat", "netbandwidth", "tputrace", "hosttrace"):
        with open(cfg1.path(f"{key}.csv"), "rb") as a, \
                open(cfg4.path(f"{key}.csv"), "rb") as b:
            assert a.read() == b.read(), key


def test_parallel_degrades_per_source(tmp_path, monkeypatch):
    """One raising parser -> ITS frame is empty, everything else survives,
    no exception escapes (jobs>1 path)."""

    def boom(text, time_base=0.0, **kw):
        raise RuntimeError("synthetic parser failure")

    monkeypatch.setattr(procfs, "parse_netstat", boom)
    f, _cfg = _run(tmp_path, "degraded", jobs=4)
    assert f["netbandwidth"].empty
    assert not f["mpstat"].empty
    assert not f["hosttrace"].empty  # the xplane leg still landed


def test_degradation_identical_serial_vs_parallel(tmp_path, monkeypatch):
    def boom(text, time_base=0.0, **kw):
        raise RuntimeError("synthetic parser failure")

    monkeypatch.setattr(procfs, "parse_mpstat", boom)
    f1, _ = _run(tmp_path, "deg1", jobs=1)
    f4, _ = _run(tmp_path, "deg4", jobs=4)
    for key in f1:
        pd.testing.assert_frame_equal(
            f1[key].reset_index(drop=True), f4[key].reset_index(drop=True),
            obj=key)
    assert f1["mpstat"].empty


def test_cluster_analyze_parallel_matches_serial(tmp_path):
    """Per-host load+analyze fans out with --jobs; the merged timeline and
    the summary table must be independent of worker count."""
    import json

    from sofa_tpu.analyze import cluster_analyze
    from sofa_tpu.trace import make_frame, write_csv

    hosts = ["hostA", "hostB", "hostC"]
    docs = {}
    for jobs, run in ((1, "s"), (4, "p")):
        base = str(tmp_path / f"clog{run}")
        for i, host in enumerate(hosts):
            d = f"{base}-{host}/"
            os.makedirs(d)
            with open(d + "sofa_time.txt", "w") as f:
                f.write(f"{1_700_000_000.0 + i}\n")
            with open(d + "misc.txt", "w") as f:
                f.write("elapsed_time 2.0\ncores 4\n")
            write_csv(make_frame([
                {"timestamp": 1.0, "duration": 0.5, "deviceId": 0,
                 "name": f"op_{host}", "device_kind": "tpu"}]),
                d + "tputrace.csv")
        cfg = SofaConfig(logdir=base + "/", cluster_hosts=hosts, jobs=jobs)
        results = cluster_analyze(cfg)
        assert set(results) == set(hosts)
        summary = pd.read_csv(cfg.path("cluster_summary.csv"))
        assert list(summary["host"]) == hosts, "host order must be stable"
        text = open(cfg.path("report.js")).read()
        docs[run] = json.loads(text[len("sofa_traces = "):].rstrip(";\n"))
        docs[f"{run}_summary"] = summary.drop(columns=["host"])
    assert [s["name"] for s in docs["s"]["series"]] == \
        [s["name"] for s in docs["p"]["series"]]
    assert docs["s"]["series"] == docs["p"]["series"]
    pd.testing.assert_frame_equal(docs["s_summary"], docs["p_summary"])


@pytest.mark.slow
def test_process_pool_path_matches_threads(tmp_path, monkeypatch):
    """SOFA_PREPROCESS_POOL=always routes the CPU-heavy parsers through a
    real process pool; frames must still match the thread-pool run."""
    # give the proc-pool leg something to parse: a perf.script sample file
    perf_lines = "".join(
        f"python 100/100 [0] 1700000000.{i:06d}: 100000 cycles: "
        f"4a{i:04x} sym_{i % 7}+0x10 (/usr/bin/python)\n"
        for i in range(200))

    d1 = _build_logdir(tmp_path, "threads")
    d2 = _build_logdir(tmp_path, "procs")
    for d in (d1, d2):
        with open(d + "perf.script", "w") as f:
            f.write(perf_lines)
    monkeypatch.delenv("SOFA_PREPROCESS_POOL", raising=False)
    f_thread = sofa_preprocess(SofaConfig(logdir=d1, ingest_cache=False,
                                          jobs=2))
    monkeypatch.setenv("SOFA_PREPROCESS_POOL", "always")
    f_proc = sofa_preprocess(SofaConfig(logdir=d2, ingest_cache=False,
                                        jobs=2))
    assert not f_proc["cputrace"].empty
    for key in f_thread:
        pd.testing.assert_frame_equal(
            f_thread[key].reset_index(drop=True),
            f_proc[key].reset_index(drop=True), obj=key)
