"""Autoregressive inference for the Llama-style transformer (KV cache).

BASELINE config #4 ("Llama-3-8B inference: HLO-op + HBM-bandwidth
attribution") needs a decode workload, not just training steps: decode is
memory-bound — every step re-reads the whole KV cache from HBM to produce
one token — which is exactly the regime the roofline pass and HBM series
exist to expose.

TPU-first shape discipline: the cache is a static [L, B, max_seq, KVH, Dh]
buffer, decode positions are masked (`j > cur_len` -> NEG_INF) instead of
sliced, prefill is one full forward pass that also emits per-layer K/V,
and the decode loop is a single `lax.scan` (one compiled step, N
iterations).  Sampling defaults to greedy argmax so runs are deterministic
and the step-vs-full-forward equivalence is testable; a SampleConfig adds
temperature / top-k / nucleus sampling with a per-step-folded PRNG key
(trace-time constants — the compiled scan stays fully static).

Tensor parallelism composes: with a mesh, the cache shards over "model"
(the KV heads) and batch over "data", matching transformer.param_specs;
sequence parallelism does not apply at decode (T=1 per step).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sofa_tpu.workloads.ring_attention import NEG_INF
from sofa_tpu.workloads.transformer import (
    TransformerConfig,
    _rmsnorm,
    layer_body,
)


def _cache_spec(mesh: Optional[Mesh]):
    if mesh is None:
        return None
    return NamedSharding(mesh, P(None, "data", None, "model", None))


def init_cache(cfg: TransformerConfig, batch: int,
               mesh: Optional[Mesh] = None):
    """Zeroed K/V buffers: a (k, v) pair of [L, B, max_seq, KVH, Dh]."""
    shape = (cfg.n_layers, batch, cfg.max_seq, cfg.n_kv_heads, cfg.d_head)
    k = jnp.zeros(shape, cfg.dtype)
    v = jnp.zeros(shape, cfg.dtype)
    if mesh is not None:
        k = jax.device_put(k, _cache_spec(mesh))
        v = jax.device_put(v, _cache_spec(mesh))
    return k, v


def _attend_cache(q, k_cache, v_cache, cur_len):
    """q: [B, T, H, Dh] attends the first cur_len+T cache positions.

    k/v_cache: [B, max_seq, KVH, Dh] (already containing this step's
    entries).  Valid keys are j <= cur_len + (query's offset), expressed
    with a mask so shapes stay static.  GQA runs as a grouped einsum — the
    cache is read once at its stored width, never materialized
    head-repeated (decode is the memory-bound regime this workload
    exists to expose; the f32 converts fuse into the dots).
    """
    b, t, h, dh = q.shape
    kvh = k_cache.shape[2]
    rep = h // kvh
    qg = q.reshape(b, t, kvh, rep, dh)
    scale = dh ** -0.5
    # Operands keep their storage dtype with f32 accumulation: an explicit
    # astype(f32) on the cache both materializes a second full-cache copy
    # in HBM (decode's whole cost IS reading the cache) and runs the MXU
    # in f32 mode — the same ~4x penalty fixed in the flash kernel.
    s = jnp.einsum("btkrd,bskd->bkrts", qg, k_cache,
                   preferred_element_type=jnp.float32) * scale
    j = jnp.arange(k_cache.shape[1])[None, None, None, None, :]
    q_pos = cur_len + jnp.arange(t)[None, None, None, :, None]
    s = jnp.where(j > q_pos, NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkrts,bskd->btkrd", p.astype(v_cache.dtype), v_cache,
                   preferred_element_type=jnp.float32)
    return o.reshape(b, t, h, dh).astype(q.dtype)


def _block(params, x, tokens_positions, cache, cur_len,
           cfg: TransformerConfig):
    """Transformer stack over x [B, T, D], reading+writing the KV cache at
    offset cur_len.  Returns (logits [B, T, vocab], cache).

    The layer math is transformer.layer_body — one shared copy — with the
    attention swapped for a cache read/write."""
    k_cache, v_cache = cache

    def layer(x, lp_kv):
        lp, kc, vc = lp_kv

        def attn(q, kk, v):
            kc2 = lax.dynamic_update_slice(kc, kk.astype(kc.dtype),
                                           (0, cur_len, 0, 0))
            vc2 = lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                           (0, cur_len, 0, 0))
            return _attend_cache(q, kc2, vc2, cur_len), (kc2, vc2)

        return layer_body(x, lp, cfg, tokens_positions, attn)

    x, (k_cache, v_cache) = lax.scan(layer, x,
                                     (params["layers"], k_cache, v_cache))
    x = _rmsnorm(x, params["final_norm"])
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, (k_cache, v_cache)


def prefill(params, tokens, cache, cfg: TransformerConfig):
    """Full-sequence forward that populates the cache.

    tokens: [B, T_prompt].  Returns (logits [B, T, vocab], cache).
    """
    b, t = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(t), (b, t))
    x = params["embed"].astype(cfg.dtype)[tokens]
    return _block(params, x, positions, cache, 0, cfg)


def decode_step(params, token, cache, cur_len, cfg: TransformerConfig):
    """One token in, one token's logits out.  token: [B] int32."""
    b = token.shape[0]
    positions = jnp.broadcast_to(cur_len, (b, 1))
    x = params["embed"].astype(cfg.dtype)[token][:, None, :]
    logits, cache = _block(params, x, positions, cache, cur_len, cfg)
    return logits[:, 0], cache


@dataclasses.dataclass(frozen=True)
class SampleConfig:
    """Decode-time sampling.  All fields are trace-time constants, so each
    combination compiles its own (fully static) decode scan — TPU-friendly:
    no data-dependent control flow, top-k via lax.top_k threshold, nucleus
    via one sort.

    temperature 0.0 = greedy (the deterministic default everywhere);
    top_k 0 = unrestricted; top_p 1.0 = nucleus off.
    """
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0


GREEDY = SampleConfig()


def sample_token(logits, key, sc: SampleConfig):
    """Next-token choice from [B, vocab] f32 logits under ``sc``."""
    if sc.temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    l = logits / sc.temperature
    if sc.top_k > 0:
        kth = lax.top_k(l, sc.top_k)[0][:, -1:]         # [B, 1]
        l = jnp.where(l < kth, NEG_INF, l)
    if sc.top_p < 1.0:
        # nucleus: keep the smallest prefix of the sorted distribution
        # whose mass reaches top_p (the first token always survives).
        # Dropped entries become +inf so the min yields the smallest KEPT
        # logit — always finite, since position 0 is never dropped.
        sorted_l = jnp.sort(l, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_mask = (cum - probs) >= sc.top_p          # drop after mass
        cut = jnp.where(cutoff_mask, jnp.inf, sorted_l).min(
            axis=-1, keepdims=True)
        l = jnp.where(l < cut, NEG_INF, l)
    return jax.random.categorical(key, l, axis=-1)


def decode_loop(params, first_tok, cache, t_prompt: int, max_new: int,
                cfg: TransformerConfig,
                sample: SampleConfig = GREEDY,
                key: Optional[jax.Array] = None) -> jax.Array:
    """Sampled/greedy scan from the first generated token: [B, max_new].

    Runs max_new - 1 decode steps (the first new token came from prefill;
    the token produced by the final step would be position max_new + 1 and
    is never computed).  The PRNG key splits per step inside the scan."""
    if key is None:
        key = jax.random.PRNGKey(0)

    def step(carry, i):
        tok, cache = carry
        logits, cache = decode_step(params, tok, cache, t_prompt + i, cfg)
        nxt = sample_token(logits, jax.random.fold_in(key, i),
                           sample).astype(tok.dtype)
        return (nxt, cache), nxt

    (_, _), toks = lax.scan(step, (first_tok, cache),
                            jnp.arange(max_new - 1))
    return jnp.concatenate([first_tok[:, None], toks.T], axis=1)


def generate(params, prompt, max_new: int, cfg: TransformerConfig,
             mesh: Optional[Mesh] = None,
             sample: SampleConfig = GREEDY,
             key: Optional[jax.Array] = None) -> jax.Array:
    """Decode: [B, T_prompt] -> [B, T_prompt + max_new].

    Greedy (deterministic) by default; pass a SampleConfig for
    temperature / top-k / nucleus sampling, with a PRNG key for
    reproducibility.  jit-able end to end; the decode loop is one
    lax.scan.
    """
    b, t_prompt = prompt.shape
    if t_prompt + max_new > cfg.max_seq:
        raise ValueError(f"{t_prompt} + {max_new} exceeds max_seq "
                         f"{cfg.max_seq}")
    if key is None:
        key = jax.random.PRNGKey(0)
    cache = init_cache(cfg, b, mesh)
    logits, cache = prefill(params, prompt, cache, cfg)
    # fold_in(max_new): disjoint from the decode steps' 0..max_new-2
    first = sample_token(logits[:, t_prompt - 1].astype(jnp.float32),
                         jax.random.fold_in(key, max_new), sample)
    next_tok = first.astype(prompt.dtype)
    new = decode_loop(params, next_tok, cache, t_prompt, max_new, cfg,
                      sample, key)
    return jnp.concatenate([prompt, new], axis=1)


def make_serving_fns(cfg: TransformerConfig, prompt_len: int, max_new: int,
                     mesh: Optional[Mesh] = None):
    """The two jitted serving entry points, split so the profiler sees the
    two regimes as separate XLA modules (jit_run_prefill / jit_run_decode —
    the names analysis/tpu.serving_profile anchors on):

      run_prefill(params, prompt)      -> (first_token, cache)
      run_decode(params, tok, cache)   -> [B, max_new] generated tokens
    """

    @jax.jit
    def run_prefill(p, x):
        cache = init_cache(cfg, x.shape[0], mesh)
        logits, cache = prefill(p, x, cache, cfg)
        tok = jnp.argmax(logits[:, x.shape[1] - 1], -1).astype(x.dtype)
        return tok, cache

    @jax.jit
    def run_decode(p, tok, cache):
        return decode_loop(p, tok, cache, prompt_len, max_new, cfg)

    return run_prefill, run_decode


def main(argv=None):
    import time

    from sofa_tpu.workloads.common import make_mesh, parse_workload_args
    from sofa_tpu.workloads.transformer import init_params, shard_params

    args = parse_workload_args(argv, {
        "batch": 4, "prompt": 128, "new_tokens": 128, "d_model": 512,
        "n_layers": 4, "n_heads": 8, "n_kv_heads": 4, "d_ff": 1408,
        "vocab": 32000, "data": 0, "model": 0,
    })
    cfg = TransformerConfig(vocab=args.vocab, d_model=args.d_model,
                            n_layers=args.n_layers, n_heads=args.n_heads,
                            n_kv_heads=args.n_kv_heads, d_ff=args.d_ff,
                            max_seq=args.prompt + args.new_tokens)
    mesh = None
    n = len(jax.devices())
    if n > 1:
        sizes = None
        if args.data or args.model:
            # A single flag set leaves the other axis to absorb the rest.
            sizes = (args.data or -1, args.model or -1)
        mesh = make_mesh(("data", "model"), sizes)
    key = jax.random.PRNGKey(0)
    params = init_params(cfg, key)
    if mesh is not None:
        # Reuse the training param specs; the decode mesh has no seq axis.
        params = shard_params(params, cfg, mesh)
    prompt = jax.random.randint(key, (args.batch, args.prompt), 0, cfg.vocab)

    # Prefill and decode are different regimes (compute- vs memory-bound);
    # time them separately so the reported numbers mean something.
    run_prefill, run_decode = make_serving_fns(
        cfg, args.prompt, args.new_tokens, mesh)

    tok, cache = run_prefill(params, prompt)
    jax.block_until_ready(run_decode(params, tok, cache))   # compile both
    t0 = time.perf_counter()
    tok, cache = run_prefill(params, prompt)
    jax.block_until_ready((tok, cache))
    t1 = time.perf_counter()
    out = run_decode(params, tok, cache)
    out.block_until_ready()
    t2 = time.perf_counter()
    pre_tps = args.batch * args.prompt / (t1 - t0)
    # The decode window runs new_tokens - 1 steps (the first new token is
    # the prefill window's argmax).
    dec_tps = args.batch * max(1, args.new_tokens - 1) / (t2 - t1)
    print(f"inference: prefill {pre_tps:,.1f} tokens/s, "
          f"decode {dec_tps:,.1f} tokens/s "
          f"(batch {args.batch}, prompt {args.prompt}, "
          f"new {args.new_tokens}, mesh={dict(mesh.shape) if mesh else None})")


if __name__ == "__main__":
    main()
