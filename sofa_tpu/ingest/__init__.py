"""Parsers turning raw collector output into unified-schema DataFrames.

One module per source (the reference concentrates all of this in the 2106-line
sofa_preprocess.py; see SURVEY §2.4 for the per-parser map).  Every parser is
a pure function ``text/path -> DataFrame`` so fixtures can test it without
running collectors.

Corruption contract: a parser that can positively identify a truncated or
corrupt raw file raises :class:`CorruptRawError` (never for a merely-empty
or absent file — those are normal degradations).  Preprocess reacts by
quarantining the file to ``<logdir>/_quarantine/`` and recording the source
as ``quarantined`` in the run manifest; see docs/ROBUSTNESS.md.

Tool contract: a parser that has raw bytes to read but whose external
converter (``perf script``, the native scanners) fails or exceeds its
deadline raises :class:`IngestToolError`.  Preprocess records the source as
``failed`` in the manifest — raw data exists but could not be converted,
which is a different (re-runnable) failure than corrupt or absent input.
"""

from __future__ import annotations


class CorruptRawError(ValueError):
    """A raw collector file is positively corrupt (not merely absent/empty).

    Carries the on-disk ``path`` so preprocess can quarantine the file.
    args stay ``(path, reason)`` so the exception survives a process-pool
    pickle round-trip with its attributes intact.
    """

    def __init__(self, path: str, reason: str):
        super().__init__(path, reason)
        self.path = path
        self.reason = reason

    def __str__(self) -> str:
        return f"{self.path}: {self.reason}"


class IngestToolError(RuntimeError):
    """An external conversion tool failed/hung over EXISTING raw bytes.

    Distinct from :class:`CorruptRawError`: the raw file may be perfectly
    fine — the converter (``perf script``, a native scanner) is what broke,
    so the file must NOT be quarantined; a re-run with a working tool can
    still ingest it.  Preprocess records the source as ``failed`` in the
    run manifest.  args stay ``(path, reason)`` for process-pool pickling.
    """

    def __init__(self, path: str, reason: str):
        super().__init__(path, reason)
        self.path = path
        self.reason = reason

    def __str__(self) -> str:
        return f"{self.path}: {self.reason}"
