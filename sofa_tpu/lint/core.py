"""The sofa-lint engine: one AST pass per file, rule dispatch by node type.

Design notes
------------

* **Single pass.**  Every rule declares the node types it cares about
  (``node_types``); the engine walks each module AST exactly once and
  dispatches nodes to interested rules.  Rules are stateless across files —
  per-file scratch state lives on the :class:`FileContext`.
* **Static only.**  The engine never imports the code it checks.  Project
  facts the rules need (the unified trace schema) are extracted from
  ``trace.py``'s AST, so linting works on a tree that does not even import
  (and costs no pandas/jax startup).
* **Suppressions.**  ``# sofa-lint: disable=SL001[,SL002]`` on the flagged
  line silences those rules for that line; ``# sofa-lint: disable-file=SL001``
  anywhere silences them for the whole file; ``all`` matches every rule.
  Comments are found with :mod:`tokenize`, so a string literal that merely
  *contains* the marker does not suppress anything.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

SEV_ERROR = "error"
SEV_WARN = "warn"

#: Rule id reserved for files the engine itself cannot parse.
PARSE_RULE_ID = "SL000"

_DISABLE_RE = re.compile(
    r"sofa-lint:\s*(?P<scope>disable-file|disable)\s*=\s*"
    r"(?P<rules>(?:all|SL\d+)(?:\s*,\s*(?:all|SL\d+))*)")


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a file:line."""

    file: str
    line: int
    rule_id: str
    message: str
    severity: str = SEV_ERROR

    def render(self) -> str:
        return (f"{self.file}:{self.line}: {self.rule_id} "
                f"[{self.severity}] {self.message}")

    def to_dict(self) -> dict:
        return {"file": self.file, "line": self.line, "rule": self.rule_id,
                "severity": self.severity, "message": self.message}


@dataclass(frozen=True)
class PassDecl:
    """One ``@analysis_pass(...)`` or ``@fleet_pass(...)`` declaration,
    read straight from the AST (never by importing): the cross-file facts
    SL010–SL013 verify pass bodies and the dependency graph against.
    ``domain`` separates the two registries — per-run analysis passes
    read trace frames, fleet passes read archive-index column families —
    and the graph rules refuse edges that cross it."""

    name: str
    func: str
    relpath: str
    line: int
    domain: str = "analysis"
    reads_frames: tuple = ()
    reads_columns: tuple = ()
    reads_features: tuple = ()
    provides_features: tuple = ()
    provides_artifacts: tuple = ()
    provides_series: bool = False
    after: tuple = ()
    enabled_when: tuple = ()


@dataclass
class ProjectContext:
    """Cross-file facts rules consult (kept deliberately small)."""

    #: The unified trace schema (trace.COLUMNS), extracted from the AST of
    #: trace.py — empty set disables the schema-drift rule.
    columns: frozenset = frozenset()
    #: Every @analysis_pass / @fleet_pass declaration in the linted tree
    #: (pass_rules.py) — PassDecl.domain tells them apart.
    passes: tuple = ()
    #: Pinned archive-index family schemas as "family.column" strings,
    #: extracted from the AST of archive/index.py — empty set disables
    #: the fleet-domain column checks.
    index_columns: frozenset = frozenset()
    #: AMBIENT_FEATURES from analysis/registry.py — features the analyze
    #: driver provides without a producing pass.
    ambient_features: tuple = ()
    #: The artifact-lifecycle flow graph (lint/artifact_rules.py) —
    #: None/inactive unless the linted set carries a registry-bearing
    #: trace.py, so fixtures and single-file lints skip SL014–SL018.
    artifacts: object = None
    #: The concurrency/execution-context graph (lint/concurrency_rules.py)
    #: — None/inactive when the context is built by hand (fixture
    #: isolation), so SL019–SL023 only run under detect().
    concurrency: object = None
    #: The client<->server protocol graph (lint/protocol_rules.py) —
    #: None/inactive unless the linted set carries a STATUS_ERRORS
    #: vocabulary module, so SL024–SL028 only fire on protocol trees.
    protocol: object = None

    @classmethod
    def detect(cls, files: Sequence[str],
               base: Optional[str] = None) -> "ProjectContext":
        """Build the context from the tree being linted: find a trace.py
        declaring BASE_COLUMNS/EXTRA_COLUMNS and read the literals out of
        its AST (falling back to this package's own trace.py so linting a
        single file still knows the schema), collect every
        ``@analysis_pass`` / ``@fleet_pass`` declaration, read
        AMBIENT_FEATURES from the registry module, and the index family
        schemas from archive/index.py.  ``base`` must match the relpath
        anchor the engine uses so declarations join up with
        FileContext.relpath."""
        candidates = [f for f in files if os.path.basename(f) == "trace.py"]
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        here = os.path.join(pkg, "trace.py")
        if os.path.isfile(here):
            candidates.append(here)
        columns: frozenset = frozenset()
        for cand in candidates:
            cols = _columns_from_trace(cand)
            if cols:
                columns = frozenset(cols)
                break
        passes: List[PassDecl] = []
        base = os.path.abspath(base or os.getcwd())
        for f in files:
            ab = os.path.abspath(f)
            rel = (os.path.relpath(ab, base)
                   if ab.startswith(base + os.sep) else ab)
            passes.extend(_pass_decls_from_file(f, rel.replace(os.sep, "/")))
        ambient = ()
        reg_candidates = [f for f in files
                          if os.path.basename(f) == "registry.py"]
        reg_candidates.append(os.path.join(pkg, "analysis", "registry.py"))
        for cand in reg_candidates:
            ambient = _ambient_from_registry(cand)
            if ambient:
                break
        index_columns: frozenset = frozenset()
        idx_candidates = [f for f in files
                          if os.path.basename(f) == "index.py"]
        idx_candidates.append(os.path.join(pkg, "archive", "index.py"))
        for cand in idx_candidates:
            cols = _index_columns_from_archive(cand)
            if cols:
                index_columns = frozenset(cols)
                break
        from sofa_tpu.lint.artifact_rules import build_artifact_graph
        from sofa_tpu.lint.concurrency_rules import build_concurrency_graph
        from sofa_tpu.lint.protocol_rules import build_protocol_graph

        artifacts = build_artifact_graph(files, base=base,
                                         passes=tuple(passes))
        concurrency = build_concurrency_graph(files, base=base)
        protocol = build_protocol_graph(files, base=base)
        return cls(columns=columns, passes=tuple(passes),
                   ambient_features=ambient, index_columns=index_columns,
                   artifacts=artifacts, concurrency=concurrency,
                   protocol=protocol)


def _columns_from_trace(path: str) -> List[str]:
    try:
        with open(path, "rb") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError, ValueError):
        return []
    lists: Dict[str, List[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        if tgt.id in ("BASE_COLUMNS", "EXTRA_COLUMNS") and \
                isinstance(node.value, ast.List):
            vals = [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)]
            lists[tgt.id] = vals
    return lists.get("BASE_COLUMNS", []) + lists.get("EXTRA_COLUMNS", [])


def _ambient_from_registry(path: str) -> tuple:
    """AMBIENT_FEATURES literal out of analysis/registry.py's AST."""
    try:
        with open(path, "rb") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError, ValueError):
        return ()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "AMBIENT_FEATURES" \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return tuple(e.value for e in node.value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str))
    return ()


#: Index schema constant -> family name (mirrors index.FAMILIES; kept as
#: a literal map so the extractor stays import-free like the rest).
_INDEX_FAMILY_CONSTS = {"CATALOG_COLUMNS": "catalog",
                        "RUNS_COLUMNS": "runs",
                        "FEATURE_COLUMNS": "features"}


def _index_columns_from_archive(path: str) -> List[str]:
    """Pinned "family.column" strings out of archive/index.py's AST."""
    try:
        with open(path, "rb") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError, ValueError):
        return []
    out: List[str] = []
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if isinstance(tgt, ast.Name) and tgt.id in _INDEX_FAMILY_CONSTS \
                and isinstance(node.value, ast.List):
            family = _INDEX_FAMILY_CONSTS[tgt.id]
            out.extend(f"{family}.{e.value}" for e in node.value.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, str))
    return out


def _str_tuple(node) -> tuple:
    """String literals out of a tuple/list AST literal (non-literals and
    non-strings are dropped — the runtime registry rejects those loudly)."""
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


#: Decorator name -> registry domain a declaration belongs to.
_PASS_DECORATORS = {"analysis_pass": "analysis", "fleet_pass": "fleet"}


def _pass_decls_from_file(path: str, relpath: str) -> List[PassDecl]:
    """Every ``@analysis_pass(...)`` / ``@fleet_pass(...)`` (bare or
    attribute-qualified) in one file, contracts read as literals.  Purely
    syntactic — a decorator of either name is treated as a pass
    declaration wherever it appears."""
    try:
        with open(path, "rb") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError, ValueError):
        return []
    out: List[PassDecl] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            if not isinstance(deco, ast.Call):
                continue
            fn = deco.func
            deco_name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else "")
            if deco_name not in _PASS_DECORATORS:
                continue
            kw = {k.arg: k.value for k in deco.keywords if k.arg}
            name_node = kw.get("name")
            name = (name_node.value
                    if isinstance(name_node, ast.Constant)
                    and isinstance(name_node.value, str) else node.name)
            series_node = kw.get("provides_series")
            out.append(PassDecl(
                name=name, func=node.name, relpath=relpath,
                line=deco.lineno, domain=_PASS_DECORATORS[deco_name],
                reads_frames=_str_tuple(kw.get("reads_frames")),
                reads_columns=_str_tuple(kw.get("reads_columns")),
                reads_features=_str_tuple(kw.get("reads_features")),
                provides_features=_str_tuple(kw.get("provides_features")),
                provides_artifacts=_str_tuple(kw.get("provides_artifacts")),
                provides_series=bool(
                    isinstance(series_node, ast.Constant)
                    and series_node.value),
                after=_str_tuple(kw.get("after")),
                enabled_when=_str_tuple(kw.get("enabled_when")),
            ))
    return out


class FileContext:
    """Per-file state handed to rules: source, AST, parents, import map."""

    def __init__(self, relpath: str, src: str, tree: ast.Module,
                 project: ProjectContext):
        self.relpath = relpath.replace(os.sep, "/")
        self.src = src
        self.lines = src.splitlines()
        self.tree = tree
        self.project = project
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        # alias -> module ("sp" -> "subprocess"); name -> dotted origin
        # ("run" -> "subprocess.run") for from-imports.
        self.import_alias: Dict[str, str] = {}
        self.from_import: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_alias[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_import[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    # -- helpers rules lean on --------------------------------------------
    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_stmt(self, node: ast.AST) -> Optional[ast.stmt]:
        cur: Optional[ast.AST] = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parents.get(cur)
        return cur

    def stmt_source(self, node: ast.AST) -> str:
        stmt = self.enclosing_stmt(node)
        if stmt is None:
            return self.line_text(getattr(node, "lineno", 0))
        return ast.get_source_segment(self.src, stmt) or \
            self.line_text(stmt.lineno)

    def resolve_call(self, node: ast.Call) -> Optional[str]:
        """Dotted origin of a call through the file's import aliases:
        ``sp.run`` -> "subprocess.run", bare ``run`` (from-imported) ->
        "subprocess.run", plain builtins -> their own name."""
        return self.resolve_name(node.func)

    def resolve_name(self, func: ast.expr) -> Optional[str]:
        if isinstance(func, ast.Name):
            return self.from_import.get(func.id,
                                        self.import_alias.get(func.id,
                                                              func.id))
        if isinstance(func, ast.Attribute):
            parts: List[str] = [func.attr]
            cur = func.value
            while isinstance(cur, ast.Attribute):
                parts.append(cur.attr)
                cur = cur.value
            if isinstance(cur, ast.Name):
                base = self.import_alias.get(cur.id,
                                             self.from_import.get(cur.id,
                                                                  cur.id))
                parts.append(base)
                return ".".join(reversed(parts))
        return None


class Rule:
    """Base rule.  Subclasses set ``rule_id``/``severity``/``node_types``
    and implement :meth:`visit`; optional :meth:`finish` runs once per file
    after the walk (for module-level checks)."""

    rule_id = ""
    severity = SEV_ERROR
    #: AST node classes this rule wants to see; () = finish()-only rule.
    node_types: Tuple[Type[ast.AST], ...] = ()
    #: relpath fragments (``/``-separated) exempting a whole file.
    exempt_files: Tuple[str, ...] = ()

    def applies(self, ctx: FileContext) -> bool:
        return not any(_path_matches(ctx.relpath, pat)
                       for pat in self.exempt_files)

    def visit(self, ctx: FileContext, node: ast.AST) -> Iterable[Finding]:
        return ()

    def finish(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finding(self, ctx: FileContext, node: ast.AST,
                message: str) -> Finding:
        return Finding(ctx.relpath, getattr(node, "lineno", 0),
                       self.rule_id, message, self.severity)


def _path_matches(relpath: str, pat: str) -> bool:
    """True when ``pat`` names this file (suffix match on /-separated
    fragments: "collectors/base.py" matches "sofa_tpu/collectors/base.py",
    "ingest/" matches any file under an ingest directory)."""
    if pat.endswith("/"):
        return f"/{pat}" in f"/{relpath}"
    return relpath == pat or relpath.endswith("/" + pat)


@dataclass
class _Suppressions:
    by_line: Dict[int, set] = field(default_factory=dict)
    whole_file: set = field(default_factory=set)

    def hides(self, f: Finding) -> bool:
        for scope in (self.whole_file, self.by_line.get(f.line, ())):
            if "all" in scope or f.rule_id in scope:
                return True
        return False


def _scan_suppressions(src: str) -> _Suppressions:
    sup = _Suppressions()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        tokens = []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _DISABLE_RE.search(tok.string)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        if m.group("scope") == "disable-file":
            sup.whole_file |= rules
        else:
            sup.by_line.setdefault(tok.start[0], set()).update(rules)
    return sup


class LintEngine:
    """Run a rule set over files; one AST walk per file."""

    def __init__(self, rules: Sequence[Rule], project: ProjectContext):
        self.rules = list(rules)
        self.project = project
        self._by_type: Dict[Type[ast.AST], List[Rule]] = {}
        for rule in self.rules:
            for nt in rule.node_types:
                self._by_type.setdefault(nt, []).append(rule)

    def lint_file(self, path: str, relpath: str) -> List[Finding]:
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                src = f.read()
        except OSError as e:
            return [Finding(relpath, 0, PARSE_RULE_ID,
                            f"cannot read file: {e}")]
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            return [Finding(relpath, e.lineno or 0, PARSE_RULE_ID,
                            f"syntax error: {e.msg}")]
        ctx = FileContext(relpath, src, tree, self.project)
        active = [r for r in self.rules if r.applies(ctx)]
        if not active:
            return []
        active_set = set(map(id, active))
        findings: List[Finding] = []
        for node in ast.walk(tree):
            for rule in self._by_type.get(type(node), ()):
                if id(rule) in active_set:
                    findings.extend(rule.visit(ctx, node))
        for rule in active:
            findings.extend(rule.finish(ctx))
        if findings:
            sup = _scan_suppressions(src)
            findings = [f for f in findings if not sup.hides(f)]
        return findings


def iter_python_files(paths: Sequence[str]) -> List[str]:
    """Expand files/dirs into a sorted .py file list (skips caches and
    hidden dirs; deterministic order keeps baselines reproducible)."""
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith((".", "__pycache__")))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
    seen, uniq = set(), []
    for f in out:
        key = os.path.abspath(f)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq


def lint_paths(paths: Sequence[str], rules: Sequence[Rule],
               project: Optional[ProjectContext] = None,
               base: Optional[str] = None, jobs: int = 1) -> List[Finding]:
    """Lint files/directories; findings sorted by (file, line, rule).

    ``base`` anchors the relpaths findings (and baseline fingerprints) are
    keyed on — defaults to the current directory, matching the
    ``python tools/sofa_lint.py sofa_tpu/`` invocation from the repo root.
    ``jobs`` > 1 fans the per-file walks across a thread pool (rules keep
    per-file scratch on the FileContext and read the project graphs
    read-only, so files are independent); results keep file order and the
    final sort makes the report byte-identical at any pool width.
    """
    files = iter_python_files(paths)
    base = os.path.abspath(base or os.getcwd())
    if project is None:
        project = ProjectContext.detect(files, base=base)
    engine = LintEngine(rules, project)

    def rel_of(f: str) -> str:
        ab = os.path.abspath(f)
        return os.path.relpath(ab, base) if ab.startswith(base + os.sep) \
            else ab

    findings: List[Finding] = []
    if jobs > 1 and len(files) > 1:
        from sofa_tpu.pool import thread_map

        for per_file in thread_map(
                lambda f: engine.lint_file(f, rel_of(f)), files, jobs):
            findings.extend(per_file)
    else:
        for f in files:
            findings.extend(engine.lint_file(f, rel_of(f)))
    findings.sort(key=lambda f: (f.file, f.line, f.rule_id))
    return findings
