"""Ring attention with the fused Pallas kernel on every hop.

Combines the two long-context mechanisms in this package: sequence
parallelism (K/V blocks rotate around a mesh axis over `lax.ppermute`,
riding ICI neighbor links — sofa_tpu/workloads/ring_attention.py) and the
streaming flash kernel (sofa_tpu/workloads/flash_pallas.py).  Each hop runs
the kernel over the visiting K/V block with a *dynamic causal shift*
(hop i on device r sees shift (i - n·[i>r])·T_local: aligned-causal for the
home block, full for blocks from earlier shards, fully-masked for later
shards), and hops are folded together by their per-row logsumexp — so
neither the per-hop [T_local, T_local] score matrix nor any cross-shard
gather ever materializes.  Per-chip live memory is O(B·H·T_local·block).

The backward is the ring form of the flash gradient: dK/dV accumulators
rotate around the ring *with* their K/V blocks, each device adds its
blockwise contribution (recomputed from the saved global logsumexp), and
after axis_size hops every accumulator is home.  One extra round-trip of
ppermute traffic, no replay of the forward.

The reference profiler only *observed* such traffic (P2P copy matrices,
/root/reference/bin/sofa_common.py:97-157); here the canonical generator of
ICI collective-permute traffic is also memory-optimal.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from sofa_tpu.workloads.compat import shard_map

from sofa_tpu.workloads.flash_pallas import (
    _flash_backward,
    _flash_forward,
    _grad_block,
)
from sofa_tpu.workloads.ring_attention import NEG_INF

# Tests pin this to force one implementation; None = auto (Pallas kernels
# on TPU, the lax fallback elsewhere — interpreted Pallas is exact but
# slow, and the CPU suite runs every ring test through the lax path).
FORCE_PALLAS_BWD: Optional[bool] = None


def _hop_grad(q, k, v, g, delta, lse, shift):
    """Per-hop blockwise gradients with the hop's traced causal shift.

    The fused Pallas backward (static_causal=False: no index-map clamps,
    compute still skipped per block) on TPU; _grad_block's lax scan
    elsewhere.  Both return f32 — the ring accumulates across hops.
    """
    use_pallas = (FORCE_PALLAS_BWD if FORCE_PALLAS_BWD is not None
                  else jax.default_backend() == "tpu")
    if use_pallas:
        return _flash_backward(q, k, v, g, None, lse, shift=shift,
                               static_causal=False, delta=delta,
                               grad_dtype=jnp.float32)
    return _grad_block(q, k, v, g, delta, lse, shift)


def _hop_shift(i, r, n, t_local):
    """Causal shift for hop i on ring position r: the visiting block came
    from shard (r - i) mod n, so its keys sit (i mod n) shards *behind* the
    local queries — except when i > r, where the wrap makes them later
    shards (fully masked, negative shift)."""
    return (i - jnp.where(i > r, n, 0)) * t_local


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def ring_flash_attention_local(q, k, v, axis_name: str):
    """Exact causal attention over the ``axis_name``-sharded sequence.

    q, k, v: [B, T_local, H, D] — this chip's shard.  Runs inside shard_map.
    """
    out, _ = _ring_fwd_impl(q, k, v, axis_name)
    return out


def _lse_merge(o, lse, o_i, lse_i):
    """Fold a new partial attention result into the running (o, lse).

    o: [B, T, H, D] f32 running output; lse: [B, H, T].  The standard
    "merge attention outputs by logsumexp" identity — the only place this
    numerically delicate step is written.
    """
    new_lse = jnp.logaddexp(lse, lse_i)
    a = jnp.exp(lse - new_lse).transpose(0, 2, 1)[..., None]
    bb = jnp.exp(lse_i - new_lse).transpose(0, 2, 1)[..., None]
    return o * a + o_i.astype(jnp.float32) * bb, new_lse


def _ring_fwd_impl(q, k, v, axis_name):
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    zero = q.astype(jnp.float32) * 0.0                 # carries q's VMA type
    o0 = zero
    lse0 = zero[..., 0].transpose(0, 2, 1) + NEG_INF   # [B, H, T]

    def hop(carry, i):
        o, lse, k_blk, v_blk = carry
        shift = _hop_shift(i, r, n, t)
        o_i, lse_i = _flash_forward(q, k_blk, v_blk, shift, None, None, None)
        o, lse = _lse_merge(o, lse, o_i, lse_i)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o, lse, k_blk, v_blk), None

    (o, lse, _, _), _ = lax.scan(hop, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype), lse


def _ring_fwd(q, k, v, axis_name):
    out, lse = _ring_fwd_impl(q, k, v, axis_name)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, res, g):
    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    t = q.shape[1]
    perm = [(j, (j + 1) % n) for j in range(n)]
    delta = jnp.einsum("bqhd,bqhd->bhq", g.astype(jnp.float32),
                       out.astype(jnp.float32))

    zero_kv = k.astype(jnp.float32) * 0.0

    def hop(carry, i):
        dq, k_blk, v_blk, dk_acc, dv_acc = carry
        shift = _hop_shift(i, r, n, t)
        dq_i, dk_i, dv_i = _hop_grad(q, k_blk, v_blk, g, delta, lse, shift)
        dq = dq + dq_i
        dk_acc = dk_acc + dk_i
        dv_acc = dv_acc + dv_i
        # Rotate the K/V blocks and their gradient accumulators together:
        # after n hops each accumulator is back on its home shard carrying
        # every device's contribution.
        k_blk, v_blk, dk_acc, dv_acc = (
            lax.ppermute(x, axis_name, perm)
            for x in (k_blk, v_blk, dk_acc, dv_acc))
        return (dq, k_blk, v_blk, dk_acc, dv_acc), None

    dq0 = q.astype(jnp.float32) * 0.0
    (dq, _, _, dk, dv), _ = lax.scan(
        hop, (dq0, k, v, zero_kv, zero_kv), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_flash_attention_local.defvjp(_ring_fwd, _ring_bwd)


def zigzag_indices(t: int, shards: int):
    """Permutation putting the zig-zag layout on a plainly-sharded axis.

    2S chunks of c = T/(2S); shard r gets chunks (r, 2S-1-r), so under
    causal attention every shard does the same total work — the plain
    blocked layout leaves shard 0 idle for most of the ring (its queries
    see almost nothing) while shard S-1 does S hops of work.  Returns
    (perm, inv): x[:, perm] is zig-zag order, y[:, inv] undoes it.
    """
    import numpy as np

    c = t // (2 * shards)
    if c * 2 * shards != t:
        raise ValueError(f"T={t} must divide into 2*{shards} chunks")
    perm = np.concatenate([
        np.concatenate([np.arange(r * c, (r + 1) * c),
                        np.arange((2 * shards - 1 - r) * c,
                                  (2 * shards - r) * c)])
        for r in range(shards)
    ])
    inv = np.argsort(perm)
    return perm, inv


def _zigzag_hop_shifts(i, r, n, c):
    """Causal shifts for the three contributing (q-half, k-half) pairs at
    hop i (visiting the pair from src = (r - i) mod n):

      lo x lo : standard ring shift (aligned / full / masked)
      hi x lo : k_lo is always globally earlier than q_hi — full
      hi x hi : sign flips (src > r means the visitor's hi chunk is
                *earlier* than ours) — full / causal / masked

    q_lo x k_hi never contributes (k_hi chunks all sit after every q_lo).
    """
    wrapped = jnp.where(i > r, n, 0)
    lo_lo = (i - wrapped) * c
    hi_hi = (wrapped - i) * c
    return lo_lo, c, hi_hi


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def zigzag_ring_flash_attention_local(q, k, v, axis_name: str):
    """Load-balanced exact causal attention; runs inside shard_map.

    q, k, v: [B, 2c, H, D] in zig-zag layout (rows [:c] = chunk r,
    rows [c:] = chunk 2S-1-r; see zigzag_indices).
    """
    out, _ = _zz_fwd_impl(q, k, v, axis_name)
    return out


def _zz_fwd_impl(q, k, v, axis_name):
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    b, t2, h, d = q.shape
    c = t2 // 2
    perm = [(j, (j + 1) % n) for j in range(n)]
    q_lo, q_hi = q[:, :c], q[:, c:]

    zero = q.astype(jnp.float32) * 0.0
    o0 = zero
    lse0 = zero[..., 0].transpose(0, 2, 1) + NEG_INF   # [B, H, 2c]

    def hop(carry, i):
        o, lse, k_blk, v_blk = carry
        s_ll, s_hl, s_hh = _zigzag_hop_shifts(i, r, n, c)
        k_lo, k_hi = k_blk[:, :c], k_blk[:, c:]
        v_lo, v_hi = v_blk[:, :c], v_blk[:, c:]
        o_ll, lse_ll = _flash_forward(q_lo, k_lo, v_lo, s_ll, None, None, None)
        o_hl, lse_hl = _flash_forward(q_hi, k_lo, v_lo, s_hl, None, None, None)
        o_hh, lse_hh = _flash_forward(q_hi, k_hi, v_hi, s_hh, None, None, None)
        o_lo, lse_lo = _lse_merge(o[:, :c], lse[..., :c], o_ll, lse_ll)
        o_hi, lse_hi = _lse_merge(o[:, c:], lse[..., c:], o_hl, lse_hl)
        o_hi, lse_hi = _lse_merge(o_hi, lse_hi, o_hh, lse_hh)
        o = jnp.concatenate([o_lo, o_hi], axis=1)
        lse = jnp.concatenate([lse_lo, lse_hi], axis=-1)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o, lse, k_blk, v_blk), None

    (o, lse, _, _), _ = lax.scan(hop, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype), lse


def _zz_fwd(q, k, v, axis_name):
    out, lse = _zz_fwd_impl(q, k, v, axis_name)
    return out, (q, k, v, out, lse)


def _zz_bwd(axis_name, res, g):
    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    c = q.shape[1] // 2
    perm = [(j, (j + 1) % n) for j in range(n)]
    delta = jnp.einsum("bqhd,bqhd->bhq", g.astype(jnp.float32),
                       out.astype(jnp.float32))
    q_lo, q_hi = q[:, :c], q[:, c:]
    g_lo, g_hi = g[:, :c], g[:, c:]
    d_lo, d_hi = delta[..., :c], delta[..., c:]
    l_lo, l_hi = lse[..., :c], lse[..., c:]

    def hop(carry, i):
        dq, k_blk, v_blk, dk_acc, dv_acc = carry
        s_ll, s_hl, s_hh = _zigzag_hop_shifts(i, r, n, c)
        k_lo, k_hi = k_blk[:, :c], k_blk[:, c:]
        v_lo, v_hi = v_blk[:, :c], v_blk[:, c:]
        dq_ll, dk_ll, dv_ll = _hop_grad(q_lo, k_lo, v_lo, g_lo, d_lo,
                                        l_lo, s_ll)
        dq_hl, dk_hl, dv_hl = _hop_grad(q_hi, k_lo, v_lo, g_hi, d_hi,
                                        l_hi, s_hl)
        dq_hh, dk_hh, dv_hh = _hop_grad(q_hi, k_hi, v_hi, g_hi, d_hi,
                                        l_hi, s_hh)
        dq = dq + jnp.concatenate([dq_ll, dq_hl + dq_hh], axis=1)
        dk_acc = dk_acc + jnp.concatenate([dk_ll + dk_hl, dk_hh], axis=1)
        dv_acc = dv_acc + jnp.concatenate([dv_ll + dv_hl, dv_hh], axis=1)
        k_blk, v_blk, dk_acc, dv_acc = (
            lax.ppermute(x, axis_name, perm)
            for x in (k_blk, v_blk, dk_acc, dv_acc))
        return (dq, k_blk, v_blk, dk_acc, dv_acc), None

    zero_kv = k.astype(jnp.float32) * 0.0
    dq0 = q.astype(jnp.float32) * 0.0
    (dq, _, _, dk, dv), _ = lax.scan(
        hop, (dq0, k, v, zero_kv, zero_kv), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


zigzag_ring_flash_attention_local.defvjp(_zz_fwd, _zz_bwd)


def zigzag_ring_flash_attention(q, k, v, mesh: Mesh, *,
                                seq_axis: str = "seq",
                                batch_axis: Optional[str] = "data",
                                head_axis: Optional[str] = "model"):
    """shard_map-wrapped zig-zag ring flash attention.

    Inputs are global [B, T, H, D] arrays ALREADY in zig-zag order along
    the sequence axis (apply zigzag_indices' perm first — in deployment
    the data pipeline emits this layout so no runtime gather is paid).
    """
    return _mapped(zigzag_ring_flash_attention_local, q, k, v, mesh,
                   seq_axis, batch_axis, head_axis)


def ring_flash_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                         batch_axis: Optional[str] = "data",
                         head_axis: Optional[str] = "model"):
    """shard_map-wrapped ring flash attention over a global [B, T, H, D].

    Drop-in for ring_attention() when the per-hop score matrix must not
    materialize (long T_local); heads shard over ``head_axis`` (TP), batch
    over ``batch_axis``, sequence over ``seq_axis``.
    """
    return _mapped(ring_flash_attention_local, q, k, v, mesh,
                   seq_axis, batch_axis, head_axis)


def _mapped(local_fn, q, k, v, mesh, seq_axis, batch_axis, head_axis):
    spec = P(batch_axis, seq_axis, head_axis, None)

    def fn(q, k, v):
        return local_fn(q, k, v, seq_axis)

    # check_vma=False: pallas_call's out_shape carries no varying-manual-axes
    # type, which the VMA checker (rightly) rejects; the kernel output is
    # per-shard by construction here.
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
