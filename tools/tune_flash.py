#!/usr/bin/env python3
"""On-chip block-size / precision sweep for the Pallas flash kernel.

Round-4 on-chip validation measured the fused kernel at 9.40 TFLOP/s for
T=16384 and +28% over the unfused fwd+bwd path (VALIDATE_r04.txt) — a real
win but far below the MXU's bf16 ceiling.  A suspected cause is the kernel
casting q/k/v to f32 *before* its two matmuls, which runs the MXU in f32
mode; this sweep measures each (precision, block_q, block_k) variant on
the real chip so the kernel defaults are data, not guesses.

Usage:  python tools/tune_flash.py [--seq 2048 4096 16384] [--json out.json]
Prints one line per variant and a final ranking.  TPU only.
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import time


def bench_fwd(f, args, n=10):
    # fence, not block_until_ready: the axon backend's block can return
    # before execution finishes (see workloads/common.py:fence) — the first
    # sweep reported physically impossible TFLOP/s because of it
    from sofa_tpu.workloads.common import fence

    fence(f(*args))                          # compile + settle
    t0 = time.perf_counter()
    for _ in range(n):
        o = f(*args)
    fence(o)
    return (time.perf_counter() - t0) / n * 1e3


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, nargs="+", default=[2048, 16384])
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--kv_heads", type=int, default=0,
                   help="compact KV heads (GQA); 0 = same as --heads")
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--bwd", action="store_true",
                   help="measure fwd+bwd (grad) instead of forward only")
    p.add_argument("--json", default="")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from sofa_tpu.workloads.flash_pallas import (
        flash_attention, flash_causal_attention)
    from sofa_tpu.workloads.ring_attention import plain_causal_attention

    if jax.default_backend() != "tpu":
        print("tune_flash: requires the real TPU backend", file=sys.stderr)
        return 1

    kvh = args.kv_heads or args.heads
    mode = "fwd+bwd" if args.bwd else "fwd"

    def plain_full(q, k, v):
        rep = args.heads // kvh
        if rep > 1:
            k, v = jnp.repeat(k, rep, 2), jnp.repeat(v, rep, 2)
        return plain_causal_attention(q, k, v)

    def as_loss(f):
        if not args.bwd:
            return jax.jit(f)
        return jax.jit(jax.grad(
            lambda *a: (f(*a).astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2)))

    results = []
    for t in args.seq:
        b = max(1, 2048 * 4 // t)           # keep total tokens comparable
        key = jax.random.PRNGKey(0)
        kq, kk_, kv_ = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, t, args.heads, args.dim), jnp.bfloat16)
        k = jax.random.normal(kk_, (b, t, kvh, args.dim), jnp.bfloat16)
        v = jax.random.normal(kv_, (b, t, kvh, args.dim), jnp.bfloat16)
        # causal flops: 2 matmuls * 2 flops * B*H*T^2*D / 2; bwd ~ 2.5x fwd
        flops = 2 * 2 * b * args.heads * t * t * args.dim / 2
        if args.bwd:
            flops *= 3.5

        try:
            # the unfused path materializes [B,H,T,T] scores — skip where
            # that alone approaches HBM so an OOM can't sink the sweep
            if b * args.heads * t * t * 4 > 8e9:
                raise MemoryError(f"scores would need "
                                  f"{b * args.heads * t * t * 4 / 1e9:.0f} GB")
            ms = bench_fwd(as_loss(plain_full), (q, k, v))
            results.append({"seq": t, "mode": mode, "variant": "plain_xla",
                            "ms": ms, "tflops": flops / (ms / 1e3) / 1e12})
            print(f"T={t:6d} {mode} plain_xla        {ms:7.2f} ms "
                  f"{results[-1]['tflops']:6.1f} TF/s", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"T={t:6d} {mode} plain_xla: SKIP {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:80]}", flush=True)

        if args.bwd:
            variants = [("flash_vjp", lambda *a: flash_causal_attention(*a))]
        else:
            variants = [
                (f"flash_bq{bq}_bk{bk}",
                 lambda q, k, v, bq=bq, bk=bk: flash_attention(
                     q, k, v, block_q=bq, block_k=bk))
                for bq, bk in itertools.product([128, 256, 512],
                                                [128, 256, 512])
                if t % bq == 0 and t % bk == 0]
        for name, fn in variants:
            try:
                ms = bench_fwd(as_loss(fn), (q, k, v))
            except Exception as e:  # noqa: BLE001 — a variant may not fit VMEM
                print(f"T={t:6d} {mode} {name}: FAIL "
                      f"{type(e).__name__}: {str(e).splitlines()[0][:100]}",
                      flush=True)
                continue
            results.append({"seq": t, "mode": mode, "variant": name,
                            "ms": ms, "tflops": flops / (ms / 1e3) / 1e12})
            print(f"T={t:6d} {mode} {name:16s} {ms:7.2f} ms "
                  f"{results[-1]['tflops']:6.1f} TF/s", flush=True)

    print("\nbest per seq:")
    for t in args.seq:
        rs = [r for r in results if r["seq"] == t]
        if not rs:
            print(f"  T={t}: every variant failed or was skipped")
            continue
        best = min(rs, key=lambda r: r["ms"])
        print(f"  T={t}: {best['variant']} {best['ms']:.2f} ms "
              f"({best['tflops']:.1f} TF/s)")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
    return 0


if __name__ == "__main__":
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.exit(main())
