// xplane_scan — columnar XPlane event extractor.
//
// Ingesting pod-scale .xplane.pb captures is bounded by the per-event
// Python loop, not by protobuf decoding (the proto runtime is already
// native).  This helper walks the protobuf wire format directly and emits
// every line's events as flat columnar arrays that numpy can frombuffer,
// so the Python side (sofa_tpu/ingest/native_scan.py) derives per-metadata
// fields once per metadata id and assembles frames vectorized.
//
// Wire schema: sofa_tpu/native/xplane.proto (field numbers mirror
// tensorflow's xplane.proto; unknown fields are skipped, so richer real
// captures parse fine).
//
// Usage: xplane_scan <in.xplane.pb> <out.bin> [derived_stat_names_csv]
//
// Output (little-endian):
//   u32 magic 0x53465831 ("SFX1" LE), u32 version=1
//   records:
//     u8 1 (plane): u32 name_len, name bytes
//     u8 2 (line):  i64 line_id, i64 timestamp_ns, u32 name_len, name
//     u8 3 (events): u64 n, n*i64 metadata_id, n*i64 offset_ps,
//                    n*i64 duration_ps, n*u8 flags
//                    flag bit0: event carries a stat whose metadata name is
//                    in the derived set (Python re-derives those rows from
//                    the proto); bit1: num_occurrences form (aggregated).
//
// Like timebase/sysmon this is built lazily (collectors/native_build.py
// pattern) and everything degrades to the pure-Python path when the
// binary or toolchain is unavailable.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <set>
#include <string>
#include <vector>

namespace {

struct Slice {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  bool done() const { return p >= end; }

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  Slice sub() {  // length-delimited payload
    uint64_t n = varint();
    // Compare against the remaining length, never `p + n > end`: n is a
    // corruption-controlled varint and p + n can overflow (pointer UB),
    // wrap below `end`, and pass the check with wild subsequent reads.
    if (!ok || n > static_cast<uint64_t>(end - p)) {
      ok = false;
      return {end, end};
    }
    Slice s{p, p + n};
    p += n;
    return s;
  }

  void skip(uint32_t wire_type) {
    switch (wire_type) {
      // Clamp fixed-width skips to `end`: advancing p past end would make
      // sub()'s `end - p` remaining-length math go negative (huge as
      // uint64) if a caller raced ahead of the ok flag.
      case 0: varint(); break;
      case 1: if (end - p >= 8) { p += 8; } else { p = end; ok = false; } break;
      case 2: sub(); break;
      case 5: if (end - p >= 4) { p += 4; } else { p = end; ok = false; } break;
      default: ok = false;
    }
  }
};

struct Out {
  FILE* f;
  void raw(const void* d, size_t n) { fwrite(d, 1, n, f); }
  void u8(uint8_t v) { raw(&v, 1); }
  void u32(uint32_t v) { raw(&v, 4); }
  void u64(uint64_t v) { raw(&v, 8); }
  void i64(int64_t v) { raw(&v, 8); }
  void str(const Slice& s) {
    u32(static_cast<uint32_t>(s.end - s.p));
    raw(s.p, s.end - s.p);
  }
};

// One pass over an XEvent: scalar fields + whether any stat's metadata id
// is in the derived set.
void scan_event(Slice ev, const std::set<uint64_t>& derived, int64_t* mid,
                int64_t* off_ps, int64_t* dur_ps, uint8_t* flags) {
  *mid = 0;
  *off_ps = 0;
  *dur_ps = 0;
  *flags = 0;
  while (!ev.done() && ev.ok) {
    uint64_t key = ev.varint();
    uint32_t field = key >> 3, wt = key & 7;
    if (field == 1 && wt == 0) {
      *mid = static_cast<int64_t>(ev.varint());
    } else if (field == 2 && wt == 0) {
      *off_ps = static_cast<int64_t>(ev.varint());
    } else if (field == 3 && wt == 0) {
      *dur_ps = static_cast<int64_t>(ev.varint());
    } else if (field == 5 && wt == 0) {
      ev.varint();
      *flags |= 2;  // aggregated num_occurrences form
    } else if (field == 4 && wt == 2) {
      Slice st = ev.sub();
      while (!st.done() && st.ok) {
        uint64_t skey = st.varint();
        if ((skey >> 3) == 1 && (skey & 7) == 0) {
          if (derived.count(st.varint())) *flags |= 1;
        } else {
          st.skip(skey & 7);
        }
      }
    } else {
      ev.skip(wt);
    }
  }
}

// stat_metadata map entry -> (id, name)
void scan_stat_metadata_entry(Slice entry, const std::set<std::string>& names,
                              std::set<uint64_t>* derived) {
  uint64_t key_id = 0;
  std::string name;
  while (!entry.done() && entry.ok) {
    uint64_t key = entry.varint();
    uint32_t field = key >> 3, wt = key & 7;
    if (field == 1 && wt == 0) {
      key_id = entry.varint();
    } else if (field == 2 && wt == 2) {
      Slice v = entry.sub();  // XStatMetadata
      while (!v.done() && v.ok) {
        uint64_t vkey = v.varint();
        uint32_t vf = vkey >> 3, vwt = vkey & 7;
        if (vf == 2 && vwt == 2) {
          Slice n = v.sub();
          name.assign(reinterpret_cast<const char*>(n.p), n.end - n.p);
        } else {
          v.skip(vwt);
        }
      }
    } else {
      entry.skip(wt);
    }
  }
  if (key_id && names.count(name)) derived->insert(key_id);
}

void scan_line(Slice line, const std::set<uint64_t>& derived, Out* out) {
  int64_t line_id = 0, ts_ns = 0;
  Slice name{nullptr, nullptr};
  std::vector<Slice> events;
  while (!line.done() && line.ok) {
    uint64_t key = line.varint();
    uint32_t field = key >> 3, wt = key & 7;
    if (field == 1 && wt == 0) {
      line_id = static_cast<int64_t>(line.varint());
    } else if (field == 2 && wt == 2) {
      name = line.sub();
    } else if (field == 3 && wt == 0) {
      ts_ns = static_cast<int64_t>(line.varint());
    } else if (field == 4 && wt == 2) {
      events.push_back(line.sub());
    } else {
      line.skip(wt);
    }
  }
  out->u8(2);
  out->i64(line_id);
  out->i64(ts_ns);
  out->str(name);

  size_t n = events.size();
  std::vector<int64_t> mids(n), offs(n), durs(n);
  std::vector<uint8_t> flags(n);
  for (size_t i = 0; i < n; i++) {
    scan_event(events[i], derived, &mids[i], &offs[i], &durs[i], &flags[i]);
  }
  out->u8(3);
  out->u64(n);
  out->raw(mids.data(), n * 8);
  out->raw(offs.data(), n * 8);
  out->raw(durs.data(), n * 8);
  out->raw(flags.data(), n);
}

void scan_plane(Slice plane, const std::set<std::string>& derived_names,
                Out* out) {
  // Pass 1: stat_metadata (serialized order is unspecified; the derived
  // set must exist before events are flagged).
  std::set<uint64_t> derived;
  Slice p1 = plane;
  Slice name{nullptr, nullptr};
  while (!p1.done() && p1.ok) {
    uint64_t key = p1.varint();
    uint32_t field = key >> 3, wt = key & 7;
    if (field == 5 && wt == 2) {
      scan_stat_metadata_entry(p1.sub(), derived_names, &derived);
    } else if (field == 2 && wt == 2) {
      name = p1.sub();
    } else {
      p1.skip(wt);
    }
  }
  out->u8(1);
  out->str(name);
  // Pass 2: lines.
  while (!plane.done() && plane.ok) {
    uint64_t key = plane.varint();
    uint32_t field = key >> 3, wt = key & 7;
    if (field == 3 && wt == 2) {
      scan_line(plane.sub(), derived, out);
    } else {
      plane.skip(wt);
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr,
            "usage: xplane_scan <in.xplane.pb> <out.bin> [derived_csv]\n");
    return 2;
  }
  FILE* in = fopen(argv[1], "rb");
  if (!in) {
    perror("open input");
    return 1;
  }
  fseek(in, 0, SEEK_END);
  long size = ftell(in);
  fseek(in, 0, SEEK_SET);
  std::vector<uint8_t> buf(size > 0 ? size : 0);
  if (size > 0 && fread(buf.data(), 1, size, in) != static_cast<size_t>(size)) {
    fclose(in);
    fprintf(stderr, "short read\n");
    return 1;
  }
  fclose(in);

  std::set<std::string> derived_names;
  if (argc > 3) {
    std::string csv(argv[3]);
    size_t start = 0;
    while (start <= csv.size()) {
      size_t comma = csv.find(',', start);
      if (comma == std::string::npos) comma = csv.size();
      if (comma > start) derived_names.insert(csv.substr(start, comma - start));
      start = comma + 1;
    }
  }

  FILE* fo = fopen(argv[2], "wb");
  if (!fo) {
    perror("open output");
    return 1;
  }
  Out out{fo};
  out.u32(0x31584653u);  // "SFX1" little-endian
  out.u32(1);

  Slice top{buf.data(), buf.data() + buf.size()};
  while (!top.done() && top.ok) {
    uint64_t key = top.varint();
    uint32_t field = key >> 3, wt = key & 7;
    if (field == 1 && wt == 2) {
      scan_plane(top.sub(), derived_names, &out);
    } else {
      top.skip(wt);
    }
  }
  // A short write (disk full) must exit nonzero, or the Python side would
  // parse a silently truncated layout.
  bool write_error = ferror(fo) != 0;
  if (fclose(fo) != 0) write_error = true;
  if (write_error) {
    fprintf(stderr, "output write failed\n");
    return 1;
  }
  if (!top.ok) {
    fprintf(stderr, "malformed protobuf input\n");
    return 1;
  }
  return 0;
}
