"""Pure-Python pcap ingest for DCN/host network traffic.

The reference shells out to `tcpdump -r` and scrapes its text output
(/root/reference/bin/sofa_preprocess.py:1187-1233); parsing the pcap file
directly removes the tcpdump dependency at report time (the capture machine
and the analysis machine are often different).

Supports classic pcap (µs and ns magic, both endians) with link types
Ethernet(1), RAW-IP(101), Linux SLL(113) and SLL2(276) — tcpdump -i any
writes SLL/SLL2.  IPv4 AND IPv6 (ethertype 0x86DD) TCP/UDP packets become
rows — the reference is IPv4-only (sofa_preprocess.py:1187-1233), but
TPU-pod DCN traffic is commonly v6, so dropping it would blank nettrace on
exactly the captures this tool targets:

  payload  = captured original length (bytes)
  pkt_src/dst = packed IPv4 (trace.packed_ip encoding) for v4; interned
             integer id (>= trace.V6_ID_BASE) for v6, with the id ->
             literal mapping written to net_addrs.csv beside the capture
  duration = payload / 128 MB/s — the reference's fixed service-rate model
             (sofa_preprocess.py:178-179), kept for comparability
  name     = "proto sport->dport"
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional

import pandas as pd

from sofa_tpu.trace import empty_frame, make_frame

_NET_MODEL_BYTES_PER_S = 128e6

_MAGICS = {
    0xA1B2C3D4: ("<", 1e-6), 0xD4C3B2A1: (">", 1e-6),
    0xA1B23C4D: ("<", 1e-9), 0x4D3CB2A1: (">", 1e-9),
}


def _ipv4_row(ts: float, data: bytes, orig_len: int, time_base: float) -> Optional[dict]:
    if len(data) < 20 or (data[0] >> 4) != 4:
        return None
    ihl = (data[0] & 0x0F) * 4
    proto = data[9]
    src = ".".join(str(b) for b in data[12:16])
    dst = ".".join(str(b) for b in data[16:20])
    sport = dport = 0
    pname = {6: "tcp", 17: "udp"}.get(proto, str(proto))
    if proto in (6, 17) and len(data) >= ihl + 4:
        sport, dport = struct.unpack("!HH", data[ihl:ihl + 4])
    from sofa_tpu.trace import packed_ip

    return {
        "timestamp": ts - time_base,
        "event": float(dport or proto),
        "duration": orig_len / _NET_MODEL_BYTES_PER_S,
        "payload": orig_len,
        "bandwidth": _NET_MODEL_BYTES_PER_S,
        "pkt_src": packed_ip(src),
        "pkt_dst": packed_ip(dst),
        "name": f"{pname} {src}:{sport}->{dst}:{dport}",
        "device_kind": "net",
    }


# IPv6 extension headers that sit between the fixed header and the L4
# payload; each is (next-header, length) framed except fragment's fixed 8.
_V6_EXT_HEADERS = {0, 43, 44, 51, 60}  # hop-by-hop, routing, frag, AH, dstopt


class _AddrIntern:
    """Literal IPv6 address -> stable integer id (>= V6_ID_BASE), assigned in
    first-seen order so the same capture always produces the same table."""

    def __init__(self):
        self.ids: dict = {}

    def __call__(self, literal: str) -> int:
        from sofa_tpu.trace import V6_ID_BASE

        hit = self.ids.get(literal)
        if hit is None:
            hit = V6_ID_BASE + len(self.ids)
            self.ids[literal] = hit
        return hit


def _ipv6_row(ts: float, data: bytes, orig_len: int, time_base: float,
              intern: _AddrIntern) -> Optional[dict]:
    if len(data) < 40 or (data[0] >> 4) != 6:
        return None
    import ipaddress

    proto = data[6]  # next header
    src = ipaddress.IPv6Address(data[8:24]).compressed
    dst = ipaddress.IPv6Address(data[24:40]).compressed
    # walk extension headers to the transport header (bounded: each hop
    # must advance, and the chain set is closed)
    off = 40
    hops = 0
    while proto in _V6_EXT_HEADERS and len(data) >= off + 8 and hops < 8:
        nxt = data[off]
        if proto == 44:  # fragment: fixed 8 bytes
            ext_len = 8
        elif proto == 51:  # AH counts 32-bit words minus 2
            ext_len = (data[off + 1] + 2) * 4
        else:  # hop-by-hop / routing / dstopts count 8-byte units minus 1
            ext_len = (data[off + 1] + 1) * 8
        proto, off, hops = nxt, off + ext_len, hops + 1
    sport = dport = 0
    pname = {6: "tcp6", 17: "udp6"}.get(proto, f"v6:{proto}")
    if proto in (6, 17) and len(data) >= off + 4:
        sport, dport = struct.unpack("!HH", data[off:off + 4])
    return {
        "timestamp": ts - time_base,
        "event": float(dport or proto),
        "duration": orig_len / _NET_MODEL_BYTES_PER_S,
        "payload": orig_len,
        "bandwidth": _NET_MODEL_BYTES_PER_S,
        "pkt_src": intern(src),
        "pkt_dst": intern(dst),
        "name": f"{pname} [{src}]:{sport}->[{dst}]:{dport}",
        "device_kind": "net",
    }


def parse_pcap_bytes(blob: bytes, time_base: float = 0.0,
                     intern: "Optional[_AddrIntern]" = None) -> pd.DataFrame:
    if len(blob) < 24:
        return empty_frame()
    magic = struct.unpack("<I", blob[:4])[0]
    if magic not in _MAGICS:
        magic = struct.unpack(">I", blob[:4])[0]
    if magic not in _MAGICS:
        return empty_frame()
    endian, tick = _MAGICS[magic]
    linktype = struct.unpack(endian + "I", blob[20:24])[0] & 0x0FFFFFFF
    if intern is None:
        intern = _AddrIntern()
    rows: List[dict] = []
    off = 24
    n = len(blob)
    _IP_ETHERTYPES = (0x0800, 0x86DD)
    while off + 16 <= n:
        ts_sec, ts_frac, incl, orig = struct.unpack(endian + "IIII", blob[off:off + 16])
        off += 16
        if off + incl > n:
            break
        data = blob[off:off + incl]
        off += incl
        ts = ts_sec + ts_frac * tick
        ip: Optional[bytes] = None
        if linktype == 1 and len(data) >= 14:  # Ethernet
            if struct.unpack("!H", data[12:14])[0] in _IP_ETHERTYPES:
                ip = data[14:]
        elif linktype == 101:  # raw IP, version from the first nibble
            ip = data
        elif linktype == 113 and len(data) >= 16:  # Linux cooked (SLL)
            if struct.unpack("!H", data[14:16])[0] in _IP_ETHERTYPES:
                ip = data[16:]
        elif linktype == 276 and len(data) >= 20:  # SLL2
            if struct.unpack("!H", data[0:2])[0] in _IP_ETHERTYPES:
                ip = data[20:]
        if ip is None or not ip:
            continue
        version = ip[0] >> 4
        row = (_ipv4_row(ts, ip, orig, time_base) if version == 4
               else _ipv6_row(ts, ip, orig, time_base, intern)
               if version == 6 else None)
        if row:
            rows.append(row)
    return make_frame(rows) if rows else empty_frame()


def write_net_addrs(intern: _AddrIntern, logdir: str) -> Optional[str]:
    """Persist the interned id->literal table next to the trace CSVs so
    netrank / the comm report can print real IPv6 addresses. No non-v4
    packets -> no file (and consumers degrade to unpack_ip placeholders)."""
    if not intern.ids:
        return None
    out = os.path.join(logdir, "net_addrs.csv")
    # Atomic (durability.atomic_write): read_net_addrs degrades gracefully
    # mid-preprocess, but a crash must never leave a half-written table
    # that LOOKS complete.
    from sofa_tpu.durability import atomic_write

    with atomic_write(out) as f:
        f.write("id,address\n")
        for literal, aid in sorted(intern.ids.items(), key=lambda kv: kv[1]):
            f.write(f"{aid},{literal}\n")
    return out


def ingest_pcap(path: str, time_base: float = 0.0) -> pd.DataFrame:
    """File-level ingest; positively-corrupt captures raise CorruptRawError
    (the preprocess quarantine contract, sofa_tpu/ingest/__init__.py).

    Corrupt means a non-empty file that cannot be a pcap: a truncated
    global header or an unknown magic.  An empty file (tcpdump launched,
    zero packets flushed) and a truncated *trailing packet* (capture
    killed mid-write — every real kill-all epilogue does this) stay benign:
    parse_pcap_bytes keeps whatever decoded.
    """
    if not os.path.isfile(path):
        return empty_frame()
    intern = _AddrIntern()
    with open(path, "rb") as f:
        blob = f.read()
    if blob:
        if len(blob) < 24:
            from sofa_tpu.ingest import CorruptRawError

            raise CorruptRawError(path, "truncated pcap global header "
                                        f"({len(blob)} bytes)")
        magic_le = struct.unpack("<I", blob[:4])[0]
        magic_be = struct.unpack(">I", blob[:4])[0]
        if magic_le not in _MAGICS and magic_be not in _MAGICS:
            from sofa_tpu.ingest import CorruptRawError

            raise CorruptRawError(path, "not a pcap: bad magic "
                                        f"0x{magic_le:08x}")
    df = parse_pcap_bytes(blob, time_base, intern=intern)
    write_net_addrs(intern, os.path.dirname(path) or ".")
    return df
