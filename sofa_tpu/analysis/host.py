"""Host-side analysis passes: CPU samples, mpstat, vmstat, disk, strace.

Reference equivalents: cpu_profile (sofa_analyze.py:694-710), mpstat_profile
(:735-790), vmstat_profile (:712-733), diskstat_profile (:640-692), and the
strace aggregation embedded in sofa_analyze (:898-977).
"""

from __future__ import annotations

import pandas as pd

from sofa_tpu.analysis.features import Features
from sofa_tpu.analysis.registry import analysis_pass
from sofa_tpu.printing import print_title


@analysis_pass(
    name="cpu_profile", order=20,
    reads_frames=("cputrace",),
    reads_columns=("duration", "deviceId", "name"),
    provides_features=("cpu_samples", "cpu_core*_exec_time"),
    provides_artifacts=("cpu_top.csv",),
    after=("spotlight",),
)
def cpu_profile(frames, cfg, features: Features) -> None:
    df = frames.get("cputrace")
    if df is None or df.empty:
        return
    roi = _roi(df, cfg)
    features.add("cpu_samples", len(roi))
    per_core = roi.groupby("deviceId")["duration"].sum()
    for core, total in per_core.items():
        features.add(f"cpu_core{core}_exec_time", total)
    top = (
        roi.groupby("name")["duration"]
        .agg(["sum", "count"])
        .sort_values("sum", ascending=False)
        .head(20)
    )
    if cfg.verbose and not top.empty:
        print_title("Top-20 hottest CPU symbols")
        print(top.to_string())
    top.to_csv(cfg.path("cpu_top.csv"))


@analysis_pass(
    name="mpstat_profile", order=30,
    reads_frames=("mpstat",),
    reads_columns=("duration", "deviceId", "name", "event"),
    provides_features=("num_cores", "mpstat_*_pct", "mpstat_*_time",
                       "cpu_util"),
)
def mpstat_profile(frames, cfg, features: Features) -> None:
    df = frames.get("mpstat")
    if df is None or df.empty:
        return
    cores = df[df["deviceId"] >= 0]
    num_cores = cores["deviceId"].nunique() if not cores.empty else 0
    features.add("num_cores", num_cores)
    agg = df[df["deviceId"] == -1]
    if agg.empty:
        return
    # Mean percentage and absolute busy time per metric over the run.
    for metric in ("usr", "sys", "iow", "irq", "idl"):
        rows = agg[agg["name"] == metric]
        if rows.empty:
            continue
        pct = float(rows["event"].mean())
        seconds = float((rows["event"] / 100.0 * rows["duration"]).sum())
        features.add(f"mpstat_{metric}_pct", pct)
        features.add(f"mpstat_{metric}_time", seconds)
    usr = features.get("mpstat_usr_pct") or 0.0
    sys_ = features.get("mpstat_sys_pct") or 0.0
    features.add("cpu_util", (usr + sys_) / 100.0)


@analysis_pass(
    name="vmstat_profile", order=40,
    reads_frames=("vmstat",),
    reads_columns=("name", "event"),
    provides_features=("vmstat_mean_*",),
)
def vmstat_profile(frames, cfg, features: Features) -> None:
    df = frames.get("vmstat")
    if df is None or df.empty:
        return
    for metric in ("bi", "bo", "cs", "in"):
        rows = df[df["name"] == f"vmstat.{metric}"]
        if not rows.empty:
            features.add(f"vmstat_mean_{metric}", float(rows["event"].mean()))


@analysis_pass(
    name="diskstat_profile", order=50,
    reads_frames=("diskstat",),
    reads_columns=("timestamp", "deviceId", "name", "event", "payload"),
    provides_features=("disk_*_r_bw_mean", "disk_*_w_bw_mean",
                       "disk_total_bytes"),
    provides_artifacts=("disk_summary.csv",),
)
def diskstat_profile(frames, cfg, features: Features) -> None:
    df = frames.get("diskstat")
    if df is None or df.empty:
        return
    table = []
    for (name,), rows in df.groupby(["name"]):
        q = rows["event"].quantile([0.25, 0.5, 0.75])
        table.append(
            {
                "metric": name,
                "mean": rows["event"].mean(),
                "q25": q.loc[0.25],
                "median": q.loc[0.5],
                "q75": q.loc[0.75],
                "max": rows["event"].max(),
            }
        )
        dev, _, metric = name.partition(".")
        if metric in ("r_bw", "w_bw"):
            features.add(f"disk_{dev}_{metric}_mean", float(rows["event"].mean()))
    summary = pd.DataFrame(table)
    summary.to_csv(cfg.path("disk_summary.csv"), index=False)
    total_bytes = df.drop_duplicates(subset=["timestamp", "deviceId"])["payload"].sum()
    features.add("disk_total_bytes", float(total_bytes))


@analysis_pass(
    name="blktrace_latency_profile", order=60,
    reads_frames=("blktrace",),
    reads_columns=("timestamp", "duration", "name", "payload"),
    provides_features=("blktrace_*",),
)
def blktrace_latency_profile(frames, cfg, features: Features) -> None:
    """Per-IO D->C latency quartiles + totals (the reference's btt-based
    pass, sofa_analyze.py:596-638, computed from our own event pairing)."""
    df = frames.get("blktrace")
    if df is None or df.empty:
        return
    lat = df["duration"]
    q = lat.quantile([0.25, 0.5, 0.75])
    features.add("blktrace_ios", len(df))
    features.add("blktrace_latency_q1", float(q.loc[0.25]))
    features.add("blktrace_latency_median", float(q.loc[0.5]))
    features.add("blktrace_latency_q3", float(q.loc[0.75]))
    features.add("blktrace_latency_max", float(lat.max()))
    features.add("blktrace_total_bytes", float(df["payload"].sum()))
    reads = df[df["name"].str.startswith("blk_r")]
    writes = df[df["name"].str.startswith("blk_w")]
    features.add("blktrace_read_ios", len(reads))
    features.add("blktrace_write_ios", len(writes))
    span = float((df["timestamp"] + df["duration"]).max()
                 - df["timestamp"].min())
    if span > 0:
        features.add("blktrace_iops", len(df) / span)
        features.add("blktrace_bandwidth", float(df["payload"].sum()) / span)


@analysis_pass(
    name="strace_profile", order=70,
    reads_frames=("strace",),
    reads_columns=("duration", "name"),
    provides_features=("syscall_total_time", "syscall_count"),
    provides_artifacts=("strace_top.csv",),
)
def strace_profile(frames, cfg, features: Features) -> None:
    df = frames.get("strace")
    if df is None or df.empty:
        return
    df = df.assign(call=df["name"].str.partition("(")[0])
    top = (
        df.groupby("call")["duration"]
        .agg(["sum", "count"])
        .sort_values("sum", ascending=False)
    )
    features.add("syscall_total_time", float(df["duration"].sum()))
    features.add("syscall_count", len(df))
    top.head(20).to_csv(cfg.path("strace_top.csv"))


@analysis_pass(
    name="pystacks_profile", order=80,
    reads_frames=("pystacks",),
    reads_columns=("timestamp", "name"),
    provides_features=("py_samples",),
    provides_artifacts=("pystacks_top.csv",),
)
def pystacks_profile(frames, cfg, features: Features) -> None:
    df = frames.get("pystacks")
    if df is None or df.empty:
        return
    features.add("py_samples", len(df))
    top = df.groupby("name")["timestamp"].count().sort_values(ascending=False)
    top.head(20).to_csv(cfg.path("pystacks_top.csv"))


def _roi(df: pd.DataFrame, cfg) -> pd.DataFrame:
    from sofa_tpu.trace import roi_clip

    return roi_clip(df, cfg)
