"""The builtin fleet passes (docs/ANALYSIS.md "Writing a fleet pass").

Three first folds over the archive's column families, all chunk-aligned
(``fleet.fold_chunks``): each keeps one small partial per index chunk —
a pure function of that chunk's bytes, computed with Arrow/numpy
kernels, never a per-row pandas round-trip over the archive — and
renders the report section by combining partials with ``math.fsum``
(exactly rounded, so a warm fold over the delta chunks is
byte-identical to a cold recompute).

* ``swarm_regress``  — cross-run regression mining over the swarm/
  cluster feature families: per-name running stats, z-score of the
  newest sample against fleet history, co-regressing names grouped by
  the run that moved them.
* ``regress_attrib`` — attribution of the fleet's SoL-distance
  regression mass over the label / host / device (config) axes: which
  axis value's mean most exceeds the fleet mean.
* ``sol_headroom``   — fleet-wide speed-of-light headroom: per device
  class totals plus the global worst-offender ranking the fleet board
  renders (provenance joined at render time, O(result)).
"""

from __future__ import annotations

import math
from fnmatch import fnmatchcase
from typing import Dict, List

from sofa_tpu.analysis.fleet import fleet_pass, fold_chunks, parts_in_order

#: Feature-name patterns each fold tracks — plain literals so the
#: report is self-describing about what was mined.
SWARM_PATTERNS = ("swarm*", "cluster*")
SOL_PATTERN = "tpu*_sol_distance"
#: Minimum fleet history and z-score for a swarm regression verdict.
SWARM_MIN_SAMPLES = 8
SWARM_Z_THRESHOLD = 2.0
#: Worst-offender rows kept per chunk partial and in the final ranking.
SOL_TOP_K = 20


def _match_filter(tbl, patterns):
    """Rows whose ``name`` matches any pattern: fnmatch the UNIQUE names
    (dozens), then one is_in kernel over the rows — the `_offender_page`
    discipline, no per-row python."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if not tbl.num_rows:
        return tbl
    names = pc.unique(tbl["name"]).to_pylist()
    keep = [n for n in names
            if any(fnmatchcase(n, p) for p in patterns)]
    return tbl.filter(pc.is_in(tbl["name"],
                               value_set=pa.array(keep or [""])))


@fleet_pass(name="swarm_regress", order=10,
            reads_frames=("features",),
            reads_columns=("features.run", "features.name",
                           "features.value", "features.timestamp"),
            provides_features=("fleet_swarm_regressions",))
def swarm_regress(state, tables, ctx, features):
    """Cross-run swarm-cluster regression mining: per tracked feature
    name, does the newest sample sit more than ``SWARM_Z_THRESHOLD``
    standard deviations above the fleet's history?  Names regressing off
    the same run are reported together — the "which kernel families
    moved together" view."""
    import numpy as np

    def partial(chunk):
        sub = _match_filter(chunk, SWARM_PATTERNS)
        names: Dict[str, list] = {}
        if sub.num_rows:
            nm = sub["name"].to_numpy(zero_copy_only=False)
            vals = sub["value"].to_numpy()
            runs = sub["run"].to_numpy(zero_copy_only=False)
            ts = sub["timestamp"].to_numpy()
            for name in sorted(set(nm.tolist())):
                mask = nm == name
                mv = vals[mask]
                last = int(np.nonzero(mask)[0][-1])
                names[name] = [int(mv.size), float(np.sum(mv)),
                               float(np.sum(mv * mv)),
                               str(runs[last]), float(vals[last]),
                               float(ts[last])]
        return {"names": names}

    st = state or {"chunks": {}}
    fold_chunks(st["chunks"], tables["features"],
                ctx.base.get("features", 0), ctx.chunk_rows, partial)

    ordered = parts_in_order(st["chunks"])
    totals: Dict[str, dict] = {}
    for part in ordered:
        for name, (n, s, sq, run, last, last_t) in part["names"].items():
            t = totals.setdefault(name, {"ns": [], "sums": [], "sqs": []})
            t["ns"].append(n)
            t["sums"].append(s)
            t["sqs"].append(sq)
            # the newest chunk containing the name wins the "last" slot
            t["last"] = [run, last, last_t]
    regressions = []
    for name, t in totals.items():
        n = int(sum(t["ns"]))
        mean = math.fsum(t["sums"]) / n if n else 0.0
        var = max(math.fsum(t["sqs"]) / n - mean * mean, 0.0) if n else 0.0
        std = math.sqrt(var)
        run, last, last_t = t["last"]
        z = (last - mean) / std if std > 0 else 0.0
        if n >= SWARM_MIN_SAMPLES and z > SWARM_Z_THRESHOLD \
                and last > mean:
            regressions.append({"name": name, "z": z, "n": n,
                                "mean": mean, "last_value": last,
                                "last_run": run, "last_t": last_t})
    regressions.sort(key=lambda r: (-r["z"], r["name"]))
    by_run: Dict[str, List[str]] = {}
    for r in regressions:
        by_run.setdefault(r["last_run"], []).append(r["name"])
    clusters = [{"run": run, "names": names}
                for run, names in sorted(by_run.items())
                if len(names) >= 2]
    features.add("fleet_swarm_regressions", float(len(regressions)))
    return {"state": st,
            "report": {"patterns": list(SWARM_PATTERNS),
                       "tracked": len(totals),
                       "regressions": regressions,
                       "clusters": clusters}}


@fleet_pass(name="regress_attrib", order=20,
            reads_frames=("features", "runs"),
            reads_columns=("features.run", "features.name",
                           "features.value", "runs.run", "runs.label",
                           "runs.host"),
            provides_features=("fleet_attrib_worst_excess",))
def regress_attrib(state, tables, ctx, features):
    """Regression attribution over the label / host / device (config)
    axes: per axis value, how far the mean SoL distance sits above the
    fleet mean.  The per-chunk join resolves each run's label/host via
    ``ctx.runs_meta`` at fold time — a re-ingest that CHANGES a run's
    axes re-attributes its old rows on the next full recompute (the
    documented fold-time-lookup caveat); the device axis is pure (it is
    the feature name's prefix)."""
    import numpy as np

    def partial(chunk):
        sub = _match_filter(chunk, (SOL_PATTERN,))
        axes: Dict[str, List[float]] = {}
        if sub.num_rows:
            runs = sub["run"].to_numpy(zero_copy_only=False)
            vals = sub["value"].to_numpy()
            nm = sub["name"].to_numpy(zero_copy_only=False)
            # per-UNIQUE python work fanned back out through np.unique's
            # inverse index — the per-row dict gets and str splits this
            # replaces were the fold's hot spot at catalog scale
            uruns, rinv = np.unique(runs, return_inverse=True)
            unm, ninv = np.unique(nm, return_inverse=True)
            meta = ctx.runs_meta(set(uruns.tolist()))
            keys = {
                "label": np.array([str((meta.get(r) or {})
                                       .get("label") or "")
                                   for r in uruns.tolist()],
                                  dtype=object)[rinv],
                "host": np.array([str((meta.get(r) or {})
                                      .get("host") or "")
                                  for r in uruns.tolist()],
                                 dtype=object)[rinv],
                "device": np.array([n.split("_", 1)[0]
                                    for n in unm.tolist()],
                                   dtype=object)[ninv],
            }
            axes["_all"] = [float(vals.size), float(np.sum(vals))]
            for axis, col in keys.items():
                # integer-code masks: np.unique's sorted uniques are the
                # old sorted(set(...)) walk, and ``codes == k`` selects
                # the same rows in the same order, so np.sum reproduces
                # the object-compare path's floats exactly
                uvals, codes = np.unique(col, return_inverse=True)
                for k, value in enumerate(uvals.tolist()):
                    mv = vals[codes == k]
                    axes[f"{axis}:{value}"] = [float(mv.size),
                                               float(np.sum(mv))]
        return {"axes": axes}

    st = state or {"chunks": {}}
    fold_chunks(st["chunks"], tables["features"],
                ctx.base.get("features", 0), ctx.chunk_rows, partial)

    sums: Dict[str, dict] = {}
    for part in parts_in_order(st["chunks"]):
        for key, (n, s) in part["axes"].items():
            t = sums.setdefault(key, {"ns": [], "sums": []})
            t["ns"].append(n)
            t["sums"].append(s)

    def mean_of(key):
        t = sums.get(key)
        if not t:
            return 0, 0.0
        n = int(sum(t["ns"]))
        return n, (math.fsum(t["sums"]) / n if n else 0.0)

    n_all, mean_all = mean_of("_all")
    axes_report: Dict[str, list] = {"label": [], "host": [], "device": []}
    worst = 0.0
    for key in sums:
        axis, _, value = key.partition(":")
        if axis not in axes_report:
            continue
        n, mean = mean_of(key)
        excess = mean - mean_all
        worst = max(worst, excess)
        axes_report[axis].append({"value": value, "n": n, "mean": mean,
                                  "excess": excess})
    for axis in axes_report:
        axes_report[axis].sort(key=lambda r: (-r["excess"], r["value"]))
        del axes_report[axis][10:]
    features.add("fleet_attrib_worst_excess", worst)
    return {"state": st,
            "report": {"metric": SOL_PATTERN,
                       "overall": {"n": n_all, "mean": mean_all},
                       "axes": axes_report}}


@fleet_pass(name="sol_headroom", order=30,
            reads_frames=("features", "runs"),
            reads_columns=("features.run", "features.name",
                           "features.value", "runs.run", "runs.label",
                           "runs.host", "runs.timestamp"),
            provides_features=("fleet_sol_*",))
def sol_headroom(state, tables, ctx, features):
    """Fleet-wide speed-of-light headroom: per device class (the
    ``tpu<N>_sol_distance`` family name), how far the fleet runs from
    the hardware's speed of light — plus the global worst-offender
    ranking `board/fleet.html` renders.  Offender provenance joins at
    RENDER time against the current runs family (byte-identity safe and
    O(result))."""
    import numpy as np

    def partial(chunk):
        sub = _match_filter(chunk, (SOL_PATTERN,))
        classes: Dict[str, list] = {}
        top: List[list] = []
        if sub.num_rows:
            nm = sub["name"].to_numpy(zero_copy_only=False)
            vals = sub["value"].to_numpy()
            runs = sub["run"].to_numpy(zero_copy_only=False)
            for name in sorted(set(nm.tolist())):
                mv = vals[nm == name]
                classes[name] = [int(mv.size), float(np.sum(mv)),
                                 float(np.max(mv))]
            # np.partition narrows to the boundary-tie candidates; only
            # those few materialize as python rows for the exact
            # (-value, run, name) ordering — no per-row python over the
            # whole chunk
            k = min(SOL_TOP_K, int(vals.size))
            kth = np.partition(vals, vals.size - k)[vals.size - k]
            cand = np.nonzero(vals >= kth)[0]
            top = sorted(([float(vals[i]), str(runs[i]), str(nm[i])]
                          for i in cand),
                         key=lambda r: (-r[0], r[1], r[2]))[:SOL_TOP_K]
        return {"classes": classes, "top": top}

    st = state or {"chunks": {}}
    fold_chunks(st["chunks"], tables["features"],
                ctx.base.get("features", 0), ctx.chunk_rows, partial)

    ordered = parts_in_order(st["chunks"])
    classes: Dict[str, dict] = {}
    merged: List[list] = []
    for part in ordered:
        for name, (n, s, mx) in part["classes"].items():
            t = classes.setdefault(name, {"ns": [], "sums": [], "max": mx})
            t["ns"].append(n)
            t["sums"].append(s)
            t["max"] = max(t["max"], mx)
        merged.extend(part["top"])
    merged.sort(key=lambda r: (-r[0], r[1], r[2]))
    merged = merged[:SOL_TOP_K]
    meta = ctx.runs_meta({run for _v, run, _n in merged})
    worst_rows = [{"run": run, "name": name, "value": value,
                   "host": str((meta.get(run) or {}).get("host") or ""),
                   "label": str((meta.get(run) or {}).get("label") or ""),
                   "t": float((meta.get(run) or {}).get("timestamp")
                              or 0.0)}
                  for value, run, name in merged]
    class_report = {}
    total_n, total_sums = [], []
    for name, t in sorted(classes.items()):
        n = int(sum(t["ns"]))
        class_report[name] = {"n": n,
                              "mean": (math.fsum(t["sums"]) / n
                                       if n else 0.0),
                              "worst": t["max"]}
        total_n.append(n)
        total_sums.extend(t["sums"])
    n_all = int(sum(total_n))
    features.add("fleet_sol_classes", float(len(class_report)))
    features.add("fleet_sol_mean",
                 math.fsum(total_sums) / n_all if n_all else 0.0)
    features.add("fleet_sol_worst",
                 worst_rows[0]["value"] if worst_rows else 0.0)
    return {"state": st,
            "report": {"pattern": SOL_PATTERN,
                       "classes": class_report,
                       "worst": worst_rows}}
