"""Disk/IO churner: writes and re-reads a few hundred MB through a temp file.

Host-only target for the diskstat/vmstat/blktrace path — the equivalent of
the reference smoke workload `dd if=/dev/zero of=dummy.out bs=100M count=10`
(BASELINE config #1), kept in Python so it runs identically everywhere.
"""

import os
import tempfile


def main(mb: int = 256, block_kb: int = 1024):
    block = os.urandom(block_kb * 1024)
    with tempfile.NamedTemporaryFile(dir=".", suffix=".sofa_io") as f:
        for _ in range(mb * 1024 // block_kb):
            f.write(block)
        f.flush()
        os.fsync(f.fileno())
        f.seek(0)
        read = 0
        while True:
            chunk = f.read(block_kb * 1024)
            if not chunk:
                break
            read += len(chunk)
    print(f"wrote+read {mb} MiB (read back {read >> 20} MiB)")


if __name__ == "__main__":
    main()
