"""HBM attribution snapshot (pprof) -> allocation-site table.

Decodes the gzipped pprof ``Profile`` that ``jax.profiler
.device_memory_profile()`` emits (captured at the observed occupancy peak by
collectors/tpumon.py, or at exit as a fallback) into a flat DataFrame:

    device | kind | count | bytes | site | stack

One row per pprof sample.  ``site`` is the innermost *user-attributable*
frame (the profiler's leaf frames are jax-internal dispatch like
``_pjit_call_impl_python``; OOM debugging wants the caller's line), and
``stack`` is the full leaf-first ``;``-joined frame path for flame-style
drill-down.

No reference analogue: nvsmi gave the reference one used-MB total per GPU
(sofa_record.py:300-310).  Attribution by allocation site is only possible
because the TPU runtime is in-process with the allocator's Python callers.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import Optional, Tuple

import pandas as pd

# Frames below this module prefix set are runtime plumbing, not user code;
# `site` skips past them to the first frame that is neither.
_RUNTIME_FRAME_HINTS = (
    "_pjit", "pjit", "cache_miss", "reraise_with_filtered_traceback",
    "backend_compile", "wrapper", "__call__", "_python_pjit_helper",
    "call_impl", "apply_primitive", "lower", "compile", "_cpp_pjit",
    # eager-dispatch leaves (live_arrays stacks, seen on-chip 2026-07-31:
    # without these the top "site" is jax plumbing like
    # EvalTrace.process_primitive, not the allocating user line)
    "process_primitive", "ExecuteReplicated", "annotate_function",
    "process_call", "_device_put", "device_put",
)


def _site_of(frames: list) -> str:
    for name in frames:
        if not any(h in name for h in _RUNTIME_FRAME_HINTS):
            return name
    return frames[0] if frames else "(unknown)"


def parse_memprof(path: str) -> pd.DataFrame:
    """Decode one ``memprof.pb.gz`` into the allocation-site DataFrame."""
    from sofa_tpu.ingest import memprof_pb2

    with open(path, "rb") as f:
        blob = f.read()
    try:
        blob = gzip.decompress(blob)
    except OSError:
        pass  # already raw proto (synthetic fixtures)
    prof = memprof_pb2.Profile()
    prof.ParseFromString(blob)

    st = list(prof.string_table)

    def s(i: int) -> str:
        return st[i] if 0 <= i < len(st) else ""

    # Column order of the sample values: find (allocations,count) and
    # (space,bytes); fall back positionally for foreign producers.
    count_i, bytes_i = 0, min(1, max(0, len(prof.sample_type) - 1))
    for i, vt in enumerate(prof.sample_type):
        unit = s(vt.unit)
        if unit == "count":
            count_i = i
        elif unit == "bytes":
            bytes_i = i

    fn_name = {f.id: s(f.name) for f in prof.function}
    loc_frames = {}
    for loc in prof.location:
        loc_frames[loc.id] = [fn_name.get(ln.function_id, "")
                              for ln in loc.line] or [f"0x{loc.address:x}"]

    rows = []
    for sample in prof.sample:
        frames = []
        for lid in sample.location_id:  # leaf first, per pprof convention
            frames.extend(loc_frames.get(lid, []))
        labels = {}
        for lb in sample.label:
            labels[s(lb.key)] = s(lb.str) if lb.str else lb.num
        values = list(sample.value)

        def v(i: int) -> int:
            return int(values[i]) if i < len(values) else 0

        rows.append({
            "device": str(labels.get("device", "")),
            "kind": str(labels.get("kind", "buffer")),
            "count": v(count_i),
            "bytes": v(bytes_i),
            "site": _site_of(frames),
            "stack": ";".join(frames),
        })
    return pd.DataFrame(
        rows, columns=["device", "kind", "count", "bytes", "site", "stack"])


def load_memprof(logdir: str) -> Tuple[Optional[pd.DataFrame], dict]:
    """(samples, meta) for a logdir, or (None, {}) when never captured.

    meta is the sidecar collectors/tpumon.py writes: unix_ns, trigger
    ("peak" | "final"), total_bytes at trigger time.
    """
    path = os.path.join(logdir, "memprof.pb.gz")
    if not os.path.isfile(path):
        return None, {}
    df = parse_memprof(path)
    meta = {}
    try:
        with open(path + ".meta.json") as f:
            meta = json.load(f)
    except (OSError, ValueError):
        pass
    return df, meta


def aggregate_sites(df: pd.DataFrame, top_k: int = 30) -> pd.DataFrame:
    """Top allocation sites by held bytes, with per-site share of total."""
    if df is None or df.empty:
        return pd.DataFrame(
            columns=["site", "kind", "bytes", "count", "share"])
    g = (df.groupby(["site", "kind"], as_index=False)
           .agg(bytes=("bytes", "sum"), count=("count", "sum"))
           .sort_values("bytes", ascending=False))
    total = float(g["bytes"].sum()) or 1.0
    g["share"] = g["bytes"] / total
    return g.head(top_k).reset_index(drop=True)
