"""sofa-lint command line (backs ``tools/sofa_lint.py`` and ``sofa lint``).

Exit-code contract (stable for CI):

  0  clean — no findings outside the baseline
  1  new findings (printed one per line as ``file:line: RULE [sev] msg``)
  2  internal error (bad baseline file, engine crash)

``--update-baseline`` regenerates ``lint_baseline.json`` from the current
findings (expired entries drop out); ``--json`` emits the machine-readable
report bench.py's evidence extras consume.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from sofa_tpu.lint.baseline import (
    Baseline,
    fingerprint_findings,
    locate_baseline,
)
from sofa_tpu.lint.core import lint_paths
from sofa_tpu.lint.rules import default_rules


def _default_paths() -> List[str]:
    """The sofa_tpu package of THIS checkout (works from any cwd)."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sofa-lint",
        description="AST-based checker for sofa_tpu's own runtime "
                    "contracts (see docs/STATIC_ANALYSIS.md)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the sofa_tpu "
                        "package)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: nearest lint_baseline.json "
                        "up from the first path)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, grandfathered or not")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from the current findings "
                        "(expired entries drop out) and exit 0")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--base", default=None,
                   help="directory findings' relative paths (and baseline "
                        "fingerprints) are anchored to (default: the "
                        "directory containing the baseline file)")
    return p


def run_lint(argv: Optional[List[str]] = None) -> int:
    try:
        return _run(build_parser().parse_args(argv))
    except SystemExit:
        raise
    except Exception as e:  # sofa-lint: disable=SL002 — exit-code contract: internal errors become rc 2 on stderr
        print(f"sofa-lint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2


def _run(args: argparse.Namespace) -> int:
    paths = args.paths or _default_paths()
    baseline_path = args.baseline or locate_baseline(paths[0])
    base = args.base or os.path.dirname(os.path.abspath(baseline_path))
    findings = lint_paths(paths, default_rules(), base=base)

    def line_text_for(f):
        path = f.file if os.path.isabs(f.file) else os.path.join(base, f.file)
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                lines = fh.read().splitlines()
            return lines[f.line - 1] if 1 <= f.line <= len(lines) else ""
        except OSError:
            return ""

    fingerprinted = fingerprint_findings(findings, line_text_for)

    if args.update_baseline:
        Baseline.write(baseline_path, fingerprinted)
        print(f"sofa-lint: baseline rewritten with {len(fingerprinted)} "
              f"entr{'y' if len(fingerprinted) == 1 else 'ies'} "
              f"-> {baseline_path}")
        return 0

    if args.no_baseline:
        new, old = findings, []
    else:
        baseline = Baseline.load(baseline_path)
        new, old = baseline.split(fingerprinted)
    # Deterministic report order — (rule, file, line) in BOTH output
    # modes, so CI diffs of findings are stable across runs and sort
    # tweaks in the engine can never churn a committed report.
    new = sorted(new, key=lambda f: (f.rule_id, f.file, f.line,
                                     f.message))

    if args.as_json:
        print(json.dumps({
            "new": [f.to_dict() for f in new],
            "baselined": len(old),
            "total": len(findings),
            "baseline": baseline_path if not args.no_baseline else None,
        }, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.render())
    tail = f", {len(old)} baselined" if old else ""
    if new:
        print(f"sofa-lint: {len(new)} new finding(s){tail} — fix, suppress "
              "inline with a justification, or (pre-existing only) "
              "--update-baseline")
        return 1
    print(f"sofa-lint: clean ({len(findings)} finding(s) total{tail})")
    return 0


if __name__ == "__main__":
    sys.exit(run_lint())
