"""bench.py retry/budget machinery — the driver-facing artifact that must
outlast multi-hour chip-tunnel outages (VERDICT r2 next #1).

The real chip path can't run in CI; these tests drive the budget loop with
a fake clock and a scripted preflight, proving: capped exponential backoff,
budget exhaustion raising the LAST observed error, the validate-checklist
hook firing exactly once in the first healthy window, and round-tag /
checklist-log plumbing.
"""

import pytest

import bench  # repo root is on sys.path via tests/conftest.py


class FakeTime:
    """Deterministic module stand-in for bench's `time` global."""

    def __init__(self):
        self.now = 1000.0
        self.sleeps = []

    def monotonic(self):
        return self.now

    def sleep(self, s):
        self.sleeps.append(s)
        self.now += s

    def time(self):
        return self.now

    def perf_counter(self):
        return self.now

    def strftime(self, fmt, t=None):
        return "2026-01-01T00:00:00Z"

    def gmtime(self):
        return None


@pytest.fixture(autouse=True)
def fresh_bench_state(monkeypatch):
    """bench._state is a module global the retry loop and signal handler
    mutate; give every test its own copy so no ordering can leak a stale
    provisional/done flag (or an unexpected provisional stdout line) into
    another test."""
    monkeypatch.setattr(bench, "_state",
                        {"phase": "starting", "done": False,
                         "provisional": False})


@pytest.fixture
def fake_time(monkeypatch):
    ft = FakeTime()
    monkeypatch.setattr(bench, "time", ft)
    return ft


def test_next_round_tag(tmp_path):
    assert bench._next_round_tag(str(tmp_path)) == "r01"
    (tmp_path / "BENCH_r01.json").write_text("{}")
    (tmp_path / "BENCH_r02.json").write_text("{}")
    assert bench._next_round_tag(str(tmp_path)) == "r03"
    (tmp_path / "BENCH_r10.json").write_text("{}")
    assert bench._next_round_tag(str(tmp_path)) == "r11"


def test_init_backend_outlasts_outage(fake_time, monkeypatch):
    """Preflight fails for many attempts (a dead tunnel), then recovers;
    the budget loop must still be waiting — with backoff capped at 150 s —
    and must run the validate hook exactly once, in the healthy window."""
    outcomes = ["down"] * 10 + [None, None]  # heal at attempt 11
    calls = {"validate": 0}
    monkeypatch.setattr(bench, "_probed_backend", "tpu")

    def fake_preflight(timeout_s=60.0):
        return outcomes.pop(0) if outcomes else None

    monkeypatch.setattr(bench, "_preflight", fake_preflight)
    monkeypatch.setattr(bench, "_run_validate_checklist",
                        lambda root=None: calls.__setitem__(
                            "validate", calls["validate"] + 1) or True)
    monkeypatch.setattr(bench, "_log_chip_holders", lambda: None)
    monkeypatch.setattr(bench, "_with_timeout",
                        lambda fn, timeout_s: ["fake_device"])
    devs = bench._init_backend(budget_s=3600.0)
    assert devs == ["fake_device"]
    assert calls["validate"] == 1
    # capped exponential backoff: grows by 1.7x, never past 150 s
    assert fake_time.sleeps[0] == pytest.approx(15.0)
    assert fake_time.sleeps[1] == pytest.approx(15.0 * 1.7)
    assert max(fake_time.sleeps) <= 150.0
    assert len(fake_time.sleeps) == 10


def test_init_backend_budget_exhausted(fake_time, monkeypatch):
    """A tunnel that never heals exhausts the budget and raises the LAST
    observed reason — not a generic message, not an infinite loop."""
    monkeypatch.setattr(bench, "_preflight",
                        lambda timeout_s=60.0: "tunnel still down")
    monkeypatch.setattr(bench, "_log_chip_holders", lambda: None)
    with pytest.raises(RuntimeError, match="tunnel still down"):
        bench._init_backend(budget_s=300.0)
    # it kept retrying until the budget ran out, no longer
    assert sum(fake_time.sleeps) <= 300.0 + 150.0
    assert len(fake_time.sleeps) >= 2


def test_init_backend_env_budget(fake_time, monkeypatch):
    monkeypatch.setenv("SOFA_BENCH_RETRY_BUDGET_S", "42")
    monkeypatch.setattr(bench, "_preflight", lambda timeout_s=60.0: "down")
    monkeypatch.setattr(bench, "_log_chip_holders", lambda: None)
    with pytest.raises(RuntimeError):
        bench._init_backend()
    assert sum(fake_time.sleeps) <= 42.0 + 15.0


def test_validate_checklist_writes_round_log(tmp_path, monkeypatch):
    """In a healthy TPU window the checklist output lands in
    VALIDATE_r<next>.txt next to the BENCH artifacts, with rc recorded."""
    import subprocess

    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "validate_tpu.py").write_text("# stub\n")
    (tmp_path / "BENCH_r02.json").write_text("{}")
    monkeypatch.setattr(bench, "_probed_backend", "tpu")

    def fake_run(argv, **kw):
        assert argv[1].endswith("validate_tpu.py")
        assert "--capture-fixture" in argv
        return subprocess.CompletedProcess(argv, 0, stdout="PASS all\n",
                                           stderr="")

    monkeypatch.setattr(subprocess, "run", fake_run)
    assert bench._run_validate_checklist(root=str(tmp_path)) is True
    out = (tmp_path / "VALIDATE_r03.txt").read_text()
    assert "rc=0" in out and "PASS all" in out


def test_emit_includes_p_value(capsys):
    """Paired-run significance mirrors the reference's t-test
    (framework_eval.py:208-215) as an extra JSON key the driver can ignore."""
    import json

    bench._emit(1.5, p_value=0.04231)
    out = json.loads(capsys.readouterr().out)
    assert out["p_value"] == 0.0423
    assert out["vs_baseline"] == 0.3
    bench._emit(None, error="x")
    out = json.loads(capsys.readouterr().out)
    assert "p_value" not in out


def test_overhead_budget_smoke(tmp_path, monkeypatch):
    """tools/overhead_budget.py runs end to end on CPU: every config row
    present, marginals computed, markdown written (the real numbers come
    from the validate_tpu run on chip)."""
    import os

    monkeypatch.syspath_prepend(os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import overhead_budget as mod

    out = tmp_path / "OVERHEAD_BUDGET.md"
    table = mod.run_budget(steps=2, reps=1, max_reps=1, out=str(out))
    assert out.is_file() and out.read_text() == table
    assert "baseline" in table
    for row in ("procmon @ 10 Hz", "tpumon @ 20 Hz", "xprof trace",
                "full sofa.profile() stack"):
        assert row in table, row
    # single-pair rows must refuse to print a CI (a sample range is not a
    # 95% CI) — they say "too few" instead of a fake "resolved ±0.00 %"
    assert table.count("too few for a 95% CI") + \
        table.count("unavailable") >= 8
    assert "[95% CI" not in table
    assert "noise floor" in table  # baseline row documents the floor


def test_overhead_budget_ci_math():
    """_median_ci: distribution-free order-statistic CI; None below 6
    samples (a sample range must never masquerade as a 95% CI)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    from overhead_budget import _median_ci

    assert _median_ci([1.0]) is None
    assert _median_ci([1.0, 2.0, 3.0, 4.0, 5.0]) is None
    lo, hi = _median_ci(list(range(20)))
    assert lo <= 9.5 <= hi
    assert 0 < hi - lo < 19  # tighter than the range, wider than a point
    # CI tightens with n
    lo2, hi2 = _median_ci([x / 5 for x in range(100)])
    assert (hi2 - lo2) < (hi - lo)


def test_provisional_line_emitted_once_on_retry(fake_time, monkeypatch,
                                                capsys):
    """The retry loop's FIRST wait leaves a parseable JSON line on stdout so
    even an uncatchable SIGKILL (BENCH_r03: rc=124, parsed null) yields a
    non-null parse; later waits must not repeat it, and the final result
    line supersedes it as the last line."""
    import json

    monkeypatch.setattr(bench, "_state",
                        {"phase": "t", "done": False, "provisional": False})
    outcomes = ["down", "down", None]
    monkeypatch.setattr(bench, "_preflight",
                        lambda timeout_s=60.0: outcomes.pop(0))
    monkeypatch.setattr(bench, "_probed_backend", "cpu")
    monkeypatch.setattr(bench, "_log_chip_holders", lambda: None)
    monkeypatch.setattr(bench, "_with_timeout", lambda fn, t: ["dev"])
    bench._init_backend(budget_s=3600.0)
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(lines) == 1  # once, not per-retry
    row = json.loads(lines[0])
    assert row["value"] is None and row["provisional"] is True
    assert "provisional" in row["error"]


def test_default_budget_under_driver_window(fake_time, monkeypatch):
    """Default retry budget must stay under the ~20 min driver timeout —
    round 3 proved a 40 min budget just means the driver kills us first."""
    monkeypatch.delenv("SOFA_BENCH_RETRY_BUDGET_S", raising=False)
    monkeypatch.setattr(bench, "_preflight", lambda timeout_s=60.0: "down")
    monkeypatch.setattr(bench, "_log_chip_holders", lambda: None)
    with pytest.raises(RuntimeError):
        bench._init_backend()
    assert sum(fake_time.sleeps) <= 900.0 + 150.0


def test_sigterm_emits_error_json():
    """A driver SIGTERM mid-retry must still produce the JSON error line —
    run a real subprocess, signal it, and parse its stdout."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import time as real_time

    code = (
        "import sys, time; sys.path.insert(0, %r); import bench\n"
        "bench._install_signal_handlers()\n"
        "bench._state['phase'] = 'retrying backend init (test)'\n"
        "print('READY', file=sys.stderr, flush=True)\n"
        "time.sleep(60)\n"
    ) % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    try:
        # wait for the handler to be installed before signalling
        assert proc.stderr.readline().strip() == "READY"
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=20)
    finally:
        proc.kill()
    row = json.loads(out.strip().splitlines()[-1])
    assert row["value"] is None
    assert "SIGTERM" in row["error"] and "retrying backend init" in row["error"]
    assert proc.returncode == 1


def test_final_emit_silences_signal_handler(monkeypatch, capsys):
    """After the real result line is printed, a late SIGTERM must NOT print
    a second JSON line (the driver parses the last line)."""
    monkeypatch.setattr(bench, "_state",
                        {"phase": "t", "done": False, "provisional": False})
    bench._emit(1.23)
    capsys.readouterr()
    exits = []
    monkeypatch.setattr(bench.os, "_exit", lambda rc: exits.append(rc))
    import signal

    bench._install_signal_handlers()
    signal.getsignal(signal.SIGTERM)(signal.SIGTERM, None)
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGALRM, signal.SIG_DFL)
    assert capsys.readouterr().out == ""
    assert exits == [1]


def test_validate_checklist_skips_cpu_smoke(tmp_path, monkeypatch):
    import subprocess

    # the script exists, so only the gates under test can return False
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "validate_tpu.py").write_text("# stub\n")
    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **k: pytest.fail("checklist ran despite the gates"))
    monkeypatch.setattr(bench, "_probed_backend", "cpu")
    assert bench._run_validate_checklist(root=str(tmp_path)) is False
    monkeypatch.setenv("SOFA_BENCH_VALIDATE", "0")
    monkeypatch.setattr(bench, "_probed_backend", "tpu")
    assert bench._run_validate_checklist(root=str(tmp_path)) is False


def test_cpu_fallback_evidence_parses_child_json(monkeypatch):
    """The dead-tunnel error line carries the CPU-smoke overhead extras —
    the subprocess's LAST stdout line wins and failure shapes degrade to a
    cpu_smoke_error key, never an exception."""
    import subprocess

    import bench

    def fake_popen(stdout_text, rc=0):
        class _P:
            returncode = rc

            def __init__(self, cmd, **kw):
                assert kw["env"]["JAX_PLATFORMS"] == "cpu"
                assert kw["env"]["SOFA_BENCH_CPU_FALLBACK"] == "0"  # no recursion

            def communicate(self, timeout=None):
                return stdout_text, ""

            def poll(self):
                return rc

            def kill(self):
                pass

        return _P

    monkeypatch.setattr(
        subprocess, "Popen",
        fake_popen('noise\n123\n{"value": 1.5, "hlo_rows": 0, '
                   '"host_rows": 42, "backend": "cpu"}\ntrue\n'))
    out = bench._cpu_fallback_evidence()
    # the bare JSON scalars around the result line are skipped, and the
    # host-row capture proof survives into the extras
    assert out["cpu_smoke_overhead_pct"] == 1.5
    assert out["cpu_smoke_host_rows"] == 42
    assert out["cpu_smoke_backend"] == "cpu"
    assert bench._state["smoke_child"] is None  # unregistered after use

    monkeypatch.setattr(subprocess, "Popen", fake_popen("no json", rc=3))
    assert "cpu_smoke_error" in bench._cpu_fallback_evidence()

    monkeypatch.setenv("SOFA_BENCH_CPU_FALLBACK", "0")
    assert bench._cpu_fallback_evidence() == {}


def test_perf_evidence_merge_preserves_onchip_section(monkeypatch):
    """tools/perf_evidence.py owns ONLY the off-chip section; the
    hand-written on-chip evidence above it survives regeneration (a
    whole-file rewrite once deleted it)."""
    import os

    monkeypatch.syspath_prepend(os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import perf_evidence as mod

    onchip = ("# Performance evidence\n\n## On-chip (TPU)\n\n"
              "- headline overhead 0.0 %\n\n")
    old = onchip + "## Off-chip performance evidence\n\nold table\n"
    new_section = "## Off-chip performance evidence\n\nnew table\n"
    merged = mod.merge_evidence(old, new_section)
    assert merged == onchip + new_section
    # no prior file / empty file: a fresh document gets the title
    assert mod.merge_evidence("", new_section).startswith(
        "# Performance evidence")
    # a file with no off-chip heading keeps all its content
    assert mod.merge_evidence("# custom notes\n", new_section).startswith(
        "# custom notes")
    # prose MENTIONING the heading text must not truncate the document
    mention = (onchip.rstrip() + "\nsee the ## Off-chip performance "
               "evidence table below\n\n")
    merged = mod.merge_evidence(
        mention + "## Off-chip performance evidence\n\nold\n", new_section)
    assert merged == mention + new_section
    # hand-written sections AFTER the off-chip table survive regeneration
    appendix = "## Appendix\n\nnotes\n"
    merged = mod.merge_evidence(
        onchip + "## Off-chip performance evidence\n\nold\n\n" + appendix,
        new_section)
    assert merged == onchip + new_section.rstrip() + "\n\n" + appendix
    # an ARCHIVED heading that merely starts with the text is hand-written
    archived = ("## Off-chip performance evidence (2026-06, archived)\n\n"
                "old history\n\n")
    merged = mod.merge_evidence(
        onchip + archived + "## Off-chip performance evidence\n\nlive\n",
        new_section)
    assert merged == onchip + archived + new_section


def test_last_good_round_trip(tmp_path, monkeypatch):
    """A successful on-chip result persists with timestamp + sha; reading
    it back tags it `cached` so a dead-tunnel error line can carry it."""
    import json
    import os

    p = str(tmp_path / "bench_last_good.json")
    monkeypatch.setattr(bench, "_LAST_GOOD_PATH", p)
    bench._write_last_good({"metric": "resnet50_profiling_overhead",
                            "value": 0.7, "unit": "percent",
                            "hlo_rows": 123, "backend": "tpu"})
    doc = json.load(open(p))
    assert doc["value"] == 0.7
    assert doc["captured_utc"].endswith("Z")
    assert "git_sha" in doc and "captured_unix" in doc
    back = bench._read_last_good()
    assert back["cached"] is True
    assert back["value"] == 0.7
    # absent / null-value files never come back
    os.unlink(p)
    assert bench._read_last_good() is None
    with open(p, "w") as f:
        json.dump({"value": None}, f)
    assert bench._read_last_good() is None


def test_committed_last_good_is_valid():
    """The repo-root bench_last_good.json (the r4 on-chip seed) must parse
    through _read_last_good: a dead-tunnel BENCH_r05 run rides on it."""
    doc = bench._read_last_good()
    assert doc is not None, "bench_last_good.json missing or unparseable"
    assert doc["backend"] == "tpu"
    assert doc["value"] is not None
    assert doc["hlo_rows"] > 0
    assert doc["cached"] is True


def test_kernel_perf_tool_pure_parts(tmp_path):
    """kernel_perf's FLOPs model, peak lookup, and markdown rendering are
    CPU-testable; the sweep itself is chip-only (validate_tpu runs it)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "kernel_perf", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "kernel_perf.py"))
    kp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(kp)

    # causal halves each matmul; bwd adds 5 matmuls to fwd's 2
    fwd = kp.attention_flops(2, 1024, 8, 128)
    assert fwd == 2 * 2 * 1024 * 1024 * 8 * 128 * 0.5 * 2
    assert kp.attention_flops(2, 1024, 8, 128, bwd=True) == fwd * 3.5
    assert kp.attention_flops(2, 1024, 8, 128, causal=False) == fwd * 2

    assert kp.peak_from_kind("TPU v5e") == 197.0
    assert kp.peak_from_kind("TPU v5p") == 459.0  # v5p beats the v5 prefix
    assert kp.peak_from_kind("weird accelerator") is None

    rows = [{"kernel": "flash fwd", "T": 16384, "gqa": False,
             "ms": 16.8, "tflops": 9.4},
            {"kernel": "flash fwd", "T": 2048, "gqa": True,
             "ms": 1.0, "tflops": 20.0}]
    md = kp.render_md(rows, 197.0, "datasheet")
    assert "| flash fwd | 16384 | off | 16.80 | 9.40 | 4.8% |" in md
    assert "NOT MET" in md  # 4.8% < the 40% target
    md2 = kp.render_md(rows, None, "unknown")
    assert "MFU column unavailable" in md2
