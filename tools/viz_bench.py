#!/usr/bin/env python3
"""Viz-path benchmark: report.js payload, tile-pyramid build, board load.

The evidence harness behind the O(pixels) viz contract (docs/ANALYSIS.md
"Timeline tiles & viz serving"): generates the pod_synth ``--raw`` logdir,
runs a cold + warm ``sofa preprocess``, and measures

  * ``report_js_bytes``          the columnar overview payload on disk
  * ``report_js_legacy_bytes``   the same series re-serialized the old way
                                 (per-point dicts) — the shrink factor
  * ``tile_build_wall_time_s``   the tiles stage from run_manifest.json
  * ``tile_warm_wall_time_s``    same stage on the warm (content-keyed
                                 cached) re-run — should be ~free
  * ``tile_count`` / ``tile_bytes``  pyramid volume
  * ``cold_board_load_bytes``    bytes a browser fetches before first
                                 paint (board chrome + report.js)
  * ``deepest_tile_gz_bytes``    a deepest-level exact tile served gzipped
                                 over the real viz server (the <= 64 KiB
                                 deep-zoom response contract)

    python tools/viz_bench.py [workdir]

bench.py folds report_js_bytes / tile_build_wall_time_s into its secondary
evidence on both the success and dead-tunnel paths.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _board_bytes(logdir: str) -> int:
    """Bytes fetched before first paint: index.html + board JS/CSS +
    report.js (detail pages and tiles load lazily)."""
    total = 0
    for name in ("index.html", "sofa_board.js", "style.css", "report.js"):
        try:
            total += os.path.getsize(os.path.join(logdir, name))
        except OSError:
            pass
    return total


def _legacy_report_bytes(logdir: str) -> int:
    """Size report.js would have in the pre-tile monolithic format
    (per-point dicts) — same series, same downsampling, old encoding."""
    with open(os.path.join(logdir, "report.js")) as f:
        doc = json.loads(f.read()[len("sofa_traces = "):].rstrip(";\n"))
    legacy_series = []
    for s in doc.get("series", []):
        data = s["data"]
        table = data["names"]
        pts = [{"x": x, "y": y, "name": table[i], "d": d}
               for x, y, i, d in zip(data["x"], data["y"],
                                     data["ni"], data["d"])]
        legacy_series.append({**s, "data": pts})
    meta = {k: v for k, v in (doc.get("meta") or {}).items() if k != "tiles"}
    return len("sofa_traces = ;\n") + len(json.dumps(
        {"series": legacy_series, "meta": meta}))


def _tiles_stage(logdir: str) -> dict:
    from sofa_tpu.telemetry import load_manifest

    doc = load_manifest(logdir) or {}
    stage = next((s for s in doc.get("stages", [])
                  if s.get("verb") == "preprocess"
                  and s.get("name") == "tiles"), {})
    return {"dur_s": stage.get("dur_s"),
            "tiles": (doc.get("meta") or {}).get("tiles") or {}}


def _deepest_tile_over_http(cfg) -> "tuple[int, bool]":
    """(gzipped response bytes, exact?) for a deepest-level tile of the
    largest series, fetched from the real viz server with gzip accepted."""
    import gzip
    import http.client
    import threading

    from sofa_tpu.viz import sofa_viz

    with open(cfg.path("report.js")) as f:
        doc = json.loads(f.read()[len("sofa_traces = "):].rstrip(";\n"))
    tiles_meta = (doc.get("meta") or {}).get("tiles") or {}
    series = tiles_meta.get("series") or {}
    if not series:
        return 0, False
    name, ent = max(series.items(), key=lambda kv: kv[1].get("count", 0))
    level = ent["levels"] - 1
    httpd = sofa_viz(cfg, serve_forever=False)
    if httpd is None:
        return 0, False
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        port = httpd.server_address[1]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        # the first non-empty tile at the deepest level
        for n in range(1 << level):
            conn.request("GET", f"/tiles/{ent['path']}/{level}/{n}.json.gz",
                         headers={"Accept-Encoding": "gzip"})
            resp = conn.getresponse()
            body = resp.read()
            if resp.status == 200:
                tile = json.loads(gzip.decompress(body))
                return len(body), bool(tile.get("exact"))
        return 0, False
    finally:
        httpd.shutdown()
        httpd.server_close()


def run(workdir: "str | None" = None) -> dict:
    from sofa_tpu.config import SofaConfig
    from sofa_tpu.preprocess import sofa_preprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cleanup = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="sofa_vizbench_")
    logdir = os.path.join(workdir, "podlog", "")
    try:
        subprocess.run(
            [sys.executable, os.path.join(root, "tools", "pod_synth.py"),
             logdir, "--raw"],
            check=True, timeout=300, capture_output=True)
        cfg = SofaConfig(logdir=logdir)
        t0 = time.perf_counter()
        sofa_preprocess(cfg)
        cold = time.perf_counter() - t0
        cold_stage = _tiles_stage(logdir)
        out = {
            "preprocess_wall_time_s": round(cold, 3),
            "report_js_bytes": os.path.getsize(cfg.path("report.js")),
            "report_js_legacy_bytes": _legacy_report_bytes(logdir),
            "tile_build_wall_time_s": cold_stage["dur_s"],
            "tile_count": cold_stage["tiles"].get("tile_count"),
            "tile_bytes": cold_stage["tiles"].get("bytes"),
        }
        t0 = time.perf_counter()
        sofa_preprocess(cfg)
        out["preprocess_warm_wall_time_s"] = round(
            time.perf_counter() - t0, 3)
        warm_stage = _tiles_stage(logdir)
        out["tile_warm_wall_time_s"] = warm_stage["dur_s"]
        out["tile_warm_cached"] = warm_stage["tiles"].get("cached")
        from sofa_tpu.analyze import stage_board

        stage_board(cfg)
        out["cold_board_load_bytes"] = _board_bytes(logdir)
        gz_bytes, exact = _deepest_tile_over_http(cfg)
        out["deepest_tile_gz_bytes"] = gz_bytes
        out["deepest_tile_exact"] = exact
        return out
    finally:
        if cleanup:
            shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    out = run(args[0] if args else None)
    shrink = (out["report_js_legacy_bytes"] / out["report_js_bytes"]
              if out.get("report_js_bytes") else 0.0)
    print(f"report.js (columnar)     {out['report_js_bytes']:>12,} B")
    print(f"report.js (legacy dicts) {out['report_js_legacy_bytes']:>12,} B"
          f"  ({shrink:.2f}x shrink)")
    print(f"cold board load          {out['cold_board_load_bytes']:>12,} B")
    print(f"tile pyramid             {out['tile_count']} tiles, "
          f"{(out['tile_bytes'] or 0):,} B")
    print(f"tile build (cold)        {out['tile_build_wall_time_s']}s of "
          f"{out['preprocess_wall_time_s']}s preprocess")
    print(f"tile build (warm)        {out['tile_warm_wall_time_s']}s "
          f"({out['tile_warm_cached']} series cached)")
    print(f"deepest tile over HTTP   {out['deepest_tile_gz_bytes']:,} B "
          f"gzipped, exact={out['deepest_tile_exact']}")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
