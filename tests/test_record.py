import os
import re

from sofa_tpu.config import SofaConfig
from sofa_tpu.record import sofa_clean, sofa_record


def test_record_smoke_sleep(logdir):
    """The e2e gate of the reference test matrix is `sofa record "sleep 5"`
    (reference test/test.py:68); ours uses a shorter sleep."""
    cfg = SofaConfig(logdir=logdir, enable_xprof=False, sys_mon_rate=50)
    rc = sofa_record("sleep 0.4", cfg)
    assert rc == 0
    sources = {
        "mpstat.txt": "/proc/stat",
        "diskstat.txt": "/proc/diskstats",
        "netstat.txt": "/proc/net/dev",
        "cpuinfo.txt": "/proc/cpuinfo",
    }
    for f in ("sofa_time.txt", "timebase.txt", "misc.txt", "mpstat.txt",
              "diskstat.txt", "netstat.txt", "cpuinfo.txt"):
        assert os.path.isfile(cfg.path(f)), f
        # Sandboxed kernels may lack a /proc source; the collector then
        # degrades to an empty file (graceful-degradation contract).
        # Recorder-generated files (not in `sources`) must never be empty.
        if f not in sources or os.path.exists(sources[f]):
            assert os.path.getsize(cfg.path(f)) > 0, f
    misc = dict(
        line.split() for line in open(cfg.path("misc.txt")) if line.strip()
    )
    assert float(misc["elapsed_time"]) >= 0.4
    assert misc["rc"] == "0"
    # timebase: 4 clock columns, monotonically plausible
    row = open(cfg.path("timebase.txt")).readline().split()
    assert len(row) == 4
    assert int(row[0]) > 1e18  # realtime ns, sane epoch


def test_record_failing_command_still_collects(logdir):
    cfg = SofaConfig(logdir=logdir, enable_xprof=False)
    rc = sofa_record("exit 3", cfg)
    assert rc == 3  # child's rc propagates so CI can detect workload failure
    misc = dict(line.split() for line in open(cfg.path("misc.txt")))
    assert misc["rc"] == "3"


def test_record_cleans_stale_files(logdir):
    cfg = SofaConfig(logdir=logdir, enable_xprof=False)
    stale = cfg.path("mpstat.txt")
    with open(stale, "w") as f:
        f.write("stale-run-data\n")
    sofa_record("true", cfg)
    assert "stale-run-data" not in open(stale).read()


def test_xprof_injection_env(logdir):
    """With xprof on, the child env must carry the injection PYTHONPATH."""
    cfg = SofaConfig(logdir=logdir)
    out = cfg.path("env.txt")
    sofa_record(f"env > {out}", cfg)
    env = open(out).read()
    assert "SOFA_TPU_XPROF_OPTS" in env
    assert "_inject" in env
    assert os.path.isfile(os.path.join(cfg.inject_dir, "sitecustomize.py"))
    assert os.path.isfile(os.path.join(cfg.inject_dir, "sofa_tpu_pystacks.py"))


def test_injected_sitecustomize_is_inert_without_jax(logdir):
    """A plain python child with the injection must run unharmed."""
    cfg = SofaConfig(logdir=logdir)
    out = cfg.path("out.txt")
    rc = sofa_record(f"python -c 'print(6*7)' > {out}", cfg)
    assert rc == 0
    assert open(out).read().strip() == "42"


def test_pystacks_sampler(logdir):
    cfg = SofaConfig(logdir=logdir, enable_py_stacks=True, py_stack_rate=200)
    code = (
        "import time\n"
        "def busy_leaf():\n"
        "    t=time.time()\n"
        "    while time.time()-t < 0.6: pass\n"
        "busy_leaf()\n"
    )
    script = os.path.join(os.path.dirname(logdir.rstrip("/")), "w.py")
    with open(script, "w") as f:
        f.write(code)
    sofa_record(f"python {script}", cfg)
    stacks = open(cfg.path("pystacks.txt")).read()
    assert "busy_leaf" in stacks


def test_tpumon_live_sampler(logdir):
    """The live runtime-metrics sampler must produce a series even with
    XPlane tracing disabled (round-1 verdict item 3)."""
    cfg = SofaConfig(logdir=logdir, enable_xprof=False, tpu_mon_rate=50)
    # The image sitecustomize force-registers a TPU backend that overrides
    # the JAX_PLATFORMS env var; pin at the config level like conftest does.
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import jax.numpy as jnp, time; "
        "x = jnp.ones((8, 8)); (x @ x).block_until_ready(); time.sleep(1.0)"
    )
    rc = sofa_record(f'python -c "{code}"', cfg)
    assert rc == 0
    assert os.path.isfile(cfg.path("tpumon.txt"))
    from sofa_tpu.ingest.tpumon_parse import ingest_tpumon

    df = ingest_tpumon(cfg.logdir, time_base=0.0)
    alive = df[df["name"] == "alive"]
    assert len(alive) >= 2  # several heartbeats over the 1 s sleep


def test_real_perf_end_to_end(logdir):
    """Exercise the REAL perf record -> perf script -> parser path.

    Round-1 verdict: all perf tests used synthetic fixtures and the recorded
    format (callchains) disagreed with the parser. This test only runs where
    perf actually works (not in the sandboxed CI image).
    """
    import shutil
    import pytest

    if shutil.which("perf") is None:
        pytest.skip("perf not installed")
    cfg = SofaConfig(logdir=logdir, enable_xprof=False)
    from sofa_tpu.collectors.perf import PerfCollector

    pc = PerfCollector(cfg)
    if pc.probe() is not None or pc.mode != "perf":
        pytest.skip("perf gated by perf_event_paranoid")
    rc = sofa_record(
        "python -c 'print(sum(i*i for i in range(3_000_000)))'", cfg)
    assert rc == 0
    assert os.path.getsize(cfg.path("perf.data")) > 0
    from sofa_tpu.ingest.perf_script import ingest_perf

    df = ingest_perf(cfg.logdir, time_base=0.0)
    assert len(df) > 0
    assert (df["duration"] > 0).all()


def test_wrap_docker_command(logdir):
    from sofa_tpu.record import wrap_docker_command

    cfg = SofaConfig(logdir=logdir)
    env = {"PYTHONPATH": cfg.inject_dir,
           "SOFA_TPU_XPROF_OPTS": '{"enable": true}'}
    out = wrap_docker_command(
        "docker run --rm myimage python train.py", cfg, env)
    logdir_abs = os.path.abspath(cfg.logdir)
    assert out.startswith("docker run -v ")
    assert f"{logdir_abs}:{logdir_abs}" in out
    assert "PYTHONPATH=" in out and "SOFA_TPU_XPROF_OPTS=" in out
    assert out.endswith("--rm myimage python train.py")
    # non-docker commands pass through untouched
    assert wrap_docker_command("python train.py", cfg, env) == "python train.py"
    # "docker run" inside an argument is NOT an invocation
    for cmd in ("grep 'docker run' notes.txt",
                "echo docker run done && python train.py"):
        assert wrap_docker_command(cmd, cfg, env) == cmd
    # env assignments / sudo before docker still wrap
    wrapped = wrap_docker_command("FOO=1 sudo docker run img", cfg, env)
    assert wrapped.endswith(" img") and "-v " in wrapped


def test_perf_cgroup_rel():
    from sofa_tpu.record import _perf_cgroup_rel

    v1 = ("12:perf_event:/docker/abc123\n"
          "3:cpu,cpuacct:/docker/abc123\n")
    assert _perf_cgroup_rel(v1) == "docker/abc123"
    v2 = "0::/system.slice/docker-abc123.scope\n"
    assert _perf_cgroup_rel(v2) == "system.slice/docker-abc123.scope"
    assert _perf_cgroup_rel("") is None


def test_add_cidfile(logdir):
    from sofa_tpu.record import _add_cidfile

    out = _add_cidfile("docker run --rm img cmd", "/tmp/x.cid")
    assert out == "docker run --cidfile /tmp/x.cid --rm img cmd"
    assert _add_cidfile("python train.py", "/tmp/x.cid") == "python train.py"


def test_docker_mode_scopes_perf_to_container(logdir, tmp_path, monkeypatch):
    """VERDICT r2 missing #1: a `docker run` workload's CPU samples must
    come from the *container's* cgroup/pid, never from the docker CLI the
    old prefix wrapped.  docker+perf are PATH stubs (absent in this image):
    `docker run` executes the workload locally and publishes a cid+pid,
    `docker inspect` serves the pid back, and the perf stub records the
    argv the watcher launched it with — the real record orchestration runs
    end to end.
    """
    import stat
    import textwrap

    stubs = tmp_path / "stubs"
    stubs.mkdir()
    pidfile = tmp_path / "container.pid"
    perf_argv = tmp_path / "perf_argv.txt"

    # /bin/sh stubs, NOT python: they must start (and write their evidence)
    # faster than the watcher->terminate window even on a loaded machine.
    (stubs / "docker").write_text(textwrap.dedent(f"""\
        #!/bin/sh
        if [ "$1" = inspect ]; then cat {pidfile}; exit 0; fi
        [ "$1" = run ] || exit 64
        shift
        while [ $# -gt 0 ]; do
          case "$1" in
            --cidfile) printf c0ffee1234beef > "$2"; shift 2;;
            img) shift; break;;
            *) shift;;
          esac
        done
        echo $$ > {pidfile}
        exec "$@"
        """))
    (stubs / "perf").write_text(textwrap.dedent(f"""\
        #!/bin/sh
        printf '%s\\n' "$@" > {perf_argv}
        exec sleep 300
        """))
    for s in ("docker", "perf"):
        os.chmod(stubs / s, os.stat(stubs / s).st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{stubs}:{os.environ['PATH']}")
    # Force perf mode regardless of this kernel's paranoid sysctl.
    import sofa_tpu.collectors.perf as perfmod
    monkeypatch.setattr(perfmod, "_read_int", lambda path: -1)

    cfg = SofaConfig(logdir=logdir, enable_xprof=False)
    rc = sofa_record("docker run img sleep 2", cfg)
    assert rc == 0
    assert perf_argv.is_file(), "watcher never launched the scoped perf"
    argv = perf_argv.read_text().splitlines()
    # scoped to the container: cgroup filter (-a -G <path>) or pid attach —
    # and in either case NOT wrapping the docker CLI as a command prefix
    assert ("-G" in argv and "-a" in argv) or "-p" in argv
    assert "docker" not in argv
    assert cfg.path("perf.data") in argv
    if "-p" in argv:
        assert argv[argv.index("-p") + 1] == pidfile.read_text().strip()
    cid = open(cfg.path("docker.cid")).read()
    assert cid.startswith("c0ffee1234")


def test_scoped_argv_repeats_cgroup_per_event(logdir):
    """perf pairs -G cgroups with -e events positionally; a multi-event
    config must repeat the cgroup or only the first event is scoped."""
    from sofa_tpu.collectors.perf import PerfCollector

    cfg = SofaConfig(logdir=logdir, perf_events="cycles,instructions")
    perf = PerfCollector(cfg)
    perf.mode = "perf"
    argv = perf.scoped_argv(cgroup="docker/abc")
    assert argv[argv.index("-G") + 1] == "docker/abc,docker/abc"
    cfg2 = SofaConfig(logdir=logdir)
    perf2 = PerfCollector(cfg2)
    perf2.mode = "perf"
    argv2 = perf2.scoped_argv(cgroup="docker/abc")
    assert argv2[argv2.index("-G") + 1] == "docker/abc"
    # commas inside raw PMU descriptors / {groups} are parameters, not
    # event separators
    cfg3 = SofaConfig(logdir=logdir,
                      perf_events="cpu/event=0x3c,umask=0x1/,cycles")
    perf3 = PerfCollector(cfg3)
    perf3.mode = "perf"
    argv3 = perf3.scoped_argv(cgroup="cg")
    assert argv3[argv3.index("-G") + 1] == "cg,cg"
    cfg4 = SofaConfig(logdir=logdir, perf_events="{cycles,instructions}")
    perf4 = PerfCollector(cfg4)
    perf4.mode = "perf"
    argv4 = perf4.scoped_argv(cgroup="cg")
    assert argv4[argv4.index("-G") + 1] == "cg"


def test_docker_scope_falls_back_to_pid_when_cgroup_perf_dies(
        tmp_path, monkeypatch):
    """A perf denied system-wide -a -G (perf_event_paranoid) exits
    immediately; the watcher must retry with the pid attach instead of
    reporting success over a dead sampler."""
    import stat
    import textwrap

    stubs = tmp_path / "stubs"
    stubs.mkdir()
    pidfile = tmp_path / "container.pid"
    perf_argv = tmp_path / "perf_argv.txt"
    (stubs / "docker").write_text(textwrap.dedent(f"""\
        #!/bin/sh
        if [ "$1" = inspect ]; then cat {pidfile}; exit 0; fi
        shift
        while [ $# -gt 0 ]; do
          case "$1" in
            --cidfile) printf c0ffee1234beef > "$2"; shift 2;;
            img) shift; break;;
            *) shift;;
          esac
        done
        echo $$ > {pidfile}
        exec "$@"
        """))
    # dies instantly when scoped by cgroup (-G); survives on pid attach
    (stubs / "perf").write_text(textwrap.dedent(f"""\
        #!/bin/sh
        printf '%s\\n' "$@" >> {perf_argv}
        for a in "$@"; do [ "$a" = "-G" ] && exit 1; done
        exec sleep 300
        """))
    for s in ("docker", "perf"):
        os.chmod(stubs / s, os.stat(stubs / s).st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{stubs}:{os.environ['PATH']}")
    import sofa_tpu.collectors.perf as perfmod
    import sofa_tpu.record as recmod
    monkeypatch.setattr(perfmod, "_read_int", lambda path: -1)
    # this sandbox runs in the root cgroup ("/"); pin a container-like one
    # so the -G attempt actually happens
    monkeypatch.setattr(recmod, "_perf_cgroup_rel",
                        lambda text: "docker/stubcid")

    logdir2 = str(tmp_path / "log") + "/"
    os.makedirs(logdir2)
    cfg = SofaConfig(logdir=logdir2, enable_xprof=False)
    rc = sofa_record("docker run img sleep 2", cfg)
    assert rc == 0
    argv = perf_argv.read_text()
    # the cgroup attempt ran AND the pid fallback followed it
    assert "-G" in argv
    assert "-p" in argv
    pid_line_idx = argv.splitlines().index("-p")
    assert argv.splitlines()[pid_line_idx + 1] == \
        pidfile.read_text().strip()


def test_cluster_record_two_localhost_hosts(tmp_path):
    """VERDICT r2 weak #4 / next #5: drive the record-side cluster
    orchestration (record.py cluster_record) through the REAL subprocess
    path with two local 'hosts' — concurrent launches, flag
    re-materialization into the child CLI, per-host logdirs, and the
    max-rc fold.  The ssh/scp remote leg shares everything but the
    transport."""
    from sofa_tpu.record import cluster_record

    base = str(tmp_path / "clog") + "/"
    sync = tmp_path / "sync"
    sync.mkdir()
    cfg = SofaConfig(logdir=base, cluster_hosts=["localhost", "127.0.0.1"],
                     enable_xprof=False, tpu_mon_rate=7)
    # Rendezvous workload: each host's child announces itself and waits for
    # BOTH hosts to appear.  Serial (non-concurrent) launches would make the
    # first child time out with rc 7 — proving concurrency without relying
    # on wall-clock comparisons.  Each child also dumps its env so flag
    # re-materialization is observable end to end.
    command = (f"env > {sync}/env.$$; touch {sync}/$$.here; n=0; "
               f"while [ $(find {sync} -name '*.here' | wc -l) -lt 2 ]; do "
               f"n=$((n+1)); [ $n -gt 300 ] && exit 7; sleep 0.1; done")
    rc = cluster_record(command, cfg)
    assert rc == 0
    here = [f for f in os.listdir(sync) if f.endswith(".here")]
    assert len(here) == 2
    envs = [open(sync / f).read() for f in os.listdir(sync)
            if f.startswith("env.")]
    assert len(envs) == 2
    for env in envs:
        # --disable_xprof and --tpu_mon_rate 7 were re-materialized into
        # each host's child CLI and reached its collectors' injection env
        assert '"enable": false' in env
        assert "SOFA_TPU_TPUMON_HZ=7" in env
    for host in ("localhost", "127.0.0.1"):
        hdir = base.rstrip("/") + f"-{host}/"
        assert os.path.isfile(os.path.join(hdir, "sofa_time.txt")), host
        assert os.path.isfile(os.path.join(hdir, "mpstat.txt")), host
        misc = dict(line.split()
                    for line in open(os.path.join(hdir, "misc.txt")))
        assert misc["rc"] == "0"

    # any host's workload failure folds into the returned rc (CI contract)
    cfg2 = SofaConfig(logdir=str(tmp_path / "clog2") + "/",
                      cluster_hosts=["localhost"], enable_xprof=False)
    assert cluster_record("exit 3", cfg2) == 3


def _write_ssh_stubs(tmp_path, with_sofa: bool):
    """PATH stubs simulating a remote host (this image has no sshd): `ssh`
    executes the remote command string through a real shell — so the
    `command -v sofa` fallback logic actually runs — and `scp` copies the
    "remote" logdir back.  with_sofa plants a fake `sofa` console script on
    the stub PATH; without it the remote leg must fall back to
    `python3 -m sofa_tpu`."""
    import stat
    import sys
    import textwrap

    stubs = tmp_path / "stubs"
    stubs.mkdir()
    seen = tmp_path / "ssh_calls.txt"
    (stubs / "ssh").write_text(textwrap.dedent(f"""\
        #!{sys.executable}
        import subprocess, sys
        args = sys.argv[1:]
        host, remote = args[-2], args[-1]
        with open({str(seen)!r}, "a") as f:
            f.write(host + " :: " + remote + chr(10))
        if remote.startswith("rm -rf"):
            # guard: only the expected remote tmp dir may ever be deleted
            target = remote[len("rm -rf"):].strip()
            assert target.startswith("/tmp/sofa_tpu_record_"), target
        # a remote shell runs the string exactly as sent
        sys.exit(subprocess.call(remote, shell=True))
        """))
    (stubs / "scp").write_text(textwrap.dedent(f"""\
        #!{sys.executable}
        import subprocess, sys
        src, dst = sys.argv[-2], sys.argv[-1]
        host, path = src.split(":", 1)
        sys.exit(subprocess.call(["cp", "-r", path, dst]))
        """))
    if with_sofa:
        (stubs / "sofa").write_text(textwrap.dedent(f"""\
            #!{sys.executable}
            import os, sys
            argv = sys.argv[1:]
            assert argv[0] == "record", argv
            logdir = argv[argv.index("--logdir") + 1]
            os.makedirs(logdir, exist_ok=True)
            with open(os.path.join(logdir, "sofa_time.txt"), "w") as f:
                f.write("1700000000.0 remote\\n")
            with open(os.path.join(logdir, "misc.txt"), "w") as f:
                f.write("rc 0\\n")
            """))
    for s in stubs.iterdir():
        os.chmod(s, os.stat(s).st_mode | stat.S_IEXEC)
    return stubs, seen


def test_cluster_record_remote_host_via_ssh_stubs(tmp_path, monkeypatch):
    """The ssh/scp remote leg of cluster_record: launch over `ssh`, fetch
    with `scp`, clean the remote tmp dir — driven end to end with PATH
    stubs, asserting command quoting, fetch placement, and remote
    cleanup."""
    from sofa_tpu.record import cluster_record

    stubs, seen = _write_ssh_stubs(tmp_path, with_sofa=True)
    monkeypatch.setenv("PATH", f"{stubs}:{os.environ['PATH']}")

    base = str(tmp_path / "clog") + "/"
    cfg = SofaConfig(logdir=base, cluster_hosts=["tpu-host-7"],
                     enable_xprof=False)
    rc = cluster_record("sleep 0.1", cfg)
    assert rc == 0
    hdir = base.rstrip("/") + "-tpu-host-7/"
    fetched = open(os.path.join(hdir, "sofa_time.txt")).read()
    assert "remote" in fetched
    calls = open(seen).read().splitlines()
    # launch first, cleanup after fetch — both addressed to the host
    assert len(calls) == 2
    assert calls[0].startswith("tpu-host-7 :: ")
    assert "sofa record" in calls[0]
    assert "sleep 0.1" in calls[0]
    assert calls[1].startswith("tpu-host-7 :: rm -rf")
    # the remote tmp dir was cleaned
    m = re.search(r"rm -rf (\S+)", calls[1])
    assert m and not os.path.exists(m.group(1))


def test_cluster_record_remote_without_console_script(tmp_path, monkeypatch):
    """A remote with the package importable but NO `sofa` on its
    non-interactive ssh PATH must still record, via the `python3 -m
    sofa_tpu` fallback (r3 verdict #7) — here the fallback runs the REAL
    record into the stub's 'remote' tmp dir."""
    import shutil

    from sofa_tpu.record import cluster_record

    stubs, seen = _write_ssh_stubs(tmp_path, with_sofa=False)
    # Drop every PATH entry that would resolve `sofa` — but that can also
    # remove the venv bin holding the only dep-complete python3, so pin
    # python3 to the running interpreter via a shim dir first on PATH.
    import sys

    pybin = tmp_path / "pybin"
    pybin.mkdir()
    os.symlink(sys.executable, pybin / "python3")
    keep = [d for d in os.environ["PATH"].split(os.pathsep)
            if d and not os.path.isfile(os.path.join(d, "sofa"))]
    monkeypatch.setenv(
        "PATH", os.pathsep.join([str(stubs), str(pybin)] + keep))
    assert shutil.which("sofa") is None

    base = str(tmp_path / "clog") + "/"
    cfg = SofaConfig(logdir=base, cluster_hosts=["tpu-host-9"],
                     enable_xprof=False)
    rc = cluster_record("sleep 0.1", cfg)
    assert rc == 0
    hdir = base.rstrip("/") + "-tpu-host-9/"
    # written by the real record via the module fallback, not the fake
    fetched = open(os.path.join(hdir, "sofa_time.txt")).read()
    assert "remote" not in fetched
    assert float(fetched.split()[0]) > 0
    calls = open(seen).read().splitlines()
    assert "python3 -m sofa_tpu record" in calls[0]


def test_edr_trigger_fires(tmp_path):
    from sofa_tpu.tools.edr import run_edr

    log = tmp_path / "train.log"
    log.write_text("setup...\nstarting epoch 1\n")
    base = str(tmp_path / "edrlog")
    rc = run_edr([
        "--log", str(log),
        "--trigger", "starting epoch=epoch",
        "--record_seconds", "0.2",
        "--logdir", base + "/",
        "--poll_s", "0.1",
        "--timeout_s", "60",
    ])
    assert rc == 0
    assert os.path.isfile(f"{base}-epoch/misc.txt")


def test_sofa_clean_keeps_raw(logdir):
    cfg = SofaConfig(logdir=logdir, enable_xprof=False)
    sofa_record("true", cfg)
    with open(cfg.path("cputrace.csv"), "w") as f:
        f.write("derived\n")
    with open(cfg.path("report.js"), "w") as f:
        f.write("derived\n")
    # The full derived surface a report leaves behind (style.css and
    # hints.txt/tpu_meta.json/sofa_hints once escaped the clean).
    for name in ("style.css", "hints.txt", "tpu_meta.json"):
        with open(cfg.path(name), "w") as f:
            f.write("derived\n")
    os.makedirs(cfg.path("sofa_hints"), exist_ok=True)
    sofa_clean(cfg)
    for name in ("cputrace.csv", "report.js", "style.css", "hints.txt",
                 "tpu_meta.json", "sofa_hints"):
        assert not os.path.exists(cfg.path(name)), name
    assert os.path.isfile(cfg.path("misc.txt"))
    assert os.path.isfile(cfg.path("mpstat.txt"))


def test_chained_sitecustomize_hang_is_bounded(tmp_path):
    """A next-on-path site hook stuck on a dead device tunnel must not hang
    the profiled program: the injection's SIGALRM guard times the chain out
    and the command still runs (observed live: an axon claim loop spinning
    forever on a dead relay stalled `sofa record` of a pure-host command)."""
    import subprocess
    import sys as _sys
    import time

    from sofa_tpu.collectors.xprof import _SITECUSTOMIZE

    inject = tmp_path / "inject"
    inject.mkdir()
    (inject / "sitecustomize.py").write_text(_SITECUSTOMIZE)
    hook = tmp_path / "hook"
    hook.mkdir()
    (hook / "sitecustomize.py").write_text("import time\ntime.sleep(300)\n")
    env = dict(
        os.environ,
        PYTHONPATH=f"{inject}{os.pathsep}{hook}",
        SOFA_TPU_CHAIN_TIMEOUT_S="2",
        SOFA_TPU_XPROF_OPTS="{}",
    )
    t0 = time.time()
    r = subprocess.run([_sys.executable, "-c", "print('program ran')"],
                       capture_output=True, text=True, env=env, timeout=60)
    assert time.time() - t0 < 30, "chain guard did not fire"
    assert "program ran" in r.stdout
    assert "chained sitecustomize" in r.stderr and "exceeded" in r.stderr


def test_record_sigterm_runs_epilogue_and_kills_tree(tmp_path):
    """SIGTERM mid-record (drivers, CI timeouts) rides the SIGINT path:
    the profiled tree is terminated via its process group, the collector
    epilogue still writes the logdir, and the exit code folds to 143."""
    import signal
    import subprocess
    import sys as _sys
    import time as _time

    d = str(tmp_path / "sig") + "/"
    p = subprocess.Popen(
        [_sys.executable, "-m", "sofa_tpu", "record", "sleep 60",
         "--logdir", d],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    deadline = _time.time() + 60
    while _time.time() < deadline and not os.path.isfile(d + "sofa_time.txt"):
        _time.sleep(0.2)   # prologue done = child launched
    _time.sleep(2.0)
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=60)
    assert p.returncode == 143, out[-400:]
    assert "interrupted; terminating profiled command" in out
    misc = dict(line.split(None, 1)
                for line in open(d + "misc.txt").read().splitlines())
    assert misc["rc"].strip() == "143"
    child_pid = int(misc["pid"])
    _time.sleep(0.5)
    assert not os.path.exists(f"/proc/{child_pid}"), "child survived"
    assert os.path.isfile(d + "mpstat.txt")  # epilogue harvested


def test_record_logdir_is_a_file_clean_error(tmp_path):
    """--logdir pointing at an existing FILE: one [ERROR] line, rc 1."""
    import subprocess
    import sys as _sys

    flat = tmp_path / "flat"
    flat.write_text("x")
    r = subprocess.run(
        [_sys.executable, "-m", "sofa_tpu", "record", "true",
         "--logdir", str(flat)],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 1
    assert "Traceback" not in r.stderr
    assert "not a directory" in r.stderr + r.stdout  # curated msg


def test_term_as_interrupt_respects_sig_ign():
    """A deliberately ignored signal (nohup'd SIGHUP) must stay ignored
    inside _term_as_interrupt, while SIGTERM is routed and restored."""
    import signal

    from sofa_tpu.record import _term_as_interrupt

    old_hup = signal.signal(signal.SIGHUP, signal.SIG_IGN)
    old_term = signal.getsignal(signal.SIGTERM)
    try:
        with _term_as_interrupt((signal.SIGHUP,)):
            assert signal.getsignal(signal.SIGHUP) is signal.SIG_IGN
            assert signal.getsignal(signal.SIGTERM) is not old_term
        assert signal.getsignal(signal.SIGTERM) is old_term
        assert signal.getsignal(signal.SIGHUP) is signal.SIG_IGN
    finally:
        signal.signal(signal.SIGHUP, old_hup)


def test_atexit_stop_trace_hang_is_bounded(tmp_path, monkeypatch):
    """stop_trace wedged on a dead device tunnel must not wedge the child:
    the injected _stop runs it on a thread deadline, gives up, records the
    breadcrumb, and the process exits with ITS OWN exit code (the live
    VERDICT-r4 repro: `sofa stat` of a completed command hung 240 s+ in
    atexit; the reference's kill-all property, sofa_record.py:480-523)."""
    import json
    import sys as _sys
    import time as _time

    prog = tmp_path / "wedge_stop.py"
    prog.write_text(
        "import os, sys, time\n"
        "import jax\n"
        "jax.devices()\n"  # init the (cpu) backend so the watcher attaches
        "def _wedge():\n"
        "    time.sleep(600)\n"
        "jax.profiler.stop_trace = _wedge\n"
        "print('program ran')\n"
        "logdir = sys.argv[1]\n"
        "for _ in range(500):\n"  # wait until the injection has attached
        "    if os.path.exists(os.path.join(logdir, 'xprof_marker.txt')):\n"
        "        break\n"
        "    time.sleep(0.02)\n"
        "sys.exit(7)\n"
    )
    d = str(tmp_path / "log") + "/"
    monkeypatch.setenv("SOFA_TPU_STOP_TIMEOUT_S", "2")
    monkeypatch.setenv("SOFA_TPU_HARD_EXIT_GRACE_S", "10")
    cfg = SofaConfig(logdir=d, enable_tpu_mon=False, enable_mem_prof=False)
    t0 = _time.time()
    rc = sofa_record(f"{_sys.executable} {prog} {d}", cfg)
    assert _time.time() - t0 < 120, "bounded-stop guard did not fire"
    assert rc == 7  # exit-code fidelity: no force-exit was needed
    with open(os.path.join(cfg.inject_dir, "atexit_stop.json")) as f:
        m = json.load(f)
    assert m["done"] is True
    assert m["ok"] is False  # the stop really did time out


def test_record_kills_child_wedged_in_epilogue(tmp_path):
    """In-process guards can be defeated (a C call wedged while HOLDING the
    GIL): once the atexit breadcrumb goes stale past the deadline, record
    TERM/KILLs the process group and returns — no hang, no orphans."""
    import time as _time
    import sys as _sys

    prog = tmp_path / "wedge_hard.py"
    prog.write_text(
        "import json, os, sys, time\n"
        "inject = sys.argv[1]\n"
        "os.makedirs(inject, exist_ok=True)\n"
        "with open(os.path.join(inject, 'atexit_stop.json'), 'w') as f:\n"
        "    json.dump({'pid': os.getpid(), 't': time.time(),\n"
        "               'timeout_s': 0, 'grace_s': 0}, f)\n"
        "print('wedging', flush=True)\n"
        "time.sleep(600)\n"
    )
    d = str(tmp_path / "log") + "/"
    cfg = SofaConfig(logdir=d, enable_tpu_mon=False, enable_mem_prof=False,
                     epilogue_deadline_s=2.0)
    t0 = _time.time()
    rc = sofa_record(f"{_sys.executable} {prog} {cfg.inject_dir}", cfg)
    assert _time.time() - t0 < 60, "epilogue deadline did not fire"
    assert rc == 143  # SIGTERM, folded to the shell convention
    misc = dict(line.split(None, 1)
                for line in open(cfg.path("misc.txt")).read().splitlines())
    child_pid = int(misc["pid"])
    _time.sleep(0.3)
    assert not os.path.exists(f"/proc/{child_pid}"), "orphan survived"


def test_epilogue_deadline_policy():
    """done+ok => never kill; done+!ok => grace window; pending => full
    two-call allowance; explicit config override wins."""
    from sofa_tpu.record import _epilogue_deadline

    cfg = SofaConfig(logdir="/tmp/x/")
    assert _epilogue_deadline(cfg, {"t": 100.0, "done": True, "ok": True}) is None
    assert _epilogue_deadline(
        cfg, {"t": 100.0, "done": True, "ok": False, "grace_s": 20}
    ) == 100.0 + 20 + 60
    assert _epilogue_deadline(
        cfg, {"t": 100.0, "timeout_s": 30, "grace_s": 20}
    ) == 100.0 + 2 * 30 + 20 + 60
    cfg.epilogue_deadline_s = 5.0
    assert _epilogue_deadline(
        cfg, {"t": 100.0, "done": True, "ok": False, "grace_s": 20}
    ) == 105.0


def test_default_env_stat_smoke_is_bounded(tmp_path):
    """The flagship verb in the environment sofa actually ships in: no cpu
    pin, whatever JAX_PLATFORMS the image forces (a dead device tunnel
    included) — `sofa stat` of a trivial command must return in bounded
    time with no orphan processes.  Opt-in (slow, environment-dependent):
    SOFA_TPU_TEST_REALENV=1."""
    import subprocess
    import sys as _sys
    import time as _time

    import pytest

    if not os.environ.get("SOFA_TPU_TEST_REALENV"):
        pytest.skip("set SOFA_TPU_TEST_REALENV=1 to run the real-env smoke")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS",)}
    # Tight child-side deadlines so the smoke stays CI-sized even when the
    # tunnel is dead; the defaults would still be bounded, just slower.
    env["SOFA_TPU_STOP_TIMEOUT_S"] = "15"
    env["SOFA_TPU_HARD_EXIT_GRACE_S"] = "10"
    env["SOFA_TPU_CHAIN_TIMEOUT_S"] = "60"
    d = str(tmp_path / "log") + "/"
    t0 = _time.time()
    r = subprocess.run(
        [_sys.executable, "-m", "sofa_tpu", "stat", "python -c 'print(42)'",
         "--logdir", d],
        capture_output=True, text=True, env=env, timeout=420)
    elapsed = _time.time() - t0
    out = r.stdout + r.stderr
    assert "42" in out, out[-800:]
    assert elapsed < 400, f"stat took {elapsed:.0f}s: not bounded"
    misc = dict(line.split(None, 1)
                for line in open(d + "misc.txt").read().splitlines())
    child_pid = int(misc["pid"])
    _time.sleep(0.5)
    assert not os.path.exists(f"/proc/{child_pid}"), "orphan survived"


def test_tpumon_final_memprof_never_triggers_backend_init(tmp_path):
    """The at-exit memprof fallback must only run on a strictly-initialized
    backend: jax.live_arrays() on a merely-imported jax *triggers* backend
    init, which with a dead device tunnel is an unbounded claim loop at
    interpreter exit (the VERDICT-r4 flagship hang, root-caused live:
    `sofa stat "python -c 'print(42)'"` printed 42, then the axon backend
    initialized 2 s later from inside this fallback and never returned)."""
    import subprocess
    import sys as _sys

    from sofa_tpu.collectors import tpumon

    inject = tmp_path / "inject"
    inject.mkdir()
    tpumon.write_sampler_module(str(inject))
    (inject / "sitecustomize.py").write_text(
        "import os\n"
        "from sofa_tpu_tpumon import start_sampler\n"
        "start_sampler(float(os.environ['SOFA_TPU_TPUMON_HZ']),\n"
        "              os.environ['SOFA_TPU_TPUMON_OUT'],\n"
        "              memprof_path=os.environ.get('SOFA_TPU_MEMPROF_OUT'))\n")
    mp = tmp_path / "memprof.pb.gz"
    env = dict(os.environ, PYTHONPATH=str(inject),
               SOFA_TPU_TPUMON_HZ="5",
               SOFA_TPU_TPUMON_OUT=str(tmp_path / "tpumon.txt"),
               SOFA_TPU_MEMPROF_OUT=str(mp))
    # The program imports jax but never initializes a backend.
    r = subprocess.run([_sys.executable, "-c", "import jax; print('ok')"],
                       capture_output=True, text=True, env=env, timeout=120)
    assert "ok" in r.stdout
    assert not mp.exists(), \
        "at-exit memprof fallback touched an uninitialized backend"


def test_marker_authoritative_paths(tmp_path):
    """The epilogue-kill breadcrumb is only authoritative from the main
    workload process (the sh wrapper or its direct child) while that
    writer is still alive — injected deeper descendants and already-exited
    writers must never arm the kill."""
    import os
    import signal
    import subprocess
    import sys as _sys

    from sofa_tpu.record import _marker_authoritative

    child = subprocess.Popen(
        [_sys.executable, "-c",
         "import subprocess, sys, time\n"
         "p = subprocess.Popen([sys.executable, '-c',"
         " 'import time; time.sleep(30)'])\n"
         "print(p.pid, flush=True)\n"
         "time.sleep(30)\n"],
        stdout=subprocess.PIPE, text=True, start_new_session=True)
    try:
        grandchild = int(child.stdout.readline())
        # the wrapper itself (sh `exec`s a single command)
        assert _marker_authoritative(child, {"pid": child.pid})
        # a live DIRECT child of the wrapper: the usual python main
        assert _marker_authoritative(child, {"pid": grandchild})
        # garbage pids
        assert not _marker_authoritative(child, {"pid": 0})
        assert not _marker_authoritative(child, {"pid": "x"})
        assert not _marker_authoritative(child, {})
        # a live process OUTSIDE the wrapper's direct children
        assert not _marker_authoritative(child, {"pid": os.getpid()})
        # an already-exited writer: leftover breadcrumb, not a live wedge
        p2 = subprocess.Popen([_sys.executable, "-c", "pass"])
        p2.wait()
        assert not _marker_authoritative(child, {"pid": p2.pid})
    finally:
        os.killpg(child.pid, signal.SIGKILL)
        child.wait()


def test_duration_stop_timeout_still_leaves_exit_breadcrumb(tmp_path,
                                                            monkeypatch):
    """--xprof_duration_s stops the trace mid-run; if THAT stop times out
    on a dead tunnel, the later atexit must still write the done/not-ok
    breadcrumb (and arm the force-exit watchdog) even though the stop
    itself already ran — teardown can wedge on the stuck thread."""
    import json
    import sys as _sys
    import time as _time

    prog = tmp_path / "wedge_duration.py"
    prog.write_text(
        "import os, sys, time\n"
        "import jax\n"
        "jax.devices()\n"
        "def _wedge():\n"
        "    time.sleep(600)\n"
        "jax.profiler.stop_trace = _wedge\n"
        "print('program ran')\n"
        "time.sleep(6)\n"  # duration timer (0.5s) fires + stop times out
        "sys.exit(7)\n"
    )
    d = str(tmp_path / "log") + "/"
    monkeypatch.setenv("SOFA_TPU_STOP_TIMEOUT_S", "2")
    monkeypatch.setenv("SOFA_TPU_HARD_EXIT_GRACE_S", "10")
    cfg = SofaConfig(logdir=d, enable_tpu_mon=False, enable_mem_prof=False,
                     xprof_duration_s=0.5)
    t0 = _time.time()
    rc = sofa_record(f"{_sys.executable} {prog}", cfg)
    assert _time.time() - t0 < 120
    assert rc == 7
    with open(os.path.join(cfg.inject_dir, "atexit_stop.json")) as f:
        m = json.load(f)
    assert m["done"] is True and m["ok"] is False


def test_record_never_kills_healthy_slow_teardown(tmp_path):
    """A clean trace stop (breadcrumb done+ok) disarms the epilogue
    deadline entirely: app atexit work running AFTER our stop (registered
    earlier => runs later, LIFO) may take arbitrarily long — killing a
    final checkpoint write would be worse than the hang we fixed."""
    import sys as _sys
    import time as _time

    prog = tmp_path / "slow_teardown.py"
    prog.write_text(
        "import atexit, os, sys, time\n"
        "atexit.register(lambda: time.sleep(4))\n"  # runs AFTER our stop
        "import jax\n"
        "jax.devices()\n"
        "print('program ran')\n"
        "logdir = sys.argv[1]\n"
        "for _ in range(500):\n"
        "    if os.path.exists(os.path.join(logdir, 'xprof_marker.txt')):\n"
        "        break\n"
        "    time.sleep(0.02)\n"
        "sys.exit(0)\n"
    )
    d = str(tmp_path / "log") + "/"
    cfg = SofaConfig(logdir=d, enable_tpu_mon=False, enable_mem_prof=False,
                     epilogue_deadline_s=1.0)   # aggressive on purpose
    t0 = _time.time()
    rc = sofa_record(f"{_sys.executable} {prog} {d}", cfg)
    elapsed = _time.time() - t0
    assert rc == 0, "healthy slow teardown was killed"
    assert elapsed >= 4.0  # the atexit sleep really ran to completion
