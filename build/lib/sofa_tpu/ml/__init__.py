"""Pattern-mining layer: iteration detection (AISI), swarm clustering (HSG),
and run-to-run swarm diff.

The reference builds these on a McCreight suffix tree + fuzzywuzzy + KMeans
(SURVEY §2.6).  This implementation uses a suffix automaton for repeated-
pattern mining (same asymptotics, far less code), difflib for fuzzy matching
(no external dependency), and exact occurrence positions instead of KMeans
boundary clustering.
"""
