"""The archive's append-only run catalog (``catalog.jsonl``).

One JSON line per event, fsync'd through ``durability.fsync_append`` (the
run journal's discipline): a crash mid-append leaves at worst one torn
final line, which :func:`read_catalog` skips.  Event vocabulary::

    {"ev": "ingest", "run": <run_id>, "t": ..., "logdir": ..., "files": N,
     "new_objects": M, "bytes_added": B, "label": ...}
    {"ev": "bench",  "metric": ..., "value": ..., "t": ..., "round": ...,
     "extra": {...}}          # bench.py's evidence trajectory
    {"ev": "gc",     "t": ..., "dropped_runs": N, "swept_objects": M,
     "freed_bytes": B}

The catalog is the archive's source of truth for run ORDER (rolling
baselines read it newest-last); the per-run content lives in
``runs/<run_id>.json``.  Re-ingesting a run appends a fresh ingest event
for the same id — readers dedup by id keeping the newest, so the file
stays append-only (`sofa archive gc` is the only compaction path).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional

from sofa_tpu.archive import CATALOG_NAME

#: Rewrite-generation sidecar (`catalog.gen`): bumped by every
#: :func:`rewrite` so the columnar catalog index (archive/index.py) can
#: detect a gc compaction DETERMINISTICALLY — a compaction that happens
#: to keep the head bytes and grow the file back past the index's
#: committed offset would otherwise be invisible to the size/head-
#: signature checks alone.
GEN_NAME = "catalog.gen"

#: Bytes of the catalog head signed into the index commit: a different
#: head under the same path is a rewritten ledger, not an append (the
#: `sofa live` rotation discipline applied to the catalog).
HEAD_SIG_BYTES = 256


def catalog_path(root: str) -> str:
    return os.path.join(root, CATALOG_NAME)


def generation(root: str) -> int:
    """The catalog's rewrite generation (0 until the first rewrite)."""
    try:
        with open(os.path.join(root, GEN_NAME)) as f:
            doc = json.load(f)
        return int(doc.get("gen", 0))
    except (OSError, ValueError, TypeError):
        return 0


def head_sig(root: str, length: "int | None" = None) -> str:
    """sha1 over the catalog's first ``min(HEAD_SIG_BYTES, length)``
    bytes (whole head when ``length`` is None).  The columnar index signs
    exactly its committed prefix's head, so an append past a short
    catalog never masquerades as a rewrite — and a rewrite under the
    same size never masquerades as an append."""
    n = HEAD_SIG_BYTES if length is None else min(HEAD_SIG_BYTES,
                                                  max(int(length), 0))
    try:
        with open(catalog_path(root), "rb") as f:
            return hashlib.sha1(f.read(n)).hexdigest()
    except OSError:
        return hashlib.sha1(b"").hexdigest()


def append_event(root: str, ev: str, **fields) -> dict:
    """Durably append one event line; returns the entry written."""
    from sofa_tpu.durability import fsync_append

    entry = {"ev": ev, "t": round(time.time(), 3), **fields}
    fsync_append(catalog_path(root),
                 json.dumps(entry, separators=(",", ":")) + "\n")
    return entry


def read_catalog(root: str) -> List[dict]:
    """Every parseable event, file order (oldest first).  A torn final
    line — the crash case the fsync'd appends are designed around — or
    any unparsable line is skipped, like the run journal's reader."""
    entries: List[dict] = []
    try:
        with open(catalog_path(root)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue  # torn tail from a mid-append crash
                if isinstance(e, dict):
                    entries.append(e)
    except OSError:
        return []
    return entries


def ingest_entries(entries: List[dict]) -> List[dict]:
    """Ingest events deduped by run id (newest wins), ordered oldest
    first — the run sequence rolling baselines walk."""
    latest: Dict[str, dict] = {}
    for e in entries:
        run = e.get("run")
        if e.get("ev") == "ingest" and isinstance(run, str):
            latest[run] = e
    return sorted(latest.values(), key=lambda e: e.get("t", 0))


def bench_entries(entries: List[dict],
                  metric: Optional[str] = None) -> List[dict]:
    """Bench evidence events, oldest first, optionally for one metric."""
    out = [e for e in entries if e.get("ev") == "bench"
           and (metric is None or e.get("metric") == metric)]
    return sorted(out, key=lambda e: e.get("t", 0))


def rewrite(root: str, entries: List[dict]) -> None:
    """Atomically replace the catalog (gc's compaction path — the ONLY
    writer that is not an append).

    Holds the root's ``derived_write_guard`` for the replace (reentrant:
    `sofa archive gc` already holds it around the whole sweep, a direct
    caller gets its own) so a reader mid-``read_catalog`` — or the fleet
    service answering ``/v1/catalog`` — sees the 503/mid-write signal
    instead of racing the swap, and bumps the rewrite generation so the
    columnar index (archive/index.py) invalidates deterministically."""
    from sofa_tpu.durability import atomic_write
    from sofa_tpu.trace import derived_write_guard

    with derived_write_guard(root):
        with atomic_write(catalog_path(root), fsync=True) as f:
            for e in entries:
                f.write(json.dumps(e, separators=(",", ":")) + "\n")
        with atomic_write(os.path.join(root, GEN_NAME), fsync=True) as f:
            json.dump({"gen": generation(root) + 1}, f)
