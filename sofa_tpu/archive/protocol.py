"""Shared client<->server protocol vocabulary for the fleet tier.

Single source of truth for the HTTP surface spoken between the archive
service (service.py / tier.py) and its consumers (client.py, the board
pages, tools).  Both sides import these constants instead of repeating
string literals; sofa-lint's protocol rules (SL024-SL028) anchor their
closure checks on the declarations in this module.

Everything here is a plain literal so the lint extractor (and humans)
can read the contract without executing code.
"""

# ---------------------------------------------------------------------------
# Typed error-body vocabulary.  Every JSON refusal carries {"error": <one of
# these>}; clients dispatch on the string, never on prose.
# ---------------------------------------------------------------------------

ERR_NO_SUCH_ROUTE = "no_such_route"
ERR_UNAUTHORIZED = "unauthorized"
ERR_BAD_TENANT = "bad_tenant"
ERR_READ_ONLY_REPLICA = "read_only_replica"
ERR_MID_GC = "mid_gc"
ERR_DRAINING = "draining"
ERR_DEADLINE_EXPIRED = "deadline_expired"
ERR_BROWNOUT = "brownout"
ERR_WAL_BACKLOG = "wal_backlog"
ERR_BAD_KIND = "bad_kind"
ERR_BAD_PARAMS = "bad_params"
ERR_REPLICA_WARMING = "replica_warming"
ERR_NO_INDEX = "no_index"
ERR_NO_FLEET_REPORT = "no_fleet_report"
ERR_NO_SUCH_CHUNK = "no_such_chunk"
ERR_NO_SUCH_RUN = "no_such_run"
ERR_LENGTH_REQUIRED = "length_required"
ERR_TOO_LARGE = "too_large"
ERR_BAD_JSON = "bad_json"
ERR_BAD_FILES_MAP = "bad_files_map"
ERR_MISSING_OBJECTS = "missing_objects"
ERR_QUOTA = "quota"
ERR_HASH_MISMATCH = "hash_mismatch"
ERR_NO_SPACE = "no_space"
ERR_LOADED = "loaded"
ERR_NO_WORKER = "no_worker"

# ---------------------------------------------------------------------------
# Status -> permitted error bodies.  Keys are every status the protocol is
# allowed to emit; the tuple lists the typed error strings a refusal with
# that status may carry (empty tuple: status carries no error body).
# ---------------------------------------------------------------------------

STATUS_ERRORS = {
    200: (),
    204: (),
    304: (),
    400: (ERR_BAD_TENANT, ERR_BAD_KIND, ERR_BAD_PARAMS, ERR_BAD_JSON,
          ERR_BAD_FILES_MAP),
    401: (ERR_UNAUTHORIZED,),
    403: (ERR_READ_ONLY_REPLICA,),
    404: (ERR_NO_SUCH_ROUTE, ERR_NO_SUCH_RUN, ERR_NO_INDEX,
          ERR_NO_SUCH_CHUNK, ERR_NO_FLEET_REPORT),
    408: (),
    409: (ERR_MISSING_OBJECTS,),
    411: (ERR_LENGTH_REQUIRED,),
    413: (ERR_TOO_LARGE,),
    422: (ERR_HASH_MISMATCH,),
    425: (),
    429: (ERR_QUOTA,),
    502: (ERR_NO_WORKER,),
    503: (ERR_MID_GC, ERR_DRAINING, ERR_BROWNOUT, ERR_REPLICA_WARMING,
          ERR_LOADED, ERR_WAL_BACKLOG),
    504: (ERR_DEADLINE_EXPIRED,),
    507: (ERR_NO_SPACE,),
}

# ---------------------------------------------------------------------------
# Retry-After discipline.  Statuses in RETRY_AFTER_STATUSES are transient
# capacity refusals and MUST attach a Retry-After header; a deadline 504
# means the caller's budget is gone, so it must NOT invite a retry.
# ---------------------------------------------------------------------------

RETRY_AFTER_STATUSES = (429, 503, 507)
NO_RETRY_AFTER_STATUSES = (504,)

# ---------------------------------------------------------------------------
# Client dispatch sets.  client._attempt classifies by status: fatal ->
# ServiceRejected, resume -> ServiceIncomplete, retry (or >= floor) ->
# ServiceUnavailable.  FATAL_ERRORS lists typed error bodies that override
# a retryable status to fatal (e.g. a 429 quota breach never clears on its
# own, even though 429 otherwise invites retry).
# ---------------------------------------------------------------------------

CLIENT_FATAL_STATUSES = (401, 403)
CLIENT_RESUME_STATUSES = (409,)
CLIENT_RETRY_STATUSES = (408, 422, 425, 429)
CLIENT_RETRY_FLOOR = 500
FATAL_ERRORS = (ERR_QUOTA,)

# ---------------------------------------------------------------------------
# Route registry.  "<name>" segments are placeholders; clients and the
# board must only speak routes whose shape appears here, and every
# concrete segment must be dispatched by a handler.
# ---------------------------------------------------------------------------

ROUTES = (
    "GET /v1/ping",
    "GET /v1/health",
    "GET /v1/tier",
    "GET /v1/metrics",
    "GET /v1/<tenant>/catalog",
    "GET /v1/<tenant>/query",
    "GET /v1/<tenant>/fleet",
    "GET /v1/<tenant>/index/commit",
    "GET /v1/<tenant>/index/<family>/<chunk>",
    "GET /v1/<tenant>/run/<run_id>",
    "POST /v1/<tenant>/have",
    "POST /v1/<tenant>/commit",
    "PUT /v1/<tenant>/object/<sha256>",
    "OPTIONS /v1/<any>",
)
