// timebase — host clock-domain anchor for sofa_tpu.
//
// The reference pins unix epoch time against the kernel profiler's uptime
// clock by running `perf record` at a known gettimeofday() instant
// (/root/reference/bin/sofa_perf_timebase.cc:8-19).  The TPU build needs the
// same bridge but across more domains: perf/ftrace stamp CLOCK_MONOTONIC (or
// BOOTTIME), the XPlane trace stamps its own session clock, and /proc
// samplers stamp CLOCK_REALTIME.  This tool emits N simultaneous
// (realtime, monotonic, boottime, monotonic_raw) samples so preprocess can
// convert any of those domains into unix time by linear fit; the XPlane
// session clock is anchored separately by the in-trace marker annotation
// (sofa_tpu/collectors/xprof.py).
//
// Output: one line per sample to stdout:
//   <realtime_ns> <monotonic_ns> <boottime_ns> <monotonic_raw_ns>
//
// Usage: timebase [samples=3] [interval_ms=0]

#include <cstdio>
#include <cstdlib>
#include <ctime>

static long long now_ns(clockid_t id) {
  struct timespec ts;
  if (clock_gettime(id, &ts) != 0) return -1;
  return static_cast<long long>(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
}

int main(int argc, char** argv) {
  int samples = argc > 1 ? atoi(argv[1]) : 3;
  int interval_ms = argc > 2 ? atoi(argv[2]) : 0;
  if (samples < 1) samples = 1;
  for (int i = 0; i < samples; ++i) {
    // Read the fast pair twice around the slower ones to bound skew; emit
    // the midpoint of realtime so the tuple is as simultaneous as possible.
    long long rt0 = now_ns(CLOCK_REALTIME);
    long long mono = now_ns(CLOCK_MONOTONIC);
    long long boot = now_ns(CLOCK_BOOTTIME);
    long long raw = now_ns(CLOCK_MONOTONIC_RAW);
    long long rt1 = now_ns(CLOCK_REALTIME);
    long long rt = (rt0 + rt1) / 2;
    printf("%lld %lld %lld %lld\n", rt, mono, boot, raw);
    if (interval_ms > 0 && i + 1 < samples) {
      struct timespec req = {interval_ms / 1000, (interval_ms % 1000) * 1000000L};
      nanosleep(&req, nullptr);
    }
  }
  return 0;
}
