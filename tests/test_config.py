import pytest

from sofa_tpu.config import DEFAULT_TPU_FILTERS, Filter, SofaConfig


def test_defaults_mirror_reference():
    cfg = SofaConfig()
    # Reference defaults preserved: sofa_config.py:47 (10 Hz), :44 (20 iters),
    # :45 (10 swarms), bin/sofa viz_port 8000, strace_min_time 1e-6.
    assert cfg.sys_mon_rate == 10
    assert cfg.num_iterations == 20
    assert cfg.num_swarms == 10
    assert cfg.viz_port == 8000
    assert cfg.strace_min_time == pytest.approx(1e-6)
    assert cfg.logdir.endswith("/")


def test_logdir_trailing_slash_and_paths():
    cfg = SofaConfig(logdir="/tmp/x")
    assert cfg.logdir == "/tmp/x/"
    assert cfg.path("a.csv") == "/tmp/x/a.csv"
    assert cfg.xprof_dir == "/tmp/x/xprof"


def test_filter_parse():
    f = Filter.parse("all-reduce:indigo")
    assert f.keyword == "all-reduce" and f.color == "indigo"
    assert Filter.parse("idle").color == "orange"


def test_default_tpu_filters_cover_collectives():
    kws = {f.keyword for f in DEFAULT_TPU_FILTERS}
    for kw in ("all-reduce", "all-gather", "reduce-scatter", "infeed", "outfeed"):
        assert kw in kws


def test_from_dict_rejects_unknown():
    with pytest.raises(ValueError):
        SofaConfig.from_dict({"nope": 1})


def test_from_toml(tmp_path):
    p = tmp_path / "sofa.toml"
    p.write_text(
        'logdir = "run1/"\nsys_mon_rate = 25\ncpu_filters = ["idle:black", "memcpy:red"]\n'
    )
    cfg = SofaConfig.from_toml(str(p))
    assert cfg.logdir == "run1/"
    assert cfg.sys_mon_rate == 25
    assert cfg.cpu_filters[1] == Filter("memcpy", "red")


def test_from_dict_type_validation():
    """Mistyped TOML values are curated config errors at load time, not an
    AttributeError deep in whatever touches the field first (found live:
    `logdir = 123` tracebacked in __post_init__)."""
    import pytest

    with pytest.raises(ValueError, match="logdir.*expected str.*int"):
        SofaConfig.from_dict({"logdir": 123})
    with pytest.raises(ValueError, match="verbose.*expected bool"):
        SofaConfig.from_dict({"verbose": 1})
    with pytest.raises(ValueError, match="num_iterations.*expected int"):
        SofaConfig.from_dict({"num_iterations": "many"})
    # int where the default is float is fine (TOML writers do this)
    assert SofaConfig.from_dict({"tpu_time_offset_ms": 5}).tpu_time_offset_ms == 5
    # Optional/None-defaulted and list fields take whatever TOML produced
    assert SofaConfig.from_dict({"hint_server": "h:1"}).hint_server == "h:1"
    assert SofaConfig.from_dict({"network_filters": ["10.0.0.1"]}
                                ).network_filters == ["10.0.0.1"]
