#!/usr/bin/env python3
"""Cross-environment install matrix (reference: /root/reference/test/test.py:37-78).

The reference proves "pip install + record + report" across six distro
containers and keeps a dated PASS/FAIL log (test/test-06-16.log).  Same
contract here, adapted to what the host offers:

  docker available   -> build a throwaway image per distro (DISTROS), pip
                        install the freshly-built wheel inside, run
                        `sofa record "sleep 5"` + `sofa report`, grep
                        Complete!!.
  docker unavailable -> degrade to a venv matrix: every CPython on the host
                        gets a fresh venv; interpreters that cannot resolve
                        the scientific deps offline produce an explicit SKIP
                        row, never a silent pass.

Every run APPENDS dated result rows to tools/INSTALL_MATRIX.log — commit
that file so each round leaves an auditable trail, like the reference's
test/test-06-16.log.

Exit code: 0 when every attempted case passed (SKIPs don't fail the run),
1 otherwise.
"""

from __future__ import annotations

import argparse
import glob
import os
import shutil
import subprocess
import sys
import sysconfig
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tools", "INSTALL_MATRIX.log")

# Distro images for the docker path, mirroring the reference's matrix
# (test/Dockerfile.*): one Debian-stable, one Ubuntu LTS, one python-slim.
DISTROS = ["debian:stable-slim", "ubuntu:22.04", "python:3.11-slim"]

DOCKERFILE = """\
FROM {image}
RUN (apt-get update && apt-get install -y --no-install-recommends \\
     python3 python3-pip python3-venv) || true
COPY {wheel} /tmp/{wheel}
RUN python3 -m pip install --break-system-packages /tmp/{wheel} \\
    || python3 -m pip install /tmp/{wheel}
RUN sofa record "sleep 5" --logdir /tmp/mlog/ --disable_xprof && \\
    sofa report --logdir /tmp/mlog/ | grep -q 'Complete!!'
"""


def _run(argv, **kw):
    return subprocess.run(argv, capture_output=True, text=True, **kw)


def _append_log(rows):
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(LOG, "a") as f:
        for name, status, detail, dt in rows:
            f.write(f"{stamp} {name:40s} {status:4s} "
                    f"({dt:5.1f}s) {detail}\n")


def build_wheel(out_dir: str) -> str | None:
    """Wheel of the current tree via pip (offline: --no-build-isolation
    resolves setuptools from the running interpreter)."""
    r = _run([sys.executable, "-m", "pip", "wheel", "--no-deps",
              "--no-build-isolation", "-w", out_dir, REPO])
    if r.returncode != 0:
        print(r.stderr[-800:], file=sys.stderr)
        return None
    wheels = glob.glob(os.path.join(out_dir, "sofa_tpu-*.whl"))
    return wheels[0] if wheels else None


def docker_available() -> bool:
    if not shutil.which("docker"):
        return False
    return _run(["docker", "info"], timeout=15).returncode == 0


def discover_interpreters() -> list:
    """Every distinct CPython on the host, the running one first."""
    seen, out = set(), []
    candidates = [sys.executable]
    for pat in ("/usr/bin/python3.*", "/usr/local/bin/python3.*"):
        candidates += sorted(glob.glob(pat))
    for c in candidates:
        if not c or not os.access(c, os.X_OK) or c.endswith("-config"):
            continue
        r = _run([c, "-c", "import sys; print(sys.implementation.name,"
                           "'%d.%d' % sys.version_info[:2])"])
        if r.returncode != 0:
            continue
        key = r.stdout.strip()
        if key in seen:
            continue
        seen.add(key)
        out.append((c, key.replace(" ", "")))
    return out


def _deps_importable(python: str, env: dict) -> str | None:
    """None when the interpreter can resolve the runtime deps (its own
    site-packages or the PYTHONPATH overlay); else the failing import."""
    r = _run([python, "-c", "import numpy, pandas"], env=env)
    if r.returncode == 0:
        return None
    tail = (r.stderr.strip().splitlines() or ["import failed"])[-1]
    return tail[:120]


def venv_case(python: str, label: str, wheel: str, workdir: str):
    """Fresh venv for `python`; install the wheel; record+report in it.

    Degradation ladder (each rung logged explicitly, never silently):
      - `-m venv` fails (Debian pythons shipped without ensurepip /
        python3.X-venv): retry `--without-pip` and install the wheel from
        the outside via the host pip's ``--python`` re-exec, which needs
        no pip inside the target venv.
      - The running env's site-packages overlay only resolves numpy/pandas
        for same-ABI interpreters; a foreign-ABI interpreter retries
        against its own system dist-packages instead.
      - Analyze deps (pandas) unresolvable offline: the pandas-free
        `sofa record` half still runs — PASS scoped "record-only" in the
        row, because it genuinely proves wheel+console-script+record
        portability on that interpreter.
    """
    t0 = time.time()
    venv = os.path.join(workdir, f"venv-{label}")
    pipless = False
    r = _run([python, "-m", "venv", venv])
    if r.returncode != 0:
        # --system-site-packages: offline, the interpreter's own
        # dist-packages are the only possible source of the analyze deps.
        r = _run([python, "-m", "venv", "--without-pip",
                  "--system-site-packages", venv])
        pipless = True
        if r.returncode != 0:
            return (label, "SKIP", "venv creation unavailable",
                    time.time() - t0)
    vpy = os.path.join(venv, "bin", "python")
    # Offline dependency story (same trick as tests/test_install.py): the
    # running env's site-packages ride PYTHONPATH; the venv's own
    # site-packages still win for the package under test.
    overlay = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=sysconfig.get_paths()["purelib"])
    bare = dict(os.environ, JAX_PLATFORMS="cpu")
    bare.pop("PYTHONPATH", None)
    env = overlay
    missing = _deps_importable(vpy, env)
    if missing:
        env = bare
        missing = _deps_importable(vpy, env)
    if pipless:
        r = _run([sys.executable, "-m", "pip", "--python", vpy, "install",
                  "--no-deps", "--quiet", wheel], env=env)
    else:
        r = _run([vpy, "-m", "pip", "install", "--no-deps", "--quiet",
                  wheel], env=env)
    if r.returncode != 0:
        return (label, "FAIL", "pip install: " + r.stderr[-120:].strip(),
                time.time() - t0)
    sofa = os.path.join(venv, "bin", "sofa")
    if not os.path.isfile(sofa):
        return (label, "FAIL", "console script missing", time.time() - t0)
    logdir = os.path.join(workdir, f"log-{label}") + "/"
    r = _run([sofa, "record", "sleep 5", "--logdir", logdir,
              "--disable_xprof"], env=env, cwd=workdir)
    if r.returncode != 0:
        return (label, "FAIL", "record rc=%d" % r.returncode,
                time.time() - t0)
    if missing:
        dep = missing.split("'")[1] if "'" in missing else missing
        return (label, "PASS",
                f"record-only ({dep} unresolvable offline; report needs it)",
                time.time() - t0)
    r = _run([sofa, "report", "--logdir", logdir], env=env, cwd=workdir)
    if r.returncode != 0 or "Complete!!" not in r.stdout:
        return (label, "FAIL", "report did not Complete!!", time.time() - t0)
    return (label, "PASS", "record+report Complete!!", time.time() - t0)


def docker_case(image: str, wheel: str, workdir: str):
    t0 = time.time()
    ctx = os.path.join(workdir, "ctx-" + image.replace(":", "-").replace("/", "-"))
    os.makedirs(ctx, exist_ok=True)
    shutil.copy(wheel, ctx)
    wheel_name = os.path.basename(wheel)
    with open(os.path.join(ctx, "Dockerfile"), "w") as f:
        f.write(DOCKERFILE.format(image=image, wheel=wheel_name))
    tag = "sofa-tpu-matrix:" + image.replace(":", "-").replace("/", "-")
    r = _run(["docker", "build", "--no-cache", "-t", tag, ctx],
             timeout=1200)
    _run(["docker", "rmi", "-f", tag])
    if r.returncode != 0:
        tail = (r.stderr.strip().splitlines() or ["build failed"])[-1]
        return (image, "FAIL", tail[:120], time.time() - t0)
    return (image, "PASS", "image build ran record+report", time.time() - t0)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--mode", choices=["auto", "docker", "venv"],
                   default="auto")
    args = p.parse_args()

    workdir = tempfile.mkdtemp(prefix="sofa_matrix_")
    try:
        wheel = build_wheel(workdir)
        if wheel is None:
            _append_log([("wheel-build", "FAIL", "pip wheel failed", 0.0)])
            return 1
        use_docker = (args.mode == "docker"
                      or (args.mode == "auto" and docker_available()))
        rows = []
        if use_docker:
            for image in DISTROS:
                print(f"matrix: docker {image} ...", flush=True)
                rows.append(docker_case(image, wheel, workdir))
        else:
            for python, key in discover_interpreters():
                label = f"{key}@{python}"
                print(f"matrix: venv {label} ...", flush=True)
                rows.append(venv_case(python, label, wheel, workdir))
        _append_log(rows)
        width = max(len(r[0]) for r in rows)
        for name, status, detail, dt in rows:
            print(f"{name:{width}s}  {status:4s}  ({dt:5.1f}s)  {detail}")
        return 0 if all(r[1] != "FAIL" for r in rows) else 1
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
