"""Pure-Python pcap ingest for DCN/host network traffic.

The reference shells out to `tcpdump -r` and scrapes its text output
(/root/reference/bin/sofa_preprocess.py:1187-1233); parsing the pcap file
directly removes the tcpdump dependency at report time (the capture machine
and the analysis machine are often different).

Supports classic pcap (µs and ns magic, both endians) with link types
Ethernet(1), RAW-IP(101), Linux SLL(113) and SLL2(276) — tcpdump -i any
writes SLL/SLL2.  IPv4 TCP/UDP packets become rows:

  payload  = captured original length (bytes)
  pkt_src/dst = packed IPv4 (trace.packed_ip encoding)
  duration = payload / 128 MB/s — the reference's fixed service-rate model
             (sofa_preprocess.py:178-179), kept for comparability
  name     = "proto sport->dport"
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional

import pandas as pd

from sofa_tpu.trace import empty_frame, make_frame

_NET_MODEL_BYTES_PER_S = 128e6

_MAGICS = {
    0xA1B2C3D4: ("<", 1e-6), 0xD4C3B2A1: (">", 1e-6),
    0xA1B23C4D: ("<", 1e-9), 0x4D3CB2A1: (">", 1e-9),
}


def _ipv4_row(ts: float, data: bytes, orig_len: int, time_base: float) -> Optional[dict]:
    if len(data) < 20 or (data[0] >> 4) != 4:
        return None
    ihl = (data[0] & 0x0F) * 4
    proto = data[9]
    src = ".".join(str(b) for b in data[12:16])
    dst = ".".join(str(b) for b in data[16:20])
    sport = dport = 0
    pname = {6: "tcp", 17: "udp"}.get(proto, str(proto))
    if proto in (6, 17) and len(data) >= ihl + 4:
        sport, dport = struct.unpack("!HH", data[ihl:ihl + 4])
    from sofa_tpu.trace import packed_ip

    return {
        "timestamp": ts - time_base,
        "event": float(dport or proto),
        "duration": orig_len / _NET_MODEL_BYTES_PER_S,
        "payload": orig_len,
        "bandwidth": _NET_MODEL_BYTES_PER_S,
        "pkt_src": packed_ip(src),
        "pkt_dst": packed_ip(dst),
        "name": f"{pname} {src}:{sport}->{dst}:{dport}",
        "device_kind": "net",
    }


def parse_pcap_bytes(blob: bytes, time_base: float = 0.0) -> pd.DataFrame:
    if len(blob) < 24:
        return empty_frame()
    magic = struct.unpack("<I", blob[:4])[0]
    if magic not in _MAGICS:
        magic = struct.unpack(">I", blob[:4])[0]
    if magic not in _MAGICS:
        return empty_frame()
    endian, tick = _MAGICS[magic]
    linktype = struct.unpack(endian + "I", blob[20:24])[0] & 0x0FFFFFFF
    rows: List[dict] = []
    off = 24
    n = len(blob)
    while off + 16 <= n:
        ts_sec, ts_frac, incl, orig = struct.unpack(endian + "IIII", blob[off:off + 16])
        off += 16
        if off + incl > n:
            break
        data = blob[off:off + incl]
        off += incl
        ts = ts_sec + ts_frac * tick
        ip: Optional[bytes] = None
        if linktype == 1 and len(data) >= 14:  # Ethernet
            ethertype = struct.unpack("!H", data[12:14])[0]
            if ethertype == 0x0800:
                ip = data[14:]
        elif linktype == 101:  # raw IP
            ip = data
        elif linktype == 113 and len(data) >= 16:  # Linux cooked (SLL)
            if struct.unpack("!H", data[14:16])[0] == 0x0800:
                ip = data[16:]
        elif linktype == 276 and len(data) >= 20:  # SLL2
            if struct.unpack("!H", data[0:2])[0] == 0x0800:
                ip = data[20:]
        if ip is None:
            continue
        row = _ipv4_row(ts, ip, orig, time_base)
        if row:
            rows.append(row)
    return make_frame(rows) if rows else empty_frame()


def ingest_pcap(path: str, time_base: float = 0.0) -> pd.DataFrame:
    if not os.path.isfile(path):
        return empty_frame()
    with open(path, "rb") as f:
        return parse_pcap_bytes(f.read(), time_base)
