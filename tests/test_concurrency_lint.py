"""SL019–SL023 concurrency & commit-ordering lint: per-rule pos/neg
fixtures, the seeded-mutation gate (deleting a Guard from telemetry.py
must fire SL019 at the right site), the shipped-tree zero-findings gate,
--jobs determinism, the import-side-effect contract, the Guard primitive
itself, and race-marked runtime tests that hammer Guard-protected state
under a tiny switch interval.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

from sofa_tpu.concurrency import Guard
from sofa_tpu.lint.cli import run_lint
from sofa_tpu.lint.core import ProjectContext, lint_paths
from sofa_tpu.lint.rules import default_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONC_RULES = ("SL019", "SL020", "SL021", "SL022", "SL023")


def run_conc(tmp_path, files):
    """Write {relname: src} fixtures, detect (context graph included),
    lint; returns only the SL019–SL023 findings."""
    paths = []
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
        paths.append(str(p))
    project = ProjectContext.detect(paths, base=str(tmp_path))
    fs = lint_paths(paths, default_rules(), project=project,
                    base=str(tmp_path))
    return [f for f in fs if f.rule_id in CONC_RULES]


def ids(findings):
    return [f.rule_id for f in findings]


# --- SL019: declared-guard contracts ----------------------------------------

def test_sl019_write_outside_declared_guard(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        from sofa_tpu.concurrency import Guard

        _G = Guard("m.items", protects=("_items",))
        _items = []

        def bad(x):
            _items.append(x)

        def good(x):
            with _G:
                _items.append(x)
    """})
    assert ids(fs) == ["SL019"]
    assert "_items" in fs[0].message and "declared guard" in fs[0].message
    # the finding anchors at bad()'s append, not good()'s
    assert fs[0].line == 8


def test_sl019_multi_context_write_needs_guard(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        import threading

        _count = {}

        def worker():
            _count["n"] = 1

        def main():
            t = threading.Thread(target=worker)
            t.start()
            _count["n"] = 2
            t.join()
    """})
    assert ids(fs) == ["SL019"]
    assert "multiple execution contexts" in fs[0].message


def test_sl019_imported_class_attr_mutation(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        import http.server

        http.server.ThreadingHTTPServer.daemon_threads = True
    """})
    assert ids(fs) == ["SL019"]
    assert "process-global" in fs[0].message


def test_sl019_clean_patterns(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        import threading

        from sofa_tpu import printing
        from sofa_tpu.concurrency import Guard

        printing.verbose = True  # module config var: the startup idiom

        _G = Guard("m.state", protects=("_state",))
        _state = {}

        def worker():
            with _G:
                _state["k"] = 1

        def main():
            t = threading.Thread(target=worker)
            t.start()
            with _G:
                _state["k"] = 2
            t.join()
    """})
    assert fs == []


# --- SL020: blocking under a guard, lock-order cycles -----------------------

def test_sl020_blocking_calls_under_lock(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        import subprocess
        import threading
        import time

        _lock = threading.Lock()

        def f():
            with _lock:
                subprocess.run(["ls"], timeout=1)
                time.sleep(0.1)

        def ok():
            with _lock:
                x = 1
            subprocess.run(["ls"], timeout=1)
    """})
    assert ids(fs) == ["SL020", "SL020"]
    assert all(f.severity == "warn" for f in fs)


def test_sl020_lock_order_cycle(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def f():
            with _a:
                with _b:
                    pass

        def g():
            with _b:
                with _a:
                    pass
    """})
    cycles = [f for f in fs if "cycle" in f.message]
    assert len(cycles) == 1 and cycles[0].rule_id == "SL020"
    assert cycles[0].severity == "error"


def test_sl020_consistent_order_is_clean(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def f():
            with _a:
                with _b:
                    pass

        def g():
            with _a:
                with _b:
                    pass
    """})
    assert fs == []


# --- SL021: commit ordering -------------------------------------------------

_VERB_TMPL = """
    from sofa_tpu.durability import Journal, atomic_write, write_digests

    def sofa_demo(logdir):
        j = Journal(logdir)
        {body}
"""


def _verb(body):
    return _VERB_TMPL.format(body=body.replace("\n", "\n        "))


def test_sl021_write_after_commit(tmp_path):
    fs = run_conc(tmp_path, {"verbmod.py": _verb("""
j.begin("demo")
with atomic_write(logdir + "/out.json") as f:
    f.write("{}")
write_digests(logdir)
j.commit("demo")
with atomic_write(logdir + "/late.json") as f:
    f.write("{}")
""")})
    assert ids(fs) == ["SL021"]
    assert "after commit()" in fs[0].message and "late.json" in fs[0].message


def test_sl021_begin_without_commit_and_inverted_window(tmp_path):
    fs = run_conc(tmp_path, {
        "nocommit.py": _verb('j.begin("demo")'),
        "inverted.py": _verb('j.commit("demo")\nj.begin("demo")'),
    })
    msgs = {f.file.split("/")[-1]: f.message for f in fs}
    assert "never commit()" in msgs["nocommit.py"]
    assert "before its begin()" in msgs["inverted.py"]


def test_sl021_write_between_digest_and_commit(tmp_path):
    fs = run_conc(tmp_path, {"verbmod.py": _verb("""
j.begin("demo")
write_digests(logdir)
with atomic_write(logdir + "/out.json") as f:
    f.write("{}")
j.commit("demo")
""")})
    assert ids(fs) == ["SL021"]
    assert "digest refresh" in fs[0].message


def test_sl021_well_ordered_verb_is_clean(tmp_path):
    fs = run_conc(tmp_path, {"verbmod.py": _verb("""
j.begin("demo")
with atomic_write(logdir + "/out.json") as f:
    f.write("{}")
write_digests(logdir)
j.commit("demo")
""")})
    assert fs == []


# --- SL022: thread-context safety -------------------------------------------

def test_sl022_module_level_thread_spawn(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        import threading

        def _poll():
            pass

        _t = threading.Thread(target=_poll, daemon=True)
        _t.start()
    """})
    assert ids(fs) == ["SL022"]
    assert "module import time" in fs[0].message


def test_sl022_signal_off_main_thread(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        import signal
        import threading

        def _handler(sig, frm):
            pass

        def _w():
            signal.signal(signal.SIGTERM, _handler)

        def go():
            t = threading.Thread(target=_w)
            t.start()
            t.join()
    """})
    assert ids(fs) == ["SL022"]
    assert "non-main execution context" in fs[0].message


def test_sl022_sentinel_check_then_act(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        import os

        def racing(logdir):
            return os.path.exists(
                os.path.join(logdir, "_derived.writing"))
    """})
    assert ids(fs) == ["SL022"]
    assert "derived_writing" in fs[0].message


def test_sl022_embedded_template_import_spawn(tmp_path):
    pad = "#" + " padding" * 30
    fs = run_conc(tmp_path, {"coll.py": f'''
        _TEMPLATE = """
        {pad}
        import threading

        def _poll():
            pass

        _t = threading.Thread(target=_poll, daemon=True)
        _t.start()
        """
    '''})
    assert ids(fs) == ["SL022"]
    assert "embedded template" in fs[0].message
    # the finding lands on the REAL file's line, inside the string
    assert fs[0].line > 5


def test_sl022_lazy_template_is_clean(tmp_path):
    pad = "#" + " padding" * 30
    fs = run_conc(tmp_path, {"coll.py": f'''
        _TEMPLATE = """
        {pad}
        import sys
        import threading

        def _arm():
            t = threading.Thread(target=_poll, daemon=True)
            t.start()
            t.join()

        def _poll():
            pass
        """
    '''})
    assert fs == []


# --- SL023: shutdown liveness -----------------------------------------------

def test_sl023_thread_without_stop_path(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        import threading

        class Daemonette:
            def start(self):
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._thread.start()

            def _run(self):
                pass
    """})
    assert ids(fs) == ["SL023"]
    assert "no reachable stop path" in fs[0].message


def test_sl023_accepts_join_return_and_cancel_registry(tmp_path):
    fs = run_conc(tmp_path, {"m.py": """
        import threading

        _TIMERS = []

        class Svc:
            def start(self):
                self._thread = threading.Thread(target=self._run)
                self._thread.start()

            def stop(self):
                self._thread.join(timeout=5)

            def _run(self):
                pass

        def bounded(fn, timeout):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            t.join(timeout)

        def handoff(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            return t

        def arm(fn, delay):
            t = threading.Timer(delay, fn)
            _TIMERS.append(t)
            t.start()

        def clear():
            while _TIMERS:
                _TIMERS.pop().cancel()
    """})
    assert fs == []


# --- the seeded-mutation gate ----------------------------------------------

def test_removing_a_guard_from_telemetry_fires_sl019(tmp_path):
    src = open(os.path.join(REPO, "sofa_tpu", "telemetry.py")).read()
    guarded = ("        with self._lock:\n"
               "            self.counters[name] = "
               "self.counters.get(name, 0) + n")
    assert guarded in src
    mutated = src.replace(
        guarded,
        "        self.counters[name] = self.counters.get(name, 0) + n")
    p = tmp_path / "telemetry.py"
    p.write_text(mutated)
    project = ProjectContext.detect([str(p)], base=str(tmp_path))
    fs = [f for f in lint_paths([str(p)], default_rules(), project=project,
                                base=str(tmp_path))
          if f.rule_id == "SL019"]
    assert len(fs) == 1
    assert "counters" in fs[0].message
    # ...at the mutated write site
    want = mutated.splitlines().index(
        "        self.counters[name] = self.counters.get(name, 0) + n") + 1
    assert fs[0].line == want


# --- shipped-tree gates -----------------------------------------------------

def test_shipped_tree_has_zero_concurrency_findings():
    """The acceptance gate: no SL019–SL023 findings on the shipped tree,
    baselined or not (the rules landed with their debt burned down)."""
    fs = lint_paths([os.path.join(REPO, "sofa_tpu")], default_rules(),
                    base=REPO)
    conc = [f for f in fs if f.rule_id in CONC_RULES]
    assert conc == []


def test_jobs_output_byte_identical(capsys):
    args = [os.path.join(REPO, "sofa_tpu"), "--no-baseline", "--json",
            "--base", REPO]
    rc1 = run_lint(args + ["--jobs", "1"])
    out1 = capsys.readouterr().out
    rc4 = run_lint(args + ["--jobs", "4"])
    out4 = capsys.readouterr().out
    assert rc1 == rc4
    assert out1 == out4
    assert json.loads(out1)["by_rule"]  # family counts ride the report


def test_rule_filter_and_exit_contract(tmp_path, capsys):
    mod = tmp_path / "m.py"
    mod.write_text("import subprocess\nsubprocess.run(['a'])\n")
    # SL001 fires unfiltered...
    rc = run_lint([str(tmp_path), "--no-baseline",
                   "--base", str(tmp_path)])
    assert rc == 1
    capsys.readouterr()
    # ...and is invisible under a disjoint --rule filter (exit 0)
    rc = run_lint([str(tmp_path), "--no-baseline",
                   "--base", str(tmp_path), "--rule", "SL019,SL023"])
    assert rc == 0
    rc = run_lint([str(tmp_path), "--no-baseline",
                   "--base", str(tmp_path), "--rule", "SL001"])
    assert rc == 1
    rc = run_lint([str(tmp_path), "--rule", "bogus"])
    assert rc == 2


def test_explain_prints_catalog_row(capsys):
    assert run_lint(["--explain", "SL021"]) == 0
    out = capsys.readouterr().out
    assert "SL021" in out and "commit" in out.lower()
    assert run_lint(["--explain", "SL999"]) == 2


def test_import_sofa_tpu_spawns_no_threads():
    """Acceptance: `import sofa_tpu` has zero thread side effects."""
    r = subprocess.run(
        [sys.executable, "-c",
         "import sofa_tpu, threading; "
         "print(','.join(sorted(t.name for t in threading.enumerate())))"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr
    assert r.stdout.strip() == "MainThread"


def test_injected_sitecustomize_spawns_no_threads_without_jax(tmp_path):
    """The canonical SL022 burn-down, verified end to end: importing the
    generated sitecustomize (what every child python does) starts zero
    threads until jax is imported."""
    from sofa_tpu.collectors.xprof import _SITECUSTOMIZE

    (tmp_path / "sitecustomize.py").write_text(_SITECUSTOMIZE)
    env = {**os.environ, "PYTHONPATH": str(tmp_path),
           "SOFA_TPU_XPROF_OPTS": json.dumps(
               {"enable": True, "logdir": str(tmp_path)})}
    r = subprocess.run(
        [sys.executable, "-c",
         "import threading; "
         "print(','.join(sorted(t.name for t in threading.enumerate())))"],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    assert r.stdout.strip() == "MainThread"


# --- the Guard primitive ----------------------------------------------------

def test_guard_is_reentrant_and_tracks_ownership():
    g = Guard("test.guard", protects=("x",))
    assert not g.held()
    with g:
        assert g.held()
        with g:  # reentrant
            assert g.held()
        assert g.held()
    assert not g.held()


def test_guard_debug_assert(monkeypatch):
    g = Guard("test.guard", protects=("x",))
    monkeypatch.setenv("SOFA_DEBUG_GUARDS", "1")
    with pytest.raises(AssertionError):
        g.assert_held()
    with g:
        g.assert_held()  # no raise
    monkeypatch.delenv("SOFA_DEBUG_GUARDS")
    g.assert_held()  # no-op outside debug mode


def test_guard_rejects_anonymous():
    with pytest.raises(ValueError):
        Guard("")


# --- race-marked runtime tests (amplified by the conftest fixture) ----------

@pytest.mark.race
def test_telemetry_counters_survive_contention():
    from sofa_tpu import telemetry

    tel = telemetry.Telemetry("race")
    n_threads, per = 8, 400

    def hammer():
        for _ in range(per):
            tel.count("events")
            tel.console("warning", "w")
            tel.collector_event("col", bytes_captured=1)
            tel.source_event("src", events=1)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert tel.counters["events"] == n_threads * per
    assert tel.counters["warnings"] == n_threads * per
    assert len(tel.warning_tail) <= 20


@pytest.mark.race
def test_telemetry_registry_survives_begin_end_churn():
    from sofa_tpu import telemetry

    def churn():
        for _ in range(200):
            tel = telemetry.begin("race")
            telemetry.collector_event("c", "started")
            telemetry.end(tel)

    threads = [threading.Thread(target=churn) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert telemetry.current() is None


@pytest.mark.race
def test_guard_excludes_writers():
    g = Guard("race.guard", protects=("shared",))
    shared = {"n": 0}

    def bump():
        for _ in range(2000):
            with g:
                shared["n"] = shared["n"] + 1

    threads = [threading.Thread(target=bump) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert shared["n"] == 12000
